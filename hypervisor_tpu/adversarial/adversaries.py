"""The five adversary classes: seeded drivers against a LIVE state.

Every function here has the same shape::

    report = sybil_flood(seed, hardened=True, quick=True)

builds a fresh deployment, drives one seeded attack against it, and
returns a `ContainmentReport` whose components answer the containment
questions for that adversary class (module docstring of
`adversarial.scoring`). `hardened` toggles the defense mechanism the
scenario exists to prove (admission damper, collusion detector,
cascade dedupe, compensation backpressure) so the before/after
containment delta is measurable; `quick` shrinks batch sizes for CI.

Determinism contract: all randomness flows from `random.Random(seed)`,
all device time is synthetic (explicit `now=`), and trace events carry
only symbolic labels — never uuids or wall-clock — so one (seed,
hardened) pair produces ONE trace digest, forever. The property tests
in `tests/unit/test_adversarial.py` pin this.
"""

from __future__ import annotations

import asyncio
import random
from types import SimpleNamespace

import numpy as np

from hypervisor_tpu.adversarial.scoring import ContainmentReport, fraction

_OMEGA = 0.5  # risk weight for host sigma_eff queries in the scenarios


def _sanitize_total(state) -> int:
    """Run one synchronous invariant sweep over a scenario's final
    tables (σ ranges, escrow conservation, FSM codes, turn chains —
    `integrity.invariants`); returns total violating rows."""
    from hypervisor_tpu.integrity import IntegrityPlane

    plane = getattr(state, "integrity", None)
    if plane is None:
        plane = IntegrityPlane(state, every=0, scrub_every=0)
    return int(plane.sanitize()["total"])


# ── 1. sybil flood ───────────────────────────────────────────────────


def sybil_flood(
    seed: int, *, hardened: bool = True, quick: bool = True
) -> ContainmentReport:
    """Mass low-sigma joins at open-workload rates.

    The admission wave sandboxes low-sigma agents (ring 3) rather than
    refusing them — the paper's design — so a flood of cheap identities
    ADMITS, each one burning a staging slot, an agent-table row, a rate
    bucket, and a seat against `max_participants` until honest joins
    refuse on capacity. The admission-rate damper
    (`resilience.policy.AdmissionDamper`, `hardened=True`) trips a
    targeted shed that refuses the flood at the gate, pre-queue, while
    honest joins keep flowing.
    """
    from hypervisor_tpu.models import SessionConfig
    from hypervisor_tpu.resilience.policy import (
        AdmissionDamper,
        DegradedModeRefusal,
    )
    from hypervisor_tpu.state import HypervisorState

    rng = random.Random(seed)
    report = ContainmentReport("sybil_flood", seed, hardened)
    n_sybils = 96 if quick else 384
    capacity = 48 if quick else 192
    flush_every = 8
    dt = 0.01  # 100 join attempts/s — open-workload arrival rate

    st = HypervisorState()
    if hardened:
        st.admission_damper = AdmissionDamper(
            rate_threshold=10.0,
            low_sigma_fraction=0.5,
            sigma_floor=0.5,
            window_seconds=1.0,
        )
    slot = st.create_session(
        "scn:sybil",
        SessionConfig(min_sigma_eff=0.6, max_participants=capacity),
        now=0.0,
    )

    # One honest join rides along with every `flush_every` sybils.
    schedule: list[tuple[str, str, float]] = []
    h = 0
    for i in range(n_sybils):
        if i % flush_every == 0:
            schedule.append(("honest", f"did:honest:{h}", 0.85))
            h += 1
        schedule.append(
            ("sybil", f"did:sybil:{i}", round(0.05 + 0.1 * rng.random(), 3))
        )
    n_honest = h

    counts = {
        "sybil_queued": 0, "sybil_damped": 0,
        "honest_queued": 0, "honest_refused": 0, "pending": 0,
    }
    admitted = {"sybil": 0, "honest": 0}
    queued_kind: dict[str, str] = {}

    def flush(t: float) -> None:
        if not counts["pending"]:
            return
        status = st.flush_joins(now=t)
        counts["pending"] = 0
        report.record("flush", int(len(status)),
                      [int(s) for s in np.asarray(status)])

    for i, (kind, did, sigma) in enumerate(schedule):
        t = i * dt
        try:
            q = st.enqueue_join(slot, did, sigma, now=t)
        except DegradedModeRefusal:
            counts["sybil_damped" if kind == "sybil" else "honest_refused"] += 1
            report.attack("join", kind, did, "damped")
            continue
        if q < 0:
            if kind == "honest":
                counts["honest_refused"] += 1
            report.attack("join", kind, did, "queue_full")
            continue
        counts[f"{kind}_queued"] += 1
        counts["pending"] += 1
        queued_kind[did] = kind
        report.attack("join", kind, did, "queued")
        if counts["pending"] >= flush_every:
            flush(t)
    flush(len(schedule) * dt)

    for did, kind in queued_kind.items():
        if st.is_member(slot, did):
            admitted[kind] += 1

    # Honest agents must ALL get their seats — the flood's goal is to
    # exhaust the session before they arrive.
    report.set("honest_admission",
               fraction(admitted["honest"], n_honest, empty=0.0))
    report.set("honest_flow",
               1.0 - fraction(counts["honest_refused"], n_honest, empty=0.0))
    # Capacity held: the fraction of the session NOT occupied by
    # sandboxed sybil identities.
    report.set("capacity_preserved",
               1.0 - fraction(admitted["sybil"], capacity, empty=0.0))
    # The damper's axis: how much of the flood was refused BEFORE it
    # could burn a staging slot + wave lane.
    report.set("flood_work_damped",
               fraction(counts["sybil_damped"], n_sybils, empty=0.0))
    report.set("invariants_clean", 1.0 if _sanitize_total(st) == 0 else 0.0)
    report.details = {
        **counts,
        "admitted": dict(admitted),
        "damper": (
            st.admission_damper.summary()
            if st.admission_damper is not None else None
        ),
    }
    return report


# ── 2. vouch-collusion ring ──────────────────────────────────────────


class _DriftStub:
    """Deterministic CMVK stand-in: the defection step always reads as
    critical behavioral drift (should_slash), so `verify_behavior`
    walks the full production slash path."""

    def check_behavioral_drift(self, **_kw):
        return SimpleNamespace(
            should_demote=False,
            should_slash=True,
            drift_score=0.91,
            severity=SimpleNamespace(value="critical"),
        )


def collusion_ring(
    seed: int, *, hardened: bool = True, quick: bool = True
) -> ContainmentReport:
    """A clique pumps sigma_eff through mutual bonds, then defects.

    Escrow conservation (PR 5's per-voucher Σ bonds invariant) is the
    invariant under test; the collusion detector
    (`liability.collusion`, `hardened=True`) must neutralize the clique
    — read-only quarantine on both planes — BEFORE the defection, with
    zero honest false positives.
    """
    from hypervisor_tpu.core import Hypervisor
    from hypervisor_tpu.models import SessionConfig
    from hypervisor_tpu.observability import HypervisorEventBus

    rng = random.Random(seed)
    report = ContainmentReport("collusion_ring", seed, hardened)
    n_honest, n_clique = 6, 4
    honest = [f"did:honest:{i}" for i in range(n_honest)]
    clique = [f"did:clique:{i}" for i in range(n_clique)]
    # Layered DAG — cycle rejection does not stop a pump ring.
    pump_edges = [(0, 1), (0, 2), (0, 3), (1, 2), (1, 3), (2, 3)]
    rng.shuffle(pump_edges)

    async def run() -> Hypervisor:
        hv = Hypervisor(event_bus=HypervisorEventBus(), cmvk=_DriftStub())
        managed = await hv.create_session(
            SessionConfig(min_sigma_eff=0.5, max_participants=32), "did:op"
        )
        sid = managed.sso.session_id
        for i, did in enumerate(honest):
            await hv.join_session(sid, did, sigma_raw=0.72 + 0.02 * i)
        # Honest sponsorship: reputable agents fan out to newcomers —
        # dense-ish but single-role, the shape the detector must NOT flag.
        hv.vouching.vouch(honest[0], honest[3], sid, voucher_sigma=0.72)
        hv.vouching.vouch(honest[0], honest[4], sid, voucher_sigma=0.72)
        hv.vouching.vouch(honest[1], honest[5], sid, voucher_sigma=0.74)
        for did in clique:
            await hv.join_session(sid, did, sigma_raw=0.55)
            report.attack("clique_join", did)
        for a, b in pump_edges:
            hv.vouching.vouch(
                clique[a], clique[b], sid, voucher_sigma=0.55
            )
            report.attack("pump_vouch", clique[a], clique[b])

        pumped = {
            did: hv.vouching.compute_sigma_eff(did, sid, 0.55, _OMEGA)
            for did in clique
        }
        report.record(
            "pumped_sigma",
            {d: round(v, 4) for d, v in sorted(pumped.items())},
        )

        findings = hv.detect_collusion(sid) if hardened else []
        report.record(
            "findings",
            [sorted(f.members) for f in findings],
        )

        sigma_before = {
            p.agent_did: p.sigma_eff for p in managed.sso.participants
        }
        # Defection: the most-pumped member goes rogue.
        defector = max(sorted(pumped), key=lambda d: pumped[d])
        report.attack("defect", defector)
        await hv.verify_behavior(sid, defector, [0.0], [1.0])

        sigma_after = {
            p.agent_did: p.sigma_eff for p in managed.sso.participants
        }
        damaged = sorted(
            d for d, v in sigma_after.items()
            if v < sigma_before[d] - 1e-6
        )
        report.record("damaged", damaged)

        quarantined = [
            did for did in clique
            if hv.quarantine.get_active_quarantine(did, sid) is not None
        ]
        honest_flagged = [
            did for did in honest
            if hv.quarantine.get_active_quarantine(did, sid) is not None
        ]
        exposure_ok = all(
            hv.vouching.get_total_exposure(did, sid)
            <= 0.80 * 1.0 + 1e-6
            for did in honest + clique
        )

        report.set(
            "pump_neutralized",
            fraction(
                len(quarantined) if hardened else 0, n_clique, empty=0.0
            ),
        )
        report.set(
            "detector_precision",
            1.0 - fraction(len(honest_flagged), n_honest, empty=0.0),
        )
        report.set(
            "honest_sigma_preserved",
            1.0 if all(d not in honest for d in damaged) else 0.0,
        )
        report.set("blast_confined",
                   1.0 if set(damaged) <= set(clique) else 0.0)
        report.set(
            "escrow_conservation",
            1.0 if exposure_ok and _sanitize_total(hv.state) == 0 else 0.0,
        )
        report.details = {
            "pumped_sigma_max": round(max(pumped.values()), 4),
            "quarantined": quarantined,
            "honest_flagged": honest_flagged,
            "damaged": damaged,
            "findings": [f.to_dict() for f in findings],
        }
        return hv

    asyncio.run(run())
    return report


# ── 3. slash cascade storm ───────────────────────────────────────────


def slash_cascade(
    seed: int, *, hardened: bool = True, quick: bool = True
) -> ContainmentReport:
    """Deep chains + diamonds across the liability graph.

    Probes the cascade bound (agents beyond `max_cascade_depth` must
    keep their sigma), per-agent settlement uniqueness (a diamond used
    to clip the shared voucher once per path — double ledger charge),
    and settlement determinism (two edge-insertion orders of the SAME
    graph must settle in ONE canonical sequence). Host engines only —
    the scalar exception-faithful path the facade's device cascade
    mirrors.
    """
    from hypervisor_tpu.liability.slashing import SlashingEngine
    from hypervisor_tpu.liability.vouching import VouchingEngine

    rng = random.Random(seed)
    report = ContainmentReport("slash_cascade", seed, hardened)
    S = "scn:cascade"
    depth = 6

    # voucher -> vouchee edges: a chain c1->c0, c2->c1, ... plus a
    # diamond (m1,m2 -> c0 backed by the shared voucher w) and honest
    # bystanders off to the side.
    edges = [(f"did:c:{i + 1}", f"did:c:{i}") for i in range(depth)]
    edges += [("did:m:1", "did:c:0"), ("did:m:2", "did:c:0"),
              ("did:w:0", "did:m:1"), ("did:w:0", "did:m:2")]
    honest_edges = [("did:h:0", "did:h:1"), ("did:h:2", "did:h:3")]
    dids = sorted({d for e in edges for d in e}
                  | {d for e in honest_edges for d in e})

    def build_and_slash(order: list) -> tuple[list, dict]:
        vouching = VouchingEngine()
        slashing = SlashingEngine(vouching, dedupe_cascade=hardened)
        for voucher, vouchee in order:
            vouching.vouch(voucher, vouchee, S, voucher_sigma=0.8)
        scores = {d: 0.8 for d in dids}
        slashing.slash(
            "did:c:0", S, 0.8, 0.99, "scenario defection", scores
        )
        settlement = []
        for event in slashing.history:
            settlement.append(["slash", event.vouchee_did,
                               event.cascade_depth])
            settlement.extend(
                ["clip", c.voucher_did] for c in event.voucher_clips
            )
        return settlement, {
            "scores": scores,
            "dedupes": slashing.cascade_dedupes,
            "max_depth": max(
                (e.cascade_depth for e in slashing.history), default=0
            ),
        }

    order_a = edges + honest_edges
    rng.shuffle(order_a)
    order_b = list(reversed(order_a))
    for voucher, vouchee in order_a:
        report.attack("edge", voucher, vouchee)
    report.attack("slash", "did:c:0")

    settle_a, out_a = build_and_slash(order_a)
    settle_b, out_b = build_and_slash(order_b)
    report.record("settlement", settle_a)
    report.record("dedupes", out_a["dedupes"])

    scores = out_a["scores"]
    settled_dids = [e[1] for e in settle_a]
    # Duplicates count WITHIN each settlement kind: a clip that wipes
    # and then cascades into a slash is the design; the same agent
    # clipped (or slashed) twice in one root event is the breach.
    dup = sum(
        len(ds) - len(set(ds))
        for kind in ("slash", "clip")
        if (ds := [e[1] for e in settle_a if e[0] == kind])
    )
    beyond_horizon = [f"did:c:{i}" for i in range(4, depth + 1)]
    bystanders = ["did:h:0", "did:h:1", "did:h:2", "did:h:3"]

    report.set(
        "depth_bounded",
        1.0
        if out_a["max_depth"] <= SlashingEngine.MAX_CASCADE_DEPTH
        and all(scores[d] == 0.8 for d in beyond_horizon)
        else 0.0,
    )
    report.set(
        "single_settlement",
        1.0 - fraction(dup, len(settled_dids), empty=0.0),
    )
    report.set(
        "deterministic_settlement", 1.0 if settle_a == settle_b else 0.0
    )
    report.set(
        "honest_preserved",
        1.0 if all(scores[d] == 0.8 for d in bystanders) else 0.0,
    )
    report.details = {
        "max_depth": out_a["max_depth"],
        "duplicates": dup,
        "dedupes": out_a["dedupes"],
        "settled": settled_dids,
    }
    return report


# ── 4. saga compensation storm ───────────────────────────────────────


def compensation_storm(
    seed: int, *, hardened: bool = True, quick: bool = True
) -> ContainmentReport:
    """Mass concurrent saga failures under bounded executor capacity.

    An attacker (or a correlated outage) fails a large cohort of sagas
    in one round, forcing reverse-order compensation for every one of
    them while honest sagas are mid-flight and new work keeps arriving.
    Unhardened, the naive executor splits its per-round capacity fairly
    between compensations and the open workload — the backlog outlives
    the drill. Hardened, the Supervisor's comp-backlog pressure flips
    degraded mode (new arrivals defer, fan-out pauses) and
    `saga_work(comp_budget)` drains a deterministic bounded batch per
    round, compensations first.
    """
    from hypervisor_tpu.models import SessionConfig
    from hypervisor_tpu.ops import saga_ops
    from hypervisor_tpu.resilience.supervisor import Supervisor
    from hypervisor_tpu.state import HypervisorState

    rng = random.Random(seed)
    report = ContainmentReport("compensation_storm", seed, hardened)
    n_honest = 6
    n_storm = 24 if quick else 96
    capacity = 6            # outcomes the executor can settle per round
    rounds = 14 if quick else 40
    arrivals_per_round = 2

    st = HypervisorState()
    sup = Supervisor(
        st,
        degrade_after_comp_backlog=(16 if hardened else 10 ** 9),
        degrade_after_failures=10 ** 9,
        degrade_after_stragglers=10 ** 9,
        degrade_after_capacity=10 ** 9,
        exit_after_clean=4,
        sleep=lambda s: None,
    )
    sess = st.create_session(
        "scn:storm", SessionConfig(min_sigma_eff=0.0), now=0.0
    )
    steps3 = [{"has_undo": True, "retries": 0, "timeout": 300.0}] * 3

    honest_slots = [
        st.create_saga(f"saga:honest:{i}", sess, steps3)
        for i in range(n_honest)
    ]
    storm_slots = [
        st.create_saga(f"saga:storm:{i}", sess, steps3)
        for i in range(n_storm)
    ]
    # Two committed steps per storm saga -> 2 reverse-order undos each.
    st.saga_round(exec_outcomes={s: True for s in storm_slots})
    st.saga_round(exec_outcomes={s: True for s in storm_slots})
    # Honest sagas are mid-flight: one committed step so far.
    st.saga_round(exec_outcomes={s: True for s in honest_slots})

    # The storm: every storm saga fails its third step in ONE round.
    report.attack("storm_fail", n_storm)
    st.saga_round(exec_outcomes={s: False for s in storm_slots})

    peak_backlog = 0
    deferred = 0
    arrived = 0
    for r in range(rounds):
        budget = capacity if hardened and sup.degraded else None
        execute, compensate = sup.dispatch(
            "saga_round_plan", st.saga_work, comp_budget=budget
        )
        peak_backlog = max(peak_backlog, len(compensate))
        if hardened and sup.degraded:
            # Degraded posture: compensations first, remaining capacity
            # settles in-flight forward steps; NEW arrivals defer.
            comp_batch = compensate[:capacity]
            exec_batch = execute[: capacity - len(comp_batch)]
            deferred += arrivals_per_round
        else:
            # Naive fair executor: alternate forward/compensation work
            # and keep accepting the open workload.
            merged: list[tuple[str, tuple[int, int]]] = []
            for i in range(max(len(execute), len(compensate))):
                if i < len(execute):
                    merged.append(("exec", execute[i]))
                if i < len(compensate):
                    merged.append(("comp", compensate[i]))
            batch = merged[:capacity]
            exec_batch = [w for kind, w in batch if kind == "exec"]
            comp_batch = [w for kind, w in batch if kind == "comp"]
            for _ in range(arrivals_per_round):
                st.create_saga(f"saga:new:{arrived}", sess, steps3)
                arrived += 1
        report.record(
            "round", r, len(execute), len(compensate),
            len(exec_batch), len(comp_batch), bool(sup.degraded),
        )
        if not exec_batch and not comp_batch:
            continue
        sup.dispatch(
            "saga_round", st.saga_round,
            exec_outcomes={s: True for s, _ in exec_batch},
            undo_outcomes={s: True for s, _ in comp_batch},
        )

    saga_state = np.asarray(st.sagas.saga_state)
    storm_done = sum(
        1 for s in storm_slots
        if saga_state[s] == saga_ops.SAGA_COMPLETED
    )
    honest_done = sum(
        1 for s in honest_slots
        if saga_state[s] == saga_ops.SAGA_COMPLETED
    )
    _, remaining = st.saga_work()

    report.set("storm_drained",
               1.0 - fraction(len(remaining), n_storm, empty=0.0))
    report.set("compensations_complete",
               fraction(storm_done, n_storm, empty=0.0))
    report.set("honest_inflight_completed",
               fraction(honest_done, n_honest, empty=0.0))
    report.set("invariants_clean",
               1.0 if _sanitize_total(st) == 0 else 0.0)
    if hardened:
        report.set(
            "backpressure_engaged",
            1.0 if sup.comp_backpressure_entries >= 1 else 0.0,
        )
        report.set("degraded_exited", 0.0 if sup.degraded else 1.0)
    report.details = {
        "peak_backlog": peak_backlog,
        "storm_completed": storm_done,
        "honest_completed": honest_done,
        "remaining_compensations": len(remaining),
        "arrivals_accepted": arrived,
        "arrivals_deferred": deferred,
        "degraded_entries": sup.degraded_entries,
    }
    _ = rng  # arrival mix is fixed; rng reserved for future jitter
    return report


# ── 5. byzantine-client API fuzz ─────────────────────────────────────


def byzantine_fuzz(
    seed: int, *, hardened: bool = True, quick: bool = True
) -> ContainmentReport:
    """Malformed / contradictory / replayed calls on the API surface.

    Runs the stdlib HTTP transport (raw malformed bodies, garbage
    query params, unknown routes) AND the service layer (contradictory
    lifecycle sequences, non-finite sigma, replayed requests).
    Containment: every byzantine call is a clean 4xx refusal — never a
    5xx, never a dropped connection, never a table mutation — and the
    honest session keeps serving afterwards with invariants intact.
    (`hardened` is accepted for signature uniformity; the transport
    and input-gate hardening this scenario proves is always-on.)
    """
    import http.client
    import json as _json

    from hypervisor_tpu.api.server import HypervisorHTTPServer
    from hypervisor_tpu.api.service import ApiError, HypervisorService

    rng = random.Random(seed)
    report = ContainmentReport("byzantine_fuzz", seed, hardened)
    n_ops = 40 if quick else 160

    svc = HypervisorService()
    run = asyncio.run

    from hypervisor_tpu.api import models as M

    created = run(svc.create_session(M.CreateSessionRequest(
        creator_did="did:op", min_sigma_eff=0.5
    )))
    sid = created.session_id
    for i in range(3):
        run(svc.join_session(sid, M.JoinSessionRequest(
            agent_did=f"did:honest:{i}", sigma_raw=0.8
        )))
    run(svc.activate_session(sid))
    sigma_before = {
        p["agent_did"]: p["sigma_eff"]
        for p in run(svc.get_session(sid)).model_dump()["participants"]
    }

    server = HypervisorHTTPServer(svc).start()

    def http_op(method, path, body: bytes | None = None,
                headers: dict | None = None) -> int:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=10)
        try:
            hdrs = {"Content-Type": "application/json"}
            hdrs.update(headers or {})
            conn.request(method, path, body=body, headers=hdrs)
            resp = conn.getresponse()
            resp.read()
            return resp.status
        except (ConnectionError, http.client.HTTPException, OSError):
            return -1  # dropped connection = containment failure
        finally:
            conn.close()

    def svc_op(coro_fn, *args) -> int:
        try:
            run(coro_fn(*args))
            return 200
        except ApiError as e:
            return e.status
        except Exception:  # noqa: BLE001 — unhandled = containment failure
            return 599

    junk = ['{"creator_did": ', "<xml>no</xml>", "\x00\xff\xfe", "[1, 2",
            '{"a": NaN}', "", "}{"]
    catalog: list[tuple[str, object, set[int]]] = [
        ("malformed_json", lambda: http_op(
            "POST", "/api/v1/sessions",
            rng.choice(junk).encode("utf-8", "ignore")), {400, 422}),
        ("wrong_types", lambda: http_op(
            "POST", "/api/v1/sessions",
            _json.dumps({"creator_did": rng.randrange(9)}).encode()),
         {200, 201, 400, 422}),
        ("array_body", lambda: http_op(
            "POST", f"/api/v1/sessions/{sid}/join",
            b'[1, 2, 3]'), {400, 422}),
        ("unknown_route", lambda: http_op(
            "POST", f"/api/v1/{rng.choice(['x', 'admin', '..'])}", b"{}"),
         {404}),
        ("bad_query_int", lambda: http_op(
            "GET", "/api/v1/events?limit=" + rng.choice(
                ["abc", "1e9x", "--", "%00"])), {400}),
        # Pydantic tolerates (ignores) stray fields — the valid core
        # admits once, then replays refuse as duplicates; the stdlib
        # fallback models refuse outright.
        ("stray_fields", lambda: http_op(
            "POST", f"/api/v1/sessions/{sid}/join",
            _json.dumps({"agent_did": "did:x", "sigma_raw": 0.7,
                         "root": True}).encode()), {200, 400, 422}),
        ("nan_sigma", lambda: svc_op(
            svc.join_session, sid, M.JoinSessionRequest(
                agent_did=f"did:nan:{rng.randrange(4)}",
                sigma_raw=rng.choice(
                    [float("nan"), float("inf"), -2.0, 7.5]),
            )), {400, 422}),
        ("dup_join", lambda: svc_op(
            svc.join_session, sid, M.JoinSessionRequest(
                agent_did="did:honest:0", sigma_raw=0.8)), {400}),
        ("ghost_session", lambda: svc_op(
            svc.join_session, f"ghost-{rng.randrange(9)}",
            M.JoinSessionRequest(agent_did="did:x", sigma_raw=0.8)),
         {404}),
        ("ghost_terminate", lambda: svc_op(
            svc.terminate_session, f"ghost-{rng.randrange(9)}"), {404}),
        ("self_vouch", lambda: svc_op(
            svc.create_vouch, sid, M.CreateVouchRequest(
                voucher_did="did:honest:1", vouchee_did="did:honest:1",
                voucher_sigma=0.8)), {400, 422}),
        ("nan_vouch", lambda: svc_op(
            svc.create_vouch, sid, M.CreateVouchRequest(
                voucher_did="did:honest:1", vouchee_did="did:honest:2",
                voucher_sigma=0.8, bond_pct=float("nan"))), {400, 422}),
        ("ghost_kill", lambda: svc_op(
            svc.kill_agent, sid, M.KillAgentRequest(
                agent_did=f"did:ghost:{rng.randrange(9)}")),
         {404, 409}),
        ("ghost_leave", lambda: svc_op(
            svc.leave_session, sid, M.LeaveSessionRequest(
                agent_did=f"did:ghost:{rng.randrange(9)}")),
         {404, 409}),
        ("replay_activate", lambda: svc_op(
            svc.activate_session, sid), {400}),
        ("ghost_saga_step", lambda: svc_op(
            svc.execute_saga_step, f"saga-{rng.randrange(9)}", "s0"),
         {404}),
    ]

    failures_5xx = 0
    unexpected = 0
    for i in range(n_ops):
        label, op, expected = catalog[rng.randrange(len(catalog))]
        status = op()
        report.attack("op", i, label, status)
        if status >= 500 or status < 0:
            failures_5xx += 1
        elif status not in expected:
            unexpected += 1

    # Honest traffic must still be served, bit-for-bit governed.
    post_status = svc_op(svc.join_session, sid, M.JoinSessionRequest(
        agent_did="did:honest:99", sigma_raw=0.8))
    honest_ok = post_status == 200
    sigma_after = {
        p["agent_did"]: p["sigma_eff"]
        for p in run(svc.get_session(sid)).model_dump()["participants"]
    }
    sigma_stable = all(
        abs(sigma_after.get(d, -1.0) - v) < 1e-9
        for d, v in sigma_before.items()
    )
    server.stop()

    report.set("no_server_errors",
               1.0 - fraction(failures_5xx, n_ops, empty=0.0))
    report.set("refusals_well_formed",
               1.0 - fraction(unexpected, n_ops, empty=0.0))
    report.set("honest_still_served", 1.0 if honest_ok else 0.0)
    report.set("honest_sigma_preserved", 1.0 if sigma_stable else 0.0)
    report.set("invariants_clean",
               1.0 if _sanitize_total(svc.hv.state) == 0 else 0.0)
    report.details = {
        "ops": n_ops,
        "server_errors": failures_5xx,
        "unexpected_statuses": unexpected,
        "post_attack_join_status": post_status,
    }
    return report


from hypervisor_tpu.adversarial.noisy_neighbor import (  # noqa: E402
    noisy_neighbor,
)

ADVERSARIES = {
    "sybil_flood": sybil_flood,
    "collusion_ring": collusion_ring,
    "slash_cascade": slash_cascade,
    "compensation_storm": compensation_storm,
    "byzantine_fuzz": byzantine_fuzz,
    # Round 16 (tenant-dense serving): one byzantine tenant at full
    # rate — containment scored on its NEIGHBORS (goodput, zero
    # cross-tenant sheds, chain heads bit-identical to a solo oracle).
    "noisy_neighbor": noisy_neighbor,
}

__all__ = [
    "ADVERSARIES",
    "byzantine_fuzz",
    "collusion_ring",
    "compensation_storm",
    "noisy_neighbor",
    "slash_cascade",
    "sybil_flood",
]
