"""Tenant-aware admission + fair-share scheduling over a TenantArena.

Two layers, mirroring the solo serving plane (PR 10):

  * `TenantFrontDoor` — one `serving.FrontDoor` PER TENANT, each bound
    to its `TenantState`. Per-tenant queue quotas fall out of the
    structure: a byzantine or flooding tenant fills ITS OWN bounded
    queues and sheds with ITS OWN typed Refusals — neighbors' tickets,
    SLO burn windows, drain-rate EWMAs, and Retry-After hints live in
    their own doors and are untouched (the noisy-neighbor drill pins
    this, scored like a PR 6 scenario).
  * `TenantWaveScheduler` — the drain. Lifecycles (the tenant-dense
    hot class) coalesce across tenants by DEFICIT ROUND-ROBIN: each
    round every backlogged tenant earns `quantum` lane credits, spends
    up to its deficit, and the takes ride ONE batched tenant wave
    (`TenantArena.governance_wave_batch` — one donated dispatch for
    all T tenants). A flooding tenant can saturate its own lanes but
    never another tenant's share of the bucket. The remaining classes
    (joins, actions, terminations, saga settles) drain through each
    tenant's solo scheduler pass — every tenant dispatches the SAME
    module-level jit programs at the SAME closed bucket shapes, so the
    whole arena warms once and never recompiles.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from hypervisor_tpu.models import SessionConfig
from hypervisor_tpu.ops import admission
from hypervisor_tpu.ops.merkle import BODY_WORDS
from hypervisor_tpu.serving.front_door import (
    FrontDoor,
    Refusal,
    ServingConfig,
    Ticket,
)
from hypervisor_tpu.serving.scheduler import WaveScheduler
from hypervisor_tpu.tenancy.arena import TenantArena

#: Classes each tenant's solo scheduler pass drains (lifecycles go
#: through the batched tenant wave instead).
SOLO_CLASSES = ("join", "action", "terminate", "saga")


class TenantFrontDoor:
    """Per-tenant ingestion doors over one arena."""

    def __init__(
        self,
        arena: TenantArena,
        config: Optional[ServingConfig] = None,
    ) -> None:
        self.arena = arena
        self.config = config or ServingConfig()
        self.doors: list[FrontDoor] = [
            FrontDoor(st, self.config) for st in arena.tenants
        ]

    def door(self, tenant: int) -> FrontDoor:
        return self.doors[tenant]

    # ── submit paths (delegate to the tenant's own door, so quotas,
    # valves, SLO burn, and refusal accounting stay per tenant) ───────

    def submit_lifecycle(self, tenant: int, *a, **kw) -> Ticket | Refusal:
        return self.doors[tenant].submit_lifecycle(*a, **kw)

    def submit_join(self, tenant: int, *a, **kw) -> Ticket | Refusal:
        return self.doors[tenant].submit_join(*a, **kw)

    def submit_action(self, tenant: int, *a, **kw) -> Ticket | Refusal:
        return self.doors[tenant].submit_action(*a, **kw)

    def submit_terminate(self, tenant: int, *a, **kw) -> Ticket | Refusal:
        return self.doors[tenant].submit_terminate(*a, **kw)

    def submit_saga_step(self, tenant: int, *a, **kw) -> Ticket | Refusal:
        return self.doors[tenant].submit_saga_step(*a, **kw)

    def queue_depths(self) -> dict[int, dict[str, int]]:
        return {t: d.queue_depths() for t, d in enumerate(self.doors)}

    def summary(self, top_k: int = 8) -> dict:
        """The `/debug/tenants` payload: the arena's pressure-ranked
        panel joined with each door's serving summary glance row."""
        out = self.arena.summary(top_k=top_k)
        out["serving"] = {
            t: {
                "shed": dict(d.shed),
                "served": dict(d.served),
                "deadline_misses": d.deadline_misses,
                "retry_after_live_s": {
                    q: d.retry_after_for(q) for q in d._queues
                },
            }
            for t, d in enumerate(self.doors)
        }
        return out


class TenantWaveScheduler:
    """Deficit-round-robin drain across T tenants' doors."""

    def __init__(
        self,
        front: TenantFrontDoor,
        quantum: Optional[int] = None,
        lifecycle_config: Optional[SessionConfig] = None,
    ) -> None:
        self.front = front
        self.arena = front.arena
        self.config = front.config
        #: Lane credits a backlogged tenant earns per round. The
        #: default — one full bucket — gives every tenant an equal
        #: claim to the wave's widest shape each round; a smaller
        #: quantum tightens fairness under sustained contention.
        self.quantum = int(quantum or self.config.max_bucket)
        #: Per-tenant quantum overrides (autopilot `drr.quantum` rule:
        #: a tenant burning SLO budget earns boosted credits until it
        #: recovers). Absent tenants earn the base `quantum`.
        self.quanta: dict[int, float] = {}
        self.deficit = [0.0] * front.arena.num_tenants
        self._lifecycle_config = lifecycle_config or SessionConfig(
            min_sigma_eff=0.0, max_participants=4
        )
        # Per-tenant solo passes for the non-lifecycle classes (same
        # shared jit programs, same closed bucket shapes).
        self.solo = [WaveScheduler(d) for d in front.doors]
        self.ticks = 0
        self.lifecycle_rounds = 0

    # ── per-tenant quanta (the autopilot's DRR knob) ─────────────────

    def quantum_of(self, tenant: int) -> float:
        """The tenant's lane credits per round (base unless boosted)."""
        return float(self.quanta.get(tenant, self.quantum))

    def set_quantum(self, tenant: int, quantum: float) -> None:
        """Override one tenant's quantum (reset by passing the base
        value). Takes effect from the NEXT lifecycle round — banked
        deficit is untouched, so fairness history survives the retune."""
        tenant = int(tenant)
        if float(quantum) == float(self.quantum):
            self.quanta.pop(tenant, None)
        else:
            self.quanta[tenant] = float(quantum)

    # ── bucket arithmetic (the solo rule) ────────────────────────────

    def bucket_for(self, n: int) -> int:
        for b in self.config.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"wave of {n} exceeds the largest bucket "
            f"{self.config.max_bucket}"
        )

    def _lifecycle_due(self, now: float) -> bool:
        for d in self.front.doors:
            q = d.lifecycles
            if len(q) >= self.config.max_bucket:
                return True
            if q and (
                now + self.config.dispatch_margin_s
                >= q[0].submitted_at + self.config.lifecycle_deadline_s
            ):
                return True
        return False

    # ── the DRR lifecycle round ──────────────────────────────────────

    def lifecycle_round(self, now: float) -> int:
        """One fair-share round: DRR take per tenant, ONE batched
        session-create + ONE batched tenant wave, tickets resolved
        against their own doors. Returns lifecycles served."""
        takes: dict[int, list[Ticket]] = {}
        for t, d in enumerate(self.front.doors):
            with d._lock:
                q = d.lifecycles
                if not q:
                    # Standard DRR: an idle flow's credit resets, so a
                    # tenant cannot bank credits while idle and burst
                    # past its fair share later.
                    self.deficit[t] = 0.0
                    continue
                self.deficit[t] += self.quantum_of(t)
                n = min(
                    len(q), int(self.deficit[t]), self.config.max_bucket
                )
                if n <= 0:
                    continue
                self.deficit[t] -= n
                takes[t] = [q.popleft() for _ in range(n)]
        if not takes:
            return 0
        self.lifecycle_rounds += 1
        bucket = self.bucket_for(max(len(v) for v in takes.values()))
        turns = self.config.lifecycle_turns
        t0 = time.perf_counter()
        slots = self.arena.create_sessions_batch(
            {t: [tk.payload["session_id"] for tk in v]
             for t, v in takes.items()},
            self._lifecycle_config,
            pad_to=bucket,
        )
        lanes = {}
        for t, tickets in takes.items():
            bodies = np.zeros((turns, len(tickets), BODY_WORDS), np.uint32)
            for i, tk in enumerate(tickets):
                bodies[:, i, :] = tk.payload["bodies"]
            lanes[t] = {
                "session_slots": slots[t],
                "dids": [tk.payload["agent_did"] for tk in tickets],
                "agent_sessions": slots[t].copy(),
                "sigma_raw": np.array(
                    [tk.payload["sigma_raw"] for tk in tickets],
                    np.float32,
                ),
                "delta_bodies": bodies,
                "trustworthy": np.array(
                    [tk.payload["trustworthy"] for tk in tickets], bool
                ),
            }
        out = self.arena.governance_wave_batch(
            lanes, bucket, now=now
        )
        wall = time.perf_counter() - t0
        served = 0
        for t, tickets in takes.items():
            d = self.front.doors[t]
            res = out[t]
            newest = max(tk.submitted_at for tk in tickets)
            with d._lock:
                for i, tk in enumerate(tickets):
                    d.resolve(
                        tk,
                        ok=res.status[i] == admission.ADMIT_OK,
                        now=now,
                        wall_s=wall,
                        status=int(res.status[i]),
                        result={
                            "merkle_root": res.merkle_root[i].tolist()
                        },
                        newest_submit=newest,
                    )
                    served += 1
                d.note_wave("lifecycle", len(tickets), bucket, now=now)
        return served

    # ── the tick ─────────────────────────────────────────────────────

    def tick(self, now: Optional[float] = None) -> dict:
        """One scheduling pass: the DRR lifecycle round when due, then
        every tenant's solo pass for the remaining classes."""
        now = (
            self.arena.tenants[0].now() if now is None else float(now)
        )
        self.ticks += 1
        report = {"lifecycle_rounds": 0, "lifecycles": 0, "solo": 0}
        if self._lifecycle_due(now):
            report["lifecycles"] = self.lifecycle_round(now)
            report["lifecycle_rounds"] = 1
        for sched in self.solo:
            solo_report = sched.tick(now, classes=SOLO_CLASSES)
            report["solo"] += sum(solo_report.values())
        return report

    def drain(self, now: Optional[float] = None, max_ticks: int = 64) -> int:
        """Tick until every tenant's queues are empty."""
        now = (
            self.arena.tenants[0].now() if now is None else float(now)
        )
        waves = 0
        for _ in range(max_ticks):
            pending = any(
                len(q)
                for d in self.front.doors
                for q in d._queues.values()
            )
            if not pending:
                break
            served = self.lifecycle_round(now)
            if served:
                waves += 1
            for d, sched in zip(self.front.doors, self.solo):
                if any(len(d._queues[c]) for c in SOLO_CLASSES):
                    waves += sched.drain(now, max_ticks=1)
        return waves

    # ── warmup ───────────────────────────────────────────────────────

    def warm(self, now: Optional[float] = None) -> dict:
        """Compile the whole serving tile set: the (bucket, T) tenant
        wave pairs via `TenantArena.warm`, plus tenant 0's solo pass
        (every non-lifecycle program at every bucket — all tenants
        share those programs and shapes, so one tenant's warm covers
        the arena). A warmed arena soak holds ZERO recompiles
        (test-pinned, the closed-bucket contract with a tenant axis).
        """
        now = (
            self.arena.tenants[0].now() if now is None else float(now)
        )
        self.arena.warm(
            self.config.buckets,
            now,
            session_config=self._lifecycle_config,
            turns=self.config.lifecycle_turns,
        )
        return self.solo[0].warm(now)


__all__ = ["SOLO_CLASSES", "TenantFrontDoor", "TenantWaveScheduler"]
