"""TenantArena: the `[T, …]` state layer — one donated dispatch for T.

The arena OWNS the device state: every tenant's AgentTable /
SessionTable / VouchTable / SagaTable / ElevationTable, DeltaLog /
EventLog / TraceLog rings, and metrics columns live STACKED along a
leading tenant axis in `_stacked`. Tenants are full `HypervisorState`s
(`TenantState`) whose table attributes route through the arena's
lend/commit component protocol:

  * **lend** — reading `tenant.agents` materialises that tenant's
    slice of the stack on demand and caches it (`_tenant_local`), so
    every existing host op — joins, vouches, sagas, WAL records,
    checkpoints, integrity repairs — works unchanged, per tenant.
  * **commit** — writing any table attribute marks the tenant dirty;
    `sync()` writes dirty slices back into the stack (`.at[t].set`)
    before the next batched dispatch.
  * **invalidate** — a batched wave rebinds the stacks (its outputs
    alias the donated inputs) and drops every tenant's cached slices.

The hot path never materialises per-tenant state: a serving round is
ONE `_TENANT_SESSIONS_CREATE` dispatch (all tenants' session creates),
ONE `_TENANT_WAVE_DONATED` dispatch (the fused governance wave vmapped
across tenants — bit-identical per tenant to the solo program, pinned
by tests/unit/test_tenancy.py), and the drain is ONE `device_get` of
the stacked metrics table fanned into per-tenant mirrors with
`tenant="<id>"` labels. Isolation is structural: a tenant's rows live
in its own slice of every stack, its refusals ride its own FrontDoor
queues, and the noisy-neighbor drill pins neighbors' chain heads
bit-identical to a solo oracle run.
"""

from __future__ import annotations

import threading
from contextlib import ExitStack
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from hypervisor_tpu.config import DEFAULT_CONFIG, HypervisorConfig
from hypervisor_tpu.models import SessionConfig, SessionState
from hypervisor_tpu.observability import health as health_plane
from hypervisor_tpu.observability import metrics as metrics_plane
from hypervisor_tpu.observability import roofline as roofline_plane
from hypervisor_tpu.observability import tracing as trace_plane
from hypervisor_tpu.ops import admission, wave_blocks
from hypervisor_tpu.ops.merkle import BODY_WORDS
from hypervisor_tpu.state import (
    HypervisorState,
    _DONATION_CACHE_SALT,
    _TENANT_SESSIONS_CREATE,
    _TENANT_UPDATE_GAUGES,
    _TENANT_WAVE,
    _TENANT_WAVE_DONATED,
    _donate_debug,
    _donate_tables,
    _poison_donated,
)

#: The stacked components, in seal order. Direct state attributes plus
#: the two device planes routed through the factory hooks
#: (`_make_metrics` / `_make_tracer`).
COMPONENTS: tuple[str, ...] = (
    "agents",
    "sessions",
    "vouches",
    "sagas",
    "elevations",
    "delta_log",
    "event_log",
    "metrics_table",
    "trace_table",
)
#: Components the batched wave writes (and, donated, consumes).
_WAVE_WRITES = (
    "agents", "sessions", "vouches", "metrics_table", "delta_log",
)

_MISSING = object()


def _component_property(name: str):
    def _get(self):
        return self._comp_get(name)

    def _set(self, value):
        self._comp_set(name, value)

    return property(_get, _set)


class TenantState(HypervisorState):
    """One tenant's `HypervisorState`, tables lent from the arena.

    Before the arena seals (during `__init__`), components live in
    `_tenant_local` like any solo state. After `TenantArena._seal`,
    the stacked copy is authoritative: reads materialise + cache a
    slice, writes mark the tenant dirty for the next `sync()`.
    """

    def __init__(
        self, config: HypervisorConfig = DEFAULT_CONFIG
    ) -> None:
        self._tenant_local: dict = {}
        self._tenant_arena: Optional["TenantArena"] = None
        self._tenant_idx: int = -1
        super().__init__(config)

    # Direct table attributes route through the component protocol.
    agents = _component_property("agents")
    sessions = _component_property("sessions")
    vouches = _component_property("vouches")
    sagas = _component_property("sagas")
    elevations = _component_property("elevations")
    delta_log = _component_property("delta_log")
    event_log = _component_property("event_log")

    def _make_metrics(self) -> "metrics_plane.Metrics":
        return _TenantMetrics(self)

    def _make_tracer(self, capacity: int) -> "trace_plane.Tracer":
        return _TenantTracer(self, capacity)

    def _comp_get(self, name: str):
        local = self._tenant_local.get(name, _MISSING)
        if local is not _MISSING:
            return local
        arena = self._tenant_arena
        if arena is None:
            raise AttributeError(
                f"tenant component {name!r} unset before first write"
            )
        value = arena.materialize(self._tenant_idx, name)
        self._tenant_local[name] = value
        return value

    def _comp_set(self, name: str, value) -> None:
        self._tenant_local[name] = value
        arena = self._tenant_arena
        if arena is not None:
            arena.note_dirty(self._tenant_idx, name)


class _TenantMetrics(metrics_plane.Metrics):
    """Metrics plane whose device table lives in the arena stack."""

    def __init__(self, owner: TenantState) -> None:
        self._owner = owner
        super().__init__()

    @property
    def table(self):
        return self._owner._comp_get("metrics_table")

    @table.setter
    def table(self, value) -> None:
        self._owner._comp_set("metrics_table", value)


class _TenantTracer(trace_plane.Tracer):
    """Tracer whose device ring lives in the arena stack."""

    def __init__(self, owner: TenantState, capacity: int) -> None:
        self._owner = owner
        super().__init__(capacity=capacity)

    @property
    def table(self):
        return self._owner._comp_get("trace_table")

    @table.setter
    def table(self, value) -> None:
        self._owner._comp_set("trace_table", value)


class _StaticFootprint:
    """Cached `footprint()` carrier for the health plane: per-tenant
    table footprints are pure config-derived metadata, computed once at
    seal — publishing them must not materialise T slices per drain."""

    def __init__(self, fp: dict) -> None:
        self._fp = fp

    def footprint(self) -> dict:
        return self._fp


class TenantWaveOut:
    """One tenant's view of a batched wave's results (host numpy,
    trimmed to the tenant's real lane/session counts)."""

    __slots__ = ("tenant", "status", "merkle_root", "fsm_error")

    def __init__(self, tenant, status, merkle_root, fsm_error):
        self.tenant = tenant
        self.status = status
        self.merkle_root = merkle_root
        self.fsm_error = fsm_error


class TenantArena:
    """T logical hypervisors behind one donated dispatch.

    Concurrency discipline: SUBMITS are free-threaded (they are
    host-only — per-door queues, staging queues, shed gates), but
    DISPATCHES — the batched waves here and any per-tenant solo wave —
    must come from one drain thread (the `TenantWaveScheduler`), the
    same serialized-driver contract the solo FrontDoor documents for
    donation. A solo dispatch reads tenant tables (materialising
    slices under the arena lock) while holding the tenant's staging
    lock; a concurrent batched dispatch takes the locks in the
    opposite order, so two concurrent dispatch threads could deadlock
    — one drain thread makes the ordering moot, exactly as today's
    scheduler does.
    """

    def __init__(
        self,
        num_tenants: int,
        config: HypervisorConfig = DEFAULT_CONFIG,
    ) -> None:
        if num_tenants < 1:
            raise ValueError("num_tenants must be >= 1")
        self.config = config
        self.num_tenants = num_tenants
        # One lock for stack mutation (sync/dispatch/drain). Per-tenant
        # host ops take their own tenant locks as always.
        self._lock = threading.RLock()
        self.tenants: list[TenantState] = [
            TenantState(config) for _ in range(num_tenants)
        ]
        # Arena-level host metrics plane: stage brackets for the
        # batched dispatches (a T-tenant wall is not any one tenant's
        # latency) and the roofline observatory's measured-walls join.
        self.metrics = metrics_plane.Metrics()
        self._stacked: dict = {}
        self._dirty: dict[str, set] = {name: set() for name in COMPONENTS}
        self._footprints: dict[str, dict] = {}
        self.waves = 0            # batched governance waves dispatched
        self.last_wave: dict = {}
        self._seal()

    # ── the component protocol ───────────────────────────────────────

    def _get_component(self, state: TenantState, name: str):
        if name == "metrics_table":
            return state.metrics.table
        if name == "trace_table":
            return state.tracer.table
        return getattr(state, name)

    def _seal(self) -> None:
        """Stack every tenant's components into the `[T, …]` pytrees
        and flip the tenants to arena-backed reads."""
        cap = self.config.capacity
        for name in COMPONENTS:
            vals = [self._get_component(st, name) for st in self.tenants]
            if all(v is None for v in vals):
                self._stacked[name] = None
            else:
                self._stacked[name] = jax.tree.map(
                    lambda *xs: jnp.stack(xs), *vals
                )
        # Static per-tenant footprints (pure metadata), from tenant 0's
        # pre-seal locals — identical across tenants by construction.
        st0 = self.tenants[0]
        rows = {
            "agents": cap.max_agents,
            "sessions": cap.max_sessions,
            "vouches": cap.max_vouch_edges,
            "sagas": cap.max_sagas,
            "elevations": cap.max_elevations,
            "delta_log": cap.delta_log_capacity,
            "event_log": cap.event_log_capacity,
        }
        for name in COMPONENTS:
            val = self._get_component(st0, name)
            if val is None:
                continue
            bytes_ = sum(
                int(getattr(leaf, "nbytes", 0))
                for leaf in jax.tree.leaves(val)
            )
            key = {
                "metrics_table": "metrics", "trace_table": "trace_log",
            }.get(name, name)
            self._footprints[key] = {
                "bytes": bytes_,
                "capacity_rows": rows.get(name, 0),
            }
        for t, st in enumerate(self.tenants):
            st._tenant_arena = self
            st._tenant_idx = t
            st._tenant_local.clear()

    def materialize(self, tenant: int, name: str):
        stacked = self._stacked[name]
        if stacked is None:
            return None
        with self._lock:
            return jax.tree.map(lambda x: x[tenant], stacked)

    def note_dirty(self, tenant: int, name: str) -> None:
        with self._lock:
            self._dirty[name].add(tenant)

    def sync(self) -> int:
        """Write every dirty tenant slice back into the stacks; returns
        the number of (tenant, component) writebacks. Dispatched before
        every batched program so slow-path host ops (vouching, saga
        creation, integrity repairs, per-tenant solo waves) and the
        batched hot path see one coherent state."""
        wrote = 0
        with self._lock:
            for name in COMPONENTS:
                dirty = self._dirty[name]
                if not dirty:
                    continue
                for t in sorted(dirty):
                    local = self.tenants[t]._tenant_local.get(
                        name, _MISSING
                    )
                    if local is _MISSING:
                        continue
                    if self._stacked[name] is None:
                        continue
                    self._stacked[name] = jax.tree.map(
                        lambda s, l: s.at[t].set(l),
                        self._stacked[name],
                        local,
                    )
                    wrote += 1
                dirty.clear()
        return wrote

    def _invalidate(self, names: Sequence[str]) -> None:
        """Drop every tenant's cached slices for `names` (the stack is
        authoritative again — e.g. right after a batched wave rebound
        it). Dirty slices must have been synced first."""
        for name in names:
            assert not self._dirty[name], (
                f"invalidate of {name} would drop unsynced tenant "
                f"writes {sorted(self._dirty[name])}"
            )
            for st in self.tenants:
                st._tenant_local.pop(name, None)

    def splice_tenant(self, tenant: int, recovered) -> None:
        """Replace one arena slot's ENTIRE state with a recovered solo
        `HypervisorState` — the absorb half of fleet failover: a dead
        worker's tenant, restored from its durable checkpoint + WAL
        suffix (`resilience.recovery.recover_tenant`), lands in a
        survivor's pre-warmed slot.

        The splice goes through the component protocol (`_comp_set` +
        `sync`), so the `[T, …]` stacked shapes never change — a warmed
        survivor absorbs with ZERO recompiles. The recovered state's
        capacity config must match this arena's (`adopt_host_from`
        refuses otherwise). Metrics/trace tables are not checkpointed,
        so the recovered state carries fresh ones — the splice wipes
        the slot's observability rings cleanly rather than leaking the
        previous occupant's telemetry into the new tenant's view.
        """
        t = int(tenant)
        if not 0 <= t < self.num_tenants:
            raise ValueError(
                f"splice_tenant: slot {t} outside arena of "
                f"{self.num_tenants}"
            )
        with self._lock:
            self.sync()
            st = self.tenants[t]
            # Host bookkeeping first: it validates capacity parity
            # before any table write lands in the stacks.
            st.adopt_host_from(recovered)
            for name in COMPONENTS:
                if name == "metrics_table":
                    value = recovered.metrics.table
                elif name == "trace_table":
                    value = recovered.tracer.table
                else:
                    value = getattr(recovered, name)
                if value is None:
                    continue
                st._comp_set(name, value)
            st._gauges_fresh = False
            self.sync()

    # ── batched session creation ─────────────────────────────────────

    def create_sessions_batch(
        self,
        ids_per_tenant: dict[int, list[str]],
        config: SessionConfig,
        pad_to: Optional[int] = None,
    ) -> dict[int, np.ndarray]:
        """Allocate each tenant's session rows in HANDSHAKING — ONE
        vmapped donated dispatch for every tenant's creates (the
        batched twin of `HypervisorState.create_sessions_batch`; the
        session config is uniform across the round, mixed configs go
        through the per-tenant solo path). Returns tenant -> slots.

        `pad_to` pins the [T, K] lane shape to a serving bucket so the
        program family stays CLOSED (the scheduler always passes its
        round's bucket; an unpadded call compiles per distinct K)."""
        with self._lock:
            self.sync()
            k_max = max(
                (len(v) for v in ids_per_tenant.values()), default=0
            )
            if k_max == 0:
                return {}
            if pad_to is not None:
                if pad_to < k_max:
                    raise ValueError(
                        f"pad_to {pad_to} below the widest tenant "
                        f"batch {k_max}"
                    )
                k_max = int(pad_to)
            t_count = self.num_tenants
            rows = np.zeros((t_count, k_max), np.int32)
            sids = np.zeros((t_count, k_max), np.int32)
            valid = np.zeros((t_count, k_max), bool)
            slots_out: dict[int, np.ndarray] = {}
            for t, ids in sorted(ids_per_tenant.items()):
                if not ids:
                    continue
                st = self.tenants[t]
                slots = st._stage_sessions_batch(ids, config)
                slots_out[t] = slots
                rows[t, : len(ids)] = slots
                sids[t, : len(ids)] = [
                    st.session_ids.intern(s) for s in ids
                ]
                valid[t, : len(ids)] = True
            with self.metrics.stage("tenant_sessions_create"):
                self._stacked["sessions"] = _TENANT_SESSIONS_CREATE(
                    self._stacked["sessions"],
                    jnp.asarray(rows),
                    jnp.asarray(sids),
                    jnp.asarray(valid),
                    jnp.int32(SessionState.HANDSHAKING.code),
                    jnp.int32(config.consistency_mode.code),
                    jnp.int32(config.max_participants),
                    jnp.float32(config.min_sigma_eff),
                    jnp.asarray(bool(config.enable_audit)),
                )
            self._invalidate(("sessions",))
        return slots_out

    # ── the batched governance wave ──────────────────────────────────

    def governance_wave_batch(
        self,
        lanes_per_tenant: dict[int, dict],
        bucket: int,
        now: float,
        omega: float = 0.5,
    ) -> dict[int, TenantWaveOut]:
        """The tenant-dense hot path: every participating tenant's
        fused governance wave as ONE donated XLA program.

        `lanes_per_tenant[t]` carries that tenant's wave inputs —
        `session_slots` (freshly created, contiguous), `dids`,
        `agent_sessions`, `sigma_raw`, `delta_bodies`
        (u32[turns, k, BODY_WORDS]) and optional `trustworthy` — each
        at most `bucket` lanes. Tenants absent from the dict idle
        through the wave as all-padding lanes (their rows untouched;
        the [T] program shape is closed per (bucket, T) tile, so a
        warmed arena never recompiles — the solo scheduler's
        closed-bucket contract, extended with the tenant axis).

        Per-tenant semantics are EXACTLY `run_governance_wave(...,
        pad_to=(bucket, bucket))`: same staging, same WAL record, same
        membership/audit/frontier bookkeeping, bit-identical tables
        (test-pinned) — which is what makes WAL replay through the
        solo program, and the noisy-neighbor drill's solo oracle
        comparison, sound.
        """
        turns = None
        for spec in lanes_per_tenant.values():
            t_this = np.asarray(spec["delta_bodies"]).shape[0]
            if turns is None:
                turns = t_this
            elif turns != t_this:
                raise ValueError(
                    "every tenant's delta_bodies must share one turn "
                    f"count (got {turns} and {t_this})"
                )
        if turns is None:
            turns = 1
        with self._lock:
            # Pre-dispatch gates per participating tenant (chaos,
            # scheduled corruption, integrity cadence) BEFORE sync so
            # injected table damage rides the writeback.
            sanitize = False
            armed: list[TenantState] = []
            for t in sorted(lanes_per_tenant):
                st = self.tenants[t]
                st._predispatch("governance_wave", fused_sanitizer=True)
                plane = st.integrity
                if plane is not None and plane.take_fused_due():
                    sanitize = True
                    armed.append(st)
            self.sync()

            # Per-tenant host staging (numpy only), then ONE stack.
            staged: dict[int, dict] = {}
            handles: dict[int, object] = {}
            slots_by_t: dict[int, np.ndarray] = {}
            journals = ExitStack()
            for t in range(self.num_tenants):
                st = self.tenants[t]
                spec = lanes_per_tenant.get(t)
                if spec is None:
                    session_slots = np.zeros((0,), np.int32)
                    dids: list = []
                    agent_sessions = np.zeros((0,), np.int32)
                    sigma_raw = np.zeros((0,), np.float32)
                    bodies = np.zeros((turns, 0, BODY_WORDS), np.uint32)
                    trustworthy = None
                else:
                    session_slots = np.asarray(
                        spec["session_slots"], np.int32
                    )
                    dids = list(spec["dids"])
                    agent_sessions = np.asarray(
                        spec["agent_sessions"], np.int32
                    )
                    sigma_raw = np.asarray(
                        spec["sigma_raw"], np.float32
                    )
                    bodies = np.asarray(spec["delta_bodies"], np.uint32)
                    trustworthy = spec.get("trustworthy")
                    if len(dids) > bucket or len(session_slots) > bucket:
                        raise ValueError(
                            f"tenant {t} wave ({len(dids)} lanes, "
                            f"{len(session_slots)} sessions) exceeds "
                            f"bucket {bucket}"
                        )
                    if st.journal is not None:
                        journals.enter_context(
                            st._journal(
                                "governance_wave",
                                session_slots=session_slots,
                                dids=dids,
                                agent_sessions=agent_sessions,
                                sigma_raw=sigma_raw,
                                delta_bodies=bodies,
                                now=float(now),
                                omega=float(omega),
                                trustworthy=(
                                    None
                                    if trustworthy is None
                                    else np.asarray(trustworthy, bool)
                                ),
                                use_pallas=False,
                                actions=None,
                                pad_to=[bucket, bucket],
                            )
                        )
                slots_by_t[t] = session_slots
                agent_slots = st._claim_wave_rows(bucket)
                parked = st._park_sessions(
                    bucket - len(session_slots), "tenant bucket"
                )
                sw = st._stage_wave_lanes(
                    session_slots, dids, agent_sessions, sigma_raw,
                    trustworthy, bodies, bucket, bucket, parked,
                )
                sw["agent_slots"] = agent_slots
                if sw["range_host"] is None:
                    raise RuntimeError(
                        "tenant wave sessions must be contiguous (fresh "
                        "arena-created blocks always are)"
                    )
                staged[t] = sw
                handles[t] = st.tracer.begin_wave(
                    "governance_wave",
                    sessions=sw["wave_sessions"][: sw["k"]],
                    lanes=sw["b"],
                    device=False,
                )
            # Pre-wave cursors for the audit bookkeeping: [T] in one
            # host sync off the stacked ring.
            base_rows = np.asarray(
                self._stacked["delta_log"].cursor
            ).astype(np.int64)

            def col(key, dtype=None):
                arr = np.stack([staged[t][key] for t in range(
                    self.num_tenants)])
                return jnp.asarray(
                    arr if dtype is None else arr.astype(dtype)
                )

            lanes_valid = np.zeros((self.num_tenants, bucket), bool)
            n_sessions_valid = np.zeros((self.num_tenants,), np.int32)
            los = np.zeros((self.num_tenants,), np.int32)
            his = np.zeros((self.num_tenants,), np.int32)
            slot_stack = np.zeros(
                (self.num_tenants, bucket), np.int32
            )
            for t in range(self.num_tenants):
                sw = staged[t]
                lanes_valid[t, : sw["b"]] = True
                n_sessions_valid[t] = sw["k"]
                los[t], his[t] = sw["range_host"]
                slot_stack[t] = sw["agent_slots"]

            donated = _donate_tables()
            wave = _TENANT_WAVE_DONATED if donated else _TENANT_WAVE
            poison = (
                tuple(
                    self._stacked[name] for name in _WAVE_WRITES
                )
                if donated and _donate_debug()
                else None
            )
            with journals:
                with self.metrics.stage("tenant_governance_wave"):
                    result = wave(
                        self._stacked["agents"],
                        self._stacked["sessions"],
                        self._stacked["vouches"],
                        self._stacked["metrics_table"],
                        self._stacked["delta_log"],
                        self._stacked["sagas"],
                        self._stacked["event_log"],
                        self._stacked["elevations"],
                        jnp.asarray(slot_stack),
                        col("did"),
                        col("agent_sessions"),
                        col("sigma_raw"),
                        col("trustworthy"),
                        col("duplicate"),
                        col("wave_sessions"),
                        col("bodies"),
                        jnp.asarray(los),
                        jnp.asarray(his),
                        jnp.asarray(lanes_valid),
                        jnp.asarray(n_sessions_valid),
                        jnp.float32(now),
                        jnp.float32(omega),
                        self.tenants[0]._ring_bursts,
                        trust=self.config.trust,
                        breach=self.config.breach,
                        rate_limit=self.config.rate_limit,
                        sanitize=sanitize,
                        config=self.config,
                        cache_salt=(
                            _DONATION_CACHE_SALT if donated else 0.0
                        ),
                        wave_kernels=wave_blocks.wave_kernels_enabled(),
                    )
            # Rebind the stacks to the wave outputs (the donated inputs
            # are dead buffers now) and drop every cached slice.
            self._stacked["agents"] = result.agents
            self._stacked["sessions"] = result.sessions
            self._stacked["vouches"] = result.vouches
            self._stacked["metrics_table"] = result.metrics
            self._stacked["delta_log"] = result.delta_log
            if poison is not None:
                _poison_donated(*poison)
            self._invalidate(_WAVE_WRITES)
            self.waves += 1

            # Host fan-out: ONE fetch per result field, numpy slices
            # per tenant for the bookkeeping and the callers' tickets.
            status = np.asarray(result.status)          # [T, bucket]
            chain = np.array(result.chain, copy=True)   # [T, turns, bucket, 8]
            roots = np.array(result.merkle_root, copy=True)
            fsm_err = np.asarray(result.fsm_error)
            out: dict[int, TenantWaveOut] = {}
            sanitizer_by_t = {}
            if sanitize and armed:
                for st in armed:
                    t = st._tenant_idx
                    sanitizer_by_t[t] = jax.tree.map(
                        lambda x, _t=t: (
                            x[_t] if hasattr(x, "shape") else x
                        ),
                        result.sanitizer,
                    )
            for t in range(self.num_tenants):
                st = self.tenants[t]
                sw = staged[t]
                b, k = sw["b"], sw["k"]
                ok = status[t, :b] == admission.ADMIT_OK
                st._publish_wave_members(
                    sw["wave_keys"][ok].tolist(),
                    recycle_rows=sw["agent_slots"].tolist(),
                )
                if k:
                    st._book_wave_audit(
                        slots_by_t[t], chain[t][:, :k], int(base_rows[t])
                    )
                st._gauges_fresh = True
                th = handles[t]
                if th is not None:
                    st.tracer.stamp_wave_host(th)
                    st.tracer.end_wave(th)
                if t in sanitizer_by_t and st.integrity is not None:
                    st.integrity.absorb_fused(sanitizer_by_t[t])
                if t in lanes_per_tenant:
                    out[t] = TenantWaveOut(
                        tenant=t,
                        status=status[t, :b],
                        merkle_root=roots[t, :k],
                        fsm_error=fsm_err[t, :k],
                    )
            self.last_wave = {
                "tenants_served": len(lanes_per_tenant),
                "bucket": bucket,
                "sanitized": bool(sanitize),
            }
        return out

    # ── drain: one device_get for all T tenants ──────────────────────

    def metrics_snapshot(self) -> dict[int, "metrics_plane.MetricsSnapshot"]:
        """Drain every tenant's metrics plane out of ONE stacked
        `device_get`. Gauges are fresh when the last dispatch was a
        fused tenant wave (its in-program tail refreshed all T
        tenants); otherwise one vmapped `update_gauges` refreshes the
        stack first (uncommitted, like the solo drain)."""
        with self._lock:
            self.sync()
            table = self._stacked["metrics_table"]
            if not all(st._gauges_fresh for st in self.tenants):
                table = _TENANT_UPDATE_GAUGES(
                    table,
                    self._stacked["agents"],
                    self._stacked["sessions"],
                    self._stacked["vouches"],
                    self._stacked["sagas"],
                    self._stacked["elevations"],
                    self._stacked["delta_log"],
                    self._stacked["event_log"],
                    self._stacked["trace_table"],
                )
            host = jax.device_get(table)
        shims = {
            name: _StaticFootprint(fp)
            for name, fp in self._footprints.items()
        }
        snaps: dict[int, metrics_plane.MetricsSnapshot] = {}
        for t, st in enumerate(self.tenants):
            health_plane.publish_compile_counters(st.metrics)
            roofline_plane.publish(st.metrics)
            st.health.publish_footprints(shims)
            host_t = jax.tree.map(lambda x: np.asarray(x)[t], host)
            snap = st.metrics.snapshot(host_table=host_t)
            st.health.update_occupancy(snap)
            if st.integrity is not None:
                st.integrity.observe_snapshot(snap)
            snaps[t] = snap
        # The arena's own host plane (stage walls for the batched
        # programs) publishes through the same drain pass.
        health_plane.publish_compile_counters(self.metrics)
        roofline_plane.publish(self.metrics)
        return snaps

    def metrics_prometheus(self) -> str:
        """One merged exposition: every tenant's series stamped with
        its `tenant="<id>"` label (per-class serving latency, SLO burn,
        sheds, occupancy — the ISSUE 15 per-tenant histogram fix),
        headers once, plus the arena's own stage brackets under
        `tenant="arena"`."""
        snaps = self.metrics_snapshot()
        parts = [
            snaps[t].to_prometheus(
                extra_labels={"tenant": str(t)}, emit_headers=(t == 0)
            )
            for t in sorted(snaps)
        ]
        parts.append(
            self.metrics.snapshot().to_prometheus(
                extra_labels={"tenant": "arena"}, emit_headers=False
            )
        )
        return "".join(parts)

    # ── summaries (what /debug/tenants and hv_top render) ────────────

    def summary(self, top_k: int = 8) -> dict:
        """The tenants panel: per-tenant live rows, queue depths, shed
        rates, SLO burn states — ranked by PRESSURE (deepest queues +
        burn) so hv_top's top-K row shows the tenants that matter."""
        rows = []
        for t, st in enumerate(self.tenants):
            serving = st.serving
            depths: dict = {}
            shed = 0
            enqueued = 0
            burn = {}
            if serving is not None:
                depths = serving.queue_depths()
                shed = sum(serving.shed.values())
                enqueued = sum(serving.enqueued.values())
                burn = {
                    q: serving.slo.state_of(q)
                    for q in serving._queues
                }
            offered = enqueued + shed
            depth_total = sum(depths.values())
            burning = sum(1 for s in burn.values() if s != "ok")
            rows.append(
                {
                    "tenant": t,
                    "sessions_live": len(st._audit_rows),
                    "members": len(st._members),
                    "queue_depth": depth_total,
                    "queues": depths,
                    "shed": shed,
                    "shed_rate": (
                        round(shed / offered, 4) if offered else 0.0
                    ),
                    "slo_states": burn,
                    "pressure": depth_total + 64 * burning + shed,
                }
            )
        ranked = sorted(
            rows, key=lambda r: r["pressure"], reverse=True
        )
        return {
            "num_tenants": self.num_tenants,
            "waves": self.waves,
            "last_wave": dict(self.last_wave),
            "top_k": ranked[: max(1, top_k)],
            "tenants": rows,
        }

    # ── warmup ───────────────────────────────────────────────────────

    def warm(
        self,
        buckets: Sequence[int],
        now: float,
        session_config: Optional[SessionConfig] = None,
        turns: int = 1,
    ) -> dict:
        """Compile the (bucket, T) tenant-wave tile set (+ the sanitize
        variant when any tenant carries an integrity plane) so a
        serving soak holds ZERO post-warmup recompiles — the solo
        scheduler's closed-bucket contract with the tenant axis
        attached. Returns the compile-telemetry totals afterward."""
        cfg = session_config or SessionConfig(
            min_sigma_eff=0.0, max_participants=4
        )
        body_words = BODY_WORDS
        planes = [
            st.integrity
            for st in self.tenants
            if st.integrity is not None
        ]
        sanitize_passes = (False, True) if planes else (False,)
        for bucket in sorted(set(buckets)):
            for sanitized in sanitize_passes:
                if sanitized:
                    for plane in planes:
                        plane._fused_due = True
                ids = {
                    0: [f"tenant:warm:b{bucket}:s{int(sanitized)}"]
                }
                slots = self.create_sessions_batch(
                    ids, cfg, pad_to=bucket
                )
                self.governance_wave_batch(
                    {
                        0: {
                            "session_slots": slots[0],
                            "dids": [
                                f"did:tenant:warm:b{bucket}"
                                f":s{int(sanitized)}"
                            ],
                            "agent_sessions": slots[0].copy(),
                            "sigma_raw": np.full(1, 0.8, np.float32),
                            "delta_bodies": np.zeros(
                                (turns, 1, body_words), np.uint32
                            ),
                        }
                    },
                    bucket,
                    now=now,
                )
        # The drain's refresh program (stale-gauge fallback) compiles
        # here too, so a mid-soak scrape never counts as fresh compile.
        self.tenants[0]._gauges_fresh = False
        self.metrics_snapshot()
        summary = health_plane.compile_summary(last=0)
        return {
            k: summary[k]
            for k in (
                "programs", "compiles", "recompiles",
                "donation_failures",
            )
        }


__all__ = ["TenantArena", "TenantState", "TenantWaveOut", "COMPONENTS"]
