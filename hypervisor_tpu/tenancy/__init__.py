"""Tenant-dense serving: T logical hypervisors, one donated dispatch.

ROOFLINE.md shows a full 10k-agent instance occupies ~15.4 MB of a
16 GB HBM — three orders of magnitude of headroom — while every wave
dispatch serves exactly ONE logical hypervisor. This package makes
tenancy a leading ARRAY AXIS instead of a deployment:

  * `TenantArena` — stacks every per-tenant table/ring into one
    `[T, …]` pytree and dispatches the PR 9 fused governance wave
    vmapped across tenants: ONE donated XLA program, one donation
    frontier, one drain `device_get` for all T tenants
    (`state._TENANT_WAVE_DONATED`).
  * `TenantState` — a `HypervisorState` whose device tables live in
    the arena's stacks (lend/commit component protocol): every host
    op, WAL record, checkpoint, and integrity hook works unchanged,
    per tenant.
  * `TenantFrontDoor` / `TenantWaveScheduler` — per-tenant admission
    quotas (a flooding tenant sheds against its OWN queues) and
    deficit-round-robin fair-share bucket filling across tenants.
  * `noisy_neighbor` (in `hypervisor_tpu.testing.scenarios` wiring) —
    the isolation drill: a byzantine tenant at full rate must leave
    every neighbor's chain heads bit-identical to a solo run.

docs/OPERATIONS.md "Tenant-dense serving" is the operator runbook.
"""

from hypervisor_tpu.tenancy.arena import TenantArena, TenantState
from hypervisor_tpu.tenancy.front_door import (
    TenantFrontDoor,
    TenantWaveScheduler,
)

__all__ = [
    "TenantArena",
    "TenantFrontDoor",
    "TenantState",
    "TenantWaveScheduler",
]
