"""Reversibility registry: action -> (Execute_API, Undo_API, omega).

Capability parity with reference `reversibility/registry.py:31-107`:
session-scoped entries populated from IATP manifests, undo lookup for saga
rollback, non-reversible detection (drives STRONG-mode forcing in the
facade), and undo-API health marking.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from hypervisor_tpu.models import ActionDescriptor, ReversibilityLevel

__all__ = ["ReversibilityEntry", "ReversibilityRegistry"]


@dataclass
class ReversibilityEntry:
    action_id: str
    execute_api: str
    undo_api: Optional[str]
    reversibility: ReversibilityLevel
    undo_window_seconds: int
    compensation_method: Optional[str]
    risk_weight: float
    undo_api_healthy: bool = True
    last_health_check: Optional[str] = None


class ReversibilityRegistry:
    """Session-scoped action reversibility map."""

    def __init__(self, session_id: str) -> None:
        self.session_id = session_id
        self._entries: dict[str, ReversibilityEntry] = {}
        self._non_reversible = 0  # running count: O(1) has_non_reversible

    def register(self, action: ActionDescriptor) -> ReversibilityEntry:
        prior = self._entries.get(action.action_id)
        if prior is not None and prior.reversibility is ReversibilityLevel.NONE:
            self._non_reversible -= 1
        entry = ReversibilityEntry(
            action_id=action.action_id,
            execute_api=action.execute_api,
            undo_api=action.undo_api,
            reversibility=action.reversibility,
            undo_window_seconds=action.undo_window_seconds,
            compensation_method=action.compensation_method,
            risk_weight=action.risk_weight,
        )
        self._entries[action.action_id] = entry
        if entry.reversibility is ReversibilityLevel.NONE:
            self._non_reversible += 1
        return entry

    def register_from_manifest(self, actions: list[ActionDescriptor]) -> int:
        for action in actions:
            self.register(action)
        return len(actions)

    def get(self, action_id: str) -> Optional[ReversibilityEntry]:
        return self._entries.get(action_id)

    def get_undo_api(self, action_id: str) -> Optional[str]:
        entry = self._entries.get(action_id)
        return entry.undo_api if entry else None

    def is_reversible(self, action_id: str) -> bool:
        entry = self._entries.get(action_id)
        return entry is not None and entry.reversibility is not ReversibilityLevel.NONE

    def get_risk_weight(self, action_id: str) -> float:
        entry = self._entries.get(action_id)
        if entry is None:
            return ReversibilityLevel.NONE.default_risk_weight
        return entry.risk_weight

    def has_non_reversible_actions(self) -> bool:
        return self._non_reversible > 0

    def mark_undo_unhealthy(self, action_id: str) -> None:
        entry = self._entries.get(action_id)
        if entry is not None:
            entry.undo_api_healthy = False

    @property
    def entries(self) -> list[ReversibilityEntry]:
        return list(self._entries.values())

    @property
    def non_reversible_actions(self) -> list[str]:
        return [
            e.action_id
            for e in self._entries.values()
            if e.reversibility is ReversibilityLevel.NONE
        ]
