"""Reversibility registry: action -> (Execute_API, Undo_API, omega).

Capability parity with reference `reversibility/registry.py:31-107`
(session-scoped entries populated from IATP manifests, undo lookup for
saga rollback, non-reversible detection driving STRONG-mode forcing in
the facade, undo-API health marking) — stored columnar: action ids are
interned to dense rows and every per-action attribute lives in a
parallel column, so the facade's hot checks (`has_non_reversible_actions`
at join time) and the device plane's omega/ring gathers read vectors,
not object graphs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from hypervisor_tpu.models import ActionDescriptor, ReversibilityLevel
from hypervisor_tpu.tables.intern import InternTable

__all__ = ["ReversibilityEntry", "ReversibilityRegistry"]

_LEVELS = (ReversibilityLevel.FULL, ReversibilityLevel.PARTIAL, ReversibilityLevel.NONE)
_LEVEL_CODE = {lvl: i for i, lvl in enumerate(_LEVELS)}
_NONE_CODE = _LEVEL_CODE[ReversibilityLevel.NONE]


@dataclass
class ReversibilityEntry:
    action_id: str
    execute_api: str
    undo_api: Optional[str]
    reversibility: ReversibilityLevel
    undo_window_seconds: int
    compensation_method: Optional[str]
    risk_weight: float
    undo_api_healthy: bool = True
    last_health_check: Optional[str] = None


class ReversibilityRegistry:
    """Session-scoped reversibility table (interned rows, parallel columns)."""

    _GROW = 16

    def __init__(self, session_id: str) -> None:
        self.session_id = session_id
        self._ids = InternTable()
        self._filled = 0
        self._non_reversible = 0  # running count: O(1) hot-path check
        self._rev = np.zeros(0, np.int8)
        self._omega = np.zeros(0, np.float32)
        self._window = np.zeros(0, np.int32)
        self._healthy = np.zeros(0, np.bool_)
        self._execute: list[str] = []
        self._undo: list[Optional[str]] = []
        self._comp: list[Optional[str]] = []

    # ── registration ────────────────────────────────────────────────────

    def register(self, action: ActionDescriptor) -> ReversibilityEntry:
        row = self._ids.intern(action.action_id)
        if row >= len(self._rev):
            extra = max(self._GROW, row + 1 - len(self._rev))
            self._rev = np.concatenate([self._rev, np.zeros(extra, np.int8)])
            self._omega = np.concatenate([self._omega, np.zeros(extra, np.float32)])
            self._window = np.concatenate([self._window, np.zeros(extra, np.int32)])
            self._healthy = np.concatenate(
                [self._healthy, np.zeros(extra, np.bool_)]
            )
        while len(self._execute) <= row:
            self._execute.append("")
            self._undo.append(None)
            self._comp.append(None)
        if row < self._filled and int(self._rev[row]) == _NONE_CODE:
            self._non_reversible -= 1  # re-registering an existing action
        self._rev[row] = _LEVEL_CODE[action.reversibility]
        if _LEVEL_CODE[action.reversibility] == _NONE_CODE:
            self._non_reversible += 1
        self._omega[row] = action.risk_weight
        self._window[row] = action.undo_window_seconds
        self._healthy[row] = True
        self._execute[row] = action.execute_api
        self._undo[row] = action.undo_api
        self._comp[row] = action.compensation_method
        self._filled = max(self._filled, row + 1)
        return self._view(row)

    def register_from_manifest(self, actions: list[ActionDescriptor]) -> int:
        for action in actions:
            self.register(action)
        return len(actions)

    # ── lookups ─────────────────────────────────────────────────────────

    def get(self, action_id: str) -> Optional[ReversibilityEntry]:
        row = self._ids.lookup(action_id)
        return self._view(row) if row >= 0 else None

    def get_undo_api(self, action_id: str) -> Optional[str]:
        row = self._ids.lookup(action_id)
        return self._undo[row] if row >= 0 else None

    def is_reversible(self, action_id: str) -> bool:
        row = self._ids.lookup(action_id)
        return row >= 0 and int(self._rev[row]) != _NONE_CODE

    def get_risk_weight(self, action_id: str) -> float:
        row = self._ids.lookup(action_id)
        if row < 0:
            return ReversibilityLevel.NONE.default_risk_weight
        return float(self._omega[row])

    def has_non_reversible_actions(self) -> bool:
        return self._non_reversible > 0

    def mark_undo_unhealthy(self, action_id: str) -> None:
        row = self._ids.lookup(action_id)
        if row >= 0:
            self._healthy[row] = False

    # ── bulk views ──────────────────────────────────────────────────────

    @property
    def entries(self) -> list[ReversibilityEntry]:
        return [self._view(row) for row in range(self._filled)]

    @property
    def non_reversible_actions(self) -> list[str]:
        rows = np.nonzero(self._rev[: self._filled] == _NONE_CODE)[0]
        return [self._ids.string(int(row)) for row in rows]

    def omega_column(self) -> np.ndarray:
        """f32[N] risk weights in row order — the device gather source."""
        return self._omega[: self._filled].copy()

    def _view(self, row: int) -> ReversibilityEntry:
        return ReversibilityEntry(
            action_id=self._ids.string(row),
            execute_api=self._execute[row],
            undo_api=self._undo[row],
            reversibility=_LEVELS[int(self._rev[row])],
            undo_window_seconds=int(self._window[row]),
            compensation_method=self._comp[row],
            risk_weight=float(self._omega[row]),
            undo_api_healthy=bool(self._healthy[row]),
        )
