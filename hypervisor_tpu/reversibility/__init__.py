"""Reversibility registry: action -> (Execute_API, Undo_API, omega).

Capability parity with reference `reversibility/registry.py:31-107`
(session-scoped entries populated from IATP manifests, undo lookup for
saga rollback, non-reversible detection driving STRONG-mode forcing in
the facade, undo-API health marking) — stored columnar: action ids are
interned to dense rows and every per-action attribute lives in a
parallel column, so the facade's hot checks (`has_non_reversible_actions`
at join time) and the device plane's omega/ring gathers read vectors,
not object graphs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from hypervisor_tpu.models import ActionDescriptor, ReversibilityLevel
from hypervisor_tpu.tables.intern import ColumnStore

__all__ = ["ReversibilityEntry", "ReversibilityRegistry"]

_LEVELS = (ReversibilityLevel.FULL, ReversibilityLevel.PARTIAL, ReversibilityLevel.NONE)
_LEVEL_CODE = {lvl: i for i, lvl in enumerate(_LEVELS)}
_NONE_CODE = _LEVEL_CODE[ReversibilityLevel.NONE]


@dataclass
class ReversibilityEntry:
    action_id: str
    execute_api: str
    undo_api: Optional[str]
    reversibility: ReversibilityLevel
    undo_window_seconds: int
    compensation_method: Optional[str]
    risk_weight: float
    undo_api_healthy: bool = True
    last_health_check: Optional[str] = None


class ReversibilityRegistry:
    """Session-scoped reversibility table (interned rows, parallel columns)."""

    def __init__(self, session_id: str) -> None:
        self.session_id = session_id
        self._non_reversible = 0  # running count: O(1) hot-path check
        self._t = ColumnStore(
            grow=16,
            rev=np.int8,
            omega=np.float32,
            window=np.int32,
            healthy=np.bool_,
        )
        self._execute: list[str] = []
        self._undo: list[Optional[str]] = []
        self._comp: list[Optional[str]] = []

    # ── registration ────────────────────────────────────────────────────

    def register(self, action: ActionDescriptor) -> ReversibilityEntry:
        row, is_new = self._t.row_for(action.action_id)
        while len(self._execute) <= row:
            self._execute.append("")
            self._undo.append(None)
            self._comp.append(None)
        if not is_new and int(self._t.rev[row]) == _NONE_CODE:
            self._non_reversible -= 1  # re-registering an existing action
        self._t.rev[row] = _LEVEL_CODE[action.reversibility]
        if _LEVEL_CODE[action.reversibility] == _NONE_CODE:
            self._non_reversible += 1
        self._t.omega[row] = action.risk_weight
        self._t.window[row] = action.undo_window_seconds
        self._t.healthy[row] = True
        self._execute[row] = action.execute_api
        self._undo[row] = action.undo_api
        self._comp[row] = action.compensation_method
        return self._view(row)

    def register_from_manifest(self, actions: list[ActionDescriptor]) -> int:
        for action in actions:
            self.register(action)
        return len(actions)

    # ── lookups ─────────────────────────────────────────────────────────

    def get(self, action_id: str) -> Optional[ReversibilityEntry]:
        row = self._t.lookup(action_id)
        return self._view(row) if row >= 0 else None

    def get_undo_api(self, action_id: str) -> Optional[str]:
        row = self._t.lookup(action_id)
        return self._undo[row] if row >= 0 else None

    def is_reversible(self, action_id: str) -> bool:
        row = self._t.lookup(action_id)
        return row >= 0 and int(self._t.rev[row]) != _NONE_CODE

    def get_risk_weight(self, action_id: str) -> float:
        row = self._t.lookup(action_id)
        if row < 0:
            return ReversibilityLevel.NONE.default_risk_weight
        return float(self._t.omega[row])

    def has_non_reversible_actions(self) -> bool:
        return self._non_reversible > 0

    def mark_undo_unhealthy(self, action_id: str) -> None:
        row = self._t.lookup(action_id)
        if row >= 0:
            self._t.healthy[row] = False

    # ── bulk views ──────────────────────────────────────────────────────

    @property
    def entries(self) -> list[ReversibilityEntry]:
        return [self._view(row) for row in range(len(self._t))]

    @property
    def non_reversible_actions(self) -> list[str]:
        rows = np.nonzero(self._t.filled("rev") == _NONE_CODE)[0]
        return [self._t.key_of(int(row)) for row in rows]

    def omega_column(self) -> np.ndarray:
        """f32[N] risk weights in row order — the device gather source."""
        return self._t.filled("omega").copy()

    def _view(self, row: int) -> ReversibilityEntry:
        return ReversibilityEntry(
            action_id=self._t.key_of(row),
            execute_api=self._execute[row],
            undo_api=self._undo[row],
            reversibility=_LEVELS[int(self._t.rev[row])],
            undo_window_seconds=int(self._t.window[row]),
            compensation_method=self._comp[row],
            risk_weight=float(self._t.omega[row]),
            undo_api_healthy=bool(self._t.healthy[row]),
        )
