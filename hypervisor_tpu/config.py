"""Centralized typed configuration for the TPU-native hypervisor.

The reference scatters its knobs across engine-level class constants
(ring thresholds `rings/enforcer.py:38-39`, bond/exposure limits
`liability/vouching.py:52-55`, cascade depth + sigma floor
`liability/slashing.py:54-55`, breach thresholds
`rings/breach_detector.py:67-72`, per-ring rate limits
`security/rate_limiter.py:52-57`, GC retention `audit/gc.py:39-45`).
Here every knob lives in one frozen dataclass so the device ops can bake
them as compile-time constants (hashable static args to `jax.jit`) or
receive them as scalars inside kernels.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class TrustConfig:
    """Trust-score (sigma) and ring-threshold knobs.

    Parity: thresholds match reference `models.py:34-42`,
    `rings/enforcer.py:38-39`, `liability/vouching.py:52-55`,
    `liability/slashing.py:54-55`.
    """

    ring1_threshold: float = 0.95
    ring2_threshold: float = 0.60
    score_scale: float = 1000.0          # Nexus 0-1000 -> 0.0-1.0
    min_voucher_sigma: float = 0.50
    default_bond_pct: float = 0.20
    max_exposure: float = 0.80           # of voucher sigma, across vouchees
    max_cascade_depth: int = 2
    sigma_floor: float = 0.05
    cascade_wipe_epsilon: float = 0.01   # sigma_after < floor+eps => cascade


@dataclasses.dataclass(frozen=True)
class BreachConfig:
    """Sliding-window ring-breach detection (reference `rings/breach_detector.py:45-77`)."""

    window_seconds: float = 60.0
    window_capacity: int = 1000
    min_calls_for_analysis: int = 5
    low_threshold: float = 0.3
    medium_threshold: float = 0.5
    high_threshold: float = 0.7
    critical_threshold: float = 0.9
    circuit_breaker_cooldown_seconds: float = 30.0


@dataclasses.dataclass(frozen=True)
class ElevationConfig:
    """Sudo-with-TTL ring elevation (reference `rings/elevation.py:53-54`)."""

    default_ttl_seconds: float = 300.0
    max_ttl_seconds: float = 3600.0


@dataclasses.dataclass(frozen=True)
class RateLimitConfig:
    """Per-ring token-bucket defaults (reference `security/rate_limiter.py:52-57`).

    Index by ring number 0..3: (rate_per_second, burst).
    """

    ring_rates: tuple[float, float, float, float] = (100.0, 50.0, 20.0, 5.0)
    ring_bursts: tuple[float, float, float, float] = (200.0, 100.0, 40.0, 10.0)


@dataclasses.dataclass(frozen=True)
class LedgerConfig:
    """Liability-ledger risk scoring (reference `liability/ledger.py:69-71,103-157`)."""

    slash_weight: float = 0.15
    quarantine_weight: float = 0.10
    fault_weight: float = 0.05
    clean_session_credit: float = 0.05
    probation_threshold: float = 0.3
    deny_threshold: float = 0.6


@dataclasses.dataclass(frozen=True)
class QuarantineConfig:
    """Quarantine manager defaults (reference `liability/quarantine.py:68`)."""

    default_duration_seconds: float = 300.0


@dataclasses.dataclass(frozen=True)
class RetentionPolicy:
    """Ephemeral-GC retention (reference `audit/gc.py:39-45`)."""

    delta_retention_days: int = 90
    keep_summary_hash_permanently: bool = True
    purge_vfs_on_terminate: bool = True


@dataclasses.dataclass(frozen=True)
class VerifierConfig:
    """Transaction-history verification (reference `verification/history.py:61`)."""

    min_history_depth: int = 5
    min_hash_length: int = 16


@dataclasses.dataclass(frozen=True)
class TableCapacity:
    """Static capacities for the HBM-resident tables.

    Dynamic membership (joins/leaves/vouches) lives inside
    capacity-preallocated arrays with active-masks; these set the
    preallocation. Compile-time constants for the device ops.
    """

    max_agents: int = 16_384
    max_sessions: int = 4_096
    max_vouch_edges: int = 65_536
    max_sagas: int = 8_192
    max_steps_per_saga: int = 16
    max_elevations: int = 4_096
    delta_log_capacity: int = 65_536
    event_log_capacity: int = 65_536
    trace_log_capacity: int = 8_192
    max_participants_per_session: int = 64


@dataclasses.dataclass(frozen=True)
class HypervisorConfig:
    """Top-level config composing every subsystem's knobs."""

    trust: TrustConfig = TrustConfig()
    breach: BreachConfig = BreachConfig()
    elevation: ElevationConfig = ElevationConfig()
    rate_limit: RateLimitConfig = RateLimitConfig()
    ledger: LedgerConfig = LedgerConfig()
    quarantine: QuarantineConfig = QuarantineConfig()
    retention: RetentionPolicy = RetentionPolicy()
    verifier: VerifierConfig = VerifierConfig()
    capacity: TableCapacity = TableCapacity()

    def replace(self, **kw) -> "HypervisorConfig":
        return dataclasses.replace(self, **kw)


DEFAULT_CONFIG = HypervisorConfig()
