"""Force-CPU JAX bootstrap shared by tests/conftest.py and __graft_entry__.py.

Hermetic virtual-mesh runs (sharding validation on N virtual CPU devices)
must never initialize the default backend: the shell environment routes it
at a real-accelerator tunnel (JAX_PLATFORMS=axon) whose plugin may be
broken or version-mismatched. This module deliberately does NOT import jax
at module level so it can run before the first jax import — the env-var
route is the only one that both (a) stops the default-platform plugin from
loading and (b) keeps XLA:CPU on its fast compile path (an explicit
jax.config.update("jax_platforms", ...) switches XLA:CPU client creation
onto a path observed to take >9 min instead of 11 s for a ~6k-op unrolled
SHA-256 program).
"""

from __future__ import annotations

import os
import re
import sys
import tempfile

_COUNT_FLAG = "xla_force_host_platform_device_count"


def cache_dir() -> str:
    """Per-user persistent compilation cache path.

    A fixed world-readable path would let one local user's cache entries
    be deserialized by another (cache poisoning) or block writes when the
    directory is owned by someone else.
    """
    return os.path.join(
        tempfile.gettempdir(), f"jax_cache_{os.getuid()}"
    )


def force_cpu_platform(n_devices: int = 8) -> None:
    """Pin JAX to the CPU platform with >= n_devices virtual devices.

    Call before the first jax import for the fast, fully-hermetic path.
    If jax was already imported (e.g. by an entry-point plugin or the
    calling driver) but the CPU backend has not been created yet, the
    XLA_FLAGS edit below still takes effect (flags are read at backend
    init) and an explicit jax.devices("cpu") request bypasses a captured
    non-cpu JAX_PLATFORMS. The one unrecoverable case is a CPU backend
    already initialized with fewer than n_devices — that surfaces later
    as mesh._device_pool's ValueError naming this flag.
    """
    jax_loaded = "jax" in sys.modules
    if not jax_loaded:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir())

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"--{_COUNT_FLAG}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = f"{flags} --{_COUNT_FLAG}={n_devices}".strip()
    elif int(m.group(1)) < n_devices:
        # No-op if the CPU backend already consumed the old value.
        os.environ["XLA_FLAGS"] = re.sub(
            rf"--{_COUNT_FLAG}=\d+", f"--{_COUNT_FLAG}={n_devices}", flags
        )

    if jax_loaded:
        import jax

        # Exact match required: a captured "axon,cpu" still initializes
        # the axon plugin on the first backend query.
        if str(jax.config.jax_platforms or "") != "cpu":
            # MUST be the config route: jax.devices("cpu") would initialize
            # every registered plugin (including the real-accelerator
            # tunnel, which hangs this process when the tunnel is down —
            # observed live). Restricting jax_platforms to "cpu" keeps all
            # other plugins untouched. The slow-compile cliff previously
            # attributed to this route does not reproduce with the
            # persistent compilation cache configured (1.5 s for the
            # unrolled SHA-256 program).
            jax.config.update("jax_platforms", "cpu")
        if not jax.config.jax_compilation_cache_dir:
            jax.config.update("jax_compilation_cache_dir", cache_dir())
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def arm_device_watchdog(seconds: float = 600.0, what: str = "device discovery"):
    """Bounded guard against a wedged accelerator tunnel.

    The environment's real-TPU plugin connects through a tunnel that can
    hang indefinitely (observed live: `jax.devices()` never returns).
    Arm this before the first backend query; call the returned disarm()
    once devices respond. If the deadline passes first, the process
    prints a diagnostic and exits nonzero — a recorded failure instead
    of an unbounded hang.
    """
    import threading

    done = threading.Event()

    def tripwire():
        if not done.wait(seconds):
            sys.stderr.write(
                f"FATAL: {what} did not complete within {seconds:.0f}s — "
                "accelerator tunnel appears wedged; aborting instead of "
                "hanging.\n"
            )
            sys.stderr.flush()
            os._exit(17)

    threading.Thread(target=tripwire, daemon=True).start()
    return done.set
