"""Full benchmark suite: every reference metric, batched the TPU way.

Mirrors the metric set of the reference harness
(`benchmarks/bench_hypervisor.py:40-304`, results in
`benchmarks/results/benchmarks.json`) plus the BASELINE.md batch configs
(Merkle over 1k deltas, 5-step saga with retry+compensation, vouch+bond+
slash over 1k DIDs). The reference measures one Python call at a time; the
TPU-native equivalent of a "call" is one batched device tick, so every
metric reports:

  * batch_p50_ms    — wall-clock p50 of one jitted tick (device round trip)
  * per_op_us       — batch p50 divided by the batch size
  * throughput      — ops per second at the measured p50
  * vs_baseline     — reference p50 (single-op, CPU Python) / per_op_us

Methodology matches the reference: perf_counter_ns, 10% warmup, p50/p95/p99
over the remaining iterations (`bench_hypervisor.py:40-114`). Results are
written to benchmarks/results/benchmarks.json and BENCHMARKS.md.

Run: python benchmarks/bench_suite.py [--iters N] [--quick]
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# Reference p50s in µs (BASELINE.md table).
BASELINE_P50_US = {
    "ring_computation": 0.2,
    "vouching_sigma_eff": 666.2,
    "delta_capture": 27.3,
    "merkle_root_10_deltas": 352.9,
    "merkle_root_100_deltas": 3381.4,
    "chain_verify_50_deltas": 2011.0,
    "session_lifecycle": 54.0,
    "saga_3_steps": 151.2,
    "full_governance_pipeline": 267.5,
}


def _percentiles(ns: list[int]) -> dict:
    arr = np.asarray(sorted(ns), np.float64)
    q = lambda p: float(np.percentile(arr, p))
    return {
        "mean_ns": float(arr.mean()),
        "p50_ns": q(50),
        "p95_ns": q(95),
        "p99_ns": q(99),
    }


def bench(fn, args, iters: int, batch: int, name: str) -> dict:
    """Time a jitted fn (10% warmup, like bench_hypervisor.py:40-114)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)  # compile outside the timed region
    warmup = max(1, iters // 10)
    samples = []
    for i in range(warmup + iters):
        t0 = time.perf_counter_ns()
        jax.block_until_ready(fn(*args))
        dt = time.perf_counter_ns() - t0
        if i >= warmup:
            samples.append(dt)
    stats = _percentiles(samples)
    per_op_us = stats["p50_ns"] / 1000.0 / batch
    rec = {
        "name": name,
        "batch": batch,
        "iterations": iters,
        "batch_p50_ms": stats["p50_ns"] / 1e6,
        "batch_mean_ms": stats["mean_ns"] / 1e6,
        "batch_p95_ms": stats["p95_ns"] / 1e6,
        "batch_p99_ms": stats["p99_ns"] / 1e6,
        "per_op_us": per_op_us,
        "throughput_ops_s": batch / (stats["p50_ns"] / 1e9),
        "_samples_ns": samples,  # stripped before writing results
    }
    base = BASELINE_P50_US.get(name)
    if base is not None:
        rec["baseline_p50_us"] = base
        rec["vs_baseline"] = base / per_op_us if per_op_us > 0 else float("inf")
    return rec


def build_benchmarks(quick: bool):
    """Yield (name, fn, args, batch) tuples; all fns jitted."""
    import jax
    import jax.numpy as jnp

    from hypervisor_tpu.ops import liability as liab_ops
    from hypervisor_tpu.ops import merkle as merkle_ops
    from hypervisor_tpu.ops import rings as ring_ops
    from hypervisor_tpu.ops import saga_ops
    from hypervisor_tpu.ops.admission import admit_batch
    from hypervisor_tpu.ops.pipeline import governance_pipeline
    from hypervisor_tpu.tables.state import AgentTable, SessionTable, VouchTable

    rng = np.random.RandomState(0)
    S = 2_048 if quick else 10_000

    # ── ring_computation ────────────────────────────────────────────────
    sigma = jnp.asarray(rng.uniform(0, 1, S).astype(np.float32))
    yield "ring_computation", jax.jit(ring_ops.compute_rings), (sigma,), S

    # ── vouching_sigma_eff: 1k vouchees, 4k edges (BASELINE config) ────
    n_agents, n_edges = 1024, 4096
    vouch = VouchTable.create(n_edges)
    import dataclasses

    vouch = dataclasses.replace(
        vouch,
        voucher=jnp.asarray(rng.randint(0, n_agents, n_edges, dtype=np.int64), jnp.int32),
        vouchee=jnp.asarray(rng.randint(0, n_agents, n_edges, dtype=np.int64), jnp.int32),
        session=jnp.zeros((n_edges,), jnp.int32),
        bond=jnp.asarray(rng.uniform(0.05, 0.2, n_edges).astype(np.float32)),
        active=jnp.ones((n_edges,), bool),
        expiry=jnp.full((n_edges,), np.inf, jnp.float32),
    )
    session_of_agent = jnp.zeros((n_agents,), jnp.int32)
    base_sigma = jnp.asarray(rng.uniform(0.4, 0.9, n_agents).astype(np.float32))
    omega = jnp.full((n_agents,), 0.55, jnp.float32)

    def sigma_eff_batch(v, sess, sig, om):
        contrib = liab_ops.contribution_by_agent(v, sess, 0.0)
        return liab_ops.sigma_eff(sig, om, contrib)

    yield "vouching_sigma_eff", jax.jit(sigma_eff_batch), (
        vouch, session_of_agent, base_sigma, omega,
    ), n_agents

    # ── delta_capture: one chained delta per lane over S lanes ─────────
    bodies1 = jnp.asarray(
        rng.randint(0, 2**32, (1, S, merkle_ops.BODY_WORDS), dtype=np.uint64
                    ).astype(np.uint32)
    )
    yield "delta_capture", jax.jit(merkle_ops.chain_digests), (bodies1,), S

    # ── merkle roots at 10 / 100 / 1000 deltas ─────────────────────────
    # Measured through the tree unit's HOST dispatch — the path the
    # audit plane actually takes for bulk recompute: one Mosaic MTU
    # launch on TPU, the native C++ tree builder on CPU backends, the
    # jitted XLA loop only where neither exists (the fallback matrix in
    # docs/OPERATIONS.md "Audit hashing & the tree unit").
    def leaves_of(p, lanes):
        return rng.randint(0, 2**32, (lanes, p, 8), dtype=np.uint64).astype(
            np.uint32
        )

    mr = merkle_ops.tree_roots_host
    lanes10 = 256 if quick else 1024
    yield "merkle_root_10_deltas", mr, (
        leaves_of(16, lanes10), np.full(lanes10, 10, np.int32),
    ), lanes10
    lanes100 = 64 if quick else 256
    yield "merkle_root_100_deltas", mr, (
        leaves_of(128, lanes100), np.full(lanes100, 100, np.int32),
    ), lanes100
    lanes1k = 16 if quick else 64
    yield "merkle_root_1000_deltas", mr, (
        leaves_of(1024, lanes1k), np.full(lanes1k, 1000, np.int32),
    ), lanes1k

    # ── chain_verify_50_deltas over parallel lanes ─────────────────────
    lanes_v = 128 if quick else 512
    bodies50 = rng.randint(
        0, 2**32, (50, lanes_v, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    recorded = np.asarray(merkle_ops.chain_digests(jnp.asarray(bodies50)))
    counts50 = np.full(lanes_v, 50, np.int32)
    yield "chain_verify_50_deltas", merkle_ops.verify_chain_digests_host, (
        bodies50, recorded, counts50,
    ), lanes_v

    # ── scrub_sweep: one full-history Merkle sweep, budgeted strips ────
    # The integrity plane's steady-state consumer of hash throughput:
    # a seeded multi-session DeltaLog history fully re-verified by the
    # scrubber (seed links, interior links, committed heads) through
    # the same tree unit. per-op = one verified link/head.
    from hypervisor_tpu.integrity.scrubber import MerkleScrubber
    from hypervisor_tpu.models import SessionConfig
    from hypervisor_tpu.state import HypervisorState

    st_scrub = HypervisorState()
    s_sess, s_turns = (4, 64) if quick else (8, 128)
    scrub_slots = st_scrub.create_sessions_batch(
        [f"scrub:{i}" for i in range(s_sess)],
        SessionConfig(min_sigma_eff=0.0),
    )
    for t in range(s_turns):
        for s in scrub_slots:
            st_scrub.stage_delta(
                int(s), 0, ts=float(t),
                change_words=rng.randint(
                    0, 2**32, 8, dtype=np.uint64
                ).astype(np.uint32),
            )
    st_scrub.flush_deltas()
    scrubber = MerkleScrubber(st_scrub, budget=256)

    def scrub_sweep():
        scrubber._pos = scrubber.sweep_size  # force a fresh sweep
        verified = 0
        while True:
            rep = scrubber.tick()
            verified += rep["links"] + rep["heads"]
            if rep["sweep_completed"]:
                return np.int64(verified)

    sweep_batch = int(scrub_sweep())
    yield "scrub_sweep", scrub_sweep, (), sweep_batch

    # ── session_lifecycle: admit a wave of S agents into S sessions ────
    agents = AgentTable.create(1 << (S - 1).bit_length())
    sessions = SessionTable.create(1 << (S - 1).bit_length())
    from hypervisor_tpu.tables.struct import replace as t_replace

    # struct.replace, not dataclasses.replace: state/max_participants/
    # min_sigma_eff are packed virtual columns now.
    sessions = t_replace(
        sessions,
        state=sessions.state.at[:S].set(1),  # HANDSHAKING
        max_participants=sessions.max_participants.at[:].set(10),
        min_sigma_eff=sessions.min_sigma_eff.at[:].set(0.6),
    )
    slot = jnp.arange(S, dtype=jnp.int32)
    did = jnp.arange(S, dtype=jnp.int32)
    sess_slot = jnp.arange(S, dtype=jnp.int32)
    sig_join = jnp.full((S,), 0.8, jnp.float32)
    trustworthy = jnp.ones((S,), bool)
    dup = jnp.zeros((S,), bool)

    def lifecycle(a, s, slot, did, ss, sig, tw, dup):
        r = admit_batch(a, s, slot, did, ss, sig, tw, dup, 0.0)
        # activate + terminate + archive the sessions (masked FSM walk)
        ok = r.status == 0
        st = r.sessions.state
        st = jnp.where(ok & (st[ss] == 1), 2, st[ss])  # ACTIVE
        st = jnp.where(ok, 4, st)                      # -> ARCHIVED
        return r.ring, st

    yield "session_lifecycle", jax.jit(lifecycle), (
        agents, sessions, slot, did, sess_slot, sig_join, trustworthy, dup,
    ), S

    # ── saga_3_steps: 3-step ladder over S sagas ───────────────────────
    def saga3(success):
        state = jnp.full(success.shape, saga_ops.STEP_PENDING, jnp.int8)
        retries = jnp.zeros(success.shape, jnp.int8)
        for _ in range(3):
            state, retries = saga_ops.execute_attempt(state, success, retries)
            state = jnp.where(
                state == saga_ops.STEP_COMMITTED, saga_ops.STEP_PENDING, state
            ).astype(jnp.int8)
        return state

    succ = jnp.ones((S,), bool)
    yield "saga_3_steps", jax.jit(saga3), (succ,), S

    # ── saga_5_steps_retry_compensate (BASELINE config) ────────────────
    def saga5(fail_step, has_undo):
        g = fail_step.shape[0]
        n_steps = 5
        states = jnp.full((g, n_steps), saga_ops.STEP_PENDING, jnp.int8)
        retries = jnp.full((g, n_steps), 1, jnp.int8)
        for i in range(n_steps):
            success = fail_step != i
            st, rt = saga_ops.execute_attempt(states[:, i], success, retries[:, i])
            # one retry for the transient half of failures
            st, rt = saga_ops.execute_attempt(
                st, success | (fail_step % 2 == 0), rt
            )
            states = states.at[:, i].set(st)
            retries = retries.at[:, i].set(rt)
        any_failed = jnp.any(states == saga_ops.STEP_FAILED, axis=1)
        comp = saga_ops.compensation_pass(
            states, has_undo[:, None], jnp.ones_like(states, bool)
        )
        states = jnp.where(any_failed[:, None], comp, states).astype(jnp.int8)
        return states

    g5 = S
    fail_step = jnp.asarray(rng.randint(-1, 5, g5, dtype=np.int64), jnp.int32)
    has_undo = jnp.asarray(rng.uniform(0, 1, g5) > 0.1)
    yield "saga_5_steps_retry_compensate", jax.jit(saga5), (fail_step, has_undo), g5

    # ── vouch_bond_slash_1k: cascade over 1k DIDs (BASELINE config) ────
    seeds = jnp.zeros((n_agents,), bool).at[jnp.asarray(
        rng.choice(n_agents, 32, replace=False))].set(True)

    def slash1k(v, sig, seeds):
        return liab_ops.slash_cascade(v, sig, seeds, 0, 0.95, 0.0).sigma

    yield "vouch_bond_slash_1k", jax.jit(slash1k), (
        vouch, base_sigma, seeds,
    ), n_agents

    # ── vouch_bond_slash_10k: north-star scale on the MXU path ─────────
    # Multi-tile matmul cascade (kernels/liability_pallas) — the Pallas
    # kernel on TPU, its bit-identical dense twin elsewhere.
    n10 = 2_048 if quick else 10_240
    e10 = 8_192
    vouch10 = dataclasses.replace(
        VouchTable.create(e10),
        voucher=jnp.asarray(rng.randint(0, n10, e10, dtype=np.int64), jnp.int32),
        vouchee=jnp.asarray(rng.randint(0, n10, e10, dtype=np.int64), jnp.int32),
        session=jnp.zeros((e10,), jnp.int32),
        bond=jnp.asarray(rng.uniform(0.05, 0.2, e10).astype(np.float32)),
        active=jnp.ones((e10,), bool),
        expiry=jnp.full((e10,), np.inf, jnp.float32),
    )
    sigma10 = jnp.asarray(rng.uniform(0.4, 0.9, n10).astype(np.float32))
    seeds10 = jnp.zeros((n10,), bool).at[jnp.asarray(
        rng.choice(n10, 128, replace=False))].set(True)
    from hypervisor_tpu.kernels.liability_pallas import (
        slash_cascade_dense,
        slash_cascade_pallas,
    )
    from hypervisor_tpu.kernels.sha256_pallas import pallas_available

    mxu_slash = slash_cascade_pallas if pallas_available() else slash_cascade_dense

    def slash10k(v, sig, seeds):
        return mxu_slash(v, sig, seeds, 0, 0.95, 0.0).sigma

    yield "vouch_bond_slash_10k_mxu", slash10k, (
        vouch10, sigma10, seeds10,
    ), n10

    # ── action_gateway_10k: every per-action gate, one fused wave ──────
    # 10k actions by 10k standing agents through breaker → quarantine →
    # ring → rate → breach recording (`ops.gateway.check_actions`) —
    # the wave the scalar reference path walks one gate-per-round-trip
    # at a time. Duplicate slots (~spread 2x) exercise the sequential
    # rate settle; a privileged-probe stripe exercises the in-wave
    # breaker prefix.
    from hypervisor_tpu.ops import gateway as gateway_ops
    from hypervisor_tpu.tables.state import ElevationTable

    n_gw = S
    ag = AgentTable.create(n_gw)
    ag = dataclasses.replace(
        ag,
        f32=ag.f32.at[:, 1].set(0.8).at[:, 4].set(40.0),  # sigma_eff, tokens
        i32=ag.i32.at[:, 0].set(jnp.arange(n_gw, dtype=jnp.int32))
        .at[:, 1].set(0),                                  # did, session
        ring=jnp.full((n_gw,), 2, jnp.int8),
    )
    gw_slots = jnp.asarray(
        rng.randint(0, n_gw, n_gw, dtype=np.int64), jnp.int32
    )
    gw_required = jnp.asarray(
        np.where(rng.uniform(size=n_gw) < 0.1, 0, 2).astype(np.int8)
    )
    gw_false = jnp.zeros((n_gw,), bool)

    def gateway_wave(a, elevs, slots, required, ro, cons, wit, ht):
        return gateway_ops.check_actions(
            a, elevs, slots, required, ro, cons, wit, ht, 1.0
        ).verdict

    yield "action_gateway_10k", jax.jit(gateway_wave), (
        ag, ElevationTable.create(64), gw_slots, gw_required,
        gw_false, gw_false, gw_false, gw_false,
    ), n_gw

    # ── full_governance_pipeline (headline) ────────────────────────────
    t = 3
    bodies3 = jnp.asarray(
        rng.randint(0, 2**32, (t, S, merkle_ops.BODY_WORDS), dtype=np.uint64
                    ).astype(np.uint32)
    )
    pipe_args = (
        jnp.full((S,), 0.8, jnp.float32),
        jnp.ones((S,), bool),
        jnp.full((S,), 0.60, jnp.float32),
        bodies3,
        jnp.ones((S,), bool),
    )
    yield "full_governance_pipeline", jax.jit(governance_pipeline), pipe_args, S

    # ── state-table wave, general vs fast-path (round-4 delta) ─────────
    # The SAME staged wave through ops.pipeline.governance_wave twice:
    # once on the general program (mask terminate, ranked capacity) and
    # once with the host-verified layout contracts (wave_range +
    # unique_sessions). The pair quantifies the round-4 program
    # reductions on whatever backend runs this suite.
    from hypervisor_tpu.ops.pipeline import governance_wave

    wv_agents = AgentTable.create(2 * S)
    wv_sessions = SessionTable.create(2 * S)
    wvs = jnp.arange(S)
    wv_sessions = t_replace(
        wv_sessions,
        state=wv_sessions.state.at[wvs].set(1),  # HANDSHAKING
        max_participants=wv_sessions.max_participants.at[wvs].set(10),
        min_sigma_eff=wv_sessions.min_sigma_eff.at[wvs].set(0.0),
    )
    wv_vouches = VouchTable.create(4096)
    wave_cols = (
        jnp.arange(S, dtype=jnp.int32),
        jnp.arange(S, dtype=jnp.int32),
        jnp.arange(S, dtype=jnp.int32),
        jnp.full((S,), 0.8, jnp.float32),
        jnp.ones((S,), bool),
        jnp.zeros((S,), bool),
        jnp.arange(S, dtype=jnp.int32),
        bodies3,
        0.0,
        0.5,
    )
    wave_jit = jax.jit(
        governance_wave,
        static_argnames=("use_pallas", "unique_sessions", "wave_kernels"),
    )
    # Staged OUTSIDE the timed callables: the fast path must not be
    # charged per-iteration device_puts the general path never pays.
    wave_range = (jnp.asarray(0, jnp.int32), jnp.asarray(S, jnp.int32))

    def wave_general(*args):
        return wave_jit(*args, wave_kernels=False).status

    # Round 12: the fast path is RE-MEASURED on the megakernel path
    # (`wave_kernels=True` — Mosaic launches on chip, the numpy twins
    # out-of-line on cpu/quick rounds); the `_xla` twin row keeps the
    # pre-megakernel program measurable so the trajectory shows the
    # delta on whatever backend runs this suite.
    def wave_fastpath(*args):
        return wave_jit(
            *args, wave_range=wave_range, unique_sessions=True,
            wave_kernels=True,
        ).status

    def wave_fastpath_xla(*args):
        return wave_jit(
            *args, wave_range=wave_range, unique_sessions=True,
            wave_kernels=False,
        ).status

    wave_args = (wv_agents, wv_sessions, wv_vouches, *wave_cols)
    yield "state_wave_general", wave_general, wave_args, S
    yield "state_wave_fastpath", wave_fastpath, wave_args, S
    yield "state_wave_fastpath_xla", wave_fastpath_xla, wave_args, S


def metrics_plane_report(results: list[dict]) -> dict:
    """Feed every benchmark's samples through the metrics plane and
    draw p50/p95 FROM its histograms (not the raw sample lists) — the
    suite reports through the same bucket math production scrapes use.
    Quantiles are therefore bucket-resolved (log-2 bounds), alongside
    the exact percentiles the suite already prints.

    Each benchmark also registers one wave on a flight-recorder tracer
    (host plane) under a fresh root trace id, and the id + wave_seq
    land in the report — the replay key that correlates a BENCH_*.json
    entry with `GET /trace/...` / `GET /debug/flight` output when the
    same harness runs mounted behind the API.
    """
    from hypervisor_tpu.observability.causal_trace import CausalTraceId
    from hypervisor_tpu.observability.metrics import Metrics, MetricsRegistry
    from hypervisor_tpu.observability.tracing import Tracer

    reg = MetricsRegistry()
    handles = {
        r["name"]: reg.histogram(
            "bench_batch_latency_us", "timed batch wall clock",
            bench=r["name"],
        )
        for r in results
    }
    metrics = Metrics(reg)
    tracer = Tracer(capacity=256)
    traces: dict[str, tuple[str, int]] = {}
    for r in results:
        for ns in r["_samples_ns"]:
            metrics.observe_us(handles[r["name"]], ns / 1e3)
        root = CausalTraceId()
        th = tracer.begin_wave(
            "governance_wave", lanes=r["batch"], root=root, device=False
        )
        tracer.stamp_wave_host(th)
        tracer.end_wave(th)
        traces[r["name"]] = (
            root.full_id,
            th.record.wave_seq if th is not None else -1,
        )
    snap = metrics.snapshot()
    report = {}
    for r in results:
        h = handles[r["name"]]
        trace_id, wave_seq = traces[r["name"]]
        report[r["name"]] = {
            "samples": snap.hist_count(h),
            "batch_p50_us": round(snap.quantile(h, 0.5), 1),
            "batch_p95_us": round(snap.quantile(h, 0.95), 1),
            "per_op_p50_us": round(snap.quantile(h, 0.5) / r["batch"], 4),
            "per_op_p95_us": round(snap.quantile(h, 0.95) / r["batch"], 4),
            "trace_root": trace_id,
            "trace_wave_seq": wave_seq,
        }
    return report


def chaos_benchmark(seed: int, quick: bool) -> dict:
    """`--chaos <seed>`: the standard governance rounds under a FIXED
    wave-layer fault plan (`testing.chaos.WaveChaosPlan`), dispatched
    through the resilience supervisor. Reports recovery latency (time
    from a dispatch's first injected fault to its eventual success) and
    the completed-wave ratio into the BENCH payload, so the trajectory
    tracks resilience alongside speed. Seeded: the same seed replays
    the same fault schedule against the same round structure.
    """
    import time as _time

    from hypervisor_tpu.models import SessionConfig
    from hypervisor_tpu.resilience import Supervisor, WriteAheadLog
    from hypervisor_tpu.state import HypervisorState
    from hypervisor_tpu.testing.chaos import WaveChaosInjector, WaveChaosPlan

    rounds = 8 if quick else 24
    lanes = 16 if quick else 64
    st = HypervisorState()
    wal_dir = Path(tempfile.mkdtemp(prefix="hv_bench_chaos_"))
    st.journal = WriteAheadLog(wal_dir / "wal.log", fsync=False)
    sup = Supervisor(
        st, max_retries=4, backoff_base_s=0.001, backoff_cap_s=0.01,
        degrade_after_failures=2, exit_after_clean=2,
    )
    plan = WaveChaosPlan(
        seed=seed, fail_rate=0.25, hang_rate=0.05, hang_seconds=0.002
    )
    st.fault_injector = WaveChaosInjector(plan)

    completed = 0
    t0 = _time.perf_counter()
    for r in range(rounds):
        slots = st.create_sessions_batch(
            [f"chaos{r}:{i}" for i in range(lanes)],
            SessionConfig(min_sigma_eff=0.0),
        )
        try:
            sup.dispatch(
                "governance_wave", st.run_governance_wave, slots,
                [f"did:chaos{r}:{i}" for i in range(lanes)], slots.copy(),
                np.full(lanes, 0.8, np.float32),
                np.zeros((1, lanes, 16), np.uint32), float(r),
            )
            completed += 1
        except Exception:  # noqa: BLE001 — exhausted retries count as lost
            pass
    wall_s = _time.perf_counter() - t0
    latencies = sorted(sup.recovery_latencies_ms)
    return {
        "seed": seed,
        "plan": {
            "fail_rate": plan.fail_rate,
            "hang_rate": plan.hang_rate,
            "hang_seconds": plan.hang_seconds,
        },
        "rounds": rounds,
        "lanes_per_round": lanes,
        "waves_completed": completed,
        "completed_wave_ratio": round(completed / rounds, 4),
        "dispatch_retries": sup.retries,
        "dispatches_failed": sup.failed_dispatches,
        "degraded_entries": sup.degraded_entries,
        "faults_injected": st.fault_injector.report(),
        "recovery_latency_ms": (
            {
                "n": len(latencies),
                "p50": round(latencies[len(latencies) // 2], 3),
                "max": round(latencies[-1], 3),
            }
            if latencies
            else {"n": 0}
        ),
        "wall_s": round(wall_s, 3),
        "wal_records": st.journal.records_written,
    }


def corrupt_benchmark(seed: int, quick: bool) -> dict:
    """`--corrupt <seed>`: the standard governance rounds with seeded
    REAL corruption (`testing.chaos.InjectedCorruption`) against a
    deployment running the full integrity plane (sanitizer sampled
    every dispatch, scrubber paced every dispatch, restore ladder over
    a WAL + watermarked checkpoint). Reports per-corruption detection
    latency (waves from injection to detection) p50/max and the
    sanitizer's clean-path overhead (%) into the BENCH payload, so the
    trajectory tracks integrity alongside speed and chaos resilience.
    Seeded: the same seed replays the same corruption schedule.
    """
    import time as _time

    from hypervisor_tpu.integrity import IntegrityPlane, StateRestoredError
    from hypervisor_tpu.models import SessionConfig
    from hypervisor_tpu.resilience import Supervisor, WriteAheadLog
    from hypervisor_tpu.state import HypervisorState
    from hypervisor_tpu.testing.chaos import (
        InjectedCorruption,
        WaveChaosInjector,
        WaveChaosPlan,
    )

    rounds = 8 if quick else 24
    lanes = 16 if quick else 64
    warm = 2  # clean rounds before the first corruption can land

    def wave(st, sup, r):
        slots = st.create_sessions_batch(
            [f"corrupt{r}:{i}" for i in range(lanes)],
            SessionConfig(min_sigma_eff=0.0),
        )
        args = (
            slots, [f"did:corrupt{r}:{i}" for i in range(lanes)],
            slots.copy(), np.full(lanes, 0.8, np.float32),
            np.zeros((1, lanes, 16), np.uint32),
        )
        t0 = _time.perf_counter()
        try:
            st.run_governance_wave(*args, now=float(r))
        except StateRestoredError:
            # the gate restored mid-traffic; re-issue on the new state
            sup.state.run_governance_wave(*args, now=float(r))
        return _time.perf_counter() - t0

    # One corruption of each class, at seeded dispatch offsets.
    import random as _random

    rng = _random.Random(seed)
    classes = ("bit_flip", "row_rewrite", "chain_tamper")
    tables = {"bit_flip": "agents", "row_rewrite": "agents"}
    span = max(rounds - warm - 2, len(classes))
    offsets = sorted(rng.sample(range(span), len(classes)))
    corruptions = tuple(
        InjectedCorruption(
            kind, at_dispatch=warm + off + 1, table=tables.get(kind, "agents")
        )
        for kind, off in zip(classes, offsets)
    )

    work_dir = Path(tempfile.mkdtemp(prefix="hv_bench_corrupt_"))
    st = HypervisorState()
    st.journal = WriteAheadLog(work_dir / "wal.log", fsync=False)
    sup = Supervisor(
        st, checkpoint_dir=str(work_dir / "ckpt"), sleep=lambda s: None
    )
    plane = IntegrityPlane(
        st, every=1, scrub_every=1, scrub_budget=128, ladder="restore"
    )

    wave_s: list[float] = []
    detections: list[int] = []   # detection latency, in waves
    injected_at: dict[int, int] = {}  # corruption idx -> round injected
    outstanding: set[int] = set()     # injected rounds not yet restored
    t_total0 = _time.perf_counter()
    for r in range(rounds):
        if r == warm:
            sup.checkpoint()
            sup.state.fault_injector = WaveChaosInjector(
                WaveChaosPlan(seed=seed, corruptions=corruptions)
            )
        restores_before = plane.restores
        wave_s.append(wave(sup.state, sup, r))
        inj = sup.state.fault_injector
        if inj is not None:
            for i, rec in enumerate(inj.corruptions_applied):
                if injected_at.setdefault(i, r) == r:
                    outstanding.add(r)
        sup.state.metrics_snapshot()  # detection closes at the drain
        if plane.restores > restores_before and outstanding:
            # A restore wipes EVERY outstanding corruption; latency is
            # honest against the OLDEST one still waiting.
            detections.append(r - min(outstanding))
            outstanding.clear()
    wall_s = _time.perf_counter() - t_total0

    # Sanitizer overhead: identical clean rounds, sampling at the
    # production cadence (HV_INTEGRITY_EVERY default) vs no plane. The
    # envelope is a P50 bar: the sampled check rides 1-in-8 waves, so
    # the median wave pays only the gate itself.
    def timed_clean(plane_on: bool) -> list[float]:
        state = HypervisorState()
        if plane_on:
            IntegrityPlane(state, every=8)
        out = []
        n = 17 if quick else 33
        for r in range(n):
            slots = state.create_sessions_batch(
                [f"ovh{int(plane_on)}:{r}:{i}" for i in range(lanes)],
                SessionConfig(min_sigma_eff=0.0),
            )
            t0 = _time.perf_counter()
            state.run_governance_wave(
                slots, [f"did:ovh{int(plane_on)}:{r}:{i}" for i in range(lanes)],
                slots.copy(), np.full(lanes, 0.8, np.float32),
                np.zeros((1, lanes, 16), np.uint32), now=float(r),
            )
            out.append(_time.perf_counter() - t0)
        return sorted(out[1:])  # drop the compile round

    overhead_pct = _overhead_p50_pct(timed_clean(False), timed_clean(True))

    detections.sort()
    return {
        "seed": seed,
        "rounds": rounds,
        "lanes_per_round": lanes,
        "corruptions_injected": [
            {k: v for k, v in rec.items()}
            for rec in (
                sup.state.fault_injector.corruptions_applied
                if sup.state.fault_injector is not None
                else []
            )
        ],
        "detection_latency_waves": (
            {
                "n": len(detections),
                "p50": detections[len(detections) // 2],
                "max": detections[-1],
            }
            if detections
            else {"n": 0}
        ),
        "sanitizer_overhead_pct": round(overhead_pct, 2),
        "restores": plane.restores,
        "repairs": plane.repairs,
        "scrub": {
            "links_verified": plane.scrubber.links_verified,
            "mismatches_escalated": plane.scrub_mismatches,
        },
        "checks": plane.checks,
        "wall_s": round(wall_s, 3),
    }


def _overhead_p50_pct(base: list[float], hardened: list[float]) -> float:
    """Shared clean-path-overhead formula: p50(hardened) vs p50(base),
    as a percentage (one definition for the corrupt and scenario
    rows so the two gates can't drift apart)."""
    if not base:
        return 0.0
    p50 = lambda xs: xs[len(xs) // 2]  # noqa: E731
    return (p50(hardened) - p50(base)) / p50(base) * 100.0


def scenario_benchmark(seed: int, quick: bool) -> dict:
    """`--scenarios <seed>`: the seeded adversarial scenario suite
    (`testing.scenarios`) — sybil flood, collusion ring, slash
    cascade, compensation storm, byzantine API fuzz — each scored on
    containment, plus the clean-path overhead of the always-attached
    governance hardening (admission damper + comp-backlog supervisor)
    measured against a bare state at production cadence. The row lands
    in the BENCH payload; `regression.py` gates `min_score` against
    the containment floor and the overhead against the perf band.
    Seeded: the same seed replays the same attack traces
    (`trace_digests` are the replay keys).
    """
    import time as _time

    from hypervisor_tpu.models import SessionConfig
    from hypervisor_tpu.resilience.policy import AdmissionDamper
    from hypervisor_tpu.resilience.supervisor import Supervisor
    from hypervisor_tpu.state import HypervisorState
    from hypervisor_tpu.testing import scenarios

    t0 = _time.perf_counter()
    results = scenarios.run_all(seed, hardened=True, quick=quick)
    agg = scenarios.aggregate(results)
    wall_s = _time.perf_counter() - t0

    # Hardening overhead on the path the hardening actually rides:
    # identical clean ADMISSION rounds (enqueue_join -> flush_joins,
    # where the damper's note_join + the shed gate live, with the
    # supervisor subscribed to health events) against a bare state.
    # run_governance_wave would bypass enqueue_join entirely and
    # measure a path the damper never touches.
    lanes = 16 if quick else 64

    def timed_clean(hardened_on: bool) -> list[float]:
        state = HypervisorState()
        if hardened_on:
            state.admission_damper = AdmissionDamper(
                rate_threshold=1e9, sigma_floor=0.5
            )
            Supervisor(state, sleep=lambda s: None)
        slot = state.create_session(
            f"sovh{int(hardened_on)}",
            SessionConfig(min_sigma_eff=0.0, max_participants=4096),
            now=0.0,
        )
        out = []
        n = 17 if quick else 33
        for r in range(n):
            t1 = _time.perf_counter()
            for i in range(lanes):
                state.enqueue_join(
                    slot, f"did:sovh{int(hardened_on)}:{r}:{i}", 0.8,
                    now=float(r) + i * 1e-4,
                )
            state.flush_joins(now=float(r))
            out.append(_time.perf_counter() - t1)
        return sorted(out[1:])  # drop the compile round

    overhead_pct = _overhead_p50_pct(timed_clean(False), timed_clean(True))

    return {
        "seed": seed,
        "quick": quick,
        "scores": agg["scores"],
        "min_score": agg["min_score"],
        "attack_events": agg["attack_events"],
        "trace_digests": agg["trace_digests"],
        "components": {
            name: r.components for name, r in results.items()
        },
        "hardening_overhead_pct": round(overhead_pct, 2),
        "wall_s": round(wall_s, 3),
    }


def soak_benchmark(seed: int, quick: bool) -> dict:
    """`--soak <seed>`: a sustained open-workload soak through the
    serving front door (`hypervisor_tpu.serving`) — seeded Poisson
    session arrivals split between ephemeral one-wave lifecycles and
    long-lived heavy-tailed sessions (joins, gateway actions, sagas,
    terminations), coalesced into shape-bucketed deadline-paced waves.
    Reports goodput, p50/p99 latency against a stated SLO, shed rate by
    refusal kind, deadline misses, and the compile-telemetry recompile
    count after warmup (the zero-recompile contract: the bucket set is
    closed, so a warmed scheduler never recompiles). Seeded: the same
    seed replays the same trace with identical admission/shed decisions
    and chain heads (`decisions_digest` / `chain_heads_digest` are the
    replay keys). `regression.py` gates the row (HV_BENCH_SOAK_*).
    """
    from hypervisor_tpu.serving import ServingConfig, WorkloadSpec, run_soak

    spec = WorkloadSpec(
        seed=seed,
        rate_hz=150.0 if quick else 400.0,
        duration_s=0.8 if quick else 3.0,
    )
    # CPU wave walls run ~100-300 ms; the cpu soak states cpu-shaped
    # deadlines and SLO (a TPU round would state its own, tighter row —
    # comparability is per backend, like every other gate).
    import jax

    cpu = jax.default_backend() != "tpu"
    config = ServingConfig(
        join_deadline_s=0.25 if cpu else 0.02,
        action_deadline_s=0.25 if cpu else 0.02,
        lifecycle_deadline_s=0.4 if cpu else 0.05,
        terminate_deadline_s=0.5 if cpu else 0.1,
        saga_deadline_s=0.25 if cpu else 0.05,
    )
    # The stated cpu SLO is non-flaky by design (deadline pacing tops
    # out ~500 ms + cpu wave walls + the drain tail, and shared CI
    # hosts add contention); it still catches the failure modes that
    # matter — a recompile storm or a de-bucketed scheduler adds whole
    # seconds to the tail.
    report = run_soak(
        spec,
        serving_config=config,
        tick_s=0.02,
        slo_p99_ms=1500.0 if cpu else 100.0,
    )
    report["seed"] = seed
    report["quick"] = quick
    return report


def autopilot_soak_benchmark(seed: int, quick: bool) -> dict:
    """`--autopilot <seed>`: the autopilot observatory proving-ground
    row (ISSUE 17) — a seeded three-phase shifting workload mix (calm ->
    lifecycle-heavy burst -> settle) replayed twice under the autopilot
    control plane (`hypervisor_tpu.autopilot`) and once against the
    deliberately narrow static config it is scored against. Reports
    goodput improvement vs static (the >= 20% floor), p99 vs the stated
    smoke SLO, decision count + outcome attribution, the decision
    ledger's digest and its bit-identity across the two replays (the
    determinism contract, also verify gate 6j), UNPLANNED post-warmup
    recompiles (raw minus the ledger-bracketed pre-warm compiles —
    pinned zero), and invariant violations. `regression.py` gates the
    row from round 17 (HV_BENCH_AUTOPILOT_*).
    """
    from hypervisor_tpu.autopilot.soak import run_autopilot_soak

    import jax

    cpu = jax.default_backend() != "tpu"
    row = run_autopilot_soak(
        seed=seed,
        quick=quick,
        slo_p99_ms=1500.0 if cpu else 100.0,
        tick_s=0.02,
        replays=2,
    )
    return row


def tenant_census_row(tenants: int, bucket: int, turns: int) -> dict | None:
    """Deviceless step census of the `[T, …]` tenant wave vs T separate
    single-tenant megakernel dispatches — the ISSUE 15 amortization
    metric, measured on the compiled ENTRY structure (the same scan the
    dispatch-census row uses, `roofline.entry_census`), so the gate
    holds with no chip attached. Both programs compile at the SAME
    per-tenant shape with the SAME fused planes riding (sanitize +
    DeltaLog append + gauge epilogue, megakernels armed, donated)."""
    import functools

    import jax
    import jax.numpy as jnp

    from hypervisor_tpu.config import DEFAULT_CONFIG, TableCapacity
    from hypervisor_tpu.observability import metrics as mp
    from hypervisor_tpu.observability.roofline import entry_census
    from hypervisor_tpu.ops import merkle as merkle_ops
    from hypervisor_tpu.ops.pipeline import governance_wave
    from hypervisor_tpu.state import _tenant_wave_fn
    from hypervisor_tpu.tables import logs as logs_mod
    from hypervisor_tpu.tables import state as tables_state

    cfg = DEFAULT_CONFIG.replace(
        capacity=TableCapacity(
            max_agents=64, max_sessions=64, max_vouch_edges=64,
            max_sagas=16, max_steps_per_saga=4, max_elevations=16,
            delta_log_capacity=256, event_log_capacity=64,
            trace_log_capacity=64,
        )
    )
    cap = cfg.capacity

    def sds(tree):
        return jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree
        )

    def stacked(tree):
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(
                (tenants,) + s.shape, s.dtype
            ),
            tree,
        )

    tables = {
        "agents": sds(tables_state.AgentTable.create(cap.max_agents)),
        "sessions": sds(
            tables_state.SessionTable.create(cap.max_sessions)
        ),
        "vouches": sds(
            tables_state.VouchTable.create(cap.max_vouch_edges)
        ),
        "sagas": sds(
            tables_state.SagaTable.create(
                cap.max_sagas, cap.max_steps_per_saga
            )
        ),
        "elevations": sds(
            tables_state.ElevationTable.create(cap.max_elevations)
        ),
        "delta_log": sds(logs_mod.DeltaLog.create(cap.delta_log_capacity)),
        "event_log": sds(logs_mod.EventLog.create(cap.event_log_capacity)),
        "metrics": sds(mp.REGISTRY.create_table()),
    }

    def lane(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    b = bucket
    lanes = {
        "slot": lane((b,), jnp.int32),
        "did": lane((b,), jnp.int32),
        "session_slot": lane((b,), jnp.int32),
        "sigma_raw": lane((b,), jnp.float32),
        "trustworthy": lane((b,), jnp.bool_),
        "duplicate": lane((b,), jnp.bool_),
        "wave_sessions": lane((b,), jnp.int32),
        "bodies": lane((turns, b, merkle_ops.BODY_WORDS), jnp.uint32),
        "lo": lane((), jnp.int32),
        "hi": lane((), jnp.int32),
        "lanes_valid": lane((b,), jnp.bool_),
        "n_valid": lane((), jnp.int32),
    }
    scalars = (
        lane((), jnp.float32), lane((), jnp.float32),
        lane((4,), jnp.float32),
    )
    statics = dict(
        trust=cfg.trust, breach=cfg.breach, rate_limit=cfg.rate_limit,
        sanitize=True, config=cfg, cache_salt=0.0, wave_kernels=True,
    )

    try:
        tenant_fn = functools.partial(_tenant_wave_fn, **statics)
        tenant_args = (
            tuple(
                stacked(tables[k])
                for k in (
                    "agents", "sessions", "vouches", "metrics",
                    "delta_log", "sagas", "event_log", "elevations",
                )
            )
            + tuple(
                jax.ShapeDtypeStruct((tenants,) + s.shape, s.dtype)
                for s in (
                    lanes["slot"], lanes["did"], lanes["session_slot"],
                    lanes["sigma_raw"], lanes["trustworthy"],
                    lanes["duplicate"], lanes["wave_sessions"],
                    lanes["bodies"], lanes["lo"], lanes["hi"],
                    lanes["lanes_valid"], lanes["n_valid"],
                )
            )
            + scalars
        )
        compiled_tenant = (
            jax.jit(tenant_fn, donate_argnums=(0, 1, 2, 3, 4))
            .lower(*tenant_args)
            .compile()
        )
        _, tenant_steps, _ = entry_census(compiled_tenant)

        def solo_fn(
            agents, sessions, vouches, metrics, delta_log, sagas,
            event_log, elevations, slot, did, session_slot, sigma_raw,
            trustworthy, duplicate, wave_sessions, bodies, lo, hi,
            lanes_valid, n_valid, now, omega, bursts,
        ):
            return governance_wave(
                agents, sessions, vouches, slot, did, session_slot,
                sigma_raw, trustworthy, duplicate, wave_sessions,
                bodies, now, omega,
                trust=cfg.trust, use_pallas=False, ring_bursts=bursts,
                wave_range=(lo, hi), unique_sessions=False,
                metrics=metrics, trace=None, trace_ctx=None,
                elevations=elevations, gateway_args=None,
                breach=cfg.breach, rate_limit=cfg.rate_limit,
                delta_log=delta_log, epilogue_tables=(sagas, event_log),
                sanitize=True, config=cfg, cache_salt=0.0,
                lanes_valid=lanes_valid, n_sessions_valid=n_valid,
                wave_kernels=True,
            )

        solo_args = (
            tuple(
                tables[k]
                for k in (
                    "agents", "sessions", "vouches", "metrics",
                    "delta_log", "sagas", "event_log", "elevations",
                )
            )
            + tuple(
                lanes[k]
                for k in (
                    "slot", "did", "session_slot", "sigma_raw",
                    "trustworthy", "duplicate", "wave_sessions",
                    "bodies", "lo", "hi", "lanes_valid", "n_valid",
                )
            )
            + scalars
        )
        compiled_solo = (
            jax.jit(solo_fn, donate_argnums=(0, 1, 2, 3, 4))
            .lower(*solo_args)
            .compile()
        )
        _, solo_steps, _ = entry_census(compiled_solo)
    except Exception:  # noqa: BLE001 — a failed census omits the block
        return None
    t_times_single = tenants * solo_steps
    return {
        "tenants": tenants,
        "bucket": bucket,
        "tenant_wave_steps": int(tenant_steps),
        "single_wave_steps": int(solo_steps),
        "t_times_single_steps": int(t_times_single),
        "amortization_ratio": (
            round(t_times_single / tenant_steps, 1)
            if tenant_steps
            else 0.0
        ),
    }


def tenant_dense_benchmark(seed: int, quick: bool, tenants: int) -> dict:
    """`--tenants <T>`: the ISSUE 15 `tenant_dense` row — ≥100 logical
    hypervisors served from ONE process through the TenantArena's
    batched dispatch (`tenancy`): per-tenant p99 vs a stated SLO,
    dispatch-bearing steps for the T-tenant wave vs T separate
    single-tenant dispatches (the amortization census, deviceless),
    the amortized µs/op of the batched wave, and the zero-recompile
    contract over the warmed (bucket, T) tile set. Seeded and
    virtual-clocked like the soak row; `regression.py` presence-gates
    it from round 16 and floors the amortization ratio
    (`HV_BENCH_TENANT_AMORT`)."""
    import time as _time

    import jax

    from hypervisor_tpu.config import DEFAULT_CONFIG, TableCapacity
    from hypervisor_tpu.observability import health as health_plane
    from hypervisor_tpu.observability import metrics as mp
    from hypervisor_tpu.serving import ServingConfig
    from hypervisor_tpu.tenancy import (
        TenantArena,
        TenantFrontDoor,
        TenantWaveScheduler,
    )

    cpu = jax.default_backend() != "tpu"
    rounds = 6 if quick else 12
    lanes_per_round = 2
    bucket_set = (4, 8)
    # The gated number is the WORST per-tenant p99 — with ~12 tickets
    # per tenant that is the global max ticket latency over T tenants,
    # a max-statistic whose cpu spread is set by host scheduling jitter
    # under DRR round alignment, not by the runtime (observed 1.4-2.7 s
    # across idle-box runs of identical code at T=100 on one core; the
    # r16-era 1.5 s bound flaked most runs). The cpu smoke bound only
    # guards against order-of-magnitude breakage; 100 ms on TPU is the
    # real contract.
    slo_p99_ms = 3000.0 if cpu else 100.0
    cfg = DEFAULT_CONFIG.replace(
        capacity=TableCapacity(
            max_agents=64,
            max_sessions=max(64, (rounds + 8) * lanes_per_round + 16),
            max_vouch_edges=64,
            max_sagas=16,
            max_steps_per_saga=4,
            max_elevations=16,
            delta_log_capacity=1024,
            event_log_capacity=64,
            trace_log_capacity=64,
        )
    )
    serving = ServingConfig(
        buckets=bucket_set,
        lifecycle_deadline_s=0.4 if cpu else 0.05,
        lifecycle_queue_depth=32,
    )
    t0 = _time.perf_counter()
    arena = TenantArena(tenants, cfg)
    front = TenantFrontDoor(arena, serving)
    sched = TenantWaveScheduler(front)
    sched.warm(now=0.0)
    warm_wall = _time.perf_counter() - t0
    base = health_plane.compile_summary(last=0)

    rng = np.random.RandomState(seed)
    # Pre-drive stage baseline: the warm waves' brackets include their
    # compile walls — the amortized-cost numbers below are deltas over
    # the DRIVEN waves only.
    h = mp.STAGE_LATENCY["tenant_governance_wave"]
    snap0 = arena.metrics.snapshot()
    walls0_us = float(snap0.hist_sum[h.index])
    count0 = snap0.hist_count(h)
    now = 10.0
    held: list = []
    lat: dict[int, list] = {t: [] for t in range(tenants)}
    t1 = _time.perf_counter()
    for r in range(rounds):
        for t in range(tenants):
            for i in range(lanes_per_round):
                tk = front.submit_lifecycle(
                    t,
                    f"td:{t}:{r}:{i}",
                    f"did:td:{t}:{r}:{i}",
                    float(0.6 + 0.3 * rng.random()),
                    now=now,
                )
                if not tk.refused:
                    held.append((t, tk))
        sched.lifecycle_round(now)
        now += 0.1
    sched.drain(now)
    drive_wall = _time.perf_counter() - t1
    for t, tk in held:
        if tk.done:
            lat[t].append(tk.latency_s * 1e3)
    after = health_plane.compile_summary(last=0)

    served = sum(
        front.doors[t].served["lifecycle"] for t in range(tenants)
    )
    p99s = {
        t: float(np.percentile(np.asarray(vs, np.float64), 99))
        for t, vs in lat.items()
        if vs
    }
    worst_p99_ms = max(p99s.values()) if p99s else None
    # Amortized device cost: the DRIVEN batched waves' measured walls
    # over the lifecycles they served (arena host plane, stage
    # bracket deltas — warm-time compile walls excluded).
    snap = arena.metrics.snapshot()
    wave_walls_us = float(snap.hist_sum[h.index]) - walls0_us
    wave_count = snap.hist_count(h) - count0
    census = tenant_census_row(
        tenants, max(bucket_set), serving.lifecycle_turns
    )
    recompiles = after["recompiles"] - base["recompiles"]
    compiles = after["compiles"] - base["compiles"]
    return {
        "seed": seed,
        "quick": quick,
        "tenants": tenants,
        "rounds": rounds,
        "buckets": list(bucket_set),
        "offered": tenants * rounds * lanes_per_round,
        "served": served,
        "waves": int(wave_count),
        "per_tenant_p99_ms": (
            round(worst_p99_ms, 3) if worst_p99_ms is not None else None
        ),
        "slo_p99_ms": slo_p99_ms,
        "within_slo": (
            worst_p99_ms is not None and worst_p99_ms <= slo_p99_ms
        ),
        "tenants_with_traffic": len(p99s),
        "amortized_us_per_op": (
            round(wave_walls_us / served, 3) if served else None
        ),
        "wave_wall_mean_ms": (
            round(wave_walls_us / wave_count / 1e3, 3)
            if wave_count
            else None
        ),
        "census": census,
        "amortization_ratio": (
            census["amortization_ratio"] if census else None
        ),
        "compiles_after_warmup": compiles,
        "recompiles_after_warmup": recompiles,
        "warm_wall_s": round(warm_wall, 3),
        "drive_wall_s": round(drive_wall, 3),
    }


def tenant_dense_row_isolated(
    seed: int, quick: bool, tenants: int, timeout_s: float = 480.0
) -> dict | None:
    """Run `tenant_dense_benchmark` in a SUBPROCESS and return its row.

    Subprocess, not in-process: `per_tenant_p99_ms` is the WORST
    per-tenant tail over T tenants' measured wave walls — a handful of
    samples per tenant, so the gated number is set by the single worst
    scheduling hiccup anywhere in the run. By this point the suite
    process has run the microbenches, scenarios, soak, census and
    roofline rows; the accumulated jit cache, host metric mirrors and
    deferred roofline-capture resolution (which re-traces on metrics
    drains) land exactly in those tails — observed inflating the p99
    ~1.5-2x over a fresh interpreter on cpu. The census row set the
    subprocess precedent. Falls back to the in-process run (None →
    caller decides) only if the child fails outright.
    """
    code = (
        "import json\n"
        "from benchmarks.bench_suite import tenant_dense_benchmark\n"
        f"row = tenant_dense_benchmark({seed!r}, {quick!r}, {tenants!r})\n"
        "print('HV_TENANT_ROW=' + json.dumps(row))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=str(Path(__file__).resolve().parent.parent),
        )
    except (OSError, subprocess.SubprocessError):
        return None
    if proc.returncode != 0:
        return None
    for line in proc.stdout.splitlines():
        if line.startswith("HV_TENANT_ROW="):
            try:
                return json.loads(line[len("HV_TENANT_ROW="):])
            except json.JSONDecodeError:
                return None
    return None


def wave_megakernel_row(
    quick: bool, iters: int, census_rec: dict | None,
    plane: dict | None = None,
) -> dict:
    """The round-12 `wave_megakernel` bench row: per-block µs/op for
    every wave-kernel block on the bench wave shape, the armed-vs-
    reference whole-wave numbers (from the suite's own
    `state_wave_fastpath` / `_xla` rows when present), and the armed
    census step structure (cross-referenced from the dispatch-census
    row). On cpu/quick rounds the blocks execute their numpy twins
    out-of-line (`mode: cpu-twin`) — chip numbers stay pending while
    the accelerator tunnel is wedged (the standing caveat).
    """
    import dataclasses

    import jax
    import jax.numpy as jnp

    from hypervisor_tpu.config import DEFAULT_CONFIG
    from hypervisor_tpu.kernels.sha256_pallas import pallas_available
    from hypervisor_tpu.observability import metrics as mp
    from hypervisor_tpu.ops import merkle as merkle_ops
    from hypervisor_tpu.ops import wave_blocks
    from hypervisor_tpu.tables.logs import DeltaLog, EventLog, TraceLog
    from hypervisor_tpu.tables.state import (
        AgentTable,
        ElevationTable,
        SagaTable,
        SessionTable,
        VouchTable,
    )
    from hypervisor_tpu.tables.struct import replace as t_replace

    rng = np.random.RandomState(12)
    S = 2_048 if quick else 10_000
    A = 1_024
    iters = max(3, min(iters, 10))

    agents = AgentTable.create(2 * S)
    sessions = SessionTable.create(2 * S)
    wvs = jnp.arange(S, dtype=jnp.int32)
    sessions = t_replace(
        sessions,
        state=sessions.state.at[wvs].set(1),
        max_participants=sessions.max_participants.at[wvs].set(10),
        min_sigma_eff=sessions.min_sigma_eff.at[wvs].set(0.0),
    )
    vouches = VouchTable.create(4096)
    sagas = SagaTable.create(1024, 8)
    elevations = ElevationTable.create(4096)
    delta_log = DeltaLog.create(1 << 16)
    event_log = EventLog.create(4096)
    trace_log = TraceLog.create(4096)
    metrics_table = mp.REGISTRY.create_table()  # noqa: F841 — shape ref
    bodies = jnp.asarray(
        rng.randint(0, 2**32, (3, S, merkle_ops.BODY_WORDS), dtype=np.uint64
                    ).astype(np.uint32)
    )
    bursts = jnp.asarray(DEFAULT_CONFIG.rate_limit.ring_bursts, jnp.float32)
    trust = DEFAULT_CONFIG.trust
    wave_range = (jnp.int32(0), jnp.int32(S))
    zeros_f = jnp.zeros((S,), jnp.float32)
    ones_b = jnp.ones((S,), bool)

    def adm(a, s):
        return wave_blocks.admission_block(
            a, s, wvs, wvs, wvs, jnp.full((S,), 0.8, jnp.float32),
            zeros_f, jnp.float32(0.5), ones_b, jnp.zeros((S,), bool),
            jnp.float32(0.0), bursts, trust, True,
        )

    def fsm(a, s, v):
        return wave_blocks.fsm_saga_block(
            a, s, v, wvs, ones_b, jnp.float32(0.0), wave_range
        )

    def audit(b_, d):
        return wave_blocks.audit_block(b_, wvs, d, None, pallas_available())

    gw_args = (
        jnp.asarray(rng.randint(0, 2 * S, A, dtype=np.int64), jnp.int32),
        jnp.full((A,), 2, jnp.int8),
        jnp.zeros((A,), bool), jnp.zeros((A,), bool),
        jnp.zeros((A,), bool), jnp.zeros((A,), bool),
        jnp.ones((A,), bool),
    )

    def gw(a, e):
        return wave_blocks.gateway_block(a, e, gw_args, jnp.float32(1.0))

    def epi(a, s, v):
        return wave_blocks.epilogue_block(
            a, s, v, sagas, elevations, delta_log, event_log, trace_log,
            bursts, True,
        )

    blocks = {
        "admission": (jax.jit(adm), (agents, sessions), S),
        "fsm_saga": (jax.jit(fsm), (agents, sessions, vouches), S),
        "audit": (jax.jit(audit), (bodies, delta_log), S),
        "gateway": (jax.jit(gw), (agents, elevations), A),
        "epilogue": (jax.jit(epi), (agents, sessions, vouches), S),
    }
    per_block = {}
    for name, (fn, args, batch) in blocks.items():
        rec = bench(fn, args, iters, batch, f"wave_block:{name}")
        per_block[name] = {
            "batch": batch,
            "batch_p50_ms": round(rec["batch_p50_ms"], 4),
            "per_op_p50_us": round(rec["per_op_us"], 4),
        }

    def plane_us(name):
        rec = (plane or {}).get(name)
        return rec.get("per_op_p50_us") if rec else None

    return {
        "quick": quick,
        "lanes": S,
        "mode": "mosaic" if pallas_available() else "cpu-twin",
        "blocks": per_block,
        # Whole-wave delta from the suite's own rows (armed vs the
        # pre-megakernel XLA program on this backend).
        "state_wave_fastpath_us": plane_us("state_wave_fastpath"),
        "state_wave_fastpath_xla_us": plane_us("state_wave_fastpath_xla"),
        # The armed census structure (the acceptance metric) — cross-
        # referenced from the dispatch-census row when it ran.
        "census_dispatch_steps": (
            (census_rec or {}).get("dispatch_steps")
        ),
        "census_phase_breakdown": (
            (census_rec or {}).get("phase_breakdown")
        ),
        "wave_cut_ratio": (census_rec or {}).get("wave_cut_ratio"),
    }


def dispatch_census_row(timeout_s: float = 900.0) -> dict | None:
    """Run `tpu_aot_census.py --json` in a SUBPROCESS and distill the
    trajectory row (`BENCH_r<NN>.json` "dispatch_census").

    Subprocess, not import: the census pins its own platform config
    (deviceless v5e AOT when the PJRT plugin answers, hermetic 8-device
    CPU otherwise), so its ENTRY-step numbers are reproducible
    regardless of how this bench process configured jax. Exit 75 =
    plugin absent/wedged with --backend tpu — here the tool auto-falls
    back to cpu, so None means the census itself failed.
    """
    tool = Path(__file__).resolve().parent / "tpu_aot_census.py"
    try:
        proc = subprocess.run(
            [sys.executable, str(tool), "--json"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    if proc.returncode != 0:
        return None
    try:
        report = json.loads(proc.stdout)
    except json.JSONDecodeError:
        return None
    fused = report["programs"]["fused_wave_sanitized"]
    nodonate = report["programs"]["fused_wave_sanitized_nodonate"]
    mk = report["programs"].get("fused_wave_megakernel")
    return {
        "backend": report["backend"],
        # Round 12: the headline steps are the MEGAKERNEL wave (the
        # program a production chip dispatches with HV_WAVE_PALLAS
        # auto-armed); the pre-megakernel fused program stays on the
        # row as reference_* so the trajectory shows the cut.
        "entry_steps": (mk or fused)["entry"],
        "dispatch_steps": (mk or fused)["dispatch"],
        "reference_entry_steps": fused["entry"],
        "reference_dispatch_steps": fused["dispatch"],
        "phase_breakdown": (mk or {}).get("phases"),
        "reference_phase_breakdown": fused.get("phases"),
        "wave_kernels_boundary": report.get("wave_kernels_boundary"),
        "entry_steps_no_donate": nodonate["entry"],
        "dispatch_steps_no_donate": nodonate["dispatch"],
        "copy_steps": (mk or fused)["top"].get("copy", 0),
        "donation_delta_steps": report["donation_delta_steps"],
        "megakernel_donation_delta_steps": report.get(
            "megakernel_donation_delta_steps"
        ),
        "unfused_total_dispatch": report["unfused_total"]["dispatch"],
        "self_fusion_ratio": report["self_fusion_ratio"],
        "fusion_ratio": report["fusion_ratio"],
        "fusion_ratio_reference": report.get("fusion_ratio_reference"),
        "r09_baseline_dispatch": (
            (report.get("r09_baseline") or {}).get("dispatch_total")
        ),
        "r10_baseline_dispatch": report.get("r10_baseline"),
        "wave_cut_ratio": report.get("wave_cut_ratio"),
    }


def static_analysis_row(timeout_s: float = 300.0) -> dict | None:
    """Run hvlint (both tiers, `--json`) in a SUBPROCESS and distill
    the trajectory row (`BENCH_r<NN>.json` "static_analysis").

    Subprocess for the census-gate reason: Tier B traces the dispatched
    programs and must run on the hermetic CPU platform no matter how
    this bench process configured jax. Exit 1 (findings) still yields a
    row — regression.py hard-gates `findings == 0`, so a violation
    shipping in a bench round fails the trajectory, not the bench.
    """
    import os

    try:
        proc = subprocess.run(
            [
                sys.executable, "-m", "hypervisor_tpu.analysis",
                "--tier", "all", "--json",
            ],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=Path(__file__).resolve().parent.parent,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    if proc.returncode not in (0, 1):
        return None
    try:
        report = json.loads(proc.stdout)
    except json.JSONDecodeError:
        return None
    return {
        "rules": len(report.get("rules", [])),
        "findings": report["counts"]["findings"],
        "suppressions": report["counts"]["suppressions_on_file"],
        "files_analyzed": report.get("files_analyzed"),
        "tiers": report.get("tiers"),
        "programs_traced": len(report.get("tier_b_programs") or []),
        "tier_a_ms": report.get("tier_a_ms"),
        "tier_b_ms": report.get("tier_b_ms"),
    }


def roofline_row(quick: bool) -> dict | None:
    """Round-15 roofline row (`BENCH_r<NN>.json` "roofline").

    Drives a short DETERMINISTIC-shaped workload on a fresh
    HypervisorState so the process-global roofline registry
    (`observability.roofline`) captures THIS process's wave programs at
    fixed bucket shapes, then distills the modeled-vs-measured join:
    modeled HBM bytes + FLOPs per program (shape-deterministic — the
    numbers `regression.py` band-gates from round 15: an accidental
    de-fusion or donation miss inflates modeled traffic on cpu, no
    chip needed), achieved-bandwidth fraction and MFU against the
    measured stage walls, the per-phase byte model with measured wall
    shares, and the distance-to-the-floor block.
    """
    try:
        from hypervisor_tpu.models import SessionConfig
        from hypervisor_tpu.observability import roofline
        from hypervisor_tpu.state import HypervisorState

        rounds = 6 if quick else 16
        # Lane counts NOTHING else in the suite uses (chaos/corrupt
        # run 16, the soak 4/8/16/32, the drills 4/8): the registry's
        # newest-capture-wins model selection means whichever wave
        # signature COMPILES last owns the gated row, and a shared
        # shape hands that to an earlier stage's wave — the soak's
        # sanitize-sweep variant models ~3x the clean-path bytes and
        # turned the canary order-sensitive. A unique shape always
        # compiles (and so captures) HERE, last, deterministically.
        lanes = 24 if quick else 72
        st = HypervisorState()
        t0 = time.perf_counter()
        for r in range(rounds):
            slots = st.create_sessions_batch(
                [f"roofline{r}:{i}" for i in range(lanes)],
                SessionConfig(min_sigma_eff=0.0),
            )
            st.run_governance_wave(
                slots,
                [f"did:roofline{r}:{i}" for i in range(lanes)],
                slots.copy(),
                np.full(lanes, 0.8, np.float32),
                np.zeros((1, lanes, 16), np.uint32),
                float(r),
            )
            # Standalone entry points so the catalog covers more than
            # the fused wave: admission (enqueue+flush), the per-action
            # gateway, and a terminate wave.
            keep = st.create_sessions_batch(
                [f"roofline{r}:keep{i}" for i in range(4)],
                SessionConfig(min_sigma_eff=0.0),
            )
            for i, slot in enumerate(keep):
                st.enqueue_join(
                    int(slot), f"did:roofline{r}:k{i}", 0.8, now=float(r)
                )
            st.flush_joins(now=float(r))
            st.check_actions_wave(
                keep, [0] * len(keep), [True] * len(keep),
                [False] * len(keep), [False] * len(keep),
                [False] * len(keep), float(r),
            )
            st.terminate_sessions(keep, now=float(r) + 0.5)
            st.metrics_snapshot()  # publish cadence: resolve + join
        summary = st.roofline_summary()
        wall_s = time.perf_counter() - t0
        if not summary.get("enabled"):
            return None
        programs = {}
        for name, row in sorted(summary["programs"].items()):
            model = row["model"]
            programs[name] = {
                "modeled_bytes": model["bytes_accessed"],
                "modeled_flops": model["flops"],
                "peak_bytes": model["peak_bytes"],
                "wall_p50_us": row["wall_p50_us"],
                "achieved_bw_frac": row["achieved_bw_frac"],
                "mfu": row["mfu"],
                "distance": row["distance"],
                "buckets": len(row["buckets"]),
            }
        phases = None
        if summary.get("phases"):
            phases = {
                "program": summary["phases"]["program"],
                "modeled_bytes": summary["phases"]["modeled_bytes"],
                "wall_shares": summary["phases"]["wall_shares"],
            }
        return {
            "quick": quick,
            "rounds": rounds,
            "lanes_per_round": lanes,
            "peak_bw_gbs": summary["peaks"]["peak_bw_gbs"],
            "peak_flops_g": summary["peaks"]["peak_flops_g"],
            "programs": programs,
            "phases": phases,
            "floor": summary["floor"],
            "worst_program": summary["worst_program"],
            "captures": summary["captures"],
            "capture_failures": summary["capture_failures"],
            "wall_s": round(wall_s, 3),
        }
    except Exception:  # noqa: BLE001 — a failed row is omitted, gated
        return None


def _git_commit() -> str | None:
    """Current commit hash, stamped into bench reports so a trajectory
    row names the code it measured; None outside a git checkout."""
    import subprocess

    try:
        return (
            subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                cwd=Path(__file__).resolve().parent.parent,
                capture_output=True,
                text=True,
                timeout=10,
            ).stdout.strip()
            or None
        )
    except (OSError, subprocess.SubprocessError):
        return None


def fleet_observatory_benchmark(
    seed: int, quick: bool, n_workers: int
) -> dict:
    """The round-18 fleet row: N REAL worker subprocesses (each the
    existing API server + a 2-tenant arena), driven over HTTP, then
    the three fleet contracts measured live:

      1. **Merged drain** — ONE exposition scraping every worker;
         series conservation (merged count == Σ per-worker counts) and
         `worker="<id>"` on every sample row (coverage == 1.0), wall
         clocked per drain.
      2. **Zero post-warmup recompiles per worker** — the warm
         contract holds ACROSS process boundaries: identical
         join-wave shapes after warmup compile nothing, measured from
         each worker's own `/debug/compiles`.
      3. **The kill drill** — SIGKILL one worker mid-drill; the lease
         registry (beats from real `/health` polls, windows on a
         virtual clock so the journal replays deterministically) must
         flip it suspected -> dead within the budget (<= 2 heartbeat
         windows), and the recorded observation journal must replay
         to a bit-identical transition digest twice.
    """
    import urllib.request

    from hypervisor_tpu.fleet import (
        DEAD,
        SUSPECTED,
        FleetObservatory,
        FleetRegistry,
        FleetSupervisor,
        LeaseConfig,
        WorkerSpec,
        worker_label_coverage,
    )

    def _get(url: str) -> dict:
        with urllib.request.urlopen(url, timeout=15) as r:
            return json.loads(r.read())

    def _post(url: str, payload: dict) -> dict:
        req = urllib.request.Request(
            url,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    lanes = 4          # constant batch shape: the warm contract's key
    warm_waves = 2
    drive_waves = 2 if quick else 4
    drains = 1 if quick else 3
    sup = FleetSupervisor([
        WorkerSpec(worker_id=f"w{i}", tenants=(0, 1))
        for i in range(n_workers)
    ])
    sup.start()
    try:
        urls = sup.urls()
        sessions = {}
        for w, base in urls.items():
            doc = _post(base + "/api/v1/sessions", {
                "creator_did": f"did:fleet:{seed}:{w}",
            })
            sessions[w] = doc["session_id"]

        def drive(w: str, base: str, tag: str, waves: int) -> None:
            for r in range(waves):
                _post(
                    base + f"/api/v1/sessions/{sessions[w]}/join-wave",
                    {"joins": [
                        {"agent_did": f"did:fleet:{w}:{tag}:{r}:{i}",
                         "sigma_raw": 0.8}
                        for i in range(lanes)
                    ]},
                )

        for w, base in urls.items():
            drive(w, base, "warm", warm_waves)
        base_comp = {
            w: _get(base + "/debug/compiles")
            for w, base in urls.items()
        }
        for w, base in urls.items():
            drive(w, base, "drive", drive_waves)
        per_worker = {}
        for w, base in urls.items():
            after = _get(base + "/debug/compiles")
            per_worker[w] = {
                "compiles_after_warmup": (
                    int(after.get("compiles", 0))
                    - int(base_comp[w].get("compiles", 0))
                ),
                "recompiles_after_warmup": (
                    int(after.get("recompiles", 0))
                    - int(base_comp[w].get("recompiles", 0))
                ),
            }

        cfg = LeaseConfig(heartbeat_interval_s=0.25)
        reg = FleetRegistry(cfg, seed=seed)
        for w in urls:
            reg.register(w, 0.0)
        obs = FleetObservatory(urls, registry=reg)
        walls, merged, snap = [], "", None
        for _ in range(drains):
            t0 = time.perf_counter()
            merged, snap = obs.drain(now=0.0)
            walls.append((time.perf_counter() - t0) * 1e3)
        series_sum = sum(v for _, v in snap.series)
        for w, n in snap.series:
            per_worker[w]["series"] = n

        # The kill drill: beats come from REAL /health polls; windows
        # advance on a virtual clock so the observation journal is a
        # pure function of what the fleet did — replayable bit-for-bit.
        victim = sorted(urls)[0]
        kill_window = 3
        detect = {"suspected": None, "dead": None}
        window = 0
        while window < kill_window + 8 and detect["dead"] is None:
            window += 1
            vnow = window * cfg.heartbeat_interval_s
            for w, base in urls.items():
                try:
                    with urllib.request.urlopen(
                        base + "/health", timeout=5
                    ) as r:
                        ok = r.status == 200
                except OSError:
                    ok = False
                if ok:
                    reg.heartbeat(w, vnow)
            states = reg.evaluate(vnow)
            if states.get(victim) == SUSPECTED and \
                    detect["suspected"] is None:
                detect["suspected"] = window - kill_window
            if states.get(victim) == DEAD and detect["dead"] is None:
                detect["dead"] = window - kill_window
            if window == kill_window:
                sup.kill(victim)  # silence AFTER this window's beat

        digest = reg.transition_digest()
        replay_digests = [
            FleetRegistry.replay(
                reg.observations, cfg, seed=seed
            ).transition_digest()
            for _ in range(2)
        ]
        walls.sort()
        return {
            "seed": seed,
            "workers": n_workers,
            "tenants_per_worker": 2,
            "heartbeat_interval_s": cfg.heartbeat_interval_s,
            "budget_windows": 2.0,
            "detection_windows": {
                "suspected": detect["suspected"],
                "dead": detect["dead"],
                "p50": detect["dead"],
                "max": detect["dead"],
            },
            "killed": victim,
            "transitions": len(reg.transitions),
            "digest": digest,
            "digest_match": all(d == digest for d in replay_digests),
            "replays": len(replay_digests),
            "merged_drain_wall_ms": round(
                walls[len(walls) // 2], 3
            ),
            "merged_series": snap.merged_series,
            "series_per_worker_sum": series_sum,
            "series_conserved": snap.merged_series == series_sum,
            "worker_label_coverage": worker_label_coverage(merged),
            "scrape_errors": len(snap.errors),
            "per_worker": per_worker,
            "compiles_after_warmup": max(
                r["compiles_after_warmup"] for r in per_worker.values()
            ),
            "recompiles_after_warmup": max(
                r["recompiles_after_warmup"] for r in per_worker.values()
            ),
        }
    finally:
        sup.stop()


def fleet_observatory_row_isolated(
    seed: int, quick: bool, n_workers: int, timeout_s: float = 600.0
) -> dict | None:
    """Run `fleet_observatory_benchmark` in a SUBPROCESS and return
    its row. The workers are subprocesses either way; isolating the
    supervisor too keeps the suite process's jit cache and metric
    mirrors out of the merged-drain walls (the tenant row's
    precedent). Returns None if the child fails outright."""
    code = (
        "import json\n"
        "from benchmarks.bench_suite import fleet_observatory_benchmark\n"
        f"row = fleet_observatory_benchmark("
        f"{seed!r}, {quick!r}, {n_workers!r})\n"
        "print('HV_FLEET_ROW=' + json.dumps(row))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=str(Path(__file__).resolve().parent.parent),
        )
    except (OSError, subprocess.SubprocessError):
        return None
    if proc.returncode != 0:
        return None
    for line in proc.stdout.splitlines():
        if line.startswith("HV_FLEET_ROW="):
            try:
                return json.loads(line[len("HV_FLEET_ROW="):])
            except json.JSONDecodeError:
                return None
    return None


def incident_capture_benchmark(seed: int, quick: bool) -> dict:
    """`--incidents <seed>`: the round-19 hindsight-plane row — the
    retained-telemetry history + black-box incident recorder measured
    live on an in-process state:

    * clean-path overhead: p50 `metrics_snapshot()` wall with the
      history sampler on vs stubbed off, same state, same drain cadence
      (the tiered rings are host-side folds over the ONE snapshot the
      drain already paid for — no extra device_get, so the overhead
      band is tight);
    * capture cost: p50/max wall and bundle bytes for a seeded drill of
      taxonomy triggers fired through the REAL health fan-out
      (`health.emit_event` -> `IncidentRecorder.observe`), classes
      spaced past the cooldown;
    * determinism: the same seeded drill replayed on two fresh states
      under a virtual clock must produce bit-identical incident-id
      sequences (ids hash rule inputs only), every id must verify its
      own content address (`replay_check`), and a seeded direct-feed
      history replay must produce bit-identical history digests;
    * zero post-warmup recompiles: the whole plane is host-side, so
      any recompile during the drill phase is a regression.

    `regression.py` presence-gates the row from round 19 and
    hard-gates overhead (HV_BENCH_INCIDENT_OVERHEAD), digest match,
    and the recompile count.
    """
    import time as _time

    from hypervisor_tpu.models import SessionConfig
    from hypervisor_tpu.observability import health as health_plane
    from hypervisor_tpu.observability.history import HistoryPlane
    from hypervisor_tpu.state import HypervisorState

    lanes = 16 if quick else 32
    rounds = 6 if quick else 16
    snap_iters = 40 if quick else 120

    # ── workload state: real governance waves feed the drained
    # snapshots the history sampler folds. Two warm waves first, so
    # the recompile budget starts after compilation settles.
    st = HypervisorState()
    for r in range(rounds + 2):
        slots = st.create_sessions_batch(
            [f"inc:{r}:{i}" for i in range(lanes)],
            SessionConfig(min_sigma_eff=0.0),
        )
        st.run_governance_wave(
            slots, [f"did:inc:{r}:{i}" for i in range(lanes)],
            slots.copy(), np.full(lanes, 0.8, np.float32),
            np.zeros((1, lanes, 16), np.uint32), now=float(r),
        )
        if r == 1:
            recompiles_before = health_plane.compile_summary()["recompiles"]
        st.metrics_snapshot()

    # Interleaved off/on pairs: machine drift (thermal, page cache,
    # sibling load) moves BOTH columns of a pair together, so the p50
    # delta isolates the sampler instead of the weather.
    class _Off:  # noqa: N801 — throwaway stub
        def sample_snapshot(self, snap, now):
            return 0

    stub, orig = _Off(), st.history
    off, on = [], []
    for _ in range(snap_iters):
        st.history = stub
        t0 = _time.perf_counter()
        st.metrics_snapshot()
        off.append(_time.perf_counter() - t0)
        st.history = orig
        t0 = _time.perf_counter()
        st.metrics_snapshot()
        on.append(_time.perf_counter() - t0)
    st.history = orig
    off.sort()
    on.sort()
    overhead_pct = _overhead_p50_pct(off, on)
    p50 = lambda xs: xs[len(xs) // 2]  # noqa: E731

    # ── seeded trigger drill (deterministic function of the seed):
    # one trigger per taxonomy class, spaced past the default 30 s
    # cooldown on the virtual clock each payload carries.
    base = 1000.0 + (seed % 997)
    drill = [
        ("degraded_enter", {"mode": "degraded", "failures": 3}),
        ("slo_burn_critical",
         {"queue": "lifecycle", "burn_fast": 14.6, "state": "critical"}),
        ("integrity_violation",
         {"table": "sessions", "kind": "bit_flip", "row": 7}),
        ("fleet_worker_dead",
         {"worker": "w1", "lease_seq": 4, "from": "suspected",
          "to": "dead"}),
        ("straggler", {"stage": "governance_wave", "p99_ms": 880.0}),
        ("state_restored", {"checkpoint_step": 12, "wal_seq": 99}),
    ]

    def run_drill(state: HypervisorState) -> tuple[list[str], list[float]]:
        ids_before = {r["id"] for r in state.incidents.index()}
        walls = []
        for i, (kind, payload) in enumerate(drill):
            payload = dict(payload, now=round(base + 40.0 * i, 6))
            t0 = _time.perf_counter()
            state.health.emit_event(kind, payload)
            walls.append(_time.perf_counter() - t0)
        ids = [
            r["id"] for r in reversed(state.incidents.index())
            if r["id"] not in ids_before
        ]
        return ids, walls

    drill_ids, capture_walls = run_drill(st)
    capture_walls.sort()
    bundle_bytes = sorted(
        st.incidents.get(i)["bytes"] for i in drill_ids
    )
    replay_ok = all(st.incidents.replay_check(i) for i in drill_ids)
    recompiles_after = health_plane.compile_summary()["recompiles"]

    # ── replay bit-identity: the same drill on two FRESH states (no
    # waves — the recorder's seq and the rule inputs are all that the
    # ids hash, so fresh states replay identically).
    replay_id_seqs = []
    for _ in range(2):
        fresh = HypervisorState()
        fresh.hindsight_clock = lambda: base
        ids, _walls = run_drill(fresh)
        replay_id_seqs.append(ids)
    incident_digest_match = float(
        replay_id_seqs[0] == replay_id_seqs[1] and bool(replay_id_seqs[0])
    )

    # ── history digest bit-identity: seeded direct-feed samples into
    # two fresh planes on a virtual clock (the caller's-clock
    # contract: same feed -> same rings -> same digest).
    def history_replay_digest() -> tuple[str, bool]:
        hp = HistoryPlane()
        rng = np.random.default_rng(seed)
        t = 0.0
        for _ in range(240 if quick else 600):
            t += 1.0
            vals = {
                name: float(rng.integers(0, 1000)) for name in hp.series
            }
            hp.sample(vals, now=t)
        return hp.digest(), hp.verify_conservation()["ok"]

    hd1, cons1 = history_replay_digest()
    hd2, cons2 = history_replay_digest()
    history_digest_match = float(hd1 == hd2)

    hist = st.history.summary()
    return {
        "seed": seed,
        "quick": quick,
        "workload": {"rounds": rounds, "lanes": lanes},
        "snapshot_p50_us": {
            "history_off": round(p50(off) * 1e6, 2),
            "history_on": round(p50(on) * 1e6, 2),
        },
        "clean_path_overhead_pct": round(overhead_pct, 2),
        "triggers_fired": len(drill),
        "captured": len(drill_ids),
        "capture_wall_us": {
            "n": len(capture_walls),
            "p50": round(p50(capture_walls) * 1e6, 1),
            "max": round(capture_walls[-1] * 1e6, 1),
        },
        "bundle_bytes": {
            "p50": bundle_bytes[len(bundle_bytes) // 2],
            "max": bundle_bytes[-1],
        },
        "replays": 2,
        "incident_digest_match": incident_digest_match,
        "history_digest_match": history_digest_match,
        "digest_match": incident_digest_match * history_digest_match,
        "replay_check_ok": replay_ok,
        "history": {
            "samples": hist["samples"],
            "evictions": hist["evictions"],
            "points_retained": hist["points_retained"],
            "conservation": bool(
                st.history.verify_conservation()["ok"] and cons1 and cons2
            ),
        },
        "recompiles_after_warmup": recompiles_after - recompiles_before,
    }


def failover_benchmark(seed: int, quick: bool) -> dict:
    """`--failover <seed>`: the round-20 fleet failover row — the full
    kill-one-worker reassignment drill on an in-process 3-worker fleet
    with a VIRTUAL clock (subprocess spawn walls would drown the
    numbers the row exists to track):

    * detection: the seeded chaos plan SIGKILLs one worker mid-drill
      (it silently stops beating); the lease registry convicts it
      within its windowed budget — detection latency in heartbeat
      windows;
    * reassignment: `FailoverController.failover` recovers the dead
      worker's tenants from their durable checkpoints + committed-WAL
      suffixes and splices them into survivors — replayed-ops count and
      the absorb wall (real seconds, also expressed in heartbeat
      windows);
    * the zombie: the dead worker's fenced WAL refuses its resume
      append with ZERO bytes written — `double_applied_ops` is the
      on-disk record-count delta across the refusal, hard-gated == 0;
    * post-splice serving: survivors keep running lifecycle rounds on
      the absorbed tenants — p50/p99 round wall vs the smoke SLO, and
      the zero-recompile absorb contract (the `[T, …]` shapes never
      changed, so the splice compiles NOTHING);
    * determinism: the ENTIRE drill (traffic, conviction, spread,
      recovery, ownership journal) replays bit-identically — two full
      runs must produce the same ownership transition digest.

    `regression.py` presence-gates the row from this round and
    hard-gates digest match, zero double-applies, and recompiles == 0.
    """
    import tempfile
    import time as _time
    from pathlib import Path as _Path

    from hypervisor_tpu.fleet import (
        DEAD,
        FleetRegistry,
        LeaseConfig,
    )
    from hypervisor_tpu.fleet.failover import (
        FailoverController,
        FencingError,
        ManagedWorker,
        OwnershipMap,
        WorkerDurability,
    )
    from hypervisor_tpu.fleet.worker import _small_capacity_config
    from hypervisor_tpu.observability import health as health_plane
    from hypervisor_tpu.resilience.wal import scan as wal_scan
    from hypervisor_tpu.serving import ServingConfig
    from hypervisor_tpu.tenancy import (
        TenantArena,
        TenantFrontDoor,
        TenantWaveScheduler,
    )
    from hypervisor_tpu.testing.chaos import (
        InjectedFleetFault,
        WaveChaosInjector,
        WaveChaosPlan,
    )

    cfg = _small_capacity_config()
    lease = LeaseConfig(heartbeat_interval_s=0.25)
    base = 1000.0 + (seed % 997)
    pre_rounds = 2 if quick else 4
    suffix_rounds = 2 if quick else 4
    post_rounds = 4 if quick else 10
    kill_round = pre_rounds + suffix_rounds  # after the WAL suffix

    plan = WaveChaosPlan(seed=seed, fleet_faults=(
        InjectedFleetFault(
            "worker_sigkill", at_round=kill_round, worker="w0"
        ),
    ))

    def build(root, wid, tenants, n_slots):
        arena = TenantArena(n_slots, cfg)
        front = TenantFrontDoor(arena, ServingConfig(buckets=(4, 8)))
        sched = TenantWaveScheduler(front)
        sched.warm(now=0.0)
        dur = WorkerDurability(
            root, wid, epoch=0, tenants=tenants, fsync=False
        ).adopt()
        slot_of = {}
        for slot, t in enumerate(tenants):
            arena.tenants[slot].journal = dur.wal(t)
            slot_of[t] = slot
        mw = ManagedWorker(
            wid, arena, dur, slot_of, list(range(len(tenants), n_slots))
        )
        return mw, front, sched

    def lifecycle_round(mw, front, sched, r, now):
        for t, slot in sorted(mw.slot_of.items()):
            front.submit_lifecycle(
                slot, f"{mw.worker_id}:r{r}:{t}",
                f"did:fo:{seed}:{mw.worker_id}:{r}:{t}", 0.8, now=now,
            )
        sched.lifecycle_round(now)

    def run_drill(root) -> dict:
        inj = WaveChaosInjector(plan)
        w0, f0, s0 = build(root, "w0", (0, 1), 2)
        w1, f1, s1 = build(root, "w1", (2,), 3)
        w2, f2, s2 = build(root, "w2", (3,), 3)
        fleet = {
            "w0": (w0, f0, s0), "w1": (w1, f1, s1), "w2": (w2, f2, s2),
        }
        reg = FleetRegistry(lease, seed=seed)
        om = OwnershipMap(seed=seed)
        ctl = FailoverController(om, config=cfg)
        now = base
        for wid in sorted(fleet):
            reg.register(wid, now)
            ctl.register(fleet[wid][0], now=now)

        dead_set: set[str] = set()
        detection = {"killed_round": None, "dead": None}
        round_no = 0
        replayed = 0
        absorb_wall_s = None
        checkpointed = False
        while detection["dead"] is None:
            round_no += 1
            for fault in inj.take_fleet_faults(round_no):
                if fault.kind == "worker_sigkill":
                    dead_set.add(fault.worker)
                    detection["killed_round"] = round_no
            for wid, (mw, front, sched) in sorted(fleet.items()):
                if wid in dead_set:
                    continue  # a SIGKILLed worker is SILENT
                if mw.slot_of:
                    lifecycle_round(mw, front, sched, round_no, now)
                reg.heartbeat(wid, now)
            # Evaluate at the SAME instant as the beats (a live worker
            # is 0 windows stale); the clock then advances one window,
            # so a silent worker ages exactly 1 window per round.
            for worker, new in reg.evaluate(now).items():
                if new == DEAD and worker in dead_set:
                    detection["dead"] = round_no
            now += lease.heartbeat_interval_s
            if round_no == pre_rounds:
                w0.arena.sync()
                for t, slot in sorted(w0.slot_of.items()):
                    w0.durability.checkpoint(
                        w0.arena.tenants[slot], t, step=1
                    )
                checkpointed = True
            if round_no > 200:  # pragma: no cover — runaway guard
                raise RuntimeError("lease plane never convicted w0")
        assert checkpointed
        w0.arena.sync()
        for slot in w0.slot_of.values():
            w0.arena.tenants[slot].journal.flush()
        detect_windows = detection["dead"] - detection["killed_round"]

        # ── the reassignment ──
        t0 = _time.perf_counter()
        report = ctl.failover("w0", now=round(now, 6))
        absorb_wall_s = _time.perf_counter() - t0
        replayed = report["replayed_ops"]

        # ── the zombie: resume the dead worker's WAL, refuse with
        # zero bytes — the on-disk committed count must not move.
        zombie_wal = w0.durability.tenant_dir(0) / "wal.log"
        before = len(wal_scan(zombie_wal).committed)
        fenced = 0
        try:
            with w0.durability.wal(0).txn("zombie_resume", {}):
                pass
        except FencingError:
            fenced = 1
        double_applied = len(wal_scan(zombie_wal).committed) - before

        # ── post-splice serving on the survivors ──
        recomp_before = health_plane.compile_summary()["recompiles"]
        walls = []
        for r in range(post_rounds):
            round_no += 1
            for wid in ("w1", "w2"):
                mw, front, sched = fleet[wid]
                t0 = _time.perf_counter()
                lifecycle_round(mw, front, sched, round_no, now)
                walls.append((_time.perf_counter() - t0) * 1e3)
            now += lease.heartbeat_interval_s
        recompiles = (
            health_plane.compile_summary()["recompiles"] - recomp_before
        )
        walls.sort()
        return {
            "detect_windows": detect_windows,
            "absorb_wall_s": absorb_wall_s,
            "replayed_ops": replayed,
            "tenants_reassigned": len(report["tenants"]),
            "survivors": report["survivors"],
            "ownership_digest": report["ownership_digest"],
            "fenced": fenced,
            "double_applied_ops": double_applied,
            "post_splice_walls_ms": walls,
            "recompiles_after_splice": recompiles,
        }

    runs = []
    with tempfile.TemporaryDirectory() as td:
        for i in range(2):
            runs.append(run_drill(_Path(td) / f"run{i}"))
    a, b = runs
    walls = a["post_splice_walls_ms"]
    p = lambda q: walls[min(len(walls) - 1, int(q * len(walls)))]  # noqa: E731
    slo_p99_ms = 750.0
    return {
        "seed": seed,
        "quick": quick,
        "workers": 3,
        "killed": "w0",
        "detection_windows": a["detect_windows"],
        "budget_windows": 2,
        "absorb_wall_s": round(a["absorb_wall_s"], 4),
        "absorb_windows": round(
            a["absorb_wall_s"] / lease.heartbeat_interval_s, 2
        ),
        "replayed_ops": a["replayed_ops"],
        "tenants_reassigned": a["tenants_reassigned"],
        "survivors": a["survivors"],
        "zombie_fenced": bool(a["fenced"]),
        "double_applied_ops": a["double_applied_ops"],
        "post_splice_rounds": len(walls),
        "post_splice_wall_ms": {
            "p50": round(p(0.50), 2), "p99": round(p(0.99), 2),
        },
        "slo_p99_ms": slo_p99_ms,
        "slo_ok": p(0.99) <= slo_p99_ms,
        "recompiles_after_splice": a["recompiles_after_splice"],
        "replays": 2,
        "digest_match": float(
            a["ownership_digest"] == b["ownership_digest"]
            and bool(a["ownership_digest"])
        ),
        "ownership_digest": a["ownership_digest"],
    }


def fleet_soak_benchmark(seed: int, quick: bool) -> dict:
    """`--fleet-soak <seed>`: the round-21 rebalancing soak — a long
    in-process 3-worker fleet run on a VIRTUAL clock that mixes the
    planned-migration plane with the crash plane at >=10x the failover
    row's session count:

    * rolling rebalances: every few rounds the deficit-aware planner
      proposes and EXECUTES zero-loss migrations (seal -> drain ->
      final checkpoint -> per-tenant fence -> adopt -> commit); the
      clean path replays ZERO WAL records per move;
    * a plain SIGKILL failover mid-soak (round 20's drill, now under
      sustained traffic), and a SECOND kill that lands mid-migration
      (source dies after `drain_source`, pre-fence) — failover wins
      the race, the migration aborts in the journal, and the tenant
      reassigns through the same splice path;
    * each dead worker's zombie resume refuses with zero bytes —
      `double_applied_ops` is the on-disk delta, hard-gated == 0;
    * exactly-one ownership is asserted EVERY round from the journal
      (`ownership_violations`, hard-gated == 0), and the `[T, ...]`
      splice contract keeps post-warmup recompiles at 0;
    * determinism: two full soak replays must produce the same
      ownership transition digest.

    `regression.py` presence-gates the row from this round and
    hard-gates the zeros, the session floor (>=10x the failover row),
    digest match, and p99 round wall within the smoke SLO.
    """
    import tempfile
    import time as _time
    from pathlib import Path as _Path

    from hypervisor_tpu.fleet import (
        DEAD,
        FleetRegistry,
        LeaseConfig,
    )
    from hypervisor_tpu.fleet.failover import (
        FailoverController,
        FencingError,
        ManagedWorker,
        OwnershipMap,
        WorkerDurability,
    )
    from hypervisor_tpu.fleet.rebalance import RebalanceController
    from hypervisor_tpu.config import DEFAULT_CONFIG, TableCapacity
    from hypervisor_tpu.observability import health as health_plane
    from hypervisor_tpu.resilience.wal import scan as wal_scan
    from hypervisor_tpu.serving import ServingConfig
    from hypervisor_tpu.tenancy import (
        TenantArena,
        TenantFrontDoor,
        TenantWaveScheduler,
    )
    from hypervisor_tpu.testing.chaos import (
        InjectedFleetFault,
        WaveChaosInjector,
        WaveChaosPlan,
    )

    lease = LeaseConfig(heartbeat_interval_s=0.25)
    base = 2000.0 + (seed % 997)
    rounds = 135 if quick else 220
    # The gate-6i small-table config, with the session table sized to
    # the soak: one lifecycle session lands per tenant per round and
    # parked sessions accrue, so a worker that ends up owning every
    # tenant needs ~`rounds` rows per tenant slot.
    cfg = DEFAULT_CONFIG.replace(capacity=TableCapacity(
        max_agents=64, max_sessions=rounds + 64, max_vouch_edges=64,
        max_sagas=16, max_steps_per_saga=4, max_elevations=16,
        delta_log_capacity=1024, event_log_capacity=64,
        trace_log_capacity=64,
    ))
    rebalance_every = 9
    checkpoint_every = 20
    kill1_round = rounds // 3       # plain SIGKILL (w0)
    kill2_round = (2 * rounds) // 3  # SIGKILL mid-migration (w1)

    plan = WaveChaosPlan(seed=seed, fleet_faults=(
        InjectedFleetFault(
            "worker_sigkill", at_round=kill1_round, worker="w0"
        ),
        InjectedFleetFault(
            "migration_kill_source", at_round=kill2_round, worker="w1"
        ),
    ))

    def build(root, wid, tenants, n_slots):
        arena = TenantArena(n_slots, cfg)
        front = TenantFrontDoor(arena, ServingConfig(buckets=(4, 8)))
        sched = TenantWaveScheduler(front)
        sched.warm(now=0.0)
        dur = WorkerDurability(
            root, wid, epoch=0, tenants=tenants, fsync=False
        ).adopt()
        slot_of = {}
        for slot, t in enumerate(tenants):
            arena.tenants[slot].journal = dur.wal(t)
            slot_of[t] = slot
        mw = ManagedWorker(
            wid, arena, dur, slot_of, list(range(len(tenants), n_slots))
        )
        return mw, front, sched

    def lifecycle_round(mw, front, sched, r, now):
        for t, slot in sorted(mw.slot_of.items()):
            front.submit_lifecycle(
                slot, f"{mw.worker_id}:r{r}:{t}",
                f"did:soak:{seed}:{mw.worker_id}:{r}:{t}", 0.8, now=now,
            )
        sched.lifecycle_round(now)
        return len(mw.slot_of)

    def flush_worker(mw):
        mw.arena.sync()
        for slot in mw.slot_of.values():
            journal = mw.arena.tenants[slot].journal
            if journal is not None:
                journal.flush()

    def run_soak(root) -> dict:
        inj = WaveChaosInjector(plan)
        fleet = {
            "w0": build(root, "w0", (0, 1, 2), 5),
            "w1": build(root, "w1", (3, 4), 5),
            "w2": build(root, "w2", (5,), 8),
        }
        all_tenants = tuple(range(6))
        reg = FleetRegistry(lease, seed=seed)
        om = OwnershipMap(seed=seed)
        ctl = FailoverController(om, config=cfg)
        reb = RebalanceController(om, ctl)
        now = base
        for wid in sorted(fleet):
            mw, front, sched = fleet[wid]
            reg.register(wid, now)
            ctl.register(mw, now=now)
            reb.attach_serving(wid, front, sched)
            # Every tenant durable from round 0: a kill at ANY round
            # must recover from a checkpoint + committed-WAL suffix.
            mw.arena.sync()
            for t, slot in sorted(mw.slot_of.items()):
                mw.durability.checkpoint(
                    mw.arena.tenants[slot], t, step=0
                )

        dead_set: set[str] = set()
        failed_over: dict[str, dict] = {}
        dead_tenants: dict[str, list[int]] = {}
        walls: dict[str, list[float]] = {w: [] for w in fleet}
        sessions = 0
        rebalance_runs = 0
        migration_replayed = 0
        failover_replayed = 0
        zombies_fenced = 0
        double_applied = 0
        ownership_violations = 0
        migrations_interrupted = 0
        replay_compiles = 0
        recomp_base = None

        def least_loaded_dest(src):
            cands = [
                (len(mw.slot_of), wid)
                for wid, (mw, _f, _s) in fleet.items()
                if wid != src
                and wid not in dead_set
                and mw.spare_slots
                and not reb._fenced_for(wid, min(fleet[src][0].slot_of))
            ]
            return min(cands)[1] if cands else None

        for r in range(1, rounds + 1):
            for fault in inj.take_fleet_faults(r):
                if fault.kind == "worker_sigkill":
                    dead_tenants[fault.worker] = sorted(
                        fleet[fault.worker][0].slot_of
                    )
                    dead_set.add(fault.worker)
                elif fault.kind == "migration_kill_source":
                    src = fault.worker
                    src_mw = fleet[src][0]
                    if src_mw.slot_of:
                        t = min(src_mw.slot_of)
                        dst = least_loaded_dest(src)
                        if dst is not None:
                            # Source dies drained-but-unfenced: the
                            # worst planned/crash interleaving.
                            reb.migrate(
                                t, dst, now, stop_after="drain_source"
                            )
                            migrations_interrupted += 1
                    dead_tenants[src] = sorted(src_mw.slot_of)
                    dead_set.add(src)
            for wid in sorted(fleet):
                mw, front, sched = fleet[wid]
                if wid in dead_set:
                    continue  # a SIGKILLed worker is SILENT
                if mw.slot_of:
                    t0 = _time.perf_counter()
                    sessions += lifecycle_round(mw, front, sched, r, now)
                    walls[wid].append(
                        (_time.perf_counter() - t0) * 1e3
                    )
                reg.heartbeat(wid, now)
            for worker, new in reg.evaluate(now).items():
                if (
                    new == DEAD
                    and worker in dead_set
                    and worker not in failed_over
                ):
                    flush_worker(fleet[worker][0])
                    # The WAL-replay path compiles its solo programs
                    # on first use (once per process) — that warmup is
                    # not a serving recompile, so it is measured apart
                    # and reported as `failover_replay_compiles`.
                    rc0 = health_plane.compile_summary()["recompiles"]
                    report = ctl.failover(worker, now=round(now, 6))
                    replay_compiles += (
                        health_plane.compile_summary()["recompiles"]
                        - rc0
                    )
                    failed_over[worker] = report
                    failover_replayed += report["replayed_ops"]
                    # The zombie: the dead worker's fenced WAL must
                    # refuse its resume append with ZERO bytes.
                    zt = dead_tenants[worker][0]
                    dur = fleet[worker][0].durability
                    zwal = dur.tenant_dir(zt) / "wal.log"
                    before = len(wal_scan(zwal).committed)
                    try:
                        with dur.wal(zt).txn("zombie_resume", {}):
                            pass
                    except FencingError:
                        zombies_fenced += 1
                    double_applied += (
                        len(wal_scan(zwal).committed) - before
                    )
            now += lease.heartbeat_interval_s
            if (
                r % rebalance_every == 0
                and not (dead_set - set(failed_over))
            ):
                rebalance_runs += 1
                res = reb.execute(now)
                for m in res["results"]:
                    if m.get("status") == "committed":
                        migration_replayed += m["replayed_ops"]
            if r % checkpoint_every == 0:
                for wid in sorted(fleet):
                    if wid in dead_set:
                        continue
                    mw = fleet[wid][0]
                    mw.arena.sync()
                    for t, slot in sorted(mw.slot_of.items()):
                        mw.durability.checkpoint(
                            mw.arena.tenants[slot], t, step=r
                        )
            # Exactly-one ownership from the journal, EVERY round.
            owners = om.summary(tail=1)["owners"]
            for t in all_tenants:
                holders = [
                    w for w, rec in owners.items()
                    if t in rec["tenants"]
                ]
                if len(holders) != 1:
                    ownership_violations += 1
            if r == 2:
                recomp_base = health_plane.compile_summary()[
                    "recompiles"
                ]

        recompiles = (
            health_plane.compile_summary()["recompiles"]
            - (recomp_base or 0)
            - replay_compiles
        )
        reb_sum = reb.summary(tail=1)
        return {
            "sessions": sessions,
            "rebalance_runs": rebalance_runs,
            "migrations_committed": reb_sum["migration_count"],
            "migrations_aborted": reb_sum["aborted_count"],
            "migrations_interrupted": migrations_interrupted,
            "migration_replayed_ops": migration_replayed,
            "failover_replayed_ops": failover_replayed,
            "failovers": len(failed_over),
            "zombies_fenced": zombies_fenced,
            "double_applied_ops": double_applied,
            "ownership_violations": ownership_violations,
            "recompiles_after_warmup": recompiles,
            "failover_replay_compiles": replay_compiles,
            "walls_ms": walls,
            "ownership_digest": om.transition_digest(),
        }

    runs = []
    with tempfile.TemporaryDirectory() as td:
        for i in range(2):
            runs.append(run_soak(_Path(td) / f"run{i}"))
    a, b = runs

    def pct(vals, q):
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    merged = [w for ws in a["walls_ms"].values() for w in ws]
    slo_p99_ms = 750.0
    return {
        "seed": seed,
        "quick": quick,
        "workers": 3,
        "tenants": 6,
        "rounds": rounds,
        "sessions": a["sessions"],
        "kills": ["w0", "w1"],
        "failovers": a["failovers"],
        "rebalance_runs": a["rebalance_runs"],
        "migrations": {
            "planned": (
                a["migrations_committed"] + a["migrations_aborted"]
            ),
            "committed": a["migrations_committed"],
            "aborted": a["migrations_aborted"],
            "interrupted_by_kill": a["migrations_interrupted"],
        },
        "migration_replayed_ops": a["migration_replayed_ops"],
        "failover_replayed_ops": a["failover_replayed_ops"],
        "zombies_fenced": a["zombies_fenced"],
        "double_applied_ops": a["double_applied_ops"],
        "ownership_violations": a["ownership_violations"],
        "recompiles_after_splice": a["recompiles_after_warmup"],
        "failover_replay_compiles": a["failover_replay_compiles"],
        "round_wall_ms": {
            "p50": round(pct(merged, 0.50), 2),
            "p99": round(pct(merged, 0.99), 2),
        },
        "per_worker_round_wall_ms": {
            wid: {
                "p50": round(pct(ws, 0.50), 2),
                "p99": round(pct(ws, 0.99), 2),
            }
            for wid, ws in sorted(a["walls_ms"].items())
            if ws
        },
        "slo_p99_ms": slo_p99_ms,
        "slo_ok": pct(merged, 0.99) <= slo_p99_ms,
        "replays": 2,
        "digest_match": float(
            a["ownership_digest"] == b["ownership_digest"]
            and bool(a["ownership_digest"])
        ),
        "ownership_digest": a["ownership_digest"],
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--quick", action="store_true", help="smaller batches")
    ap.add_argument("--json-only", action="store_true")
    ap.add_argument(
        "--metrics-out",
        type=str,
        default=None,
        help=(
            "write a metrics-plane report (p50/p95 drawn from the "
            "plane's histograms) to this path, e.g. BENCH_r06.json — or "
            "'auto' to land the next BENCH_r<NN>.json at the repo root "
            "and refresh BENCH_trajectory.json (the perf-regression "
            "gate's input, benchmarks/regression.py)"
        ),
    )
    ap.add_argument(
        "--chaos",
        type=int,
        default=None,
        metavar="SEED",
        help=(
            "also run the standard governance rounds under a fixed "
            "wave-layer fault plan (seeded, replayable) through the "
            "resilience supervisor, and report recovery latency + "
            "completed-wave ratio into the BENCH payload"
        ),
    )
    ap.add_argument(
        "--corrupt",
        type=int,
        default=None,
        metavar="SEED",
        help=(
            "also run the standard governance rounds with seeded REAL "
            "table corruption (bit flips / row rewrites / chain "
            "tampers) against the full integrity plane, and report "
            "detection-latency p50/max (waves) + sanitizer overhead "
            "(%%) into the BENCH payload"
        ),
    )
    ap.add_argument(
        "--scenarios",
        type=int,
        default=None,
        metavar="SEED",
        help=(
            "also run the seeded adversarial scenario suite (sybil "
            "flood, collusion ring, slash cascade, compensation storm, "
            "byzantine API fuzz; testing/scenarios.py) and report "
            "per-scenario containment scores + hardening clean-path "
            "overhead (%%) into the BENCH payload"
        ),
    )
    ap.add_argument(
        "--soak",
        type=int,
        default=None,
        metavar="SEED",
        help=(
            "also run the sustained open-workload soak through the "
            "serving front door (seeded Poisson arrivals, heavy-tailed "
            "lifetimes, deadline-paced bucketed waves; "
            "hypervisor_tpu/serving/loadgen.py) and report goodput + "
            "p50/p99 latency vs a stated SLO + shed rate + post-warmup "
            "recompile count into the BENCH payload"
        ),
    )
    ap.add_argument(
        "--autopilot",
        type=int,
        default=None,
        metavar="SEED",
        help=(
            "also run the autopilot shifting-mix soak (ISSUE 17): the "
            "same seeded three-phase trace replayed twice under the "
            "autopilot decision plane (hypervisor_tpu/autopilot) and "
            "once static, and report goodput improvement vs static, "
            "p99 vs the smoke SLO, decision count + outcomes, the "
            "decision-ledger digest's bit-identity across replays, and "
            "the zero-UNPLANNED-recompile contract into the BENCH "
            "payload"
        ),
    )
    ap.add_argument(
        "--tenants",
        type=int,
        default=None,
        metavar="T",
        help=(
            "also run the tenant-dense serving round (ISSUE 15): T "
            "logical hypervisors behind one TenantArena — per-tenant "
            "p99 vs SLO, the T-tenant wave's dispatch-step census vs "
            "T separate single-tenant dispatches (the amortization "
            "ratio regression.py floors), amortized µs/op, and the "
            "zero-recompile contract over the warmed (bucket, T) tiles"
        ),
    )
    ap.add_argument(
        "--fleet",
        type=int,
        default=None,
        metavar="N",
        help=(
            "also run the fleet observatory drill (ISSUE 18): N real "
            "worker subprocesses (existing API server + 2-tenant arena "
            "each) driven over HTTP — merged-drain series conservation "
            "+ worker-label coverage, per-worker zero post-warmup "
            "recompiles, and the SIGKILL liveness drill (detection "
            "latency in heartbeat windows vs the <= 2-window budget, "
            "lease-journal replay digest bit-identity)"
        ),
    )
    ap.add_argument(
        "--incidents",
        type=int,
        default=None,
        metavar="SEED",
        help=(
            "also run the hindsight-plane drill (ISSUE 19): retained "
            "telemetry history + black-box incident recorder on a live "
            "in-process state — clean-path snapshot overhead (history "
            "sampler on vs off), capture p50 + bundle bytes for a "
            "seeded taxonomy drill through the real health fan-out, "
            "incident-id and history-digest bit-identity over 2 "
            "replays, and the zero post-warmup recompile contract"
        ),
    )
    ap.add_argument(
        "--failover",
        type=int,
        default=None,
        metavar="SEED",
        help=(
            "also run the fleet failover drill (ISSUE 19 round 20): "
            "seeded 3-worker in-process fleet on a virtual clock — "
            "SIGKILL one worker mid-drill, lease conviction within the "
            "windowed budget, durable per-tenant recovery + splice into "
            "survivors, fenced zombie resume (zero double-applied "
            "ops), post-splice p50/p99 vs SLO on survivors, zero "
            "recompiles after splice, and ownership-digest bit-identity "
            "over 2 full drill replays"
        ),
    )
    ap.add_argument(
        "--fleet-soak",
        type=int,
        default=None,
        metavar="SEED",
        help=(
            "also run the fleet rebalancing soak (ISSUE 20 round 21): "
            "seeded 3-worker in-process fleet on a virtual clock at "
            ">=10x the failover row's session count — rolling planned "
            "zero-loss migrations, one plain SIGKILL failover plus one "
            "kill landing mid-migration (failover wins, journaled "
            "abort), fenced zombie resumes (zero double-applied ops), "
            "exactly-one ownership asserted every round, zero "
            "post-warmup recompiles, per-worker round-wall p50/p99 vs "
            "SLO, and ownership-digest bit-identity over 2 full soak "
            "replays"
        ),
    )
    ap.add_argument(
        "--no-census",
        action="store_true",
        help=(
            "skip the dispatch census row (tpu_aot_census.py --json in a "
            "subprocess). The census is on by default whenever "
            "--metrics-out is set: committed BENCH rounds must carry the "
            "ENTRY-step counts regression.py gates (a step-count "
            "regression fails CI even with no chip attached)"
        ),
    )
    ap.add_argument(
        "--write-results",
        action="store_true",
        help=(
            "overwrite benchmarks/results/ even off-TPU (the committed "
            "artifacts are TPU numbers; a CPU smoke run must not clobber "
            "them by accident)"
        ),
    )
    args = ap.parse_args()

    import jax

    device = jax.devices()[0]
    results = []
    for name, fn, fargs, batch in build_benchmarks(args.quick):
        rec = bench(fn, fargs, args.iters, batch, name)
        results.append(rec)
        if not args.json_only:
            vs = rec.get("vs_baseline")
            vs_s = f"{vs:>12,.1f}x" if vs else " " * 13
            print(
                f"{name:32s} batch={batch:6d} p50={rec['batch_p50_ms']:8.3f} ms "
                f"per-op={rec['per_op_us']:9.4f} µs {vs_s}",
                flush=True,
            )

    chaos_rec = None
    if args.chaos is not None:
        chaos_rec = chaos_benchmark(args.chaos, args.quick)
        if not args.json_only:
            lat = chaos_rec["recovery_latency_ms"]
            print(
                f"chaos[seed={args.chaos}]: "
                f"{chaos_rec['waves_completed']}/{chaos_rec['rounds']} waves "
                f"(ratio {chaos_rec['completed_wave_ratio']}), "
                f"{chaos_rec['dispatch_retries']} retries, recovery p50 "
                f"{lat.get('p50', '—')} ms",
                flush=True,
            )

    integrity_rec = None
    if args.corrupt is not None:
        integrity_rec = corrupt_benchmark(args.corrupt, args.quick)
        if not args.json_only:
            det = integrity_rec["detection_latency_waves"]
            print(
                f"corrupt[seed={args.corrupt}]: "
                f"{len(integrity_rec['corruptions_injected'])} injected, "
                f"{integrity_rec['restores']} restores, detection p50 "
                f"{det.get('p50', '—')}/max {det.get('max', '—')} waves, "
                f"sanitizer overhead "
                f"{integrity_rec['sanitizer_overhead_pct']}%",
                flush=True,
            )

    scenario_rec = None
    if args.scenarios is not None:
        scenario_rec = scenario_benchmark(args.scenarios, args.quick)
        if not args.json_only:
            worst = min(
                scenario_rec["scores"], key=scenario_rec["scores"].get
            )
            print(
                f"scenarios[seed={args.scenarios}]: min containment "
                f"{scenario_rec['min_score']} ({worst}), "
                f"{scenario_rec['attack_events']} attack events, "
                f"hardening overhead "
                f"{scenario_rec['hardening_overhead_pct']}%",
                flush=True,
            )

    soak_rec = None
    if args.soak is not None:
        soak_rec = soak_benchmark(args.soak, args.quick)
        if not args.json_only:
            lat = soak_rec["latency_ms"]
            print(
                f"soak[seed={args.soak}]: {soak_rec['served']} served of "
                f"{soak_rec['offered']['total']} offered at "
                f"{soak_rec['arrival_rate_hz']:.0f} Hz "
                f"(goodput {soak_rec['goodput_ops_s']} ops/s), p99 "
                f"{lat['p99']} ms vs SLO {soak_rec['slo_p99_ms']} ms, "
                f"shed rate {soak_rec['shed_rate']}, "
                f"{soak_rec['recompiles_after_warmup']} recompiles after "
                "warmup",
                flush=True,
            )
            # Latency observatory (round 14): per-class decomposition +
            # burn-rate plane summary next to the aggregate numbers.
            attr = soak_rec.get("latency_attribution") or {}
            slo_block = soak_rec.get("slo") or {}
            print(
                "  attribution: "
                f"{attr.get('tickets', 0)} tickets, sum err "
                f"{attr.get('max_sum_error_ms', 0)} ms, exemplar "
                f"coverage {attr.get('exemplar_coverage', 0)}, phase "
                f"shares {attr.get('phase_shares')}; slo alerts "
                f"{slo_block.get('alerts')}",
                flush=True,
            )

    census_rec = None
    if args.metrics_out and not args.no_census:
        census_rec = dispatch_census_row()
        if not args.json_only:
            if census_rec is None:
                print("dispatch census FAILED (row omitted)", flush=True)
            else:
                print(
                    f"dispatch census [{census_rec['backend']}]: fused "
                    f"{census_rec['dispatch_steps']} dispatch steps "
                    f"({census_rec['entry_steps']} entry), donation saves "
                    f"{census_rec['donation_delta_steps']}, fusion ratio "
                    f"vs r09 {census_rec['fusion_ratio']}",
                    flush=True,
                )

    roofline_rec = None
    if args.metrics_out:
        roofline_rec = roofline_row(args.quick)
        if not args.json_only:
            if roofline_rec is None:
                print("roofline row FAILED (row omitted)", flush=True)
            else:
                fl = roofline_rec.get("floor") or {}
                print(
                    f"roofline: {len(roofline_rec['programs'])} programs "
                    f"modeled ({roofline_rec['captures']} captures), wave "
                    f"floor {fl.get('modeled_floor_us')} µs, measured "
                    f"{fl.get('measured_p50_us')} µs, distance "
                    f"{fl.get('distance')}x, worst program "
                    f"{roofline_rec['worst_program']}",
                    flush=True,
                )

    # The tenant-dense round runs AFTER the roofline row on purpose:
    # its warm pass dispatches the shared solo programs at the arena's
    # SMALL per-tenant shapes, and a later capture of the same program
    # would shadow the bench-shaped model the roofline bytes band-gate
    # compares across rounds (`registry.latest` — newest capture wins).
    tenant_rec = None
    if args.tenants is not None:
        # Fresh interpreter for the tail-sensitive per-tenant p99 (see
        # tenant_dense_row_isolated); in-process only as a fallback.
        tenant_rec = tenant_dense_row_isolated(17, args.quick, args.tenants)
        if tenant_rec is None:
            tenant_rec = tenant_dense_benchmark(17, args.quick, args.tenants)
        if not args.json_only:
            c = tenant_rec.get("census") or {}
            print(
                f"tenant_dense[T={tenant_rec['tenants']}]: "
                f"{tenant_rec['served']} lifecycles over "
                f"{tenant_rec['waves']} batched waves, worst per-tenant "
                f"p99 {tenant_rec['per_tenant_p99_ms']} ms vs SLO "
                f"{tenant_rec['slo_p99_ms']} ms, amortized "
                f"{tenant_rec['amortized_us_per_op']} µs/op, census "
                f"{c.get('tenant_wave_steps')} steps vs "
                f"{c.get('t_times_single_steps')} for T solo dispatches "
                f"({c.get('amortization_ratio')}x), "
                f"{tenant_rec['recompiles_after_warmup']} recompiles "
                "after warmup",
                flush=True,
            )

    # The autopilot soak runs LAST among the timed rows: its grown-
    # bucket tiles (16/32/64) and three full trace replays would
    # otherwise pressure the process-global jit cache under the
    # tenant-dense bench's measured walls (and shadow the roofline
    # registry with small-shape captures).
    autopilot_rec = None
    if args.autopilot is not None:
        autopilot_rec = autopilot_soak_benchmark(args.autopilot, args.quick)
        if not args.json_only:
            outcomes = autopilot_rec["decision_outcomes"]
            print(
                f"autopilot[seed={args.autopilot}]: "
                f"{autopilot_rec['decisions']} decisions "
                f"({outcomes.get('confirmed', 0)} confirmed / "
                f"{outcomes.get('refuted', 0)} refuted), goodput "
                f"+{autopilot_rec.get('goodput_improvement', 0.0):.1%} vs "
                f"static, p99 {autopilot_rec['p99_ms']} ms vs SLO "
                f"{autopilot_rec['slo_p99_ms']} ms, buckets "
                f"{autopilot_rec['static']['buckets']} -> "
                f"{autopilot_rec['buckets_final']}, "
                f"{autopilot_rec['recompiles_after_warmup']} unplanned "
                f"recompiles (raw "
                f"{autopilot_rec['recompiles_after_warmup_raw']}), digest "
                f"match {autopilot_rec['digest_match']} over "
                f"{autopilot_rec['replays']} replays",
                flush=True,
            )


    # The fleet drill runs after every timed row: its workers are
    # fresh subprocesses (own jit caches), and the supervisor-side
    # drill is subprocess-isolated too, so ordering only matters for
    # machine load — the virtual-window lease clock is load-immune.
    fleet_rec = None
    if args.fleet is not None:
        fleet_rec = fleet_observatory_row_isolated(18, args.quick, args.fleet)
        if fleet_rec is None:
            fleet_rec = fleet_observatory_benchmark(
                18, args.quick, args.fleet
            )
        if not args.json_only:
            det = fleet_rec["detection_windows"]
            print(
                f"fleet[N={fleet_rec['workers']}]: killed "
                f"{fleet_rec['killed']}, detected suspected/dead in "
                f"{det['suspected']}/{det['dead']} windows (budget "
                f"{fleet_rec['budget_windows']}), digest match "
                f"{fleet_rec['digest_match']} over "
                f"{fleet_rec['replays']} replays, merged drain "
                f"{fleet_rec['merged_drain_wall_ms']} ms for "
                f"{fleet_rec['merged_series']} series "
                f"(conserved={fleet_rec['series_conserved']}, "
                f"coverage={fleet_rec['worker_label_coverage']}), "
                f"{fleet_rec['recompiles_after_warmup']} recompiles "
                "after warmup (worst worker)",
                flush=True,
            )

    # The incident drill runs after the fleet row: it is host-side
    # (no device work past its small warmup waves), so late ordering
    # keeps its clean-path overhead numbers off the jit-cache churn
    # the timed rows above generate.
    incident_rec = None
    if args.incidents is not None:
        incident_rec = incident_capture_benchmark(args.incidents, args.quick)
        if not args.json_only:
            cap = incident_rec["capture_wall_us"]
            print(
                f"incidents[seed={args.incidents}]: "
                f"{incident_rec['captured']}/"
                f"{incident_rec['triggers_fired']} triggers captured, "
                f"capture p50 {cap['p50']} µs, bundle p50 "
                f"{incident_rec['bundle_bytes']['p50']} B, clean-path "
                f"overhead {incident_rec['clean_path_overhead_pct']}%, "
                f"digest match {incident_rec['digest_match']} over "
                f"{incident_rec['replays']} replays (history "
                f"{incident_rec['history_digest_match']}), conservation "
                f"{incident_rec['history']['conservation']}, "
                f"{incident_rec['recompiles_after_warmup']} recompiles "
                "after warmup",
                flush=True,
            )

    # The failover drill runs after the incident row: it is virtual-
    # clock in-process (load-immune where it must be deterministic);
    # only its absorb wall and post-splice round walls are real time.
    failover_rec = None
    if args.failover is not None:
        failover_rec = failover_benchmark(args.failover, args.quick)
        if not args.json_only:
            ps = failover_rec["post_splice_wall_ms"]
            print(
                f"failover[seed={args.failover}]: killed "
                f"{failover_rec['killed']}, convicted in "
                f"{failover_rec['detection_windows']} windows (budget "
                f"{failover_rec['budget_windows']}), "
                f"{failover_rec['tenants_reassigned']} tenants absorbed "
                f"by {failover_rec['survivors']} in "
                f"{failover_rec['absorb_wall_s']} s "
                f"({failover_rec['absorb_windows']} windows), "
                f"{failover_rec['replayed_ops']} WAL ops replayed, "
                f"zombie fenced={failover_rec['zombie_fenced']} "
                f"(double-applied {failover_rec['double_applied_ops']}), "
                f"post-splice p50/p99 {ps['p50']}/{ps['p99']} ms vs SLO "
                f"{failover_rec['slo_p99_ms']} ms, "
                f"{failover_rec['recompiles_after_splice']} recompiles "
                f"after splice, digest match "
                f"{failover_rec['digest_match']} over "
                f"{failover_rec['replays']} replays",
                flush=True,
            )

    # The rebalancing soak runs after the failover drill: it reuses
    # the same virtual-clock fleet harness, so running it last keeps
    # its (much longer) round-wall series off the other rows' walls.
    fleet_soak_rec = None
    if args.fleet_soak is not None:
        fleet_soak_rec = fleet_soak_benchmark(args.fleet_soak, args.quick)
        if not args.json_only:
            rw = fleet_soak_rec["round_wall_ms"]
            mig = fleet_soak_rec["migrations"]
            print(
                f"fleet-soak[seed={args.fleet_soak}]: "
                f"{fleet_soak_rec['sessions']} sessions over "
                f"{fleet_soak_rec['rounds']} rounds, migrations "
                f"planned/committed/aborted "
                f"{mig['planned']}/{mig['committed']}/{mig['aborted']} "
                f"({fleet_soak_rec['migration_replayed_ops']} clean-"
                f"path WAL ops replayed), "
                f"{fleet_soak_rec['failovers']} failovers "
                f"(kills {fleet_soak_rec['kills']}, "
                f"{fleet_soak_rec['failover_replayed_ops']} ops "
                f"replayed), zombies fenced="
                f"{fleet_soak_rec['zombies_fenced']} (double-applied "
                f"{fleet_soak_rec['double_applied_ops']}), "
                f"{fleet_soak_rec['ownership_violations']} ownership "
                f"violations, round wall p50/p99 "
                f"{rw['p50']}/{rw['p99']} ms vs SLO "
                f"{fleet_soak_rec['slo_p99_ms']} ms, "
                f"{fleet_soak_rec['recompiles_after_splice']} "
                f"recompiles after warmup, digest match "
                f"{fleet_soak_rec['digest_match']} over "
                f"{fleet_soak_rec['replays']} replays",
                flush=True,
            )

    static_rec = None
    if args.metrics_out:
        static_rec = static_analysis_row()
        if not args.json_only:
            if static_rec is None:
                print("static analysis FAILED to run (row omitted)",
                      flush=True)
            else:
                print(
                    f"static analysis: {static_rec['rules']} rules, "
                    f"{static_rec['findings']} finding(s), "
                    f"{static_rec['suppressions']} suppressed, "
                    f"{static_rec['programs_traced']} programs traced",
                    flush=True,
                )

    if args.metrics_out:
        from benchmarks import regression

        if args.metrics_out == "auto":
            out_path = regression.next_round_path()
        else:
            out_path = Path(args.metrics_out)
        plane = metrics_plane_report(results)
        # Round-12 megakernel row: per-block µs/op + the armed census
        # structure; regression.py presence-gates it from round 12.
        wave_rec = wave_megakernel_row(
            args.quick, args.iters, census_rec, plane
        )
        if not args.json_only:
            blk = ", ".join(
                f"{k} {v['per_op_p50_us']}" for k, v in
                wave_rec["blocks"].items()
            )
            print(
                f"wave megakernel [{wave_rec['mode']}]: per-block µs/op "
                f"{blk}; armed census "
                f"{wave_rec['census_dispatch_steps']} steps",
                flush=True,
            )
        report = {
            "source": "benchmarks/bench_suite.py metrics plane",
            "device": str(device.device_kind),
            "backend": jax.default_backend(),
            "git_commit": _git_commit(),
            "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "iterations": args.iters,
            "quick": args.quick,
            "pipeline_latency_us": plane.get("full_governance_pipeline"),
            "benchmarks": plane,
            # Resilience row (--chaos <seed>): the trajectory tracks
            # completed-wave ratio + recovery latency alongside speed.
            "chaos": chaos_rec,
            # Integrity row (--corrupt <seed>): detection latency +
            # sanitizer overhead land in the trajectory too, and
            # regression.py gates the overhead.
            "integrity": integrity_rec,
            # Adversarial row (--scenarios <seed>): per-scenario
            # containment scores + hardening overhead; regression.py
            # gates min_score against the containment floor.
            "scenarios": scenario_rec,
            # Dispatch-census row (round 9): ENTRY/dispatch-bearing step
            # counts of the fused donated wave from tpu_aot_census.py —
            # regression.py gates the step count and the fusion ratio,
            # so a de-fusing refactor fails CI devicelessly. From round
            # 12 the headline steps are the MEGAKERNEL wave.
            "dispatch_census": census_rec,
            # Megakernel row (round 12): per-block µs/op + armed step
            # structure; presence-gated by regression.py from round 12.
            "wave_megakernel": wave_rec,
            # Serving-soak row (round 11, bench_suite --soak): goodput +
            # tail latency vs the stated SLO + shed rate + post-warmup
            # recompiles; regression.py gates the SLO, the goodput
            # floor, and the zero-recompile contract.
            "soak": soak_rec,
            # Static-analysis row (round 13, ISSUE 12): hvlint rule /
            # finding / suppression counts — regression.py presence-
            # gates it from round 13 and hard-gates findings == 0.
            "static_analysis": static_rec,
            # Roofline row (round 15, ISSUE 14): per-program modeled
            # HBM bytes + FLOPs from the live observatory joined with
            # measured walls — regression.py presence-gates it from
            # round 15 and band-gates modeled bytes per program
            # (HV_BENCH_ROOFLINE_BYTES_TOL): a fusion regression or
            # donation miss fails the gate on the MODEL, on cpu,
            # without waiting for the tunnel to heal.
            "roofline": roofline_rec,
            # Tenant-dense row (round 16, ISSUE 15, --tenants <T>):
            # per-tenant p99 vs SLO at >=100 tenants, the T-tenant
            # wave's step census vs T solo dispatches, amortized
            # µs/op, zero post-warmup recompiles — regression.py
            # presence-gates it from round 16 and floors the
            # amortization ratio (HV_BENCH_TENANT_AMORT).
            "tenant_dense": tenant_rec,
            # Autopilot row (round 17, --autopilot <seed>): the
            # shifting-mix soak under the deterministic decision plane
            # — goodput improvement vs static, p99 vs the smoke SLO,
            # decision count + outcomes, replay digest bit-identity,
            # zero UNPLANNED recompiles — regression.py presence-gates
            # it from round 17 and floors the improvement
            # (HV_BENCH_AUTOPILOT_GAIN).
            "autopilot_soak": autopilot_rec,
            # Fleet row (round 18, --fleet <N>): merged-drain series
            # conservation + worker-label coverage, per-worker zero
            # post-warmup recompiles across process boundaries, and
            # the SIGKILL kill drill (detection <= 2 heartbeat
            # windows, lease-journal digest bit-identical over 2
            # replays) — regression.py presence-gates it from round
            # 18 (HV_BENCH_FLEET_MIN workers, HV_BENCH_FLEET_DETECT
            # windows).
            "fleet": fleet_rec,
            # Incident row (round 19, --incidents <seed>): retained
            # history + black-box recorder — clean-path snapshot
            # overhead (history sampler on vs off), capture p50 +
            # bundle bytes, incident-id/history-digest bit-identity
            # over 2 replays, zero post-warmup recompiles —
            # regression.py presence-gates it from round 19 and
            # hard-gates overhead (HV_BENCH_INCIDENT_OVERHEAD),
            # digest match, and the recompile count.
            "incident_capture": incident_rec,
            # Failover row (round 20, --failover <seed>): the kill-one-
            # worker reassignment drill — detection + absorb latency in
            # heartbeat windows, replayed-ops count, fenced zombie
            # (double_applied_ops == 0), post-splice p50/p99 vs SLO on
            # survivors, zero recompiles after splice, ownership-digest
            # bit-identity over 2 full drill replays — regression.py
            # presence-gates it from round 20 and hard-gates digest
            # match, zero double-applies, and recompiles == 0.
            "failover": failover_rec,
            # Fleet-soak row (round 21, --fleet-soak <seed>): the
            # rebalancing soak at >=10x the failover row's session
            # count — rolling planned zero-loss migrations under
            # sustained traffic, one plain kill plus one kill landing
            # mid-migration (journaled abort, failover wins), fenced
            # zombies, exactly-one ownership asserted every round,
            # per-worker round-wall p50/p99 — regression.py
            # presence-gates it from round 21 and hard-gates the
            # session floor, zero double-applies / violations /
            # recompiles, digest match, and p99 within SLO.
            "fleet_soak": fleet_soak_rec,
        }
        out_path.write_text(json.dumps(report, indent=2) + "\n")
        if not args.json_only:
            print(f"wrote metrics-plane report to {out_path}")
        # A BENCH_r<NN>.json landing at the repo root is a new
        # trajectory row: rebuild the cumulative file regression.py
        # gates and hv_top.py renders.
        if regression._ROUND_RE.search(out_path.name):
            traj = regression.refresh_trajectory(out_path.parent)
            if not args.json_only:
                print(f"refreshed {traj}")

    results = [
        {k: v for k, v in r.items() if k != "_samples_ns"} for r in results
    ]
    out = {
        "device": str(device.device_kind),
        "backend": jax.default_backend(),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "iterations": args.iters,
        "quick": args.quick,
        "benchmarks": results,
        "chaos": chaos_rec,
        "integrity": integrity_rec,
        "scenarios": scenario_rec,
        "soak": soak_rec,
        "tenant_dense": tenant_rec,
        "autopilot_soak": autopilot_rec,
        "fleet": fleet_rec,
        "incident_capture": incident_rec,
        "failover": failover_rec,
        "fleet_soak": fleet_soak_rec,
    }
    if jax.default_backend() not in ("tpu",) and not args.write_results:
        print(
            f"\n[{jax.default_backend()} backend] results NOT written — the "
            "committed artifacts are TPU numbers. Pass --write-results to "
            "overwrite anyway."
        )
        return
    results_dir = Path(__file__).parent / "results"
    results_dir.mkdir(exist_ok=True)
    (results_dir / "benchmarks.json").write_text(json.dumps(out, indent=2))

    lines = [
        "# hypervisor_tpu benchmarks",
        "",
        f"Device: {device.device_kind} ({jax.default_backend()})  ",
        f"Methodology: perf_counter_ns, 10% warmup, {args.iters} iterations, "
        "p50 of batched device ticks (compile excluded). Reference numbers: "
        "single-op CPU Python p50s from BASELINE.md.",
        "",
        "Multi-shard structure: see [SCALING.md](SCALING.md) — per-phase "
        "collective census from the compiled HLO plus the weak-scaling "
        "table (`benchmarks/bench_scaling.py`).",
        "",
        "| metric | batch | batch p50 (ms) | per-op (µs) | throughput (ops/s) | ref p50 (µs) | speedup |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in results:
        base = r.get("baseline_p50_us")
        lines.append(
            f"| {r['name']} | {r['batch']:,} | {r['batch_p50_ms']:.3f} "
            f"| {r['per_op_us']:.4f} | {r['throughput_ops_s']:,.0f} "
            f"| {base if base is not None else '—'} "
            f"| {'%.0fx' % r['vs_baseline'] if 'vs_baseline' in r else '—'} |"
        )
    (results_dir / "BENCHMARKS.md").write_text("\n".join(lines) + "\n")
    if not args.json_only:
        print(f"\nwrote {results_dir}/benchmarks.json and BENCHMARKS.md")


if __name__ == "__main__":
    main()
