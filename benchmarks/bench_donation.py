"""Buffer donation for the wave's table arguments: before/after.

The fused governance wave reads AND rewrites the whole Agent/Session/
Vouch tables each dispatch; without donation XLA materialises a second
copy of every column per wave. `donate_argnums=(0, 1, 2)` lets the
outputs alias the input buffers (in-place HBM update) under the
re-staging contract documented at `state._WAVE_DONATED`.

Both loops CHAIN the tables through iterations (each wave's outputs are
the next wave's inputs) — exactly the state bridge's usage, and the
only legal usage once buffers are donated.

Run on the real chip for the committed number; the CPU run is the
methodology check (CPU donation support varies by jax version, so a
null CPU result does not reject the optimisation).
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--agents", type=int, default=10_000)
    ap.add_argument(
        "--cpu", action="store_true",
        help="force the hermetic CPU platform (skip the accelerator)",
    )
    args = ap.parse_args()
    if args.cpu:
        from _jax_platform import force_cpu_platform

        force_cpu_platform(1)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hypervisor_tpu.models import SessionState
    from hypervisor_tpu.ops import merkle as merkle_ops
    from hypervisor_tpu.ops.pipeline import governance_wave
    from hypervisor_tpu.tables.state import AgentTable, SessionTable, VouchTable
    from hypervisor_tpu.tables.struct import replace as t_replace

    n = args.agents
    b = k = 1024 if n >= 10_000 else max(8, n // 8)
    t = 3
    use_pallas = jax.default_backend() == "tpu"
    rng = np.random.RandomState(0)

    def fresh_tables():
        sessions = SessionTable.create(2 * k)
        ws = jnp.arange(k)
        sessions = t_replace(
            sessions,
            state=sessions.state.at[ws].set(
                jnp.int8(SessionState.HANDSHAKING.code)
            ),
            max_participants=sessions.max_participants.at[ws].set(8),
            min_sigma_eff=sessions.min_sigma_eff.at[ws].set(0.0),
        )
        return AgentTable.create(n), sessions, VouchTable.create(4096)

    bodies = jnp.asarray(
        rng.randint(0, 2**32, (t, k, merkle_ops.BODY_WORDS), dtype=np.uint64
                    ).astype(np.uint32)
    )
    cols = (
        jnp.arange(b, dtype=jnp.int32),             # slot
        jnp.arange(b, dtype=jnp.int32),             # did
        jnp.arange(b, dtype=jnp.int32) % k,         # session_slot
        jnp.full((b,), 0.8, jnp.float32),
        jnp.ones((b,), bool),
        jnp.zeros((b,), bool),
        jnp.arange(k, dtype=jnp.int32),             # wave_sessions
        bodies,
        0.0,
    )

    # wave_sessions is arange(k): measure the same range-compare fast
    # path the bridge/bench take in production, in BOTH arms.
    wave_range = (jnp.asarray(0, jnp.int32), jnp.asarray(k, jnp.int32))

    def run(donate: bool) -> float:
        fn = jax.jit(
            governance_wave,
            static_argnames=("use_pallas",),
            donate_argnums=(0, 1, 2) if donate else (),
        )
        agents, sessions, vouches = fresh_tables()
        out = fn(agents, sessions, vouches, *cols, use_pallas=use_pallas,
                 wave_range=wave_range)
        jax.block_until_ready(out.status)
        agents, sessions, vouches = out.agents, out.sessions, out.vouches
        times = []
        for _ in range(args.iters):
            t0 = time.perf_counter_ns()
            out = fn(agents, sessions, vouches, *cols, use_pallas=use_pallas,
                     wave_range=wave_range)
            jax.block_until_ready(out.status)
            times.append(time.perf_counter_ns() - t0)
            agents, sessions, vouches = out.agents, out.sessions, out.vouches
        times.sort()
        return times[len(times) // 2] / 1e6

    base = run(donate=False)
    donated = run(donate=True)
    backend = jax.default_backend()
    print(
        f"governance_wave {n} agents / {b} joins ({backend}): "
        f"p50 no-donate={base:.3f} ms, donate={donated:.3f} ms, "
        f"delta={100 * (base - donated) / base:+.1f}%"
    )
    import json

    print(json.dumps({
        "metric": "wave_table_donation",
        "backend": backend,
        "p50_ms_no_donate": round(base, 4),
        "p50_ms_donate": round(donated, 4),
        "delta_pct": round(100 * (base - donated) / base, 2),
    }))


if __name__ == "__main__":
    main()
