"""Buffer donation for the wave's table arguments: before/after.

The fused governance wave reads AND rewrites the whole Agent/Session/
Vouch tables each dispatch; without donation XLA materialises a second
copy of every column per wave. `donate_argnums` lets the outputs alias
the input buffers (in-place HBM update) under the re-staging contract
documented at `state._WAVE_DONATED`. Donation is the DEFAULT since
round 9 (`HV_DONATE_TABLES=0` opts out); this harness measures the
before/after that decision rests on, plus the round-9 fused-epilogue
configuration (gateway + audit append + gauge/sanitizer tail in the
same program — the production facade path).

Three arms, all CHAINING the tables through iterations (each wave's
outputs are the next wave's inputs — the state bridge's usage, and the
only legal usage once buffers are donated):

  * no-donate   — the plain wave, copy-on-write outputs
  * donate      — same program, donated tables
  * fused       — the round-9 fused program (donated, epilogue riding)

Run on the real chip for the committed number; the CPU run is the
methodology check (XLA:CPU reuses host buffers aggressively, so a null
CPU delta does not reject the optimisation). `--metrics-out auto` folds
the result into the newest committed `BENCH_r<NN>.json` as its
`donation` row and refreshes `BENCH_trajectory.json` — so the chip
number lands in the trajectory the day the accelerator tunnel unwedges.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--agents", type=int, default=10_000)
    ap.add_argument(
        "--cpu", action="store_true",
        help="force the hermetic CPU platform (skip the accelerator)",
    )
    ap.add_argument(
        "--metrics-out", type=str, default=None,
        help=(
            "'auto' folds the result into the newest committed "
            "BENCH_r<NN>.json as its 'donation' row and refreshes "
            "BENCH_trajectory.json; any other value is a path for a "
            "standalone JSON report"
        ),
    )
    args = ap.parse_args()
    if args.cpu:
        from _jax_platform import force_cpu_platform

        force_cpu_platform(1)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hypervisor_tpu.models import SessionState
    from hypervisor_tpu.ops import merkle as merkle_ops
    from hypervisor_tpu.ops.pipeline import governance_wave
    from hypervisor_tpu.observability import metrics as mp
    from hypervisor_tpu.observability import tracing
    from hypervisor_tpu.tables.logs import DeltaLog, EventLog, TraceLog
    from hypervisor_tpu.tables.state import (
        AgentTable,
        ElevationTable,
        SagaTable,
        SessionTable,
        VouchTable,
    )
    from hypervisor_tpu.tables.struct import replace as t_replace

    n = args.agents
    b = k = 1024 if n >= 10_000 else max(8, n // 8)
    t = 3
    use_pallas = jax.default_backend() == "tpu"
    rng = np.random.RandomState(0)

    def fresh_tables():
        sessions = SessionTable.create(2 * k)
        ws = jnp.arange(k)
        sessions = t_replace(
            sessions,
            state=sessions.state.at[ws].set(
                jnp.int8(SessionState.HANDSHAKING.code)
            ),
            max_participants=sessions.max_participants.at[ws].set(8),
            min_sigma_eff=sessions.min_sigma_eff.at[ws].set(0.0),
        )
        return AgentTable.create(n), sessions, VouchTable.create(4096)

    bodies = jnp.asarray(
        rng.randint(0, 2**32, (t, k, merkle_ops.BODY_WORDS), dtype=np.uint64
                    ).astype(np.uint32)
    )
    cols = (
        jnp.arange(b, dtype=jnp.int32),             # slot
        jnp.arange(b, dtype=jnp.int32),             # did
        jnp.arange(b, dtype=jnp.int32) % k,         # session_slot
        jnp.full((b,), 0.8, jnp.float32),
        jnp.ones((b,), bool),
        jnp.zeros((b,), bool),
        jnp.arange(k, dtype=jnp.int32),             # wave_sessions
        bodies,
        0.0,
    )

    # wave_sessions is arange(k): measure the same range-compare fast
    # path the bridge/bench take in production, in BOTH arms.
    wave_range = (jnp.asarray(0, jnp.int32), jnp.asarray(k, jnp.int32))

    def run_plain(donate: bool) -> float:
        fn = jax.jit(
            governance_wave,
            static_argnames=("use_pallas",),
            donate_argnums=(0, 1, 2) if donate else (),
        )
        agents, sessions, vouches = fresh_tables()
        out = fn(agents, sessions, vouches, *cols, use_pallas=use_pallas,
                 wave_range=wave_range)
        jax.block_until_ready(out.status)
        agents, sessions, vouches = out.agents, out.sessions, out.vouches
        times = []
        for _ in range(args.iters):
            t0 = time.perf_counter_ns()
            out = fn(agents, sessions, vouches, *cols, use_pallas=use_pallas,
                     wave_range=wave_range)
            jax.block_until_ready(out.status)
            times.append(time.perf_counter_ns() - t0)
            agents, sessions, vouches = out.agents, out.sessions, out.vouches
        times.sort()
        return times[len(times) // 2] / 1e6

    def run_fused() -> float:
        """The round-9 production configuration: donated tables + ring
        + gauge epilogue in ONE program (no gateway lanes — the bench
        wave carries no actions, like bench.py's)."""
        fn = jax.jit(
            governance_wave,
            static_argnames=("use_pallas",),
            donate_argnames=(
                "agents", "sessions", "vouches", "metrics", "trace",
                "delta_log",
            ),
        )
        agents, sessions, vouches = fresh_tables()
        sagas = SagaTable.create(256, 8)
        elevations = ElevationTable.create(256)
        delta_log = DeltaLog.create(1 << 16)
        event_log = EventLog.create(1 << 12)
        trace = TraceLog.create(1 << 12)
        metrics = mp.REGISTRY.create_table()
        ctx = tracing.TraceContext(
            trace=jnp.uint32(1), span=jnp.uint32(2),
            wave_seq=jnp.int32(0), sampled=jnp.asarray(False),
        )

        def step(agents, sessions, vouches, metrics, trace, delta_log):
            return fn(
                agents, sessions, vouches, *cols, use_pallas=use_pallas,
                wave_range=wave_range, metrics=metrics, trace=trace,
                trace_ctx=ctx, elevations=elevations, delta_log=delta_log,
                epilogue_tables=(sagas, event_log),
            )

        out = step(agents, sessions, vouches, metrics, trace, delta_log)
        jax.block_until_ready(out.status)
        state = (out.agents, out.sessions, out.vouches, out.metrics,
                 out.trace, out.delta_log)
        times = []
        for _ in range(args.iters):
            t0 = time.perf_counter_ns()
            out = step(*state)
            jax.block_until_ready(out.status)
            times.append(time.perf_counter_ns() - t0)
            state = (out.agents, out.sessions, out.vouches, out.metrics,
                     out.trace, out.delta_log)
        times.sort()
        return times[len(times) // 2] / 1e6

    base = run_plain(donate=False)
    donated = run_plain(donate=True)
    fused = run_fused()
    backend = jax.default_backend()
    print(
        f"governance_wave {n} agents / {b} joins ({backend}): "
        f"p50 no-donate={base:.3f} ms, donate={donated:.3f} ms "
        f"({100 * (base - donated) / base:+.1f}%), fused-epilogue "
        f"(donated, all planes)={fused:.3f} ms"
    )
    row = {
        "metric": "wave_table_donation",
        "backend": backend,
        "p50_ms_no_donate": round(base, 4),
        "p50_ms_donate": round(donated, 4),
        "p50_ms_fused_epilogue": round(fused, 4),
        "delta_pct": round(100 * (base - donated) / base, 2),
        "fused_vs_no_donate_pct": round(100 * (base - fused) / base, 2),
        "iters": args.iters,
        "agents": n,
    }
    print(json.dumps(row))

    if args.metrics_out == "auto":
        # Fold into the newest committed round file so the trajectory
        # carries the donation evidence next to the census row.
        from benchmarks import regression

        rounds = sorted(
            regression.REPO_ROOT.glob("BENCH_r*.json"),
            key=lambda p: p.name,
        )
        if not rounds:
            print("no BENCH_r*.json to fold into; skipped")
            return
        target = rounds[-1]
        doc = json.loads(target.read_text())
        doc["donation"] = row
        target.write_text(json.dumps(doc, indent=2) + "\n")
        traj = regression.refresh_trajectory()
        print(f"folded donation row into {target.name}; refreshed {traj}")
    elif args.metrics_out:
        Path(args.metrics_out).write_text(json.dumps(row, indent=2) + "\n")


if __name__ == "__main__":
    main()
