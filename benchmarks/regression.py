"""Perf-regression harness over the committed `BENCH_r*.json` history.

The repo has been publishing one `BENCH_r<NN>.json` per growth round
but nothing consumed them — a regression on the headline envelope
(≤268 µs p50 full-pipeline, BASELINE.md) would land unnoticed. This
module closes the loop:

  * **Trajectory** — `load_history()` parses every committed round.
    Two formats exist: the *wrapper* form (r01–r05: the bench driver's
    `{"n", "cmd", "rc", "tail", "parsed": {...}}` capture; failed runs
    carry `rc != 0` and no parse) and the *suite* form (r06+:
    `bench_suite.py --metrics-out` metrics-plane reports).
    `write_trajectory()` folds them into one cumulative
    `BENCH_trajectory.json` — the file `hv_top.py` renders and this
    gate reads.
  * **Gate** — `compare()` checks the NEWEST round against the median
    of its *comparable* priors and fails on any per-bench p50 above
    `baseline * (1 + tolerance)`. Rounds are comparable only when
    format, backend, AND quick-flag match: cpu smoke numbers must
    never gate tpu envelopes (or vice versa), and `--quick` batches
    are a different workload than full-scale ones. Historical
    fluctuation between OLD rounds never fails the gate — only the
    tip is judged.

Tolerance defaults are per-backend (`HV_BENCH_TOL` overrides): tpu
runs are stable enough for 0.5 (fail at 1.5× the baseline); cpu runs
on shared CI hosts get 3.0 (fail at 4×) so the tier-1 gate is
non-flaky while still catching order-of-magnitude cliffs.

CLI::

    python benchmarks/regression.py                  # gate the newest round
    python benchmarks/regression.py --check F.json   # gate a fresh report
    python benchmarks/regression.py --tolerance 0.5
"""

from __future__ import annotations

import argparse
import json
import os
import re
import statistics
import sys
from pathlib import Path
from typing import Optional

REPO_ROOT = Path(__file__).resolve().parent.parent
_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")

#: Backend -> default tolerance band (fraction above baseline allowed).
DEFAULT_TOLERANCE = {"tpu": 0.5, "cpu": 3.0}

#: Backend -> max sanitizer overhead (%) a `--corrupt` round may report
#: (`HV_BENCH_INTEGRITY_OVERHEAD` overrides). The acceptance envelope
#: is ≤2% on TPU; cpu CI hosts get a wide non-flaky band that still
#: catches a sanitizer accidentally riding the hot path.
DEFAULT_INTEGRITY_OVERHEAD = {"tpu": 2.0, "cpu": 50.0}

#: Minimum containment score a `--scenarios` round's WORST adversary
#: class may report (`HV_SCENARIO_FLOOR` overrides). Containment is a
#: min-over-components conjunction, so one floor gates every scenario.
DEFAULT_SCENARIO_FLOOR = 0.8

#: Backend -> max hardening clean-path overhead (%) a `--scenarios`
#: round may report (`HV_BENCH_HARDENING_OVERHEAD` overrides) — the
#: damper + supervisor must be invisible on the clean path. The cpu
#: bound is an order-of-magnitude smoke guard only: the overhead is a
#: percent of sub-ms clean-path walls, and on a one-core cpu box host
#: scheduling jitter alone swings identical code between ~2% and ~70%
#: run to run (observed r17; committed history ≤10.4% under quieter
#: hosts). 2% on TPU is the real contract.
DEFAULT_HARDENING_OVERHEAD = {"tpu": 2.0, "cpu": 100.0}

#: Audit-plane rows every suite round must carry — the tree unit's
#: bench coverage (ISSUE 7) must not silently vanish from the payload.
REQUIRED_SUITE_BENCHES = (
    "merkle_root_10_deltas",
    "merkle_root_100_deltas",
    "merkle_root_1000_deltas",
    "chain_verify_50_deltas",
)
#: `scrub_sweep` joined the standard payload in round 9; earlier
#: committed rounds are exempt.
SCRUB_ROW_SINCE = 9

#: The dispatch-census row joined the standard payload in round 10
#: (the dispatch-floor mega-fusion PR); earlier rounds are exempt.
CENSUS_ROW_SINCE = 10

#: The serving-soak row (bench_suite --soak) joined the standard
#: payload in round 11 (the serving front door PR); earlier rounds are
#: exempt. A suite round from 11 on that drops the row regresses
#: serving coverage even if every other number is fine.
SOAK_ROW_SINCE = 11

#: The static-analysis row (hvlint, ISSUE 12) joined the standard
#: payload in round 13; earlier rounds are exempt. A suite round from
#: 13 on that drops the row regresses the contract-analysis coverage
#: even if every number is fine — and a row with findings > 0 means
#: an unsuppressed contract violation shipped.
STATIC_ROW_SINCE = 13

#: The latency observatory joined the soak row in round 14 (ISSUE 13):
#: per-class p50/p99 latency (`latency_ms_by_kind`) and the
#: critical-path attribution block (`latency_attribution`: per-class
#: queue_wait/pad_wait/wave_wall decomposition, the attribution-sum
#: invariant's worst error, exemplar coverage, wave-phase shares). A
#: soak row from 14 on missing either regresses the observability
#: coverage even if every latency number is fine.
ATTR_ROW_SINCE = 14

#: Hard cap on the soak row's reported `max_sum_error_ms`: the
#: decomposition must PARTITION the measured ticket latency — it is
#: arithmetic on the same floats, so anything above rounding noise
#: means a component was dropped or double-counted
#: (`HV_BENCH_ATTR_SUM_TOL_MS` overrides).
DEFAULT_ATTR_SUM_TOL_MS = 0.01

#: Minimum goodput ratio (served / offered) a soak row may report
#: (`HV_BENCH_SOAK_GOODPUT` overrides): the front door must actually
#: serve an open workload, not shed its way to a fast p99.
DEFAULT_SOAK_GOODPUT = 0.7

#: Multiplier on the soak row's own stated SLO the measured p99 must
#: stay under (`HV_BENCH_SOAK_SLO_FACTOR` overrides; 1.0 = the row
#: passes exactly when it met its stated SLO).
DEFAULT_SOAK_SLO_FACTOR = 1.0

#: Minimum r09-anchored fusion ratio a census row may report
#: (`HV_CENSUS_FUSION_FLOOR` overrides): the round-10 acceptance bar —
#: the donated fused wave must stay at least 2x below the r09 five-
#: program dispatch total. A de-fusing refactor (or a phase silently
#: falling out of the fused program) lands here even with no chip.
DEFAULT_CENSUS_FUSION_FLOOR = 2.0

#: The round-12 floor (whole-wave Mosaic megakernels): from round 12
#: the census row's `dispatch_steps` is the MEGAKERNEL wave, and the
#: fusion ratio must reflect the >=4x step cut vs the r10 anchor —
#: 322 / 37 ≈ 8.7 (148 -> <=37 intra-program steps, ISSUE 11
#: acceptance). Pre-r12 rounds keep the old floor; the env override
#: outranks both.
R12_CENSUS_FUSION_FLOOR = 8.7

#: The census row measures the megakernel wave from this round on, and
#: the `wave_megakernel` bench row (per-block µs/op + step counts)
#: becomes a required payload key — dropping it regresses the
#: megakernel coverage even if every other number is fine.
WAVE_ROW_SINCE = 12

#: The roofline observatory joined the trajectory in round 15
#: (ISSUE 14): per-program modeled HBM bytes + FLOPs from the live
#: cost registry (`observability.roofline`), achieved-bandwidth
#: fraction / MFU against the measured stage walls, the per-phase byte
#: model, and the distance-to-the-floor block. A suite round from 15
#: on missing the row regresses the observability coverage.
ROOFLINE_ROW_SINCE = 15

#: Allowed fractional drift of each program's MODELED HBM bytes vs the
#: median of comparable prior rounds (`HV_BENCH_ROOFLINE_BYTES_TOL`
#: overrides). Modeled bytes are deterministic per jax/XLA version and
#: bucket shape — the band absorbs compiler upgrades, not fusion
#: regressions or donation misses, which inflate modeled traffic and
#: fail HERE, on cpu, without waiting for the accelerator tunnel to
#: heal. Gated both directions: silently SHRINKING traffic is a model
#: break worth a look too.
DEFAULT_ROOFLINE_BYTES_TOL = 0.25

#: The tenant-dense row joined the trajectory in round 16 (ISSUE 15):
#: >=100 logical hypervisors behind one TenantArena — per-tenant p99
#: vs the row's stated SLO, the T-tenant wave's dispatch-step census
#: vs T separate single-tenant dispatches, amortized µs/op, and the
#: zero-recompile contract over the warmed (bucket, T) tile set. A
#: suite round from 16 on missing the row regresses the coverage.
TENANT_ROW_SINCE = 16

#: Amortization floor for the tenant wave (`HV_BENCH_TENANT_AMORT`
#: overrides): dispatch-bearing steps for T separate single-tenant
#: megakernel dispatches over the ONE T-tenant program's steps must
#: stay >= this — the ISSUE 15 acceptance bar (>=50x at T=100, i.e.
#: the batched wave holds <= 2x the solo census). Deterministic per
#: jax/XLA version, devicelessly measured, so a de-vmapped or
#: per-tenant-looped regression fails HERE with no chip attached.
DEFAULT_TENANT_AMORT_FLOOR = 50.0

#: Minimum tenant count the row must serve (`HV_BENCH_TENANT_MIN`
#: overrides) — the acceptance criterion's ">=100 tenants from one
#: process".
DEFAULT_TENANT_MIN = 100

#: The autopilot row joined the trajectory in round 17 (ISSUE 17,
#: bench_suite --autopilot): the shifting-workload-mix soak under the
#: deterministic decision plane (`hypervisor_tpu/autopilot`) — goodput
#: improvement vs the static baseline, p99 vs the row's stated smoke
#: SLO, decision count + outcome attribution, the decision ledger's
#: replay digest bit-identity, zero UNPLANNED post-warmup recompiles,
#: zero invariant violations. A suite round from 17 on missing the row
#: regresses the control-plane coverage even if every number is fine.
AUTOPILOT_ROW_SINCE = 17

#: Minimum goodput improvement vs static the autopilot row may report
#: (`HV_BENCH_AUTOPILOT_GAIN` overrides) — the ISSUE 17 acceptance
#: bar: >=20% better goodput than the static baseline on the shifting
#: mix the static bucket set saturates on.
DEFAULT_AUTOPILOT_GAIN = 0.2

#: Multiplier on the autopilot row's own stated SLO the measured p99
#: must stay under (`HV_BENCH_AUTOPILOT_SLO_FACTOR` overrides).
DEFAULT_AUTOPILOT_SLO_FACTOR = 1.0

#: Minimum decision count (`HV_BENCH_AUTOPILOT_DECISIONS` overrides):
#: a run where the controller never fired proves nothing about the
#: decision plane — the shifting mix is built to trigger it.
DEFAULT_AUTOPILOT_MIN_DECISIONS = 1

#: The fleet row joined the trajectory in round 18 (ISSUE 18,
#: bench_suite --fleet): N real worker subprocesses behind one merged
#: drain — series conservation (merged == Σ per-worker) with
#: `worker="<id>"` on every row, per-worker zero post-warmup
#: recompiles across process boundaries, the SIGKILL kill drill's
#: detection latency vs the windowed budget, and the lease journal's
#: replay digest bit-identity. A suite round from 18 on missing the
#: row regresses the fleet-observability coverage.
FLEET_ROW_SINCE = 18

#: Minimum worker count the fleet row must run
#: (`HV_BENCH_FLEET_MIN` overrides): one worker proves nothing about
#: a merged cross-process drain.
DEFAULT_FLEET_MIN_WORKERS = 2

#: Detection budget in heartbeat windows (`HV_BENCH_FLEET_DETECT`
#: overrides): the kill drill's DEAD verdict must land within this
#: many windows of the victim's last beat — push0's detect half of
#: detect-and-reassign, pinned ahead of the shard-out.
DEFAULT_FLEET_DETECT_WINDOWS = 2.0

#: The incident row joined the trajectory in round 19 (ISSUE 19,
#: bench_suite --incidents): the hindsight plane — retained telemetry
#: history + black-box incident recorder measured live. Clean-path
#: snapshot overhead (history sampler on vs off), capture p50 + bundle
#: bytes for a seeded taxonomy drill through the real health fan-out,
#: incident-id and history-digest bit-identity over two replays, zero
#: post-warmup recompiles. A suite round from 19 on missing the row
#: regresses the postmortem-evidence coverage.
INCIDENT_ROW_SINCE = 19

#: Max clean-path overhead (%) the history sampler may add to the
#: metrics drain (`HV_BENCH_INCIDENT_OVERHEAD` overrides): the tiered
#: rings fold the snapshot the drain already paid for — host-side
#: appends only, zero extra device_get — so the band is tight.
DEFAULT_INCIDENT_OVERHEAD_PCT = 15.0

#: The failover row joined the trajectory in round 20 (ISSUE 19,
#: bench_suite --failover): the kill-one-worker reassignment drill —
#: detection latency vs the windowed budget, durable recovery
#: (checkpoint + committed-WAL suffix) spliced into survivors, the
#: fenced zombie's double-applied-op count (hard zero), post-splice
#: serving latency, zero recompiles on absorb, and bit-identical
#: ownership transition digests over two full drill replays. A suite
#: round from 20 on missing the row regresses the reassign half of
#: detect-and-reassign.
FAILOVER_ROW_SINCE = 20

#: Detection budget (heartbeat windows) for the failover drill's
#: conviction (`HV_BENCH_FAILOVER_DETECT` overrides) — same contract
#: as the fleet row's kill drill: DEAD within this many windows.
DEFAULT_FAILOVER_DETECT_WINDOWS = 2.0

#: The fleet-soak row joined the trajectory in round 21 (ISSUE 20,
#: bench_suite --fleet-soak): the rebalancing soak — rolling planned
#: zero-loss migrations under sustained traffic at >=10x the failover
#: row's session count, one plain kill plus one kill landing
#: mid-migration (journaled abort, failover wins), fenced zombies
#: (hard-zero double-applies), exactly-one ownership asserted every
#: round (hard-zero violations), zero post-warmup serving recompiles,
#: per-worker round-wall percentiles vs the smoke SLO, and
#: ownership-digest bit-identity over two full soak replays. A suite
#: round from 21 on missing the row regresses the planned half of the
#: handoff plane.
FLEET_SOAK_ROW_SINCE = 21

#: Session floor for the fleet soak (`HV_BENCH_FLEET_SOAK_SESSIONS`
#: overrides): >=10x the failover drill's ~76-session count — the soak
#: exists to prove the handoff protocol at sustained scale, so a row
#: that quietly shrank its traffic is a regression.
DEFAULT_FLEET_SOAK_SESSIONS = 760


def census_fusion_floor(round_num: int) -> float:
    """The fusion-ratio floor for a given round: env override, else the
    r12 megakernel floor from WAVE_ROW_SINCE on, else the r10 floor."""
    env_floor = os.environ.get("HV_CENSUS_FUSION_FLOOR")
    if env_floor:
        return float(env_floor)
    return (
        R12_CENSUS_FUSION_FLOOR
        if round_num >= WAVE_ROW_SINCE
        else DEFAULT_CENSUS_FUSION_FLOOR
    )

#: Allowed fractional growth of the fused wave's dispatch-bearing step
#: count vs the median of comparable prior rounds
#: (`HV_BENCH_CENSUS_TOL` overrides). Step counts are deterministic per
#: jax/XLA version; the band absorbs compiler upgrades, not refactors.
DEFAULT_CENSUS_TOL = 0.15


def _backend_of(device: str) -> str:
    return "tpu" if "tpu" in (device or "").lower() else "cpu"


def parse_round_file(path: Path) -> Optional[dict]:
    """One trajectory row from one BENCH_r*.json, or None when the
    round recorded a failed run (wrapper rc != 0) or an unknown shape."""
    m = _ROUND_RE.search(path.name)
    if not m:
        return None
    try:
        doc = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None
    row = {
        "round": int(m.group(1)),
        "file": path.name,
    }
    if "benchmarks" in doc and isinstance(doc["benchmarks"], dict):
        # Suite form: bench_suite.py metrics-plane report.
        benches = {
            name: rec["per_op_p50_us"]
            for name, rec in doc["benchmarks"].items()
            if isinstance(rec, dict) and "per_op_p50_us" in rec
        }
        headline = (doc.get("pipeline_latency_us") or {}).get(
            "per_op_p50_us"
        )
        chaos = doc.get("chaos")
        integrity = doc.get("integrity")
        scenarios = doc.get("scenarios")
        census = doc.get("dispatch_census")
        donation = doc.get("donation")
        soak = doc.get("soak")
        static = doc.get("static_analysis")
        roofline = doc.get("roofline")
        row.update(
            format="suite",
            backend=doc.get("backend", "cpu"),
            device=doc.get("device", ""),
            quick=bool(doc.get("quick", False)),
            timestamp=doc.get("timestamp"),
            git_commit=doc.get("git_commit"),
            headline_per_op_us=headline,
            benches=benches,
            # Resilience row (bench_suite --chaos): completed-wave ratio
            # + recovery latency land in the trajectory alongside speed.
            chaos=(
                {
                    "seed": chaos.get("seed"),
                    "completed_wave_ratio": chaos.get("completed_wave_ratio"),
                    "recovery_latency_ms": chaos.get("recovery_latency_ms"),
                    "degraded_entries": chaos.get("degraded_entries"),
                }
                if isinstance(chaos, dict)
                else None
            ),
            # Integrity row (bench_suite --corrupt): detection latency
            # + sanitizer overhead tracked alongside speed, and the
            # overhead is gated below.
            integrity=(
                {
                    "seed": integrity.get("seed"),
                    "detection_latency_waves": integrity.get(
                        "detection_latency_waves"
                    ),
                    "sanitizer_overhead_pct": integrity.get(
                        "sanitizer_overhead_pct"
                    ),
                    "restores": integrity.get("restores"),
                    "repairs": integrity.get("repairs"),
                }
                if isinstance(integrity, dict)
                else None
            ),
            # Adversarial row (bench_suite --scenarios): per-scenario
            # containment + hardening overhead, gated below.
            scenarios=(
                {
                    "seed": scenarios.get("seed"),
                    "scores": scenarios.get("scores"),
                    "min_score": scenarios.get("min_score"),
                    "hardening_overhead_pct": scenarios.get(
                        "hardening_overhead_pct"
                    ),
                    "attack_events": scenarios.get("attack_events"),
                }
                if isinstance(scenarios, dict)
                else None
            ),
            # Dispatch-census row (round 10): the fused wave's ENTRY /
            # dispatch-bearing step counts + donated-vs-not diff, gated
            # below — the tunnel-wedge-proof perf metric. From round 12
            # `dispatch_steps` is the MEGAKERNEL wave.
            census=census if isinstance(census, dict) else None,
            # Megakernel row (round 12): per-block µs/op + the armed
            # wave's step structure; presence-gated from WAVE_ROW_SINCE.
            wave_megakernel=(
                doc.get("wave_megakernel")
                if isinstance(doc.get("wave_megakernel"), dict)
                else None
            ),
            # Donation chip row (bench_donation.py --metrics-out):
            # informational until the tunnel unwedges — the trajectory
            # carries it so the chip number lands the day it measures.
            donation=donation if isinstance(donation, dict) else None,
            # Serving-soak row (bench_suite --soak, round 11): goodput,
            # tail latency vs the stated SLO, shed rate, post-warmup
            # recompiles — gated below.
            soak=(
                {
                    "seed": soak.get("seed"),
                    "arrival_rate_hz": soak.get("arrival_rate_hz"),
                    "served": soak.get("served"),
                    "offered": (soak.get("offered") or {}).get("total"),
                    "goodput_ops_s": soak.get("goodput_ops_s"),
                    "goodput_ratio": soak.get("goodput_ratio"),
                    "shed_rate": soak.get("shed_rate"),
                    "latency_p50_ms": (soak.get("latency_ms") or {}).get("p50"),
                    "latency_p99_ms": (soak.get("latency_ms") or {}).get("p99"),
                    "slo_p99_ms": soak.get("slo_p99_ms"),
                    "deadline_misses": soak.get("deadline_misses"),
                    "recompiles_after_warmup": soak.get(
                        "recompiles_after_warmup"
                    ),
                    "invariant_violations": soak.get("invariant_violations"),
                    # Latency observatory (round 14): per-class spread +
                    # the critical-path attribution summary — presence-
                    # gated below so the trajectory keeps showing
                    # class-level drift and decomposition health.
                    "latency_ms_by_kind": soak.get("latency_ms_by_kind"),
                    "latency_attribution": (
                        {
                            "tickets": attr.get("tickets"),
                            "max_sum_error_ms": attr.get("max_sum_error_ms"),
                            "exemplar_coverage": attr.get(
                                "exemplar_coverage"
                            ),
                            "phase_shares": attr.get("phase_shares"),
                            "classes": attr.get("classes"),
                        }
                        if isinstance(
                            attr := soak.get("latency_attribution"), dict
                        )
                        else None
                    ),
                    "slo_alerts": (
                        (soak.get("slo") or {}).get("alerts")
                        if isinstance(soak.get("slo"), dict)
                        else None
                    ),
                }
                if isinstance(soak, dict)
                else None
            ),
            # Static-analysis row (round 13, ISSUE 12): hvlint's rule /
            # finding / suppression counts ride the trajectory so
            # dropping the gate is itself a regression (presence-gated
            # below, findings hard-gated to zero).
            static_analysis=(
                {
                    "rules": static.get("rules"),
                    "findings": static.get("findings"),
                    "suppressions": static.get("suppressions"),
                    "files_analyzed": static.get("files_analyzed"),
                    "programs_traced": static.get("programs_traced"),
                }
                if isinstance(static, dict)
                else None
            ),
            # Tenant-dense row (round 16, ISSUE 15): per-tenant p99 vs
            # SLO, the T-tenant wave's amortization census, amortized
            # µs/op, zero post-warmup recompiles — gated below.
            tenant_dense=(
                {
                    "seed": tenant.get("seed"),
                    "tenants": tenant.get("tenants"),
                    "served": tenant.get("served"),
                    "waves": tenant.get("waves"),
                    "per_tenant_p99_ms": tenant.get("per_tenant_p99_ms"),
                    "slo_p99_ms": tenant.get("slo_p99_ms"),
                    "amortized_us_per_op": tenant.get(
                        "amortized_us_per_op"
                    ),
                    "census": tenant.get("census"),
                    "amortization_ratio": tenant.get(
                        "amortization_ratio"
                    ),
                    "recompiles_after_warmup": tenant.get(
                        "recompiles_after_warmup"
                    ),
                    "compiles_after_warmup": tenant.get(
                        "compiles_after_warmup"
                    ),
                }
                if isinstance(
                    tenant := doc.get("tenant_dense"), dict
                )
                else None
            ),
            # Autopilot row (round 17, ISSUE 17): the shifting-mix
            # soak under the deterministic decision plane — goodput
            # improvement vs static, p99 vs the stated SLO, decision
            # count + outcomes, replay digest bit-identity, zero
            # UNPLANNED recompiles — gated below.
            autopilot_soak=(
                {
                    "seed": pilot.get("seed"),
                    "quick": pilot.get("quick"),
                    "events": pilot.get("events"),
                    "p99_ms": pilot.get("p99_ms"),
                    "slo_p99_ms": pilot.get("slo_p99_ms"),
                    "goodput_ratio": pilot.get("goodput_ratio"),
                    "goodput_improvement": pilot.get(
                        "goodput_improvement"
                    ),
                    "decisions": pilot.get("decisions"),
                    "decision_outcomes": pilot.get("decision_outcomes"),
                    "decisions_digest": pilot.get("decisions_digest"),
                    "digest_match": pilot.get("digest_match"),
                    "replays": pilot.get("replays"),
                    "buckets_final": pilot.get("buckets_final"),
                    "recompiles_after_warmup": pilot.get(
                        "recompiles_after_warmup"
                    ),
                    "recompiles_after_warmup_raw": pilot.get(
                        "recompiles_after_warmup_raw"
                    ),
                    "prewarm": pilot.get("prewarm"),
                    "invariant_violations": pilot.get(
                        "invariant_violations"
                    ),
                    "static": pilot.get("static"),
                }
                if isinstance(
                    pilot := doc.get("autopilot_soak"), dict
                )
                else None
            ),
            # Fleet row (round 18, ISSUE 18): merged-drain series
            # conservation + worker-label coverage, per-worker zero
            # post-warmup recompiles, kill-drill detection latency vs
            # the windowed budget, lease-journal replay digest
            # bit-identity — gated below.
            fleet=(
                {
                    "seed": fleet.get("seed"),
                    "workers": fleet.get("workers"),
                    "budget_windows": fleet.get("budget_windows"),
                    "detection_windows": fleet.get("detection_windows"),
                    "digest": fleet.get("digest"),
                    "digest_match": fleet.get("digest_match"),
                    "replays": fleet.get("replays"),
                    "merged_drain_wall_ms": fleet.get(
                        "merged_drain_wall_ms"
                    ),
                    "merged_series": fleet.get("merged_series"),
                    "series_per_worker_sum": fleet.get(
                        "series_per_worker_sum"
                    ),
                    "series_conserved": fleet.get("series_conserved"),
                    "worker_label_coverage": fleet.get(
                        "worker_label_coverage"
                    ),
                    "recompiles_after_warmup": fleet.get(
                        "recompiles_after_warmup"
                    ),
                    "compiles_after_warmup": fleet.get(
                        "compiles_after_warmup"
                    ),
                    "per_worker": fleet.get("per_worker"),
                }
                if isinstance(fleet := doc.get("fleet"), dict)
                else None
            ),
            # Incident row (round 19, ISSUE 19): hindsight-plane
            # clean-path overhead, capture cost + bundle bytes,
            # incident-id/history-digest replay bit-identity, history
            # conservation, zero post-warmup recompiles — gated below.
            incident_capture=(
                {
                    "seed": inc.get("seed"),
                    "quick": inc.get("quick"),
                    "snapshot_p50_us": inc.get("snapshot_p50_us"),
                    "clean_path_overhead_pct": inc.get(
                        "clean_path_overhead_pct"
                    ),
                    "triggers_fired": inc.get("triggers_fired"),
                    "captured": inc.get("captured"),
                    "capture_wall_us": inc.get("capture_wall_us"),
                    "bundle_bytes": inc.get("bundle_bytes"),
                    "replays": inc.get("replays"),
                    "incident_digest_match": inc.get(
                        "incident_digest_match"
                    ),
                    "history_digest_match": inc.get(
                        "history_digest_match"
                    ),
                    "digest_match": inc.get("digest_match"),
                    "replay_check_ok": inc.get("replay_check_ok"),
                    "history": inc.get("history"),
                    "recompiles_after_warmup": inc.get(
                        "recompiles_after_warmup"
                    ),
                }
                if isinstance(inc := doc.get("incident_capture"), dict)
                else None
            ),
            # Failover row (round 20, ISSUE 19): kill-one-worker
            # reassignment drill — detection windows vs budget, durable
            # recovery + splice into survivors, fenced-zombie double
            # applies (hard zero), post-splice serving, zero absorb
            # recompiles, ownership-digest replay bit-identity — gated
            # below.
            failover=(
                {
                    "seed": fo.get("seed"),
                    "quick": fo.get("quick"),
                    "workers": fo.get("workers"),
                    "killed": fo.get("killed"),
                    "detection_windows": fo.get("detection_windows"),
                    "budget_windows": fo.get("budget_windows"),
                    "absorb_wall_s": fo.get("absorb_wall_s"),
                    "absorb_windows": fo.get("absorb_windows"),
                    "replayed_ops": fo.get("replayed_ops"),
                    "tenants_reassigned": fo.get("tenants_reassigned"),
                    "survivors": fo.get("survivors"),
                    "zombie_fenced": fo.get("zombie_fenced"),
                    "double_applied_ops": fo.get("double_applied_ops"),
                    "post_splice_wall_ms": fo.get("post_splice_wall_ms"),
                    "slo_p99_ms": fo.get("slo_p99_ms"),
                    "slo_ok": fo.get("slo_ok"),
                    "recompiles_after_splice": fo.get(
                        "recompiles_after_splice"
                    ),
                    "replays": fo.get("replays"),
                    "digest_match": fo.get("digest_match"),
                    "ownership_digest": fo.get("ownership_digest"),
                }
                if isinstance(fo := doc.get("failover"), dict)
                else None
            ),
            # Fleet-soak row (round 21, ISSUE 20): the rebalancing
            # soak — planned zero-loss migrations + kills under
            # sustained traffic at >=10x the failover row's sessions,
            # hard-zero double-applies / ownership violations /
            # serving recompiles, per-worker round walls vs SLO,
            # ownership-digest replay bit-identity — gated below.
            fleet_soak=(
                {
                    "seed": fs.get("seed"),
                    "quick": fs.get("quick"),
                    "workers": fs.get("workers"),
                    "tenants": fs.get("tenants"),
                    "rounds": fs.get("rounds"),
                    "sessions": fs.get("sessions"),
                    "kills": fs.get("kills"),
                    "failovers": fs.get("failovers"),
                    "rebalance_runs": fs.get("rebalance_runs"),
                    "migrations": fs.get("migrations"),
                    "migration_replayed_ops": fs.get(
                        "migration_replayed_ops"
                    ),
                    "failover_replayed_ops": fs.get(
                        "failover_replayed_ops"
                    ),
                    "zombies_fenced": fs.get("zombies_fenced"),
                    "double_applied_ops": fs.get("double_applied_ops"),
                    "ownership_violations": fs.get(
                        "ownership_violations"
                    ),
                    "recompiles_after_splice": fs.get(
                        "recompiles_after_splice"
                    ),
                    "failover_replay_compiles": fs.get(
                        "failover_replay_compiles"
                    ),
                    "round_wall_ms": fs.get("round_wall_ms"),
                    "per_worker_round_wall_ms": fs.get(
                        "per_worker_round_wall_ms"
                    ),
                    "slo_p99_ms": fs.get("slo_p99_ms"),
                    "slo_ok": fs.get("slo_ok"),
                    "replays": fs.get("replays"),
                    "digest_match": fs.get("digest_match"),
                    "ownership_digest": fs.get("ownership_digest"),
                }
                if isinstance(fs := doc.get("fleet_soak"), dict)
                else None
            ),
            # Roofline row (round 15, ISSUE 14): per-program modeled
            # bytes/FLOPs + achieved fractions from the live cost
            # registry — presence-gated from ROOFLINE_ROW_SINCE and
            # bytes band-gated per program below.
            roofline=(
                {
                    "quick": roofline.get("quick"),
                    "programs": {
                        name: {
                            "modeled_bytes": p.get("modeled_bytes"),
                            "modeled_flops": p.get("modeled_flops"),
                            "achieved_bw_frac": p.get("achieved_bw_frac"),
                            "mfu": p.get("mfu"),
                            "wall_p50_us": p.get("wall_p50_us"),
                        }
                        for name, p in (
                            roofline.get("programs") or {}
                        ).items()
                    },
                    "phases": roofline.get("phases"),
                    "floor": roofline.get("floor"),
                    "worst_program": roofline.get("worst_program"),
                }
                if isinstance(roofline, dict)
                else None
            ),
        )
        return row
    if "parsed" in doc or "rc" in doc:
        # Wrapper form: the bench driver capture. Failed runs (rc != 0)
        # carry no numbers — kept out of the trajectory, never gated.
        parsed = doc.get("parsed")
        if doc.get("rc", 1) != 0 or not isinstance(parsed, dict):
            return None
        value = parsed.get("value")
        if value is None:
            return None
        device = parsed.get("device", "")
        row.update(
            format="wrapper",
            backend=_backend_of(device),
            device=device,
            quick=False,
            timestamp=None,
            git_commit=None,
            headline_per_op_us=float(value),
            benches={"full_governance_pipeline": float(value)},
        )
        return row
    return None


def load_history(root: Path = REPO_ROOT) -> list[dict]:
    """Every parseable committed round, sorted by round number."""
    rows = []
    for path in sorted(root.glob("BENCH_r*.json")):
        row = parse_round_file(path)
        if row is not None:
            rows.append(row)
    rows.sort(key=lambda r: r["round"])
    return rows


def _comparable_key(row: dict) -> tuple:
    return (row["format"], row["backend"], row["quick"])


def build_trajectory(rows: list[dict]) -> dict:
    return {
        "source": "benchmarks/regression.py",
        "rounds": rows,
    }


def write_trajectory(
    rows: list[dict], path: Optional[Path] = None, root: Path = REPO_ROOT
) -> Path:
    """Write the cumulative trajectory (rebuilt from the round files —
    append-by-rebuild keeps it consistent even if a round is amended)."""
    path = path or (root / "BENCH_trajectory.json")
    path.write_text(json.dumps(build_trajectory(rows), indent=2) + "\n")
    return path


def refresh_trajectory(root: Path = REPO_ROOT) -> Path:
    """Re-scan the round files and rewrite BENCH_trajectory.json —
    called by `bench_suite.py` right after it lands a new round."""
    return write_trajectory(load_history(root), root=root)


def baseline_for(current: dict, rows: list[dict]) -> tuple[dict, int]:
    """Per-bench median over the comparable rounds BEFORE `current`."""
    key = _comparable_key(current)
    priors = [
        r
        for r in rows
        if r["round"] < current["round"] and _comparable_key(r) == key
    ]
    per_bench: dict[str, list[float]] = {}
    for r in priors:
        for name, value in r["benches"].items():
            if value is not None and value > 0:
                per_bench.setdefault(name, []).append(float(value))
    return (
        {name: statistics.median(vs) for name, vs in per_bench.items()},
        len(priors),
    )


def compare(
    current: dict, rows: list[dict], tolerance: Optional[float] = None
) -> dict:
    """Gate `current` against its comparable baseline.

    Returns {"ok", "tolerance", "baseline_rounds", "checked",
    "regressions", "improvements", "skipped"} — `ok` is False iff any
    bench's p50 exceeds `baseline_median * (1 + tolerance)`.
    """
    if tolerance is None:
        env = os.environ.get("HV_BENCH_TOL")
        tolerance = (
            float(env)
            if env
            else DEFAULT_TOLERANCE.get(current["backend"], 3.0)
        )
    baseline, n_priors = baseline_for(current, rows)
    regressions, improvements, checked = [], [], []
    for name, value in sorted(current["benches"].items()):
        base = baseline.get(name)
        if base is None or value is None or value <= 0:
            continue
        ratio = value / base
        entry = {
            "bench": name,
            "current_per_op_us": round(float(value), 4),
            "baseline_per_op_us": round(base, 4),
            "ratio": round(ratio, 3),
        }
        checked.append(entry)
        if ratio > 1.0 + tolerance:
            regressions.append(entry)
        elif ratio < 1.0 / (1.0 + tolerance):
            improvements.append(entry)
    # Audit-row presence gate: a suite round missing the tree unit's
    # rows regresses COVERAGE even if every present number is fine.
    if current.get("format") == "suite":
        required = list(REQUIRED_SUITE_BENCHES)
        if current["round"] >= SCRUB_ROW_SINCE:
            required.append("scrub_sweep")
        for name in required:
            if name not in current["benches"]:
                entry = {
                    "bench": f"missing:{name}",
                    "current_per_op_us": 0.0,
                    "baseline_per_op_us": 0.0,
                    "ratio": 0.0,
                }
                checked.append(entry)
                regressions.append(entry)
    # Integrity gate: a round that ran the corruption drill must keep
    # the sanitizer's clean-path overhead inside the backend's band.
    integrity = current.get("integrity")
    if integrity and integrity.get("sanitizer_overhead_pct") is not None:
        env_cap = os.environ.get("HV_BENCH_INTEGRITY_OVERHEAD")
        cap = (
            float(env_cap)
            if env_cap
            else DEFAULT_INTEGRITY_OVERHEAD.get(current["backend"], 50.0)
        )
        overhead = float(integrity["sanitizer_overhead_pct"])
        entry = {
            "bench": "integrity_sanitizer_overhead",
            "current_per_op_us": overhead,
            "baseline_per_op_us": cap,
            "ratio": round(overhead / cap, 3) if cap else 0.0,
        }
        checked.append(entry)
        if overhead > cap:
            regressions.append(entry)
    # Scenario gate: a round that ran the adversarial suite must keep
    # its WORST containment score at/above the floor AND the hardening
    # mechanisms invisible on the clean path.
    scenarios = current.get("scenarios")
    if scenarios and scenarios.get("min_score") is not None:
        env_floor = os.environ.get("HV_SCENARIO_FLOOR")
        floor = float(env_floor) if env_floor else DEFAULT_SCENARIO_FLOOR
        min_score = float(scenarios["min_score"])
        entry = {
            "bench": "scenario_containment_min",
            "current_per_op_us": min_score,
            "baseline_per_op_us": floor,
            "ratio": round(min_score / floor, 3) if floor else 0.0,
        }
        checked.append(entry)
        if min_score < floor:
            regressions.append(entry)
    # Dispatch-census gates (round 10): the fused wave's step count is
    # the dispatch-floor metric — deviceless, deterministic, chip-free.
    census = current.get("census")
    if current.get("format") == "suite" and current["round"] >= CENSUS_ROW_SINCE:
        if not census:
            entry = {
                "bench": "missing:dispatch_census",
                "current_per_op_us": 0.0,
                "baseline_per_op_us": 0.0,
                "ratio": 0.0,
            }
            checked.append(entry)
            regressions.append(entry)
    if census and census.get("dispatch_steps") is not None:
        # (a) r09-anchored fusion ratio floor: the mega-fusion must
        # hold — and from round 12 the bumped megakernel floor (the
        # >=4x whole-wave step cut vs the r10 anchor, ISSUE 11).
        ratio_val = census.get("fusion_ratio")
        if ratio_val is not None:
            floor = census_fusion_floor(current["round"])
            entry = {
                "bench": "census_fusion_ratio",
                "current_per_op_us": float(ratio_val),
                "baseline_per_op_us": floor,
                "ratio": round(float(ratio_val) / floor, 3) if floor else 0.0,
            }
            checked.append(entry)
            if float(ratio_val) < floor:
                regressions.append(entry)
        # (b) step-count creep vs the median of comparable prior rounds
        # that censused the SAME backend.
        priors = [
            r["census"]["dispatch_steps"]
            for r in rows
            if r["round"] < current["round"]
            and _comparable_key(r) == _comparable_key(current)
            and r.get("census")
            and r["census"].get("backend") == census.get("backend")
            and r["census"].get("dispatch_steps")
        ]
        if priors:
            env_tol = os.environ.get("HV_BENCH_CENSUS_TOL")
            ctol = float(env_tol) if env_tol else DEFAULT_CENSUS_TOL
            base = statistics.median(priors)
            steps = float(census["dispatch_steps"])
            entry = {
                "bench": "census_dispatch_steps",
                "current_per_op_us": steps,
                "baseline_per_op_us": base,
                "ratio": round(steps / base, 3) if base else 0.0,
            }
            checked.append(entry)
            if steps > base * (1.0 + ctol):
                regressions.append(entry)
    # Megakernel-row presence gate (round 12): a suite round from 12 on
    # must carry the `wave_megakernel` bench row (per-block µs/op +
    # armed step structure) — dropping it regresses the whole-wave
    # kernel coverage even if every other number is fine.
    if (
        current.get("format") == "suite"
        and current["round"] >= WAVE_ROW_SINCE
        and not current.get("wave_megakernel")
    ):
        entry = {
            "bench": "missing:wave_megakernel",
            "current_per_op_us": 0.0,
            "baseline_per_op_us": 0.0,
            "ratio": 0.0,
        }
        checked.append(entry)
        regressions.append(entry)
    # Serving-soak gates (round 11): presence from SOAK_ROW_SINCE, then
    # the row's own stated SLO, a goodput floor (no shedding your way
    # to a fast p99), and the zero-recompile + zero-violation contract.
    soak = current.get("soak")
    if (
        current.get("format") == "suite"
        and current["round"] >= SOAK_ROW_SINCE
        and not soak
    ):
        entry = {
            "bench": "missing:soak",
            "current_per_op_us": 0.0,
            "baseline_per_op_us": 0.0,
            "ratio": 0.0,
        }
        checked.append(entry)
        regressions.append(entry)
    if soak:
        p99 = soak.get("latency_p99_ms")
        slo = soak.get("slo_p99_ms")
        if p99 is not None and slo:
            env_f = os.environ.get("HV_BENCH_SOAK_SLO_FACTOR")
            factor = float(env_f) if env_f else DEFAULT_SOAK_SLO_FACTOR
            cap = float(slo) * factor
            entry = {
                "bench": "soak_latency_p99_ms",
                "current_per_op_us": float(p99),
                "baseline_per_op_us": cap,
                "ratio": round(float(p99) / cap, 3) if cap else 0.0,
            }
            checked.append(entry)
            if float(p99) > cap:
                regressions.append(entry)
        ratio_val = soak.get("goodput_ratio")
        if ratio_val is not None:
            env_g = os.environ.get("HV_BENCH_SOAK_GOODPUT")
            floor = float(env_g) if env_g else DEFAULT_SOAK_GOODPUT
            entry = {
                "bench": "soak_goodput_ratio",
                "current_per_op_us": float(ratio_val),
                "baseline_per_op_us": floor,
                "ratio": round(float(ratio_val) / floor, 3) if floor else 0.0,
            }
            checked.append(entry)
            if float(ratio_val) < floor:
                regressions.append(entry)
        for hard_zero in ("recompiles_after_warmup", "invariant_violations"):
            value = soak.get(hard_zero)
            if value is None:
                continue
            entry = {
                "bench": f"soak_{hard_zero}",
                "current_per_op_us": float(value),
                "baseline_per_op_us": 0.0,
                "ratio": float(value),
            }
            checked.append(entry)
            if value != 0:
                regressions.append(entry)
        # Latency-observatory gates (round 14): the soak row must carry
        # the per-class latency spread and the attribution block, and
        # the decomposition must sum to the measured ticket latency
        # within tolerance (a drifting sum means a component fell out
        # of the partition — broken attribution, not slow serving).
        if current["round"] >= ATTR_ROW_SINCE:
            for field in ("latency_ms_by_kind", "latency_attribution"):
                if not soak.get(field):
                    entry = {
                        "bench": f"missing:soak.{field}",
                        "current_per_op_us": 0.0,
                        "baseline_per_op_us": 0.0,
                        "ratio": 0.0,
                    }
                    checked.append(entry)
                    regressions.append(entry)
        attr = soak.get("latency_attribution")
        if attr and attr.get("max_sum_error_ms") is not None:
            env_tol = os.environ.get("HV_BENCH_ATTR_SUM_TOL_MS")
            tol = float(env_tol) if env_tol else DEFAULT_ATTR_SUM_TOL_MS
            err = float(attr["max_sum_error_ms"])
            entry = {
                "bench": "soak_attr_sum_error_ms",
                "current_per_op_us": err,
                "baseline_per_op_us": tol,
                "ratio": round(err / tol, 3) if tol else 0.0,
            }
            checked.append(entry)
            if err > tol:
                regressions.append(entry)
    # Tenant-dense gates (round 16, ISSUE 15): presence from
    # TENANT_ROW_SINCE, the tenant-count floor, the row's own stated
    # per-tenant SLO, the amortization floor (the ONE-dispatch-for-T
    # acceptance bar, devicelessly measured), and the zero-recompile
    # contract over the warmed (bucket, T) tiles.
    tenant = current.get("tenant_dense")
    if (
        current.get("format") == "suite"
        and current["round"] >= TENANT_ROW_SINCE
        and not tenant
    ):
        entry = {
            "bench": "missing:tenant_dense",
            "current_per_op_us": 0.0,
            "baseline_per_op_us": 0.0,
            "ratio": 0.0,
        }
        checked.append(entry)
        regressions.append(entry)
    if tenant:
        n_tenants = tenant.get("tenants") or 0
        env_min = os.environ.get("HV_BENCH_TENANT_MIN")
        t_floor = float(env_min) if env_min else DEFAULT_TENANT_MIN
        entry = {
            "bench": "tenant_dense_tenants",
            "current_per_op_us": float(n_tenants),
            "baseline_per_op_us": t_floor,
            "ratio": (
                round(float(n_tenants) / t_floor, 3) if t_floor else 0.0
            ),
        }
        checked.append(entry)
        if float(n_tenants) < t_floor:
            regressions.append(entry)
        p99 = tenant.get("per_tenant_p99_ms")
        slo = tenant.get("slo_p99_ms")
        if p99 is not None and slo:
            entry = {
                "bench": "tenant_dense_p99_ms",
                "current_per_op_us": float(p99),
                "baseline_per_op_us": float(slo),
                "ratio": round(float(p99) / float(slo), 3),
            }
            checked.append(entry)
            if float(p99) > float(slo):
                regressions.append(entry)
        amort = tenant.get("amortization_ratio")
        env_a = os.environ.get("HV_BENCH_TENANT_AMORT")
        a_floor = float(env_a) if env_a else DEFAULT_TENANT_AMORT_FLOOR
        entry = {
            "bench": "tenant_dense_amortization",
            "current_per_op_us": float(amort or 0.0),
            "baseline_per_op_us": a_floor,
            "ratio": (
                round(float(amort or 0.0) / a_floor, 3)
                if a_floor
                else 0.0
            ),
        }
        checked.append(entry)
        if float(amort or 0.0) < a_floor:
            regressions.append(entry)
        recomp = tenant.get("recompiles_after_warmup")
        if recomp is not None:
            entry = {
                "bench": "tenant_dense_recompiles_after_warmup",
                "current_per_op_us": float(recomp),
                "baseline_per_op_us": 0.0,
                "ratio": float(recomp),
            }
            checked.append(entry)
            if recomp != 0:
                regressions.append(entry)
    # Autopilot gates (round 17, ISSUE 17): presence from
    # AUTOPILOT_ROW_SINCE, the goodput-improvement floor vs static,
    # the row's own stated SLO, a minimum decision count, the replay
    # digest bit-identity, and the hard-zero UNPLANNED-recompile +
    # invariant-violation contract.
    pilot = current.get("autopilot_soak")
    if (
        current.get("format") == "suite"
        and current["round"] >= AUTOPILOT_ROW_SINCE
        and not pilot
    ):
        entry = {
            "bench": "missing:autopilot_soak",
            "current_per_op_us": 0.0,
            "baseline_per_op_us": 0.0,
            "ratio": 0.0,
        }
        checked.append(entry)
        regressions.append(entry)
    if pilot:
        gain = pilot.get("goodput_improvement")
        if gain is not None:
            env_g = os.environ.get("HV_BENCH_AUTOPILOT_GAIN")
            g_floor = float(env_g) if env_g else DEFAULT_AUTOPILOT_GAIN
            entry = {
                "bench": "autopilot_goodput_improvement",
                "current_per_op_us": float(gain),
                "baseline_per_op_us": g_floor,
                "ratio": (
                    round(float(gain) / g_floor, 3) if g_floor else 0.0
                ),
            }
            checked.append(entry)
            if float(gain) < g_floor:
                regressions.append(entry)
        p99 = pilot.get("p99_ms")
        slo = pilot.get("slo_p99_ms")
        if p99 is not None and slo:
            env_f = os.environ.get("HV_BENCH_AUTOPILOT_SLO_FACTOR")
            factor = (
                float(env_f) if env_f else DEFAULT_AUTOPILOT_SLO_FACTOR
            )
            cap = float(slo) * factor
            entry = {
                "bench": "autopilot_p99_ms",
                "current_per_op_us": float(p99),
                "baseline_per_op_us": cap,
                "ratio": round(float(p99) / cap, 3) if cap else 0.0,
            }
            checked.append(entry)
            if float(p99) > cap:
                regressions.append(entry)
        decisions = pilot.get("decisions")
        if decisions is not None:
            env_d = os.environ.get("HV_BENCH_AUTOPILOT_DECISIONS")
            d_floor = (
                float(env_d) if env_d else DEFAULT_AUTOPILOT_MIN_DECISIONS
            )
            entry = {
                "bench": "autopilot_decisions",
                "current_per_op_us": float(decisions),
                "baseline_per_op_us": d_floor,
                "ratio": (
                    round(float(decisions) / d_floor, 3)
                    if d_floor
                    else 0.0
                ),
            }
            checked.append(entry)
            if float(decisions) < d_floor:
                regressions.append(entry)
        # Replay determinism: digest_match is the ledger's bit-identity
        # across the row's own replays of the same trace + seed — False
        # means the decision stream depends on something outside the
        # drained snapshots (the replay contract is broken).
        match = pilot.get("digest_match")
        if match is not None:
            entry = {
                "bench": "autopilot_digest_match",
                "current_per_op_us": 1.0 if match else 0.0,
                "baseline_per_op_us": 1.0,
                "ratio": 1.0 if match else 0.0,
            }
            checked.append(entry)
            if not match:
                regressions.append(entry)
        for hard_zero in (
            "recompiles_after_warmup",
            "invariant_violations",
        ):
            value = pilot.get(hard_zero)
            if value is None:
                continue
            entry = {
                "bench": f"autopilot_{hard_zero}",
                "current_per_op_us": float(value),
                "baseline_per_op_us": 0.0,
                "ratio": float(value),
            }
            checked.append(entry)
            if value != 0:
                regressions.append(entry)
    # Fleet gates (round 18, ISSUE 18): presence from FLEET_ROW_SINCE,
    # a minimum worker count, the kill drill's detection budget, the
    # lease journal's replay digest bit-identity, series conservation
    # + full worker-label coverage on the merged drain, and the
    # hard-zero per-worker post-warmup recompile contract.
    fleet = current.get("fleet")
    if (
        current.get("format") == "suite"
        and current["round"] >= FLEET_ROW_SINCE
        and not fleet
    ):
        entry = {
            "bench": "missing:fleet",
            "current_per_op_us": 0.0,
            "baseline_per_op_us": 0.0,
            "ratio": 0.0,
        }
        checked.append(entry)
        regressions.append(entry)
    if fleet:
        workers = fleet.get("workers")
        if workers is not None:
            env_w = os.environ.get("HV_BENCH_FLEET_MIN")
            w_floor = (
                float(env_w) if env_w else DEFAULT_FLEET_MIN_WORKERS
            )
            entry = {
                "bench": "fleet_workers",
                "current_per_op_us": float(workers),
                "baseline_per_op_us": w_floor,
                "ratio": (
                    round(float(workers) / w_floor, 3) if w_floor else 0.0
                ),
            }
            checked.append(entry)
            if float(workers) < w_floor:
                regressions.append(entry)
        det = fleet.get("detection_windows") or {}
        dead = det.get("max", det.get("dead"))
        env_b = os.environ.get("HV_BENCH_FLEET_DETECT")
        budget = (
            float(env_b) if env_b else DEFAULT_FLEET_DETECT_WINDOWS
        )
        entry = {
            "bench": "fleet_detection_windows",
            # A drill that never detected the kill reports None —
            # recorded as -1 and gated as a regression outright.
            "current_per_op_us": (
                float(dead) if dead is not None else -1.0
            ),
            "baseline_per_op_us": budget,
            "ratio": (
                round(float(dead) / budget, 3)
                if dead is not None and budget
                else 0.0
            ),
        }
        checked.append(entry)
        if dead is None or float(dead) > budget:
            regressions.append(entry)
        # Replay determinism: the lease journal must replay to the
        # SAME transition digest — liveness truth is evidence for the
        # shard-out's reassignment decisions, so it must be auditable.
        match = fleet.get("digest_match")
        if match is not None:
            entry = {
                "bench": "fleet_digest_match",
                "current_per_op_us": 1.0 if match else 0.0,
                "baseline_per_op_us": 1.0,
                "ratio": 1.0 if match else 0.0,
            }
            checked.append(entry)
            if not match:
                regressions.append(entry)
        # Merged-drain conservation: merged series == Σ per-worker
        # series AND every sample row carries the worker label — a
        # dropped worker or an unstamped row breaks attribution.
        conserved = fleet.get("series_conserved")
        coverage = fleet.get("worker_label_coverage")
        if conserved is not None or coverage is not None:
            ok = bool(conserved) and coverage == 1.0
            entry = {
                "bench": "fleet_merge_conservation",
                "current_per_op_us": 1.0 if ok else 0.0,
                "baseline_per_op_us": 1.0,
                "ratio": 1.0 if ok else 0.0,
            }
            checked.append(entry)
            if not ok:
                regressions.append(entry)
        value = fleet.get("recompiles_after_warmup")
        if value is not None:
            entry = {
                "bench": "fleet_recompiles_after_warmup",
                "current_per_op_us": float(value),
                "baseline_per_op_us": 0.0,
                "ratio": float(value),
            }
            checked.append(entry)
            if value != 0:
                regressions.append(entry)
    # Incident gates (round 19, ISSUE 19): presence from
    # INCIDENT_ROW_SINCE, the clean-path overhead band, bit-identical
    # incident-id + history digests over the seeded replays (postmortem
    # evidence must be auditable), history conservation across the
    # tier folds, and the hard-zero post-warmup recompile contract
    # (the whole plane is host-side).
    inc = current.get("incident_capture")
    if (
        current.get("format") == "suite"
        and current["round"] >= INCIDENT_ROW_SINCE
        and not inc
    ):
        entry = {
            "bench": "missing:incident_capture",
            "current_per_op_us": 0.0,
            "baseline_per_op_us": 0.0,
            "ratio": 0.0,
        }
        checked.append(entry)
        regressions.append(entry)
    if inc:
        overhead = inc.get("clean_path_overhead_pct")
        if overhead is not None:
            env_o = os.environ.get("HV_BENCH_INCIDENT_OVERHEAD")
            band = float(env_o) if env_o else DEFAULT_INCIDENT_OVERHEAD_PCT
            entry = {
                "bench": "incident_clean_path_overhead_pct",
                "current_per_op_us": float(overhead),
                "baseline_per_op_us": band,
                "ratio": round(float(overhead) / band, 3) if band else 0.0,
            }
            checked.append(entry)
            if float(overhead) > band:
                regressions.append(entry)
        match = inc.get("digest_match")
        if match is not None:
            ok = bool(match) and bool(inc.get("replay_check_ok", True))
            entry = {
                "bench": "incident_digest_match",
                "current_per_op_us": 1.0 if ok else 0.0,
                "baseline_per_op_us": 1.0,
                "ratio": 1.0 if ok else 0.0,
            }
            checked.append(entry)
            if not ok:
                regressions.append(entry)
        conserved = (inc.get("history") or {}).get("conservation")
        if conserved is not None:
            entry = {
                "bench": "incident_history_conservation",
                "current_per_op_us": 1.0 if conserved else 0.0,
                "baseline_per_op_us": 1.0,
                "ratio": 1.0 if conserved else 0.0,
            }
            checked.append(entry)
            if not conserved:
                regressions.append(entry)
        value = inc.get("recompiles_after_warmup")
        if value is not None:
            entry = {
                "bench": "incident_recompiles_after_warmup",
                "current_per_op_us": float(value),
                "baseline_per_op_us": 0.0,
                "ratio": float(value),
            }
            checked.append(entry)
            if value != 0:
                regressions.append(entry)
    # Failover gates (round 20, ISSUE 19): presence from
    # FAILOVER_ROW_SINCE, the kill drill's detection budget, the
    # ownership journal's replay digest bit-identity, the hard-zero
    # fenced-zombie double-apply contract (an unfenced zombie
    # re-committing WAL records is silent state divergence), and the
    # hard-zero absorb-recompile contract (the splice never changes a
    # `[T, …]` shape).
    fo = current.get("failover")
    if (
        current.get("format") == "suite"
        and current["round"] >= FAILOVER_ROW_SINCE
        and not fo
    ):
        entry = {
            "bench": "missing:failover",
            "current_per_op_us": 0.0,
            "baseline_per_op_us": 0.0,
            "ratio": 0.0,
        }
        checked.append(entry)
        regressions.append(entry)
    if fo:
        det = fo.get("detection_windows")
        env_b = os.environ.get("HV_BENCH_FAILOVER_DETECT")
        budget = (
            float(env_b) if env_b else DEFAULT_FAILOVER_DETECT_WINDOWS
        )
        entry = {
            "bench": "failover_detection_windows",
            # A drill that never convicted the kill reports None —
            # recorded as -1 and gated as a regression outright.
            "current_per_op_us": (
                float(det) if det is not None else -1.0
            ),
            "baseline_per_op_us": budget,
            "ratio": (
                round(float(det) / budget, 3)
                if det is not None and budget
                else 0.0
            ),
        }
        checked.append(entry)
        if det is None or float(det) > budget:
            regressions.append(entry)
        # Replay determinism: two full drills (traffic, conviction,
        # spread, recovery, journal) must land the SAME ownership
        # transition digest — reassignment is an auditable decision.
        match = fo.get("digest_match")
        if match is not None:
            entry = {
                "bench": "failover_digest_match",
                "current_per_op_us": 1.0 if match else 0.0,
                "baseline_per_op_us": 1.0,
                "ratio": 1.0 if match else 0.0,
            }
            checked.append(entry)
            if not match:
                regressions.append(entry)
        # The zombie MUST be fenced and MUST NOT double-apply: the
        # on-disk committed-record count across its refused resume
        # append is a hard zero delta.
        fenced = fo.get("zombie_fenced")
        doubles = fo.get("double_applied_ops")
        if fenced is not None or doubles is not None:
            ok = bool(fenced) and (doubles == 0)
            entry = {
                "bench": "failover_zombie_fenced_zero_double_applies",
                "current_per_op_us": (
                    float(doubles) if doubles is not None else -1.0
                ),
                "baseline_per_op_us": 0.0,
                "ratio": 0.0 if ok else 1.0,
            }
            checked.append(entry)
            if not ok:
                regressions.append(entry)
        value = fo.get("recompiles_after_splice")
        if value is not None:
            entry = {
                "bench": "failover_recompiles_after_splice",
                "current_per_op_us": float(value),
                "baseline_per_op_us": 0.0,
                "ratio": float(value),
            }
            checked.append(entry)
            if value != 0:
                regressions.append(entry)
    # Fleet-soak gates (round 21, ISSUE 20): presence from
    # FLEET_SOAK_ROW_SINCE, the >=10x session floor, ownership-digest
    # replay bit-identity over two full soaks, the hard-zero contracts
    # (fenced zombies never double-apply, exactly-one ownership holds
    # at every round boundary, the splice path never recompiles a
    # serving shape), and p99 round wall within the smoke SLO.
    fs = current.get("fleet_soak")
    if (
        current.get("format") == "suite"
        and current["round"] >= FLEET_SOAK_ROW_SINCE
        and not fs
    ):
        entry = {
            "bench": "missing:fleet_soak",
            "current_per_op_us": 0.0,
            "baseline_per_op_us": 0.0,
            "ratio": 0.0,
        }
        checked.append(entry)
        regressions.append(entry)
    if fs:
        sessions = fs.get("sessions")
        env_s = os.environ.get("HV_BENCH_FLEET_SOAK_SESSIONS")
        floor = float(env_s) if env_s else DEFAULT_FLEET_SOAK_SESSIONS
        entry = {
            "bench": "fleet_soak_sessions_floor",
            "current_per_op_us": (
                float(sessions) if sessions is not None else -1.0
            ),
            "baseline_per_op_us": floor,
            "ratio": (
                round(float(sessions) / floor, 3)
                if sessions is not None and floor
                else 0.0
            ),
        }
        checked.append(entry)
        if sessions is None or float(sessions) < floor:
            regressions.append(entry)
        match = fs.get("digest_match")
        if match is not None:
            entry = {
                "bench": "fleet_soak_digest_match",
                "current_per_op_us": 1.0 if match else 0.0,
                "baseline_per_op_us": 1.0,
                "ratio": 1.0 if match else 0.0,
            }
            checked.append(entry)
            if not match:
                regressions.append(entry)
        # Every kill's zombie MUST be fenced and MUST NOT double-apply.
        fenced = fs.get("zombies_fenced")
        doubles = fs.get("double_applied_ops")
        if fenced is not None or doubles is not None:
            ok = (
                fenced is not None
                and doubles == 0
                and int(fenced) == int(fs.get("failovers") or 0)
                and int(fenced) > 0
            )
            entry = {
                "bench": "fleet_soak_zombies_fenced_zero_double_applies",
                "current_per_op_us": (
                    float(doubles) if doubles is not None else -1.0
                ),
                "baseline_per_op_us": 0.0,
                "ratio": 0.0 if ok else 1.0,
            }
            checked.append(entry)
            if not ok:
                regressions.append(entry)
        for key, bench in (
            ("ownership_violations", "fleet_soak_ownership_violations"),
            (
                "recompiles_after_splice",
                "fleet_soak_recompiles_after_splice",
            ),
        ):
            value = fs.get(key)
            if value is not None:
                entry = {
                    "bench": bench,
                    "current_per_op_us": float(value),
                    "baseline_per_op_us": 0.0,
                    "ratio": float(value),
                }
                checked.append(entry)
                if value != 0:
                    regressions.append(entry)
        rw = fs.get("round_wall_ms") or {}
        p99 = rw.get("p99")
        slo = fs.get("slo_p99_ms")
        if p99 is not None and slo:
            entry = {
                "bench": "fleet_soak_round_wall_p99",
                "current_per_op_us": float(p99),
                "baseline_per_op_us": float(slo),
                "ratio": round(float(p99) / float(slo), 3),
            }
            checked.append(entry)
            if float(p99) > float(slo):
                regressions.append(entry)
    # Static-analysis gates (round 13): presence from STATIC_ROW_SINCE,
    # then zero unsuppressed findings — hvlint findings shipping in a
    # bench round mean a contract violation crossed CI.
    static = current.get("static_analysis")
    if (
        current.get("format") == "suite"
        and current["round"] >= STATIC_ROW_SINCE
        and not static
    ):
        entry = {
            "bench": "missing:static_analysis",
            "current_per_op_us": 0.0,
            "baseline_per_op_us": 0.0,
            "ratio": 0.0,
        }
        checked.append(entry)
        regressions.append(entry)
    if static and static.get("findings") is not None:
        entry = {
            "bench": "static_analysis_findings",
            "current_per_op_us": float(static["findings"]),
            "baseline_per_op_us": 0.0,
            "ratio": float(static["findings"]),
        }
        checked.append(entry)
        if static["findings"] != 0:
            regressions.append(entry)
    # Roofline gates (round 15, ISSUE 14): presence from
    # ROOFLINE_ROW_SINCE, then each program's MODELED HBM bytes held
    # to a band around the median of comparable prior rounds — the
    # model is deterministic per shape, so an accidental de-fusion or
    # donation miss that inflates traffic fails on the model alone,
    # on cpu, with no chip attached.
    roofline = current.get("roofline")
    if (
        current.get("format") == "suite"
        and current["round"] >= ROOFLINE_ROW_SINCE
        and not roofline
    ):
        entry = {
            "bench": "missing:roofline",
            "current_per_op_us": 0.0,
            "baseline_per_op_us": 0.0,
            "ratio": 0.0,
        }
        checked.append(entry)
        regressions.append(entry)
    if roofline and roofline.get("programs"):
        env_tol = os.environ.get("HV_BENCH_ROOFLINE_BYTES_TOL")
        rtol = float(env_tol) if env_tol else DEFAULT_ROOFLINE_BYTES_TOL
        # Per-program medians over comparable priors that carried the
        # row (same backend/quick/format, like every other band here).
        prior_bytes: dict[str, list[float]] = {}
        for r in rows:
            if (
                r["round"] >= current["round"]
                or _comparable_key(r) != _comparable_key(current)
                or not r.get("roofline")
            ):
                continue
            for name, p in (r["roofline"].get("programs") or {}).items():
                value = p.get("modeled_bytes")
                if value:
                    prior_bytes.setdefault(name, []).append(float(value))
        for name, p in sorted(roofline["programs"].items()):
            value = p.get("modeled_bytes")
            priors = prior_bytes.get(name)
            if not value or not priors:
                continue
            base = statistics.median(priors)
            entry = {
                "bench": f"roofline_bytes:{name}",
                "current_per_op_us": float(value),
                "baseline_per_op_us": base,
                "ratio": round(float(value) / base, 3) if base else 0.0,
            }
            checked.append(entry)
            if base and abs(float(value) / base - 1.0) > rtol:
                regressions.append(entry)
    if scenarios and scenarios.get("hardening_overhead_pct") is not None:
        env_cap = os.environ.get("HV_BENCH_HARDENING_OVERHEAD")
        cap = (
            float(env_cap)
            if env_cap
            else DEFAULT_HARDENING_OVERHEAD.get(current["backend"], 50.0)
        )
        overhead = float(scenarios["hardening_overhead_pct"])
        entry = {
            "bench": "scenario_hardening_overhead",
            "current_per_op_us": overhead,
            "baseline_per_op_us": cap,
            "ratio": round(overhead / cap, 3) if cap else 0.0,
        }
        checked.append(entry)
        if overhead > cap:
            regressions.append(entry)
    return {
        "ok": not regressions,
        "round": current["round"],
        "file": current["file"],
        "backend": current["backend"],
        "quick": current["quick"],
        "tolerance": tolerance,
        "baseline_rounds": n_priors,
        "checked": checked,
        "regressions": regressions,
        "improvements": improvements,
        "skipped": sorted(set(current["benches"]) - set(baseline)),
    }


def next_round_path(root: Path = REPO_ROOT) -> Path:
    """The next BENCH_r<NN>.json slot (bench_suite `--metrics-out auto`)."""
    taken = [
        int(m.group(1))
        for p in root.glob("BENCH_r*.json")
        if (m := _ROUND_RE.search(p.name))
    ]
    return root / f"BENCH_r{(max(taken, default=0) + 1):02d}.json"


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument(
        "--root", type=Path, default=REPO_ROOT,
        help="directory holding BENCH_r*.json (default: repo root)",
    )
    ap.add_argument(
        "--check", type=Path, default=None,
        help="gate this report instead of the newest committed round",
    )
    ap.add_argument(
        "--tolerance", type=float, default=None,
        help="allowed fraction above baseline (default per backend: "
        f"{DEFAULT_TOLERANCE}; env HV_BENCH_TOL overrides)",
    )
    ap.add_argument(
        "--trajectory-out", type=Path, default=None,
        help="trajectory path (default <root>/BENCH_trajectory.json)",
    )
    ap.add_argument(
        "--no-write", action="store_true",
        help="do not (re)write the trajectory file",
    )
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    rows = load_history(args.root)
    if not args.no_write:
        path = write_trajectory(rows, args.trajectory_out, args.root)
        if not args.quiet:
            print(f"trajectory: {len(rows)} round(s) -> {path}")

    if args.check is not None:
        current = parse_round_file(args.check)
        if current is None:
            print(f"unparseable report: {args.check}", file=sys.stderr)
            return 2
    elif rows:
        current = rows[-1]
    else:
        if not args.quiet:
            print("no bench history — nothing to gate")
        return 0

    report = compare(current, rows, args.tolerance)
    if not args.quiet:
        print(
            f"gate round r{report['round']:02d} ({report['backend']}"
            f"{', quick' if report['quick'] else ''}) vs median of "
            f"{report['baseline_rounds']} comparable prior round(s), "
            f"tolerance +{report['tolerance'] * 100:.0f}%"
        )
        for entry in report["checked"]:
            flag = (
                "REGRESSION"
                if entry in report["regressions"]
                else "improved"
                if entry in report["improvements"]
                else "ok"
            )
            print(
                f"  {entry['bench']:36s} {entry['current_per_op_us']:>12.4f} "
                f"vs {entry['baseline_per_op_us']:>12.4f} µs/op "
                f"(x{entry['ratio']:.2f}) {flag}"
            )
        if not report["checked"]:
            print(
                "  no comparable baseline (first round of its "
                "format/backend/quick group) — gate passes vacuously"
            )
    if not report["ok"]:
        print(
            f"PERF REGRESSION: {len(report['regressions'])} bench(es) "
            f"above tolerance in {report['file']}",
            file=sys.stderr,
        )
        return 1
    if not args.quiet:
        print("perf-regression gate PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
