"""Deviceless dispatch census of the governance-wave executables.

The round-5 discovery that powers ROOFLINE.md's TPU-true numbers:
`jax.experimental.topologies.get_topology_desc("tpu", "v5e:2x4")`
builds a PJRT topology for the BASELINE target with no device attached
— compiling against it runs the real XLA:TPU + Mosaic compiler. Round 9
made the tool **tunnel-wedge-proof**: the TPU plugin probe is
subprocess-bounded (`HV_AOT_PROBE_TIMEOUT`, the same guard as
tests/parity/test_mosaic_aot.py — the wedged accelerator tunnel can
hang `get_topology_desc` forever), and when the plugin is absent or
wedged the census falls back to the hermetic CPU backend, whose
ENTRY-step structure gates the same fusion/donation regressions with no
chip attached.

What it measures (the round-9 mega-fusion metric, extended in round 12
for the whole-wave Mosaic megakernels):

  * the FUSED bench-shaped wave — governance + gateway + audit append +
    gauge/sanitizer epilogue as ONE program (`ops.pipeline.
    governance_wave` with every round-9 plane riding), donated and not,
  * the MEGAKERNEL wave — the same program with `HV_WAVE_PALLAS` armed
    (`wave_kernels=True`): the serialized phase chains collapse into
    the wave-block boundaries (`ops.wave_blocks` — Mosaic launches on
    chip, the numpy twins out-of-line on this hermetic backend; either
    way ONE custom call per block). This is the round-12 headline:
    `fusion_ratio` gates IT from round 12 on,
  * the UNFUSED equivalents — the five standalone programs a pre-r10
    runtime dispatched per wave step (wave, DeltaLog append, gateway,
    update_gauges, check_invariants),
  * per-PHASE attribution — every dispatch-bearing step bucketed by
    the `hv_phase.*` named scope its fusion root carries (admission /
    fsm_saga / audit / gateway / epilogue; un-scoped steps are glue),
    so the census shows WHERE the megakernels cut,
  * `fusion_ratio` — r09-anchored dispatch-step cut (see R09_BASELINE);
    `wave_cut_ratio` — the r10 fused anchor vs the megakernel wave,
  * live HBM buffer sizes where the backend exposes them.

Dispatch-bearing ENTRY steps = fusions + custom calls + array copies +
dynamic-update-slices + sorts + reduce-windows + gathers + scatters.
Rank-0 (scalar) copies are prologue plumbing on every backend and are
excluded. Round-12 metric note: tuple-result custom calls (the
megakernel block boundaries lower to exactly these) are counted —
other tuple-result instructions keep the historical (single-result)
parse so the committed r09/r10 anchors stay comparable.

CLI::

    python benchmarks/tpu_aot_census.py                # auto: tpu -> cpu
    python benchmarks/tpu_aot_census.py --json         # machine-readable
    python benchmarks/tpu_aot_census.py --backend cpu  # hermetic, always works

Exit codes: 0 = census ran; **75** (EX_TEMPFAIL) = TPU plugin absent or
wedged AND --backend tpu was explicitly requested — callers
(scripts/verify_tier1.sh, CI) treat that as "skip", distinct from 1 =
census failed/regressed.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
from collections import Counter
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

#: Bench shape (BASELINE 10k wave) + gateway lane block.
S, T, N, SC, E, A = 10_000, 3, 16_384, 16_384, 65_536, 1_024
TOPOLOGY = "v5e:2x4"

EXIT_OK = 0
EXIT_TPU_UNAVAILABLE = 75  # EX_TEMPFAIL: plugin absent/wedged, not a failure

# The compiled-program scan (ENTRY-step iteration, dispatch-bearing
# kinds, phase attribution) and the cost/memory-analysis extraction
# moved to `hypervisor_tpu.observability.roofline` in round 15: the
# live observatory and this offline census MUST count with one rule
# set or their numbers drift. Re-exported here so the committed
# anchors, tests, and downstream tooling keep their import paths.
from hypervisor_tpu.observability.roofline import (  # noqa: E402
    DISPATCH_OPS,
    _computation_phases,
    _entry_body,
    _iter_entry_steps,
    compiled_cost,
    entry_census,
    phase_census,
)

#: r09-HEAD anchor (commit 4e1ca24, measured on this census's refined
#: metric): the five programs one fully-loaded bench wave step
#: dispatched before the round-9 mega-fusion, on the hermetic CPU
#: backend — governance_wave (metrics+trace, no donation: the r09
#: default) 101, DeltaLog.append_batch 5, gateway (metrics+trace) 96,
#: update_gauges 59, check_invariants 61. `fusion_ratio` in the report
#: is r09_total / fused_dispatch. The v5e anchor is the wave alone
#: (DONATION.md: 244 ENTRY instructions); the remaining v5e plane
#: programs await an unwedged tunnel, so no tpu total is anchored yet.
R09_BASELINE = {
    "cpu": {"dispatch_total": 322, "entry_total": 573, "programs": 5},
    "tpu": None,
}

#: r10-HEAD anchor (commit 194ea9b): the ONE fused donated+sanitized
#: program's dispatch-bearing step count on the hermetic CPU census —
#: the number the round-12 megakernels must cut >=4x (ISSUE 11
#: acceptance: 148 -> <=37).
R10_FUSED_BASELINE = {"cpu": 148, "tpu": None}

#: Wave phases the megakernels carve the program into (`hv_phase.*`
#: named scopes in ops/pipeline.py); un-scoped steps bucket as "glue".
WAVE_PHASES = ("admission", "fsm_saga", "audit", "gateway", "epilogue")


def _probe_timeout() -> float:
    try:
        return float(os.environ.get("HV_AOT_PROBE_TIMEOUT", "45"))
    except ValueError:
        return 45.0


def probe_tpu_topology() -> bool:
    """Subprocess-bounded check that the TPU PJRT plugin can build the
    deviceless topology — the wedged accelerator tunnel can HANG
    `get_topology_desc` inside initialize_pjrt_plugin (observed live;
    same guard as tests/parity/test_mosaic_aot.py)."""
    code = (
        "from jax.experimental import topologies;"
        f"topologies.get_topology_desc(platform='tpu', topology_name={TOPOLOGY!r})"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            timeout=_probe_timeout(),
        )
    except subprocess.TimeoutExpired:
        return False
    except OSError:
        return False
    return proc.returncode == 0


#: The attribution shape: XLA:CPU's parallel-task rewrite rebuilds the
#: bench-shape program's fused computations WITHOUT their op metadata
#: (measured: zero `hv_phase` tags survive in the 10k module), so the
#: REFERENCE program's per-phase breakdown is measured on this smaller
#: twin of the same program, where the metadata survives. Step TOTALS
#: always come from the bench shape.
ATTR_SHAPE = {"S": 256, "T": 3, "N": 1_024, "SC": 1_024, "E": 2_048, "A": 64}


def _shapes(jax, jnp, merkle_ops, mp, tables_state, logs_mod, shape=None):
    """ShapeDtypeStructs for every program the census compiles."""
    d = shape or {"S": S, "T": T, "N": N, "SC": SC, "E": E, "A": A}
    s_, t_, n_, sc_, e_, a_ = (
        d["S"], d["T"], d["N"], d["SC"], d["E"], d["A"]
    )

    def sds(tree):
        return jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree
        )

    return {
        "agents": sds(tables_state.AgentTable.create(n_)),
        "sessions": sds(tables_state.SessionTable.create(sc_)),
        "vouches": sds(tables_state.VouchTable.create(e_)),
        "sagas": sds(tables_state.SagaTable.create(1024, 8)),
        "elevations": sds(tables_state.ElevationTable.create(4096)),
        "delta_log": sds(logs_mod.DeltaLog.create(65536)),
        "event_log": sds(logs_mod.EventLog.create(65536)),
        "trace_log": sds(logs_mod.TraceLog.create(65536)),
        "metrics": sds(mp.REGISTRY.create_table()),
        "li": jax.ShapeDtypeStruct((s_,), jnp.int32),
        "lb": jax.ShapeDtypeStruct((s_,), jnp.bool_),
        "lf": jax.ShapeDtypeStruct((s_,), jnp.float32),
        "li8": jax.ShapeDtypeStruct((s_,), jnp.int8),
        "sf": jax.ShapeDtypeStruct((), jnp.float32),
        "si": jax.ShapeDtypeStruct((), jnp.int32),
        "su": jax.ShapeDtypeStruct((), jnp.uint32),
        "sb": jax.ShapeDtypeStruct((), jnp.bool_),
        "bodies": jax.ShapeDtypeStruct(
            (t_, s_, merkle_ops.BODY_WORDS), jnp.uint32
        ),
        "rb": jax.ShapeDtypeStruct((4,), jnp.float32),
        "ai": jax.ShapeDtypeStruct((a_,), jnp.int32),
        "ai8": jax.ShapeDtypeStruct((a_,), jnp.int8),
        "ab": jax.ShapeDtypeStruct((a_,), jnp.bool_),
    }


def census_report(backend: str, sharding=None) -> dict:
    """Compile every program and assemble the machine-readable report.

    `backend` is "tpu" (deviceless v5e AOT or a live chip) or "cpu".
    `sharding` pins in/out shardings for the deviceless-AOT path.
    """
    from functools import partial

    import jax
    import jax.numpy as jnp

    from hypervisor_tpu.config import DEFAULT_CONFIG
    from hypervisor_tpu.integrity import invariants as inv
    from hypervisor_tpu.observability import metrics as mp
    from hypervisor_tpu.observability import tracing
    from hypervisor_tpu.ops import gateway as gateway_ops
    from hypervisor_tpu.ops import merkle as merkle_ops
    from hypervisor_tpu.ops.pipeline import governance_wave
    from hypervisor_tpu.tables import logs as logs_mod
    from hypervisor_tpu.tables import state as tables_state

    use_pallas = backend == "tpu"
    sh = _shapes(jax, jnp, merkle_ops, mp, tables_state, logs_mod)
    jit_kw = {}
    if sharding is not None:
        jit_kw = {"in_shardings": sharding, "out_shardings": sharding}

    wave_args = (
        sh["agents"], sh["sessions"], sh["vouches"],
        sh["li"], sh["li"], sh["li"], sh["lf"], sh["lb"], sh["lb"],
        sh["li"], sh["bodies"], sh["sf"], sh["sf"],
    )
    ctx_args = (sh["su"], sh["su"], sh["si"], sh["sb"])
    gw_cols = (sh["ai"], sh["ai8"], sh["ab"], sh["ab"], sh["ab"],
               sh["ab"], sh["ab"])

    def fused_fn(sanitize, wave_kernels=False):
        def fn(*a):
            (*w, lo, hi, m, tr, ct, cs, cw, cb, elev,
             g0, g1, g2, g3, g4, g5, g6, d, sg, ev, bursts) = a
            return governance_wave(
                *w, use_pallas=use_pallas, unique_sessions=True,
                wave_range=(lo, hi), ring_bursts=bursts, metrics=m,
                trace=tr,
                trace_ctx=tracing.TraceContext(
                    trace=ct, span=cs, wave_seq=cw, sampled=cb
                ),
                elevations=elev,
                gateway_args=(g0, g1, g2, g3, g4, g5, g6),
                delta_log=d, epilogue_tables=(sg, ev), sanitize=sanitize,
                wave_kernels=wave_kernels,
            )

        return fn

    def _fused_args_of(shd):
        wa = (
            shd["agents"], shd["sessions"], shd["vouches"],
            shd["li"], shd["li"], shd["li"], shd["lf"], shd["lb"],
            shd["lb"], shd["li"], shd["bodies"], shd["sf"], shd["sf"],
        )
        gw = (shd["ai"], shd["ai8"], shd["ab"], shd["ab"], shd["ab"],
              shd["ab"], shd["ab"])
        return (
            wa + (shd["si"], shd["si"], shd["metrics"], shd["trace_log"])
            + (shd["su"], shd["su"], shd["si"], shd["sb"])
            + (shd["elevations"],) + gw
            + (shd["delta_log"], shd["sagas"], shd["event_log"], shd["rb"])
        )

    fused_args = _fused_args_of(sh)
    # Donation frontier: agents(0) sessions(1) vouches(2) metrics(15)
    # trace(16) delta_log(29) — positions in fused_args, mirroring
    # `state._WAVE_DONATED`. No cache salt here: this process never
    # configures a persistent compilation cache and never EXECUTES the
    # programs (compile + census only), so the donated-reload hazard
    # the salt defends against (see state._DONATION_CACHE_SALT) cannot
    # bite.
    donate = (0, 1, 2, 15, 16, 29)

    programs: dict[str, dict] = {}
    hbm = None

    def compile_and_census(name, fn, args, donate_argnums=(), phases=False):
        compiled = (
            jax.jit(fn, donate_argnums=donate_argnums, **jit_kw)
            .lower(*args)
            .compile()
        )
        total, heavy, top = entry_census(compiled)
        programs[name] = {"entry": total, "dispatch": heavy, "top": top}
        if phases:
            programs[name]["phases"] = phase_census(compiled)
        return compiled

    compiled_fused = compile_and_census(
        "fused_wave_sanitized", fused_fn(True), fused_args, donate,
        phases=True,
    )
    compile_and_census("fused_wave", fused_fn(False), fused_args, donate)
    compile_and_census(
        "fused_wave_sanitized_nodonate", fused_fn(True), fused_args
    )
    # ── the round-12 megakernel wave: the SAME program with the wave
    # blocks armed (`wave_kernels=True`). On this hermetic backend each
    # block is one out-of-line twin custom call; on chip each named
    # block is a Mosaic launch — either way the census counts the block
    # boundaries, which is the dispatch structure the chip serializes.
    compile_and_census(
        "fused_wave_megakernel", fused_fn(True, wave_kernels=True),
        fused_args, donate, phases=True,
    )
    compile_and_census(
        "fused_wave_megakernel_nodonate",
        fused_fn(True, wave_kernels=True), fused_args,
    )
    if backend == "cpu":
        # The reference program's per-phase breakdown, measured at the
        # attribution shape (ATTR_SHAPE) where the parallel-task
        # rewrite hasn't stripped the `hv_phase` metadata: the phase
        # STRUCTURE is shape-invariant, so this is where the
        # megakernels' cut is shown — totals stay bench-shaped.
        sh_attr = _shapes(
            jax, jnp, merkle_ops, mp, tables_state, logs_mod, ATTR_SHAPE
        )
        attr_compiled = (
            jax.jit(fused_fn(True))
            .lower(*_fused_args_of(sh_attr))
            .compile()
        )
        programs["fused_wave_sanitized"]["phases"] = phase_census(
            attr_compiled
        )
        programs["fused_wave_sanitized"]["phases_shape"] = ATTR_SHAPE
    # ONE extraction rule with the live observatory
    # (`roofline.compiled_cost`): the census's HBM block and the
    # runtime registry must read the same analysis the same way.
    cost = compiled_cost(compiled_fused)
    hbm = None
    if cost is not None and cost.get("temp_bytes") is not None:
        hbm = {
            "temp_mb": round(cost["temp_bytes"] / 1e6, 2),
            "args_mb": round(cost["argument_bytes"] / 1e6, 2),
            "out_mb": round(cost["output_bytes"] / 1e6, 2),
        }
    if cost is not None:
        programs["fused_wave_sanitized"]["cost"] = cost

    # ── the unfused equivalents (what a de-fused runtime re-pays) ────
    def wave_plain(*a):
        *w, lo, hi, m, tr, ct, cs, cw, cb, bursts = a
        return governance_wave(
            *w, use_pallas=use_pallas, unique_sessions=True,
            wave_range=(lo, hi), ring_bursts=bursts, metrics=m, trace=tr,
            trace_ctx=tracing.TraceContext(
                trace=ct, span=cs, wave_seq=cw, sampled=cb
            ),
        )

    compile_and_census(
        "unfused:governance_wave", wave_plain,
        wave_args + (sh["si"], sh["si"], sh["metrics"], sh["trace_log"])
        + ctx_args + (sh["rb"],),
    )
    compile_and_census(
        "unfused:delta_append",
        lambda d, b_, dg, s_, t_: d.append_batch(b_, dg, s_, t_),
        (
            sh["delta_log"],
            jax.ShapeDtypeStruct((S * T, merkle_ops.BODY_WORDS), jnp.uint32),
            jax.ShapeDtypeStruct((S * T, 8), jnp.uint32),
            jax.ShapeDtypeStruct((S * T,), jnp.int32),
            jax.ShapeDtypeStruct((S * T,), jnp.int32),
        ),
    )

    def gw_fn(a, e, s_, r_, ro, co, wi, ht, now, valid, m, tr, ct, cs,
              cw, cb):
        return gateway_ops.check_actions(
            a, e, s_, r_, ro, co, wi, ht, now, valid=valid,
            breach=DEFAULT_CONFIG.breach,
            rate_limit=DEFAULT_CONFIG.rate_limit,
            trust=DEFAULT_CONFIG.trust, metrics=m, trace=tr,
            trace_ctx=tracing.TraceContext(
                trace=ct, span=cs, wave_seq=cw, sampled=cb
            ),
        )

    compile_and_census(
        "unfused:gateway", gw_fn,
        (sh["agents"], sh["elevations"], *gw_cols[:6], sh["sf"],
         gw_cols[6], sh["metrics"], sh["trace_log"], *ctx_args),
    )
    compile_and_census(
        "unfused:update_gauges", mp.update_gauges,
        (sh["metrics"], sh["agents"], sh["sessions"], sh["vouches"],
         sh["sagas"], sh["elevations"], sh["delta_log"], sh["event_log"],
         sh["trace_log"]),
    )
    compile_and_census(
        "unfused:check_invariants",
        partial(inv.check_invariants, config=DEFAULT_CONFIG),
        (sh["agents"], sh["sessions"], sh["vouches"], sh["sagas"],
         sh["elevations"], sh["delta_log"], sh["event_log"],
         sh["trace_log"], sh["rb"], sh["metrics"]),
    )

    unfused = [v for k, v in programs.items() if k.startswith("unfused:")]
    unfused_total = {
        "entry": sum(p["entry"] for p in unfused),
        "dispatch": sum(p["dispatch"] for p in unfused),
        "programs": len(unfused),
    }
    fused = programs["fused_wave_sanitized"]
    mk = programs["fused_wave_megakernel"]
    anchor = R09_BASELINE.get(backend)
    r10 = R10_FUSED_BASELINE.get(backend)
    report = {
        "source": "benchmarks/tpu_aot_census.py",
        "backend": backend,
        "topology": TOPOLOGY if sharding is not None else None,
        "shape": {"S": S, "T": T, "N": N, "SC": SC, "E": E, "A": A},
        "metric": (
            "ENTRY instructions; dispatch = fusion+custom-call+array-copy"
            "+dus+sort+reduce-window+gather+scatter (rank-0 copies"
            " excluded; tuple-result custom calls counted since r12)"
        ),
        "programs": programs,
        "unfused_total": unfused_total,
        # Self-contained de-fusion guard: the five standalone programs
        # AT THIS COMMIT vs the one fused program.
        "self_fusion_ratio": round(
            unfused_total["dispatch"] / max(fused["dispatch"], 1), 3
        ),
        # The acceptance headline since round 12: the r09-HEAD
        # five-program total (anchored constant, see R09_BASELINE) vs
        # today's MEGAKERNEL wave — the program a production chip
        # dispatches with HV_WAVE_PALLAS auto-armed.
        "r09_baseline": anchor,
        "fusion_ratio": (
            round(anchor["dispatch_total"] / max(mk["dispatch"], 1), 3)
            if anchor
            else None
        ),
        # Continuity key: the same ratio for the UNARMED fused wave
        # (the r10/r11 headline) so the trajectory stays readable.
        "fusion_ratio_reference": (
            round(anchor["dispatch_total"] / max(fused["dispatch"], 1), 3)
            if anchor
            else None
        ),
        # ISSUE 11 acceptance: the r10 fused anchor vs the megakernel
        # wave — the >=4x whole-wave step cut.
        "r10_baseline": r10,
        "wave_cut_ratio": (
            round(r10 / max(mk["dispatch"], 1), 3) if r10 else None
        ),
        # How the armed blocks execute on THIS backend (the fallback
        # matrix): out-of-line numpy twins on the hermetic CPU census,
        # Mosaic launches + inline gateway/epilogue on chip.
        "wave_kernels_boundary": (
            "twin" if backend == "cpu" else "mosaic+inline"
        ),
        "donation_delta_steps": (
            programs["fused_wave_sanitized_nodonate"]["dispatch"]
            - fused["dispatch"]
        ),
        "megakernel_donation_delta_steps": (
            programs["fused_wave_megakernel_nodonate"]["dispatch"]
            - mk["dispatch"]
        ),
        "hbm": hbm,
    }
    return report


def _print_text(report: dict) -> None:
    print(
        f"backend: {report['backend']}"
        + (f" ({report['topology']})" if report["topology"] else "")
    )
    for name, p in report["programs"].items():
        print(
            f"{name:32s} entry={p['entry']:4d} dispatch={p['dispatch']:4d}"
            f"  {p['top']}"
        )
    ut = report["unfused_total"]
    print(
        f"{'UNFUSED total':32s} entry={ut['entry']:4d} "
        f"dispatch={ut['dispatch']:4d}  ({ut['programs']} programs)"
    )
    print(
        f"fusion ratio vs r09: {report['fusion_ratio']} (megakernel; "
        f"reference {report['fusion_ratio_reference']}, self: "
        f"{report['self_fusion_ratio']}x, donation saves "
        f"{report['donation_delta_steps']} steps)"
    )
    mk = report["programs"]["fused_wave_megakernel"]
    print(
        f"megakernel wave: {mk['dispatch']} dispatch steps vs r10's "
        f"{report['r10_baseline']} (cut {report['wave_cut_ratio']}x, "
        f"blocks as {report['wave_kernels_boundary']}); phases: "
        f"{mk.get('phases')}"
    )
    if report["hbm"]:
        print(f"HBM MB (fused): {report['hbm']}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument(
        "--backend", choices=("auto", "tpu", "cpu"), default="auto",
        help="tpu = deviceless v5e AOT (needs the PJRT plugin; probe is "
        "subprocess-bounded); cpu = hermetic XLA:CPU census; auto = tpu "
        "with cpu fallback",
    )
    ap.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    ap.add_argument(
        "--out", type=Path, default=None, help="also write the JSON here"
    )
    args = ap.parse_args(argv)

    from _jax_platform import force_cpu_platform

    backend = args.backend
    sharding = None
    if backend in ("auto", "tpu"):
        if probe_tpu_topology():
            import jax

            from jax.experimental import topologies
            from jax.sharding import SingleDeviceSharding

            td = topologies.get_topology_desc(
                platform="tpu", topology_name=TOPOLOGY
            )
            sharding = SingleDeviceSharding(td.devices[0])
            jax.config.update("jax_compilation_cache_dir", None)
            backend = "tpu"
        elif args.backend == "tpu":
            print(
                "TPU PJRT topology unavailable (plugin absent or tunnel "
                f"wedged past {_probe_timeout():.0f}s) — nothing to "
                "census. Exit 75 = skip, not failure."
            )
            return EXIT_TPU_UNAVAILABLE
        else:
            backend = "cpu"
    if backend == "cpu":
        force_cpu_platform(8)

    report = census_report(backend, sharding)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        _print_text(report)
    if args.out is not None:
        args.out.write_text(json.dumps(report, indent=2) + "\n")
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
