"""Deviceless-AOT census of the real v5e executables (no chip needed).

The round-5 discovery that powers ROOFLINE.md's TPU-true numbers:
`jax.experimental.topologies.get_topology_desc("tpu", "v5e:2x4")`
builds a PJRT topology for the BASELINE target with no device attached
— even while the accelerator tunnel is wedged — and compiling against
it runs the real XLA:TPU + Mosaic compiler. This script extracts, from
the actual v5e executables:

  * the bench-shaped 10k wave's ENTRY instruction census (the dispatch
    structure that dominates wave latency — ROOFLINE.md §4),
  * the donated-wave diff (how many copy steps donation removes),
  * a per-phase dispatch attribution (the mega-fusion priority list),
  * live HBM buffer sizes (temp/args/outputs).

Run: python benchmarks/tpu_aot_census.py   (requires the TPU PJRT
plugin; skips with a message where it is absent, e.g. GitHub CI).
"""

from __future__ import annotations

import re
import sys
from collections import Counter
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from _jax_platform import force_cpu_platform  # noqa: E402

force_cpu_platform(8)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

S, T, N, SC, E = 10_000, 3, 16_384, 16_384, 65_536
TOPOLOGY = "v5e:2x4"

# Dispatch-bearing instruction kinds (parameters/bitcasts/tuples are
# metadata; copy-done is the completion half of an async copy).
DISPATCH_OPS = (
    "fusion", "custom-call", "copy", "dynamic-update-slice", "sort",
    "reduce-window", "gather", "scatter",
)


def entry_census(compiled) -> tuple[int, int, dict]:
    txt = compiled.as_text()
    entry = txt[txt.index("ENTRY "):]
    body = entry[entry.index("{") + 1:]
    depth, end = 1, 0
    for i, ch in enumerate(body):
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                end = i
                break
    insts = re.findall(
        r"^\s*(?:ROOT\s+)?[%\w.-]+ = \S+ ([a-z-]+)\(", body[:end], re.M
    )
    c = Counter(insts)
    return sum(c.values()), sum(c[k] for k in DISPATCH_OPS), dict(
        c.most_common(10)
    )


def main() -> int:
    try:
        from jax.experimental import topologies

        td = topologies.get_topology_desc(
            platform="tpu", topology_name=TOPOLOGY
        )
    except Exception as exc:
        print(f"TPU PJRT topology unavailable ({exc!r}); nothing to census.")
        return 0
    from jax.sharding import SingleDeviceSharding

    dev = td.devices[0]
    print(f"target: {dev.device_kind} x{len(td.devices)} ({TOPOLOGY})")
    s = SingleDeviceSharding(dev)
    jax.config.update("jax_compilation_cache_dir", None)

    from hypervisor_tpu.config import DEFAULT_CONFIG
    from hypervisor_tpu.ops import admission as admission_ops
    from hypervisor_tpu.ops import gateway as gateway_ops
    from hypervisor_tpu.ops import liability as liability_ops
    from hypervisor_tpu.ops import merkle as merkle_ops
    from hypervisor_tpu.ops import saga_ops, terminate as terminate_ops
    from hypervisor_tpu.ops.pipeline import governance_wave
    from hypervisor_tpu.tables.state import (
        AgentTable,
        ElevationTable,
        SessionTable,
        VouchTable,
    )

    def sds(tree):
        return jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree
        )

    at, st, vt, et = (
        sds(AgentTable.create(N)),
        sds(SessionTable.create(SC)),
        sds(VouchTable.create(E)),
        sds(ElevationTable.create(4096)),
    )
    li = jax.ShapeDtypeStruct((S,), jnp.int32)
    lb = jax.ShapeDtypeStruct((S,), jnp.bool_)
    lf = jax.ShapeDtypeStruct((S,), jnp.float32)
    li8 = jax.ShapeDtypeStruct((S,), jnp.int8)
    sf = jax.ShapeDtypeStruct((), jnp.float32)
    si = jax.ShapeDtypeStruct((), jnp.int32)
    bodies = jax.ShapeDtypeStruct((T, S, merkle_ops.BODY_WORDS), jnp.uint32)
    wave_args = (at, st, vt, li, li, li, lf, lb, lb, li, bodies, sf, sf)

    def wave_fastpath(*a):
        *w, lo, hi = a
        return governance_wave(
            *w, use_pallas=True, unique_sessions=True, wave_range=(lo, hi)
        )

    # ── the bench wave, plain and donated ────────────────────────────
    for label, extra in (("wave", {}), ("wave+donate",
                                       {"donate_argnums": (0, 1, 2)})):
        compiled = (
            jax.jit(wave_fastpath, in_shardings=s, out_shardings=s, **extra)
            .lower(*wave_args, si, si)
            .compile()
        )
        total, heavy, top = entry_census(compiled)
        print(f"{label:14s} entry={total:4d} dispatch-ish={heavy:4d}  {top}")
        if not extra:
            mm = compiled.memory_analysis()
            print(
                "               HBM MB: temp"
                f" {mm.temp_size_in_bytes / 1e6:.2f} args"
                f" {mm.argument_size_in_bytes / 1e6:.2f} out"
                f" {mm.output_size_in_bytes / 1e6:.2f}"
            )

    # ── per-phase attribution ────────────────────────────────────────
    def audit(b):
        chain = merkle_ops.chain_digests(b, use_pallas=True)
        p = 1 << max(0, (T - 1).bit_length())
        leaves = jnp.zeros((S, p, 8), jnp.uint32)
        leaves = leaves.at[:, :T].set(jnp.transpose(chain, (1, 0, 2)))
        return merkle_ops.merkle_root_lanes(
            leaves, jnp.int32(T), use_pallas=True
        )

    phases = [
        ("contribution",
         lambda v, ts, now: liability_ops.contribution_toward(v, ts, now),
         (vt, jax.ShapeDtypeStruct((N,), jnp.int32), sf)),
        ("admission",
         partial(admission_ops.admit_batch, trust=DEFAULT_CONFIG.trust,
                 unique_sessions=True),
         (at, st, li, li, li, lf, lb, lb, sf)),
        ("audit(hash)", audit, (bodies,)),
        ("saga step",
         lambda q, ok: saga_ops.execute_attempt(
             q, success=ok, retries_left=jnp.zeros((S,), jnp.int8)),
         (li8, lb)),
        ("terminate",
         lambda a, v, lo, hi: terminate_ops.release_session_scope(
             a, v, None, wave_range=(lo, hi)),
         (at, vt, si, si)),
        ("gateway",
         partial(gateway_ops.check_actions, breach=DEFAULT_CONFIG.breach,
                 rate_limit=DEFAULT_CONFIG.rate_limit,
                 trust=DEFAULT_CONFIG.trust),
         (at, et, li, li8, lb, lb, lb, lb, sf)),
    ]
    for name, fn, args in phases:
        compiled = (
            jax.jit(fn, in_shardings=s, out_shardings=s)
            .lower(*args)
            .compile()
        )
        total, heavy, top = entry_census(compiled)
        print(f"{name:14s} entry={total:4d} dispatch-ish={heavy:4d}  {top}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
