"""Sharded-wave scaling study: per-phase collective census + weak scaling.

Two artifacts (written to `benchmarks/results/SCALING.md`):

1. **Per-phase breakdown** — each sharded phase program (admission,
   audit chain, slash cascade, action gateway, the fused governance
   wave) is compiled for the mesh and its HLO is scanned for the
   collectives XLA actually inserted (`all-reduce`, `all-gather`,
   `collective-permute`, `all-to-all`). The census is
   environment-independent: the same program lowers to the same
   collective structure on ICI — only the link bandwidth changes.
   Wall-times come from the current backend (the virtual CPU mesh in
   development; the real chip when the tunnel allows) and are labeled
   with it.

2. **Weak scaling** — the fused wave at fixed PER-SHARD load
   (joins/shard and sessions/shard constant) across 1/2/4/8 shards.
   Ideal weak scaling is flat; growth isolates the collective cost.

Run: `python benchmarks/bench_scaling.py [--iters N] [--write]`.
Uses the hermetic CPU mesh path (never touches the accelerator tunnel)
unless --platform overrides.
"""

from __future__ import annotations

import argparse
import json
import re
import time
from pathlib import Path

# Force the virtual CPU platform BEFORE jax fully imports (the shell
# env routes the default backend at the accelerator tunnel).
import sys

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from _jax_platform import force_cpu_platform  # noqa: E402

COLLECTIVE_OPS = ("all-reduce", "all-gather", "collective-permute", "all-to-all")

# The statically-known dominant collective per phase (what the census
# verifies): see `parallel/collectives.py` phase docstrings.
DOMINANT = {
    "admission": "all-gather (global capacity ranking)",
    "audit_chain": "collective-permute (turn-axis carry ring)",
    "slash_cascade": "all-reduce (per-round exposure psum)",
    "action_gateway": "none (shard-local by placement contract)",
    "fused_wave": "all-reduce (admission + session folds)",
    "fused_wave_contiguous": "all-reduce (terminate mask psum removed)",
    "fused_wave_fastpaths": "all-reduce (rank all_gathers removed too)",
    "fused_wave_gw_modes": "all-reduce (admission + session folds)",
}


def _census(compiled) -> dict:
    txt = compiled.as_text()
    return {op: len(re.findall(re.escape(op) + r"[-.\"( ]", txt))
            for op in COLLECTIVE_OPS}


def _p50_ms(fn, args, iters: int) -> float:
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter_ns()
        out = fn(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter_ns() - t0)
    times.sort()
    return times[len(times) // 2] / 1e6


def build_phase_programs(n_dev: int, rows_per_shard: int = 16):
    """(name, jitted_fn, args) per sharded phase, sized for n_dev."""
    import jax.numpy as jnp
    import numpy as np

    from hypervisor_tpu.models import SessionState
    from hypervisor_tpu.ops import merkle as merkle_ops
    from hypervisor_tpu.parallel import make_mesh
    from hypervisor_tpu.parallel.collectives import (
        sharded_admission,
        sharded_chain,
        sharded_gateway,
        sharded_governance_wave,
        sharded_slash,
    )
    from hypervisor_tpu.tables.state import (
        AgentTable,
        ElevationTable,
        SessionTable,
        VouchTable,
    )
    from hypervisor_tpu.tables.struct import replace as t_replace

    mesh = make_mesh(n_dev, platform="cpu")
    rng = np.random.RandomState(0)
    b = 16 * n_dev            # joins (16 per shard)
    k = 4 * n_dev             # wave sessions (4 per shard)
    t = 3
    cap = rows_per_shard * n_dev
    e_cap = 8 * n_dev

    agents = AgentTable.create(cap)
    sessions = SessionTable.create(2 * k)
    ws = jnp.arange(k)
    sessions = t_replace(
        sessions,
        state=sessions.state.at[ws].set(jnp.int8(SessionState.HANDSHAKING.code)),
        max_participants=sessions.max_participants.at[ws].set(32),
        min_sigma_eff=sessions.min_sigma_eff.at[ws].set(0.0),
    )
    vouches = VouchTable.create(e_cap)
    per = b // n_dev
    slots = np.array(
        [(i // per) * rows_per_shard + (i % per) for i in range(b)], np.int32
    )
    sess_of = np.array([i % k for i in range(b)], np.int32)
    bodies = rng.randint(
        0, 2**32, size=(t, k, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)

    join_cols = (
        jnp.asarray(slots),
        jnp.arange(b, dtype=jnp.int32),
        jnp.asarray(sess_of),
        jnp.full((b,), 0.8, jnp.float32),
        jnp.ones((b,), bool),
        jnp.zeros((b,), bool),
    )

    yield "admission", sharded_admission(mesh), (
        agents, sessions, vouches, *join_cols, 0.0, 0.5,
    )

    chain_bodies = rng.randint(
        0, 2**32, size=(2 * n_dev, 4, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    yield "audit_chain", sharded_chain(mesh), (
        jnp.asarray(chain_bodies), jnp.zeros((4, 8), jnp.uint32),
    )

    vt = t_replace(
        vouches,
        voucher=vouches.voucher.at[: e_cap // 2].set(
            jnp.arange(e_cap // 2, dtype=jnp.int32) % 8
        ),
        vouchee=vouches.vouchee.at[: e_cap // 2].set(
            8 + jnp.arange(e_cap // 2, dtype=jnp.int32) % 8
        ),
        session=vouches.session.at[: e_cap // 2].set(0),
        bond=vouches.bond.at[: e_cap // 2].set(0.1),
        active=vouches.active.at[: e_cap // 2].set(True),
        expiry=vouches.expiry.at[: e_cap // 2].set(1e9),
    )
    sigma_v = jnp.full((cap,), 0.9, jnp.float32)
    seeds_v = jnp.zeros((cap,), bool).at[jnp.array([8, 9])].set(True)
    yield "slash_cascade", sharded_slash(mesh), (
        vt, sigma_v, seeds_v, 0, 0.5, 0.0,
    )

    act = b
    act_slots = jnp.asarray(slots[:act])
    yield "action_gateway", sharded_gateway(mesh), (
        agents, ElevationTable.create(8), act_slots,
        jnp.full((act,), 2, jnp.int8), jnp.zeros((act,), bool),
        jnp.zeros((act,), bool), jnp.zeros((act,), bool),
        jnp.zeros((act,), bool), jnp.ones((act,), bool), 1.0,
    )

    wave_args = (
        agents, sessions, vouches, *join_cols,
        jnp.asarray(np.arange(k, dtype=np.int32)), jnp.asarray(bodies),
        0.0, 0.5,
    )
    yield "fused_wave", sharded_governance_wave(mesh), wave_args

    # The contiguous-wave variant: terminate's [S_cap] membership-mask
    # psum is replaced by range compares against the replicated (lo, hi)
    # scalars — one fewer all-reduce in the census, zero gathers in the
    # phase (ops/terminate.py wave_range path).
    yield "fused_wave_contiguous", sharded_governance_wave(
        mesh, contiguous_waves=True
    ), (
        *wave_args,
        jnp.asarray(0, jnp.int32), jnp.asarray(k, jnp.int32),
    )

    # Both host-verified layout contracts at once (the bench's shape:
    # ONE join per session). SAME join count b as the other fused
    # phases so the p50 column stays comparable on the load driver —
    # which forces b wave sessions (the contract's price, also the
    # 10k-session bench's own shape): terminate mask psum gone AND the
    # admission capacity-rank all_gathers gone; the fused wave's only
    # remaining collectives are the admission psums and session folds.
    sessions_u = SessionTable.create(2 * b)
    wsu = jnp.arange(b)
    sessions_u = t_replace(
        sessions_u,
        state=sessions_u.state.at[wsu].set(
            jnp.int8(SessionState.HANDSHAKING.code)
        ),
        max_participants=sessions_u.max_participants.at[wsu].set(32),
        min_sigma_eff=sessions_u.min_sigma_eff.at[wsu].set(0.0),
    )
    bodies_u = rng.randint(
        0, 2**32, size=(t, b, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    join_cols_u = (
        jnp.asarray(slots),
        jnp.arange(b, dtype=jnp.int32),
        jnp.arange(b, dtype=jnp.int32),      # one join per session
        jnp.full((b,), 0.8, jnp.float32),
        jnp.ones((b,), bool),
        jnp.zeros((b,), bool),
    )
    yield "fused_wave_fastpaths", sharded_governance_wave(
        mesh, contiguous_waves=True, unique_sessions=True
    ), (
        agents, sessions_u, vouches, *join_cols_u,
        jnp.asarray(np.arange(b, dtype=np.int32)), jnp.asarray(bodies_u),
        0.0, 0.5,
        jnp.asarray(0, jnp.int32), jnp.asarray(b, jnp.int32),
    )

    yield "fused_wave_gw_modes", sharded_governance_wave(
        mesh, with_gateway=True, mode_dispatch=True
    ), (
        *wave_args,
        ElevationTable.create(8),
        act_slots, jnp.full((act,), 2, jnp.int8), jnp.zeros((act,), bool),
        jnp.zeros((act,), bool), jnp.zeros((act,), bool),
        jnp.zeros((act,), bool), jnp.ones((act,), bool),
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument(
        "--write", action="store_true",
        help="write benchmarks/results/SCALING.md",
    )
    args = ap.parse_args()

    force_cpu_platform(args.devices)
    import jax

    backend = jax.default_backend()
    kind = jax.devices()[0].device_kind

    # ── per-phase census + timing at the full mesh ───────────────────
    phase_rows = []
    for name, fn, fargs in build_phase_programs(args.devices):
        compiled = fn.lower(*fargs).compile()
        census = _census(compiled)
        p50 = _p50_ms(fn, fargs, args.iters)
        phase_rows.append((name, p50, census, DOMINANT[name]))
        print(f"{name:22s} p50={p50:8.3f} ms  {census}")

    # ── weak scaling: fixed per-shard load over 1/2/4/8 shards ───────
    # Alongside the fused wave, two CONTROLS at each shard count
    # separate "virtual-mesh artifact" from "structural serial section"
    # (round-4 verdict ask): the action gateway compiles to ZERO
    # collectives, and the elementwise program is a bare x*2+1 under
    # shard_map — if those degrade like the wave does, the cliff is the
    # host mesh's per-device dispatch/rendezvous, not our collectives.
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from hypervisor_tpu.parallel.collectives import AGENT_AXIS

    weak_rows = []
    d = 1
    while d <= args.devices:
        row = {}
        for name, fn, fargs in build_phase_programs(d):
            if name == "fused_wave":
                row["wave"] = _p50_ms(fn, fargs, args.iters)
            elif name == "action_gateway":
                row["gateway"] = _p50_ms(fn, fargs, args.iters)
            if len(row) == 2:
                break
        from hypervisor_tpu.parallel import make_mesh

        mesh = make_mesh(d, platform="cpu")
        ew = jax.jit(
            jax.shard_map(
                lambda x: x * 2.0 + 1.0,
                mesh=mesh,
                in_specs=P(AGENT_AXIS),
                out_specs=P(AGENT_AXIS),
            )
        )
        row["elementwise"] = _p50_ms(
            ew, (jnp.zeros((d * 1024,), jnp.float32),), args.iters
        )
        weak_rows.append((d, 16 * d, 4 * d, row))
        print(
            f"weak d={d}: B={16*d} K={4*d} wave={row['wave']:.3f} ms "
            f"gateway0coll={row['gateway']:.3f} ms "
            f"elementwise={row['elementwise']:.3f} ms"
        )
        d *= 2

    base = weak_rows[0][3]["wave"]
    lines = [
        "# Sharded-wave scaling study",
        "",
        f"Backend: {kind} ({backend}) — virtual-mesh times are NOT "
        "predictive of ICI; the collective census is structural and "
        "holds on any backend.  ",
        f"Methodology: p50 of {args.iters} post-warmup runs; census = "
        "op counts in the compiled HLO.",
        "",
        "## Per-phase collective census (8 shards)",
        "",
        "| phase | p50 (ms) | all-reduce | all-gather | collective-permute | all-to-all | dominant collective |",
        "|---|---|---|---|---|---|---|",
    ]
    for name, p50, census, dom in phase_rows:
        lines.append(
            f"| {name} | {p50:.3f} | {census['all-reduce']} "
            f"| {census['all-gather']} | {census['collective-permute']} "
            f"| {census['all-to-all']} | {dom} |"
        )
    lines += [
        "",
        "## Weak scaling — fused governance wave, fixed per-shard load",
        "",
        "16 joins + 4 sessions per shard; ideal weak scaling is flat. The",
        "two control columns carry the diagnosis below: `gateway` compiles",
        "to ZERO collectives, `elementwise` is a bare `x*2+1` shard_map.",
        "",
        "| shards | joins | sessions | wave p50 (ms) | vs 1 shard | gateway (0-coll) | elementwise |",
        "|---|---|---|---|---|---|---|",
    ]
    for d, b, k, row in weak_rows:
        lines.append(
            f"| {d} | {b} | {k} | {row['wave']:.3f} "
            f"| {row['wave'] / base:.2f}x | {row['gateway']:.3f} "
            f"| {row['elementwise']:.3f} |"
        )
    last = weak_rows[-1][3]
    first = weak_rows[0][3]
    n_last = weak_rows[-1][0]
    gw_x = last["gateway"] / max(first["gateway"], 1e-9)
    ew_x = last["elementwise"] / max(first["elementwise"], 1e-9)
    wv_x = last["wave"] / max(first["wave"], 1e-9)
    lines += [
        "",
        "## Weak-scaling cliff: diagnosis (round-5)",
        "",
        "The cliff is a VIRTUAL-MESH MEASUREMENT ARTIFACT, not a",
        "structural serial section in the wave:",
        "",
        f"* the zero-collective gateway degrades {gw_x:.1f}x over "
        f"1→{n_last} shards at fixed per-shard load — no collective can be",
        "  responsible, the program is shard-local end to end;",
        f"* a trivial elementwise shard_map degrades {ew_x:.1f}x — the",
        "  per-device overhead is in XLA:CPU's multi-device dispatch and",
        "  rendezvous (N host 'devices' share one process and thread",
        "  pool, so per-device launch overhead serializes), not in the",
        "  program at all;",
        f"* the fused wave degrades {wv_x:.1f}x — the same envelope as its",
        "  zero-collective control, so the wave adds no serial section of",
        "  its own;",
        "* a bare [1k/shard] psum on this mesh costs about the same as",
        "  the elementwise control (measured in the round-5 experiment:",
        "  0.67 ms vs 0.66 ms at 8 shards), i.e. host-mesh collectives",
        "  are dispatch-bound, not payload-bound.",
        "",
        "Structural view (backend-independent): the census above shows",
        "the fused wave at 4 all-reduces — the dependency floor (slot→",
        "session map, contribution, admission counts + terminate mask,",
        "post-terminate fold; each depends on the previous). On real",
        "v5e ICI (~1-5 µs small-payload all-reduce latency at 8 chips,",
        "payloads here are [S_cap]-row vectors ≤ tens of KB), the wave's",
        "collective budget is ~4-20 µs per tick — two orders of",
        "magnitude below the single-chip wave body (~0.4 ms measured in",
        "round 1). Expected real-ICI weak scaling is flat until the",
        "per-shard body shrinks to collective-latency scale.",
        "See also benchmarks/results/ROOFLINE.md.",
    ]
    report = "\n".join(lines) + "\n"
    print()
    print(report)
    if args.write:
        out = Path(__file__).parent / "results" / "SCALING.md"
        out.write_text(report)
        print(f"wrote {out}")
        (Path(__file__).parent / "results" / "scaling.json").write_text(
            json.dumps(
                {
                    "backend": backend,
                    "device_kind": kind,
                    "phases": [
                        {"name": n, "p50_ms": p, "census": c, "dominant": dom}
                        for n, p, c, dom in phase_rows
                    ],
                    "weak_scaling": [
                        {
                            "shards": d,
                            "joins": b,
                            "sessions": k,
                            "p50_ms": row["wave"],
                            "gateway_p50_ms": row["gateway"],
                            "elementwise_p50_ms": row["elementwise"],
                        }
                        for d, b, k, row in weak_rows
                    ],
                },
                indent=2,
            )
        )


if __name__ == "__main__":
    main()
