"""On-chip evidence capture daemon.

The accelerator tunnel wedges unpredictably (rounds 2-4: hours-long
outages; a killed-mid-claim process can also leave the single-claim
tunnel stuck until the lease clears). This driver turns "run everything
on the chip" into a crash-only loop:

  probe -> (healthy) -> run the next pending step in a fresh subprocess
        -> (wedged/timeout) -> back off, probe again

Every step runs in its own subprocess with a hard timeout (a wedge
mid-step is unrecoverable in-process — the PJRT plugin never returns),
so one wedge costs one step attempt, not the run. Progress is journaled
to benchmarks/results/capture_r05.json so a restarted daemon resumes
where it left off; all output streams to capture_r05.log.

Steps, in order (each skipped once recorded as ok):
  parity      HV_TPU_TESTS=1 pytest of the compiled-Mosaic parity tests
  bench       python bench.py (the driver's headline JSON line)
  suite       python benchmarks/bench_suite.py --write-results
  scaling     python benchmarks/bench_scaling.py --write
  donation    python benchmarks/bench_donation.py
  pack_before bench.py in the .beforeafter/prepack worktree (1efd237,
              the commit before column packing landed)
  pack_after  bench.py in .beforeafter/postpack (0b029bf, packing)
  fuse_after  bench.py in .beforeafter/postfuse (50805e5, terminate
              gather fusion)
The last three give the TPU before/after that ROADMAP promises for the
round-3 packing and terminate-fusion changes; HEAD's own number comes
from the `bench` step.

Run: nohup python benchmarks/capture_evidence.py >/dev/null 2>&1 &
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
RESULTS = REPO / "benchmarks" / "results"
JOURNAL = RESULTS / "capture_r05.json"
LOG = RESULTS / "capture_r05.log"

PROBE_TIMEOUT_S = 90
PROBE_INTERVAL_S = 300  # between failed probes
STEP_COOLDOWN_S = 20  # claim-release settle between steps
# A step that keeps failing with the tunnel HEALTHY is broken, not
# wedged — park it after this many attempts so it can't starve the
# steps queued behind it (each attempt can hold the single-claim
# tunnel for up to its full timeout).
MAX_ATTEMPTS = 3

# (name, argv, extra env, timeout seconds, cwd relative to REPO)
STEPS: list[tuple[str, list[str], dict[str, str], float, str]] = [
    (
        "parity",
        [
            sys.executable,
            "-m",
            "pytest",
            "tests/parity/test_pallas_sha256.py",
            "tests/parity/test_liability_pallas.py",
            "-v",
        ],
        {"HV_TPU_TESTS": "1"},
        2400.0,
        ".",
    ),
    ("bench", [sys.executable, "bench.py"], {}, 3000.0, "."),
    (
        "suite",
        [sys.executable, "benchmarks/bench_suite.py", "--write-results"],
        {},
        3000.0,
        ".",
    ),
    (
        "scaling",
        [sys.executable, "benchmarks/bench_scaling.py", "--write"],
        {},
        2400.0,
        ".",
    ),
    ("donation", [sys.executable, "benchmarks/bench_donation.py"], {}, 2400.0, "."),
    ("pack_before", [sys.executable, "bench.py"], {}, 3000.0, ".beforeafter/prepack"),
    ("pack_after", [sys.executable, "bench.py"], {}, 3000.0, ".beforeafter/postpack"),
    ("fuse_after", [sys.executable, "bench.py"], {}, 3000.0, ".beforeafter/postfuse"),
]


def log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    with LOG.open("a") as f:
        f.write(line + "\n")


def load_journal() -> dict:
    if JOURNAL.exists():
        return json.loads(JOURNAL.read_text())
    return {"steps": {}}


def save_journal(j: dict) -> None:
    JOURNAL.write_text(json.dumps(j, indent=2))


def probe() -> bool:
    """Tunnel health: jax.devices() in a fresh subprocess (a wedged
    probe hangs forever in-process; the timeout reaps it)."""
    try:
        r = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; d = jax.devices(); print(d)",
            ],
            cwd=REPO,
            timeout=PROBE_TIMEOUT_S,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "TPU" in (r.stdout or "")


def run_step(
    name: str, cmd: list[str], env_extra: dict, timeout: float, cwd: str
) -> dict:
    env = dict(os.environ)
    env.update(env_extra)
    workdir = (REPO / cwd).resolve()
    start = time.time()
    try:
        with LOG.open("a") as f:
            f.write(f"\n===== step {name} in {cwd}: {' '.join(cmd)} =====\n")
            f.flush()
            r = subprocess.run(
                cmd, cwd=workdir, env=env, timeout=timeout, stdout=f, stderr=f
            )
        rc: int | None = r.returncode
    except subprocess.TimeoutExpired:
        rc = None
    return {
        "rc": rc,
        "ok": rc == 0,
        "seconds": round(time.time() - start, 1),
        "at": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--deadline",
        type=float,
        default=0.0,
        help="unix timestamp after which no NEW step starts (the round "
        "driver runs its own bench at round end — two claimants on the "
        "single-claim tunnel wedge each other; stop before it starts)",
    )
    args = ap.parse_args()
    journal = load_journal()
    log(f"daemon start, pid={os.getpid()}, deadline={args.deadline or 'none'}")
    while True:
        if args.deadline and time.time() >= args.deadline:
            save_journal(journal)
            log("deadline reached — daemon exits (tunnel freed for the "
                "round driver)")
            return
        runnable = []
        parked = []
        waiting = []
        for s in STEPS:
            rec = journal["steps"].get(s[0], {})
            if rec.get("ok"):
                continue
            if not (REPO / s[4]).resolve().is_dir():
                # Worktree not set up (yet): skip WITHOUT burning the
                # attempt budget — re-evaluated every loop, so creating
                # the worktree and restarting (or just waiting) resumes
                # the step.
                waiting.append(s[0])
            elif rec.get("attempts", 0) >= MAX_ATTEMPTS:
                parked.append(s[0])
            else:
                runnable.append(s)
        if not runnable:
            if waiting:
                log(f"no runnable step (waiting on workdirs: {waiting}, "
                    f"parked: {parked or 'none'}); sleeping {PROBE_INTERVAL_S}s")
                time.sleep(PROBE_INTERVAL_S)
                continue
            journal["done"] = not parked
            journal["parked"] = parked
            save_journal(journal)
            log(f"daemon done (parked: {parked or 'none'})")
            return
        pending = runnable
        if not probe():
            log(f"tunnel wedged; sleeping {PROBE_INTERVAL_S}s "
                f"(pending: {[s[0] for s in pending]})")
            time.sleep(PROBE_INTERVAL_S)
            continue
        name, cmd, env_extra, timeout, cwd = pending[0]
        log(f"tunnel healthy — running step '{name}' (timeout {timeout}s)")
        res = run_step(name, cmd, env_extra, timeout, cwd)
        attempts = journal["steps"].get(name, {}).get("attempts", 0) + 1
        res["attempts"] = attempts
        journal["steps"][name] = res
        save_journal(journal)
        log(f"step '{name}' -> {res}")
        time.sleep(STEP_COOLDOWN_S)


if __name__ == "__main__":
    main()
