"""Runtime callers for the formerly-orphan device ops (VERDICT #8):
batched VFS write waves (rate limit + vector-clock prepass), breach
sweeps, and elevation expiry over the state tables.
"""

from __future__ import annotations

import numpy as np
import pytest

from hypervisor_tpu.config import DEFAULT_CONFIG
from hypervisor_tpu.models import SessionConfig
from hypervisor_tpu.runtime.write_wave import (
    WRITE_CONFLICT,
    WRITE_OK,
    WRITE_RATE_LIMITED,
    WriteWave,
)
from hypervisor_tpu.session.vfs import SessionVFS
from hypervisor_tpu.state import HypervisorState
from hypervisor_tpu.tables.state import FLAG_BREAKER_TRIPPED


class TestWriteWave:
    def test_wave_applies_and_attributes(self):
        vfs = SessionVFS("s1")
        wave = WriteWave(vfs)
        for i in range(4):
            wave.submit(f"did:a{i}", f"/f{i}.txt", f"content {i}")
        report = wave.flush(now=0.0)
        assert report.applied == 4 and not report.conflicts
        assert vfs.read("/f2.txt") == "content 2"
        assert vfs.edit_log[-1].agent_did == "did:a3"

    def test_stale_writer_rejected_fresh_after_observe(self):
        vfs = SessionVFS("s1")
        wave = WriteWave(vfs)
        wave.submit("did:w1", "/doc", "v1")
        assert wave.flush(now=0.0).applied == 1
        # w2 writes without having observed w1's write: stale (strict).
        wave.submit("did:w2", "/doc", "v2-blind")
        report = wave.flush(now=1.0)
        assert report.status[0] == WRITE_CONFLICT
        assert vfs.read("/doc") == "v1"
        # After a read barrier, w2's write is causally fresh.
        wave.observe("did:w2", "/doc")
        wave.submit("did:w2", "/doc", "v2-seen")
        assert wave.flush(now=2.0).applied == 1
        assert vfs.read("/doc") == "v2-seen"

    def test_same_wave_same_path_orders_sequentially(self):
        vfs = SessionVFS("s1")
        wave = WriteWave(vfs)
        wave.submit("did:w1", "/log", "first")
        wave.submit("did:w1", "/log", "second")  # same writer saw its own write
        report = wave.flush(now=0.0)
        assert list(report.status) == [WRITE_OK, WRITE_OK]
        assert vfs.read("/log") == "second"

    def test_rate_limit_gates_wave(self):
        vfs = SessionVFS("s1")
        wave = WriteWave(vfs)
        burst = int(DEFAULT_CONFIG.rate_limit.ring_bursts[3])  # ring 3 = 10
        for i in range(burst + 3):
            wave.submit("did:spammy", f"/f{i}", "x", ring=3)
        report = wave.flush(now=0.0)
        assert report.applied == burst
        assert report.rate_limited == 3
        assert (report.status[burst:] == WRITE_RATE_LIMITED).all()

    def test_concurrent_writers_different_paths_all_land(self):
        vfs = SessionVFS("s1")
        wave = WriteWave(vfs)
        for i in range(8):
            wave.submit(f"did:w{i}", f"/own/{i}", f"v{i}", ring=1)
        assert wave.flush(now=0.0).applied == 8


class TestBreachSweep:
    def _admitted_state(self, n=4, sigma=0.8):
        st = HypervisorState()
        slot = st.create_session("s:b", SessionConfig(max_participants=32))
        for i in range(n):
            st.enqueue_join(slot, f"did:b{i}", sigma)
        assert (st.flush_joins() == 0).all()
        return st

    def test_privileged_call_ratio_trips_breaker(self):
        st = self._admitted_state()
        # Agent 0 (ring 2) hammers ring-0 targets; agent 1 behaves.
        st.record_calls([0] * 8, [0] * 8)
        st.record_calls([1] * 8, [2] * 8)
        severity, tripped = st.breach_sweep_tick(now=1.0)
        assert severity[0] == 4 and tripped[0]          # CRITICAL
        assert severity[1] == 0 and not tripped[1]
        assert int(np.asarray(st.agents.flags)[0]) & FLAG_BREAKER_TRIPPED

    def test_below_min_calls_no_analysis(self):
        st = self._admitted_state()
        st.record_calls([0] * 3, [0] * 3)  # < min_calls_for_analysis (5)
        severity, tripped = st.breach_sweep_tick(now=1.0)
        assert severity[0] == 0 and not tripped[0]

    def test_sweep_honors_custom_breach_config(self):
        """The sweep analyzes with the STATE's BreachConfig, not the
        module default (round-5 fix: _BREACH_SWEEP/_RECORD_CALLS were
        silently defaulting, so custom thresholds never reached the
        device plane)."""
        import dataclasses

        cfg = DEFAULT_CONFIG.replace(
            breach=dataclasses.replace(
                DEFAULT_CONFIG.breach,
                min_calls_for_analysis=3,   # default: 5
                high_threshold=0.5,         # default: 0.7
            )
        )
        st = HypervisorState(cfg)
        slot = st.create_session("s:cfg", SessionConfig(max_participants=8))
        st.enqueue_join(slot, "did:cfg", 0.8)  # ring 2
        assert (st.flush_joins() == 0).all()
        # 4 calls, 2 privileged: rate 0.5. Default config: below
        # min_calls (5) -> no analysis. Custom config: analyzable (>=3)
        # and at the lowered high threshold -> trips.
        st.record_calls([0] * 4, [0, 0, 2, 2], now=1.0)
        severity, tripped = st.breach_sweep_tick(now=1.0)
        assert int(severity[0]) >= 3 and bool(tripped[0])

    def test_breaker_cooldown_expires(self):
        st = self._admitted_state()
        st.record_calls([0] * 6, [0] * 6)
        _, tripped = st.breach_sweep_tick(now=0.0)
        assert tripped[0]
        cooldown = DEFAULT_CONFIG.breach.circuit_breaker_cooldown_seconds
        # Clean behavior after the cooldown: breaker resets.
        st.breach_sweep_tick(now=cooldown + 1.0)
        assert not (
            int(np.asarray(st.agents.flags)[0]) & FLAG_BREAKER_TRIPPED
        )


class TestElevation:
    def _state_with_agent(self):
        st = HypervisorState()
        slot = st.create_session("s:e", SessionConfig())
        st.enqueue_join(slot, "did:e", 0.8)  # ring 2
        assert (st.flush_joins() == 0).all()
        return st

    def test_grant_and_effective_ring(self):
        st = self._state_with_agent()
        st.grant_elevation(0, granted_ring=1, now=0.0, ttl_seconds=100.0)
        assert st.effective_rings(now=50.0)[0] == 1
        assert st.effective_rings(now=150.0)[0] == 2  # lapsed

    def test_expiry_tick_deactivates(self):
        st = self._state_with_agent()
        st.grant_elevation(0, granted_ring=1, now=0.0, ttl_seconds=10.0)
        assert st.elevation_tick(now=5.0) == 0
        assert st.elevation_tick(now=11.0) == 1
        assert not bool(np.asarray(st.elevations.active)[0])

    def test_grant_rules(self):
        st = self._state_with_agent()
        with pytest.raises(ValueError, match="Ring 0"):
            st.grant_elevation(0, granted_ring=0, now=0.0)
        with pytest.raises(ValueError, match="more privileged"):
            st.grant_elevation(0, granted_ring=2, now=0.0)  # already ring 2

    def test_ttl_capped(self):
        st = self._state_with_agent()
        cfg = DEFAULT_CONFIG.elevation
        st.grant_elevation(0, granted_ring=1, now=0.0, ttl_seconds=1e9)
        assert float(np.asarray(st.elevations.expires_at)[0]) == pytest.approx(
            cfg.max_ttl_seconds
        )


class TestQuarantinePlane:
    def test_enter_extend_and_sweep(self):
        from hypervisor_tpu.models import SessionConfig
        from hypervisor_tpu.state import HypervisorState

        st = HypervisorState()
        slot = st.create_session("session:q", SessionConfig())
        for i in range(3):
            st.enqueue_join(slot, f"did:q{i}", sigma_raw=0.8)
        assert (st.flush_joins() == 0).all()

        st.quarantine_rows([0, 1], now=100.0)          # default 300s
        mask = st.quarantined_mask()
        assert mask[0] and mask[1] and not mask[2]

        # Escalation keeps the original deadline (reference
        # `quarantine.py:96-103`: merge, expires_at unchanged).
        st.quarantine_rows([0], now=150.0, duration=500.0)
        import numpy as np
        until = np.asarray(st.agents.quarantine_until)
        assert until[0] == 400.0 and until[1] == 400.0

        # Sweep at/below the deadline: nothing released (the host
        # boundary is strictly-past: now > expires_at).
        assert st.quarantine_tick(now=399.0) == []
        assert st.quarantine_tick(now=400.0) == []
        assert st.quarantine_tick(now=400.5) == [0, 1]
        assert not st.quarantined_mask().any()

        # A fresh quarantine after release gets its own window.
        st.quarantine_rows([0], now=500.0, duration=100.0)
        assert np.asarray(st.agents.quarantine_until)[0] == 600.0
        assert st.quarantine_tick(now=601.0) == [0]

    def test_write_wave_refuses_quarantined_writer(self):
        import numpy as np
        from hypervisor_tpu.runtime.write_wave import (
            WRITE_OK,
            WRITE_QUARANTINED,
            WriteWave,
        )
        from hypervisor_tpu.session.vfs import SessionVFS

        vfs = SessionVFS("session:qw")
        held = {"did:frozen"}
        wave = WriteWave(vfs, is_quarantined=lambda did: did in held)
        wave.submit("did:frozen", "/a", "x", ring=2)
        wave.submit("did:free", "/b", "y", ring=2)
        report = wave.flush(now=0.0)
        assert report.status.tolist() == [WRITE_QUARANTINED, WRITE_OK]
        assert report.quarantined == 1 and report.applied == 1
        assert vfs.read("/b") == "y"
        assert vfs.read("/a") is None  # never written

    async def test_drift_slash_quarantines_device_row(self):
        from hypervisor_tpu import Hypervisor, SessionConfig
        from hypervisor_tpu.integrations.cmvk_adapter import CMVKAdapter
        from hypervisor_tpu.liability.quarantine import QuarantineReason

        class Verifier:
            def verify_embeddings(self, embedding_a, embedding_b,
                                  metric="cosine", threshold_profile=None,
                                  explain=False):
                class V:
                    drift_score = 0.8
                    explanation = "test"
                return V()

        hv = Hypervisor(cmvk=CMVKAdapter(verifier=Verifier()))
        ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
        sid = ms.sso.session_id
        await hv.join_session(sid, "did:bad", sigma_raw=0.9)
        await hv.activate_session(sid)
        await hv.verify_behavior(sid, "did:bad", "c", "o")

        # Host record with forensic data...
        rec = hv.quarantine.get_active_quarantine("did:bad", sid)
        assert rec is not None
        assert rec.reason is QuarantineReason.BEHAVIORAL_DRIFT
        assert rec.forensic_data["drift_score"] == 0.8
        # ...and the device row flagged read-only.
        row = hv.state.agent_row("did:bad")
        assert hv.state.quarantined_mask()[row["slot"]]

    async def test_managed_session_write_wave_prewired(self):
        """ManagedSession.write_wave() refuses device-quarantined writers
        without any manual predicate assembly."""
        from hypervisor_tpu import Hypervisor, SessionConfig
        from hypervisor_tpu.runtime.write_wave import WRITE_OK, WRITE_QUARANTINED

        hv = Hypervisor()
        ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
        sid = ms.sso.session_id
        await hv.join_session(sid, "did:iso", sigma_raw=0.8)
        await hv.join_session(sid, "did:ok", sigma_raw=0.8)
        await hv.activate_session(sid)

        row = hv.state.agent_row("did:iso")
        hv.state.quarantine_rows([row["slot"]], now=hv.state.now())

        wave = ms.write_wave()
        wave.submit("did:iso", "/doc.md", "nope", ring=2)
        wave.submit("did:ok", "/doc.md", "yes", ring=2)
        report = wave.flush(now=hv.state.now())
        assert report.status.tolist() == [WRITE_QUARANTINED, WRITE_OK]
        assert ms.sso.vfs.read("/doc.md") == "yes"

        # Sweep past the deadline: the writer is readmitted.
        hv.state.quarantine_tick(now=hv.state.now() + 301.0)
        wave2 = ms.write_wave()
        wave2.submit("did:iso", "/doc2.md", "back", ring=2)
        assert wave2.flush(now=hv.state.now()).status.tolist() == [WRITE_OK]


class TestIsolationLevels:
    """IsolationLevel flags gate the batched write path
    (`session/isolation.py`): SNAPSHOT skips the causal prepass,
    READ_COMMITTED is the default clock-gated path, SERIALIZABLE
    additionally demands a write-capable intent lock."""

    def test_snapshot_tolerates_causally_stale_writers(self):
        from hypervisor_tpu.runtime.write_wave import WRITE_OK, WriteWave
        from hypervisor_tpu.session.isolation import IsolationLevel
        from hypervisor_tpu.session.vfs import SessionVFS

        vfs = SessionVFS("session:iso-snap")
        wave = WriteWave(vfs, isolation=IsolationLevel.SNAPSHOT)
        wave.submit("did:w1", "/doc", "v1")
        assert wave.flush(now=0.0).applied == 1
        # A blind write that READ_COMMITTED would reject as stale lands.
        wave.submit("did:w2", "/doc", "v2-blind")
        report = wave.flush(now=1.0)
        assert report.status.tolist() == [WRITE_OK] and report.conflicts == 0
        assert vfs.read("/doc") == "v2-blind"

    def test_read_committed_still_rejects_stale(self):
        from hypervisor_tpu.runtime.write_wave import WRITE_CONFLICT, WriteWave
        from hypervisor_tpu.session.isolation import IsolationLevel
        from hypervisor_tpu.session.vfs import SessionVFS

        vfs = SessionVFS("session:iso-rc")
        wave = WriteWave(vfs, isolation=IsolationLevel.READ_COMMITTED)
        wave.submit("did:w1", "/doc", "v1")
        wave.flush(now=0.0)
        wave.submit("did:w2", "/doc", "v2-blind")
        assert wave.flush(now=1.0).status.tolist() == [WRITE_CONFLICT]

    def test_serializable_requires_write_lock(self):
        import pytest

        from hypervisor_tpu.runtime.write_wave import (
            WRITE_LOCK_REQUIRED,
            WRITE_OK,
            WriteWave,
        )
        from hypervisor_tpu.session.intent_locks import (
            IntentLockManager,
            LockIntent,
        )
        from hypervisor_tpu.session.isolation import IsolationLevel
        from hypervisor_tpu.session.vfs import SessionVFS

        with pytest.raises(ValueError, match="lock_manager"):
            WriteWave(
                SessionVFS("x"), isolation=IsolationLevel.SERIALIZABLE
            )

        locks = IntentLockManager()
        vfs = SessionVFS("session:iso-ser")
        sid = vfs.session_id
        wave = WriteWave(
            vfs, isolation=IsolationLevel.SERIALIZABLE, lock_manager=locks
        )
        # No lock: refused before any clock tick, and counted.
        wave.submit("did:w1", "/doc", "v1")
        report = wave.flush(now=0.0)
        assert report.status.tolist() == [WRITE_LOCK_REQUIRED]
        assert report.lock_required == 1
        # READ lock is not write-capable.
        locks.acquire("did:w1", sid, "/doc", LockIntent.READ)
        wave.submit("did:w1", "/doc", "v1")
        assert wave.flush(now=1.0).status.tolist() == [WRITE_LOCK_REQUIRED]
        # A WRITE lock held in a DIFFERENT session does not satisfy the
        # gate (locks are session-scoped).
        locks.release_agent_locks("did:w1", sid)
        locks.acquire("did:w1", "session:other", "/doc", LockIntent.WRITE)
        wave.submit("did:w1", "/doc", "v1")
        assert wave.flush(now=2.0).status.tolist() == [WRITE_LOCK_REQUIRED]
        # A WRITE lock in THIS session admits.
        locks.acquire("did:w1", sid, "/doc", LockIntent.WRITE)
        wave.submit("did:w1", "/doc", "v1")
        assert wave.flush(now=3.0).status.tolist() == [WRITE_OK]
        assert vfs.read("/doc") == "v1"
