"""Facade-wired ring elevation: one grant, both planes.

The reference exports RingElevationManager but never wires it into the
Hypervisor (`SURVEY §1 "exported but not wired"`); here
`Hypervisor.grant_elevation` lands the grant in the host manager AND
the device ElevationTable, so host queries and device
`effective_rings` waves agree, revocation and expiry retire it on both
planes together, and a device refusal rolls the host grant back.
"""

from __future__ import annotations

import numpy as np
import pytest

from hypervisor_tpu import Hypervisor, SessionConfig
from hypervisor_tpu.models import ExecutionRing
from hypervisor_tpu.rings.elevation import RingElevationError


async def _session_with(hv, *joins):
    ms = await hv.create_session(
        SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
    )
    for did, sigma in joins:
        await hv.join_session(ms.sso.session_id, did, sigma_raw=sigma)
    return ms


class TestFacadeElevation:
    async def test_grant_lands_on_both_planes(self):
        hv = Hypervisor()
        ms = await _session_with(hv, ("did:e", 0.8))  # Ring 2
        sid = ms.sso.session_id
        grant = await hv.grant_elevation(
            sid, "did:e", ExecutionRing.RING_1_PRIVILEGED, ttl_seconds=120
        )
        # Host plane.
        assert hv.elevation.get_effective_ring(
            "did:e", sid, ExecutionRing.RING_2_STANDARD
        ) is ExecutionRing.RING_1_PRIVILEGED
        # Device plane: effective_rings resolves the elevated ring.
        row = hv.state.agent_row("did:e", ms.slot)
        eff = hv.state.effective_rings(hv.state.now())
        assert eff[row["slot"]] == 1
        assert np.asarray(hv.state.agents.ring)[row["slot"]] == 2  # base kept
        assert grant.remaining_seconds > 0

    async def test_refusals_leave_device_untouched(self):
        hv = Hypervisor()
        ms = await _session_with(hv, ("did:e", 0.8))
        sid = ms.sso.session_id
        with pytest.raises(RingElevationError):  # not more privileged
            await hv.grant_elevation(
                sid, "did:e", ExecutionRing.RING_2_STANDARD
            )
        with pytest.raises(RingElevationError):  # Ring 0 unreachable
            await hv.grant_elevation(sid, "did:e", ExecutionRing.RING_0_ROOT)
        assert not np.asarray(hv.state.elevations.active).any()

        await hv.grant_elevation(sid, "did:e", ExecutionRing.RING_1_PRIVILEGED)
        with pytest.raises(RingElevationError):  # one live grant
            await hv.grant_elevation(
                sid, "did:e", ExecutionRing.RING_1_PRIVILEGED
            )
        assert int(np.asarray(hv.state.elevations.active).sum()) == 1

    async def test_revoke_retires_both_planes(self):
        hv = Hypervisor()
        ms = await _session_with(hv, ("did:e", 0.8))
        sid = ms.sso.session_id
        grant = await hv.grant_elevation(
            sid, "did:e", ExecutionRing.RING_1_PRIVILEGED
        )
        await hv.revoke_elevation(grant.elevation_id)
        assert (
            hv.elevation.get_active_elevation("did:e", sid) is None
        )
        row = hv.state.agent_row("did:e", ms.slot)
        eff = hv.state.effective_rings(hv.state.now())
        assert eff[row["slot"]] == 2  # back to base
        assert not np.asarray(hv.state.elevations.active).any()

    async def test_expiry_sweep_retires_both_planes(self):
        from datetime import datetime, timedelta, timezone

        hv = Hypervisor()
        ms = await _session_with(hv, ("did:e", 0.8))
        sid = ms.sso.session_id
        grant = await hv.grant_elevation(
            sid, "did:e", ExecutionRing.RING_1_PRIVILEGED, ttl_seconds=60
        )
        # Back-date the host grant (the repo's standard expiry-test
        # pattern) and push the device clock past the TTL.
        grant.expires_at = datetime.now(timezone.utc) - timedelta(seconds=1)
        row_slot = hv.state.agent_row("did:e", ms.slot)["slot"]
        dev_row = hv._elev_row_of[grant.elevation_id]
        from hypervisor_tpu.tables.struct import replace as t_replace

        hv.state.elevations = t_replace(
            hv.state.elevations,
            expires_at=hv.state.elevations.expires_at.at[dev_row].set(
                hv.state.now() - 1.0
            ),
        )
        expired = hv.sweep_elevations()
        assert expired == 1
        assert hv.elevation.get_active_elevation("did:e", sid) is None
        eff = hv.state.effective_rings(hv.state.now())
        assert eff[row_slot] == 2
        assert not np.asarray(hv.state.elevations.active).any()

    async def test_elevation_event_emitted(self):
        from hypervisor_tpu import EventType, HypervisorEventBus

        bus = HypervisorEventBus()
        hv = Hypervisor(event_bus=bus)
        ms = await _session_with(hv, ("did:e", 0.8))
        await hv.grant_elevation(
            ms.sso.session_id, "did:e", ExecutionRing.RING_1_PRIVILEGED,
            reason="oncall",
        )
        events = bus.query(event_type=EventType.RING_ELEVATED)
        assert len(events) == 1
        assert events[0].payload["to"] == 1


class TestElevationLifecycleScrub:
    async def test_leave_retires_the_membership_grant(self):
        hv = Hypervisor()
        ms = await _session_with(hv, ("did:e", 0.8), ("did:f", 0.8))
        sid = ms.sso.session_id
        grant = await hv.grant_elevation(
            sid, "did:e", ExecutionRing.RING_1_PRIVILEGED
        )
        slot = hv.state.agent_row("did:e", ms.slot)["slot"]
        await hv.leave_session(sid, "did:e")
        # Host grant revoked; device grant row deactivated — the freed
        # agent row's next tenant must NOT inherit Ring 1.
        assert hv.elevation.get_active_elevation("did:e", sid) is None
        assert not np.asarray(hv.state.elevations.active).any()
        assert grant.elevation_id not in hv._elev_row_of
        eff = hv.state.effective_rings(hv.state.now())
        assert eff[slot] >= 2

    async def test_terminate_retires_session_grants(self):
        hv = Hypervisor()
        ms = await _session_with(hv, ("did:e", 0.8))
        sid = ms.sso.session_id
        await hv.activate_session(sid)
        await hv.grant_elevation(sid, "did:e", ExecutionRing.RING_1_PRIVILEGED)
        await hv.terminate_session(sid)
        assert hv.elevation.get_active_elevation("did:e", sid) is None
        assert not np.asarray(hv.state.elevations.active).any()
        assert hv._elev_row_of == {}

    async def test_stale_handle_never_clobbers_recycled_row(self):
        # Reviewer-found hazard: grant G's device row is freed (leave
        # scrub) and recycled to ANOTHER agent's grant; a later revoke
        # of G must not deactivate the new tenant's elevation.
        hv = Hypervisor()
        ms = await _session_with(hv, ("did:e", 0.8), ("did:f", 0.8))
        sid = ms.sso.session_id
        g1 = await hv.grant_elevation(sid, "did:e", ExecutionRing.RING_1_PRIVILEGED)
        dev_row_1 = hv._elev_row_of[g1.elevation_id]
        # Simulate a stale mapping surviving a scrub (the facade normally
        # pops it on leave; force the hazard window explicitly).
        await hv.leave_session(sid, "did:e")
        hv._elev_row_of[g1.elevation_id] = dev_row_1  # stale handle
        # The freed elevation row recycles to did:f's new grant.
        g2 = await hv.grant_elevation(sid, "did:f", ExecutionRing.RING_1_PRIVILEGED)
        assert hv._elev_row_of[g2.elevation_id] == dev_row_1
        await hv.revoke_elevation(g1.elevation_id)
        # did:f's grant survives on both planes.
        assert hv.elevation.get_active_elevation("did:f", sid) is not None
        row_f = hv.state.agent_row("did:f", ms.slot)
        eff = hv.state.effective_rings(hv.state.now())
        assert eff[row_f["slot"]] == 1

    async def test_host_expiry_revokes_device_row_explicitly(self):
        # Host grant lapses while the device f32 TTL has NOT (clock
        # skew): the sweep must retire the device row explicitly rather
        # than waiting for coincident device expiry.
        from datetime import datetime, timedelta, timezone

        hv = Hypervisor()
        ms = await _session_with(hv, ("did:e", 0.8))
        sid = ms.sso.session_id
        grant = await hv.grant_elevation(
            sid, "did:e", ExecutionRing.RING_1_PRIVILEGED, ttl_seconds=300
        )
        grant.expires_at = datetime.now(timezone.utc) - timedelta(seconds=1)
        # Device row still far from its TTL — no device-side expiry.
        assert hv.sweep_elevations() == 1
        row = hv.state.agent_row("did:e", ms.slot)
        eff = hv.state.effective_rings(hv.state.now())
        assert eff[row["slot"]] == 2, "device kept serving a retired grant"
        assert not np.asarray(hv.state.elevations.active).any()

    async def test_lapsed_unswept_grant_leaves_no_stale_handle(self):
        # Grant lapses host-side with NO sweep; agent leaves, rejoins,
        # and gets a new grant that recycles the old device row. The
        # later sweep must not deactivate the new grant (same agent =>
        # expected_agent alone cannot catch this; the mapping purge on
        # leave must).
        from datetime import datetime, timedelta, timezone

        hv = Hypervisor()
        ms = await _session_with(hv, ("did:e", 0.8))
        sid = ms.sso.session_id
        g1 = await hv.grant_elevation(sid, "did:e", ExecutionRing.RING_1_PRIVILEGED)
        old_row = hv._elev_row_of[g1.elevation_id]
        g1.expires_at = datetime.now(timezone.utc) - timedelta(seconds=1)
        await hv.leave_session(sid, "did:e")
        assert g1.elevation_id not in hv._elev_row_of  # purged though lapsed

        ms2 = await _session_with(hv, ("did:e", 0.8))
        g2 = await hv.grant_elevation(
            ms2.sso.session_id, "did:e", ExecutionRing.RING_1_PRIVILEGED
        )
        assert hv._elev_row_of[g2.elevation_id] == old_row  # recycled
        hv.sweep_elevations()  # host-expires g1
        # g2 survives on both planes.
        assert (
            hv.elevation.get_active_elevation("did:e", ms2.sso.session_id)
            is not None
        )
        row = hv.state.agent_row("did:e", ms2.slot)
        eff = hv.state.effective_rings(hv.state.now())
        assert eff[row["slot"]] == 1

    async def test_demotion_retires_live_grant(self):
        # An operator demotion must not leave the agent holding sudo for
        # the grant's remaining TTL.
        hv = Hypervisor()
        ms = await _session_with(hv, ("did:e", 0.8))  # Ring 2
        sid = ms.sso.session_id
        await hv.grant_elevation(sid, "did:e", ExecutionRing.RING_1_PRIVILEGED)
        await hv.update_agent_ring(
            sid, "did:e", ExecutionRing.RING_3_SANDBOX, reason="suspicious"
        )
        assert hv.elevation.get_active_elevation("did:e", sid) is None
        row = hv.state.agent_row("did:e", ms.slot)
        eff = hv.state.effective_rings(hv.state.now())
        assert eff[row["slot"]] == 3, "demoted agent kept sudo ring"

    async def test_sweep_counts_facade_and_device_grants_additively(self):
        from datetime import datetime, timedelta, timezone

        from hypervisor_tpu.tables.struct import replace as t_replace

        hv = Hypervisor()
        ms = await _session_with(hv, ("did:e", 0.8), ("did:f", 0.8))
        sid = ms.sso.session_id
        g = await hv.grant_elevation(sid, "did:e", ExecutionRing.RING_1_PRIVILEGED)
        g.expires_at = datetime.now(timezone.utc) - timedelta(seconds=1)
        # A device-only grant for did:f, already past its device TTL.
        row_f = hv.state.agent_row("did:f", ms.slot)
        dev_row = hv.state.grant_elevation(
            row_f["slot"], granted_ring=1, now=hv.state.now() - 100.0,
            ttl_seconds=10.0,
        )
        assert dev_row is not None
        assert hv.sweep_elevations() == 2  # one facade + one device-only

    async def test_floor_ring_drift_still_retires_sudo(self):
        # A Ring-3 agent with a live grant drifts MEDIUM: no ring left
        # to take, but the sudo grant must still die on both planes.
        from hypervisor_tpu.integrations.cmvk_adapter import CMVKAdapter
        from tests.integration.test_stateful_coherence import _InjectableDrift

        hv = Hypervisor(cmvk=CMVKAdapter(verifier=_InjectableDrift()))
        ms = await _session_with(hv, ("did:low", 0.4))  # Ring 3
        sid = ms.sso.session_id
        await hv.grant_elevation(sid, "did:low", ExecutionRing.RING_1_PRIVILEGED)
        result = await hv.verify_behavior(
            sid, "did:low", claimed_embedding=0.35, observed_embedding=0.0
        )
        assert result.should_demote
        assert hv.elevation.get_active_elevation("did:low", sid) is None
        row = hv.state.agent_row("did:low", ms.slot)
        eff = hv.state.effective_rings(hv.state.now())
        assert eff[row["slot"]] == 3, "drifting floor-ring agent kept sudo"
