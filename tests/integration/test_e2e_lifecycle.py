"""End-to-end lifecycle through the Hypervisor facade.

Mirrors the reference's e2e coverage (`tests/integration/
test_hypervisor_e2e.py`): create -> join -> activate -> capture ->
terminate with a 64-char Merkle root; saga timeout/retry/compensation;
tamper detection; GC purge; admission edge cases.
"""

import asyncio

import pytest

from hypervisor_tpu import (
    ActionDescriptor,
    ConsistencyMode,
    ExecutionRing,
    Hypervisor,
    ReversibilityLevel,
    SessionConfig,
    SessionParticipantError,
    VFSChange,
)
from hypervisor_tpu.saga import SagaState, SagaTimeoutError, StepState


@pytest.fixture
def hv():
    return Hypervisor()


async def make_active_session(hv, n_agents=1, sigma=0.8, **config_kw):
    session = await hv.create_session(
        config=SessionConfig(**config_kw), creator_did="did:mesh:admin"
    )
    sid = session.sso.session_id
    for i in range(n_agents):
        await hv.join_session(sid, f"did:mesh:agent-{i}", sigma_raw=sigma)
    await hv.activate_session(sid)
    return session, sid


class TestLifecycle:
    async def test_full_lifecycle_with_merkle_root(self, hv):
        session, sid = await make_active_session(hv)
        for turn in range(3):
            session.delta_engine.capture(
                "did:mesh:agent-0",
                [VFSChange(path=f"/f{turn}.md", operation="add", content_hash="a" * 64)],
            )
        root = await hv.terminate_session(sid)
        assert root is not None and len(root) == 64
        assert hv.commitment.verify(sid, root)
        assert session.sso.state.value == "archived"

    async def test_audit_disabled_returns_none(self, hv):
        session, sid = await make_active_session(hv, enable_audit=False)
        session.delta_engine.capture("did:mesh:agent-0", [])
        root = await hv.terminate_session(sid)
        assert root is None

    async def test_join_assigns_ring_from_sigma(self, hv):
        session = await hv.create_session(SessionConfig(), "did:mesh:admin")
        sid = session.sso.session_id
        ring = await hv.join_session(sid, "did:mesh:good", sigma_raw=0.85)
        assert ring == ExecutionRing.RING_2_STANDARD
        ring = await hv.join_session(sid, "did:mesh:weak", sigma_raw=0.30)
        assert ring == ExecutionRing.RING_3_SANDBOX

    async def test_duplicate_join_rejected(self, hv):
        session, sid = await make_active_session(hv)
        with pytest.raises(SessionParticipantError):
            await hv.join_session(sid, "did:mesh:agent-0", sigma_raw=0.8)

    async def test_max_participants(self, hv):
        session = await hv.create_session(
            SessionConfig(max_participants=2), "did:mesh:admin"
        )
        sid = session.sso.session_id
        await hv.join_session(sid, "did:mesh:a", sigma_raw=0.8)
        await hv.join_session(sid, "did:mesh:b", sigma_raw=0.8)
        with pytest.raises(SessionParticipantError):
            await hv.join_session(sid, "did:mesh:c", sigma_raw=0.8)

    async def test_nonreversible_actions_force_strong_mode(self, hv):
        session = await hv.create_session(SessionConfig(), "did:mesh:admin")
        sid = session.sso.session_id
        actions = [
            ActionDescriptor(
                action_id="deploy",
                name="Deploy",
                execute_api="/api/deploy",
                reversibility=ReversibilityLevel.NONE,
            )
        ]
        await hv.join_session(sid, "did:mesh:a", actions=actions, sigma_raw=0.8)
        assert session.sso.consistency_mode == ConsistencyMode.STRONG


class TestSagaE2E:
    async def test_step_timeout(self, hv):
        session, sid = await make_active_session(hv)
        saga = session.saga.create_saga(sid)
        step = session.saga.add_step(
            saga.saga_id, "slow", "did:mesh:agent-0", "/api/slow", timeout_seconds=1
        )

        async def slow():
            await asyncio.sleep(10)

        with pytest.raises(SagaTimeoutError):
            await session.saga.execute_step(saga.saga_id, step.step_id, slow)
        assert step.state == StepState.FAILED

    async def test_retry_succeeds_on_third_attempt(self, hv):
        session, sid = await make_active_session(hv)
        saga = session.saga.create_saga(sid)
        step = session.saga.add_step(
            saga.saga_id, "flaky", "did:mesh:agent-0", "/api/flaky", max_retries=2
        )
        calls = {"n": 0}

        async def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("boom")
            return "ok"

        # Shrink backoff so the test runs fast.
        session.saga.DEFAULT_RETRY_DELAY_SECONDS = 0.01
        result = await session.saga.execute_step(saga.saga_id, step.step_id, flaky)
        assert result == "ok" and calls["n"] == 3
        assert step.state == StepState.COMMITTED

    async def test_reverse_order_compensation(self, hv):
        session, sid = await make_active_session(hv)
        saga = session.saga.create_saga(sid)
        s1 = session.saga.add_step(
            saga.saga_id, "step1", "did:mesh:agent-0", "/api/1", undo_api="/undo/1"
        )
        s2 = session.saga.add_step(
            saga.saga_id, "step2", "did:mesh:agent-0", "/api/2", undo_api="/undo/2"
        )
        for s in (s1, s2):
            async def ok():
                return "done"
            await session.saga.execute_step(saga.saga_id, s.step_id, ok)

        order = []

        async def compensator(step):
            order.append(step.action_id)
            return "undone"

        failed = await session.saga.compensate(saga.saga_id, compensator)
        assert failed == []
        assert order == ["step2", "step1"]
        assert saga.state == SagaState.COMPLETED

    async def test_escalation_on_missing_undo(self, hv):
        session, sid = await make_active_session(hv)
        saga = session.saga.create_saga(sid)
        step = session.saga.add_step(
            saga.saga_id, "noundo", "did:mesh:agent-0", "/api/x"
        )

        async def ok():
            return "done"

        await session.saga.execute_step(saga.saga_id, step.step_id, ok)

        async def compensator(step):
            return "undone"

        failed = await session.saga.compensate(saga.saga_id, compensator)
        assert len(failed) == 1
        assert saga.state == SagaState.ESCALATED
        assert "Joint Liability slashing triggered" in saga.error


class TestTamperDetection:
    async def test_verify_chain_detects_mutation(self, hv):
        session, sid = await make_active_session(hv)
        for i in range(4):
            session.delta_engine.capture(
                "did:mesh:agent-0",
                [VFSChange(path=f"/f{i}", operation="add", content_hash="c" * 64)],
            )
        assert session.delta_engine.verify_chain()
        # Mutate a stored delta's content.
        session.delta_engine.deltas  # view copy
        session.delta_engine._deltas[1].agent_did = "did:mesh:attacker"
        assert not session.delta_engine.verify_chain()

    async def test_verify_chain_detects_tail_mutation(self, hv):
        session, sid = await make_active_session(hv)
        for i in range(3):
            session.delta_engine.capture("did:mesh:agent-0", [])
        session.delta_engine._deltas[-1].agent_did = "did:mesh:attacker"
        assert not session.delta_engine.verify_chain()


class TestGCIntegration:
    async def test_gc_purges_vfs_on_terminate(self, hv):
        session, sid = await make_active_session(hv)
        session.sso.vfs.write("/report.md", "data", agent_did="did:mesh:agent-0")
        session.sso.vfs.write("/notes.md", "more", agent_did="did:mesh:agent-0")
        assert session.sso.vfs.file_count == 2
        await hv.terminate_session(sid)
        assert hv.gc.is_purged(sid)
        assert session.sso.vfs.file_count == 0  # actually purged

    async def test_cross_session_exposure_isolated(self, hv):
        s1, sid1 = await make_active_session(hv)
        s2, sid2 = await make_active_session(hv)
        hv.vouching.vouch("did:mesh:v", "did:mesh:agent-0", sid1, 0.9, bond_pct=0.5)
        assert hv.vouching.get_total_exposure("did:mesh:v", sid1) > 0
        assert hv.vouching.get_total_exposure("did:mesh:v", sid2) == 0.0


class TestLeaveSession:
    async def test_leave_updates_both_planes(self):
        import numpy as np

        from hypervisor_tpu import Hypervisor, SessionConfig
        from hypervisor_tpu.session import SessionParticipantError
        import pytest

        hv = Hypervisor()
        ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
        sid = ms.sso.session_id
        await hv.join_session(sid, "did:stay", sigma_raw=0.8)
        await hv.join_session(sid, "did:go", sigma_raw=0.8)
        going = hv.state.agent_row("did:go")

        await hv.leave_session(sid, "did:go")

        # Host: participant inactive, count dropped.
        assert ms.sso.participant_count == 1
        assert not ms.sso._participants["did:go"].is_active
        # Device: row freed, count matches.
        assert hv.state.agent_row("did:go") is None
        assert (
            int(np.asarray(hv.state.sessions.n_participants)[ms.slot]) == 1
        )
        assert going["slot"] in hv.state._free_agent_slots
        # Leave is terminal for the session: rejoin is a duplicate.
        with pytest.raises(SessionParticipantError):
            await hv.join_session(sid, "did:go", sigma_raw=0.9)
        # Unknown agent refuses with the reference error.
        with pytest.raises(SessionParticipantError):
            await hv.leave_session(sid, "did:ghost")

    async def test_leaver_edges_scrub_and_remirror(self):
        import numpy as np

        from hypervisor_tpu import Hypervisor, SessionConfig

        hv = Hypervisor()
        ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
        sid = ms.sso.session_id
        await hv.join_session(sid, "did:voucher", sigma_raw=0.9)
        await hv.join_session(sid, "did:vouchee", sigma_raw=0.7)
        hv.vouching.vouch("did:voucher", "did:vouchee", sid, voucher_sigma=0.9)
        assert int(np.asarray(hv.state.vouches.active).sum()) == 1

        await hv.leave_session(sid, "did:voucher")
        # Device edge scrubbed (its voucher row was freed)...
        assert int(np.asarray(hv.state.vouches.active).sum()) == 0
        # ...host bond survives...
        assert len(hv.vouching.get_vouchers_for("did:vouchee", sid)) == 1
        # ...and re-mirrors when the voucher becomes resident again.
        ms2 = await hv.create_session(SessionConfig(), creator_did="did:lead")
        await hv.join_session(ms2.sso.session_id, "did:voucher", sigma_raw=0.9)
        assert int(np.asarray(hv.state.vouches.active).sum()) == 1

    async def test_cross_session_leave_any_order_and_double_leave(self):
        import numpy as np
        import pytest

        from hypervisor_tpu import Hypervisor, SessionConfig
        from hypervisor_tpu.session import SessionParticipantError

        hv = Hypervisor()
        a = await hv.create_session(SessionConfig(), creator_did="did:lead")
        b = await hv.create_session(SessionConfig(), creator_did="did:lead")
        await hv.join_session(a.sso.session_id, "did:x", sigma_raw=0.8)
        await hv.join_session(b.sso.session_id, "did:x", sigma_raw=0.8)

        # One device row per (agent, session): leaving the EARLIER join
        # works even though a later join exists (the round-2 constraint
        # refused this; the reference's cross-session scenarios,
        # `test_hypervisor_e2e.py:499-538`, treat it as the normal case).
        await hv.leave_session(a.sso.session_id, "did:x")
        assert not a.sso.get_participant("did:x").is_active
        assert int(np.asarray(hv.state.sessions.n_participants)[a.slot]) == 0
        # Session b's membership is untouched by a's leave.
        assert b.sso.get_participant("did:x").is_active
        assert hv.state.agent_row("did:x", b.slot) is not None
        assert int(np.asarray(hv.state.sessions.n_participants)[b.slot]) == 1

        # Leave b too; double leave refuses with the reference error.
        await hv.leave_session(b.sso.session_id, "did:x")
        with pytest.raises(SessionParticipantError):
            await hv.leave_session(b.sso.session_id, "did:x")  # double leave


class TestUpdateAgentRing:
    async def test_demotion_syncs_device_and_resets_bucket(self):
        import numpy as np

        from hypervisor_tpu import (
            EventType,
            ExecutionRing,
            Hypervisor,
            HypervisorEventBus,
            SessionConfig,
        )
        from hypervisor_tpu.config import DEFAULT_CONFIG

        bus = HypervisorEventBus()
        hv = Hypervisor(event_bus=bus)
        ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
        sid = ms.sso.session_id
        await hv.join_session(sid, "did:d", sigma_raw=0.8)  # Ring 2
        row = hv.state.agent_row("did:d")
        # Drain some of the ring-2 bucket so the reset is observable.
        from hypervisor_tpu.tables.struct import replace as t_replace

        hv.state.agents = t_replace(
            hv.state.agents,
            rl_tokens=hv.state.agents.rl_tokens.at[row["slot"]].set(1.0),
        )

        await hv.update_agent_ring(
            sid, "did:d", ExecutionRing.RING_3_SANDBOX, reason="drift"
        )

        assert ms.sso.get_participant("did:d").ring is ExecutionRing.RING_3_SANDBOX
        assert int(np.asarray(hv.state.agents.ring)[row["slot"]]) == 3
        # Bucket recreated FULL at ring 3's burst (rate_limiter.py:132-149).
        assert float(np.asarray(hv.state.agents.rl_tokens)[row["slot"]]) == (
            DEFAULT_CONFIG.rate_limit.ring_bursts[3]
        )
        events = [e for e in bus.all_events
                  if e.event_type is EventType.RING_DEMOTED]
        assert len(events) == 1 and events[0].payload["reason"] == "drift"

    async def test_promotion_emits_elevated(self):
        from hypervisor_tpu import (
            EventType,
            ExecutionRing,
            Hypervisor,
            HypervisorEventBus,
            SessionConfig,
        )

        bus = HypervisorEventBus()
        hv = Hypervisor(event_bus=bus)
        ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
        sid = ms.sso.session_id
        await hv.join_session(sid, "did:u", sigma_raw=0.5)  # Ring 3
        await hv.update_agent_ring(sid, "did:u", ExecutionRing.RING_2_STANDARD)
        assert any(
            e.event_type is EventType.RING_ELEVATED for e in bus.all_events
        )


class TestSessionExpiry:
    async def test_overdue_sessions_terminate_through_audit_path(self):
        import numpy as np

        from hypervisor_tpu import Hypervisor, SessionConfig
        from hypervisor_tpu.models import SessionState

        hv = Hypervisor()
        short = await hv.create_session(
            SessionConfig(max_duration_seconds=1), creator_did="did:lead"
        )
        lasting = await hv.create_session(
            SessionConfig(max_duration_seconds=3600), creator_did="did:lead"
        )
        sid = short.sso.session_id
        await hv.join_session(sid, "did:a", sigma_raw=0.8)
        await hv.activate_session(sid)
        short.delta_engine.capture("did:a", [])

        # Not yet overdue.
        assert hv.state.session_expiry_sweep(hv.state.now()) == []
        # Push the clock past the short session's budget.
        overdue = hv.state.session_expiry_sweep(hv.state.now() + 2.0)
        assert overdue == [short.slot]

        # Facade sweep needs real elapsed time; emulate by back-dating.
        from hypervisor_tpu.tables.struct import replace as t_replace

        hv.state.sessions = t_replace(
            hv.state.sessions,
            created_at=hv.state.sessions.created_at.at[short.slot].set(
                hv.state.now() - 5.0
            ),
        )
        expired = await hv.sweep_expired_sessions()
        assert expired == [sid]
        assert short.sso.state is SessionState.ARCHIVED
        assert lasting.sso.state.value != "archived"
        # Audit ran: a commitment exists for the expired session.
        assert hv.commitment.get_commitment(sid) is not None

    async def test_unlimited_sessions_never_expire(self):
        from hypervisor_tpu import Hypervisor, SessionConfig

        hv = Hypervisor()
        await hv.create_session(
            SessionConfig(max_duration_seconds=0), creator_did="did:lead"
        )
        assert hv.state.session_expiry_sweep(hv.state.now() + 1e9) == []


class TestE2EGapParity:
    """Discrete reference e2e behaviors (`test_hypervisor_e2e.py`) not
    separately pinned above."""

    async def test_gc_tracks_purged_sessions(self):
        from hypervisor_tpu import Hypervisor, SessionConfig

        hv = Hypervisor()
        ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
        sid = ms.sso.session_id
        await hv.join_session(sid, "did:g", sigma_raw=0.8)
        await hv.activate_session(sid)
        ms.sso.vfs.write("/junk.md", "x", "did:g")
        ms.delta_engine.capture("did:g", [])
        assert not hv.gc.is_purged(sid)
        await hv.terminate_session(sid)
        assert hv.gc.is_purged(sid)
        assert len(hv.gc.history) == 1
        assert hv.gc.history[0].session_id == sid

    async def test_cannot_join_nonexistent_session_at_facade(self):
        import pytest

        from hypervisor_tpu import Hypervisor

        hv = Hypervisor()
        with pytest.raises(ValueError, match="not found"):
            await hv.join_session("session:ghost", "did:a", sigma_raw=0.8)

    async def test_merkle_root_deterministic_for_same_content(self):
        from hypervisor_tpu.audit.delta import DeltaEngine
        from hypervisor_tpu.utils.clock import ManualClock

        roots = []
        for _ in range(2):
            eng = DeltaEngine("session:det", clock=ManualClock())
            for i in range(5):
                eng.capture(f"did:d{i}", [], delta_id=f"delta:{i + 1}")
            roots.append(eng.compute_merkle_root())
        assert roots[0] == roots[1]

    async def test_multiple_concurrent_sessions_isolated_roots(self):
        from hypervisor_tpu import Hypervisor, SessionConfig

        hv = Hypervisor()
        roots = []
        for k in range(3):
            ms = await hv.create_session(
                SessionConfig(), creator_did="did:lead"
            )
            sid = ms.sso.session_id
            await hv.join_session(sid, f"did:m{k}", sigma_raw=0.8)
            await hv.activate_session(sid)
            for t in range(k + 1):
                ms.delta_engine.capture(f"did:m{k}", [])
            roots.append(await hv.terminate_session(sid))
        assert len(set(roots)) == 3  # distinct, all present
        assert all(r and len(r) == 64 for r in roots)
