"""STRONG vs EVENTUAL through the facade: the mode column is executed.

VERDICT round-2 #6: the session `mode` column must DISPATCH — EVENTUAL
sessions take the local-tick + between-tick `reconcile_sessions` path
end-to-end, and STRONG vs EVENTUAL converge to the same final table.
Reference anchor: `/root/reference/src/hypervisor/models.py:12-16` (the
flag the reference stores but never executes on) + SURVEY §5's mapping
(STRONG = in-tick allreduce on ICI, EVENTUAL = deferred reconciliation).
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from hypervisor_tpu import Hypervisor, SessionConfig
from hypervisor_tpu.models import ConsistencyMode
from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.parallel import make_mesh

N_DEV = 8
LANES = 16  # 2 per shard
T = 2


def _bodies(seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(
        0, 2**32, size=(T, LANES, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)


async def _facade_with_modes():
    hv = Hypervisor()
    strong = await hv.create_session(
        SessionConfig(
            consistency_mode=ConsistencyMode.STRONG,
            min_sigma_eff=0.0,
            max_participants=64,
        ),
        creator_did="did:lead",
    )
    eventual = await hv.create_session(
        SessionConfig(
            consistency_mode=ConsistencyMode.EVENTUAL,
            min_sigma_eff=0.0,
            max_participants=64,
        ),
        creator_did="did:lead",
    )
    return hv, strong, eventual


class TestConsistencyDispatch:
    async def test_mode_column_reflects_config(self):
        hv, strong, eventual = await _facade_with_modes()
        modes = np.asarray(hv.state.sessions.mode)
        assert modes[strong.slot] == ConsistencyMode.STRONG.code
        assert modes[eventual.slot] == ConsistencyMode.EVENTUAL.code

    async def test_eventual_defers_strong_lands_in_tick(self):
        hv, strong, eventual = await _facade_with_modes()
        mesh = make_mesh(N_DEV, platform="cpu")
        rt = hv.consistency_runtime(mesh)

        # Half the lanes target the STRONG session, half the EVENTUAL one
        # (interleaved so both modes land on every shard).
        lane_sessions = np.where(
            np.arange(LANES) % 2 == 0, strong.slot, eventual.slot
        ).astype(np.int32)
        assert rt.lane_modes(lane_sessions).sum() == LANES // 2

        before = np.asarray(hv.state.sessions.n_participants).copy()
        result = rt.tick(
            lane_sessions,
            sigma_raw=np.full(LANES, 0.8, np.float32),
            trustworthy=np.ones(LANES, bool),
            delta_bodies=_bodies(),
        )
        assert (np.asarray(result.status) == 0).all()

        after = np.asarray(hv.state.sessions.n_participants)
        # STRONG lanes' deltas landed IN-tick (consensus barrier)...
        assert after[strong.slot] - before[strong.slot] == LANES // 2
        # ...EVENTUAL lanes' did NOT (zero in-tick communication).
        assert after[eventual.slot] == before[eventual.slot]
        assert rt.has_pending

        # The consensus vector counted only STRONG lanes.
        assert float(np.asarray(result.consensus)[0]) == LANES // 2

        # Between-tick reconcile: EVENTUAL converges.
        counts, sigma = rt.reconcile()
        assert counts[eventual.slot] == LANES // 2
        assert sigma[eventual.slot] == pytest.approx(0.8 * LANES / 2, rel=1e-5)
        final = np.asarray(hv.state.sessions.n_participants)
        assert final[eventual.slot] - before[eventual.slot] == LANES // 2
        assert not rt.has_pending

    async def test_strong_and_eventual_converge_to_same_table(self):
        # Run the SAME lanes once all-STRONG and once all-EVENTUAL (+
        # reconcile); the final session tables must match.
        hv_s, strong_s, _ = await _facade_with_modes()
        hv_e, _, eventual_e = await _facade_with_modes()
        mesh = make_mesh(N_DEV, platform="cpu")

        rt_s = hv_s.consistency_runtime(mesh)
        rt_e = hv_e.consistency_runtime(mesh)
        bodies = _bodies(3)
        sigma = np.linspace(0.6, 0.95, LANES).astype(np.float32)
        trust = np.ones(LANES, bool)

        rt_s.tick(
            np.full(LANES, strong_s.slot, np.int32), sigma, trust, bodies
        )
        assert not rt_s.has_pending  # STRONG: nothing deferred

        rt_e.tick(
            np.full(LANES, eventual_e.slot, np.int32), sigma, trust, bodies
        )
        assert rt_e.has_pending
        rt_e.reconcile()

        n_s = int(np.asarray(hv_s.state.sessions.n_participants)[strong_s.slot])
        n_e = int(
            np.asarray(hv_e.state.sessions.n_participants)[eventual_e.slot]
        )
        assert n_s == n_e == LANES

    async def test_runtime_cached_per_mesh(self):
        # Pending EVENTUAL partials live on the runtime: repeated facade
        # calls must return the SAME instance or deltas already ticked
        # would be stranded on a discarded one.
        hv, _, _ = await _facade_with_modes()
        mesh = make_mesh(N_DEV, platform="cpu")
        assert hv.consistency_runtime(mesh) is hv.consistency_runtime(mesh)

    async def test_nonreversible_manifest_forces_strong_dispatch(self):
        # The reference forces STRONG when non-reversible actions register
        # (`core.py:146-147`); the forced mode must change DISPATCH, not
        # just the stored flag.
        from hypervisor_tpu.models import ActionDescriptor, ReversibilityLevel

        hv, _, eventual = await _facade_with_modes()
        sid = eventual.sso.session_id
        await hv.join_session(
            sid,
            "did:perm",
            actions=[
                ActionDescriptor(
                    action_id="drop_table",
                    name="drop table",
                    execute_api="/exec",
                    undo_api=None,
                    reversibility=ReversibilityLevel.NONE,
                )
            ],
            sigma_raw=0.9,
        )
        mesh = make_mesh(N_DEV, platform="cpu")
        rt = hv.consistency_runtime(mesh)
        lanes = np.full(LANES, eventual.slot, np.int32)
        assert rt.lane_modes(lanes).all(), (
            "forced-STRONG session still dispatching as EVENTUAL"
        )
