"""Hypervisor.check_action: every per-action gate, composed and ordered.

The reference ships quarantine isolation, the ring enforcer, the rate
limiter, and the breach detector as separate engines and leaves the
composition to callers; `check_action` is the wired pipeline —
quarantine (read-only isolation) -> effective ring (sudo grants) ->
ring enforcement -> device rate bucket -> breach recording on BOTH
planes (refused probes count).
"""

from __future__ import annotations

import numpy as np
import pytest

from hypervisor_tpu import Hypervisor, SessionConfig
from hypervisor_tpu.models import (
    ActionDescriptor,
    ExecutionRing,
    ReversibilityLevel,
)


def _action(ring3=False, **kw):
    base = dict(
        action_id="a1",
        name="write file",
        execute_api="/x",
        undo_api="/undo",
        reversibility=ReversibilityLevel.FULL,
    )
    if ring3:
        base.update(is_read_only=True)
    base.update(kw)
    return ActionDescriptor(**base)


async def _session(hv, *joins):
    ms = await hv.create_session(
        SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
    )
    for did, sigma in joins:
        await hv.join_session(ms.sso.session_id, did, sigma_raw=sigma)
    return ms


class TestActionGateway:
    async def test_allowed_action_burns_a_token_and_records(self):
        hv = Hypervisor()
        ms = await _session(hv, ("did:a", 0.8))  # Ring 2
        sid = ms.sso.session_id
        result = await hv.check_action(sid, "did:a", _action())
        assert result.allowed and result.reason == "allowed"
        assert result.effective_ring is ExecutionRing.RING_2_STANDARD
        # Both planes recorded the call.
        from hypervisor_tpu.ops import security_ops

        row = hv.state.agent_row("did:a", ms.slot)
        calls, _ = security_ops.window_totals(
            hv.state.agents.bd_window, hv.state.now(), hv.state.config.breach
        )
        assert int(np.asarray(calls)[row["slot"]]) == 1
        assert hv.breach_detector.get_agent_stats("did:a", sid)["total_calls"] == 1

    async def test_quarantined_membership_is_read_only(self):
        from hypervisor_tpu.liability.quarantine import QuarantineReason

        hv = Hypervisor()
        ms = await _session(hv, ("did:q", 0.8))
        sid = ms.sso.session_id
        row = hv.state.agent_row("did:q", ms.slot)
        hv.quarantine.quarantine(
            "did:q", sid, QuarantineReason.MANUAL, details="hold"
        )
        hv.state.quarantine_rows([row["slot"]], now=hv.state.now())

        denied = await hv.check_action(sid, "did:q", _action())
        assert not denied.allowed and denied.quarantined
        # Read-only actions still serve (isolation, not a ban).
        reads = await hv.check_action(sid, "did:q", _action(ring3=True))
        assert reads.allowed

    async def test_sudo_grant_clears_a_privileged_action(self):
        hv = Hypervisor()
        ms = await _session(hv, ("did:s", 0.97))  # Ring 2 (no consensus)
        sid = ms.sso.session_id
        privileged = _action(
            undo_api=None, reversibility=ReversibilityLevel.NONE
        )  # required ring 1
        refused = await hv.check_action(
            sid, "did:s", privileged, has_consensus=True
        )
        assert not refused.allowed  # base Ring 2 < required Ring 1

        await hv.grant_elevation(sid, "did:s", ExecutionRing.RING_1_PRIVILEGED)
        granted = await hv.check_action(
            sid, "did:s", privileged, has_consensus=True
        )
        assert granted.allowed
        assert granted.effective_ring is ExecutionRing.RING_1_PRIVILEGED

    async def test_rate_limit_exhausts_and_emits(self):
        from hypervisor_tpu import EventType, HypervisorEventBus

        bus = HypervisorEventBus()
        hv = Hypervisor(event_bus=bus)
        from hypervisor_tpu.tables.struct import replace as t_replace

        ms = await _session(hv, ("did:r", 0.4))  # Ring 3 sandbox
        sid = ms.sso.session_id
        slot = hv.state.agent_row("did:r", ms.slot)["slot"]
        # Deterministic drain: 3 tokens in the bucket and a FAR-FUTURE
        # stamp, so wall-clock refill between calls is exactly zero
        # (consume clamps elapsed at >= 0).
        hv.state.agents = t_replace(
            hv.state.agents,
            rl_tokens=hv.state.agents.rl_tokens.at[slot].set(3.0),
            rl_stamp=hv.state.agents.rl_stamp.at[slot].set(
                hv.state.now() + 3600.0
            ),
        )
        outcomes = []
        for _ in range(5):
            outcomes.append(
                (
                    await hv.check_action(sid, "did:r", _action(ring3=True))
                ).allowed
            )
            # consume resets the stamp to `now`; re-pin it so the NEXT
            # call also sees zero wall-clock refill (deterministic).
            hv.state.agents = t_replace(
                hv.state.agents,
                rl_stamp=hv.state.agents.rl_stamp.at[slot].set(
                    hv.state.now() + 3600.0
                ),
            )
        assert outcomes == [True, True, True, False, False]
        refused = [r for r in outcomes if not r]
        assert len(refused) >= 1
        assert len(bus.query(event_type=EventType.RATE_LIMITED)) >= 1

    async def test_refused_probes_count_toward_breach(self):
        hv = Hypervisor()
        ms = await _session(hv, ("did:p", 0.7))  # Ring 2
        sid = ms.sso.session_id
        admin = _action(
            is_admin=True, undo_api=None,
            reversibility=ReversibilityLevel.NONE,
        )  # required ring 0
        breach = None
        for _ in range(8):
            result = await hv.check_action(sid, "did:p", admin)
            assert not result.allowed
            breach = result.breach_event or breach
            if result.breaker_tripped:
                break  # probing tripped the breaker mid-loop — the point
        # Repeated privileged probing crossed an anomaly threshold.
        assert breach is not None
        from hypervisor_tpu.ops import security_ops

        row = hv.state.agent_row("did:p", ms.slot)
        # Every PRE-trip probe was recorded on the device plane too
        # (min_calls_for_analysis probes are needed before the ladder).
        _, priv = security_ops.window_totals(
            hv.state.agents.bd_window, hv.state.now(), hv.state.config.breach
        )
        assert int(np.asarray(priv)[row["slot"]]) >= 5

    async def test_tripped_breaker_refuses_until_cooldown(self):
        hv = Hypervisor()
        ms = await _session(hv, ("did:b", 0.7))
        sid = ms.sso.session_id
        admin = _action(
            is_admin=True, undo_api=None,
            reversibility=ReversibilityLevel.NONE,
        )
        # Probe until the breaker trips...
        for _ in range(12):
            await hv.check_action(sid, "did:b", admin)
        assert hv.breach_detector.is_breaker_tripped("did:b", sid)
        # ...after which even benign read-only actions refuse (gate 1).
        result = await hv.check_action(sid, "did:b", _action(ring3=True))
        assert not result.allowed and result.breaker_tripped

    async def test_duplicate_slots_settle_sequentially(self):
        # Device twin of the host limiter's check_many duplicate rule
        # (`security/rate_limiter.py:160-166`): k-th call on one bucket
        # allowed iff the refilled level covers k tokens.
        from hypervisor_tpu.tables.struct import replace as t_replace

        hv = Hypervisor()
        ms = await _session(hv, ("did:d", 0.8))
        slot = hv.state.agent_row("did:d", ms.slot)["slot"]
        now = hv.state.now()
        hv.state.agents = t_replace(
            hv.state.agents,
            rl_tokens=hv.state.agents.rl_tokens.at[slot].set(1.4),
            rl_stamp=hv.state.agents.rl_stamp.at[slot].set(now),
        )
        allowed = hv.state.consume_rate([slot, slot, slot], now)
        assert allowed.tolist() == [True, False, False]
        assert float(np.asarray(hv.state.agents.rl_tokens)[slot]) == (
            pytest.approx(0.4, abs=1e-3)
        )

    async def test_sudo_grant_rates_at_elevated_budget(self):
        from hypervisor_tpu.tables.struct import replace as t_replace

        hv = Hypervisor()
        ms = await _session(hv, ("did:v", 0.4))  # Ring 3: burst 10
        sid = ms.sso.session_id
        await hv.grant_elevation(sid, "did:v", ExecutionRing.RING_2_STANDARD)
        slot = hv.state.agent_row("did:v", ms.slot)["slot"]
        now = hv.state.now()
        # 15 tokens would exceed Ring 3's burst cap but fits Ring 2's 40;
        # rated at the ELEVATED ring, all 12 calls clear.
        hv.state.agents = t_replace(
            hv.state.agents,
            rl_tokens=hv.state.agents.rl_tokens.at[slot].set(15.0),
            rl_stamp=hv.state.agents.rl_stamp.at[slot].set(now),
        )
        outcomes = [
            (await hv.check_action(sid, "did:v", _action(ring3=True))).allowed
            for _ in range(12)
        ]
        assert all(outcomes), "elevated budget should cover all 12 calls"
