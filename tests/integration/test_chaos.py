"""Chaos runs: the device saga scheduler under seeded random faults.

Every saga must reach a terminal state, retry budgets must absorb
transient failures, exhausted steps must unwind through compensation,
and the whole run must be reproducible from its seed.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from hypervisor_tpu.models import SessionConfig
from hypervisor_tpu.ops import saga_ops
from hypervisor_tpu.runtime.saga_scheduler import SagaScheduler
from hypervisor_tpu.state import HypervisorState
from hypervisor_tpu.testing import ChaosExecutorFactory, ChaosPlan


def _run_fleet(seed: float, fail_rate: float, n_sagas: int = 8, n_steps: int = 4):
    st = HypervisorState()
    sess = st.create_session("session:chaos", SessionConfig())
    chaos = ChaosExecutorFactory(ChaosPlan(seed=seed, fail_rate=fail_rate))
    sched = SagaScheduler(st, retry_backoff_seconds=0.0)
    completions: list[str] = []

    for g in range(n_sagas):
        slot = st.create_saga(
            f"saga:chaos{g}",
            sess,
            [
                {"retries": 2, "has_undo": True, "timeout": 5.0}
                for _ in range(n_steps)
            ],
        )
        for i in range(n_steps):
            async def work(g=g, i=i):
                completions.append(f"{g}.{i}")
                return "ok"

            async def undo(g=g, i=i):
                completions.append(f"undo:{g}.{i}")
                return "undone"

            sched.register(slot, i, chaos.wrap(work, key=f"{g}.{i}"), undo=undo)

    asyncio.run(sched.run_until_settled())
    return st, chaos, completions, n_sagas


def test_every_saga_terminal_under_chaos():
    st, chaos, _, n = _run_fleet(seed=11, fail_rate=0.25)
    states = np.asarray(st.sagas.saga_state)[:n]
    terminal = {saga_ops.SAGA_COMPLETED, saga_ops.SAGA_ESCALATED,
                saga_ops.SAGA_FAILED}
    assert all(int(s) in terminal for s in states), states
    assert chaos.stats.failures > 0  # the chaos actually bit


def test_retry_budgets_absorb_low_fault_rate():
    st, chaos, _, n = _run_fleet(seed=3, fail_rate=0.10)
    states = np.asarray(st.sagas.saga_state)[:n]
    # With 2 retries per step and a 10% fault rate, (almost) everything
    # should complete forward; assert a strong majority did.
    completed = int((states == saga_ops.SAGA_COMPLETED).sum())
    assert completed >= n - 1, (completed, states.tolist())


def test_exhausted_steps_compensate_committed_prefix():
    st, chaos, completions, n = _run_fleet(seed=1234, fail_rate=0.55)
    step_state = np.asarray(st.sagas.step_state)
    saga_state = np.asarray(st.sagas.saga_state)
    for g in range(n):
        if int(saga_state[g]) == saga_ops.SAGA_COMPLETED:
            continue
        # A saga that gave up must hold no COMMITTED steps (all undone).
        assert not (step_state[g] == saga_ops.STEP_COMMITTED).any()
    # Some compensation actually ran at this fault rate.
    assert any(c.startswith("undo:") for c in completions)


def test_chaos_replays_identically_from_seed():
    st1, chaos1, _, n = _run_fleet(seed=99, fail_rate=0.3)
    st2, chaos2, _, _ = _run_fleet(seed=99, fail_rate=0.3)
    np.testing.assert_array_equal(
        np.asarray(st1.sagas.saga_state)[:n],
        np.asarray(st2.sagas.saga_state)[:n],
    )
    np.testing.assert_array_equal(
        np.asarray(st1.sagas.step_state)[:n],
        np.asarray(st2.sagas.step_state)[:n],
    )
    assert chaos1.report() == chaos2.report()


def test_hang_injection_hits_step_timeout():
    st = HypervisorState()
    sess = st.create_session("session:hang", SessionConfig())
    slot = st.create_saga(
        "saga:hang", sess, [{"retries": 0, "has_undo": False, "timeout": 0.05}]
    )
    chaos = ChaosExecutorFactory(
        ChaosPlan(seed=0, fail_rate=0.0, hang_rate=1.0, hang_seconds=5.0)
    )
    sched = SagaScheduler(st, retry_backoff_seconds=0.0)

    async def fine():
        return "ok"

    sched.register(slot, 0, chaos.wrap(fine, key="h"))
    asyncio.run(sched.run_until_settled())
    # The hang ate the timeout; no undo API -> saga escalates... with no
    # committed steps it settles COMPLETED after compensating nothing.
    assert chaos.stats.hangs == 1
    state = int(np.asarray(st.sagas.saga_state)[slot])
    assert state in (saga_ops.SAGA_COMPLETED, saga_ops.SAGA_ESCALATED)
    assert int(np.asarray(st.sagas.step_state)[slot, 0]) == saga_ops.STEP_FAILED
