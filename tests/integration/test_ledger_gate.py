"""Facade-wired liability ledger: persistent risk gates admission.

The reference exports the LiabilityLedger but never consults it
(`SURVEY §1 "exported but not wired"`); here verify_behavior slashes
charge the ledger (rogue + cascaded vouchers + quarantine), clean
terminations credit it, and join_session applies the recommendation —
deny refuses, probation joins sandboxed at Ring 3 on BOTH planes, and
the membership row carries the risk score.
"""

from __future__ import annotations

import numpy as np
import pytest

from hypervisor_tpu import Hypervisor, SessionConfig
from hypervisor_tpu.integrations.cmvk_adapter import CMVKAdapter
from hypervisor_tpu.session import SessionParticipantError
from tests.integration.test_stateful_coherence import _InjectableDrift


def _hv():
    return Hypervisor(cmvk=CMVKAdapter(verifier=_InjectableDrift()))


async def _slash_in_fresh_session(hv, did, drift=0.95):
    ms = await hv.create_session(
        SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
    )
    await hv.join_session(ms.sso.session_id, did, sigma_raw=0.8)
    await hv.verify_behavior(
        ms.sso.session_id, did, claimed_embedding=drift, observed_embedding=0.0
    )
    return ms


class TestLedgerGate:
    async def test_slash_charges_and_probation_sandboxes(self):
        hv = _hv()
        # One slash charges ~0.24 (slash 0.15x0.95 + quarantine
        # 0.10x0.95) — still "admit" per the reference thresholds; a
        # second pushes past the 0.3 probation line.
        await _slash_in_fresh_session(hv, "did:r")
        assert hv.ledger.compute_risk_profile("did:r").recommendation == "admit"
        await _slash_in_fresh_session(hv, "did:r")
        profile = hv.ledger.compute_risk_profile("did:r")
        assert profile.recommendation == "probation"

        ms2 = await hv.create_session(
            SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
        )
        ring = await hv.join_session(ms2.sso.session_id, "did:r", sigma_raw=0.9)
        assert ring.value == 3, "probation must sandbox"
        row = hv.state.agent_row("did:r", ms2.slot)
        assert row["ring"] == 3
        # The membership row carries the ledger risk.
        risk_col = np.asarray(hv.state.agents.risk_score)
        assert risk_col[row["slot"]] == pytest.approx(
            profile.risk_score, rel=1e-5
        )

    async def test_repeat_offender_denied(self):
        hv = _hv()
        for _ in range(3):
            await _slash_in_fresh_session(hv, "did:rogue")
        assert hv.ledger.compute_risk_profile("did:rogue").recommendation == "deny"
        ms = await hv.create_session(
            SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
        )
        with pytest.raises(SessionParticipantError, match="liability ledger"):
            await hv.join_session(ms.sso.session_id, "did:rogue", sigma_raw=0.9)
        # Refusal leaves no trace on either plane.
        assert hv.state.agent_row("did:rogue", ms.slot) is None
        assert (
            int(np.asarray(hv.state.sessions.n_participants)[ms.slot]) == 0
        )

    async def test_cascaded_vouchers_charged(self):
        hv = _hv()
        ms = await hv.create_session(
            SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
        )
        sid = ms.sso.session_id
        await hv.join_session(sid, "did:rogue", sigma_raw=0.6)
        await hv.join_session(sid, "did:backer", sigma_raw=0.9)
        hv.vouching.vouch("did:backer", "did:rogue", sid, voucher_sigma=0.9)
        await hv.verify_behavior(
            sid, "did:rogue", claimed_embedding=0.95, observed_embedding=0.0
        )
        backer = hv.ledger.compute_risk_profile("did:backer")
        assert backer.risk_score > 0.0, "clipped voucher must be charged"

    async def test_clean_sessions_credit_risk_down(self):
        hv = _hv()
        await _slash_in_fresh_session(hv, "did:redeemed")
        risk_after_slash = hv.ledger.compute_risk_profile(
            "did:redeemed"
        ).risk_score
        # Serve several clean sessions (probation: sandboxed but admitted).
        for i in range(4):
            ms = await hv.create_session(
                SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
            )
            await hv.join_session(
                ms.sso.session_id, "did:redeemed", sigma_raw=0.8
            )
            await hv.activate_session(ms.sso.session_id)
            await hv.terminate_session(ms.sso.session_id)
        profile = hv.ledger.compute_risk_profile("did:redeemed")
        assert profile.risk_score < risk_after_slash

    async def test_cascaded_voucher_earns_no_clean_credit(self):
        # Reviewer-found: the clipped backer must NOT also collect the
        # clean-session credit for the session that penalized it.
        hv = _hv()
        ms = await hv.create_session(
            SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
        )
        sid = ms.sso.session_id
        await hv.join_session(sid, "did:rogue", sigma_raw=0.6)
        await hv.join_session(sid, "did:backer", sigma_raw=0.9)
        hv.vouching.vouch("did:backer", "did:rogue", sid, voucher_sigma=0.9)
        await hv.activate_session(sid)
        await hv.verify_behavior(
            sid, "did:rogue", claimed_embedding=0.95, observed_embedding=0.0
        )
        risk_before_term = hv.ledger.compute_risk_profile(
            "did:backer"
        ).risk_score
        await hv.terminate_session(sid)
        after = hv.ledger.compute_risk_profile("did:backer")
        assert after.risk_score == pytest.approx(risk_before_term), (
            "penalized backer collected a clean-session credit"
        )
        kinds = [
            e.entry_type.value
            for e in hv.ledger.get_agent_history("did:backer")
        ]
        assert "clean_session" not in kinds
        # ...and the session's penalty index does not leak.
        assert sid not in hv._penalized_in


class TestAttributionWiring:
    async def test_attribution_charges_ledger_shares(self):
        from hypervisor_tpu import EventType, HypervisorEventBus

        bus = HypervisorEventBus()
        hv = Hypervisor(event_bus=bus)
        ms = await hv.create_session(
            SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
        )
        sid = ms.sso.session_id
        for did in ("did:root", "did:enabler"):
            await hv.join_session(sid, did, sigma_raw=0.8)
        await hv.activate_session(sid)

        result = hv.attribute_fault(
            saga_id="saga:f",
            session_id=sid,
            agent_actions={
                "did:root": [{"action_id": "a1", "step_id": "s2",
                              "success": False}],
                "did:enabler": [{"action_id": "a0", "step_id": "s1",
                                 "success": True,
                                 "dependencies": []}],
            },
            failure_step_id="s2",
            failure_agent_did="did:root",
        )
        shares = {f.agent_did: f.liability_score for f in result.attributions}
        assert sum(shares.values()) == pytest.approx(1.0)
        assert shares["did:root"] > shares.get("did:enabler", 0.0)
        # Ledger charged proportionally; both marked penalized.
        assert hv.ledger.compute_risk_profile("did:root").risk_score > 0
        kinds = [
            e.entry_type.value for e in hv.ledger.get_agent_history("did:root")
        ]
        assert "fault_attributed" in kinds
        ev = bus.query(event_type=EventType.FAULT_ATTRIBUTED)
        assert len(ev) == 1 and "did:root" in ev[0].payload["shares"]

        # Clean-credit skips the attributed agents at terminate.
        await hv.terminate_session(sid)
        kinds = [
            e.entry_type.value for e in hv.ledger.get_agent_history("did:root")
        ]
        assert "clean_session" not in kinds

    async def test_global_slash_forfeits_clean_credit_everywhere(self):
        # Reviewer-found: a rogue slashed in A is blacklisted in B too
        # (agent-global); B's termination must NOT hand it a clean
        # credit that offsets the slash charge.
        hv = _hv()
        a = await hv.create_session(
            SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
        )
        b = await hv.create_session(
            SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
        )
        await hv.join_session(a.sso.session_id, "did:r", sigma_raw=0.8)
        await hv.join_session(b.sso.session_id, "did:r", sigma_raw=0.8)
        await hv.activate_session(b.sso.session_id)
        await hv.verify_behavior(
            a.sso.session_id, "did:r",
            claimed_embedding=0.95, observed_embedding=0.0,
        )
        risk = hv.ledger.compute_risk_profile("did:r").risk_score
        await hv.terminate_session(b.sso.session_id)
        assert hv.ledger.compute_risk_profile("did:r").risk_score == (
            pytest.approx(risk)
        ), "other-session clean credit offset the slash"

    async def test_denied_join_does_not_mutate_session(self):
        # Reviewer-found: the deny gate must fire BEFORE manifest
        # processing — a refused rogue's non-reversible manifest must
        # not force the session into STRONG or register actions.
        from hypervisor_tpu.models import (
            ActionDescriptor,
            ConsistencyMode,
            ReversibilityLevel,
        )

        hv = _hv()
        for _ in range(3):
            await _slash_in_fresh_session(hv, "did:rogue")
        ms = await hv.create_session(
            SessionConfig(
                consistency_mode=ConsistencyMode.EVENTUAL, min_sigma_eff=0.0
            ),
            creator_did="did:lead",
        )
        with pytest.raises(SessionParticipantError, match="liability ledger"):
            await hv.join_session(
                ms.sso.session_id,
                "did:rogue",
                sigma_raw=0.9,
                actions=[
                    ActionDescriptor(
                        action_id="nuke",
                        name="nuke",
                        execute_api="/x",
                        undo_api=None,
                        reversibility=ReversibilityLevel.NONE,
                    )
                ],
            )
        assert ms.sso.config.consistency_mode is ConsistencyMode.EVENTUAL
        assert not ms.reversibility.has_non_reversible_actions()
        modes = np.asarray(hv.state.sessions.mode)
        assert modes[ms.slot] == ConsistencyMode.EVENTUAL.code

    async def test_post_mortem_attribution_charges_without_leak(self):
        hv = _hv()
        ms = await hv.create_session(
            SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
        )
        sid = ms.sso.session_id
        await hv.join_session(sid, "did:x", sigma_raw=0.8)
        await hv.activate_session(sid)
        await hv.terminate_session(sid)
        hv.attribute_fault(
            saga_id="saga:pm",
            session_id=sid,
            agent_actions={"did:x": [{"step_id": "s1", "success": False}]},
            failure_step_id="s1",
            failure_agent_did="did:x",
        )
        kinds = [
            e.entry_type.value for e in hv.ledger.get_agent_history("did:x")
        ]
        assert "fault_attributed" in kinds  # charge landed post-mortem
        assert sid not in hv._penalized_in  # no dead-key leak
        with pytest.raises(ValueError):
            hv.attribute_fault(
                saga_id="s", session_id="session:ghost",
                agent_actions={}, failure_step_id="s",
                failure_agent_did="did:x",
            )

    async def test_global_slash_skips_archived_sessions(self):
        # Reviewer-found leak: a slash must not re-create the popped
        # penalty key of an ARCHIVED session the rogue once sat in.
        hv = _hv()
        a = await hv.create_session(
            SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
        )
        b = await hv.create_session(
            SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
        )
        await hv.join_session(a.sso.session_id, "did:r", sigma_raw=0.8)
        await hv.join_session(b.sso.session_id, "did:r", sigma_raw=0.8)
        await hv.activate_session(b.sso.session_id)
        await hv.terminate_session(b.sso.session_id)  # pops B's key
        await hv.verify_behavior(
            a.sso.session_id, "did:r",
            claimed_embedding=0.95, observed_embedding=0.0,
        )
        assert b.sso.session_id not in hv._penalized_in
        assert "did:r" in hv._penalized_in[a.sso.session_id]

    async def test_post_mortem_slash_leaves_no_penalty_key(self):
        # Reviewer-found: slashing via a session that ALREADY archived
        # must charge the ledger but not resurrect the popped key.
        hv = _hv()
        ms = await hv.create_session(
            SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
        )
        sid = ms.sso.session_id
        await hv.join_session(sid, "did:late", sigma_raw=0.8)
        await hv.activate_session(sid)
        await hv.terminate_session(sid)
        await hv.verify_behavior(
            sid, "did:late", claimed_embedding=0.95, observed_embedding=0.0
        )
        assert sid not in hv._penalized_in
        assert hv.ledger.compute_risk_profile("did:late").risk_score > 0
