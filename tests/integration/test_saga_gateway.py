"""Saga steps pass the isolation gates: mid-saga quarantine/breaker
refuses the NEXT step, on both planes.

The reference ships quarantine isolation and the circuit breaker but
never consults them on the saga path — a quarantined agent's in-flight
saga keeps executing (`saga/orchestrator.py:104-143` has no gate). Here
the facade wires every ManagedSession's orchestrator with the live
gates (`Hypervisor._saga_gate`), and the device scheduler consults
`HypervisorState.isolation_refusal` for steps registered with their
acting agent's row.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from hypervisor_tpu import Hypervisor, SessionConfig
from hypervisor_tpu.saga.orchestrator import SagaGateRefused
from hypervisor_tpu.saga.state_machine import StepState


class TestHostPlaneSagaGate:
    async def test_mid_saga_quarantine_refuses_next_step(self):
        from hypervisor_tpu.liability.quarantine import QuarantineReason

        hv = Hypervisor()
        ms = await hv.create_session(
            SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
        )
        sid = ms.sso.session_id
        await hv.join_session(sid, "did:worker", sigma_raw=0.8)

        saga = ms.saga.create_saga(sid)
        s1 = ms.saga.add_step(
            saga.saga_id, action_id="a1", agent_did="did:worker",
            execute_api="/x", undo_api="/u",
        )
        s2 = ms.saga.add_step(
            saga.saga_id, action_id="a2", agent_did="did:worker",
            execute_api="/x", undo_api="/u",
        )

        ran = []

        async def ok():
            ran.append("ran")
            return "ok"

        await ms.saga.execute_step(saga.saga_id, s1.step_id, ok)
        assert ran == ["ran"]

        # Quarantine mid-saga, both planes (the facade's quarantine path).
        row = hv.state.agent_row("did:worker", ms.slot)
        hv.quarantine.quarantine(
            "did:worker", sid, QuarantineReason.MANUAL, details="hold"
        )
        hv.state.quarantine_rows([row["slot"]], now=hv.state.now())

        with pytest.raises(SagaGateRefused, match="quarantined"):
            await ms.saga.execute_step(saga.saga_id, s2.step_id, ok)
        assert ran == ["ran"], "refused step's executor must never run"
        # The refusal is a gate outcome, not an execution outcome: the
        # step stays PENDING (re-refusable now, executable once the
        # hold clears) with the reason recorded.
        assert s2.state is StepState.PENDING
        assert "quarantined" in s2.error

        # Second attempt while still held: refuses again, no crash.
        with pytest.raises(SagaGateRefused, match="quarantined"):
            await ms.saga.execute_step(saga.saga_id, s2.step_id, ok)

        # Release the quarantine on both planes: the step now executes.
        hv.quarantine.release("did:worker", sid)
        import numpy as np
        from hypervisor_tpu.tables.state import FLAG_QUARANTINED
        from hypervisor_tpu.tables.struct import replace as t_replace

        slot = row["slot"]
        hv.state.agents = t_replace(
            hv.state.agents,
            flags=hv.state.agents.flags.at[slot].set(
                int(np.asarray(hv.state.agents.flags)[slot])
                & ~FLAG_QUARANTINED
            ),
        )
        assert (await ms.saga.execute_step(saga.saga_id, s2.step_id, ok)) == "ok"
        assert ran == ["ran", "ran"]

    async def test_tripped_breaker_refuses_step(self):
        from hypervisor_tpu.models import ActionDescriptor, ReversibilityLevel

        hv = Hypervisor()
        ms = await hv.create_session(
            SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
        )
        sid = ms.sso.session_id
        await hv.join_session(sid, "did:prober", sigma_raw=0.7)

        # Trip the breaker by privileged probing through the gateway.
        admin = ActionDescriptor(
            action_id="adm", name="a", execute_api="/x", undo_api=None,
            is_admin=True, reversibility=ReversibilityLevel.NONE,
        )
        for _ in range(8):
            await hv.check_action(sid, "did:prober", admin)
        assert hv.breach_detector.is_breaker_tripped("did:prober", sid)

        saga = ms.saga.create_saga(sid)
        s1 = ms.saga.add_step(
            saga.saga_id, action_id="a1", agent_did="did:prober",
            execute_api="/x", undo_api="/u",
        )

        async def ok():
            return "ok"

        with pytest.raises(SagaGateRefused, match="breaker"):
            await ms.saga.execute_step(saga.saga_id, s1.step_id, ok)


class TestDevicePlaneSagaGate:
    def test_mid_saga_quarantine_fails_step_and_compensates(self):
        from hypervisor_tpu.ops import saga_ops
        from hypervisor_tpu.runtime.saga_scheduler import SagaScheduler
        from hypervisor_tpu.state import HypervisorState

        st = HypervisorState()
        sess = st.create_session("sg:dev", SessionConfig(min_sigma_eff=0.0))
        st.enqueue_join(sess, "did:dev", sigma_raw=0.8)
        assert (st.flush_joins(now=1.0) == 0).all()
        agent_slot = 0

        g = st.create_saga(
            "saga:gated", sess,
            [{"has_undo": True}, {"has_undo": True}],
        )
        sched = SagaScheduler(st, retry_backoff_seconds=0.0)
        ran = []

        async def step0():
            # Quarantine the acting agent DURING step 0: step 1 must
            # refuse at dispatch, executor never running.
            ran.append(0)
            st.quarantine_rows([agent_slot], now=st.now())
            return "ok"

        async def step1():
            ran.append(1)
            return "ok"

        async def undo():
            return "undone"

        sched.register(g, 0, step0, undo=undo, agent_slot=agent_slot)
        sched.register(g, 1, step1, undo=undo, agent_slot=agent_slot)
        asyncio.run(sched.run_until_settled())

        assert ran == [0], "quarantined step's executor must never run"
        assert "quarantined" in sched.errors[(g, 1)]
        states = np.asarray(st.sagas.step_state)[g]
        # Step 1 failed at the gate; step 0's committed work compensated
        # (the undo RUNS for the isolated agent — its side effects must
        # remain undoable).
        assert states[1] == saga_ops.STEP_FAILED
        assert states[0] == saga_ops.STEP_COMPENSATED
        # Clean compensation settles the saga (the device plane's
        # terminal for a fully-compensated run).
        assert int(np.asarray(st.sagas.saga_state)[g]) == (
            saga_ops.SAGA_COMPLETED
        )

    def test_handoff_drops_victim_gate_binding(self):
        """A kill-switch style reassign must not gate the substitute on
        the VICTIM's quarantine: the binding clears on reassign (and can
        re-arm on the substitute's own row)."""
        from hypervisor_tpu.ops import saga_ops
        from hypervisor_tpu.runtime.saga_scheduler import SagaScheduler
        from hypervisor_tpu.state import HypervisorState

        st = HypervisorState()
        sess = st.create_session("sg:ho", SessionConfig(min_sigma_eff=0.0))
        st.enqueue_join(sess, "did:victim", sigma_raw=0.8)
        st.enqueue_join(sess, "did:sub", sigma_raw=0.8)
        assert (st.flush_joins(now=1.0) == 0).all()
        victim_slot, sub_slot = 0, 1

        g = st.create_saga("saga:handoff", sess, [{"has_undo": True}])
        sched = SagaScheduler(st, retry_backoff_seconds=0.0)
        ran = []

        async def victim_exec():
            ran.append("victim")
            return "ok"

        async def sub_exec():
            ran.append("sub")
            return "ok"

        sched.register(g, 0, victim_exec, agent_slot=victim_slot)
        # Victim quarantined BEFORE the saga runs; its step hands off.
        st.quarantine_rows([victim_slot], now=st.now())
        sched.reassign(g, 0, sub_exec, agent_slot=sub_slot)
        asyncio.run(sched.run_until_settled())

        assert ran == ["sub"], ran
        states = np.asarray(st.sagas.step_state)[g]
        assert states[0] == saga_ops.STEP_COMMITTED

    def test_ungated_registration_unchanged(self):
        from hypervisor_tpu.ops import saga_ops
        from hypervisor_tpu.runtime.saga_scheduler import SagaScheduler
        from hypervisor_tpu.state import HypervisorState

        st = HypervisorState()
        sess = st.create_session("sg:un", SessionConfig(min_sigma_eff=0.0))
        st.enqueue_join(sess, "did:un", sigma_raw=0.8)
        assert (st.flush_joins(now=1.0) == 0).all()
        st.quarantine_rows([0], now=st.now())

        g = st.create_saga("saga:ungated", sess, [{"has_undo": False}])
        sched = SagaScheduler(st, retry_backoff_seconds=0.0)

        async def ok():
            return "ok"

        # No agent_slot: runs ungated (reference behavior preserved).
        sched.register(g, 0, ok)
        asyncio.run(sched.run_until_settled())
        states = np.asarray(st.sagas.step_state)[g]
        assert states[0] == saga_ops.STEP_COMMITTED
