"""Facade-wired kill switch: handoff, then both-plane removal.

The reference exports KillSwitch but never wires it into the Hypervisor
(`security/kill_switch.py:64-180`); `Hypervisor.kill_agent` runs the
substitute handoff and then the full leave path — device row freed,
vouch edges scrubbed/re-pointed, membership elevations retired — with
an AGENT_KILLED event carrying the handoff outcome.
"""

from __future__ import annotations

import numpy as np

from hypervisor_tpu import EventType, Hypervisor, HypervisorEventBus, SessionConfig
from hypervisor_tpu.security.kill_switch import HandoffStatus, KillReason


async def _session_with(hv, *joins):
    ms = await hv.create_session(
        SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
    )
    for did, sigma in joins:
        await hv.join_session(ms.sso.session_id, did, sigma_raw=sigma)
    return ms


class TestFacadeKill:
    async def test_kill_hands_off_and_removes_membership(self):
        bus = HypervisorEventBus()
        hv = Hypervisor(event_bus=bus)
        ms = await _session_with(hv, ("did:victim", 0.8), ("did:sub", 0.9))
        sid = ms.sso.session_id
        hv.kill_switch.register_substitute(sid, "did:sub")

        result = await hv.kill_agent(
            sid,
            "did:victim",
            reason=KillReason.RING_BREACH,
            in_flight_steps=[
                {"step_id": "s1", "saga_id": "g1"},
                {"step_id": "s2", "saga_id": "g1"},
            ],
        )
        # Handoff: both steps rehomed to the substitute.
        assert result.handoff_success_count == 2
        assert all(
            h.status is HandoffStatus.HANDED_OFF and h.to_agent == "did:sub"
            for h in result.handoffs
        )
        # Membership removed on both planes.
        assert not ms.sso.get_participant("did:victim").is_active
        assert hv.state.agent_row("did:victim", ms.slot) is None
        assert int(np.asarray(hv.state.sessions.n_participants)[ms.slot]) == 1
        # Event carries the outcome.
        ev = bus.query(event_type=EventType.AGENT_KILLED)
        assert len(ev) == 1 and ev[0].payload["handed_off"] == 2

    async def test_kill_without_substitutes_compensates(self):
        hv = Hypervisor()
        ms = await _session_with(hv, ("did:victim", 0.8))
        result = await hv.kill_agent(
            ms.sso.session_id,
            "did:victim",
            in_flight_steps=[{"step_id": "s1", "saga_id": "g1"}],
        )
        assert result.compensation_triggered
        assert result.handoffs[0].status is HandoffStatus.COMPENSATED
        assert result.reason is KillReason.MANUAL

    async def test_victim_never_rescues_itself(self):
        hv = Hypervisor()
        ms = await _session_with(hv, ("did:victim", 0.8))
        sid = ms.sso.session_id
        hv.kill_switch.register_substitute(sid, "did:victim")
        result = await hv.kill_agent(
            sid, "did:victim",
            in_flight_steps=[{"step_id": "s1", "saga_id": "g1"}],
        )
        assert result.handoffs[0].status is HandoffStatus.COMPENSATED

    async def test_kill_retires_vouch_edges_and_elevations(self):
        from hypervisor_tpu.models import ExecutionRing

        hv = Hypervisor()
        ms = await _session_with(hv, ("did:victim", 0.8), ("did:other", 0.9))
        sid = ms.sso.session_id
        hv.vouching.vouch("did:other", "did:victim", sid, voucher_sigma=0.9)
        await hv.grant_elevation(
            sid, "did:victim", ExecutionRing.RING_1_PRIVILEGED
        )
        assert int(np.asarray(hv.state.vouches.active).sum()) == 1
        assert int(np.asarray(hv.state.elevations.active).sum()) == 1

        await hv.kill_agent(sid, "did:victim")
        assert int(np.asarray(hv.state.vouches.active).sum()) == 0
        assert int(np.asarray(hv.state.elevations.active).sum()) == 0
        assert (
            hv.elevation.get_active_elevation("did:victim", sid) is None
        )

    async def test_kill_validation_precedes_side_effects(self):
        # A failed kill must not log a phantom KillResult nor rotate the
        # substitute pool (reviewer-found ordering hazard).
        import pytest

        from hypervisor_tpu.session import SessionParticipantError

        hv = Hypervisor()
        ms = await _session_with(hv, ("did:a", 0.8), ("did:s", 0.9))
        sid = ms.sso.session_id
        hv.kill_switch.register_substitute(sid, "did:s")
        with pytest.raises(SessionParticipantError):
            await hv.kill_agent(sid, "did:ghost")
        assert hv.kill_switch.total_kills == 0
        assert hv.kill_switch.substitutes(sid) == ["did:s"]
        # Double-kill refuses too (the victim already left).
        await hv.kill_agent(sid, "did:a")
        with pytest.raises(SessionParticipantError):
            await hv.kill_agent(sid, "did:a")
        assert hv.kill_switch.total_kills == 1

    async def test_leave_and_terminate_clean_substitute_pools(self):
        hv = Hypervisor()
        ms = await _session_with(hv, ("did:s", 0.9), ("did:v", 0.8))
        sid = ms.sso.session_id
        await hv.activate_session(sid)
        hv.kill_switch.register_substitute(sid, "did:s")
        # A departed agent can no longer substitute.
        await hv.leave_session(sid, "did:s")
        assert hv.kill_switch.substitutes(sid) == []
        result = await hv.kill_agent(
            sid, "did:v", in_flight_steps=[{"step_id": "s1", "saga_id": "g"}]
        )
        assert result.handoffs[0].status is HandoffStatus.COMPENSATED
        # Termination drops the whole pool.
        hv.kill_switch.register_substitute(sid, "did:late")
        await hv.terminate_session(sid)
        assert sid not in hv.kill_switch._pools

    async def test_kill_with_scheduler_rewires_device_steps(self):
        # End-to-end: the facade kill rewires the victim's steps onto
        # the device saga table when given the scheduler + executors.
        import asyncio as aio

        from hypervisor_tpu.ops import saga_ops
        from hypervisor_tpu.runtime.saga_scheduler import SagaScheduler

        hv = Hypervisor()
        ms = await _session_with(hv, ("did:victim", 0.8), ("did:sub", 0.9))
        sid = ms.sso.session_id
        hv.kill_switch.register_substitute(sid, "did:sub")
        g = hv.state.create_saga(
            "saga:fk", ms.slot, [{"retries": 0}, {"retries": 0}]
        )
        sched = SagaScheduler(hv.state, retry_backoff_seconds=0.0)
        log = []

        async def dead():
            raise RuntimeError("victim is dead")

        async def sub_exec():
            log.append("sub")
            return "ok"

        sched.register(g, 0, sub_exec)   # healthy first step
        sched.register(g, 1, dead)       # victim-owned step

        await hv.kill_agent(
            sid,
            "did:victim",
            in_flight_steps=[{"step_id": "s1", "saga_id": "saga:fk"}],
            scheduler=sched,
            step_index={("saga:fk", "s1"): (g, 1)},
            substitute_executors={"did:sub": sub_exec},
        )
        await sched.run_until_settled()
        assert (
            int(np.asarray(hv.state.sagas.saga_state)[g])
            == saga_ops.SAGA_COMPLETED
        )
        assert "sub" in log

    async def test_malformed_steps_leave_pool_untouched(self):
        import pytest

        hv = Hypervisor()
        ms = await _session_with(hv, ("did:v", 0.8), ("did:s", 0.9))
        sid = ms.sso.session_id
        hv.kill_switch.register_substitute(sid, "did:s")
        with pytest.raises(TypeError):
            await hv.kill_agent(
                sid, "did:v",
                in_flight_steps=[{"step_id": "ok", "saga_id": "g"}, "oops"],
            )
        # Neither the pool nor the kill log mutated; the victim is alive.
        assert hv.kill_switch.substitutes(sid) == ["did:s"]
        assert hv.kill_switch.total_kills == 0
        assert ms.sso.get_participant("did:v").is_active
