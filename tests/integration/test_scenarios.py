"""Cross-module governance scenarios driven by mock adapters.

Mirrors the reference's scenario strategy (`tests/integration/
test_scenarios.py` in /root/reference: rogue-agent slash cascade, IATP
onboarding with STRONG forcing, drift demotion, voucher cascades, adapter
fallbacks, threshold configuration, fully-wired Hypervisor) — re-expressed
against this framework's engines. No external services: the adapter
Protocols are satisfied by the in-file mocks below.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import pytest

from hypervisor_tpu import (
    ConsistencyMode,
    EventType,
    ExecutionRing,
    Hypervisor,
    HypervisorEventBus,
    SessionConfig,
)
from hypervisor_tpu.integrations.cmvk_adapter import CMVKAdapter, DriftThresholds
from hypervisor_tpu.integrations.iatp_adapter import IATPAdapter
from hypervisor_tpu.integrations.nexus_adapter import NexusAdapter


# ── mock backing services ────────────────────────────────────────────


@dataclass
class FakeScore:
    total_score: int
    successful_tasks: int = 10
    failed_tasks: int = 0


class MockNexusScorer:
    """Score table + slash penalty bookkeeping."""

    def __init__(self, scores: dict[str, int]):
        self.scores = dict(scores)
        self.slashes: list[tuple[str, str]] = []
        self.outcomes: list[tuple[str, str]] = []
        self._current: str | None = None

    def calculate_trust_score(self, verification_level="standard", history=None,
                              capabilities=None):
        did = history if isinstance(history, str) else self._current
        return FakeScore(self.scores.get(did, 500))

    def score_for(self, did):
        self._current = did
        return self

    def slash_reputation(self, agent_did, reason, severity, evidence_hash=None):
        penalty = {"low": 50, "medium": 100, "high": 250, "critical": 500}[severity]
        self.scores[agent_did] = max(0, self.scores.get(agent_did, 500) - penalty)
        self.slashes.append((agent_did, severity))

    def record_task_outcome(self, agent_did, outcome):
        self.outcomes.append((agent_did, outcome))


@dataclass
class FakeVerdict:
    drift_score: float
    explanation: str = "mock"


class MockCMVKVerifier:
    """Injects per-agent drift scores keyed by the claimed embedding."""

    def __init__(self, drift_by_key: dict[str, float]):
        self.drift_by_key = drift_by_key
        self.calls: list[str] = []

    def verify_embeddings(self, embedding_a, embedding_b, metric="cosine",
                          threshold_profile=None, explain=False):
        key = str(embedding_a)
        self.calls.append(key)
        return FakeVerdict(self.drift_by_key.get(key, 0.0))


def manifest_dict(did: str, trust="trusted", score=8, caps=None):
    return {
        "agent_id": did,
        "trust_level": trust,
        "trust_score": score,
        "capabilities": caps or [],
    }


# ── 1. rogue agent: drift -> slash -> nexus penalty ──────────────────


async def test_rogue_agent_slash_reports_to_nexus():
    scorer = MockNexusScorer({"did:rogue": 800, "did:clean": 900})
    hv = Hypervisor(
        nexus=NexusAdapter(scorer=scorer),
        cmvk=CMVKAdapter(verifier=MockCMVKVerifier({"claimed": 0.62})),
    )
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    sid = ms.sso.session_id
    await hv.join_session(sid, "did:rogue", sigma_raw=0.8)
    await hv.join_session(sid, "did:clean", sigma_raw=0.9)
    await hv.activate_session(sid)

    result = await hv.verify_behavior(sid, "did:rogue", "claimed", "observed")
    assert result.should_slash
    # slash recorded + Nexus penalty applied at high severity
    assert hv.slashing.history[-1].vouchee_did == "did:rogue"
    assert ("did:rogue", "high") in scorer.slashes
    assert scorer.scores["did:rogue"] == 800 - 250

    ms.delta_engine.capture("did:clean", [])
    root = await hv.terminate_session(sid)
    assert root and len(root) == 64


async def test_clean_agent_passes_verification():
    hv = Hypervisor(cmvk=CMVKAdapter(verifier=MockCMVKVerifier({"claimed": 0.05})))
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    sid = ms.sso.session_id
    await hv.join_session(sid, "did:ok", sigma_raw=0.8)
    await hv.activate_session(sid)
    result = await hv.verify_behavior(sid, "did:ok", "claimed", "observed")
    assert result.passed and not result.should_slash
    assert hv.slashing.history == []


# ── 2. IATP onboarding ───────────────────────────────────────────────


async def test_iatp_manifest_sigma_hint_assigns_ring():
    hv = Hypervisor(iatp=IATPAdapter())
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    ring = await hv.join_session(
        ms.sso.session_id,
        "did:vendor",
        manifest=manifest_dict("did:vendor", trust="trusted", score=8),
    )
    # sigma hint 0.8 -> Ring 2 (no consensus)
    assert ring == ExecutionRing.RING_2_STANDARD


async def test_iatp_non_reversible_capability_forces_strong():
    hv = Hypervisor(iatp=IATPAdapter())
    ms = await hv.create_session(
        SessionConfig(consistency_mode=ConsistencyMode.EVENTUAL),
        creator_did="did:lead",
    )
    caps = [
        {"action_id": "wire", "name": "wire transfer", "execute_api": "api/wire",
         "reversibility": "none"},
    ]
    await hv.join_session(
        ms.sso.session_id,
        "did:bank",
        manifest=manifest_dict("did:bank", caps=caps),
    )
    assert ms.sso.consistency_mode == ConsistencyMode.STRONG
    assert ms.reversibility.has_non_reversible_actions()


async def test_iatp_reversible_capabilities_keep_eventual():
    hv = Hypervisor(iatp=IATPAdapter())
    ms = await hv.create_session(
        SessionConfig(consistency_mode=ConsistencyMode.EVENTUAL),
        creator_did="did:lead",
    )
    caps = [{"action_id": "note", "name": "write note", "execute_api": "api/note",
             "undo_api": "api/unnote", "reversibility": "full"}]
    await hv.join_session(
        ms.sso.session_id, "did:scribe",
        manifest=manifest_dict("did:scribe", caps=caps),
    )
    assert ms.sso.consistency_mode == ConsistencyMode.EVENTUAL


# ── 3. drift demotion (MEDIUM severity: demote, don't slash) ─────────


async def test_medium_drift_demotes_without_slashing():
    cmvk = CMVKAdapter(verifier=MockCMVKVerifier({"claimed": 0.35}))
    hv = Hypervisor(cmvk=cmvk)
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    sid = ms.sso.session_id
    await hv.join_session(sid, "did:wobbly", sigma_raw=0.85)
    await hv.activate_session(sid)
    result = await hv.verify_behavior(sid, "did:wobbly", "claimed", "observed")
    assert result.should_demote and not result.should_slash
    assert hv.slashing.history == []
    # host applies the demotion through the SSO ring update
    p = ms.sso.get_participant("did:wobbly")
    demoted = ExecutionRing(min(p.ring.value + 1, 3))
    await hv.update_agent_ring(sid, "did:wobbly", demoted, reason="drift")
    assert ms.sso.get_participant("did:wobbly").ring == demoted


async def test_drift_history_and_rate_tracking():
    cmvk = CMVKAdapter(verifier=MockCMVKVerifier({"bad": 0.8, "ok": 0.01}))
    for key in ("bad", "ok", "ok", "bad"):
        cmvk.check_behavioral_drift("did:x", "session:1", key, "obs")
    assert cmvk.total_checks == 4
    assert cmvk.total_violations == 2
    assert cmvk.get_drift_rate("did:x") == pytest.approx(0.5)
    assert cmvk.get_mean_drift_score("did:x") == pytest.approx((0.8 + 0.01 * 2 + 0.8) / 4)


# ── 4. voucher cascade ───────────────────────────────────────────────


async def test_voucher_cascade_clips_and_reports():
    scorer = MockNexusScorer({})
    hv = Hypervisor(nexus=NexusAdapter(scorer=scorer),
                    cmvk=CMVKAdapter(verifier=MockCMVKVerifier({"claimed": 0.9})))
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    sid = ms.sso.session_id
    await hv.join_session(sid, "did:mentor", sigma_raw=0.9)
    await hv.join_session(sid, "did:junior", sigma_raw=0.65)
    await hv.activate_session(sid)

    vouch = hv.vouching.vouch("did:mentor", "did:junior", sid, voucher_sigma=0.9)
    assert vouch.is_active

    await hv.verify_behavior(sid, "did:junior", "claimed", "observed")
    slash = hv.slashing.history[-1]
    assert slash.vouchee_did == "did:junior"
    clipped = {c.voucher_did for c in slash.voucher_clips}
    assert "did:mentor" in clipped
    # critical drift (0.9 >= 0.75) escalates the Nexus severity
    assert ("did:junior", "critical") in scorer.slashes
    # the consumed bond is released
    assert all(
        not v.is_active for v in hv.vouching.get_vouchers_for("did:junior", sid)
    )


async def test_cascade_depth_two_wipes_chain():
    hv = Hypervisor()
    ms = await hv.create_session(
        SessionConfig(max_participants=10), creator_did="did:lead"
    )
    sid = ms.sso.session_id
    scores = {"did:a": 0.9, "did:b": 0.62, "did:c": 0.61}
    for did, sig in scores.items():
        await hv.join_session(sid, did, sigma_raw=sig)
    # a vouches b vouches c
    hv.vouching.vouch("did:a", "did:b", sid, voucher_sigma=0.9)
    hv.vouching.vouch("did:b", "did:c", sid, voucher_sigma=0.62)

    result = hv.slashing.slash(
        "did:c", sid, vouchee_sigma=scores["did:c"], risk_weight=0.95,
        reason="violation", agent_scores=scores,
    )
    assert scores["did:c"] == 0.0
    # b clipped to floor -> wiped -> cascades to a within depth 2
    assert scores["did:b"] <= 0.05 + 1e-9
    assert scores["did:a"] < 0.9
    assert result.voucher_clips


# ── 5. adapter fallbacks without backing services ────────────────────


async def test_nexus_default_sigma_without_scorer():
    hv = Hypervisor(nexus=NexusAdapter())
    ms = await hv.create_session(SessionConfig(min_sigma_eff=0.4), creator_did="did:l")
    ring = await hv.join_session(ms.sso.session_id, "did:unknown")
    # default sigma 0.5 -> below ring2 threshold -> sandbox
    assert ring == ExecutionRing.RING_3_SANDBOX
    p = ms.sso.get_participant("did:unknown")
    assert p.sigma_eff == pytest.approx(0.5)


async def test_cmvk_without_verifier_passes():
    cmvk = CMVKAdapter()
    result = cmvk.check_behavioral_drift("did:x", "s", "a", "b")
    assert result.passed and result.drift_score == 0.0


async def test_iatp_unknown_trust_level_sandboxes():
    analysis = IATPAdapter().analyze_manifest_dict(
        manifest_dict("did:mystery", trust="unheard_of_level", score=2)
    )
    assert analysis.ring_hint == ExecutionRing.RING_3_SANDBOX


# ── 6. threshold configuration ───────────────────────────────────────


async def test_custom_drift_thresholds_change_severity():
    strict = CMVKAdapter(
        verifier=MockCMVKVerifier({"claimed": 0.2}),
        thresholds=DriftThresholds(low=0.05, medium=0.1, high=0.15, critical=0.3),
    )
    default = CMVKAdapter(verifier=MockCMVKVerifier({"claimed": 0.2}))
    assert strict.check_behavioral_drift("d", "s", "claimed", "o").should_slash
    assert not default.check_behavioral_drift("d", "s", "claimed", "o").should_slash


async def test_max_exposure_limits_vouching():
    hv = Hypervisor(max_exposure=0.2)
    ms = await hv.create_session(
        SessionConfig(max_participants=10), creator_did="did:lead"
    )
    sid = ms.sso.session_id
    for did in ("did:v", "did:e1", "did:e2"):
        await hv.join_session(sid, did, sigma_raw=0.9)
    hv.vouching.vouch("did:v", "did:e1", sid, voucher_sigma=0.9)
    from hypervisor_tpu import VouchingError

    with pytest.raises(VouchingError):
        hv.vouching.vouch("did:v", "did:e2", sid, voucher_sigma=0.9)


# ── 7. fully-wired hypervisor with event bus ─────────────────────────


async def test_fully_wired_pipeline_emits_events():
    scorer = MockNexusScorer({"did:worker": 850})
    bus = HypervisorEventBus()
    hv = Hypervisor(
        nexus=NexusAdapter(scorer=scorer),
        cmvk=CMVKAdapter(verifier=MockCMVKVerifier({"claimed": 0.55})),
        iatp=IATPAdapter(),
        event_bus=bus,
    )
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    sid = ms.sso.session_id
    await hv.join_session(
        sid, "did:worker",
        manifest=manifest_dict("did:worker", trust="trusted", score=9),
    )
    await hv.activate_session(sid)
    ms.delta_engine.capture("did:worker", [])
    await hv.verify_behavior(sid, "did:worker", "claimed", "observed")
    root = await hv.terminate_session(sid)

    assert root and len(root) == 64
    types = {e.event_type for e in bus.all_events}
    assert {
        EventType.SESSION_CREATED,
        EventType.SESSION_JOINED,
        EventType.SESSION_ACTIVATED,
        EventType.SLASH_EXECUTED,
        EventType.SESSION_TERMINATED,
    } <= types
    by_agent = bus.query_by_agent("did:worker")
    assert len(by_agent) >= 2


# ── 8. sigma resolution + adapter edge behaviors (reference
#      test_scenarios.py:765-819,936-1051 equivalents) ─────────────────


async def test_nexus_auto_resolves_sigma_when_zero():
    scorer = MockNexusScorer({"did:known": 900})  # 900/1000 -> sigma 0.9
    hv = Hypervisor(nexus=NexusAdapter(scorer=scorer))
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    ring = await hv.join_session(
        ms.sso.session_id, "did:known", sigma_raw=0.0, agent_history="did:known"
    )
    p = ms.sso.get_participant("did:known")
    assert p.sigma_eff == pytest.approx(0.9)
    assert ring == ExecutionRing.RING_2_STANDARD  # 0.9 w/o consensus -> Ring 2


async def test_nexus_conservative_merge_takes_minimum():
    # Agent claims 0.95 but Nexus only backs 600/1000 = 0.6: the join
    # must trust the lower number.
    scorer = MockNexusScorer({"did:boastful": 600})
    hv = Hypervisor(nexus=NexusAdapter(scorer=scorer))
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    await hv.join_session(
        ms.sso.session_id, "did:boastful", sigma_raw=0.95,
        agent_history="did:boastful",
    )
    p = ms.sso.get_participant("did:boastful")
    assert p.sigma_eff == pytest.approx(0.6)


async def test_verify_behavior_none_without_cmvk():
    hv = Hypervisor()
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    sid = ms.sso.session_id
    await hv.join_session(sid, "did:a", sigma_raw=0.8)
    await hv.activate_session(sid)
    assert await hv.verify_behavior(sid, "did:a", "x", "y") is None
    assert hv.slashing.history == []


async def test_backward_compat_no_adapters_full_lifecycle():
    """The facade works with zero adapters, exactly like the reference's
    bare Hypervisor (`core.py:69-89` with all-None integrations)."""
    hv = Hypervisor()
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    sid = ms.sso.session_id
    ring = await hv.join_session(sid, "did:solo", sigma_raw=0.7)
    assert ring == ExecutionRing.RING_2_STANDARD
    await hv.activate_session(sid)
    ms.delta_engine.capture("did:solo", [])
    root = await hv.terminate_session(sid)
    assert root and len(root) == 64
    assert hv.get_session(sid) is not None
    assert sid not in [m.sso.session_id for m in hv.active_sessions]


async def test_nexus_cache_invalidated_by_slash_report():
    scorer = MockNexusScorer({"did:x": 800})
    adapter = NexusAdapter(scorer=scorer)
    first = adapter.resolve_sigma("did:x", history="did:x")
    assert first == pytest.approx(0.8)
    assert adapter.get_cached_result("did:x") is not None
    adapter.report_slash("did:x", reason="drift", severity="high")
    # Cache dropped; next resolve sees the penalized score.
    assert adapter.get_cached_result("did:x") is None
    again = adapter.resolve_sigma("did:x", history="did:x")
    assert again == pytest.approx((800 - 250) / 1000)


async def test_critical_drift_slashes_and_reports_critical():
    scorer = MockNexusScorer({"did:evil": 950})
    hv = Hypervisor(
        nexus=NexusAdapter(scorer=scorer),
        cmvk=CMVKAdapter(verifier=MockCMVKVerifier({"claimed": 0.9})),
    )
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    sid = ms.sso.session_id
    await hv.join_session(sid, "did:evil", sigma_raw=0.9)
    await hv.activate_session(sid)
    result = await hv.verify_behavior(sid, "did:evil", "claimed", "observed")
    assert result.severity.value == "critical" and result.should_slash
    assert ("did:evil", "critical") in scorer.slashes
    # Slashed to zero (blacklisted).
    assert hv.slashing.history[-1].vouchee_sigma_after == 0.0


async def test_repeated_medium_drift_tracks_rate_and_demotes():
    """Medium drift demotes without slashing; repeated offenses are
    visible in the adapter's history/rate for escalation decisions
    (reference `test_scenarios.py:421-449`)."""
    cmvk = CMVKAdapter(
        verifier=MockCMVKVerifier({"c1": 0.35, "c2": 0.4, "c3": 0.42})
    )
    hv = Hypervisor(cmvk=cmvk)
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    sid = ms.sso.session_id
    await hv.join_session(sid, "did:wobbly", sigma_raw=0.8)
    await hv.activate_session(sid)
    for key in ("c1", "c2", "c3"):
        result = await hv.verify_behavior(sid, "did:wobbly", key, "obs")
        assert result.should_demote and not result.should_slash
    assert hv.slashing.history == []
    assert cmvk.get_drift_rate("did:wobbly") == pytest.approx(1.0)
    assert len(cmvk.get_agent_drift_history("did:wobbly")) == 3


async def test_iatp_verified_partner_reaches_privileged_ring():
    """A verified-partner manifest with a top IATP score hints sigma high
    enough for Ring 1 eligibility checks (with consensus)."""
    hv = Hypervisor(iatp=IATPAdapter())
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    await hv.join_session(
        ms.sso.session_id, "did:partner",
        manifest=manifest_dict("did:partner", trust="verified_partner", score=10),
    )
    p = ms.sso.get_participant("did:partner")
    assert p.sigma_eff > 0.95
    from hypervisor_tpu.models import ActionDescriptor, ReversibilityLevel

    deploy = ActionDescriptor(
        action_id="m.deploy", name="deploy", execute_api="/d",
        reversibility=ReversibilityLevel.NONE,  # requires Ring 1
    )
    check = hv.ring_enforcer.check(
        ExecutionRing.RING_1_PRIVILEGED, deploy,
        sigma_eff=p.sigma_eff, has_consensus=True,
    )
    assert check.allowed


async def test_strong_forcing_reaches_device_mode_column():
    """Non-reversible actions force STRONG on BOTH planes: the host SSO
    flag and the device session row's mode/has_nonreversible columns
    (which STRONG/EVENTUAL tick dispatch reads)."""
    import numpy as np

    from hypervisor_tpu.models import (
        ActionDescriptor,
        ConsistencyMode,
        ReversibilityLevel,
    )

    hv = Hypervisor()
    ms = await hv.create_session(SessionConfig(), creator_did="did:lead")
    assert int(np.asarray(hv.state.sessions.mode)[ms.slot]) == (
        ConsistencyMode.EVENTUAL.code
    )

    irreversible = ActionDescriptor(
        action_id="m.nuke", name="nuke", execute_api="/n",
        reversibility=ReversibilityLevel.NONE,
    )
    await hv.join_session(
        ms.sso.session_id, "did:ops", sigma_raw=0.9, actions=[irreversible]
    )
    assert ms.sso.consistency_mode is ConsistencyMode.STRONG
    assert int(np.asarray(hv.state.sessions.mode)[ms.slot]) == (
        ConsistencyMode.STRONG.code
    )
    assert bool(np.asarray(hv.state.sessions.has_nonreversible)[ms.slot])
