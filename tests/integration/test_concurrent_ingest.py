"""Concurrent admission staging: producer threads + the tick driver.

The native StagingQueue claims slots atomically (lock-free CAS in
`native/hv_runtime.cpp`); `HypervisorState.enqueue_join` is thread-safe
for the host-side indices. These tests run REAL producer threads pushing
joins while the main thread flushes admission waves — the concurrency
story the round-1 verdict called ornamental.
"""

from __future__ import annotations

import threading

import numpy as np

from hypervisor_tpu.models import SessionConfig
from hypervisor_tpu.state import HypervisorState


def _producer(state, session_slot, prefix, count, barrier):
    barrier.wait()
    for i in range(count):
        state.enqueue_join(session_slot, f"did:{prefix}:{i}", 0.8)


class TestConcurrentIngest:
    def test_threaded_producers_one_flush(self):
        st = HypervisorState()
        slot = st.create_session(
            "s:conc", SessionConfig(max_participants=1000)
        )
        n_threads, per_thread = 8, 25
        barrier = threading.Barrier(n_threads)
        threads = [
            threading.Thread(
                target=_producer, args=(st, slot, f"t{t}", per_thread, barrier)
            )
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        status = st.flush_joins()
        assert len(status) == n_threads * per_thread
        assert (status == 0).all(), np.unique(status)
        assert st.participant_count(slot) == n_threads * per_thread
        # every producer's agents landed with correct bookkeeping
        for t in range(n_threads):
            for i in range(per_thread):
                row = st.agent_row(f"did:t{t}:{i}")
                assert row is not None and row["session"] == slot

    def test_producers_interleaved_with_flushes(self):
        st = HypervisorState()
        slot = st.create_session(
            "s:interleave", SessionConfig(max_participants=1000)
        )
        n_threads, per_thread = 4, 30
        barrier = threading.Barrier(n_threads + 1)
        threads = [
            threading.Thread(
                target=_producer, args=(st, slot, f"p{t}", per_thread, barrier)
            )
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        barrier.wait()
        # The tick driver flushes whatever each epoch harvested while
        # producers keep pushing.
        admitted = 0
        while any(t.is_alive() for t in threads):
            admitted += int((st.flush_joins() == 0).sum())
        for t in threads:
            t.join()
        admitted += int((st.flush_joins() == 0).sum())
        assert admitted == n_threads * per_thread
        assert st.participant_count(slot) == n_threads * per_thread

    def test_capacity_budget_respected_under_concurrency(self):
        st = HypervisorState()
        slot = st.create_session(
            "s:cap", SessionConfig(max_participants=17)
        )
        n_threads, per_thread = 6, 10
        barrier = threading.Barrier(n_threads)
        threads = [
            threading.Thread(
                target=_producer, args=(st, slot, f"c{t}", per_thread, barrier)
            )
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        status = st.flush_joins()
        assert int((status == 0).sum()) == 17
        assert st.participant_count(slot) == 17

    def test_same_agent_raced_from_many_threads_admits_once(self):
        """Concurrent joins of ONE (session, did) must admit exactly once:
        the staged-membership dedup closes the window between the
        membership check and the wave flush."""
        st = HypervisorState()
        slot = st.create_session("s:dupe", SessionConfig(max_participants=100))
        barrier = threading.Barrier(6)

        def racer():
            barrier.wait()
            st.enqueue_join(slot, "did:same", 0.9)

        threads = [threading.Thread(target=racer) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        status = st.flush_joins()
        assert int((status == 0).sum()) == 1, status
        assert st.participant_count(slot) == 1
        did = st.agent_ids.lookup("did:same")
        assert int((np.asarray(st.agents.did) == did).sum()) == 1
