"""Stateful property test: random facade op sequences keep both planes
coherent.

A hypothesis RuleBasedStateMachine drives the `Hypervisor` facade with
arbitrary interleavings of create/join/activate/vouch/terminate and
checks, after every step, that the host engines (SSO participants,
vouch graph) and the device plane (AgentTable rows, VouchTable edges,
SessionTable counts) describe the same world — the plane-unification
contract (VERDICT round-1 #2) under sequences no example-based test
enumerates.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

hyp = pytest.importorskip("hypothesis")
from hypothesis import settings, strategies as st  # noqa: E402
from hypothesis.stateful import (  # noqa: E402
    RuleBasedStateMachine,
    invariant,
    precondition,
    rule,
)

from hypervisor_tpu import Hypervisor, SessionConfig  # noqa: E402
from hypervisor_tpu.session import (  # noqa: E402
    SessionLifecycleError,
    SessionParticipantError,
)

AGENTS = [f"did:st{i}" for i in range(8)]


class _InjectableDrift:
    """CMVK verifier stub: the claimed embedding IS the drift score."""

    def verify_embeddings(self, embedding_a, embedding_b, **_):
        class V:
            drift_score = float(embedding_a)
            explanation = None

        return V()


class PlaneCoherence(RuleBasedStateMachine):
    def __init__(self):
        super().__init__()
        from hypervisor_tpu.integrations.cmvk_adapter import CMVKAdapter

        self.hv = Hypervisor(cmvk=CMVKAdapter(verifier=_InjectableDrift()))
        self.sessions: list[str] = []          # live (not terminated)
        self.joined: dict[str, set[str]] = {}  # sid -> dids
        self.loop = asyncio.new_event_loop()

    def teardown(self):
        self.loop.close()

    def go(self, coro):
        return self.loop.run_until_complete(coro)

    # ── rules ────────────────────────────────────────────────────────

    @rule()
    def create_session(self):
        if len(self.sessions) >= 4:
            return
        ms = self.go(
            self.hv.create_session(
                SessionConfig(max_participants=5, min_sigma_eff=0.0),
                creator_did="did:creator",
            )
        )
        self.sessions.append(ms.sso.session_id)
        self.joined[ms.sso.session_id] = set()

    @precondition(lambda self: self.sessions)
    @rule(agent=st.sampled_from(AGENTS), sigma=st.floats(0.25, 1.0),
          pick=st.integers(0, 3))
    def join(self, agent, sigma, pick):
        sid = self.sessions[pick % len(self.sessions)]
        try:
            self.go(self.hv.join_session(sid, agent, sigma_raw=float(sigma)))
            self.joined[sid].add(agent)
        except (SessionParticipantError, SessionLifecycleError):
            pass  # duplicate / capacity / wrong state — legal refusals

    @precondition(lambda self: self.sessions)
    @rule(pick=st.integers(0, 3))
    def activate(self, pick):
        sid = self.sessions[pick % len(self.sessions)]
        try:
            self.go(self.hv.activate_session(sid))
        except SessionLifecycleError:
            pass

    @precondition(lambda self: any(self.joined.values()))
    @rule(pick=st.integers(0, 3), voucher=st.sampled_from(AGENTS))
    def vouch(self, pick, voucher):
        sids = [s for s in self.sessions if self.joined[s]]
        if not sids:
            return
        sid = sids[pick % len(sids)]
        vouchee = sorted(self.joined[sid])[0]
        if voucher == vouchee:
            return
        try:
            self.hv.vouching.vouch(voucher, vouchee, sid, voucher_sigma=0.9)
        except Exception:
            pass  # cycle/exposure refusals are fine

    @precondition(lambda self: any(self.joined.values()))
    @rule(pick=st.integers(0, 3))
    def leave(self, pick):
        sids = [s for s in self.sessions if self.joined[s]]
        if not sids:
            return
        sid = sids[pick % len(sids)]
        agent = sorted(self.joined[sid])[0]
        self.go(self.hv.leave_session(sid, agent))
        self.joined[sid].discard(agent)

    @precondition(lambda self: self.sessions)
    @rule(pick=st.integers(0, 3))
    def terminate(self, pick):
        sid = self.sessions[pick % len(self.sessions)]
        try:
            root = self.go(self.hv.terminate_session(sid))
        except SessionLifecycleError:
            return
        # Audit contract: any session that captured deltas yields a root.
        managed = self.hv.get_session(sid)
        if managed.delta_engine.turn_count:
            assert root and len(root) == 64
        self.sessions.remove(sid)
        self.joined.pop(sid)

    @precondition(lambda self: any(self.joined.values()))
    @rule(pick=st.integers(0, 3), new_ring=st.integers(1, 3))
    def update_ring(self, pick, new_ring):
        from hypervisor_tpu.models import ExecutionRing

        sids = [s for s in self.sessions if self.joined[s]]
        if not sids:
            return
        sid = sids[pick % len(sids)]
        agent = sorted(self.joined[sid])[0]
        self.go(
            self.hv.update_agent_ring(
                sid, agent, ExecutionRing(new_ring), reason="prop"
            )
        )

    @precondition(lambda self: any(self.joined.values()))
    @rule(pick=st.integers(0, 3))
    def quarantine_agent(self, pick):
        from hypervisor_tpu.liability.quarantine import QuarantineReason

        sids = [s for s in self.sessions if self.joined[s]]
        if not sids:
            return
        sid = sids[pick % len(sids)]
        agent = sorted(self.joined[sid])[0]
        # Session-scoped on BOTH planes: flag the membership's row in
        # THIS session (the round-2 bug flagged "the agent's row", which
        # could belong to a later join in another session).
        row = self.hv.state.agent_row(agent, self.hv.get_session(sid).slot)
        if row is None:
            return
        self.hv.quarantine.quarantine(
            agent, sid, QuarantineReason.MANUAL, details="prop"
        )
        self.hv.state.quarantine_rows([row["slot"]], now=self.hv.state.now())

    @precondition(lambda self: any(self.joined.values()))
    @rule(pick=st.integers(0, 3))
    def drift_slash(self, pick):
        """HIGH drift through the facade: agent-global slash + session-
        scoped quarantine, host participants synced to the cascade."""
        from hypervisor_tpu.tables.state import FLAG_BLACKLISTED

        sids = [s for s in self.sessions if self.joined[s]]
        if not sids:
            return
        sid = sids[pick % len(sids)]
        agent = sorted(self.joined[sid])[0]
        mask_before = self.hv.state.quarantined_mask().copy()
        self.go(
            self.hv.verify_behavior(
                sid, agent, claimed_embedding=0.6, observed_embedding=0.0
            )
        )
        # Post-conditions: every live row of the agent is blacklisted
        # with sigma 0 (reference slash is agent-global), but THIS slash
        # quarantines only the slashing session's row — rows in other
        # sessions keep whatever quarantine state they already had.
        flags = np.asarray(self.hv.state.agents.flags)
        mask = self.hv.state.quarantined_mask()
        slot_here = self.hv.get_session(sid).slot
        for row in self.hv.state.agent_rows(agent):
            assert flags[row["slot"]] & FLAG_BLACKLISTED
            assert row["sigma_eff"] == 0.0
            if row["session"] != slot_here:
                assert mask[row["slot"]] == mask_before[row["slot"]], (
                    "quarantine leaked into another session's row"
                )

    @precondition(lambda self: any(self.joined.values()))
    @rule(pick=st.integers(0, 3))
    def kill(self, pick):
        """Facade kill: handoff bookkeeping then both-plane removal."""
        sids = [s for s in self.sessions if self.joined[s]]
        if not sids:
            return
        sid = sids[pick % len(sids)]
        agent = sorted(self.joined[sid])[0]
        self.go(
            self.hv.kill_agent(
                sid, agent,
                in_flight_steps=[{"step_id": "s", "saga_id": "g"}],
            )
        )
        self.joined[sid].discard(agent)

    @precondition(lambda self: any(self.joined.values()))
    @rule(pick=st.integers(0, 3))
    def drift_demote(self, pick):
        """MEDIUM drift: one-ring demotion on both planes, no slash."""
        sids = [s for s in self.sessions if self.joined[s]]
        if not sids:
            return
        sid = sids[pick % len(sids)]
        agent = sorted(self.joined[sid])[0]
        self.go(
            self.hv.verify_behavior(
                sid, agent, claimed_embedding=0.35, observed_embedding=0.0
            )
        )

    @precondition(lambda self: any(self.joined.values()))
    @rule(pick=st.integers(0, 3))
    def elevate(self, pick):
        """Facade elevation: one grant, both planes."""
        from hypervisor_tpu.models import ExecutionRing
        from hypervisor_tpu.rings.elevation import RingElevationError

        sids = [s for s in self.sessions if self.joined[s]]
        if not sids:
            return
        sid = sids[pick % len(sids)]
        agent = sorted(self.joined[sid])[0]
        ring = self.hv.get_session(sid).sso.get_participant(agent).ring
        if ring.value <= 1:
            return
        try:
            self.go(
                self.hv.grant_elevation(
                    sid, agent, ExecutionRing(ring.value - 1), ttl_seconds=120
                )
            )
        except RingElevationError:
            pass  # one live grant per (agent, session) — legal refusal

    @precondition(lambda self: any(self.joined.values()))
    @rule(pick=st.integers(0, 3), kind=st.integers(0, 2))
    def gateway(self, pick, kind):
        """check_action under arbitrary interleavings: a quarantined
        writer must refuse, a tripped breaker must refuse, and the
        verdict must never crash whatever the planes hold."""
        from hypervisor_tpu.models import ActionDescriptor, ReversibilityLevel

        sids = [s for s in self.sessions if self.joined[s]]
        if not sids:
            return
        sid = sids[pick % len(sids)]
        agent = sorted(self.joined[sid])[0]
        action = ActionDescriptor(
            action_id=f"act{kind}",
            name="probe",
            execute_api="/x",
            undo_api="/u" if kind == 0 else None,
            reversibility=[
                ReversibilityLevel.FULL,
                ReversibilityLevel.NONE,
                ReversibilityLevel.FULL,
            ][kind],
            is_read_only=(kind == 2),
        )
        result = self.go(self.hv.check_action(sid, agent, action))
        row = self.hv.state.agent_row(agent, self.hv.get_session(sid).slot)
        if (
            row is not None
            and self.hv.state.quarantined_mask()[row["slot"]]
            and not action.is_read_only
        ):
            assert not result.allowed and (
                result.quarantined or result.breaker_tripped
            )
        if self.hv.breach_detector.is_breaker_tripped(agent, sid):
            # The trip may have happened on THIS call's recording; the
            # next call must refuse at gate 1.
            again = self.go(self.hv.check_action(sid, agent, action))
            assert not again.allowed and again.breaker_tripped

    @precondition(lambda self: any(self.joined.values()))
    @rule(pick=st.integers(0, 3), kinds=st.lists(st.integers(0, 2),
                                                 min_size=1, max_size=6))
    def gateway_wave(self, pick, kinds):
        """check_actions: a whole wave through the fused gateway must
        agree with the planes — every wave verdict for a quarantined
        writer refuses, and a wave never crashes whatever the planes
        hold (duplicate agents settle sequentially inside it)."""
        from hypervisor_tpu.models import ActionDescriptor, ReversibilityLevel

        sids = [s for s in self.sessions if self.joined[s]]
        if not sids:
            return
        sid = sids[pick % len(sids)]
        agents = sorted(self.joined[sid])
        reqs = []
        for i, kind in enumerate(kinds):
            agent = agents[i % len(agents)]
            reqs.append((
                agent,
                ActionDescriptor(
                    action_id=f"wv{kind}",
                    name="probe",
                    execute_api="/x",
                    undo_api="/u" if kind == 0 else None,
                    reversibility=[
                        ReversibilityLevel.FULL,
                        ReversibilityLevel.NONE,
                        ReversibilityLevel.FULL,
                    ][kind],
                    is_read_only=(kind == 2),
                ),
            ))
        results = self.go(self.hv.check_actions(sid, reqs))
        assert len(results) == len(reqs)
        for (agent, action), result in zip(reqs, results):
            row = self.hv.state.agent_row(
                agent, self.hv.get_session(sid).slot
            )
            if (
                row is not None
                and self.hv.state.quarantined_mask()[row["slot"]]
                and not action.is_read_only
            ):
                assert not result.allowed and (
                    result.quarantined or result.breaker_tripped
                )

    @rule()
    def sweeps(self):
        now = self.hv.state.now()
        self.hv.state.breach_sweep_tick(now)
        self.hv.sweep_elevations()
        self.hv.state.quarantine_tick(now)

    @precondition(lambda self: any(self.joined.values()))
    @rule(pick=st.integers(0, 3))
    def capture_delta(self, pick):
        sids = [s for s in self.sessions if self.joined[s]]
        if not sids:
            return
        sid = sids[pick % len(sids)]
        managed = self.hv.get_session(sid)
        agent = sorted(self.joined[sid])[0]
        managed.delta_engine.capture(agent, [])

    # ── invariants: both planes describe the same world ──────────────

    @invariant()
    def breach_windows_agree_across_planes(self):
        """Round-5 sliding window: after ANY interleaving of actions,
        gateway waves, sweeps, quarantines, handoffs, and elevations,
        every live membership's device window total equals the host
        detector's window — a sweep can no longer diverge the planes
        (the old tumbling counters reset on every sweep rule here).
        Machine runs finish far inside one sub-window, so the
        oldest-partial-band imprecision cannot engage."""
        from hypervisor_tpu.ops import security_ops

        st = self.hv.state
        calls, _ = security_ops.window_totals(
            st.agents.bd_window, st.now(), st.config.breach
        )
        calls = np.asarray(calls)
        for sid in self.sessions:
            managed = self.hv.get_session(sid)
            for did in sorted(self.joined[sid]):
                row = st.agent_row(did, managed.slot)
                if row is None:
                    continue
                hs = self.hv.breach_detector.get_agent_stats(did, sid)
                assert hs["window_calls"] == int(calls[row["slot"]]), (
                    f"window divergence for {did} in {sid}: host "
                    f"{hs['window_calls']} device {int(calls[row['slot']])}"
                )

    @invariant()
    def participants_match_device_rows(self):
        for sid in self.sessions:
            managed = self.hv.get_session(sid)
            for p in managed.sso.participants:
                # One device row per (agent, session): EVERY membership
                # has its own row in its own session — no carve-outs.
                row = self.hv.state.agent_row(p.agent_did, managed.slot)
                assert row is not None, (
                    f"{p.agent_did} missing from device in {sid}"
                )
                assert row["slot"] >= 0
                assert row["session"] == managed.slot
                dev_ring = int(np.asarray(self.hv.state.agents.ring)[row["slot"]])
                assert dev_ring == p.ring.value, (
                    f"ring mismatch for {p.agent_did}: host {p.ring.value} "
                    f"device {dev_ring}"
                )

    @invariant()
    def participant_counts_match(self):
        for sid in self.sessions:
            managed = self.hv.get_session(sid)
            if managed.slot < 0:
                continue
            dev_count = int(
                np.asarray(self.hv.state.sessions.n_participants)[managed.slot]
            )
            assert dev_count == managed.sso.participant_count, (
                f"count mismatch for {sid}: host "
                f"{managed.sso.participant_count} device {dev_count}"
            )

    @invariant()
    def vouch_edges_mirror_host_graph(self):
        # The mirror covers edges whose BOTH endpoints are device-resident
        # (a non-participant voucher has no agent row to hang an edge on).
        host_mirrorable = sum(
            1
            for r in self.hv.vouching.all_records()
            if r.is_active
            and r.session_id in self.sessions
            and self.hv.state.agent_row(r.voucher_did) is not None
            and self.hv.state.agent_row(r.vouchee_did) is not None
        )
        dev_live = int(np.asarray(self.hv.state.vouches.active).sum())
        assert dev_live == host_mirrorable, (
            f"vouch mirror drift: host {host_mirrorable} device {dev_live}"
        )

    @invariant()
    def effective_rings_agree(self):
        # Facade-wired elevation: for every live membership, the device
        # effective ring (base min active grants on the row) equals the
        # host manager's resolution for that (agent, session).
        eff = self.hv.state.effective_rings(self.hv.state.now())
        for sid in self.sessions:
            managed = self.hv.get_session(sid)
            for p in managed.sso.participants:
                row = self.hv.state.agent_row(p.agent_did, managed.slot)
                if row is None:
                    continue
                host_eff = self.hv.elevation.get_effective_ring(
                    p.agent_did, sid, p.ring
                )
                assert eff[row["slot"]] == host_eff.value, (
                    f"effective ring drift for {p.agent_did} in {sid}: "
                    f"device {eff[row['slot']]} host {host_eff.value}"
                )

    @invariant()
    def mirrored_edges_point_at_best_rows(self):
        # Edge-resolution contract: every mirrored edge hangs on its
        # endpoint's row IN the bond's session when resident there, else
        # the endpoint's most recent live row (fallback). Leaves,
        # terminates, and rejoins must maintain this (re-mirror +
        # re-point), or slash cascades match the wrong rows.
        voucher_col = np.asarray(self.hv.state.vouches.voucher)
        vouchee_col = np.asarray(self.hv.state.vouches.vouchee)
        for vouch_id, edge in self.hv._edge_of_vouch.items():
            record = self.hv.vouching.record(vouch_id)
            if record is None or not record.is_active:
                continue
            managed = self.hv.get_session(record.session_id)
            if managed is None or record.session_id not in self.sessions:
                continue
            for did, col in (
                (record.voucher_did, voucher_col),
                (record.vouchee_did, vouchee_col),
            ):
                best = self.hv.state.agent_row(
                    did, managed.slot
                ) or self.hv.state.agent_row(did)
                assert best is not None, f"mirrored edge for absent {did}"
                assert col[edge] == best["slot"], (
                    f"edge {edge} for {vouch_id} points at row "
                    f"{col[edge]}, best resolution for {did} is "
                    f"{best['slot']}"
                )

    @invariant()
    def quarantine_planes_agree(self):
        # Quarantine is session-scoped on both planes: a flagged device
        # row implies a live host record for THAT (agent, session) — and
        # an agent flagged in one session is never flagged in another
        # unless that other session quarantined it too.
        mask = self.hv.state.quarantined_mask()
        for sid in self.sessions:
            managed = self.hv.get_session(sid)
            for p in managed.sso.participants:
                row = self.hv.state.agent_row(p.agent_did, managed.slot)
                if row is None:
                    continue
                if mask[row["slot"]]:
                    assert (
                        self.hv.quarantine.get_active_quarantine(
                            p.agent_did, sid
                        )
                        is not None
                    ), f"device-only quarantine for {p.agent_did} in {sid}"

    @invariant()
    def delta_log_covers_every_capture(self):
        total = sum(
            self.hv.get_session(s).delta_engine.turn_count
            for s in self.sessions
        )
        dev = int(np.asarray(self.hv.state.delta_log.cursor))
        staged = len(self.hv.state._pending_deltas)
        assert dev + staged >= total, (
            f"device DeltaLog behind: {dev}+{staged} staged < {total}"
        )


import os  # noqa: E402

_DEEP = os.environ.get("HV_DEEP_STATEFUL", "") == "1"
PlaneCoherence.TestCase.settings = settings(
    max_examples=60 if _DEEP else 20,
    stateful_step_count=60 if _DEEP else 30,
    deadline=None,
)
TestPlaneCoherence = PlaneCoherence.TestCase


class TestCrossSessionQuarantineRegression:
    """Pins the round-2 plane-coherence bug: agent joins session A, then
    session B; quarantined in A. With one-row-per-agent the device flag
    landed on the row belonging to B, which B's host QuarantineManager
    knew nothing about — B's write waves refused the agent with no
    explanation. Per-(agent, session) rows keep the planes coherent."""

    def test_quarantine_in_a_does_not_poison_b(self):
        from hypervisor_tpu.liability.quarantine import QuarantineReason

        async def run():
            hv = Hypervisor()
            a = await hv.create_session(
                SessionConfig(min_sigma_eff=0.0), creator_did="did:creator"
            )
            b = await hv.create_session(
                SessionConfig(min_sigma_eff=0.0), creator_did="did:creator"
            )
            sid_a, sid_b = a.sso.session_id, b.sso.session_id
            await hv.join_session(sid_a, "did:x", sigma_raw=0.8)
            await hv.join_session(sid_b, "did:x", sigma_raw=0.8)

            # Both memberships hold live device rows in their sessions.
            row_a = hv.state.agent_row("did:x", a.slot)
            row_b = hv.state.agent_row("did:x", b.slot)
            assert row_a is not None and row_b is not None
            assert row_a["slot"] != row_b["slot"]
            assert row_a["session"] == a.slot
            assert row_b["session"] == b.slot

            # Quarantine in A (host record + device flag on A's row).
            hv.quarantine.quarantine(
                "did:x", sid_a, QuarantineReason.MANUAL, details="repro"
            )
            hv.state.quarantine_rows([row_a["slot"]], now=hv.state.now())

            mask = hv.state.quarantined_mask()
            assert mask[row_a["slot"]], "A's membership row must be flagged"
            assert not mask[row_b["slot"]], (
                "B's membership row must NOT be flagged — the round-2 bug"
            )
            # B's write path still serves the agent.
            assert (
                hv.quarantine.get_active_quarantine("did:x", sid_b) is None
            )

            # And the agent can still leave A (the old one-row constraint
            # refused when a later join owned 'the' row).
            await hv.leave_session(sid_a, "did:x")
            assert hv.state.agent_row("did:x", a.slot) is None
            assert hv.state.agent_row("did:x", b.slot) is not None

        asyncio.run(run())

    def test_slash_history_records_pre_slash_sigma(self):
        # The host sync zeroes the live participant during the device
        # cascade; the forensic slash history must still record the
        # PRE-slash sigma (regression: it briefly recorded 0.0).
        from hypervisor_tpu.integrations.cmvk_adapter import CMVKAdapter

        async def run():
            hv = Hypervisor(cmvk=CMVKAdapter(verifier=_InjectableDrift()))
            ms = await hv.create_session(
                SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
            )
            sid = ms.sso.session_id
            await hv.join_session(sid, "did:r", sigma_raw=0.8)
            await hv.verify_behavior(
                sid, "did:r", claimed_embedding=0.6, observed_embedding=0.0
            )
            record = hv.slashing.history[-1]
            assert record.vouchee_sigma_before == pytest.approx(0.8)
            # ...and the live participant mirrors the post-slash device row.
            assert ms.sso.get_participant("did:r").sigma_eff == 0.0

        asyncio.run(run())

    def test_join_repoints_fallback_edge_to_session_row(self):
        # Edge-resolution maintenance across leaves and joins. Phase 1:
        # B vouches for A in X; A leaves X and the edge re-attaches to
        # A's surviving Z row (fallback). Phase 2: B vouches for A in a
        # fresh session Y BEFORE A joins Y (the edge hangs on the Z
        # fallback row); when A then joins Y, the backfill must MOVE the
        # edge onto A's new Y row — without the re-point, a later slash
        # cascade in Y would match the wrong row forever.
        import numpy as np

        async def run():
            hv = Hypervisor()
            x = await hv.create_session(
                SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
            )
            z = await hv.create_session(
                SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
            )
            sx, sz = x.sso.session_id, z.sso.session_id
            await hv.join_session(sx, "did:A", sigma_raw=0.8)
            await hv.join_session(sz, "did:A", sigma_raw=0.8)
            await hv.join_session(sx, "did:B", sigma_raw=0.9)
            rec = hv.vouching.vouch("did:B", "did:A", sx, voucher_sigma=0.9)
            edge = hv._edge_of_vouch[rec.vouch_id]
            a_x = hv.state.agent_row("did:A", x.slot)["slot"]
            assert int(np.asarray(hv.state.vouches.vouchee)[edge]) == a_x

            await hv.leave_session(sx, "did:A")
            # Edge re-attached to A's Z row (endpoint still resident).
            edge2 = hv._edge_of_vouch[rec.vouch_id]
            a_z = hv.state.agent_row("did:A", z.slot)["slot"]
            assert int(np.asarray(hv.state.vouches.vouchee)[edge2]) == a_z

            # Phase 2 (rejoining X itself is a duplicate — membership
            # is terminal — so the vouch-before-join shape plays out in
            # a fresh session Y).
            y = await hv.create_session(
                SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
            )
            sy = y.sso.session_id
            rec2 = hv.vouching.vouch("did:B", "did:A", sy, voucher_sigma=0.9)
            edge3 = hv._edge_of_vouch[rec2.vouch_id]
            # A is not in Y yet: the edge hangs on A's fallback (Z) row.
            assert int(np.asarray(hv.state.vouches.vouchee)[edge3]) == a_z
            await hv.join_session(sy, "did:A", sigma_raw=0.8)
            # The join re-points the Y bond onto A's NEW Y row.
            edge4 = hv._edge_of_vouch[rec2.vouch_id]
            a_y = hv.state.agent_row("did:A", y.slot)["slot"]
            assert int(np.asarray(hv.state.vouches.vouchee)[edge4]) == a_y
            # The X bond (still on Z fallback) is untouched and active.
            assert bool(np.asarray(hv.state.vouches.active)[edge2])

        asyncio.run(run())


class TestDriftDemotionLadder:
    """MEDIUM drift demotes one ring on both planes (the adapter's
    should_demote rung, which the reference defines but never wires —
    its scenario tests demote by hand)."""

    def test_medium_drift_demotes_both_planes(self):
        from hypervisor_tpu.integrations.cmvk_adapter import CMVKAdapter

        async def run():
            hv = Hypervisor(cmvk=CMVKAdapter(verifier=_InjectableDrift()))
            ms = await hv.create_session(
                SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
            )
            sid = ms.sso.session_id
            await hv.join_session(sid, "did:m", sigma_raw=0.8)  # Ring 2
            result = await hv.verify_behavior(
                sid, "did:m", claimed_embedding=0.35, observed_embedding=0.0
            )
            assert result.should_demote and not result.should_slash
            assert ms.sso.get_participant("did:m").ring.value == 3
            row = hv.state.agent_row("did:m", ms.slot)
            assert row["ring"] == 3
            # No slash: sigma untouched, no quarantine, no blacklist.
            from hypervisor_tpu.tables.state import FLAG_BLACKLISTED

            assert row["sigma_eff"] == pytest.approx(0.8)
            flags = np.asarray(hv.state.agents.flags)
            assert not flags[row["slot"]] & FLAG_BLACKLISTED
            assert not hv.state.quarantined_mask()[row["slot"]]

            # Already-sandboxed agents stay at Ring 3 (no-op, no event).
            result2 = await hv.verify_behavior(
                sid, "did:m", claimed_embedding=0.35, observed_embedding=0.0
            )
            assert result2.should_demote
            assert ms.sso.get_participant("did:m").ring.value == 3

        asyncio.run(run())

    def test_medium_drift_retires_live_elevation(self):
        from hypervisor_tpu.integrations.cmvk_adapter import CMVKAdapter
        from hypervisor_tpu.models import ExecutionRing

        async def run():
            hv = Hypervisor(cmvk=CMVKAdapter(verifier=_InjectableDrift()))
            ms = await hv.create_session(
                SessionConfig(min_sigma_eff=0.0), creator_did="did:lead"
            )
            sid = ms.sso.session_id
            await hv.join_session(sid, "did:m", sigma_raw=0.8)
            await hv.grant_elevation(
                sid, "did:m", ExecutionRing.RING_1_PRIVILEGED
            )
            await hv.verify_behavior(
                sid, "did:m", claimed_embedding=0.35, observed_embedding=0.0
            )
            # The demotion superseded the sudo grant on both planes.
            assert hv.elevation.get_active_elevation("did:m", sid) is None
            row = hv.state.agent_row("did:m", ms.slot)
            eff = hv.state.effective_rings(hv.state.now())
            assert eff[row["slot"]] == 3

        asyncio.run(run())
