"""Facade <-> device-plane unification tests (VERDICT round-1 item #2/#4).

Asserts the two planes share one source of truth: identical Merkle roots
and ring assignments for the same scenario, device bond release at
terminate, and the batched SagaTable scheduler matching the reference
orchestrator's semantics (retry ladder, reverse-order compensation,
ESCALATED on missing undo — `/root/reference/src/hypervisor/saga/
orchestrator.py:77-198`).
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from hypervisor_tpu import Hypervisor, SessionConfig
from hypervisor_tpu.audit.delta import VFSChange, merkle_root_host
from hypervisor_tpu.models import SessionState
from hypervisor_tpu.ops import saga_ops
from hypervisor_tpu.ops.sha256 import digests_to_hex
from hypervisor_tpu.runtime.saga_scheduler import SagaScheduler
from hypervisor_tpu.state import HypervisorState
from hypervisor_tpu.tables.state import FLAG_ACTIVE


def _run(coro):
    return asyncio.run(coro)


class TestFacadeDeviceParity:
    def test_join_lands_in_device_tables(self):
        hv = Hypervisor()

        async def flow():
            managed = await hv.create_session(SessionConfig(), "did:creator")
            sid = managed.sso.session_id
            ring = await hv.join_session(sid, "did:a", sigma_raw=0.97)
            return managed, sid, ring

        managed, sid, ring = _run(flow())
        row = hv.state.agent_row("did:a")
        assert row is not None
        assert row["session"] == managed.slot
        assert row["ring"] == ring.value
        assert row["sigma_eff"] == pytest.approx(0.97)
        assert hv.state.participant_count(managed.slot) == 1

    def test_ring_assignments_match_across_planes(self):
        hv = Hypervisor()

        async def flow():
            managed = await hv.create_session(
                SessionConfig(min_sigma_eff=0.0), "did:creator"
            )
            sid = managed.sso.session_id
            rings = {}
            for did, sigma in [
                ("did:high", 0.97),
                ("did:mid", 0.75),
                ("did:low", 0.30),
            ]:
                rings[did] = await hv.join_session(sid, did, sigma_raw=sigma)
            return rings

        rings = _run(flow())
        for did, ring in rings.items():
            assert hv.state.agent_row(did)["ring"] == ring.value

    def test_merkle_roots_identical_host_vs_device(self):
        hv = Hypervisor()

        async def flow():
            managed = await hv.create_session(SessionConfig(), "did:creator")
            sid = managed.sso.session_id
            await hv.join_session(sid, "did:a", sigma_raw=0.9)
            await hv.activate_session(sid)
            for i in range(5):
                managed.delta_engine.capture(
                    "did:a",
                    [VFSChange(path=f"/f{i}", operation="add", content_hash=f"h{i}")],
                )
            host_root = managed.delta_engine.compute_merkle_root()
            returned = await hv.terminate_session(sid)
            return managed, host_root, returned

        managed, host_root, returned = _run(flow())
        # The facade return IS the device-computed root; it must equal the
        # host engine's tree over the same leaves.
        assert returned == host_root
        # Independently recompute from the device log's recorded leaves.
        leaves = hv.state.session_leaf_digests(managed.slot)
        assert merkle_root_host(digests_to_hex(leaves)) == host_root
        # The commitment engine verifies the device root.
        assert hv.commitment.verify(managed.sso.session_id, returned)

    def test_terminate_wave_releases_device_bonds_and_archives(self):
        hv = Hypervisor()

        async def flow():
            managed = await hv.create_session(SessionConfig(), "did:creator")
            sid = managed.sso.session_id
            await hv.join_session(sid, "did:voucher", sigma_raw=0.9)
            await hv.join_session(sid, "did:vouchee", sigma_raw=0.5)
            return managed, sid

        managed, sid = _run(flow())
        st = hv.state
        v = st.agent_row("did:voucher")
        e = st.agent_row("did:vouchee")
        edge = st.add_vouch(v["slot"], e["slot"], managed.slot, bond=0.18)
        assert bool(np.asarray(st.vouches.active)[edge])

        _run(hv.terminate_session(sid))
        assert not bool(np.asarray(st.vouches.active)[edge])
        assert (
            int(np.asarray(st.sessions.state)[managed.slot])
            == SessionState.ARCHIVED.code
        )
        assert not (
            int(np.asarray(st.agents.flags)[v["slot"]]) & FLAG_ACTIVE
        )

    def test_device_rejection_matches_host_exception(self):
        from hypervisor_tpu.session import SessionParticipantError

        hv = Hypervisor()

        async def flow():
            managed = await hv.create_session(
                SessionConfig(max_participants=1), "did:creator"
            )
            sid = managed.sso.session_id
            await hv.join_session(sid, "did:a", sigma_raw=0.9)
            with pytest.raises(SessionParticipantError, match="capacity"):
                await hv.join_session(sid, "did:b", sigma_raw=0.9)
            with pytest.raises(SessionParticipantError, match="already in session"):
                await hv.join_session(sid, "did:a", sigma_raw=0.9)

        _run(flow())


class TestSagaTable:
    def _state(self):
        return HypervisorState()

    def test_five_step_retry_compensate_escalate(self):
        """The bench scenario: 5 steps, retries, then forced compensation."""
        st = self._state()
        slot = st.create_session("s:saga", SessionConfig())
        g = st.create_saga(
            "saga:bench",
            slot,
            [
                {"retries": 1, "has_undo": True},
                {"has_undo": True},
                {"has_undo": False},
                {"has_undo": True},
                {"retries": 2},  # will exhaust -> compensation
            ],
        )
        sched = SagaScheduler(st, retry_backoff_seconds=0.0)
        attempts = {"s0": 0, "s4": 0}

        async def flaky_first():
            attempts["s0"] += 1
            if attempts["s0"] == 1:
                raise RuntimeError("transient")
            return "ok"

        async def ok():
            return "ok"

        async def always_fails():
            attempts["s4"] += 1
            raise RuntimeError("permanent")

        sched.register(g, 0, flaky_first, undo=ok)
        sched.register(g, 1, ok, undo=ok)
        sched.register(g, 2, ok)  # no undo API
        sched.register(g, 3, ok, undo=ok)
        sched.register(g, 4, always_fails)
        asyncio.run(sched.run_until_settled())

        states = np.asarray(st.sagas.step_state)[g]
        assert attempts["s0"] == 2           # one retry
        assert attempts["s4"] == 3           # 1 + 2 retries
        assert states[4] == saga_ops.STEP_FAILED
        assert states[3] == saga_ops.STEP_COMPENSATED
        assert states[2] == saga_ops.STEP_COMPENSATION_FAILED  # missing undo
        assert states[1] == saga_ops.STEP_COMPENSATED
        assert states[0] == saga_ops.STEP_COMPENSATED
        # Any compensation failure escalates (liability trigger).
        assert (
            int(np.asarray(st.sagas.saga_state)[g]) == saga_ops.SAGA_ESCALATED
        )

    def test_all_steps_commit_completes(self):
        st = self._state()
        slot = st.create_session("s:ok", SessionConfig())
        g = st.create_saga("saga:ok", slot, [{}, {}, {}])
        sched = SagaScheduler(st)

        async def ok():
            return 1

        for i in range(3):
            sched.register(g, i, ok)
        asyncio.run(sched.run_until_settled())
        assert (
            int(np.asarray(st.sagas.saga_state)[g]) == saga_ops.SAGA_COMPLETED
        )
        assert int(np.asarray(st.sagas.cursor)[g]) == 3

    def test_timeout_counts_as_failure(self):
        st = self._state()
        slot = st.create_session("s:slow", SessionConfig())
        g = st.create_saga("saga:slow", slot, [{"timeout": 0.05}])
        sched = SagaScheduler(st, retry_backoff_seconds=0.0)

        async def hangs():
            await asyncio.sleep(10)

        sched.register(g, 0, hangs)
        asyncio.run(sched.run_until_settled())
        assert int(np.asarray(st.sagas.saga_state)[g]) in (
            saga_ops.SAGA_COMPLETED,  # nothing committed -> settles clean
        )
        assert (
            np.asarray(st.sagas.step_state)[g, 0] == saga_ops.STEP_FAILED
        )

    def test_many_sagas_advance_in_one_round(self):
        """The point of the table: G sagas per jitted tick, not G ticks."""
        st = self._state()
        slot = st.create_session("s:many", SessionConfig())
        n = 32
        slots = [
            st.create_saga(f"saga:{i}", slot, [{}, {}]) for i in range(n)
        ]
        # Round 1: all cursor-0 steps commit at once.
        st.saga_round({g: True for g in slots})
        cursors = np.asarray(st.sagas.cursor)[slots]
        assert (cursors == 1).all()
        # Round 2: all finish.
        st.saga_round({g: True for g in slots})
        states = np.asarray(st.sagas.saga_state)[slots]
        assert (states == saga_ops.SAGA_COMPLETED).all()


class TestAgentRowGC:
    def test_terminated_sessions_reclaim_agent_rows(self):
        """A long-running state must not exhaust the agent table: rows of
        terminated sessions return to the free list and get reused."""
        st = HypervisorState()
        for round_no in range(3):
            slot = st.create_session(f"s:gc{round_no}", SessionConfig())
            for a in range(4):
                st.enqueue_join(slot, f"did:gc{round_no}:{a}", 0.8)
            assert (st.flush_joins() == 0).all()
            st.terminate_sessions([slot])
        # 12 joins total, but rows recycled: the high-water mark stays
        # at one round's worth.
        assert st._next_agent_slot == 4
        assert len(st._free_agent_slots) == 4

    def test_no_double_free_on_repeat_terminate(self):
        st = HypervisorState()
        slot = st.create_session("s:dup", SessionConfig())
        st.enqueue_join(slot, "did:x", 0.8)
        assert (st.flush_joins() == 0).all()
        st.terminate_sessions([slot])
        first = list(st._free_agent_slots)
        st.terminate_sessions([slot])  # idempotent re-terminate
        assert st._free_agent_slots == first


class TestLiabilityMirror:
    def test_host_vouch_appears_as_device_edge(self):
        hv = Hypervisor()

        async def flow():
            managed = await hv.create_session(SessionConfig(), "did:c")
            sid = managed.sso.session_id
            await hv.join_session(sid, "did:strong", sigma_raw=0.9)
            await hv.join_session(sid, "did:weak", sigma_raw=0.5)
            rec = hv.vouching.vouch("did:strong", "did:weak", sid, voucher_sigma=0.9)
            return managed, sid, rec

        managed, sid, rec = _run(flow())
        st = hv.state
        edge = hv._edge_of_vouch[rec.vouch_id]
        assert bool(np.asarray(st.vouches.active)[edge])
        assert float(np.asarray(st.vouches.bond)[edge]) == pytest.approx(
            rec.bonded_amount
        )
        assert int(np.asarray(st.vouches.session)[edge]) == managed.slot
        # host release mirrors too
        hv.vouching.release_bond(rec.vouch_id)
        assert not bool(np.asarray(st.vouches.active)[edge])

    def test_drift_slash_cascades_on_device(self):
        class Verdict:
            drift_score = 0.8
            explanation = None

        class Verifier:
            def verify_embeddings(self, **kw):
                return Verdict()

        from hypervisor_tpu.integrations import CMVKAdapter
        from hypervisor_tpu.tables.state import FLAG_BLACKLISTED

        hv = Hypervisor(cmvk=CMVKAdapter(verifier=Verifier()))

        async def flow():
            managed = await hv.create_session(SessionConfig(), "did:c")
            sid = managed.sso.session_id
            await hv.join_session(sid, "did:voucher", sigma_raw=0.9)
            await hv.join_session(sid, "did:rogue", sigma_raw=0.62)
            rec = hv.vouching.vouch("did:voucher", "did:rogue", sid, voucher_sigma=0.9)
            drift = await hv.verify_behavior(sid, "did:rogue", "claimed", "observed")
            return managed, rec, drift

        managed, rec, drift = _run(flow())
        assert drift.should_slash
        st = hv.state
        rogue = st.agent_row("did:rogue")
        voucher = st.agent_row("did:voucher")
        # device blacklisted the rogue and clipped its voucher
        assert rogue["sigma_eff"] == 0.0
        assert int(np.asarray(st.agents.flags)[rogue["slot"]]) & FLAG_BLACKLISTED
        assert voucher["sigma_eff"] == pytest.approx(
            max(0.9 * (1 - 0.95), 0.05), abs=1e-6
        )
        assert rogue["ring"] == 3  # demoted by the post-slash ring recompute
        # the consumed edge released on device
        edge = hv._edge_of_vouch.get(rec.vouch_id)
        if edge is not None:
            assert not bool(np.asarray(st.vouches.active)[edge])


class TestDslToDevice:
    def test_dsl_definition_runs_on_saga_table(self):
        """DSL -> SagaTable -> scheduler: the declarative topology drives
        the device scheduling rounds end-to-end."""
        from hypervisor_tpu.saga import SagaDSLParser

        st = HypervisorState()
        slot = st.create_session("s:dsl", SessionConfig())
        definition = SagaDSLParser().parse(
            {
                "name": "deploy",
                "session_id": "s:dsl",
                "steps": [
                    {"id": "validate", "action_id": "m.v", "agent": "did:v",
                     "undo_api": "/undo-v", "retries": 1},
                    {"id": "deploy", "action_id": "m.d", "agent": "did:d",
                     "undo_api": "/undo-d"},
                    {"id": "announce", "action_id": "m.a", "agent": "did:a"},
                ],
            }
        )
        g = st.create_saga_from_dsl(definition, slot)
        retries = np.asarray(st.sagas.retries_left)[g]
        has_undo = np.asarray(st.sagas.has_undo)[g]
        assert retries[0] == 1 and retries[1] == 0
        assert list(has_undo[:3]) == [True, True, False]

        sched = SagaScheduler(st, retry_backoff_seconds=0.0)
        calls = []

        async def ok_factory(name):
            async def run():
                calls.append(name)
                return name
            return run

        async def wire():
            sched.register_definition(
                g,
                definition,
                executors={
                    "validate": await ok_factory("validate"),
                    "deploy": await ok_factory("deploy"),
                    "announce": await ok_factory("announce"),
                },
            )
            await sched.run_until_settled()

        asyncio.run(wire())
        assert calls == ["validate", "deploy", "announce"]
        assert (
            int(np.asarray(st.sagas.saga_state)[g]) == saga_ops.SAGA_COMPLETED
        )

    def test_missing_executor_is_a_wiring_error(self):
        from hypervisor_tpu.saga import SagaDSLParser

        st = HypervisorState()
        slot = st.create_session("s:dsl2", SessionConfig())
        definition = SagaDSLParser().parse(
            {
                "name": "x", "session_id": "s",
                "steps": [{"id": "only", "action_id": "m", "agent": "d"}],
            }
        )
        g = st.create_saga_from_dsl(definition, slot)
        sched = SagaScheduler(st)
        with pytest.raises(KeyError, match="only"):
            sched.register_definition(g, definition, executors={})


class TestFullGovernanceCrossPlane:
    def test_adapters_vouch_drift_terminate_planes_agree(self):
        """The capstone scenario: IATP manifest -> Nexus sigma -> device
        admission -> mirrored vouch -> CMVK drift -> dual-plane slash ->
        device-root termination. At every stage the device tables must
        agree with the host engines."""
        from hypervisor_tpu.integrations import (
            CMVKAdapter,
            IATPAdapter,
            NexusAdapter,
        )
        from hypervisor_tpu.observability import HypervisorEventBus
        from hypervisor_tpu.tables.state import FLAG_BLACKLISTED

        class Score:
            total_score = 820
            successful_tasks = 10
            failed_tasks = 0

        class Scorer:
            slashes: list = []

            def calculate_trust_score(self, **kw):
                return Score()

            def slash_reputation(self, **kw):
                self.slashes.append((kw["agent_did"], kw["severity"]))

            def record_task_outcome(self, agent_did, outcome):
                pass

        class Verdict:
            drift_score = 0.8
            explanation = None

        class Verifier:
            def verify_embeddings(self, **kw):
                return Verdict()

        bus = HypervisorEventBus()
        hv = Hypervisor(
            nexus=NexusAdapter(scorer=Scorer()),
            cmvk=CMVKAdapter(verifier=Verifier()),
            iatp=IATPAdapter(),
            event_bus=bus,
        )

        async def flow():
            managed = await hv.create_session(SessionConfig(), "did:admin")
            sid = managed.sso.session_id
            # Manifest-driven join: sigma hint from IATP (trust_score 8).
            await hv.join_session(
                sid,
                "did:contractor",
                manifest={
                    "agent_id": "did:contractor",
                    "trust_level": "trusted",
                    "trust_score": 8,
                    "actions": [
                        {"action_id": "db.migrate", "reversibility": "partial",
                         "undo_api": "/undo"},
                    ],
                },
            )
            await hv.join_session(sid, "did:mentor", sigma_raw=0.9)
            hv.vouching.vouch("did:mentor", "did:contractor", sid, 0.9)
            await hv.activate_session(sid)
            managed.delta_engine.capture(
                "did:contractor",
                [VFSChange(path="/migration.sql", operation="add")],
            )
            drift = await hv.verify_behavior(
                sid, "did:contractor", [1, 0], [0, 1]
            )
            # Device rows are GC'd at terminate: capture the post-slash
            # device view first.
            contractor = hv.state.agent_row("did:contractor")
            mentor = hv.state.agent_row("did:mentor")
            root = await hv.terminate_session(sid)
            return managed, sid, drift, root, contractor, mentor

        managed, sid, drift, root, contractor, mentor = _run(flow())
        st = hv.state

        # Admission happened on device: both agents were resident.
        assert contractor is not None and mentor is not None

        # Drift slash hit both planes: device blacklist + host history.
        assert drift.should_slash
        assert contractor["sigma_eff"] == 0.0
        assert (
            int(np.asarray(st.agents.flags)[contractor["slot"]])
            & FLAG_BLACKLISTED
        )
        assert hv.slashing.history[-1].vouchee_did == "did:contractor"
        # Mentor clipped on device exactly as the host formula dictates.
        assert mentor["sigma_eff"] == pytest.approx(
            max(0.9 * (1 - 0.95), 0.05), abs=1e-6
        )
        assert ("did:contractor", "critical") in type(hv.nexus._scorer).slashes

        # Termination: device root committed + verified; host chain agrees.
        assert root == managed.delta_engine.compute_merkle_root()
        assert hv.commitment.verify(sid, root)
        assert (
            int(np.asarray(st.sessions.state)[managed.slot])
            == SessionState.ARCHIVED.code
        )
        # Device edges all released; GC recorded the purge.
        assert not np.asarray(st.vouches.active)[: st._next_edge_slot].any()
        assert hv.gc.is_purged(sid)
        # The event bus mirror lands the trail in the device EventLog.
        assert hv.sync_events_to_device() >= 0
        assert int(np.asarray(st.event_log.cursor)) >= bus.event_count


class TestKillSwitchHandoff:
    def test_killed_agents_steps_hand_off_and_saga_completes(self):
        """Elastic recovery on the device plane: a victim's in-flight
        steps hand off to a substitute through the kill switch, the
        scheduler rewires the executors, and the saga COMPLETES."""
        from hypervisor_tpu.security import KillReason, KillSwitch

        st = HypervisorState()
        slot = st.create_session("s:kill", SessionConfig())
        g = st.create_saga(
            "saga:kill", slot, [{"retries": 0}, {"retries": 0}, {}]
        )
        sched = SagaScheduler(st, retry_backoff_seconds=0.0)
        log = []

        async def victim_exec():
            raise RuntimeError("victim agent is dead")

        async def healthy():
            log.append("step0")
            return "ok"

        def sub_factory(name):
            async def run():
                log.append(name)
                return f"done by {name}"
            return run

        sched.register(g, 0, healthy)
        sched.register(g, 1, victim_exec)   # owned by the victim
        sched.register(g, 2, victim_exec)   # owned by the victim

        ks = KillSwitch()
        ks.register_substitute("s:kill", "did:sub")
        result = ks.kill(
            "did:victim",
            "s:kill",
            KillReason.BEHAVIORAL_DRIFT,
            in_flight_steps=[
                {"step_id": "step1", "saga_id": "saga:kill"},
                {"step_id": "step2", "saga_id": "saga:kill"},
            ],
        )
        assert result.handoff_success_count == 2

        rewired = sched.apply_handoffs(
            result,
            step_index={
                ("saga:kill", "step1"): (g, 1),
                ("saga:kill", "step2"): (g, 2),
            },
            substitute_executors={"did:sub": sub_factory("substitute")},
        )
        assert rewired == 2
        asyncio.run(sched.run_until_settled())
        assert (
            int(np.asarray(st.sagas.saga_state)[g]) == saga_ops.SAGA_COMPLETED
        )
        assert log == ["step0", "substitute", "substitute"]

    def test_no_substitute_routes_to_compensation(self):
        from hypervisor_tpu.security import KillReason, KillSwitch

        st = HypervisorState()
        slot = st.create_session("s:nokill", SessionConfig())
        g = st.create_saga("saga:nk", slot, [{"has_undo": True}, {}])
        sched = SagaScheduler(st, retry_backoff_seconds=0.0)

        async def ok():
            return "ok"

        async def dead():
            raise RuntimeError("victim gone")

        sched.register(g, 0, ok, undo=ok)
        sched.register(g, 1, dead)

        ks = KillSwitch()  # empty substitute pool
        result = ks.kill(
            "did:victim", "s:nokill", KillReason.MANUAL,
            in_flight_steps=[{"step_id": "s1", "saga_id": "saga:nk"}],
        )
        assert result.compensation_triggered
        # No substitute: the dead executor stays; the saga fails forward
        # into compensation and settles cleanly (step 0 undone).
        sched.apply_handoffs(result, {("saga:nk", "s1"): (g, 1)}, {})
        asyncio.run(sched.run_until_settled())
        states = np.asarray(st.sagas.step_state)[g]
        assert states[0] == saga_ops.STEP_COMPENSATED
        assert states[1] == saga_ops.STEP_FAILED

    def test_handoff_restores_retry_budget_and_rearm(self):
        """A substitute inherits a FRESH retry ladder, and a step the
        victim already drove to FAILED is rearmed while the saga runs."""
        from hypervisor_tpu.security import KillReason, KillSwitch

        st = HypervisorState()
        slot = st.create_session("s:rearm", SessionConfig())
        g = st.create_saga("saga:rearm", slot, [{"retries": 1}])
        sched = SagaScheduler(st, retry_backoff_seconds=0.0)

        async def dead():
            raise RuntimeError("victim gone")

        sched.register(g, 0, dead)
        # Victim burns the retry budget (but saga not yet settled: the
        # second round would fail it, so only run one round).
        st.saga_round({g: False})
        assert int(np.asarray(st.sagas.retries_left)[g, 0]) == 0

        ks = KillSwitch()
        ks.register_substitute("s:rearm", "did:sub")
        result = ks.kill(
            "did:victim", "s:rearm", KillReason.MANUAL,
            in_flight_steps=[{"step_id": "s0", "saga_id": "saga:rearm"}],
        )
        calls = {"n": 0}

        async def flaky_sub():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("substitute warm-up flake")
            return "ok"

        sched.apply_handoffs(
            result,
            {("saga:rearm", "s0"): (g, 0)},
            {"did:sub": flaky_sub},
            retries=1,
        )
        assert int(np.asarray(st.sagas.retries_left)[g, 0]) == 1
        asyncio.run(sched.run_until_settled())
        assert calls["n"] == 2  # substitute retried on its fresh budget
        assert (
            int(np.asarray(st.sagas.saga_state)[g]) == saga_ops.SAGA_COMPLETED
        )


class TestDeviceFanOut:
    """DSL fan-out groups scheduled on the device SagaTable: branches
    dispatch concurrently, settle via ops.saga_ops.fanout_round, and
    policy failures unwind committed branches through the reverse walk
    (reference `saga/fan_out.py:110-179`)."""

    def _definition(self, policy: str, n_branches: int = 3, tail: bool = True):
        from hypervisor_tpu.saga.dsl import SagaDSLParser

        steps = [
            {"id": f"b{i}", "action_id": f"m.b{i}", "agent": "did:f",
             "execute_api": f"/b{i}", "undo_api": f"/ub{i}"}
            for i in range(n_branches)
        ]
        if tail:
            steps.append(
                {"id": "finish", "action_id": "m.finish", "agent": "did:f",
                 "execute_api": "/fin"}
            )
        return SagaDSLParser().parse({
            "name": "fan",
            "session_id": "session:fan",
            "steps": steps,
            "fan_out": [{
                "policy": policy,
                "branches": [f"b{i}" for i in range(n_branches)],
            }],
        })

    def _run(self, policy, branch_ok, tail=True):
        import asyncio
        import numpy as np

        from hypervisor_tpu.models import SessionConfig
        from hypervisor_tpu.ops import saga_ops
        from hypervisor_tpu.runtime.saga_scheduler import SagaScheduler
        from hypervisor_tpu.state import HypervisorState

        st = HypervisorState()
        sess = st.create_session("session:fan", SessionConfig())
        definition = self._definition(policy, len(branch_ok), tail)
        slot = st.create_saga_from_dsl(definition, sess)
        sched = SagaScheduler(st, retry_backoff_seconds=0.0)
        ran: list[str] = []

        def mk(i, ok):
            async def run():
                ran.append(f"b{i}")
                if not ok:
                    raise RuntimeError("branch down")
                return f"ok{i}"
            return run

        async def undo(i):
            ran.append(f"undo-b{i}")
            return "undone"

        executors = {f"b{i}": mk(i, ok) for i, ok in enumerate(branch_ok)}
        undos = {f"b{i}": (lambda i=i: undo(i)) for i in range(len(branch_ok))}
        if tail:
            async def fin():
                ran.append("finish")
                return "done"
            executors["finish"] = fin
        sched.register_definition(slot, definition, executors, undos=undos)
        asyncio.run(sched.run_until_settled())
        return st, slot, ran, saga_ops, np

    def test_all_policy_success_runs_tail(self):
        st, slot, ran, ops, np = self._run("all_must_succeed", [True, True, True])
        assert int(np.asarray(st.sagas.saga_state)[slot]) == ops.SAGA_COMPLETED
        # branches dispatched before the tail; all three ran
        assert set(ran[:3]) == {"b0", "b1", "b2"} and ran[3] == "finish"

    def test_all_policy_failure_compensates_winners(self):
        st, slot, ran, ops, np = self._run("all_must_succeed", [True, False, True])
        states = np.asarray(st.sagas.step_state)[slot]
        # winners compensated in reverse order, loser stays FAILED,
        # tail never ran, saga COMPLETED after clean compensation.
        assert int(np.asarray(st.sagas.saga_state)[slot]) == ops.SAGA_COMPLETED
        assert states[0] == ops.STEP_COMPENSATED
        assert states[1] == ops.STEP_FAILED
        assert states[2] == ops.STEP_COMPENSATED
        assert "finish" not in ran
        assert ran.index("undo-b2") < ran.index("undo-b0")  # reverse order

    def test_majority_policy_tolerates_minority_failure(self):
        st, slot, ran, ops, np = self._run(
            "majority_must_succeed", [True, True, False]
        )
        assert int(np.asarray(st.sagas.saga_state)[slot]) == ops.SAGA_COMPLETED
        states = np.asarray(st.sagas.step_state)[slot]
        assert states[2] == ops.STEP_FAILED       # minority loss tolerated
        assert "finish" in ran                    # saga continued past group

    def test_any_policy_single_survivor(self):
        st, slot, ran, ops, np = self._run(
            "any_must_succeed", [False, False, True]
        )
        assert int(np.asarray(st.sagas.saga_state)[slot]) == ops.SAGA_COMPLETED
        assert "finish" in ran

    def test_any_policy_total_failure_compensates(self):
        st, slot, ran, ops, np = self._run(
            "any_must_succeed", [False, False], tail=False
        )
        # Nothing committed; saga settles without escalation.
        assert int(np.asarray(st.sagas.saga_state)[slot]) == ops.SAGA_COMPLETED
        assert "finish" not in ran

    def test_non_contiguous_branches_rejected(self):
        from hypervisor_tpu.models import SessionConfig
        from hypervisor_tpu.saga.dsl import SagaDSLParser
        from hypervisor_tpu.state import HypervisorState
        import pytest

        definition = SagaDSLParser().parse({
            "name": "bad",
            "session_id": "session:bad",
            "steps": [
                {"id": "b0", "action_id": "m.b0", "agent": "d", "execute_api": "/0"},
                {"id": "mid", "action_id": "m.mid", "agent": "d", "execute_api": "/m"},
                {"id": "b2", "action_id": "m.b2", "agent": "d", "execute_api": "/2"},
            ],
            "fan_out": [
                {"policy": "all_must_succeed", "branches": ["b0", "b2"]}
            ],
        })
        st = HypervisorState()
        sess = st.create_session("session:bad", SessionConfig())
        with pytest.raises(ValueError, match="consecutive"):
            st.create_saga_from_dsl(definition, sess)


class TestDeltaLogWrapGuard:
    def _small_log_state(self, capacity=8):
        import dataclasses

        from hypervisor_tpu.config import DEFAULT_CONFIG
        from hypervisor_tpu.state import HypervisorState

        config = dataclasses.replace(
            DEFAULT_CONFIG,
            capacity=dataclasses.replace(
                DEFAULT_CONFIG.capacity, delta_log_capacity=capacity
            ),
        )
        return HypervisorState(config)

    def test_wrap_into_live_session_refused(self):
        import numpy as np
        import pytest

        from hypervisor_tpu.models import SessionConfig
        from hypervisor_tpu.ops.sha256 import hex_to_words

        st = self._small_log_state(capacity=8)
        slot = st.create_session("session:wrapA", SessionConfig())
        st.enqueue_join(slot, "did:w", sigma_raw=0.8)
        assert (st.flush_joins() == 0).all()
        digest = hex_to_words(["ab" * 32])[0]
        for _ in range(8):
            st.stage_delta(slot, 0, ts=0.0, digest_words=digest)
        st.flush_deltas()
        # The 9th delta would recycle the live session's own first row.
        st.stage_delta(slot, 0, ts=0.0, digest_words=digest)
        with pytest.raises(RuntimeError, match="delta log wrapped into live"):
            st.flush_deltas()

    def test_archived_rows_recycle_silently(self):
        import numpy as np

        from hypervisor_tpu.models import SessionConfig
        from hypervisor_tpu.ops.sha256 import hex_to_words

        st = self._small_log_state(capacity=8)
        digest = hex_to_words(["cd" * 32])[0]
        a = st.create_session("session:wrapB", SessionConfig())
        st.enqueue_join(a, "did:a", sigma_raw=0.8)
        assert (st.flush_joins() == 0).all()
        for _ in range(8):
            st.stage_delta(a, 0, ts=0.0, digest_words=digest)
        st.flush_deltas()
        st.terminate_sessions([a], now=1.0)   # archived -> rows reusable

        b = st.create_session("session:wrapC", SessionConfig())
        st.enqueue_join(b, "did:b", sigma_raw=0.8)
        assert (st.flush_joins() == 0).all()
        for _ in range(6):
            st.stage_delta(b, 1, ts=2.0, digest_words=digest)
        st.flush_deltas()                      # wraps over A's rows: fine
        assert len(st._audit_rows.get(b, [])) == 6


# ── moved from tests/unit (round-5): these touch the device plane
# (ops constants / batched saga ops execute XLA), which the unit
# modules must stay free of — they are the blocking Windows CI
# subset (tests/conftest.py _HOST_PLANE_FILES).


class TestBatchedSagaOps:
    def test_transition_matrix_gather(self):
        from hypervisor_tpu.ops import saga_ops

        frm = np.array([0, 1, 1, 2, 6], np.int8)  # P, E, E, C, F
        to = np.array([1, 2, 6, 3, 1], np.int8)   # E, C, F, CP, E
        valid = np.asarray(saga_ops.step_transition_valid(frm, to))
        assert valid.tolist() == [True, True, True, True, False]

    def test_execute_attempt_retry_ladder(self):
        from hypervisor_tpu.ops import saga_ops

        state = np.zeros(3, np.int8)  # all PENDING
        success = np.array([True, False, False])
        retries = np.array([0, 1, 0], np.int32)
        new_state, new_retries = saga_ops.execute_attempt(state, success, retries)
        assert np.asarray(new_state).tolist() == [
            saga_ops.STEP_COMMITTED,
            saga_ops.STEP_PENDING,   # retrying
            saga_ops.STEP_FAILED,
        ]
        assert np.asarray(new_retries).tolist() == [0, 0, 0]

    def test_fanout_policy_check_batch(self):
        from hypervisor_tpu.ops import saga_ops

        success = np.array([[1, 1, 1], [1, 0, 0], [0, 0, 1]], bool)
        valid = np.ones((3, 3), bool)
        policy = np.array([0, 1, 2], np.int8)  # ALL, MAJORITY, ANY
        out = np.asarray(saga_ops.fanout_policy_check(success, valid, policy))
        assert out.tolist() == [True, False, True]

    def test_settle_sagas(self):
        from hypervisor_tpu.ops import saga_ops

        step_state = np.array(
            [
                [2, 2, 0],  # committed + pending -> completed
                [4, 5, 4],  # compensation failed -> escalated
                [4, 4, 4],  # all compensated -> completed
            ],
            np.int8,
        )
        saga_state = np.array(
            [saga_ops.SAGA_RUNNING, saga_ops.SAGA_COMPENSATING, saga_ops.SAGA_COMPENSATING],
            np.int8,
        )
        out = np.asarray(saga_ops.settle_sagas(step_state, saga_state))
        assert out.tolist() == [
            saga_ops.SAGA_COMPLETED,
            saga_ops.SAGA_ESCALATED,
            saga_ops.SAGA_COMPLETED,
        ]


class TestStatusMapping:
    """utils.status: batched codes -> the reference's exception types."""

    def test_admission_codes_raise_reference_exceptions(self):
        import pytest

        from hypervisor_tpu.ops import admission
        from hypervisor_tpu.session import (
            SessionLifecycleError,
            SessionParticipantError,
        )
        from hypervisor_tpu.utils import status as S

        S.raise_for_status([0, 0, 0])  # all ok: no raise
        with pytest.raises(SessionParticipantError, match="did:dup already"):
            S.raise_for_status(
                [0, admission.ADMIT_DUPLICATE],
                who=["did:a", "did:dup"],
            )
        with pytest.raises(SessionLifecycleError):
            S.raise_for_status([admission.ADMIT_BAD_STATE])
        with pytest.raises(RuntimeError, match="unknown status"):
            S.raise_for_status([99])

    def test_write_and_lock_tables(self):
        import pytest

        from hypervisor_tpu.runtime.lock_wave import LOCK_DEADLOCK
        from hypervisor_tpu.runtime.write_wave import WRITE_QUARANTINED
        from hypervisor_tpu.session.intent_locks import DeadlockError
        from hypervisor_tpu.utils import status as S

        with pytest.raises(S.QuarantinedError):
            S.raise_for_status([WRITE_QUARANTINED], table=S.WRITE_ERRORS)
        with pytest.raises(DeadlockError):
            S.raise_for_status([LOCK_DEADLOCK], table=S.LOCK_ERRORS)

    def test_describe_labels(self):
        from hypervisor_tpu.ops import admission
        from hypervisor_tpu.utils import status as S

        labels = S.describe([0, admission.ADMIT_CAPACITY, 42])
        assert labels == ["ok", "SessionParticipantError", "unknown(42)"]
