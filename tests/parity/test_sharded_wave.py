"""The fully-sharded fused governance wave vs the single-device wave.

Round-3 item (VERDICT #4): ONE shard_map program over the real tables —
Agent rows + Vouch edges sharded over an 8-device mesh, SessionTable
replicated — must reproduce the single-device `ops.pipeline.
governance_wave` bit-for-bit: admission statuses, rings, vouched
sigma_eff, chain digests, Merkle roots, FSM walks, bond releases, and
every output table column. Reference semantics anchor:
`/root/reference/benchmarks/bench_hypervisor.py:217-239`.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from hypervisor_tpu.models import SessionState
from hypervisor_tpu.ops import admission
from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.ops.pipeline import governance_wave
from hypervisor_tpu.parallel import make_mesh
from hypervisor_tpu.parallel.collectives import sharded_governance_wave
from hypervisor_tpu.tables.state import AgentTable, SessionTable, VouchTable
from hypervisor_tpu.tables.struct import replace as t_replace

N_DEV = 8
ROWS_PER_SHARD = 8
N_CAP = N_DEV * ROWS_PER_SHARD
E_CAP = N_DEV * 4
S_CAP = 16
B = 16            # joining agents (2 per shard)
K = 8             # wave sessions (1 per shard)
T = 3             # deltas per session
NOW = 12.5
OMEGA = 0.5


def _tables(capacity=10, min_sigma=0.6):
    agents = AgentTable.create(N_CAP)
    sessions = SessionTable.create(S_CAP)
    ws = jnp.arange(K)
    sessions = t_replace(
        sessions,
        state=sessions.state.at[ws].set(
            jnp.int8(SessionState.HANDSHAKING.code)
        ),
        max_participants=sessions.max_participants.at[ws].set(capacity),
        min_sigma_eff=sessions.min_sigma_eff.at[ws].set(min_sigma),
    )
    vouches = VouchTable.create(E_CAP)
    return agents, sessions, vouches


def _wave_inputs():
    """B joiners, 2 per wave session; slots satisfy the shard contract
    (element i's row lives on shard i // (B/D)); a few vouch edges whose
    rows live on shards OTHER than their vouchee's row shard."""
    b_local = B // N_DEV
    slots = np.array(
        [(i // b_local) * ROWS_PER_SHARD + (i % b_local) for i in range(B)],
        np.int32,
    )
    dids = np.arange(B, dtype=np.int32)
    agent_sessions = np.array([i // 2 for i in range(B)], np.int32)
    sigma = np.full(B, 0.8, np.float32)
    # Elements 0 and 5 join with low sigma; vouch edges lift them.
    sigma[0] = 0.45
    sigma[5] = 0.50
    trustworthy = np.ones(B, bool)
    trustworthy[7] = False  # sandboxed (floor-exempt)
    duplicate = np.zeros(B, bool)
    rng = np.random.RandomState(7)
    bodies = rng.randint(
        0, 2**32, size=(T, K, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    return slots, dids, agent_sessions, sigma, trustworthy, duplicate, bodies


def _add_vouches(vouches, slots, agent_sessions):
    """Edges on shards 2 and 5 (rows 9 and 21) vouching for the low-sigma
    joiners whose agent rows live on shards 0 and 2 — the contribution
    psum must cross shards."""
    for row, (element, bond) in ((9, (0, 0.40)), (21, (5, 0.30))):
        vouches = t_replace(
            vouches,
            voucher=vouches.voucher.at[row].set(N_CAP - 1),  # phantom
            vouchee=vouches.vouchee.at[row].set(int(slots[element])),
            session=vouches.session.at[row].set(int(agent_sessions[element])),
            bond=vouches.bond.at[row].set(bond),
            active=vouches.active.at[row].set(True),
        )
    return vouches


class TestShardedGovernanceWave:
    def _both(self):
        slots, dids, sess, sigma, trust, dup, bodies = _wave_inputs()
        wave_sessions = np.arange(K, dtype=np.int32)

        agents, sessions, vouches = _tables()
        vouches = _add_vouches(vouches, slots, sess)
        args = (
            jnp.asarray(slots),
            jnp.asarray(dids),
            jnp.asarray(sess),
            jnp.asarray(sigma),
            jnp.asarray(trust),
            jnp.asarray(dup),
            jnp.asarray(wave_sessions),
            jnp.asarray(bodies),
            NOW,
            OMEGA,
        )
        single = jax.jit(governance_wave, static_argnames=("use_pallas",))(
            agents, sessions, vouches, *args, use_pallas=False
        )

        mesh = make_mesh(N_DEV, platform="cpu")
        fused = sharded_governance_wave(mesh)
        agents2, sessions2, vouches2 = _tables()
        vouches2 = _add_vouches(vouches2, slots, sess)
        sharded = fused(agents2, sessions2, vouches2, *args)
        return single, sharded

    def test_bit_parity_with_single_device_wave(self):
        single, sharded = self._both()

        np.testing.assert_array_equal(
            np.asarray(sharded.status), np.asarray(single.status)
        )
        np.testing.assert_array_equal(
            np.asarray(sharded.ring), np.asarray(single.ring)
        )
        np.testing.assert_array_equal(
            np.asarray(sharded.sigma_eff), np.asarray(single.sigma_eff)
        )
        np.testing.assert_array_equal(
            np.asarray(sharded.saga_step_state),
            np.asarray(single.saga_step_state),
        )
        np.testing.assert_array_equal(
            np.asarray(sharded.chain), np.asarray(single.chain)
        )
        np.testing.assert_array_equal(
            np.asarray(sharded.merkle_root), np.asarray(single.merkle_root)
        )
        np.testing.assert_array_equal(
            np.asarray(sharded.fsm_error), np.asarray(single.fsm_error)
        )
        assert int(np.asarray(sharded.released)) == int(
            np.asarray(single.released)
        )

    def test_output_tables_bit_identical(self):
        single, sharded = self._both()
        for col in (
            "did", "session", "sigma_raw", "sigma_eff", "ring", "flags",
            "joined_at",
        ):
            np.testing.assert_array_equal(
                np.asarray(getattr(sharded.agents, col)),
                np.asarray(getattr(single.agents, col)),
                err_msg=f"agents.{col} diverged",
            )
        for col in ("state", "n_participants", "terminated_at"):
            np.testing.assert_array_equal(
                np.asarray(getattr(sharded.sessions, col)),
                np.asarray(getattr(single.sessions, col)),
                err_msg=f"sessions.{col} diverged",
            )
        np.testing.assert_array_equal(
            np.asarray(sharded.vouches.active),
            np.asarray(single.vouches.active),
        )

    def test_contiguous_variant_bit_parity(self):
        """contiguous_waves=True (range compares, no terminate mask psum)
        must equal the mask-psum variant on every output."""
        slots, dids, sess, sigma, trust, dup, bodies = _wave_inputs()
        wave_sessions = np.arange(K, dtype=np.int32)
        args = (
            jnp.asarray(slots),
            jnp.asarray(dids),
            jnp.asarray(sess),
            jnp.asarray(sigma),
            jnp.asarray(trust),
            jnp.asarray(dup),
            jnp.asarray(wave_sessions),
            jnp.asarray(bodies),
            NOW,
            OMEGA,
        )
        mesh = make_mesh(N_DEV, platform="cpu")

        agents, sessions, vouches = _tables()
        vouches = _add_vouches(vouches, slots, sess)
        masked = sharded_governance_wave(mesh)(agents, sessions, vouches, *args)

        agents2, sessions2, vouches2 = _tables()
        vouches2 = _add_vouches(vouches2, slots, sess)
        ranged = sharded_governance_wave(mesh, contiguous_waves=True)(
            agents2, sessions2, vouches2, *args,
            jnp.asarray(0, jnp.int32), jnp.asarray(K, jnp.int32),
        )

        for field in ("status", "ring", "sigma_eff", "saga_step_state",
                      "chain", "merkle_root", "fsm_error"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ranged, field)),
                np.asarray(getattr(masked, field)),
                err_msg=f"{field} diverged",
            )
        assert int(np.asarray(ranged.released)) == int(
            np.asarray(masked.released)
        )
        for col in ("did", "session", "sigma_eff", "ring", "flags"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ranged.agents, col)),
                np.asarray(getattr(masked.agents, col)),
                err_msg=f"agents.{col} diverged",
            )
        for col in ("state", "n_participants", "terminated_at"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ranged.sessions, col)),
                np.asarray(getattr(masked.sessions, col)),
                err_msg=f"sessions.{col} diverged",
            )
        np.testing.assert_array_equal(
            np.asarray(ranged.vouches.active),
            np.asarray(masked.vouches.active),
        )

    def test_wave_semantics(self):
        """Sanity on the shared outcome (not just parity): vouched lifts,
        sandbox, archives, bond release."""
        _, sharded = self._both()
        status = np.asarray(sharded.status)
        ring = np.asarray(sharded.ring)
        sig = np.asarray(sharded.sigma_eff)
        assert (status == admission.ADMIT_OK).all()
        # Vouched element 0: 0.45 + 0.5*0.40 = 0.65 -> Ring 2.
        assert sig[0] == pytest.approx(0.65) and ring[0] == 2
        # Vouched element 5: 0.50 + 0.5*0.30 = 0.65 -> Ring 2.
        assert sig[5] == pytest.approx(0.65) and ring[5] == 2
        # Untrustworthy element 7 sandboxed.
        assert ring[7] == 3
        # Every wave session archived with stamped terminated_at.
        sess_state = np.asarray(sharded.sessions.state)[:K]
        assert (sess_state == SessionState.ARCHIVED.code).all()
        assert (np.asarray(sharded.sessions.terminated_at)[:K] == NOW).all()
        # Both cross-shard vouch bonds released at terminate.
        assert int(np.asarray(sharded.released)) == 2
        assert not np.asarray(sharded.fsm_error).any()
