"""admit_batch vs an independent scalar Python oracle.

The oracle re-implements the reference's join semantics directly from
the reference's rules (NOT by calling any hypervisor_tpu op):
per-agent, in wave order — state guard, duplicate, sigma floor with
the sandbox exemption, then capacity as seats fill
(`/root/reference/src/hypervisor/session/__init__.py:85-113`,
`core.py:153-175`; ring thresholds `models.py:34-42`; vouched
sigma_eff `liability/vouching.py:128-151`). Randomized waves with
mixed duplicates, tight capacities, low sigmas, and untrustworthy
agents must produce identical statuses, rings, sigma_eff, and
participant counts on both the ranked and unique-free paths where they
apply.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from hypervisor_tpu.config import DEFAULT_CONFIG
from hypervisor_tpu.models import SessionState
from hypervisor_tpu.ops import admission
from hypervisor_tpu.tables.state import AgentTable, SessionTable
from hypervisor_tpu.tables.struct import replace as t_replace

B, S_CAP, N_CAP = 48, 12, 96
OMEGA = 0.5


def _oracle(wave, sessions_init, trust):
    """Reference-semantics scalar walk. Returns (status, ring,
    sigma_eff, counts)."""
    state = dict(sessions_init["state"])
    counts = dict(sessions_init["counts"])
    cap = sessions_init["max_participants"]
    min_sig = sessions_init["min_sigma_eff"]
    out_status, out_ring, out_sig = [], [], []
    for lane in wave:
        s = lane["session"]
        sigma_eff = min(lane["sigma_raw"] + OMEGA * lane["contribution"], 1.0)
        # Ring from sigma (no consensus in this wave), sandbox override.
        if lane["trustworthy"]:
            if sigma_eff > trust.ring1_threshold:  # needs consensus -> never 1
                ring = 2 if sigma_eff > trust.ring2_threshold else 3
            elif sigma_eff > trust.ring2_threshold:
                ring = 2
            else:
                ring = 3
        else:
            ring = 3
        status = 0
        if state[s] not in (
            SessionState.HANDSHAKING.code,
            SessionState.ACTIVE.code,
        ):
            status = admission.ADMIT_BAD_STATE
        elif lane["duplicate"]:
            status = admission.ADMIT_DUPLICATE
        elif sigma_eff < min_sig[s] and ring != 3:
            status = admission.ADMIT_SIGMA_LOW
        elif counts[s] >= cap[s]:
            status = admission.ADMIT_CAPACITY
        if status == 0:
            counts[s] += 1
        out_status.append(status)
        out_ring.append(ring)
        out_sig.append(sigma_eff)
    return out_status, out_ring, out_sig, counts


@pytest.mark.parametrize("seed", range(6))
def test_admit_batch_matches_scalar_oracle(seed):
    rng = np.random.RandomState(100 + seed)
    trust = DEFAULT_CONFIG.trust

    # Sessions: random states (mostly joinable), tight capacities,
    # random floors, some pre-filled counts.
    states = rng.choice(
        [
            SessionState.CREATED.code,
            SessionState.HANDSHAKING.code,
            SessionState.ACTIVE.code,
            SessionState.ARCHIVED.code,
        ],
        size=S_CAP,
        p=[0.1, 0.5, 0.3, 0.1],
    ).astype(np.int8)
    caps = rng.randint(1, 5, S_CAP)
    floors = rng.choice([0.0, 0.6, 0.8], size=S_CAP)
    pre_counts = rng.randint(0, 2, S_CAP)

    sessions = SessionTable.create(S_CAP)
    sessions = t_replace(
        sessions,
        state=jnp.asarray(states),
        max_participants=jnp.asarray(caps, jnp.int32),
        min_sigma_eff=jnp.asarray(floors, jnp.float32),
        n_participants=jnp.asarray(pre_counts, jnp.int32),
    )
    agents = AgentTable.create(N_CAP)

    session_slot = rng.randint(0, S_CAP, B).astype(np.int32)
    sigma_raw = rng.choice([0.3, 0.55, 0.7, 0.9, 0.99], size=B).astype(
        np.float32
    )
    contribution = rng.choice([0.0, 0.0, 0.2, 0.5], size=B).astype(np.float32)
    trustworthy = rng.rand(B) > 0.15
    duplicate = rng.rand(B) < 0.1

    wave = [
        dict(
            session=int(session_slot[i]),
            sigma_raw=float(sigma_raw[i]),
            contribution=float(contribution[i]),
            trustworthy=bool(trustworthy[i]),
            duplicate=bool(duplicate[i]),
        )
        for i in range(B)
    ]
    want_status, want_ring, want_sig, want_counts = _oracle(
        wave,
        dict(
            state={i: int(states[i]) for i in range(S_CAP)},
            counts={i: int(pre_counts[i]) for i in range(S_CAP)},
            max_participants={i: int(caps[i]) for i in range(S_CAP)},
            min_sigma_eff={i: float(floors[i]) for i in range(S_CAP)},
        ),
        trust,
    )

    got = admission.admit_batch(
        agents,
        sessions,
        slot=jnp.arange(B, dtype=jnp.int32),
        did=jnp.arange(B, dtype=jnp.int32),
        session_slot=jnp.asarray(session_slot),
        sigma_raw=jnp.asarray(sigma_raw),
        trustworthy=jnp.asarray(trustworthy),
        duplicate=jnp.asarray(duplicate),
        now=1.0,
        contribution=jnp.asarray(contribution),
        omega=OMEGA,
    )
    np.testing.assert_array_equal(np.asarray(got.status), want_status)
    np.testing.assert_array_equal(np.asarray(got.ring), want_ring)
    np.testing.assert_allclose(
        np.asarray(got.sigma_eff), np.asarray(want_sig, np.float32),
        rtol=0, atol=1e-6,
    )
    got_counts = np.asarray(got.sessions.n_participants)
    for s in range(S_CAP):
        assert int(got_counts[s]) == want_counts[s], (s, seed)
