"""Property: mixed STRONG/EVENTUAL ticks converge to the all-STRONG
result for ARBITRARY mode assignments and lane targets.

`mode_tick` routes each lane's session delta by the session's mode
column — STRONG in-tick psum, EVENTUAL deferred to reconcile. After the
reconcile, no interleaving of modes may change the final SessionTable:
consistency modes trade freshness, never outcomes (SURVEY §5 mapping of
the reference's ConsistencyMode flag).
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
hyp = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from hypervisor_tpu.models import ConsistencyMode, SessionConfig
from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.parallel import make_mesh
from hypervisor_tpu.state import HypervisorState

N_DEV = 8
LANES = 16
S = 6  # sessions
T = 2

_mesh = None


def mesh():
    global _mesh
    if _mesh is None:
        _mesh = make_mesh(N_DEV, platform="cpu")
    return _mesh


def _run(modes: list[int], lane_sessions: list[int], sigma: list[float]):
    """One mixed-mode tick + reconcile on a fresh facade; returns the
    final participant counts."""
    from hypervisor_tpu import Hypervisor

    hv = Hypervisor(state=HypervisorState())
    import asyncio

    async def build():
        slots = []
        for i in range(S):
            ms = await hv.create_session(
                SessionConfig(
                    consistency_mode=(
                        ConsistencyMode.STRONG
                        if modes[i]
                        else ConsistencyMode.EVENTUAL
                    ),
                    min_sigma_eff=0.0,
                    max_participants=64,
                ),
                creator_did="did:lead",
            )
            slots.append(ms.slot)
        return slots

    slots = asyncio.run(build())
    rt = hv.consistency_runtime(mesh())
    rng = np.random.RandomState(0)
    bodies = rng.randint(
        0, 2**32, size=(T, LANES, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    rt.tick(
        np.array([slots[s] for s in lane_sessions], np.int32),
        np.asarray(sigma, np.float32),
        np.ones(LANES, bool),
        bodies,
    )
    rt.reconcile()
    return np.asarray(hv.state.sessions.n_participants)[: S + 1].copy()


@settings(max_examples=10, deadline=None)
@given(
    modes=st.lists(st.integers(0, 1), min_size=S, max_size=S),
    lane_sessions=st.lists(
        st.integers(0, S - 1), min_size=LANES, max_size=LANES
    ),
    sigma=st.lists(
        st.floats(0.3, 1.0), min_size=LANES, max_size=LANES
    ),
)
def test_mixed_modes_converge_to_all_strong(modes, lane_sessions, sigma):
    mixed = _run(modes, lane_sessions, sigma)
    all_strong = _run([1] * S, lane_sessions, sigma)
    np.testing.assert_array_equal(mixed, all_strong)
