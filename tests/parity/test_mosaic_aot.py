"""Compiled-Mosaic proof WITHOUT a chip: deviceless AOT for TPU v5e.

Round 1-4 could only run the Pallas kernels under the interpreter
unless the accelerator tunnel was healthy (`HV_TPU_TESTS=1`), so
"layout/lowering bugs only appear in the real backend" stayed an open
risk (VERDICT r4 weak #2). This file closes the LOWERING half without
any device: `jax.experimental.topologies.get_topology_desc("tpu",
"v5e:2x4")` builds a deviceless PJRT topology for exactly the
BASELINE target (TPU v5 lite, 8 chips), and `jit(...).lower(...)
.compile()` against it runs the real XLA:TPU + Mosaic compiler —
layout assignment, Mosaic lowering of the fully-unrolled SHA-256, MXU
tiling of the liability cascade, the whole bench-shaped wave program.
A kernel that would fail to lower on hardware fails HERE, with no
tunnel in the loop — on any machine with the TPU PJRT plugin installed
(the dev/driver environments), which is where the Mosaic code is
developed. (Execution-time parity remains chip-gated: `HV_TPU_TESTS=1`
+ `benchmarks/capture_evidence.py`; the kernels' numerics are
interpreter-verified bit-exact against hashlib.)

Skips cleanly where the TPU PJRT plugin is absent — including GitHub
CI, so the merge gate does NOT carry this proof; the dev-machine suite
and the round driver do.
"""

from __future__ import annotations

import functools
import os
import subprocess
import sys
from functools import partial

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

# This module bills tier-1 ~45 s every run no matter what the tunnel
# does: with the TPU plugin healthy it is the deviceless XLA:TPU +
# Mosaic compile of the fully-unrolled SHA-256 (the persistent cache
# can't absorb it — DeserializeLoadedExecutable is unimplemented for
# deviceless AOT executables, see `_no_persistent_cache` below), and
# with the tunnel down it is the full `_PROBE_TIMEOUT_S` burned before
# the skip. The sibling proofs ride the first test's in-process Mosaic
# kernel cache, so deselecting one just moves the bill. The lowering
# proof only changes when the kernels change — the whole module runs on
# the nightly leg (`-m slow`) rather than inside the tier-1 wall
# budget.
pytestmark = pytest.mark.slow

TOPOLOGY = "v5e:2x4"

#: Hard bound on the plugin capability probe. The TPU PJRT plugin
#: connects through a tunnel that can wedge a process INDEFINITELY
#: (docs/OPERATIONS.md "Wedged-accelerator posture" — observed live:
#: this module's `get_topology_desc` hung an entire tier-1 run inside
#: `initialize_pjrt_plugin`). A capability probe must skip, not hang.
_PROBE_TIMEOUT_S = float(os.environ.get("HV_AOT_PROBE_TIMEOUT", "45"))


@functools.lru_cache(maxsize=None)
def _topology_unavailable_reason() -> str | None:
    """None when the deviceless TPU topology is usable; else the skip
    reason. Probed once per session in a SUBPROCESS with a hard
    timeout, so a wedged tunnel costs this module a bounded skip
    instead of hanging the suite at `initialize_pjrt_plugin`."""
    code = (
        "from jax.experimental import topologies\n"
        "topologies.get_topology_desc("
        f"platform='tpu', topology_name={TOPOLOGY!r})\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=_PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        return (
            f"TPU PJRT plugin wedged: topology probe exceeded "
            f"{_PROBE_TIMEOUT_S:.0f}s (tunnel down? see OPERATIONS.md)"
        )
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()
        return tail[-1] if tail else f"probe rc={proc.returncode}"
    return None


@pytest.fixture(autouse=True)
def _no_persistent_cache():
    """Deviceless AOT executables cannot round-trip the persistent
    compilation cache (DeserializeLoadedExecutable unimplemented) —
    writing entries just burns disk and warns on every later run.
    Disable the cache for this module only."""
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def _v5e_sharding():
    reason = _topology_unavailable_reason()
    if reason is not None:
        pytest.skip(f"deviceless TPU topology unavailable: {reason}")
    try:
        from jax.experimental import topologies

        td = topologies.get_topology_desc(
            platform="tpu", topology_name=TOPOLOGY
        )
    except Exception as e:  # no TPU plugin / unsupported topology API
        pytest.skip(f"deviceless TPU topology unavailable: {e!r}")
    dev = td.devices[0]
    assert dev.device_kind == "TPU v5 lite", dev.device_kind
    return jax.sharding.SingleDeviceSharding(dev)


def test_sha256_mosaic_kernel_compiles_for_v5e():
    """The fully-unrolled 64-round Mosaic SHA-256 lowers and compiles
    through the real XLA:TPU backend at the bench tile shape."""
    from hypervisor_tpu.kernels.sha256_pallas import sha256_words

    s = _v5e_sharding()
    compiled = (
        jax.jit(partial(sha256_words, n_blocks=2), in_shardings=s,
                out_shardings=s)
        .lower(jax.ShapeDtypeStruct((1024, 32), jnp.uint32))
        .compile()
    )
    assert compiled.cost_analysis() is not None


def test_liability_mosaic_cascade_compiles_for_v5e():
    """The MXU-formulated slash cascade (gather/scatter Pallas passes)
    compiles for v5e at a 10k-agent multi-tile shape."""
    from hypervisor_tpu.kernels import liability_pallas as lp
    from hypervisor_tpu.tables.state import VouchTable

    vouch = VouchTable.create(4096)
    sigma = jnp.full((10_000,), 0.8, jnp.float32)
    seeds = jnp.zeros((10_000,), bool)
    rows = lp._prep(vouch, sigma, seeds)[0]
    row_shapes = {
        k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in rows.items()
    }

    s = _v5e_sharding()
    from hypervisor_tpu.config import DEFAULT_CONFIG

    compiled = (
        jax.jit(
            partial(
                lp._cascade, trust=DEFAULT_CONFIG.trust, use_pallas=True
            ),
            in_shardings=s,
            out_shardings=s,
        )
        .lower(
            row_shapes,
            jax.ShapeDtypeStruct((), jnp.float32),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.float32),
        )
        .compile()
    )
    assert compiled.cost_analysis() is not None


def test_full_10k_wave_with_mosaic_hash_compiles_for_v5e():
    """The ENTIRE bench-shaped governance wave — admission, FSM, the
    Mosaic chain/Merkle hashing, saga step, range-compare terminate —
    compiles for v5e as one program (both the wave_range fast path the
    bench runs and use_pallas=True)."""
    from hypervisor_tpu.models import SessionState  # noqa: F401
    from hypervisor_tpu.ops import merkle as merkle_ops
    from hypervisor_tpu.ops.pipeline import governance_wave
    from hypervisor_tpu.tables.state import (
        AgentTable,
        SessionTable,
        VouchTable,
    )

    s = _v5e_sharding()
    S, T = 10_000, 3
    tables = (
        AgentTable.create(16_384),
        SessionTable.create(16_384),
        VouchTable.create(65_536),
    )
    shapes = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tables
    )
    lane_i = jax.ShapeDtypeStruct((S,), jnp.int32)
    lane_b = jax.ShapeDtypeStruct((S,), jnp.bool_)
    args = (
        *shapes, lane_i, lane_i, lane_i,
        jax.ShapeDtypeStruct((S,), jnp.float32), lane_b, lane_b, lane_i,
        jax.ShapeDtypeStruct((T, S, merkle_ops.BODY_WORDS), jnp.uint32),
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((), jnp.float32),
    )
    scalar_i = jax.ShapeDtypeStruct((), jnp.int32)

    def wave_fastpath(*a):
        *wave_args, lo, hi = a
        return governance_wave(
            *wave_args,
            use_pallas=True,
            unique_sessions=True,
            wave_range=(lo, hi),
        )

    compiled = (
        jax.jit(wave_fastpath, in_shardings=s, out_shardings=s)
        .lower(*args, scalar_i, scalar_i)
        .compile()
    )
    assert compiled.cost_analysis() is not None
