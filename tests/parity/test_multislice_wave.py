"""Multislice (dcn × agents) governance wave + DCN reconcile ≡ the
single-device wave.

SURVEY §5's ICI-vs-DCN split, executed end to end: agent rows and vouch
edges shard over the flattened 2-D grid, each slice's wave arithmetic
rides slice-local psums, the only in-tick DCN reductions are the vouch
row-map/contribution psums and the released total, and EVERY session
commit comes back as per-shard partials folded once over DCN by
`multislice_reconcile_wave`. After the fold, tables and outputs must be
bit-identical to one single-device wave over the combined load.
Contracts: the fast-path layouts (contiguous session block, unique
sessions) plus slice affinity (each wave session joined from one
slice). Runs on the virtual 8-CPU mesh reshaped 2×4 AND 4×2 (round-5:
the grid aspect must not change the math), with an asymmetric-load leg
(ragged lanes concentrated on one slice) and the refusal path for a
wave session joined from two slices (the bridge's host-verified
unique-seat contract is exactly what makes cross-slice double-joins
impossible to stage — test_bridge_refuses_cross_slice_double_join).
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from hypervisor_tpu.models import SessionState
from hypervisor_tpu.ops import admission
from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.ops.pipeline import governance_wave
from hypervisor_tpu.parallel import make_multislice_mesh
from hypervisor_tpu.parallel.collectives import (
    multislice_reconcile_wave,
    sharded_governance_wave,
)
from hypervisor_tpu.tables.state import AgentTable, SessionTable, VouchTable
from hypervisor_tpu.tables.struct import replace as t_replace

N_SLICES, PER_SLICE = 2, 4
D = N_SLICES * PER_SLICE
# Grid aspects for the parametrized legs: same 8 shards, both carvings.
GRIDS = [(2, 4), (4, 2)]
GRID_IDS = ["2x4", "4x2"]
ROWS_PER_SHARD = 8
N_CAP = D * ROWS_PER_SHARD
E_CAP = D * 4
S_CAP = 32
B = D          # one join per shard; one session per join (unique)
K = B
T = 3
NOW = 4.5
OMEGA = 0.5


def _tables():
    agents = AgentTable.create(N_CAP)
    sessions = SessionTable.create(S_CAP)
    ws = jnp.arange(K)
    sessions = t_replace(
        sessions,
        state=sessions.state.at[ws].set(
            jnp.int8(SessionState.HANDSHAKING.code)
        ),
        max_participants=sessions.max_participants.at[ws].set(10),
        min_sigma_eff=sessions.min_sigma_eff.at[ws].set(0.6),
    )
    vouches = VouchTable.create(E_CAP)
    # A vouch edge on the LAST shard of slice 1 lifting the low-sigma
    # joiner whose agent row lives on slice 0 — the contribution psum
    # must cross the DCN axis.
    vouches = t_replace(
        vouches,
        voucher=vouches.voucher.at[E_CAP - 1].set(N_CAP - 1),
        vouchee=vouches.vouchee.at[E_CAP - 1].set(0),  # slot of joiner 0
        session=vouches.session.at[E_CAP - 1].set(0),
        bond=vouches.bond.at[E_CAP - 1].set(0.40),
        active=vouches.active.at[E_CAP - 1].set(True),
    )
    return agents, sessions, vouches


def _wave_args():
    slots = np.array([i * ROWS_PER_SHARD for i in range(B)], np.int32)
    sigma = np.full(B, 0.8, np.float32)
    sigma[0] = 0.45  # vouched across the DCN axis
    rng = np.random.RandomState(13)
    bodies = rng.randint(
        0, 2**32, size=(T, K, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    return (
        jnp.asarray(slots),
        jnp.arange(B, dtype=jnp.int32),
        jnp.arange(B, dtype=jnp.int32),   # unique session per join
        jnp.asarray(sigma),
        jnp.ones(B, bool),
        jnp.zeros(B, bool),
        jnp.asarray(np.arange(K, dtype=np.int32)),
        jnp.asarray(bodies),
        NOW,
        OMEGA,
    )


@pytest.mark.parametrize("grid", GRIDS, ids=GRID_IDS)
def test_multislice_wave_plus_dcn_reconcile_matches_single_device(grid):
    mesh = make_multislice_mesh(*grid)
    args = _wave_args()
    wave_range = (jnp.asarray(0, jnp.int32), jnp.asarray(K, jnp.int32))

    agents, sessions, vouches = _tables()
    ms = sharded_governance_wave(
        mesh,
        mode_dispatch=True,
        contiguous_waves=True,
        unique_sessions=True,
        multislice=True,
    )
    res, partials = ms(agents, sessions, vouches, *args, *wave_range)
    folded = multislice_reconcile_wave(mesh)(
        res.sessions, partials.counts, partials.owned, partials.state,
        partials.terminated,
    )

    agents2, sessions2, vouches2 = _tables()
    single = jax.jit(
        governance_wave,
        static_argnames=("use_pallas", "unique_sessions"),
    )(
        agents2, sessions2, vouches2, *args,
        use_pallas=False, wave_range=wave_range, unique_sessions=True,
    )

    for field in ("status", "ring", "sigma_eff", "saga_step_state",
                  "chain", "merkle_root", "fsm_error"):
        np.testing.assert_array_equal(
            np.asarray(getattr(res, field)),
            np.asarray(getattr(single, field)),
            err_msg=f"{field} diverged",
        )
    assert int(np.asarray(res.released)) == int(np.asarray(single.released))
    # The DCN-crossing vouch lifted joiner 0 identically.
    assert float(np.asarray(res.sigma_eff)[0]) == pytest.approx(0.65)
    assert (np.asarray(res.status) == admission.ADMIT_OK).all()
    # Post-reconcile replica == the single-device committed table.
    for col in ("state", "n_participants", "terminated_at"):
        np.testing.assert_array_equal(
            np.asarray(getattr(folded, col)),
            np.asarray(getattr(single.sessions, col)),
            err_msg=f"sessions.{col} diverged after DCN fold",
        )
    # Agent/vouch tables match too (terminate ran on every shard).
    np.testing.assert_array_equal(
        np.asarray(res.agents.flags), np.asarray(single.agents.flags)
    )
    np.testing.assert_array_equal(
        np.asarray(res.vouches.active), np.asarray(single.vouches.active)
    )


@pytest.mark.parametrize("grid", GRIDS, ids=GRID_IDS)
def test_permuted_assignment_crosses_slices(grid):
    """Element i joins session B-1-i: still contiguous + unique, but
    every session's FSM lane lives on a different shard (often a
    different SLICE) than its joiner — the view psum must be global or
    has_members silently misses cross-slice joins and the FSM walk is
    skipped."""
    mesh = make_multislice_mesh(*grid)
    slots = np.array([i * ROWS_PER_SHARD for i in range(B)], np.int32)
    rng = np.random.RandomState(21)
    bodies = rng.randint(
        0, 2**32, size=(T, K, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    args = (
        jnp.asarray(slots),
        jnp.arange(B, dtype=jnp.int32),
        jnp.asarray(np.arange(B - 1, -1, -1, dtype=np.int32)),  # reversed
        jnp.full((B,), 0.8, jnp.float32),
        jnp.ones(B, bool),
        jnp.zeros(B, bool),
        jnp.asarray(np.arange(K, dtype=np.int32)),
        jnp.asarray(bodies),
        NOW,
        OMEGA,
    )
    wave_range = (jnp.asarray(0, jnp.int32), jnp.asarray(K, jnp.int32))

    agents, sessions, vouches = _tables()
    ms = sharded_governance_wave(
        mesh, mode_dispatch=True, contiguous_waves=True,
        unique_sessions=True, multislice=True,
    )
    res, partials = ms(agents, sessions, vouches, *args, *wave_range)
    folded = multislice_reconcile_wave(mesh)(
        res.sessions, partials.counts, partials.owned, partials.state,
        partials.terminated,
    )

    agents2, sessions2, vouches2 = _tables()
    single = jax.jit(
        governance_wave,
        static_argnames=("use_pallas", "unique_sessions"),
    )(
        agents2, sessions2, vouches2, *args,
        use_pallas=False, wave_range=wave_range, unique_sessions=True,
    )
    np.testing.assert_array_equal(
        np.asarray(res.status), np.asarray(single.status)
    )
    np.testing.assert_array_equal(
        np.asarray(res.fsm_error), np.asarray(single.fsm_error)
    )
    for col in ("state", "n_participants", "terminated_at"):
        np.testing.assert_array_equal(
            np.asarray(getattr(folded, col)),
            np.asarray(getattr(single.sessions, col)),
            err_msg=f"sessions.{col} diverged after DCN fold",
        )
    # Every session with members walked to ARCHIVED.
    assert (
        np.asarray(folded.state)[:K] == SessionState.ARCHIVED.code
    ).all()


def test_bridge_runs_multislice_wave():
    """HypervisorState.run_governance_wave(mesh=<2-D mesh>) builds the
    multislice variant, folds the DCN partials behind the wave, and
    lands the same world as the single-device bridge."""
    import dataclasses

    from hypervisor_tpu.config import DEFAULT_CONFIG
    from hypervisor_tpu.models import SessionConfig
    from hypervisor_tpu.state import HypervisorState

    cfg = dataclasses.replace(
        DEFAULT_CONFIG,
        capacity=dataclasses.replace(
            DEFAULT_CONFIG.capacity, max_agents=N_CAP
        ),
    )
    mesh = make_multislice_mesh(N_SLICES, PER_SLICE)

    def run(use_mesh):
        st = HypervisorState(cfg)
        slots = st.create_sessions_batch(
            [f"ms:s{i}" for i in range(K)], SessionConfig(min_sigma_eff=0.0)
        )
        dids = [f"did:ms:{i}" for i in range(K)]
        rng = np.random.RandomState(3)
        bodies = rng.randint(
            0, 2**32, size=(T, K, merkle_ops.BODY_WORDS), dtype=np.uint64
        ).astype(np.uint32)
        res = st.run_governance_wave(
            slots, dids, np.asarray(slots, np.int32),
            np.full(K, 0.8, np.float32), bodies,
            now=2.0, mesh=mesh if use_mesh else None,
            **({} if use_mesh else {"use_pallas": False}),
        )
        return st, res

    st_ms, res_ms = run(True)
    st_sd, res_sd = run(False)
    # Actions FUSE into the multislice wave (round 5; the single-device
    # path composes behind its wave). Probe a genuinely STANDING member
    # (admitted via the staging path, so it survives the wave) with
    # identical state on both paths — the fused gateway's verdicts must
    # MATCH the composed single-device ones, not merely exist.
    gw_verdicts = []
    for st, mesh_arg in ((st_ms, mesh), (st_sd, None)):
        standing_sess = st.create_session(
            "ms:standing", SessionConfig(min_sigma_eff=0.0)
        )
        assert st.enqueue_join(
            standing_sess, "did:ms:standing", sigma_raw=0.8
        ) >= 0
        assert (st.flush_joins(now=2.5) == 0).all()
        probe_slot = st._slot_of_member[
            (st.agent_ids.lookup("did:ms:standing"), standing_sess)
        ]

        slots2 = st.create_sessions_batch(
            ["ms:extra"], SessionConfig(min_sigma_eff=0.0)
        )
        extra = st.run_governance_wave(
            slots2, ["did:ms:probe"],
            np.asarray(slots2, np.int32),
            np.full(1, 0.9, np.float32),
            np.zeros((1, 1, merkle_ops.BODY_WORDS), np.uint32),
            now=3.0,
            mesh=mesh_arg,
            actions=dict(slots=np.array([probe_slot], np.int32)),
            **({} if mesh_arg is not None else {"use_pallas": False}),
        )
        assert isinstance(extra, tuple) and extra[1] is not None
        gw_verdicts.append(np.asarray(extra[1].verdict))
    np.testing.assert_array_equal(gw_verdicts[0], gw_verdicts[1])
    # The standing member's write is GRANTED on both paths.
    assert int(gw_verdicts[0][0]) == 0
    np.testing.assert_array_equal(
        np.asarray(res_ms.status), np.asarray(res_sd.status)
    )
    np.testing.assert_array_equal(
        np.asarray(res_ms.merkle_root), np.asarray(res_sd.merkle_root)
    )
    # The bridge folded the DCN partials: the committed tables agree.
    np.testing.assert_array_equal(
        np.asarray(st_ms.sessions.state), np.asarray(st_sd.sessions.state)
    )
    np.testing.assert_array_equal(
        np.asarray(st_ms.sessions.n_participants),
        np.asarray(st_sd.sessions.n_participants),
    )
    for i in range(K):
        assert st_ms.is_member(i, f"did:ms:{i}")


def test_multislice_sharded_gateway_matches_single_device():
    """check_actions_wave(mesh=<2-D mesh>) — the zero-collective
    gateway over the flattened (dcn, agents) grid — must produce the
    single-device verdict columns bit-for-bit on a ragged request."""
    import dataclasses

    from hypervisor_tpu.config import DEFAULT_CONFIG
    from hypervisor_tpu.models import SessionConfig
    from hypervisor_tpu.state import HypervisorState

    cfg = dataclasses.replace(
        DEFAULT_CONFIG,
        capacity=dataclasses.replace(
            DEFAULT_CONFIG.capacity, max_agents=N_CAP
        ),
    )
    mesh = make_multislice_mesh(N_SLICES, PER_SLICE)

    def staged():
        st = HypervisorState(cfg)
        sess = st.create_session("gw:s", SessionConfig(min_sigma_eff=0.0))
        for i in range(5):
            assert st.enqueue_join(sess, f"did:gw:{i}", sigma_raw=0.8) >= 0
        assert (st.flush_joins(now=1.0) == 0).all()
        slots = [
            st._slot_of_member[(st.agent_ids.lookup(f"did:gw:{i}"), sess)]
            for i in range(5)
        ]
        # Ragged, duplicate-slot request (same membership twice keeps
        # the sequential settle on one shard).
        req = np.array(slots + [slots[0]], np.int32)
        return st, req

    st_ms, req_ms = staged()
    st_sd, req_sd = staged()
    np.testing.assert_array_equal(req_ms, req_sd)
    n_req = len(req_ms)
    cols = dict(
        required_rings=np.full(n_req, 2, np.int8),
        is_read_only=np.zeros(n_req, bool),
        has_consensus=np.zeros(n_req, bool),
        has_sre_witness=np.zeros(n_req, bool),
        host_tripped=np.zeros(n_req, bool),
    )
    gw_ms = st_ms.check_actions_wave(req_ms, now=2.0, mesh=mesh, **cols)
    gw_sd = st_sd.check_actions_wave(req_sd, now=2.0, **cols)
    for field in ("verdict", "ring_status", "eff_ring", "tripped"):
        np.testing.assert_array_equal(
            np.asarray(getattr(gw_ms, field)),
            np.asarray(getattr(gw_sd, field)),
            err_msg=field,
        )


@pytest.mark.parametrize("grid", GRIDS, ids=GRID_IDS)
def test_asymmetric_slice_load_ragged_across_slices(grid):
    """Ragged ACROSS slices: the real lanes concentrate on slice 0 and
    the tail shards (all of the last slice) carry only duplicate-masked
    padding lanes whose sessions are parked. The asymmetric load must
    not disturb the DCN fold — padding admits nothing, parked sessions
    keep HANDSHAKING with no members, and the fold still matches the
    single-device wave bit-for-bit."""
    n_slices, per_slice = grid
    mesh = make_multislice_mesh(n_slices, per_slice)
    slots = np.array([i * ROWS_PER_SHARD for i in range(B)], np.int32)
    rng = np.random.RandomState(34)
    bodies = rng.randint(
        0, 2**32, size=(T, K, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    # The whole LAST slice's lanes are padding (duplicate => refused
    # before the seat check; their sessions stay parked).
    pad_lanes = per_slice  # lanes per slice == shards per slice here
    duplicate = np.zeros(B, bool)
    duplicate[B - pad_lanes :] = True
    args = (
        jnp.asarray(slots),
        jnp.arange(B, dtype=jnp.int32),
        jnp.arange(B, dtype=jnp.int32),
        jnp.full((B,), 0.8, jnp.float32),
        jnp.ones(B, bool),
        jnp.asarray(duplicate),
        jnp.asarray(np.arange(K, dtype=np.int32)),
        jnp.asarray(bodies),
        NOW,
        OMEGA,
    )
    wave_range = (jnp.asarray(0, jnp.int32), jnp.asarray(K, jnp.int32))

    agents, sessions, vouches = _tables()
    ms = sharded_governance_wave(
        mesh, mode_dispatch=True, contiguous_waves=True,
        unique_sessions=True, multislice=True,
    )
    res, partials = ms(agents, sessions, vouches, *args, *wave_range)
    folded = multislice_reconcile_wave(mesh)(
        res.sessions, partials.counts, partials.owned, partials.state,
        partials.terminated,
    )

    agents2, sessions2, vouches2 = _tables()
    single = jax.jit(
        governance_wave,
        static_argnames=("use_pallas", "unique_sessions"),
    )(
        agents2, sessions2, vouches2, *args,
        use_pallas=False, wave_range=wave_range, unique_sessions=True,
    )
    for field in ("status", "ring", "sigma_eff", "saga_step_state",
                  "chain", "merkle_root", "fsm_error"):
        np.testing.assert_array_equal(
            np.asarray(getattr(res, field)),
            np.asarray(getattr(single, field)),
            err_msg=f"{field} diverged",
        )
    assert int(np.asarray(res.released)) == int(np.asarray(single.released))
    np.testing.assert_array_equal(
        np.asarray(res.agents.flags), np.asarray(single.agents.flags)
    )
    np.testing.assert_array_equal(
        np.asarray(res.vouches.active), np.asarray(single.vouches.active)
    )
    # Padding lanes refused as duplicates; real lanes admitted.
    assert (
        np.asarray(res.status)[B - pad_lanes :] == admission.ADMIT_DUPLICATE
    ).all()
    assert (
        np.asarray(res.status)[: B - pad_lanes] == admission.ADMIT_OK
    ).all()
    for col in ("state", "n_participants", "terminated_at"):
        np.testing.assert_array_equal(
            np.asarray(getattr(folded, col)),
            np.asarray(getattr(single.sessions, col)),
            err_msg=f"sessions.{col} diverged after DCN fold",
        )
    # Parked sessions (the padding lanes' targets) never left
    # HANDSHAKING: no members, so the FSM walk skipped them.
    assert (
        np.asarray(folded.state)[B - pad_lanes : K]
        == SessionState.HANDSHAKING.code
    ).all()
    assert (
        np.asarray(folded.state)[: B - pad_lanes]
        == SessionState.ARCHIVED.code
    ).all()


@pytest.mark.parametrize("grid", GRIDS, ids=GRID_IDS)
def test_fused_multislice_gateway_matches_single_device(grid):
    """with_gateway=True on a 2-D mesh (round 5): the gateway phase
    fuses into the multislice wave — shard-local by the placement
    contract, the grid only changes each shard's linear base row. One
    standing member per shard acts after the wave; verdicts and the
    post-gateway agent table must match the single-device fused
    composition bit-for-bit."""
    from hypervisor_tpu.ops import gateway as gateway_ops
    from hypervisor_tpu.tables.state import ElevationTable

    n_slices, per_slice = grid
    mesh = make_multislice_mesh(n_slices, per_slice)
    args = _wave_args()
    wave_range = (jnp.asarray(0, jnp.int32), jnp.asarray(K, jnp.int32))
    elevs = ElevationTable.create(8)

    def standing(agents):
        # Pre-existing members OUTSIDE the wave cohort: the last row of
        # each shard's block, admitted before the wave.
        slots = jnp.asarray(
            [(i + 1) * ROWS_PER_SHARD - 1 for i in range(D)], jnp.int32
        )
        return t_replace(
            agents,
            did=agents.did.at[slots].set(1000 + jnp.arange(D)),
            sigma_eff=agents.sigma_eff.at[slots].set(0.8),
            ring=agents.ring.at[slots].set(2),
            rl_tokens=agents.rl_tokens.at[slots].set(5.0),
        ), slots

    act_cols = lambda slots: (  # noqa: E731
        slots,
        jnp.full((D,), 2, jnp.int8),
        jnp.zeros((D,), bool),
        jnp.zeros((D,), bool),
        jnp.zeros((D,), bool),
        jnp.zeros((D,), bool),
    )
    act_valid = jnp.ones((D,), bool)

    agents, sessions, vouches = _tables()
    agents, act_slots = standing(agents)
    ms = sharded_governance_wave(
        mesh, mode_dispatch=True, contiguous_waves=True,
        unique_sessions=True, multislice=True, with_gateway=True,
    )
    res, lanes, partials = ms(
        agents, sessions, vouches, *args, *wave_range,
        elevs, *act_cols(act_slots), act_valid,
    )

    agents2, sessions2, vouches2 = _tables()
    agents2, act_slots2 = standing(agents2)
    single = jax.jit(
        governance_wave, static_argnames=("use_pallas", "unique_sessions")
    )(
        agents2, sessions2, vouches2, *args,
        use_pallas=False, wave_range=wave_range, unique_sessions=True,
    )
    gw = gateway_ops.check_actions(
        single.agents, elevs, *act_cols(act_slots2), NOW, valid=act_valid,
    )

    np.testing.assert_array_equal(
        np.asarray(lanes.verdict), np.asarray(gw.verdict)
    )
    np.testing.assert_array_equal(
        np.asarray(lanes.eff_ring), np.asarray(gw.eff_ring)
    )
    np.testing.assert_array_equal(
        np.asarray(lanes.window_calls), np.asarray(gw.window_calls)
    )
    # Standing members' actions were all granted (the point of the
    # placement: each lane's row lives on its own shard).
    assert (np.asarray(lanes.verdict) == gateway_ops.GATE_ALLOWED).all()
    # Post-gateway agent table (incl. breach windows and token burns)
    # matches the composed single-device path.
    for name in ("f32", "i32", "ring", "bd_window"):
        np.testing.assert_array_equal(
            np.asarray(getattr(res.agents, name)),
            np.asarray(getattr(gw.agents, name)),
            err_msg=name,
        )


def test_bridge_refuses_cross_slice_double_join():
    """The slice-affinity contract's failure mode: a wave session
    joined from TWO slices. The bridge's host-verified unique-seat
    check is what forbids it — two seat-consuming joins to one session
    make unique_sessions False, and the multislice path REFUSES the
    wave instead of staging a cross-slice commit that the one-DCN-fold
    design cannot merge (FSM overwrites from two slices would collide
    in the masked-sum fold)."""
    import dataclasses

    from hypervisor_tpu.config import DEFAULT_CONFIG
    from hypervisor_tpu.models import SessionConfig
    from hypervisor_tpu.state import HypervisorState

    cfg = dataclasses.replace(
        DEFAULT_CONFIG,
        capacity=dataclasses.replace(
            DEFAULT_CONFIG.capacity, max_agents=N_CAP
        ),
    )
    mesh = make_multislice_mesh(N_SLICES, PER_SLICE)
    st = HypervisorState(cfg)
    slots = st.create_sessions_batch(
        [f"xs:s{i}" for i in range(K)], SessionConfig(min_sigma_eff=0.0)
    )
    # K joins, but joins 0 and K-1 BOTH target session 0: with one join
    # per shard, those two seats live on different slices of the 2-D
    # grid.
    sess_of = np.asarray(slots, np.int32)
    sess_of[K - 1] = sess_of[0]
    bodies = np.zeros((T, K, merkle_ops.BODY_WORDS), np.uint32)
    with pytest.raises(ValueError, match="one seat-consuming join"):
        st.run_governance_wave(
            slots,
            [f"did:xs:{i}" for i in range(K)],
            sess_of,
            np.full(K, 0.8, np.float32),
            bodies,
            now=2.0,
            mesh=mesh,
        )


def test_pre_reconcile_replica_is_unchanged():
    """Before the DCN fold, every slice's session replica equals the
    tick-start table — no cross-slice divergence mid-tick."""
    mesh = make_multislice_mesh(N_SLICES, PER_SLICE)
    args = _wave_args()
    wave_range = (jnp.asarray(0, jnp.int32), jnp.asarray(K, jnp.int32))
    agents, sessions, vouches = _tables()
    ms = sharded_governance_wave(
        mesh,
        mode_dispatch=True,
        contiguous_waves=True,
        unique_sessions=True,
        multislice=True,
    )
    res, _ = ms(agents, sessions, vouches, *args, *wave_range)
    np.testing.assert_array_equal(
        np.asarray(res.sessions.n_participants),
        np.asarray(sessions.n_participants),
    )
    np.testing.assert_array_equal(
        np.asarray(res.sessions.state), np.asarray(sessions.state)
    )
