"""Ragged sharded governance waves: no divisibility, no caller padding.

Round-3's sharded wave demanded B % D == 0, K % D == 0 and caller-side
slot placement; the bridge now pads internally — refused join lanes
(duplicate=True touches nothing) and parked session lanes (unallocated
rows whose no-member walk is a masked no-op) round any request up to
the mesh size. These tests run the VERDICT-prescribed shape (13 joins,
5 sessions on 8 shards) and pin the mesh path against single-device
semantics, plus the parked rows staying untouched.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from hypervisor_tpu.config import DEFAULT_CONFIG
from hypervisor_tpu.models import SessionConfig, SessionState
from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.parallel import make_mesh
from hypervisor_tpu.state import HypervisorState

N_DEV = 8
B = 13          # not divisible by 8
K = 5           # not divisible by 8
T = 3


def _config():
    return dataclasses.replace(
        DEFAULT_CONFIG,
        capacity=dataclasses.replace(
            DEFAULT_CONFIG.capacity, max_agents=N_DEV * 16
        ),
    )


def _staged(st):
    session_slots = st.create_sessions_batch(
        [f"rg:s{i}" for i in range(K)], SessionConfig(min_sigma_eff=0.0)
    )
    dids = [f"did:rg:{i}" for i in range(B)]
    agent_sessions = np.array([i % K for i in range(B)], np.int32)
    sigma = np.linspace(0.58, 0.95, B).astype(np.float32)
    rng = np.random.RandomState(9)
    bodies = rng.randint(
        0, 2**32, size=(T, K, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    return session_slots, dids, agent_sessions, sigma, bodies


class TestRaggedWave:
    def test_13_joins_5_sessions_on_8_shards(self):
        mesh = make_mesh(N_DEV, platform="cpu")

        st_single = HypervisorState(_config())
        res_s = st_single.run_governance_wave(
            *_staged(st_single), now=2.0, use_pallas=False
        )
        st_mesh = HypervisorState(_config())
        res_m = st_mesh.run_governance_wave(
            *_staged(st_mesh), now=2.0, mesh=mesh
        )

        # Caller-shaped outputs, identical semantics on both paths.
        assert np.asarray(res_m.status).shape == (B,)
        assert np.asarray(res_m.merkle_root).shape[0] == K
        np.testing.assert_array_equal(
            np.asarray(res_m.status), np.asarray(res_s.status)
        )
        np.testing.assert_array_equal(
            np.asarray(res_m.ring), np.asarray(res_s.ring)
        )
        np.testing.assert_array_equal(
            np.asarray(res_m.chain), np.asarray(res_s.chain)
        )
        np.testing.assert_array_equal(
            np.asarray(res_m.merkle_root), np.asarray(res_s.merkle_root)
        )

        # Both worlds agree afterwards: archived sessions, memberships,
        # participant counts, audit index.
        for st in (st_single, st_mesh):
            state_col = np.asarray(st.sessions.state)[:K]
            assert (state_col == SessionState.ARCHIVED.code).all()
            for i in range(B):
                assert st.is_member(i % K, f"did:rg:{i}")
            for s in range(K):
                assert len(st._audit_rows[s]) == T
        np.testing.assert_array_equal(
            np.asarray(st_mesh.sessions.n_participants),
            np.asarray(st_single.sessions.n_participants),
        )
        np.testing.assert_array_equal(
            np.asarray(st_mesh.delta_log.digest),
            np.asarray(st_single.delta_log.digest),
        )

        # Parked session rows (the K..K_pad internal lanes) stayed
        # untouched: still unallocated, zero participants, CREATED.
        parked = np.arange(K, -(-K // N_DEV) * N_DEV)
        assert (np.asarray(st_mesh.sessions.sid)[parked] == -1).all()
        assert (
            np.asarray(st_mesh.sessions.n_participants)[parked] == 0
        ).all()
        assert (np.asarray(st_mesh.sessions.state)[parked] == 0).all()
        # Padded join lanes' parked agent rows stayed free.
        assert (np.asarray(st_mesh.agents.did) >= 0).sum() == B

    def test_single_join_single_session(self):
        """The extreme ragged case: B=1, K=1 on 8 shards."""
        mesh = make_mesh(N_DEV, platform="cpu")
        st = HypervisorState(_config())
        slots = st.create_sessions_batch(
            ["rg1:s"], SessionConfig(min_sigma_eff=0.0)
        )
        rng = np.random.RandomState(2)
        bodies = rng.randint(
            0, 2**32, size=(T, 1, merkle_ops.BODY_WORDS), dtype=np.uint64
        ).astype(np.uint32)
        res = st.run_governance_wave(
            slots, ["did:rg1"], np.zeros(1, np.int32),
            np.asarray([0.8], np.float32), bodies, now=2.0, mesh=mesh,
        )
        assert np.asarray(res.status).tolist() == [0]
        assert int(np.asarray(st.sessions.state)[0]) == (
            SessionState.ARCHIVED.code
        )
        assert st.is_member(0, "did:rg1")
