"""governance_wave with wave_range vs without: bit parity.

The range-compare fast path (wave_sessions == arange(lo, hi), the slot
allocator's layout) replaces terminate's [E]/[N] membership gathers and
the [S_cap] mask scatter. Every WaveResult field and every output table
column must be bit-identical to the mask path — the fast path changes
the program, never the answer. Reference semantics anchor:
`/root/reference/src/hypervisor/core.py:192-227` (terminate: bond
release + archive) via `ops.terminate.release_session_scope`.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from hypervisor_tpu.models import SessionState
from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.ops.pipeline import governance_wave
from hypervisor_tpu.tables.state import AgentTable, SessionTable, VouchTable
from hypervisor_tpu.tables.struct import replace as t_replace

N_CAP, E_CAP, S_CAP = 64, 32, 16
T = 3
NOW = 12.5
OMEGA = 0.5

_WAVE = jax.jit(governance_wave, static_argnames=("use_pallas",))


def _build(lo: int, k: int, b: int):
    """b joiners spread over the k wave sessions [lo, lo+k); some vouch
    edges; a few STRAGGLER edges/agents in sessions OUTSIDE the range
    that must survive the terminate untouched."""
    rng = np.random.RandomState(lo * 101 + k)
    agents = AgentTable.create(N_CAP)
    sessions = SessionTable.create(S_CAP)
    ws = jnp.arange(lo, lo + k)
    sessions = t_replace(
        sessions,
        state=sessions.state.at[ws].set(jnp.int8(SessionState.HANDSHAKING.code)),
        max_participants=sessions.max_participants.at[ws].set(10),
        min_sigma_eff=sessions.min_sigma_eff.at[ws].set(0.6),
    )
    vouches = VouchTable.create(E_CAP)

    slots = np.arange(b, dtype=np.int32)
    dids = np.arange(b, dtype=np.int32)
    agent_sessions = (lo + (np.arange(b) % k)).astype(np.int32)
    sigma = np.full(b, 0.8, np.float32)
    sigma[0] = 0.45  # vouched below

    # One live vouch edge toward joiner 0's session; one edge scoped to a
    # session OUTSIDE the wave range (must stay active through terminate).
    # When the range covers the whole table no real slot is outside —
    # fall back to an unattached sentinel (-5), which every membership
    # path must treat as matching nothing.
    if (lo + k) < S_CAP:
        outside = lo + k
    elif lo > 0:
        outside = lo - 1
    else:
        outside = -5
    vouches = t_replace(
        vouches,
        voucher=vouches.voucher.at[0].set(N_CAP - 1),
        vouchee=vouches.vouchee.at[0].set(0),
        session=vouches.session.at[0].set(int(agent_sessions[0])),
        bond=vouches.bond.at[0].set(0.40),
        active=vouches.active.at[0].set(True),
    )
    vouches = t_replace(
        vouches,
        voucher=vouches.voucher.at[1].set(N_CAP - 2),
        vouchee=vouches.vouchee.at[1].set(N_CAP - 3),
        session=vouches.session.at[1].set(int(outside)),
        bond=vouches.bond.at[1].set(0.10),
        active=vouches.active.at[1].set(True),
    )
    # A standing agent in the outside session: must stay FLAG_ACTIVE.
    from hypervisor_tpu.tables.state import FLAG_ACTIVE

    agents = t_replace(
        agents,
        session=agents.session.at[N_CAP - 3].set(int(outside)),
        flags=agents.flags.at[N_CAP - 3].set(FLAG_ACTIVE),
    )

    bodies = rng.randint(
        0, 2**32, size=(T, k, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    args = (
        jnp.asarray(slots),
        jnp.asarray(dids),
        jnp.asarray(agent_sessions),
        jnp.asarray(sigma),
        jnp.ones(b, bool),
        jnp.zeros(b, bool),
        jnp.asarray(np.arange(lo, lo + k, dtype=np.int32)),
        jnp.asarray(bodies),
        NOW,
        OMEGA,
    )
    return agents, sessions, vouches, args


AGENT_COLS = ("did", "session", "sigma_raw", "sigma_eff", "ring", "flags",
              "joined_at")
SESSION_COLS = ("state", "n_participants", "terminated_at")


@pytest.mark.parametrize("lo,k,b", [(0, 4, 8), (3, 5, 10), (0, S_CAP, 16)])
def test_wave_range_bit_parity(lo, k, b):
    agents, sessions, vouches, args = _build(lo, k, b)
    plain = _WAVE(agents, sessions, vouches, *args, use_pallas=False)
    ranged = _WAVE(
        agents,
        sessions,
        vouches,
        *args,
        use_pallas=False,
        wave_range=(jnp.asarray(lo, jnp.int32), jnp.asarray(lo + k, jnp.int32)),
    )
    for field in ("status", "ring", "sigma_eff", "saga_step_state", "chain",
                  "merkle_root", "fsm_error"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ranged, field)),
            np.asarray(getattr(plain, field)),
            err_msg=f"{field} diverged",
        )
    assert int(np.asarray(ranged.released)) == int(np.asarray(plain.released))
    for col in AGENT_COLS:
        np.testing.assert_array_equal(
            np.asarray(getattr(ranged.agents, col)),
            np.asarray(getattr(plain.agents, col)),
            err_msg=f"agents.{col} diverged",
        )
    for col in SESSION_COLS:
        np.testing.assert_array_equal(
            np.asarray(getattr(ranged.sessions, col)),
            np.asarray(getattr(plain.sessions, col)),
            err_msg=f"sessions.{col} diverged",
        )
    np.testing.assert_array_equal(
        np.asarray(ranged.vouches.active), np.asarray(plain.vouches.active)
    )


def test_outside_scope_survives_ranged_terminate():
    lo, k, b = 2, 4, 8
    agents, sessions, vouches, args = _build(lo, k, b)
    ranged = _WAVE(
        agents,
        sessions,
        vouches,
        *args,
        use_pallas=False,
        wave_range=(jnp.asarray(lo, jnp.int32), jnp.asarray(lo + k, jnp.int32)),
    )
    # The out-of-range vouch edge and standing agent are untouched.
    assert bool(np.asarray(ranged.vouches.active)[1])
    from hypervisor_tpu.tables.state import FLAG_ACTIVE

    assert int(np.asarray(ranged.agents.flags)[N_CAP - 3]) & FLAG_ACTIVE
