"""The FULL governance wave vs a pure-Python reference oracle.

`test_admission_oracle` pins the admission phase; this test pins the
whole fused program — admission statuses/rings/sigma (vouched), hashlib
chain digests, the reference Merkle-root combine
(`audit.delta.merkle_root_host`, itself pinned bit-for-bit against
/root/reference's tree semantics), per-session participant accounting,
the session FSM end states, bond release counts, and participant
deactivation — against plain Python loops that never touch a device op.
If this passes, the one-program wave IS the reference pipeline.
"""

from __future__ import annotations

import hashlib

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from hypervisor_tpu.audit.delta import merkle_root_host
from hypervisor_tpu.config import DEFAULT_CONFIG
from hypervisor_tpu.models import SessionState
from hypervisor_tpu.ops import admission
from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.ops.pipeline import governance_wave
from hypervisor_tpu.tables.state import (
    AgentTable,
    FLAG_ACTIVE,
    SessionTable,
    VouchTable,
)
from hypervisor_tpu.tables.struct import replace as t_replace

B, K, S_CAP, N_CAP, E_CAP, T = 24, 8, 16, 64, 32, 3
NOW = 6.0
OMEGA = 0.5

_WAVE = jax.jit(governance_wave, static_argnames=("use_pallas",))


def _host_chain(bodies_lane: np.ndarray) -> list[str]:
    """Reference chain semantics: digest_n = sha256(body_n || parent)."""
    parent = b"\x00" * 32
    out = []
    for body in bodies_lane:  # [T, BODY_WORDS]
        digest = hashlib.sha256(body.astype(">u4").tobytes() + parent).digest()
        parent = digest
        out.append(digest.hex())
    return out


@pytest.mark.parametrize("seed", range(3))
def test_wave_matches_python_oracle(seed):
    rng = np.random.RandomState(500 + seed)
    trust = DEFAULT_CONFIG.trust

    # Sessions 0..K-1 joinable with tight capacity; the rest untouched.
    caps = rng.randint(2, 5, K)
    agents = AgentTable.create(N_CAP)
    sessions = SessionTable.create(S_CAP)
    ws = jnp.arange(K)
    sessions = t_replace(
        sessions,
        state=sessions.state.at[ws].set(jnp.int8(SessionState.HANDSHAKING.code)),
        max_participants=sessions.max_participants.at[ws].set(
            jnp.asarray(caps, jnp.int32)
        ),
        min_sigma_eff=sessions.min_sigma_eff.at[ws].set(0.6),
    )

    # Vouch edges toward a few joiners; one edge scoped elsewhere.
    vouches = VouchTable.create(E_CAP)
    session_slot = rng.randint(0, K, B).astype(np.int32)
    sigma_raw = rng.choice([0.45, 0.55, 0.8, 0.95], size=B).astype(np.float32)
    vouched_lanes = [0, 3]
    contribution = np.zeros(B, np.float32)
    for row, lane in enumerate(vouched_lanes):
        bond = 0.3 + 0.1 * row
        contribution[lane] = bond
        vouches = t_replace(
            vouches,
            voucher=vouches.voucher.at[row].set(N_CAP - 1 - row),
            vouchee=vouches.vouchee.at[row].set(lane),  # slot == lane below
            session=vouches.session.at[row].set(int(session_slot[lane])),
            bond=vouches.bond.at[row].set(bond),
            active=vouches.active.at[row].set(True),
        )
    trustworthy = rng.rand(B) > 0.1
    duplicate = rng.rand(B) < 0.1

    bodies = rng.randint(
        0, 2**32, size=(T, K, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)

    res = _WAVE(
        agents,
        sessions,
        vouches,
        jnp.arange(B, dtype=jnp.int32),
        jnp.arange(B, dtype=jnp.int32),
        jnp.asarray(session_slot),
        jnp.asarray(sigma_raw),
        jnp.asarray(trustworthy),
        jnp.asarray(duplicate),
        jnp.asarray(np.arange(K, dtype=np.int32)),
        jnp.asarray(bodies),
        NOW,
        OMEGA,
        use_pallas=False,
    )

    # ── oracle: admission (reference join walk, seats fill in order) ──
    counts = {s: 0 for s in range(K)}
    want_status, want_ring, want_sig = [], [], []
    for i in range(B):
        s = int(session_slot[i])
        sig = min(float(sigma_raw[i]) + OMEGA * float(contribution[i]), 1.0)
        if trustworthy[i]:
            ring = 2 if sig > trust.ring2_threshold else 3
        else:
            ring = 3
        status = 0
        if duplicate[i]:
            status = admission.ADMIT_DUPLICATE
        elif sig < 0.6 and ring != 3:
            status = admission.ADMIT_SIGMA_LOW
        elif counts[s] >= int(caps[s]):
            status = admission.ADMIT_CAPACITY
        if status == 0:
            counts[s] += 1
        want_status.append(status)
        want_ring.append(ring)
        want_sig.append(sig)
    np.testing.assert_array_equal(np.asarray(res.status), want_status)
    np.testing.assert_array_equal(np.asarray(res.ring), want_ring)
    np.testing.assert_allclose(
        np.asarray(res.sigma_eff), np.asarray(want_sig, np.float32), atol=1e-6
    )

    # ── oracle: audit chain + Merkle root per session lane ───────────
    chain = np.asarray(res.chain)          # [T, K, 8]
    roots = np.asarray(res.merkle_root)    # [K, 8]
    for lane in range(K):
        want_hex = _host_chain(bodies[:, lane])
        got_hex = [
            np.ascontiguousarray(chain[t, lane].astype(">u4"))
            .tobytes()
            .hex()
            for t in range(T)
        ]
        assert got_hex == want_hex, f"lane {lane} chain diverged"
        want_root = merkle_root_host(want_hex)
        got_root = (
            np.ascontiguousarray(roots[lane].astype(">u4")).tobytes().hex()
        )
        assert got_root == want_root, f"lane {lane} Merkle root diverged"

    # ── oracle: terminate — bonds released, members deactivated, FSM ──
    # Live edges scoped to wave sessions release; all wave sessions with
    # members archive.
    want_released = sum(
        1
        for row, lane in enumerate(vouched_lanes)
        # every wave session terminates, so every planted edge releases
    )
    assert int(np.asarray(res.released)) == want_released
    assert not np.asarray(res.vouches.active)[: len(vouched_lanes)].any()
    state_after = np.asarray(res.sessions.state)
    for s in range(K):
        if counts[s] > 0:
            assert state_after[s] == SessionState.ARCHIVED.code, s
        else:
            # No members ever joined: the walk never leaves HANDSHAKING.
            assert state_after[s] == SessionState.HANDSHAKING.code, s
    assert (state_after[K:] == SessionState.CREATED.code).all()
    # Admitted rows were deactivated by the in-wave terminate.
    flags = np.asarray(res.agents.flags)
    for i in range(B):
        assert not (flags[i] & FLAG_ACTIVE), i
    assert not np.asarray(res.fsm_error).any()
