"""Mode-dispatched governance wave: mixed STRONG/EVENTUAL + reconcile
≡ the all-STRONG wave, on the REAL tables.

Round-3 executed the consistency mode in the lane-level `mode_tick`;
this pins the same convergence property on the fused sharded wave
(`sharded_governance_wave(mode_dispatch=True)`): EVENTUAL sessions'
replica updates (participant counts, FSM state, terminated_at) come
back as per-shard `EventualPartials` and the replicated SessionTable
does NOT see them in-wave; after `reconcile_wave_sessions` folds them,
the table is bit-identical to the wave that committed everything under
the STRONG psum barrier. Reference anchor: the `ConsistencyMode` flag
the reference stores but never executes (`models.py:12-16`).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from hypervisor_tpu.config import DEFAULT_CONFIG
from hypervisor_tpu.models import ConsistencyMode, SessionConfig, SessionState
from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.parallel import make_mesh
from hypervisor_tpu.parallel.collectives import (
    reconcile_wave_sessions,
    sharded_governance_wave,
)
from hypervisor_tpu.state import HypervisorState
from hypervisor_tpu.tables.state import AgentTable, SessionTable, VouchTable
from hypervisor_tpu.tables.struct import replace as t_replace

N_DEV = 8
B = 16          # joining agents (2 per shard)
K = 8           # wave sessions (1 per shard); odd lanes EVENTUAL
T = 2
ROWS = 8        # agent rows per shard


def _tables(modes: np.ndarray):
    agents = AgentTable.create(N_DEV * ROWS)
    sessions = SessionTable.create(2 * K)
    ws = jnp.arange(K)
    sessions = t_replace(
        sessions,
        state=sessions.state.at[ws].set(
            jnp.int8(SessionState.HANDSHAKING.code)
        ),
        mode=sessions.mode.at[: 2 * K].set(jnp.asarray(modes, jnp.int8)),
        max_participants=sessions.max_participants.at[ws].set(10),
        min_sigma_eff=sessions.min_sigma_eff.at[ws].set(0.6),
    )
    return agents, sessions, VouchTable.create(N_DEV * 4)


def _wave_args(rng):
    slots = np.array(
        [(i // 2) * ROWS + (i % 2) for i in range(B)], np.int32
    )
    sess = np.array([i // 2 for i in range(B)], np.int32)
    bodies = rng.randint(
        0, 2**32, size=(T, K, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    return (
        jnp.asarray(slots),
        jnp.arange(B, dtype=jnp.int32),
        jnp.asarray(sess),
        jnp.full((B,), 0.8, jnp.float32),
        jnp.ones((B,), bool),
        jnp.zeros((B,), bool),
        jnp.asarray(np.arange(K, dtype=np.int32)),
        jnp.asarray(bodies),
        7.5,
        0.5,
    )


class TestModeDispatchedWave:
    def test_mixed_plus_reconcile_equals_all_strong(self):
        mesh = make_mesh(N_DEV, platform="cpu")
        rng = np.random.RandomState(11)
        args = _wave_args(rng)

        mixed_modes = np.array(
            [i % 2 for i in range(2 * K)], np.int8  # odd lanes EVENTUAL
        )
        strong_modes = np.zeros(2 * K, np.int8)

        wave = sharded_governance_wave(mesh, mode_dispatch=True)

        res_s, part_s = wave(*_tables(strong_modes), *args)
        res_m, part_m = wave(*_tables(mixed_modes), *args)

        # The per-lane outcomes (admission, audit, archive walk) are
        # mode-independent — consistency changes WHEN the replica
        # commits, never the transaction's arithmetic.
        np.testing.assert_array_equal(
            np.asarray(res_m.status), np.asarray(res_s.status)
        )
        np.testing.assert_array_equal(
            np.asarray(res_m.merkle_root), np.asarray(res_s.merkle_root)
        )
        assert int(np.asarray(res_m.released)) == int(
            np.asarray(res_s.released)
        )

        # All-STRONG: no partials, table fully committed in-wave.
        assert (np.asarray(part_s.counts) == 0).all()
        assert (np.asarray(part_s.owned) == 0).all()
        arch = np.asarray(res_s.sessions.state)[:K]
        assert (arch == SessionState.ARCHIVED.code).all()

        # Mixed, PRE-reconcile: EVENTUAL lanes' replica rows are stale —
        # still HANDSHAKING, zero participants, no terminated_at.
        m_state = np.asarray(res_m.sessions.state)[:K]
        m_counts = np.asarray(res_m.sessions.n_participants)[:K]
        ev = mixed_modes[:K] == 1
        assert (m_state[~ev] == SessionState.ARCHIVED.code).all()
        assert (m_state[ev] == SessionState.HANDSHAKING.code).all()
        assert (m_counts[ev] == 0).all()
        assert (np.asarray(part_m.counts).sum(axis=0)[:K][ev] > 0).all()

        # Mixed + reconcile == all-STRONG, bit for bit, every column.
        folded = reconcile_wave_sessions(mesh)(
            res_m.sessions, part_m.counts, part_m.owned, part_m.state,
            part_m.terminated,
        )
        for col in (
            "sid", "state", "mode", "n_participants", "terminated_at",
            "created_at", "max_participants", "min_sigma_eff",
        ):
            got = np.asarray(getattr(folded, col))
            want = np.asarray(getattr(res_s.sessions, col))
            if col == "mode":
                # The mode column itself legitimately differs (it IS the
                # experiment variable); everything else must match.
                continue
            np.testing.assert_array_equal(got, want, err_msg=col)

    def test_bridge_defers_and_folds_on_demand(self):
        """`run_governance_wave(mesh=..., defer_reconcile=True)` leaves
        EVENTUAL sessions' replica rows stale until
        `reconcile_session_partials` folds them — and the default path
        (auto-reconcile) lands the identical end state."""
        mesh = make_mesh(N_DEV, platform="cpu")
        cfg = dataclasses.replace(
            DEFAULT_CONFIG,
            capacity=dataclasses.replace(
                DEFAULT_CONFIG.capacity, max_agents=N_DEV * 16
            ),
        )

        def staged(st):
            session_slots = st.create_sessions_batch(
                [f"md:s{i}" for i in range(K)],
                SessionConfig(
                    min_sigma_eff=0.0,
                    consistency_mode=ConsistencyMode.EVENTUAL,
                ),
            )
            # Even lanes forced STRONG: a genuinely mixed wave.
            for s in session_slots[::2]:
                st.force_session_mode(
                    int(s), ConsistencyMode.STRONG, has_nonreversible=False
                )
            dids = [f"did:md:{i}" for i in range(B)]
            agent_sessions = np.array([i % K for i in range(B)], np.int32)
            sigma = np.linspace(0.62, 0.95, B).astype(np.float32)
            rng = np.random.RandomState(3)
            bodies = rng.randint(
                0, 2**32, size=(T, K, merkle_ops.BODY_WORDS),
                dtype=np.uint64,
            ).astype(np.uint32)
            return session_slots, dids, agent_sessions, sigma, bodies

        st_defer = HypervisorState(cfg)
        slots_d = staged(st_defer)
        st_defer.run_governance_wave(
            *slots_d, now=2.0, mesh=mesh, defer_reconcile=True
        )
        ev_lanes = np.asarray(slots_d[0])[1::2]
        stale = np.asarray(st_defer.sessions.state)[ev_lanes]
        assert (stale == SessionState.HANDSHAKING.code).all()
        assert st_defer.reconcile_session_partials(mesh) == 1
        fresh = np.asarray(st_defer.sessions.state)[ev_lanes]
        assert (fresh == SessionState.ARCHIVED.code).all()

        st_auto = HypervisorState(cfg)
        st_auto.run_governance_wave(*staged(st_auto), now=2.0, mesh=mesh)
        np.testing.assert_array_equal(
            np.asarray(st_auto.sessions.state),
            np.asarray(st_defer.sessions.state),
        )
        np.testing.assert_array_equal(
            np.asarray(st_auto.sessions.n_participants),
            np.asarray(st_defer.sessions.n_participants),
        )
        assert st_auto.reconcile_session_partials(mesh) == 0
