"""Batched gateway wave ≡ sequential check_action, pinned.

The fused `ops.gateway.check_actions` program settles a whole action
wave in one device dispatch; these tests run the SAME action sequence
(a) as one `Hypervisor.check_actions` wave and (b) as per-element
`check_action` calls against an identical twin world, and require
identical verdicts, reasons, flags, breach counters, breaker trips,
and token levels — including the order-dependent cases the scalar
pipeline defines: an early probe tripping the breaker that refuses a
later action, and duplicate slots draining one bucket sequentially
(`security/rate_limiter.py:160-166` semantics).

Refill rates are zeroed so wall-clock drift between the scalar calls
cannot move a bucket across a verdict boundary.
"""

from __future__ import annotations

import numpy as np
import pytest

from hypervisor_tpu import Hypervisor, SessionConfig
from hypervisor_tpu.config import DEFAULT_CONFIG, RateLimitConfig
from hypervisor_tpu.models import (
    ActionDescriptor,
    ExecutionRing,
    ReversibilityLevel,
)
from hypervisor_tpu.state import HypervisorState
from hypervisor_tpu.tables.state import (
    FLAG_BREAKER_TRIPPED,
    FLAG_QUARANTINED,
)
from hypervisor_tpu.tables.struct import replace as t_replace

NO_REFILL = DEFAULT_CONFIG.replace(
    rate_limit=RateLimitConfig(ring_rates=(0.0, 0.0, 0.0, 0.0))
)

AGENTS = [
    ("did:ok", 0.8),      # Ring 2, plenty of budget
    ("did:probe", 0.7),   # Ring 2, will probe admin actions
    ("did:quar", 0.8),    # Ring 2, quarantined
    ("did:low", 0.4),     # Ring 3 sandbox
    ("did:drain", 0.8),   # Ring 2, bucket pinned to 2.4 tokens
    ("did:sudo", 0.97),   # Ring 2 (no consensus), sudo-grant candidate
]


def _write(**kw):
    base = dict(
        action_id="w",
        name="write",
        execute_api="/x",
        undo_api="/u",
        reversibility=ReversibilityLevel.FULL,
    )
    base.update(kw)
    return ActionDescriptor(**base)


def _read():
    return _write(action_id="r", is_read_only=True)


def _admin():
    return _write(
        action_id="adm", is_admin=True, undo_api=None,
        reversibility=ReversibilityLevel.NONE,
    )


async def _world():
    """One deterministic world: a session, five members, one quarantine,
    one drained bucket."""
    from hypervisor_tpu.liability.quarantine import QuarantineReason

    hv = Hypervisor(state=HypervisorState(NO_REFILL))
    ms = await hv.create_session(
        SessionConfig(min_sigma_eff=0.0, max_participants=10),
        creator_did="did:lead",
    )
    sid = ms.sso.session_id
    for did, sigma in AGENTS:
        await hv.join_session(sid, did, sigma_raw=sigma)

    q_slot = hv.state.agent_row("did:quar", ms.slot)["slot"]
    hv.quarantine.quarantine("did:quar", sid, QuarantineReason.MANUAL)
    hv.state.quarantine_rows([q_slot], now=hv.state.now())

    d_slot = hv.state.agent_row("did:drain", ms.slot)["slot"]
    hv.state.agents = t_replace(
        hv.state.agents,
        rl_tokens=hv.state.agents.rl_tokens.at[d_slot].set(2.4),
    )
    return hv, ms, sid


# The wave: interleaved so the probe agent's breaker trips MID-wave
# (min_calls_for_analysis=5 → probes 6+ refuse at gate 1), with drain
# calls woven between them and an allowed/quarantined/ring mix around.
SEQUENCE = [
    ("did:ok", _write(), False, False),
    ("did:quar", _write(), False, False),     # quarantined (write)
    ("did:quar", _read(), False, False),      # allowed (read-only isolation)
    ("did:probe", _admin(), False, False),    # ring-refused, privileged probe 1
    ("did:drain", _read(), False, False),     # token 1 of 2.4
    ("did:probe", _admin(), False, False),    # probe 2
    ("did:probe", _admin(), False, False),    # probe 3
    ("did:low", _write(), False, False),      # ring insufficient (3 > 2)
    ("did:probe", _admin(), False, False),    # probe 4
    ("did:drain", _read(), False, False),     # token 2 of 2.4
    ("did:probe", _admin(), False, False),    # probe 5 → trips breaker
    ("did:probe", _admin(), False, False),    # breaker-refused (gate 1)
    ("did:drain", _read(), False, False),     # bucket empty → rate-refused
    ("did:probe", _read(), False, False),     # breaker refuses benign reads too
    ("did:drain", _read(), False, False),     # still empty → rate-refused
    ("did:ok", _write(), False, False),
]


def _window_totals(hv):
    """(calls[N], privileged[N]) of the device sliding window at now."""
    from hypervisor_tpu.ops import security_ops

    calls, priv = security_ops.window_totals(
        hv.state.agents.bd_window, hv.state.now(), hv.state.config.breach
    )
    return np.asarray(calls), np.asarray(priv)


def _plant_window(hv, slot, calls, privileged=0):
    """Inject device-only window counts into the CURRENT sub-window
    bucket (host detector never sees them — deliberate divergence)."""
    import jax.numpy as jnp

    from hypervisor_tpu.ops.security_ops import window_epoch
    from hypervisor_tpu.tables.state import BD_BUCKETS

    cur = int(window_epoch(hv.state.now(), hv.state.config.breach))
    j0 = cur % BD_BUCKETS
    w = hv.state.agents.bd_window
    w = (
        w.at[slot, j0].set(calls)
        .at[slot, BD_BUCKETS + j0].set(privileged)
        .at[slot, 2 * BD_BUCKETS + j0].set(cur)
    )
    hv.state.agents = t_replace(hv.state.agents, bd_window=jnp.asarray(w))


def _snapshot(hv, ms, dids):
    ag = hv.state.agents
    calls_all, priv_all = _window_totals(hv)
    out = {}
    for did in dids:
        slot = hv.state.agent_row(did, ms.slot)["slot"]
        out[did] = dict(
            calls=int(calls_all[slot]),
            privileged=int(priv_all[slot]),
            tripped=bool(np.asarray(ag.flags)[slot] & FLAG_BREAKER_TRIPPED),
            quarantined=bool(np.asarray(ag.flags)[slot] & FLAG_QUARANTINED),
            tokens=float(np.asarray(ag.rl_tokens)[slot]),
        )
    return out


class TestGatewayWaveParity:
    async def test_wave_matches_sequential(self):
        hv_w, ms_w, sid_w = await _world()
        hv_s, ms_s, sid_s = await _world()

        wave = await hv_w.check_actions(sid_w, SEQUENCE)
        seq = [
            await hv_s.check_action(sid_s, did, action, c, w)
            for did, action, c, w in SEQUENCE
        ]

        assert len(wave) == len(seq) == len(SEQUENCE)
        for i, (rw, rs) in enumerate(zip(wave, seq)):
            assert rw.allowed == rs.allowed, (i, rw.reason, rs.reason)
            assert rw.reason == rs.reason, i
            assert rw.quarantined == rs.quarantined, i
            assert rw.rate_limited == rs.rate_limited, i
            assert rw.breaker_tripped == rs.breaker_tripped, i
            assert rw.effective_ring is rs.effective_ring, i
            assert (rw.ring_check is None) == (rs.ring_check is None), i
            if rw.ring_check is not None:
                assert rw.ring_check.reason == rs.ring_check.reason, i

        # The exact refusal story the sequence was built to exercise.
        kinds = [
            "allowed" if r.allowed
            else "breaker" if r.breaker_tripped
            else "quar" if r.quarantined
            else "rate" if r.rate_limited
            else "ring"
            for r in wave
        ]
        assert kinds == [
            "allowed", "quar", "allowed", "ring", "allowed", "ring",
            "ring", "ring", "ring", "allowed", "ring", "breaker",
            "rate", "breaker", "rate", "allowed",
        ]

        # Post-state parity on the device columns (stamps/deadlines are
        # wall-clock and excluded; rates are zeroed so tokens are exact).
        dids = [d for d, _ in AGENTS]
        snap_w = _snapshot(hv_w, ms_w, dids)
        snap_s = _snapshot(hv_s, ms_s, dids)
        for did in dids:
            for key in ("calls", "privileged", "tripped", "quarantined"):
                assert snap_w[did][key] == snap_s[did][key], (did, key)
            assert snap_w[did]["tokens"] == pytest.approx(
                snap_s[did]["tokens"], abs=1e-4
            ), did

        # Both planes agree the probe agent's breaker is live.
        assert snap_w["did:probe"]["tripped"]
        assert hv_w.breach_detector.is_breaker_tripped("did:probe", sid_w)

    async def test_elevated_calls_are_not_privileged_probes(self):
        """A live sudo grant applies to the wave's window accounting:
        calls at the granted ring don't count as privileged probing
        (the documented check_action contract), and the bucket charges
        the ELEVATED ring's budget."""
        hv_w, ms_w, sid_w = await _world()
        hv_s, ms_s, sid_s = await _world()

        # NONE-reversibility write → required ring 1; with σ=0.97 and
        # consensus, the only blocker is the agent's base ring 2 — the
        # sudo grant clears it.
        ring1_action = _write(undo_api=None, reversibility=ReversibilityLevel.NONE)
        seq2 = [("did:sudo", ring1_action, True, False)] * 6
        for hv, sid in ((hv_w, sid_w), (hv_s, sid_s)):
            await hv.grant_elevation(
                sid, "did:sudo", ExecutionRing.RING_1_PRIVILEGED
            )

        wave = await hv_w.check_actions(sid_w, seq2)
        seq = [
            await hv_s.check_action(sid_s, did, action, c, w)
            for did, action, c, w in seq2
        ]
        for i, (rw, rs) in enumerate(zip(wave, seq)):
            assert rw.allowed == rs.allowed, i
            assert rw.effective_ring is rs.effective_ring, i
            assert rw.effective_ring is ExecutionRing.RING_1_PRIVILEGED, i

        slot = hv_w.state.agent_row("did:sudo", ms_w.slot)["slot"]
        ag = hv_w.state.agents
        calls_all, priv_all = _window_totals(hv_w)
        assert int(calls_all[slot]) == 6
        # required ring 1 == effective ring 1 → never a privileged probe
        # (against the BASE ring 2 every one of these would have counted,
        # 6 > min_calls and the breaker would already be live).
        assert int(priv_all[slot]) == 0
        assert not bool(np.asarray(ag.flags)[slot] & FLAG_BREAKER_TRIPPED)
        assert all(r.allowed for r in wave)

    async def test_host_only_trip_mid_wave_gates_later_actions(self):
        """When the planes' windows disagree (device window diluted by
        planted clean calls the host detector never saw), a HOST-plane
        trip during the wave must still refuse later actions — each
        action's host breaker state is read after the mirror recorded
        everything before it, like the sequential pipeline."""
        hv_w, ms_w, sid_w = await _world()
        hv_s, ms_s, sid_s = await _world()

        # Dilute the DEVICE window only: 200 clean in-window calls mean
        # 7 privileged probes stay under the 0.7 trip threshold on
        # device, while the host's undiluted window trips at probe 5.
        for hv, ms in ((hv_w, ms_w), (hv_s, ms_s)):
            slot = hv.state.agent_row("did:probe", ms.slot)["slot"]
            _plant_window(hv, slot, calls=200)

        probes = [("did:probe", _admin(), False, False)] * 7
        wave = await hv_w.check_actions(sid_w, probes)
        seq = [
            await hv_s.check_action(sid_s, did, action, c, w)
            for did, action, c, w in probes
        ]
        kinds_w = [
            "breaker" if r.breaker_tripped else "ring" for r in wave
        ]
        kinds_s = [
            "breaker" if r.breaker_tripped else "ring" for r in seq
        ]
        assert kinds_w == kinds_s
        assert kinds_w == ["ring"] * 5 + ["breaker"] * 2

    async def test_empty_wave_is_a_noop(self):
        hv, ms, sid = await _world()
        before = _snapshot(hv, ms, [d for d, _ in AGENTS])
        assert await hv.check_actions(sid, []) == []
        after = _snapshot(hv, ms, [d for d, _ in AGENTS])
        for did in before:
            for key in ("calls", "privileged", "tripped", "quarantined"):
                assert before[did][key] == after[did][key]


class TestGatewayOpMasking:
    def test_padded_lanes_change_nothing(self):
        """valid=False lanes (ragged-wave padding) must not touch any
        row — verdicts on real lanes and the post-state table are
        bit-identical to the unpadded wave."""
        import jax.numpy as jnp

        from hypervisor_tpu.ops import gateway as gw
        from hypervisor_tpu.tables.state import AgentTable, ElevationTable

        agents = AgentTable.create(8)
        agents = t_replace(
            agents,
            did=agents.did.at[:4].set(jnp.arange(4)),
            sigma_eff=agents.sigma_eff.at[:4].set(0.8),
            ring=agents.ring.at[:4].set(2),
            rl_tokens=agents.rl_tokens.at[:4].set(5.0),
        )
        elevs = ElevationTable.create(4)
        slot = jnp.asarray([0, 1, 0, 2], jnp.int32)
        req = jnp.asarray([2, 2, 2, 2], jnp.int8)
        ro = jnp.zeros((4,), bool)
        cw = jnp.zeros((4,), bool)
        ht = jnp.zeros((4,), bool)

        bare = gw.check_actions(
            agents, elevs, slot, req, ro, cw, cw, ht, now=100.0
        )

        def pad4(x, fill=0):
            return jnp.concatenate([x, jnp.full((4,), fill, x.dtype)])

        padded = gw.check_actions(
            agents,
            elevs,
            pad4(slot),
            pad4(req),
            pad4(ro),
            pad4(cw),
            pad4(cw),
            pad4(ht),
            now=100.0,
            valid=jnp.asarray([1, 1, 1, 1, 0, 0, 0, 0], bool),
        )
        assert np.array_equal(
            np.asarray(bare.verdict), np.asarray(padded.verdict[:4])
        )
        assert np.all(
            np.asarray(padded.verdict[4:]) == gw.GATE_INVALID
        )
        for name in ("f32", "i32", "ring"):
            assert np.array_equal(
                np.asarray(getattr(bare.agents, name)),
                np.asarray(getattr(padded.agents, name)),
            ), name
