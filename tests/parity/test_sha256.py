"""Bit-for-bit parity of the device SHA-256 pipeline against hashlib."""

import hashlib
import struct

import numpy as np
import jax.numpy as jnp
import pytest

from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.ops import sha256 as sha_ops
from hypervisor_tpu.audit.delta import merkle_root_host


class TestSha256Blocks:
    @pytest.mark.parametrize("msg_len", [0, 1, 55, 56, 64, 100, 119, 120, 128])
    def test_parity_vs_hashlib(self, msg_len):
        rng = np.random.RandomState(msg_len)
        batch = rng.randint(0, 256, size=(4, msg_len), dtype=np.int64).astype(np.uint8)
        words, n_blocks = sha_ops.pad_messages_np(batch, msg_len)
        digests = sha_ops.sha256_blocks(jnp.asarray(words), n_blocks)
        got = sha_ops.digests_to_hex(np.asarray(digests))
        want = [hashlib.sha256(batch[i].tobytes()).hexdigest() for i in range(4)]
        assert got == want

    def test_hex_pair_matches_reference_combine(self):
        lh = [hashlib.sha256(b"left%d" % i).hexdigest() for i in range(8)]
        rh = [hashlib.sha256(b"right%d" % i).hexdigest() for i in range(8)]
        out = sha_ops.sha256_hex_pair(
            jnp.asarray(sha_ops.hex_to_words(lh)), jnp.asarray(sha_ops.hex_to_words(rh))
        )
        got = sha_ops.digests_to_hex(np.asarray(out))
        want = [hashlib.sha256((a + b).encode()).hexdigest() for a, b in zip(lh, rh)]
        assert got == want


class TestMerkleRoot:
    @pytest.mark.parametrize("n", [1, 2, 3, 5, 6, 7, 12, 16, 33])
    def test_device_root_equals_host_loop(self, n):
        hexes = [hashlib.sha256(b"leaf%d" % i).hexdigest() for i in range(n)]
        p = 1 << max(0, (n - 1).bit_length())
        leaves = np.zeros((max(p, 1), 8), np.uint32)
        leaves[:n] = sha_ops.hex_to_words(hexes)
        root = merkle_ops.merkle_root(jnp.asarray(leaves), jnp.int32(n))
        got = sha_ops.digests_to_hex(np.asarray(root)[None])[0]
        assert got == merkle_root_host(hexes)


class TestChain:
    def test_chain_digests_match_hashlib(self):
        rng = np.random.RandomState(7)
        bodies = rng.randint(
            0, 2**32, size=(6, 2, merkle_ops.BODY_WORDS), dtype=np.uint64
        ).astype(np.uint32)
        digests = np.asarray(merkle_ops.chain_digests(jnp.asarray(bodies)))
        for lane in range(2):
            parent = b"\x00" * 32
            for t in range(6):
                msg = b"".join(struct.pack(">I", x) for x in bodies[t, lane]) + parent
                want = hashlib.sha256(msg).digest()
                got = b"".join(struct.pack(">I", x) for x in digests[t, lane])
                assert got == want
                parent = want

    def test_verify_detects_tamper(self):
        rng = np.random.RandomState(3)
        bodies = rng.randint(
            0, 2**32, size=(5, 3, merkle_ops.BODY_WORDS), dtype=np.uint64
        ).astype(np.uint32)
        digests = np.asarray(merkle_ops.chain_digests(jnp.asarray(bodies)))
        counts = jnp.asarray([5, 5, 5], jnp.int32)
        ok = merkle_ops.verify_chain_digests(
            jnp.asarray(bodies), jnp.asarray(digests), counts
        )
        assert np.asarray(ok).tolist() == [True, True, True]
        tampered = digests.copy()
        tampered[2, 1, 0] ^= 1
        ok = merkle_ops.verify_chain_digests(
            jnp.asarray(bodies), jnp.asarray(tampered), counts
        )
        assert np.asarray(ok).tolist() == [True, False, True]
