"""Pallas SHA-256 kernel parity vs hashlib and the XLA implementation.

The Mosaic kernel body (`_compress_unrolled` + the [Bt, n_words, 8, 128]
tiling) is verified bit-for-bit by executing the identical code on numpy
arrays (`sha256_words_unrolled_np`) — XLA:CPU cannot compile the ~6k-op
fully unrolled program reliably (11 s to >9 min, "Very slow compile?"),
and Mosaic interpret mode stalls when a TPU PJRT plugin is registered.
The compiled `pallas_call` path itself is exercised on the real chip by
bench.py and the TPU-gated test below.

Reference semantics: `audit/delta.py:41-64` (hashlib.sha256 digests).
"""

from __future__ import annotations

import hashlib

import numpy as np
import jax.numpy as jnp
import pytest

from hypervisor_tpu.kernels.sha256_pallas import (
    TILE,
    pallas_available,
    sha256_words,
    sha256_words_unrolled_np,
)
from hypervisor_tpu.ops.sha256 import digests_to_hex, pad_messages_np


@pytest.mark.parametrize("msg_len", [0, 1, 55, 56, 64, 96, 200])
def test_unrolled_kernel_math_matches_hashlib(msg_len):
    rng = np.random.RandomState(msg_len)
    b = 33
    msgs = rng.randint(0, 256, size=(b, msg_len), dtype=np.int64).astype(np.uint8)
    words, nb = pad_messages_np(msgs, msg_len)
    got = digests_to_hex(sha256_words_unrolled_np(words, nb))
    want = [hashlib.sha256(m.tobytes()).hexdigest() for m in msgs]
    assert got == want


def test_unrolled_kernel_tiling_multi_tile():
    # > one 1024-lane tile + ragged remainder: exercises the grid tiling and
    # padding logic exactly as the kernel's BlockSpec walks it.
    rng = np.random.RandomState(7)
    b, msg_len = TILE + 7, 96
    msgs = rng.randint(0, 256, size=(b, msg_len), dtype=np.int64).astype(np.uint8)
    words, nb = pad_messages_np(msgs, msg_len)
    got = digests_to_hex(sha256_words_unrolled_np(words, nb))
    want = [hashlib.sha256(m.tobytes()).hexdigest() for m in msgs]
    assert got == want


@pytest.mark.skipif(
    not pallas_available(),
    reason="compiled Mosaic kernel needs a TPU backend "
    "(opt in with HV_TPU_TESTS=1 to run against the real chip)",
)
def test_compiled_pallas_kernel_matches_hashlib_on_tpu():
    rng = np.random.RandomState(11)
    b, msg_len = 2050, 96
    msgs = rng.randint(0, 256, size=(b, msg_len), dtype=np.int64).astype(np.uint8)
    words, nb = pad_messages_np(msgs, msg_len)
    got = digests_to_hex(np.asarray(sha256_words(jnp.asarray(words), nb)))
    want = [hashlib.sha256(m.tobytes()).hexdigest() for m in msgs]
    assert got == want
