"""Sharded action-gateway wave ≡ single-device gateway wave, pinned.

`parallel.collectives.sharded_gateway` runs `ops.gateway.check_actions`
under shard_map with agent rows sharded and elevations replicated; the
state bridge (`check_actions_wave(mesh=...)`) builds the shard layout
itself from an arbitrary RAGGED request list — any slots, any counts,
no caller-side padding. These tests pin the sharded path bit-for-bit
against the single-device wave on identical tables, and the fused
governance-wave-with-gateway program against the composed two-call
sequence.

Runs on the virtual 8-device CPU mesh (conftest forces the platform).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from hypervisor_tpu.config import DEFAULT_CONFIG, RateLimitConfig
from hypervisor_tpu.models import SessionConfig
from hypervisor_tpu.ops import gateway as gw
from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.ops import security_ops
from hypervisor_tpu.parallel import make_mesh
from hypervisor_tpu.state import HypervisorState
from hypervisor_tpu.tables.state import FLAG_BREAKER_TRIPPED
from hypervisor_tpu.tables.struct import replace as t_replace

N_DEV = 8
N_AGENTS = 40    # rows 0..39 — shards 0..4 populated, 5..7 empty


def _config(max_agents: int = 64):
    return dataclasses.replace(
        DEFAULT_CONFIG,
        rate_limit=RateLimitConfig(ring_rates=(0.0, 0.0, 0.0, 0.0)),
        capacity=dataclasses.replace(
            DEFAULT_CONFIG.capacity, max_agents=max_agents
        ),
    )


def _sigma(i: int) -> float:
    if i in (7, 33):
        return 0.97    # sudo-grant candidates (rings 1 need σ > 0.95)
    if i == 13:
        return 0.40    # Ring 3 sandbox
    return 0.80        # Ring 2


def _state(max_agents: int = 64) -> tuple[HypervisorState, int]:
    """Deterministic world: 40 members across 5 shard regions (under
    the default 64-row capacity), one quarantined row, one sudo grant,
    one drained bucket. Every `now` is explicit so twin builds are
    bit-identical."""
    st = HypervisorState(_config(max_agents))
    sess = st.create_session(
        "sg:s0", SessionConfig(min_sigma_eff=0.0, max_participants=64)
    )
    for i in range(N_AGENTS):
        st.enqueue_join(sess, f"did:g{i}", sigma_raw=_sigma(i))
    assert (st.flush_joins(now=10.0) == 0).all()
    st.quarantine_rows([21], now=10.0)          # shard 2
    st.grant_elevation(7, granted_ring=1, now=10.0, ttl_seconds=900.0)
    st.agents = t_replace(
        st.agents, rl_tokens=st.agents.rl_tokens.at[30].set(1.4)  # shard 3
    )
    return st, sess


# A ragged 15-action wave touching 4 shards: duplicate slots on the
# drained bucket (sequential settle), privileged probes that trip row
# 33's breaker mid-wave, a quarantined write + read, an elevated ring-1
# action, and a sandboxed agent's refused write.
#   columns: (slot, required_ring, read_only, consensus, witness)
ACTIONS = [
    (2, 2, False, False, False),    # shard 0: allowed write
    (21, 2, False, False, False),   # shard 2: quarantined write -> refused
    (21, 3, True, False, False),    # shard 2: quarantined read -> allowed
    (33, 0, False, False, False),   # shard 4: privileged probe 1
    (30, 3, True, False, False),    # shard 3: drain token 1 (of 1.4)
    (33, 0, False, False, False),   # probe 2
    (33, 0, False, False, False),   # probe 3
    (13, 2, False, False, False),   # shard 1: ring 3 sandbox -> refused
    (33, 0, False, False, False),   # probe 4
    (30, 3, True, False, False),    # drain token 2 -> rate-refused (1.4)
    (33, 0, False, False, False),   # probe 5 -> trips breaker
    (33, 0, False, False, False),   # probe 6 -> breaker-refused
    (7, 1, False, True, False),     # shard 0: sudo ring-1 action, allowed
    (33, 3, True, False, False),    # breaker refuses benign reads
    (2, 2, False, False, False),    # allowed write
]


def _cols():
    return (
        np.asarray([r[0] for r in ACTIONS], np.int32),
        np.asarray([r[1] for r in ACTIONS], np.int8),
        np.asarray([r[2] for r in ACTIONS], bool),
        np.asarray([r[3] for r in ACTIONS], bool),
        np.asarray([r[4] for r in ACTIONS], bool),
        np.zeros(len(ACTIONS), bool),
    )


class TestShardedGateway:
    def test_ragged_wave_matches_single_device_bitwise(self):
        mesh = make_mesh(N_DEV, platform="cpu")
        st1, _ = _state()
        st2, _ = _state()
        # Twin builds must start bit-identical (all nows explicit).
        np.testing.assert_array_equal(
            np.asarray(st1.agents.f32), np.asarray(st2.agents.f32)
        )

        slots, req, ro, cons, wit, ht = _cols()
        r1 = st1.check_actions_wave(slots, req, ro, cons, wit, ht, now=20.0)
        r2 = st2.check_actions_wave(
            slots, req, ro, cons, wit, ht, now=20.0, mesh=mesh
        )

        for name in (
            "verdict", "ring_status", "eff_ring", "sigma_eff",
            "severity", "anomaly_rate", "window_calls", "tripped",
        ):
            np.testing.assert_array_equal(
                np.asarray(getattr(r1, name)),
                np.asarray(getattr(r2, name)),
                err_msg=name,
            )

        # The exact refusal story the wave was built to exercise.
        kinds = [int(v) for v in np.asarray(r1.verdict)]
        assert kinds == [
            gw.GATE_ALLOWED, gw.GATE_QUARANTINED, gw.GATE_ALLOWED,
            gw.GATE_RING, gw.GATE_ALLOWED, gw.GATE_RING, gw.GATE_RING,
            gw.GATE_RING, gw.GATE_RING, gw.GATE_RATE, gw.GATE_RING,
            gw.GATE_BREAKER, gw.GATE_ALLOWED, gw.GATE_BREAKER,
            gw.GATE_ALLOWED,
        ]

        # Post-state tables agree bit-for-bit (one shared `now`, so
        # even the restamped bucket columns match).
        np.testing.assert_array_equal(
            np.asarray(st1.agents.f32), np.asarray(st2.agents.f32)
        )
        np.testing.assert_array_equal(
            np.asarray(st1.agents.i32), np.asarray(st2.agents.i32)
        )
        np.testing.assert_array_equal(
            np.asarray(st1.agents.ring), np.asarray(st2.agents.ring)
        )
        # Row 33's breaker tripped on both planes' tables.
        assert np.asarray(st2.agents.flags)[33] & FLAG_BREAKER_TRIPPED

    def test_single_action_and_cross_shard_elevation(self):
        """N=1 sharded waves work, and a grant whose agent lives on a
        non-zero shard applies (the replicated ElevationTable localizes
        by shard base row)."""
        mesh = make_mesh(N_DEV, platform="cpu")
        st1, _ = _state()
        st2, _ = _state()
        # Row 7's grant lives on shard 0; add one for row 33 (shard 4).
        for st in (st1, st2):
            st.grant_elevation(33, granted_ring=1, now=10.0,
                               ttl_seconds=900.0)
        one = (
            np.asarray([33], np.int32), np.asarray([1], np.int8),
            np.asarray([False]), np.asarray([True]), np.asarray([False]),
            np.asarray([False]),
        )
        r1 = st1.check_actions_wave(*one, now=20.0)
        r2 = st2.check_actions_wave(*one, now=20.0, mesh=mesh)
        assert int(r1.verdict[0]) == int(r2.verdict[0]) == gw.GATE_ALLOWED
        assert int(r1.eff_ring[0]) == int(r2.eff_ring[0]) == 1


class TestShardedGatewayEdges:
    def test_empty_wave_is_a_noop(self):
        mesh = make_mesh(N_DEV, platform="cpu")
        st, _ = _state()
        before = np.asarray(st.agents.i32).copy()
        empty = np.asarray([], np.int32)
        r = st.check_actions_wave(
            empty, empty, empty.astype(bool), empty.astype(bool),
            empty.astype(bool), empty.astype(bool), now=20.0, mesh=mesh,
        )
        assert len(np.asarray(r.verdict)) == 0
        np.testing.assert_array_equal(np.asarray(st.agents.i32), before)

    def test_indivisible_capacity_refuses_clearly(self):
        mesh = make_mesh(N_DEV, platform="cpu")
        st, _ = _state(max_agents=60)  # 60 % 8 != 0
        with pytest.raises(ValueError, match="not divisible"):
            st.check_actions_wave(
                [0], [2], [False], [False], [False], [False],
                now=20.0, mesh=mesh,
            )


class TestFusedWaveWithGateway:
    def test_fused_gateway_phase_matches_composed_calls(self):
        """run_governance_wave(mesh=..., actions=...) — admissions,
        terminations, AND standing-membership action checks as ONE
        shard_map program — matches the composed wave-then-gateway
        sequence on a single device."""
        T, K, B = 2, 8, 16
        mesh = make_mesh(N_DEV, platform="cpu")

        def staged(st):
            session_slots = st.create_sessions_batch(
                [f"fw:s{i}" for i in range(K)],
                SessionConfig(min_sigma_eff=0.0),
            )
            dids = [f"did:fw:{i}" for i in range(B)]
            agent_sessions = np.array([i % K for i in range(B)], np.int32)
            sigma = np.linspace(0.62, 0.95, B).astype(np.float32)
            rng = np.random.RandomState(7)
            bodies = rng.randint(
                0, 2**32, size=(T, K, merkle_ops.BODY_WORDS), dtype=np.uint64
            ).astype(np.uint32)
            return session_slots, dids, agent_sessions, sigma, bodies

        slots, req, ro, cons, wit, ht = _cols()
        actions = dict(
            slots=slots, required_rings=req, is_read_only=ro,
            has_consensus=cons, has_sre_witness=wit, host_tripped=ht,
        )

        # Wave rows live at the top of each shard region; 512 rows keep
        # the 40 standing members clear of them (they land on shard 0 —
        # cross-shard action placement is the standalone test's job).
        st1, _ = _state(max_agents=512)
        res1, gw1 = st1.run_governance_wave(
            *staged(st1), now=20.0, use_pallas=False, actions=actions
        )
        st2, _ = _state(max_agents=512)
        res2, gw2 = st2.run_governance_wave(
            *staged(st2), now=20.0, mesh=mesh, actions=actions
        )

        np.testing.assert_array_equal(
            np.asarray(res1.status), np.asarray(res2.status)
        )
        np.testing.assert_array_equal(
            np.asarray(res1.merkle_root), np.asarray(res2.merkle_root)
        )
        for name in ("verdict", "ring_status", "eff_ring", "tripped"):
            np.testing.assert_array_equal(
                np.asarray(getattr(gw1, name)),
                np.asarray(getattr(gw2, name)),
                err_msg=name,
            )
        # The metrics plane must agree too: the fused mesh path tallies
        # gateway verdicts on the host plane of the same series the
        # single-device path counts in-wave.
        from hypervisor_tpu.observability import metrics as mp

        snap1, snap2 = st1.metrics_snapshot(), st2.metrics_snapshot()
        for handle in (mp.GATEWAY_ALLOWED, mp.GATEWAY_DENIED):
            assert snap1.counter(handle) == snap2.counter(handle), handle
        assert (
            snap1.counter(mp.GATEWAY_ALLOWED)
            + snap1.counter(mp.GATEWAY_DENIED)
            == len(slots)
        )
        # Standing rows live at the same slots on both paths, so their
        # gateway columns agree bit-for-bit.
        for st in (st1, st2):
            assert np.asarray(st.agents.flags)[33] & FLAG_BREAKER_TRIPPED
            calls, _ = security_ops.window_totals(
                st.agents.bd_window, st.now(), st.config.breach
            )
            assert int(np.asarray(calls)[33]) == 7
