"""HypervisorState.run_governance_wave(mesh=...) — the sharded fused
wave on the REAL state tables vs the single-device state wave.

BASELINE's "10k concurrent sessions multi-chip" config, scaled down to
the virtual 8-device CPU mesh: the state-bridge path must produce the
same semantic outcome (admissions, chains/Merkle roots, bond releases,
archival, membership, DeltaLog audit index) whether the wave runs as one
single-device program or one shard_map program with sharded tables.
Agent row PLACEMENT legitimately differs (bump region vs the mesh slot
contract's top-of-shard regions), so the comparison is semantic, not
row-for-row.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from hypervisor_tpu.config import DEFAULT_CONFIG
from hypervisor_tpu.models import SessionConfig, SessionState
from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.parallel import make_mesh
from hypervisor_tpu.state import HypervisorState
from hypervisor_tpu.tables.struct import replace as t_replace

N_DEV = 8
B = 32          # joining agents (4 per shard)
K = 8           # wave sessions
T = 3


def _config():
    return dataclasses.replace(
        DEFAULT_CONFIG,
        capacity=dataclasses.replace(
            DEFAULT_CONFIG.capacity, max_agents=N_DEV * 16
        ),
    )


def _staged(state):
    session_slots = state.create_sessions_batch(
        [f"mw:s{i}" for i in range(K)], SessionConfig(min_sigma_eff=0.0)
    )
    dids = [f"did:mw:{i}" for i in range(B)]
    agent_sessions = np.array([i % K for i in range(B)], np.int32)
    sigma = np.linspace(0.62, 0.95, B).astype(np.float32)
    # A vouch preload: phantom voucher lifts element 0's low sigma.
    sigma[0] = 0.45
    state.vouches = t_replace(
        state.vouches,
        voucher=state.vouches.voucher.at[0].set(state.agents.did.shape[0] - 1),
        vouchee=state.vouches.vouchee.at[0].set(-7),  # patched per path
        session=state.vouches.session.at[0].set(0),
        bond=state.vouches.bond.at[0].set(0.40),
        active=state.vouches.active.at[0].set(True),
    )
    rng = np.random.RandomState(5)
    bodies = rng.randint(
        0, 2**32, size=(T, K, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    return session_slots, dids, agent_sessions, sigma, bodies


def _patch_vouchee(state, slot):
    state.vouches = t_replace(
        state.vouches, vouchee=state.vouches.vouchee.at[0].set(int(slot))
    )


class TestStateMeshWave:
    def test_mesh_wave_matches_single_device_semantics(self):
        mesh = make_mesh(N_DEV, platform="cpu")

        st_single = HypervisorState(_config())
        args_s = _staged(st_single)
        _patch_vouchee(st_single, st_single._next_agent_slot)  # element 0's row
        res_s = st_single.run_governance_wave(
            args_s[0], args_s[1], args_s[2], args_s[3], args_s[4],
            now=2.0, use_pallas=False,
        )

        st_mesh = HypervisorState(_config())
        args_m = _staged(st_mesh)
        _patch_vouchee(st_mesh, st_mesh._mesh_wave_slots(B, N_DEV)[0])
        res_m = st_mesh.run_governance_wave(
            args_m[0], args_m[1], args_m[2], args_m[3], args_m[4],
            now=2.0, mesh=mesh,
        )

        np.testing.assert_array_equal(
            np.asarray(res_m.status), np.asarray(res_s.status)
        )
        np.testing.assert_array_equal(
            np.asarray(res_m.ring), np.asarray(res_s.ring)
        )
        np.testing.assert_array_equal(
            np.asarray(res_m.sigma_eff), np.asarray(res_s.sigma_eff)
        )
        np.testing.assert_array_equal(
            np.asarray(res_m.chain), np.asarray(res_s.chain)
        )
        np.testing.assert_array_equal(
            np.asarray(res_m.merkle_root), np.asarray(res_s.merkle_root)
        )
        assert int(np.asarray(res_m.released)) == int(
            np.asarray(res_s.released)
        )
        # Vouched element 0 lifted identically on both paths.
        assert float(np.asarray(res_m.sigma_eff)[0]) == pytest.approx(
            0.45 + 0.5 * 0.40
        )

        # Both states agree on the world afterwards.
        for st in (st_single, st_mesh):
            state_col = np.asarray(st.sessions.state)[:K]
            assert (state_col == SessionState.ARCHIVED.code).all()
            for i in range(B):
                assert st.is_member(i % K, f"did:mw:{i}")
            # Audit index carries T leaves per wave session.
            for s in range(K):
                assert len(st._audit_rows[s]) == T
        np.testing.assert_array_equal(
            np.asarray(st_mesh.sessions.n_participants),
            np.asarray(st_single.sessions.n_participants),
        )
        np.testing.assert_array_equal(
            np.asarray(st_mesh.delta_log.digest),
            np.asarray(st_single.delta_log.digest),
        )

    def test_non_contiguous_wave_takes_mask_fallback(self):
        """A caller-supplied NON-contiguous session wave (every other
        slot) must refuse the range fast path on host and still match
        the single-device outcome through the mask-variant program."""
        mesh = make_mesh(N_DEV, platform="cpu")

        def staged(st):
            all_slots = st.create_sessions_batch(
                [f"nc:s{i}" for i in range(2 * K)],
                SessionConfig(min_sigma_eff=0.0),
            )
            wave_slots = all_slots[::2]  # 0, 2, 4, ... — gaps on purpose
            dids = [f"did:nc:{i}" for i in range(B)]
            agent_sessions = np.asarray(wave_slots, np.int32)[
                np.arange(B) % K
            ]
            rng = np.random.RandomState(9)
            bodies = rng.randint(
                0, 2**32, size=(T, K, merkle_ops.BODY_WORDS), dtype=np.uint64
            ).astype(np.uint32)
            return wave_slots, dids, agent_sessions, bodies

        st_single = HypervisorState(_config())
        ws_s, dids_s, asess_s, bodies_s = staged(st_single)
        res_s = st_single.run_governance_wave(
            ws_s, dids_s, asess_s, np.full(B, 0.8, np.float32), bodies_s,
            now=3.0, use_pallas=False,
        )

        st_mesh = HypervisorState(_config())
        ws_m, dids_m, asess_m, bodies_m = staged(st_mesh)
        res_m = st_mesh.run_governance_wave(
            ws_m, dids_m, asess_m, np.full(B, 0.8, np.float32), bodies_m,
            now=3.0, mesh=mesh,
        )

        np.testing.assert_array_equal(
            np.asarray(res_m.status), np.asarray(res_s.status)
        )
        np.testing.assert_array_equal(
            np.asarray(res_m.merkle_root), np.asarray(res_s.merkle_root)
        )
        assert int(np.asarray(res_m.released)) == int(
            np.asarray(res_s.released)
        )
        for st, ws in ((st_single, ws_s), (st_mesh, ws_m)):
            state_col = np.asarray(st.sessions.state)
            # Wave sessions archived; the SKIPPED odd slots are untouched
            # (still HANDSHAKING) — the exact hazard a wrongly-applied
            # range path would create.
            assert (
                state_col[np.asarray(ws)] == SessionState.ARCHIVED.code
            ).all()
            skipped = np.setdiff1d(
                np.arange(2 * K, dtype=np.int32), np.asarray(ws)
            )
            assert (
                state_col[skipped] == SessionState.HANDSHAKING.code
            ).all()

    def test_mesh_wave_rows_recycle_without_free_list(self):
        mesh = make_mesh(N_DEV, platform="cpu")
        st = HypervisorState(_config())
        for round_i in range(2):
            session_slots = st.create_sessions_batch(
                [f"mw2:r{round_i}:s{i}" for i in range(K)],
                SessionConfig(min_sigma_eff=0.0),
            )
            dids = [f"did:mw2:r{round_i}:{i}" for i in range(B)]
            rng = np.random.RandomState(round_i)
            bodies = rng.randint(
                0, 2**32, size=(T, K, merkle_ops.BODY_WORDS), dtype=np.uint64
            ).astype(np.uint32)
            res = st.run_governance_wave(
                session_slots,
                dids,
                np.asarray(session_slots, np.int32)[
                    np.arange(B) % K
                ],
                np.full(B, 0.8, np.float32),
                bodies,
                now=1.0 + round_i,
                mesh=mesh,
            )
            assert (np.asarray(res.status) == 0).all()
        # Mesh rows never leaked into the general free list.
        assert not st._free_agent_slots

    def test_bump_overlap_refuses_loudly(self):
        st = HypervisorState(_config())
        # Push the bump allocator into the mesh-wave region of shard 0.
        st._next_agent_slot = st.agents.did.shape[0] // N_DEV
        with pytest.raises(RuntimeError, match="mesh-wave region"):
            st._mesh_wave_slots(B, N_DEV)
