"""Randomized-shape soak: sharded + multislice waves ≡ single device.

Opt-in (HV_SOAK=1): every distinct (B, K) shape compiles its own
programs (~10-30 s each on the virtual CPU mesh), so this is a soak
harness rather than a default-suite test. It randomizes the wave
geometry the deterministic parity tests keep fixed — join counts,
session counts, shard-local load balance, duplicate-lane placement,
sigma mixes, vouch edges — and pins the sharded and multislice waves
bit-par with the single-device wave on every draw.

Run: HV_SOAK=1 python -m pytest tests/parity/test_wave_shape_fuzz.py -q
(optionally HV_SOAK_ITERS=N, default 6).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from hypervisor_tpu.models import SessionState
from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.ops.pipeline import governance_wave
from hypervisor_tpu.parallel import make_mesh, make_multislice_mesh
from hypervisor_tpu.parallel.collectives import (
    multislice_reconcile_wave,
    sharded_governance_wave,
)
from hypervisor_tpu.tables.state import AgentTable, SessionTable, VouchTable
from hypervisor_tpu.tables.struct import replace as t_replace

pytestmark = pytest.mark.skipif(
    os.environ.get("HV_SOAK") != "1",
    reason="shape-fuzz soak is opt-in (HV_SOAK=1): each draw compiles "
    "its own programs",
)

D = 8
ROWS = 16
T = 2


def _world(rng, b, k, s_cap):
    agents = AgentTable.create(ROWS * D)
    sessions = SessionTable.create(s_cap)
    ws = jnp.arange(k)
    sessions = t_replace(
        sessions,
        state=sessions.state.at[ws].set(
            jnp.int8(SessionState.HANDSHAKING.code)
        ),
        max_participants=sessions.max_participants.at[ws].set(
            int(rng.integers(2, 8))
        ),
        min_sigma_eff=sessions.min_sigma_eff.at[ws].set(0.3),
    )
    vouches = VouchTable.create(4 * D)
    # A few random active edges vouching for wave joiners.
    n_edges = int(rng.integers(0, 4))
    for e in range(n_edges):
        vouches = t_replace(
            vouches,
            voucher=vouches.voucher.at[e].set(int(rng.integers(0, ROWS * D))),
            vouchee=vouches.vouchee.at[e].set(
                int(rng.integers(0, b)) * (ROWS * D // max(b, 1))
                % (ROWS * D)
            ),
            session=vouches.session.at[e].set(int(rng.integers(0, k))),
            bond=vouches.bond.at[e].set(float(rng.uniform(0.05, 0.4))),
            active=vouches.active.at[e].set(True),
            expiry=vouches.expiry.at[e].set(1e9),
        )
    return agents, sessions, vouches


def _draw(rng):
    """One random wave geometry honoring the shard contracts."""
    per_shard = int(rng.integers(1, 4))         # joins per shard
    b = per_shard * D
    k = b                                        # unique: 1 session/join
    s_cap = 1 << int(np.ceil(np.log2(max(2 * k, 4))))
    slots = np.array(
        [(i // per_shard) * ROWS + (i % per_shard) for i in range(b)],
        np.int32,
    )
    sigma = rng.uniform(0.2, 1.0, b).astype(np.float32)
    trust = rng.random(b) > 0.1
    dup = rng.random(b) < 0.2                    # ragged padding lanes
    bodies = rng.integers(
        0, 2**32, size=(T, k, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    return b, k, s_cap, slots, sigma, trust, dup, bodies


def test_random_shapes_sharded_and_multislice_match_single_device():
    iters = int(os.environ.get("HV_SOAK_ITERS", "6"))
    rng = np.random.default_rng(int(os.environ.get("HV_SOAK_SEED", "7")))
    mesh1 = make_mesh(D, platform="cpu")
    # platform="cpu": hermetic like mesh1 — the soak must never
    # initialize the default backend (a real-accelerator tunnel under
    # HV_TPU_TESTS=1).
    mesh2 = make_multislice_mesh(2, D // 2, platform="cpu")

    for it in range(iters):
        b, k, s_cap, slots, sigma, trust, dup, bodies = _draw(rng)
        args = (
            jnp.asarray(slots),
            jnp.arange(b, dtype=jnp.int32),
            jnp.arange(b, dtype=jnp.int32),
            jnp.asarray(sigma),
            jnp.asarray(trust),
            jnp.asarray(dup),
            jnp.asarray(np.arange(k, dtype=np.int32)),
            jnp.asarray(bodies),
            float(it + 1),
            0.5,
        )
        wave_range = (jnp.asarray(0, jnp.int32), jnp.asarray(k, jnp.int32))

        agents0, sessions0, vouches0 = _world(
            np.random.default_rng(1000 + it), b, k, s_cap
        )
        single = jax.jit(
            governance_wave,
            static_argnames=("use_pallas", "unique_sessions"),
        )(
            agents0, sessions0, vouches0, *args,
            use_pallas=False, wave_range=wave_range, unique_sessions=True,
        )

        agents1, sessions1, vouches1 = _world(
            np.random.default_rng(1000 + it), b, k, s_cap
        )
        shard = sharded_governance_wave(
            mesh1, contiguous_waves=True, unique_sessions=True
        )(agents1, sessions1, vouches1, *args, *wave_range)

        agents2, sessions2, vouches2 = _world(
            np.random.default_rng(1000 + it), b, k, s_cap
        )
        ms_res, ms_part = sharded_governance_wave(
            mesh2, mode_dispatch=True, contiguous_waves=True,
            unique_sessions=True, multislice=True,
        )(agents2, sessions2, vouches2, *args, *wave_range)
        folded = multislice_reconcile_wave(mesh2)(
            ms_res.sessions, ms_part.counts, ms_part.owned,
            ms_part.state, ms_part.terminated,
        )

        for name in ("status", "ring", "sigma_eff", "merkle_root",
                     "chain", "fsm_error"):
            np.testing.assert_array_equal(
                np.asarray(getattr(shard, name)),
                np.asarray(getattr(single, name)),
                err_msg=f"[{it}] sharded {name} (b={b}, k={k})",
            )
            np.testing.assert_array_equal(
                np.asarray(getattr(ms_res, name)),
                np.asarray(getattr(single, name)),
                err_msg=f"[{it}] multislice {name} (b={b}, k={k})",
            )
        for col in ("state", "n_participants", "terminated_at"):
            np.testing.assert_array_equal(
                np.asarray(getattr(shard.sessions, col)),
                np.asarray(getattr(single.sessions, col)),
                err_msg=f"[{it}] sharded sessions.{col}",
            )
            np.testing.assert_array_equal(
                np.asarray(getattr(folded, col)),
                np.asarray(getattr(single.sessions, col)),
                err_msg=f"[{it}] multislice sessions.{col}",
            )
        np.testing.assert_array_equal(
            np.asarray(shard.agents.i32), np.asarray(single.agents.i32),
            err_msg=f"[{it}] sharded agents.i32",
        )
        print(f"draw {it}: b={b} k={k} dup={int(dup.sum())} OK", flush=True)
