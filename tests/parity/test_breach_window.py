"""Device sliding-window breach model ≡ host detector, across sweeps.

The round-4 device plane kept tumbling counters that a security sweep
reset, diverging from the host detector's sliding window whenever a
sweep fired mid-window (VERDICT r4 weak #5). The bucketed sliding
window (`tables.state.BD_BUCKETS` sub-windows rolled by absolute epoch
stamps, `ops.security_ops`) removes that regime: sweeps never touch
window state, expiry is timestamp math. These tests pin

  * the headline criterion: a sweep fires MID-WINDOW and both planes
    still agree exactly on the anomaly analysis afterwards,
  * sliding expiry: calls leave the device window after window_seconds
    without any sweep,
  * bucket-wrap correctness: a bucket reused K epochs later evicts the
    stale counts first,
  * the precision contract: host and device agree exactly while every
    call's age stays clear of the oldest partial sub-window,
  * checkpoint migration: legacy width-5 agents.i32 blocks restore.

Host semantics anchor: reference `rings/breach_detector.py:120-186`
(60 s sliding window, severity ladder on the privileged-call rate).
"""

from __future__ import annotations

from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from hypervisor_tpu.config import DEFAULT_CONFIG
from hypervisor_tpu.models import ExecutionRing, SessionConfig
from hypervisor_tpu.ops import security_ops
from hypervisor_tpu.rings.breach_detector import RingBreachDetector
from hypervisor_tpu.state import HypervisorState
from hypervisor_tpu.tables.state import BD_BUCKETS, FLAG_BREAKER_TRIPPED

CFG = DEFAULT_CONFIG.breach
SUB = CFG.window_seconds / BD_BUCKETS
EPOCH0 = datetime(2026, 1, 1, tzinfo=timezone.utc)


class FakeClock:
    """Host-detector clock pinned to the device plane's relative time."""

    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> datetime:
        return EPOCH0 + timedelta(seconds=self.t)


def _admitted_state(n: int = 2, sigma: float = 0.8) -> HypervisorState:
    st = HypervisorState()
    slot = st.create_session("s:bw", SessionConfig(max_participants=32))
    for i in range(n):
        st.enqueue_join(slot, f"did:bw{i}", sigma)
    assert (st.flush_joins() == 0).all()
    return st


def _totals(st: HypervisorState, now: float) -> tuple[np.ndarray, np.ndarray]:
    calls, priv = security_ops.window_totals(
        st.agents.bd_window, now, st.config.breach
    )
    return np.asarray(calls), np.asarray(priv)


class TestSweepMidWindow:
    def test_sweep_mid_window_both_planes_agree(self):
        """THE r4 divergence regime: record → sweep mid-window → record
        → analyze. The old tumbling model forgot the pre-sweep calls;
        the sliding window must keep them, matching the host detector
        call for call."""
        st = _admitted_state()
        clock = FakeClock()
        host = RingBreachDetector(clock=clock)

        # 4 privileged probes at t=1 (ring-2 agent calling ring 0).
        clock.t = 1.0
        st.record_calls([0] * 4, [0] * 4, now=1.0)
        host_events = [
            host.record_call(
                "did:bw0", "s:bw", ExecutionRing.RING_2_STANDARD,
                ExecutionRing.RING_0_ROOT,
            )
            for _ in range(4)
        ]
        assert all(e is None for e in host_events)  # < min_calls (5)

        # A sweep fires MID-WINDOW. Old model: counters reset to 0 here.
        severity, tripped = st.breach_sweep_tick(now=2.0)
        assert int(severity[0]) == 0 and not tripped[0]  # < min_calls
        calls, priv = _totals(st, 2.0)
        assert int(calls[0]) == 4 and int(priv[0]) == 4  # window SURVIVED

        # 2 more probes at t=3: analysis must see 6/6 privileged — the
        # host trips CRITICAL at call 5; the device sweep agrees.
        clock.t = 3.0
        st.record_calls([0] * 2, [0] * 2, now=3.0)
        ev5 = host.record_call(
            "did:bw0", "s:bw", ExecutionRing.RING_2_STANDARD,
            ExecutionRing.RING_0_ROOT,
        )
        assert ev5 is not None and ev5.actual_rate == 1.0
        assert host.is_breaker_tripped("did:bw0", "s:bw")

        severity, tripped = st.breach_sweep_tick(now=3.0)
        assert int(severity[0]) == 4 and bool(tripped[0])  # CRITICAL
        calls, priv = _totals(st, 3.0)
        assert int(calls[0]) == 6 and int(priv[0]) == 6
        assert int(np.asarray(st.agents.flags)[0]) & FLAG_BREAKER_TRIPPED

    def test_agreement_through_many_sweeps(self):
        """Rate parity host-vs-device after every record wave, with a
        sweep between each wave — mixed privileged/clean traffic."""
        st = _admitted_state()
        clock = FakeClock()
        host = RingBreachDetector(clock=clock)
        pattern = [1, 0, 1, 1, 0, 1, 1, 1, 0, 1]  # 1 = privileged probe

        anom = total = 0
        for k, p in enumerate(pattern):
            t = 1.0 + k  # all well inside one window
            clock.t = t
            st.record_calls([0], [0 if p else 2], now=t)
            host.record_call(
                "did:bw0", "s:bw", ExecutionRing.RING_2_STANDARD,
                ExecutionRing.RING_0_ROOT if p
                else ExecutionRing.RING_2_STANDARD,
            )
            total += 1
            anom += p
            st.breach_sweep_tick(now=t)  # a sweep after EVERY wave
            calls, priv = _totals(st, t)
            assert int(calls[0]) == total
            assert int(priv[0]) == anom
            hs = host.get_agent_stats("did:bw0", "s:bw")
            assert hs["window_calls"] == total


class TestSlidingExpiry:
    def test_calls_expire_without_any_sweep(self):
        st = _admitted_state()
        st.record_calls([0] * 6, [0] * 6, now=5.0)
        calls, priv = _totals(st, 5.0)
        assert int(calls[0]) == 6 and int(priv[0]) == 6
        # Still in-window just before expiry...
        calls, _ = _totals(st, 5.0 + CFG.window_seconds - SUB - 1.0)
        assert int(calls[0]) == 6
        # ...gone after the window has slid past (no sweep ever ran).
        calls, priv = _totals(st, 5.0 + CFG.window_seconds + SUB)
        assert int(calls[0]) == 0 and int(priv[0]) == 0

    def test_expired_window_does_not_trip(self):
        st = _admitted_state()
        st.record_calls([0] * 8, [0] * 8, now=1.0)
        late = 1.0 + 2 * CFG.window_seconds
        severity, tripped = st.breach_sweep_tick(now=late)
        assert int(severity[0]) == 0 and not tripped[0]

    def test_partial_expiry_slides_not_tumbles(self):
        """Calls in two different sub-windows expire independently."""
        st = _admitted_state()
        st.record_calls([0] * 4, [0] * 4, now=0.5 * SUB)       # bucket e0
        st.record_calls([0] * 3, [2] * 3, now=3.5 * SUB)       # bucket e3
        t1 = 0.5 * SUB + CFG.window_seconds + SUB  # first batch aged out
        calls, priv = _totals(st, t1)
        assert int(calls[0]) == 3 and int(priv[0]) == 0
        t2 = 3.5 * SUB + CFG.window_seconds + SUB  # second batch too
        calls, _ = _totals(st, t2)
        assert int(calls[0]) == 0

    def test_bucket_wrap_evicts_stale_counts(self):
        """A write K epochs later reuses the same bucket index and must
        evict the stale counts, not accumulate into them."""
        st = _admitted_state()
        t0 = 2.5 * SUB
        st.record_calls([0] * 5, [0] * 5, now=t0)
        t1 = t0 + BD_BUCKETS * SUB  # same bucket index, next wrap
        st.record_calls([0] * 2, [2] * 2, now=t1)
        calls, priv = _totals(st, t1)
        assert int(calls[0]) == 2 and int(priv[0]) == 0

    def test_idle_agent_releases_after_cooldown_despite_inwindow_calls(self):
        """Reference: analysis only runs on record_call, so an agent
        idle since its breaker released stays released even while the
        old anomalous calls are technically still in-window
        (`breach_detector.py:123-127` suppression + lazy release)."""
        st = _admitted_state()
        st.record_calls([0] * 6, [0] * 6, now=0.0)
        _, tripped = st.breach_sweep_tick(now=0.0)
        assert tripped[0]
        cooldown = CFG.circuit_breaker_cooldown_seconds
        # Past cooldown, still inside the 60 s window: the calls are
        # in-window but predate the release → no re-analysis, released.
        st.breach_sweep_tick(now=cooldown + 1.0)
        assert not (
            int(np.asarray(st.agents.flags)[0]) & FLAG_BREAKER_TRIPPED
        )

    def test_fresh_probes_after_release_retrip(self):
        """New probes after release re-arm analysis (reference: the next
        record_call after cooldown re-runs the ladder on the window)."""
        st = _admitted_state()
        st.record_calls([0] * 6, [0] * 6, now=0.0)
        _, tripped = st.breach_sweep_tick(now=0.0)
        assert tripped[0]
        cooldown = CFG.circuit_breaker_cooldown_seconds
        # Fresh probes land AFTER the release instant, in a sub-window
        # starting at/after it (cooldown=30 is sub-window aligned).
        t = cooldown + SUB
        st.record_calls([0] * 2, [0] * 2, now=t)
        severity, tripped = st.breach_sweep_tick(now=t)
        assert bool(tripped[0]) and int(severity[0]) == 4


class TestWindowProperty:
    """Random call schedules: both planes match their own oracle
    exactly, and their divergence is the documented bound.

    The precision contract (`ops/security_ops.py` module docstring):
    the device window at `now` covers bucket epochs in
    (cur - K, cur], i.e. wall-clock (now - W + sub - now%sub, now] —
    the host window [now - W, now] shortened at the OLD edge by up to
    one sub-window. So for every schedule:

      * device totals == the epoch-rule oracle, exactly, always
        (including expiry and bucket-index wraps),
      * host window count == the age-rule oracle, exactly, always,
      * host - device == the calls inside the oldest partial band —
        never negative (device ⊆ host), never more than one
        sub-window's worth, and ZERO whenever the band is empty.
    """

    def test_random_schedules_match_oracles_and_bound(self):
        hypothesis = pytest.importorskip("hypothesis")
        given, settings = hypothesis.given, hypothesis.settings
        hst = hypothesis.strategies

        events = hst.lists(
            hst.tuples(
                hst.integers(min_value=0, max_value=3 * BD_BUCKETS),  # gap
                hst.booleans(),  # privileged?
            ),
            min_size=1,
            max_size=25,
        )
        k = BD_BUCKETS
        w = CFG.window_seconds

        @settings(max_examples=40, deadline=None)
        @given(events=events)
        def run(events):
            st = _admitted_state(n=1)
            clock = FakeClock()
            host = RingBreachDetector(clock=clock)
            calls: list[tuple[float, int, bool]] = []  # (ts, epoch, priv)
            t_units = 0
            for gap, privileged in events:
                t_units += gap
                ts = (t_units + 0.5) * SUB
                clock.t = ts
                st.record_calls([0], [0 if privileged else 2], now=ts)
                host.record_call(
                    "did:bw0", "s:bw", ExecutionRing.RING_2_STANDARD,
                    ExecutionRing.RING_0_ROOT if privileged
                    else ExecutionRing.RING_2_STANDARD,
                )
                calls.append((ts, t_units, privileged))

                a = (t_units + 1) * SUB  # analysis on the sub grid
                cur = t_units + 1
                clock.t = a
                dev_calls, dev_priv = _totals(st, a)
                dev_oracle = [
                    (ts_j, p_j) for ts_j, e_j, p_j in calls if e_j > cur - k
                ]
                host_oracle = [
                    (ts_j, p_j) for ts_j, e_j, p_j in calls if a - ts_j <= w
                ]
                band = [
                    ts_j
                    for ts_j, e_j, p_j in calls
                    if a - ts_j <= w and not e_j > cur - k
                ]
                # Device == its oracle, exactly.
                assert int(dev_calls[0]) == len(dev_oracle), (events, a)
                assert int(dev_priv[0]) == sum(p for _, p in dev_oracle)
                # Host == its oracle, exactly.
                hs = host.get_agent_stats("did:bw0", "s:bw")
                assert hs["window_calls"] == len(host_oracle), (events, a)
                # The divergence IS the oldest-partial-band content:
                # never negative, gone whenever the band is empty, and
                # every band call's age is within one sub-window of W.
                diff = len(host_oracle) - len(dev_oracle)
                assert diff == len(band) >= 0, (events, a)
                for ts_j in band:
                    assert w - SUB < a - ts_j <= w, (events, a, ts_j)

        run()


class TestCheckpointMigration:
    def test_legacy_width5_i32_block_restores(self, tmp_path):
        """A checkpoint whose agents.i32 still carries the r4 tumbling
        counters (width 5) restores: identity columns survive, the
        transient breach window starts fresh."""
        from hypervisor_tpu.runtime import checkpoint as ckpt

        st = _admitted_state()
        st.record_calls([0] * 6, [0] * 6, now=1.0)
        target = ckpt.save_state(st, tmp_path, step=1)

        # Rewrite the save in the round-4 layout: i32 narrowed to 5 with
        # tumbling counters in cols 3-4 (no window columns).
        data = dict(np.load(target / "tables.npz"))
        i32 = data.pop("agents.i32")
        n = i32.shape[0]
        bdw = i32[:, 3:]  # the live window slice
        legacy = np.zeros((n, 5), np.int32)
        legacy[:, :3] = i32[:, :3]
        legacy[:, 3] = bdw[:, :BD_BUCKETS].sum(1)
        legacy[:, 4] = bdw[:, BD_BUCKETS : 2 * BD_BUCKETS].sum(1)
        data["agents.i32"] = legacy
        np.savez(target / "tables.npz", **data)

        restored = ckpt.restore_state(target)
        np.testing.assert_array_equal(
            np.asarray(restored.agents.did), np.asarray(st.agents.did)
        )
        np.testing.assert_array_equal(
            np.asarray(restored.agents.flags), np.asarray(st.agents.flags)
        )
        assert not np.asarray(restored.agents.bd_window).any()

    def test_midround5_separate_bd_window_restores(self, tmp_path):
        """An early-round-5 save (width-3 i32 + its own agents.bd_window
        array) folds the window back into the block losslessly."""
        from hypervisor_tpu.runtime import checkpoint as ckpt

        st = _admitted_state()
        st.record_calls([0] * 6, [1] * 6, now=2.0)
        target = ckpt.save_state(st, tmp_path, step=3)

        data = dict(np.load(target / "tables.npz"))
        i32 = data.pop("agents.i32")
        data["agents.i32"] = i32[:, :3]
        data["agents.bd_window"] = i32[:, 3:]
        np.savez(target / "tables.npz", **data)

        restored = ckpt.restore_state(target)
        np.testing.assert_array_equal(
            np.asarray(restored.agents.i32), np.asarray(st.agents.i32)
        )
        calls, priv = _totals(restored, 2.0)
        assert int(calls[0]) == 6 and int(priv[0]) == 6

    def test_legacy_session_i8_block_restores(self, tmp_path):
        """A checkpoint from before the SessionTable state/mode merge
        (separate i8[S,2] block, width-3 i32) restores losslessly."""
        from hypervisor_tpu.runtime import checkpoint as ckpt
        from hypervisor_tpu.tables.state import SI32_MODE, SI32_STATE

        st = _admitted_state()
        target = ckpt.save_state(st, tmp_path, step=2)

        data = dict(np.load(target / "tables.npz"))
        i32 = data["sessions.i32"]
        assert i32.shape[1] == 5
        data["sessions.i8"] = np.stack(
            [i32[:, SI32_STATE], i32[:, SI32_MODE]], axis=1
        ).astype(np.int8)
        data["sessions.i32"] = i32[:, :3]
        np.savez(target / "tables.npz", **data)

        restored = ckpt.restore_state(target)
        np.testing.assert_array_equal(
            np.asarray(restored.sessions.state),
            np.asarray(st.sessions.state),
        )
        np.testing.assert_array_equal(
            np.asarray(restored.sessions.mode), np.asarray(st.sessions.mode)
        )
        np.testing.assert_array_equal(
            np.asarray(restored.sessions.n_participants),
            np.asarray(st.sessions.n_participants),
        )
