"""The fused governance pipeline vs the host facade, plus multi-chip tests."""

import hashlib
import struct

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.ops import pipeline as pipe
from hypervisor_tpu.parallel import make_mesh, strong_tick, eventual_tick, reconcile


def run_pipeline(s=8, t=3, sigma=0.8, trustworthy=True):
    rng = np.random.RandomState(0)
    bodies = rng.randint(
        0, 2**32, size=(t, s, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    return pipe.governance_pipeline(
        jnp.full((s,), sigma, jnp.float32),
        jnp.full((s,), trustworthy, bool),
        jnp.full((s,), 0.60, jnp.float32),
        jnp.asarray(bodies),
        jnp.ones((s,), bool),
    ), bodies


class TestPipelineSemantics:
    def test_happy_path(self):
        result, bodies = run_pipeline()
        assert np.all(np.asarray(result.status) == pipe.PIPE_OK)
        assert np.all(np.asarray(result.ring) == 2)  # sigma 0.8 -> Ring 2
        assert np.all(np.asarray(result.session_state) == pipe.S_ARCHIVED)
        assert np.all(np.asarray(result.saga_step_state) == 2)  # COMMITTED
        # consensus: [n_ok, sum sigma, ring mass, checksum]
        c = np.asarray(result.consensus)
        assert c[0] == 8 and abs(c[1] - 8 * 0.8) < 1e-3

    def test_untrustworthy_sandboxed(self):
        result, _ = run_pipeline(trustworthy=False)
        assert np.all(np.asarray(result.ring) == 3)
        # sandbox agents are exempt from the sigma floor -> still OK
        assert np.all(np.asarray(result.status) == pipe.PIPE_OK)

    def test_sigma_below_min_rejected(self):
        # sigma 0.7 -> ring 2, but session floor 0.75 -> rejected
        s = 4
        bodies = np.zeros((3, s, merkle_ops.BODY_WORDS), np.uint32)
        result = pipe.governance_pipeline(
            jnp.full((s,), 0.7, jnp.float32),
            jnp.ones((s,), bool),
            jnp.full((s,), 0.75, jnp.float32),
            jnp.asarray(bodies),
            jnp.ones((s,), bool),
        )
        assert np.all(np.asarray(result.status) == pipe.PIPE_SIGMA_BELOW_MIN)
        assert np.all(np.asarray(result.session_state) == pipe.S_CREATED)

    def test_merkle_root_matches_hashlib(self):
        result, bodies = run_pipeline(s=2, t=3)
        # Recompute lane 0 root by hand: chain then 3-leaf tree with
        # hex-pair combine and odd duplication.
        parent = b"\x00" * 32
        hexes = []
        for turn in range(3):
            msg = b"".join(struct.pack(">I", x) for x in bodies[turn, 0]) + parent
            parent = hashlib.sha256(msg).digest()
            hexes.append(parent.hex())
        l01 = hashlib.sha256((hexes[0] + hexes[1]).encode()).hexdigest()
        l22 = hashlib.sha256((hexes[2] + hexes[2]).encode()).hexdigest()
        want = hashlib.sha256((l01 + l22).encode()).hexdigest()
        got = "".join(f"{int(w):08x}" for w in np.asarray(result.merkle_root)[0])
        assert got == want


class TestMultiChip:
    def test_strong_tick_on_8_device_mesh(self):
        assert jax.device_count() >= 8, "conftest must force 8 CPU devices"
        mesh = make_mesh(8)
        tick = strong_tick(mesh)
        s, t = 64, 3
        rng = np.random.RandomState(1)
        bodies = rng.randint(
            0, 2**32, size=(t, s, merkle_ops.BODY_WORDS), dtype=np.uint64
        ).astype(np.uint32)
        result = tick(
            jnp.full((s,), 0.8, jnp.float32),
            jnp.ones((s,), bool),
            jnp.full((s,), 0.60, jnp.float32),
            jnp.asarray(bodies),
            jnp.ones((s,), bool),
        )
        # psum'd consensus identical to single-device run
        single = pipe.governance_pipeline(
            jnp.full((s,), 0.8, jnp.float32),
            jnp.ones((s,), bool),
            jnp.full((s,), 0.60, jnp.float32),
            jnp.asarray(bodies),
            jnp.ones((s,), bool),
        )
        np.testing.assert_allclose(
            np.asarray(result.consensus), np.asarray(single.consensus), rtol=1e-6
        )
        # per-lane outputs identical too
        np.testing.assert_array_equal(
            np.asarray(result.merkle_root), np.asarray(single.merkle_root)
        )

    def test_eventual_then_reconcile_equals_strong(self):
        mesh = make_mesh(8)
        s, t = 32, 3
        bodies = np.zeros((t, s, merkle_ops.BODY_WORDS), np.uint32)
        args = (
            jnp.full((s,), 0.8, jnp.float32),
            jnp.ones((s,), bool),
            jnp.full((s,), 0.60, jnp.float32),
            jnp.asarray(bodies),
            jnp.ones((s,), bool),
        )
        strong = strong_tick(mesh)(*args)
        eventual = eventual_tick(mesh)(*args)
        # Partial per-shard aggregates reconcile to the strong consensus.
        rec = reconcile(mesh)(eventual.consensus.reshape(8, -1).reshape(-1))
        # consensus vector is 4 values per shard under eventual
        partials = np.asarray(eventual.consensus).reshape(8, 4)
        np.testing.assert_allclose(
            partials.sum(axis=0), np.asarray(strong.consensus), rtol=1e-6
        )
