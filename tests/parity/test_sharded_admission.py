"""Cross-shard STRONG-mode admission vs the host engine (VERDICT #5).

A session whose joining participants land on DIFFERENT shards of an
8-device mesh must admit exactly the agents the sequential host engine
admits: the seat budget, sigma floor, and vouched sigma_eff must be
computed globally (psum/all_gather over the mesh), not per shard.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from hypervisor_tpu.models import ExecutionRing, SessionConfig
from hypervisor_tpu.ops import admission
from hypervisor_tpu.parallel import make_mesh
from hypervisor_tpu.parallel.collectives import sharded_admission
from hypervisor_tpu.session import (
    SessionParticipantError,
    SharedSessionObject,
)
from hypervisor_tpu.tables.state import AgentTable, SessionTable, VouchTable
from hypervisor_tpu.tables.struct import replace as t_replace

N_DEV = 8
ROWS_PER_SHARD = 8
N_CAP = N_DEV * ROWS_PER_SHARD
E_CAP = N_DEV * 4
S_CAP = 8


def _mesh():
    return make_mesh(N_DEV, platform="cpu")


def _session_table(max_participants: int, min_sigma: float) -> SessionTable:
    t = SessionTable.create(S_CAP)
    return t_replace(
        t,
        state=t.state.at[0].set(1),  # HANDSHAKING
        max_participants=t.max_participants.at[0].set(max_participants),
        min_sigma_eff=t.min_sigma_eff.at[0].set(min_sigma),
    )


def _host_expected(sigmas, trusts, contribs, omega, capacity, min_sigma):
    """Drive the reference-parity host SSO in global wave order."""
    sso = SharedSessionObject(
        config=SessionConfig(
            max_participants=capacity, min_sigma_eff=min_sigma
        ),
        creator_did="did:creator",
    )
    sso.begin_handshake()
    statuses, rings = [], []
    for i, (s, tr, c) in enumerate(zip(sigmas, trusts, contribs)):
        sigma_eff = min(s + omega * c, 1.0)
        ring = ExecutionRing.from_sigma_eff(sigma_eff, has_consensus=False)
        if not tr:
            ring = ExecutionRing.RING_3_SANDBOX
        try:
            sso.join(f"did:{i}", sigma_raw=s, sigma_eff=sigma_eff, ring=ring)
            statuses.append(admission.ADMIT_OK)
        except SessionParticipantError as e:
            if "capacity" in str(e):
                statuses.append(admission.ADMIT_CAPACITY)
            else:
                statuses.append(admission.ADMIT_SIGMA_LOW)
        rings.append(ring.value)
    return np.array(statuses, np.int8), np.array(rings, np.int8)


class TestShardedAdmission:
    def _run(self, sigmas, trusts, capacity, min_sigma, vouch_rows=(), omega=0.5):
        mesh = _mesh()
        admit = sharded_admission(mesh)
        b = len(sigmas)
        assert b % N_DEV == 0
        b_local = b // N_DEV

        agents = AgentTable.create(N_CAP)
        sessions = _session_table(capacity, min_sigma)
        vouches = VouchTable.create(E_CAP)
        for row, (vouchee_slot, bond) in enumerate(vouch_rows):
            vouches = t_replace(
                vouches,
                voucher=vouches.voucher.at[row].set(N_CAP - 1),
                vouchee=vouches.vouchee.at[row].set(vouchee_slot),
                session=vouches.session.at[row].set(0),
                bond=vouches.bond.at[row].set(bond),
                active=vouches.active.at[row].set(True),
            )

        # Slot contract: element i lives on shard i // b_local; its agent
        # row must belong to that shard.
        slots = np.array(
            [
                (i // b_local) * ROWS_PER_SHARD + (i % b_local)
                for i in range(b)
            ],
            np.int32,
        )
        out = admit(
            agents,
            sessions,
            vouches,
            jnp.asarray(slots),
            jnp.arange(b, dtype=jnp.int32),
            jnp.zeros(b, jnp.int32),           # everyone joins session 0
            jnp.asarray(np.asarray(sigmas, np.float32)),
            jnp.asarray(np.asarray(trusts, bool)),
            jnp.zeros(b, bool),
            0.0,
            omega,
        )
        new_agents, new_sessions, status, ring, sigma_eff = out
        contribs = np.zeros(b, np.float32)
        for vouchee_slot, bond in vouch_rows:
            contribs[list(slots).index(vouchee_slot)] += bond
        want_status, want_ring = _host_expected(
            sigmas, trusts, contribs, omega, capacity, min_sigma
        )
        return (
            np.asarray(status),
            np.asarray(ring),
            np.asarray(sigma_eff),
            new_agents,
            new_sessions,
            want_status,
            want_ring,
        )

    def test_session_spanning_all_shards_respects_capacity(self):
        # 16 joiners across 8 shards, 5 seats: exactly the first 5 in
        # global wave order get in — same as the sequential host engine.
        sigmas = [0.8] * 16
        trusts = [True] * 16
        status, ring, sig, agents, sessions, want_status, want_ring = self._run(
            sigmas, trusts, capacity=5, min_sigma=0.6
        )
        np.testing.assert_array_equal(status, want_status)
        np.testing.assert_array_equal(ring, want_ring)
        assert int(np.asarray(sessions.n_participants)[0]) == 5

    def test_mixed_rejections_match_host_engine(self):
        # Low-sigma (rejected), untrustworthy (sandboxed, floor-exempt),
        # and normal joiners interleaved across shards.
        sigmas = [0.8, 0.4, 0.9, 0.3, 0.7, 0.95, 0.2, 0.8] * 2
        trusts = [True, True, True, False, True, True, True, True] * 2
        status, ring, sig, agents, sessions, want_status, want_ring = self._run(
            sigmas, trusts, capacity=16, min_sigma=0.6
        )
        np.testing.assert_array_equal(status, want_status)
        np.testing.assert_array_equal(ring, want_ring)

    def test_vouched_sigma_crosses_shards(self):
        # The vouchee sits on shard 3; its vouch edge lives in an edge
        # shard owned by a different device. The psum'd contribution must
        # still lift it over the floor.
        b = 16
        b_local = b // N_DEV
        sigmas = [0.8] * b
        lifted = 13  # wave position on shard 6
        sigmas[lifted] = 0.45
        slot_of_lifted = (lifted // b_local) * ROWS_PER_SHARD + (
            lifted % b_local
        )
        trusts = [True] * b
        status, ring, sig, agents, sessions, want_status, want_ring = self._run(
            sigmas,
            trusts,
            capacity=16,
            min_sigma=0.6,
            vouch_rows=[(slot_of_lifted, 0.40)],
            omega=0.5,
        )
        np.testing.assert_array_equal(status, want_status)
        assert status[lifted] == admission.ADMIT_OK
        assert sig[lifted] == pytest.approx(0.45 + 0.5 * 0.40)
        assert ring[lifted] == 2
        # Without the vouch the same agent lands in the sandbox ring
        # (sigma 0.45 -> Ring 3, floor-exempt) instead of Ring 2.
        status2, ring2, *_ = self._run(
            list(sigmas), trusts, capacity=16, min_sigma=0.6
        )
        assert status2[lifted] == admission.ADMIT_OK
        assert ring2[lifted] == 3

    def test_replicated_session_table_identical_on_all_shards(self):
        sigmas = [0.8] * 16
        trusts = [True] * 16
        *_, agents, sessions, _ws, _wr = self._run(
            sigmas, trusts, capacity=7, min_sigma=0.6
        )
        # The replicated table must hold ONE consistent value (a psum'd
        # actual delta), observable identically from host.
        assert int(np.asarray(sessions.n_participants)[0]) == 7
        # Admitted agents landed on their owning shards.
        dids = np.asarray(agents.did)
        assert (dids >= 0).sum() == 7

class TestEventualReconcile:
    def test_session_table_deltas_merge_across_shards(self):
        """EVENTUAL mode: shards tick locally, reconcile folds the ACTUAL
        per-session deltas (not a 4-float aggregate) into the replica."""
        from hypervisor_tpu.parallel.collectives import reconcile_sessions

        mesh = _mesh()
        merge = reconcile_sessions(mesh)
        sessions = _session_table(max_participants=64, min_sigma=0.0)

        # Each shard admitted a different number of agents into sessions
        # 0 and 1 during its local (EVENTUAL) ticks.
        count_deltas = np.zeros((N_DEV, S_CAP), np.int32)
        sigma_deltas = np.zeros((N_DEV, S_CAP), np.float32)
        for d in range(N_DEV):
            count_deltas[d, 0] = d % 3
            count_deltas[d, 1] = 1
            sigma_deltas[d, 0] = 0.1 * (d % 3)

        out_sessions, total_counts, total_sigma = merge(
            sessions, jnp.asarray(count_deltas), jnp.asarray(sigma_deltas)
        )
        want0 = sum(d % 3 for d in range(N_DEV))
        assert int(np.asarray(total_counts)[0]) == want0
        assert int(np.asarray(total_counts)[1]) == N_DEV
        assert int(np.asarray(out_sessions.n_participants)[0]) == want0
        assert int(np.asarray(out_sessions.n_participants)[1]) == N_DEV
        np.testing.assert_allclose(
            float(np.asarray(total_sigma)[0]),
            sum(0.1 * (d % 3) for d in range(N_DEV)),
            rtol=1e-6,
        )


class TestShardedChain:
    def test_pipelined_chain_matches_single_device(self):
        """A delta chain sharded over the TURN axis (sequence-parallel,
        ppermute carry ring) must produce bit-identical digests to the
        single-device lax.scan chain."""
        from hypervisor_tpu.ops import merkle as merkle_ops
        from hypervisor_tpu.parallel.collectives import sharded_chain

        mesh = _mesh()
        chain = sharded_chain(mesh)
        t_total, lanes = N_DEV * 4, 8
        rng = np.random.RandomState(0)
        bodies = rng.randint(
            0, 2**32, size=(t_total, lanes, merkle_ops.BODY_WORDS),
            dtype=np.uint64,
        ).astype(np.uint32)
        seed = rng.randint(
            0, 2**32, size=(lanes, 8), dtype=np.uint64
        ).astype(np.uint32)

        want = np.asarray(
            merkle_ops.chain_digests(jnp.asarray(bodies), jnp.asarray(seed))
        )
        got = np.asarray(chain(jnp.asarray(bodies), jnp.asarray(seed)))
        np.testing.assert_array_equal(got, want)

    def test_zero_seed_matches_too(self):
        from hypervisor_tpu.ops import merkle as merkle_ops
        from hypervisor_tpu.parallel.collectives import sharded_chain

        mesh = _mesh()
        chain = sharded_chain(mesh)
        t_total, lanes = N_DEV * 2, 4
        rng = np.random.RandomState(1)
        bodies = rng.randint(
            0, 2**32, size=(t_total, lanes, merkle_ops.BODY_WORDS),
            dtype=np.uint64,
        ).astype(np.uint32)
        seed = np.zeros((lanes, 8), np.uint32)
        want = np.asarray(merkle_ops.chain_digests(jnp.asarray(bodies)))
        got = np.asarray(chain(jnp.asarray(bodies), jnp.asarray(seed)))
        np.testing.assert_array_equal(got, want)


class TestMultisliceReconcile:
    def test_dcn_axis_folds_slice_deltas(self):
        """2-D (dcn, agents) mesh: per-device deltas reduce over ICI then
        DCN and fold into the replicated session table."""
        from hypervisor_tpu.parallel import make_multislice_mesh
        from hypervisor_tpu.parallel.collectives import multislice_reconcile

        n_slices, per_slice = 2, 4
        mesh = make_multislice_mesh(n_slices, per_slice)
        merge = multislice_reconcile(mesh)
        sessions = _session_table(max_participants=64, min_sigma=0.0)

        deltas = np.zeros((n_slices, per_slice, S_CAP), np.int32)
        for sl in range(n_slices):
            for d in range(per_slice):
                deltas[sl, d, 0] = sl + 1   # slice 0 adds 1/dev, slice 1 adds 2/dev
                deltas[sl, d, 2] = d % 2
        out_sessions, total = merge(sessions, jnp.asarray(deltas))
        want0 = per_slice * (1 + 2)
        want2 = n_slices * sum(d % 2 for d in range(per_slice))
        assert int(np.asarray(total)[0]) == want0
        assert int(np.asarray(total)[2]) == want2
        assert int(np.asarray(out_sessions.n_participants)[0]) == want0


class TestVouchedStrongTick:
    def test_contribution_lifts_rings_across_mesh(self):
        """strong_tick(with_vouching=True): bonded contributions lift
        vouched lanes over the ring threshold on every shard."""
        from hypervisor_tpu.ops import merkle as merkle_ops
        from hypervisor_tpu.parallel import strong_tick

        mesh = _mesh()
        tick = strong_tick(mesh, with_vouching=True)
        s, t = N_DEV * 4, 2
        rng = np.random.RandomState(0)
        bodies = rng.randint(
            0, 2**32, size=(t, s, merkle_ops.BODY_WORDS), dtype=np.uint64
        ).astype(np.uint32)
        sigma = np.full(s, 0.5, np.float32)
        contribution = np.zeros(s, np.float32)
        contribution[:: N_DEV] = 0.4  # one vouched lane per shard
        out = tick(
            jnp.asarray(sigma),
            jnp.ones(s, bool),
            jnp.zeros(s, jnp.float32),
            jnp.asarray(bodies),
            jnp.ones(s, bool),
            jnp.asarray(contribution),
        )
        rings = np.asarray(out.ring)
        sig = np.asarray(out.sigma_eff)
        assert (rings[:: N_DEV] == 2).all()          # lifted: 0.5+0.5*0.4=0.7
        assert (np.delete(rings, slice(None, None, N_DEV)) == 3).all()
        np.testing.assert_allclose(sig[:: N_DEV], 0.7, rtol=1e-6)
