"""Batched liability ops vs the host engines (same inputs, same outcomes)."""

import numpy as np
import jax.numpy as jnp
import pytest

from hypervisor_tpu.liability import SlashingEngine, VouchingEngine
from hypervisor_tpu.ops import liability as lops
from hypervisor_tpu.ops import rate_limit as rlops
from hypervisor_tpu.ops import clock_ops
from hypervisor_tpu.models import ExecutionRing
from hypervisor_tpu.security import AgentRateLimiter
from hypervisor_tpu.session.vector_clock import VectorClockManager, CausalViolationError
from hypervisor_tpu.utils.clock import ManualClock

S = "session:par"


def build_engine(edges):
    """edges: list of (voucher, vouchee, sigma, pct)."""
    eng = VouchingEngine()
    for voucher, vouchee, sigma, pct in edges:
        eng.vouch(voucher, vouchee, S, sigma, bond_pct=pct)
    return eng


class TestSigmaEffParity:
    def test_contribution_matches_host(self):
        eng = build_engine(
            [("h1", "l", 0.9, 0.2), ("h2", "l", 0.8, 0.3), ("h1", "m", 0.9, 0.1)]
        )
        table = eng.to_device(capacity=8)
        sess = eng.sessions.lookup(S)
        for vouchee, sigma in [("l", 0.4), ("m", 0.3), ("nobody", 0.5)]:
            slot = eng.agents.lookup(vouchee)
            batch = lops.voucher_contribution(
                table,
                jnp.asarray([max(slot, 0)], jnp.int32)
                if slot >= 0
                else jnp.asarray([99], jnp.int32),
                jnp.asarray([sess], jnp.int32),
                now=0.0,
            )
            host = eng.compute_sigma_eff(vouchee, S, sigma, risk_weight=1.0) - sigma
            assert float(batch[0]) == pytest.approx(host, abs=1e-6)

    def test_exposure_matches_host(self):
        eng = build_engine([("h", "a", 0.8, 0.3), ("h", "b", 0.8, 0.2)])
        table = eng.to_device(capacity=8)
        out = lops.exposure_by_voucher(
            table,
            jnp.asarray([eng.agents.lookup("h")], jnp.int32),
            jnp.asarray([eng.sessions.lookup(S)], jnp.int32),
            now=0.0,
        )
        assert float(out[0]) == pytest.approx(eng.get_total_exposure("h", S), abs=1e-6)


class TestSlashCascadeParity:
    def _run_both(self, edges, seed, sigma0, omega):
        """Run host SlashingEngine and device slash_cascade on the same graph."""
        host_eng = build_engine(edges)
        slasher = SlashingEngine(host_eng)
        scores = dict(sigma0)
        slasher.slash(seed, S, sigma0[seed], omega, "parity", scores)

        dev_eng = build_engine(edges)
        table = dev_eng.to_device(capacity=16)
        n = len(dev_eng.agents)
        sigma = np.zeros(n, np.float32)
        for name, v in sigma0.items():
            slot = dev_eng.agents.lookup(name)
            if slot >= 0:
                sigma[slot] = v
        seeds = np.zeros(n, bool)
        seeds[dev_eng.agents.lookup(seed)] = True
        result = lops.slash_cascade(
            table,
            jnp.asarray(sigma),
            jnp.asarray(seeds),
            dev_eng.sessions.lookup(S),
            omega,
            now=0.0,
        )
        dev_scores = {
            name: float(np.asarray(result.sigma)[dev_eng.agents.lookup(name)])
            for name in sigma0
        }
        return scores, dev_scores, result

    def test_simple_slash(self):
        host, dev, _ = self._run_both(
            [("h", "l", 0.9, 0.2)], "l", {"h": 0.9, "l": 0.4}, omega=0.5
        )
        for k in host:
            assert dev[k] == pytest.approx(host[k], abs=1e-6), k

    def test_cascade_depth_1(self):
        host, dev, result = self._run_both(
            [("g", "h", 0.9, 0.2), ("h", "l", 0.9, 0.2)],
            "l",
            {"g": 0.9, "h": 0.9, "l": 0.4},
            omega=0.99,
        )
        for k in host:
            assert dev[k] == pytest.approx(host[k], abs=1e-5), k
        assert int(np.asarray(result.slashed).sum()) >= 2

    def test_no_cascade_when_survives(self):
        host, dev, _ = self._run_both(
            [("g", "h", 0.9, 0.2), ("h", "l", 0.9, 0.2)],
            "l",
            {"g": 0.9, "h": 0.9, "l": 0.4},
            omega=0.5,
        )
        for k in host:
            assert dev[k] == pytest.approx(host[k], abs=1e-6), k

    def test_multi_vouchee_simultaneous_clip(self):
        # One voucher backing two seeds slashed in the same wave: the
        # (1-omega)^k formula must match sequential clipping.
        host_eng = build_engine([("h", "a", 0.9, 0.2), ("h", "b", 0.9, 0.2)])
        slasher = SlashingEngine(host_eng)
        scores = {"h": 0.9, "a": 0.4, "b": 0.4}
        slasher.slash("a", S, 0.4, 0.5, "x", scores)
        slasher.slash("b", S, 0.4, 0.5, "x", scores)

        dev_eng = build_engine([("h", "a", 0.9, 0.2), ("h", "b", 0.9, 0.2)])
        table = dev_eng.to_device(capacity=8)
        n = len(dev_eng.agents)
        sigma = np.zeros(n, np.float32)
        for name, v in {"h": 0.9, "a": 0.4, "b": 0.4}.items():
            sigma[dev_eng.agents.lookup(name)] = v
        seeds = np.zeros(n, bool)
        seeds[dev_eng.agents.lookup("a")] = True
        seeds[dev_eng.agents.lookup("b")] = True
        result = lops.slash_cascade(
            table, jnp.asarray(sigma), jnp.asarray(seeds),
            dev_eng.sessions.lookup(S), 0.5, now=0.0,
        )
        got = float(np.asarray(result.sigma)[dev_eng.agents.lookup("h")])
        assert got == pytest.approx(scores["h"], abs=1e-6)


class TestRateLimitParity:
    def test_batch_matches_scalar_buckets(self):
        clock = ManualClock()
        host = AgentRateLimiter(clock=clock)
        t0 = clock().timestamp()

        n = 4
        rings = np.array([0, 1, 2, 3], np.int8)
        tokens = np.asarray(
            [200.0, 100.0, 40.0, 10.0], np.float32
        )  # full buckets
        stamp = np.full(n, t0, np.float32)

        # Consume 12 sequentially; compare allowed counts per ring.
        batch_allowed = np.zeros(n, np.int32)
        tok, stp = jnp.asarray(tokens), jnp.asarray(stamp)
        for _ in range(12):
            decision = rlops.consume(tok, stp, jnp.asarray(rings), now=t0)
            tok, stp = decision.tokens, decision.stamp
            batch_allowed += np.asarray(decision.allowed)

        host_allowed = np.zeros(n, np.int32)
        for i, ring in enumerate(
            [ExecutionRing.RING_0_ROOT, ExecutionRing.RING_1_PRIVILEGED,
             ExecutionRing.RING_2_STANDARD, ExecutionRing.RING_3_SANDBOX]
        ):
            for _ in range(12):
                if host.try_check(f"a{i}", "s", ring):
                    host_allowed[i] += 1
        assert batch_allowed.tolist() == host_allowed.tolist()

    def test_refill_after_elapsed(self):
        decision = rlops.consume(
            jnp.asarray([0.0], jnp.float32),
            jnp.asarray([0.0], jnp.float32),
            jnp.asarray([3], jnp.int8),
            now=1.0,  # 1s at 5 rps -> 5 tokens
        )
        assert bool(decision.allowed[0])
        assert float(decision.tokens[0]) == pytest.approx(4.0)


class TestClockOpsParity:
    def test_write_prepass_matches_manager(self):
        mgr = VectorClockManager()
        mgr.write("/p0", "a0")          # a0 owns p0
        mgr.read("/p0", "a1")           # a1 catches up
        mgr.write("/p0", "a1")          # ok
        # a2 stale write -> conflict
        try:
            mgr.write("/p0", "a2")
        except CausalViolationError:
            pass
        assert mgr.conflict_count == 1

        # Device mirror of the same scenario.
        path_clocks = jnp.zeros((1, 3), jnp.int32)
        agent_clocks = jnp.zeros((3, 3), jnp.int32)
        # a0 writes p0
        out = clock_ops.batched_write_prepass(
            path_clocks, agent_clocks,
            jnp.asarray([0], jnp.int32), jnp.asarray([0], jnp.int32),
        )
        assert bool(out.allowed[0])
        # a1 reads (merge path into agent clock), then writes
        agent_clocks = out.agent_clocks.at[1].set(
            clock_ops.merge(out.agent_clocks[1], out.path_clocks[0])
        )
        out2 = clock_ops.batched_write_prepass(
            out.path_clocks, agent_clocks,
            jnp.asarray([0], jnp.int32), jnp.asarray([1], jnp.int32),
        )
        assert bool(out2.allowed[0])
        # a2 never read -> stale, rejected
        out3 = clock_ops.batched_write_prepass(
            out2.path_clocks, out2.agent_clocks,
            jnp.asarray([0], jnp.int32), jnp.asarray([2], jnp.int32),
        )
        assert not bool(out3.allowed[0])
        assert int(out3.conflicts) == 1
        # Final path clock matches the host manager's.
        host_clock = mgr.get_path_clock("/p0").clocks
        dev_clock = np.asarray(out3.path_clocks[0])
        assert dev_clock.tolist() == [host_clock.get("a0", 0), host_clock.get("a1", 0), 0]

    def test_happens_before_matrix(self):
        a = jnp.asarray([[1, 0], [1, 1], [2, 0]], jnp.int32)
        b = jnp.broadcast_to(jnp.asarray([1, 1], jnp.int32), (3, 2))
        hb = np.asarray(clock_ops.happens_before(a, b))
        assert hb.tolist() == [True, False, False]
        conc = np.asarray(clock_ops.is_concurrent(a, b))
        # Equal clocks count as concurrent (neither happens-before), matching
        # the reference's is_concurrent definition.
        assert conc.tolist() == [False, True, True]
