"""Cross-shard slash cascade vs the single-device op.

The liability graph's edge axis shards over an 8-device mesh; a slash
whose cascade crosses shard boundaries (a voucher's slashed vouchees'
edges on different chips; a wiped voucher whose own vouchers live on yet
another chip) must produce bit-identical results to
`ops.liability.slash_cascade` run on one device over the whole table.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from hypervisor_tpu.ops import liability as liability_ops
from hypervisor_tpu.parallel import make_mesh
from hypervisor_tpu.parallel.collectives import sharded_slash
from hypervisor_tpu.tables.state import VouchTable
from hypervisor_tpu.tables.struct import replace as t_replace

N_DEV = 8
EDGES_PER_SHARD = 4
E_CAP = N_DEV * EDGES_PER_SHARD   # 32 edge rows
N_AGENTS = 24
SESSION = 3


def _vouch_table(edges: list[tuple[int, int, float]]) -> VouchTable:
    """Edge list (voucher, vouchee, bond) -> padded VouchTable.

    Edges are deliberately scattered across shard blocks: edge i lives on
    shard i // EDGES_PER_SHARD, so related edges land on different chips.
    """
    t = VouchTable.create(E_CAP)
    rows = np.linspace(0, E_CAP - 1, num=len(edges), dtype=np.int32)
    voucher = np.array(t.voucher)
    vouchee = np.array(t.vouchee)
    session = np.array(t.session)
    bond = np.array(t.bond)
    active = np.array(t.active)
    expiry = np.array(t.expiry)
    for row, (a, b, bd) in zip(rows, edges):
        voucher[row], vouchee[row], session[row] = a, b, SESSION
        bond[row], active[row], expiry[row] = bd, True, 1e9
    return t_replace(
        t,
        voucher=jnp.asarray(voucher),
        vouchee=jnp.asarray(vouchee),
        session=jnp.asarray(session),
        bond=jnp.asarray(bond),
        active=jnp.asarray(active),
        expiry=jnp.asarray(expiry),
    )


def _run_both(edges, sigma_host, seeds_idx, omega):
    vouch = _vouch_table(edges)
    sigma = jnp.asarray(np.asarray(sigma_host, np.float32))
    seeds = jnp.zeros((N_AGENTS,), bool).at[jnp.asarray(seeds_idx)].set(True)

    single = liability_ops.slash_cascade(
        vouch, sigma, seeds, SESSION, omega, now=0.0
    )

    mesh = make_mesh(N_DEV, platform="cpu")
    sharded = sharded_slash(mesh)(vouch, sigma, seeds, SESSION, omega, 0.0)
    return single, sharded


def _assert_identical(single, sharded):
    np.testing.assert_array_equal(
        np.asarray(single.sigma), np.asarray(sharded.sigma)
    )
    np.testing.assert_array_equal(
        np.asarray(single.slashed), np.asarray(sharded.slashed)
    )
    np.testing.assert_array_equal(
        np.asarray(single.clipped), np.asarray(sharded.clipped)
    )
    np.testing.assert_array_equal(
        np.asarray(single.wave_of), np.asarray(sharded.wave_of)
    )
    np.testing.assert_array_equal(
        np.asarray(single.vouch.active), np.asarray(sharded.vouch.active)
    )


def test_voucher_with_vouchees_on_different_shards():
    # Agent 0 vouches for 1 and 2; those two edges land on different
    # shards (rows 0 and 31). Slashing both vouchees at once must clip
    # agent 0 with the GLOBAL k=2, not k=1 per shard.
    edges = [(0, 1, 0.2), (0, 2, 0.2)]
    sigma = np.full(N_AGENTS, 0.9, np.float32)
    single, sharded = _run_both(edges, sigma, [1, 2], omega=0.5)
    _assert_identical(single, sharded)
    # k=2: 0.9 * 0.5^2 = 0.225.
    assert np.asarray(sharded.sigma)[0] == pytest.approx(0.225)


def test_cascade_crosses_shards():
    # Chain: 10 vouches for 5 (edge on one shard); slashing 5 wipes 10
    # (high omega); 10's own voucher 20 sits on a different shard and
    # must be clipped in wave 1.
    edges = [(10, 5, 0.3), (20, 10, 0.3)]
    sigma = np.full(N_AGENTS, 0.9, np.float32)
    sigma[10] = 0.052  # one clip wipes 10 to the floor
    single, sharded = _run_both(edges, sigma, [5], omega=0.99)
    _assert_identical(single, sharded)
    out = np.asarray(sharded.sigma)
    assert np.asarray(sharded.slashed)[5]
    # 10 was wiped to the floor by the clip, then re-slashed to 0 as the
    # depth-1 cascade seed (reference `slashing.py:124-141`).
    assert out[10] == 0.0
    assert np.asarray(sharded.wave_of)[10] == 1     # cascaded at depth 1
    assert out[20] < 0.9                            # cross-shard clip

def test_random_graphs_match(seed=0):
    rng = np.random.RandomState(seed)
    for trial in range(4):
        n_edges = rng.randint(3, 16)
        edges = []
        seen = set()
        for _ in range(n_edges):
            a, b = rng.randint(0, N_AGENTS, 2)
            if a == b or (a, b) in seen or (b, a) in seen:
                continue
            seen.add((a, b))
            edges.append((int(a), int(b), float(rng.uniform(0.05, 0.4))))
        if not edges:
            continue
        sigma = rng.uniform(0.05, 1.0, N_AGENTS).astype(np.float32)
        seeds = rng.choice(N_AGENTS, size=rng.randint(1, 4), replace=False)
        omega = float(rng.uniform(0.3, 0.99))
        single, sharded = _run_both(edges, sigma, list(map(int, seeds)), omega)
        _assert_identical(single, sharded)
