"""Property-style invariant sweeps over the governance math.

The reference lists hypothesis as a dev dependency but ships no property
tests (SURVEY §4); these seeded random sweeps cover the same ground:
formula invariants that must hold for ANY input, checked across many
random draws rather than a few hand-picked examples.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax.numpy as jnp
import pytest

from hypervisor_tpu.config import DEFAULT_CONFIG
from hypervisor_tpu.models import ExecutionRing
from hypervisor_tpu.ops import liability as liab_ops
from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.ops import rings as ring_ops
from hypervisor_tpu.tables.state import VouchTable


@pytest.mark.parametrize("seed", range(5))
def test_vectorized_rings_match_scalar_enum_everywhere(seed):
    """compute_rings == ExecutionRing.from_sigma_eff for any sigma,
    including values straddling the thresholds."""
    rng = np.random.RandomState(seed)
    # Boundary probes sit clearly on one side of the threshold in BOTH
    # precisions: exactly-at-threshold f32 values tie differently under
    # f32 (device) vs f64 (host enum) comparison — an inherent float
    # artifact, not a semantics difference.
    sigma = np.concatenate(
        [
            rng.uniform(0, 1, 500).astype(np.float32),
            np.array([0.6000005, 0.5999995, 0.9500005,
                      0.9499995, 0.0, 1.0], np.float32),
        ]
    )
    for consensus in (False, True):
        got = np.asarray(ring_ops.compute_rings(jnp.asarray(sigma), consensus))
        want = np.array(
            [
                ExecutionRing.from_sigma_eff(float(s), has_consensus=consensus).value
                for s in sigma
            ],
            np.int8,
        )
        np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("seed", range(3))
def test_sigma_eff_always_capped_and_monotone(seed):
    """sigma_eff = min(sigma + omega*contribution, 1): in [sigma, 1],
    monotone in the contribution."""
    rng = np.random.RandomState(seed)
    sigma = jnp.asarray(rng.uniform(0, 1, 300).astype(np.float32))
    omega = jnp.asarray(rng.uniform(0, 1, 300).astype(np.float32))
    contrib = jnp.asarray(rng.uniform(0, 5, 300).astype(np.float32))
    eff = np.asarray(liab_ops.sigma_eff(sigma, omega, contrib))
    assert (eff <= 1.0 + 1e-6).all()
    assert (eff >= np.asarray(sigma) - 1e-6).all()
    more = np.asarray(liab_ops.sigma_eff(sigma, omega, contrib + 1.0))
    assert (more >= eff - 1e-6).all()


def _random_vouch_graph(rng, n_agents, n_edges):
    v = VouchTable.create(n_edges)
    return dataclasses.replace(
        v,
        voucher=jnp.asarray(rng.randint(0, n_agents, n_edges, dtype=np.int64), jnp.int32),
        vouchee=jnp.asarray(rng.randint(0, n_agents, n_edges, dtype=np.int64), jnp.int32),
        session=jnp.asarray(rng.randint(0, 3, n_edges, dtype=np.int64), jnp.int32),
        bond=jnp.asarray(rng.uniform(0.01, 0.3, n_edges).astype(np.float32)),
        active=jnp.asarray(rng.uniform(0, 1, n_edges) > 0.3),
        expiry=jnp.full((n_edges,), np.inf, jnp.float32),
    )


@pytest.mark.parametrize("seed", range(4))
def test_slash_cascade_invariants(seed):
    """For any random graph and seeds: sigma stays in [0, 1], every
    slashed agent ends at exactly 0, every surviving clipped agent
    respects the floor, and released bonds are exactly the in-session
    edges feeding slashed vouchees."""
    rng = np.random.RandomState(seed)
    n = 128
    vouch = _random_vouch_graph(rng, n, 512)
    sigma = jnp.asarray(rng.uniform(0.05, 1.0, n).astype(np.float32))
    seeds = jnp.asarray(rng.uniform(0, 1, n) > 0.9)
    trust = DEFAULT_CONFIG.trust

    out = liab_ops.slash_cascade(vouch, sigma, seeds, 1, 0.95, 0.0)
    s = np.asarray(out.sigma)
    slashed = np.asarray(out.slashed)
    clipped = np.asarray(out.clipped)

    assert (s >= -1e-7).all() and (s <= 1.0 + 1e-6).all()
    # A purely-slashed agent is blacklisted to exactly 0. One that ALSO
    # vouched for another slashed agent gets the clip floor afterwards —
    # the reference's sequential slash produces the same 0.05
    # (`slashing.py:89` then `:95-99` with sigma=0 input).
    assert (s[slashed & ~clipped] == 0.0).all()
    assert (s[slashed] <= trust.sigma_floor + 1e-6).all()
    survivors = clipped & ~slashed
    assert (s[survivors] >= trust.sigma_floor - 1e-6).all()
    # Released edges: active before, inactive after, and each fed a
    # slashed vouchee in the slashed session.
    before = np.asarray(vouch.active)
    after = np.asarray(out.vouch.active)
    released = before & ~after
    vee = np.asarray(vouch.vouchee)
    sess = np.asarray(vouch.session)
    assert (slashed[vee[released]]).all()
    assert (sess[released] == 1).all()
    # No edge became active out of nowhere.
    assert not (~before & after).any()


@pytest.mark.parametrize("seed", range(3))
def test_chain_verify_catches_any_single_bit_tamper(seed):
    """Flipping ANY single bit of any body must fail verification for
    that lane and leave the other lanes verified."""
    rng = np.random.RandomState(seed)
    t, lanes = 6, 4
    bodies = rng.randint(
        0, 2**32, size=(t, lanes, merkle_ops.BODY_WORDS), dtype=np.uint64
    ).astype(np.uint32)
    recorded = merkle_ops.chain_digests(jnp.asarray(bodies))
    counts = jnp.full((lanes,), t, jnp.int32)

    ok = np.asarray(
        merkle_ops.verify_chain_digests(jnp.asarray(bodies), recorded, counts)
    )
    assert ok.all()

    tampered = bodies.copy()
    turn = rng.randint(t)
    lane = rng.randint(lanes)
    word = rng.randint(merkle_ops.BODY_WORDS)
    bit = np.uint32(1 << rng.randint(32))
    tampered[turn, lane, word] ^= bit
    ok2 = np.asarray(
        merkle_ops.verify_chain_digests(jnp.asarray(tampered), recorded, counts)
    )
    assert not ok2[lane]
    mask = np.ones(lanes, bool)
    mask[lane] = False
    assert ok2[mask].all()


@pytest.mark.parametrize("seed", range(3))
def test_contribution_toward_equals_bruteforce(seed):
    """The segment-sum joint-liability contribution equals a per-edge
    Python brute force for any random graph and target map."""
    rng = np.random.RandomState(seed)
    n = 64
    vouch = _random_vouch_graph(rng, n, 256)
    target = rng.randint(-2, 3, n).astype(np.int32)  # incl. "not joining"
    got = np.asarray(
        liab_ops.contribution_toward(vouch, jnp.asarray(target), 0.0)
    )
    want = np.zeros(n, np.float32)
    for e in range(256):
        vee = int(np.asarray(vouch.vouchee)[e])
        if vee < 0 or not bool(np.asarray(vouch.active)[e]):
            continue
        if int(np.asarray(vouch.session)[e]) == int(target[vee]):
            want[vee] += float(np.asarray(vouch.bond)[e])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_packed_transition_bits_match_matrices_exhaustively():
    """The u32-bitmask legality tests equal the source boolean matrices
    for EVERY (from, to) pair — the session 5x5, saga 5x5, and the
    49-bit step 7x7 that spans two words (TPU has no u64)."""
    import jax.numpy as jnp

    from hypervisor_tpu.ops import saga_ops, session_fsm
    from hypervisor_tpu.saga.state_machine import (
        SAGA_TRANSITION_MATRIX,
        STEP_TRANSITION_MATRIX,
    )

    cases = (
        (session_fsm.session_transition_valid,
         session_fsm.SESSION_TRANSITION_MATRIX),
        (saga_ops.saga_transition_valid, SAGA_TRANSITION_MATRIX),
        (saga_ops.step_transition_valid, STEP_TRANSITION_MATRIX),
    )
    for fn, matrix in cases:
        n = matrix.shape[0]
        frm, to = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
        got = np.asarray(
            fn(jnp.asarray(frm.ravel(), jnp.int8),
               jnp.asarray(to.ravel(), jnp.int8))
        ).reshape(n, n)
        np.testing.assert_array_equal(got, matrix.astype(bool))
        # Out-of-range codes (corrupted/uninitialized rows) are ILLEGAL,
        # deterministically — not clamped onto an arbitrary entry, not
        # an undefined oversize shift.
        bad = np.array([n, 7, 100, -1, 127], np.int8)
        assert not np.asarray(
            fn(jnp.asarray(bad), jnp.zeros(bad.shape, jnp.int8))
        ).any()
        assert not np.asarray(
            fn(jnp.zeros(bad.shape, jnp.int8), jnp.asarray(bad))
        ).any()
