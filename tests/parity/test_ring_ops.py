"""Parity: batched ring ops vs the scalar facade vs reference semantics."""

import itertools

import numpy as np

from hypervisor_tpu.models import ActionDescriptor, ExecutionRing, ReversibilityLevel
from hypervisor_tpu.ops import rings as ring_ops
from hypervisor_tpu.rings import RingEnforcer


class TestComputeRings:
    def test_thresholds_match_reference(self):
        # Boundary semantics per reference models.py:34-42 (strict >).
        sigmas = np.array([0.0, 0.3, 0.60, 0.601, 0.95, 0.951, 1.0], np.float32)
        rings = np.asarray(ring_ops.compute_rings(sigmas, False))
        assert rings.tolist() == [3, 3, 3, 2, 2, 2, 2]
        rings_c = np.asarray(ring_ops.compute_rings(sigmas, True))
        assert rings_c.tolist() == [3, 3, 3, 2, 2, 1, 1]

    def test_scalar_enum_agrees_with_batch(self):
        # The device path compares in float32, the scalar path in float64
        # (reference-exact); they can only disagree inside the ~4e-8
        # representability window at a threshold, so sweep off-boundary.
        sigmas = [0.0, 0.1, 0.25, 0.4, 0.55, 0.59, 0.61, 0.7, 0.8, 0.9, 0.94, 0.96, 1.0]
        for sigma in sigmas:
            for consensus in (False, True):
                scalar = ExecutionRing.from_sigma_eff(sigma, consensus).value
                batch = int(
                    np.asarray(ring_ops.compute_rings(np.float32(sigma), consensus))
                )
                assert scalar == batch, (sigma, consensus)


class TestRingCheckParity:
    def test_batch_matches_scalar_facade(self):
        """Exhaustive sweep: the device op and the host scalar path agree."""
        enforcer = RingEnforcer()
        combos = list(
            itertools.product(
                range(4),                      # agent ring
                [True, False],                 # is_admin
                list(ReversibilityLevel),      # reversibility
                [True, False],                 # is_read_only
                [0.3, 0.7, 0.96],              # sigma
                [True, False],                 # consensus
                [True, False],                 # witness
            )
        )
        agent_rings, requireds, sigmas, cons, wits, scalar_codes = [], [], [], [], [], []
        for ar, admin, rev, ro, sigma, consensus, witness in combos:
            action = ActionDescriptor(
                action_id="a",
                name="a",
                execute_api="/x",
                reversibility=rev,
                is_read_only=ro,
                is_admin=admin,
            )
            result = enforcer.check(
                ExecutionRing(ar), action, sigma, consensus, witness
            )
            scalar_codes.append(
                enforcer._check_code(ar, action.required_ring.value, sigma, consensus, witness)
            )
            assert result.allowed == (scalar_codes[-1] == ring_ops.CHECK_OK)
            agent_rings.append(ar)
            requireds.append(action.required_ring.value)
            sigmas.append(sigma)
            cons.append(consensus)
            wits.append(witness)

        batch_codes = np.asarray(
            ring_ops.ring_check(
                np.array(agent_rings, np.int8),
                np.array(requireds, np.int8),
                np.array(sigmas, np.float32),
                np.array(cons),
                np.array(wits),
            )
        )
        assert batch_codes.tolist() == scalar_codes

    def test_should_demote_parity(self):
        enforcer = RingEnforcer()
        rings = np.array([1, 1, 2, 2, 3, 3], np.int8)
        sigmas = np.array([0.99, 0.5, 0.7, 0.3, 0.1, 0.9], np.float32)
        batch = np.asarray(ring_ops.should_demote(rings, sigmas))
        scalar = [
            enforcer.should_demote(ExecutionRing(int(r)), float(s))
            for r, s in zip(rings, sigmas)
        ]
        assert batch.tolist() == scalar
