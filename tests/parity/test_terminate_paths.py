"""release_session_scope: broadcast-compare path vs mask-gather path.

Small terminate waves (K <= _BROADCAST_K_MAX) test session membership
with a [E, K] broadcast compare instead of gathering from the [S_cap]
mask (docs/ROADMAP.md: the two edge/agent gathers measured ~0.19 ms of
the TPU wave p50). Both paths must release exactly the same bonds and
deactivate exactly the same participants.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from hypervisor_tpu.ops.terminate import _BROADCAST_K_MAX, release_session_scope
from hypervisor_tpu.tables.state import (
    AgentTable,
    FLAG_ACTIVE,
    VouchTable,
)
from hypervisor_tpu.tables.struct import replace as t_replace

N, E, S_CAP = 64, 48, 16


def _tables(rng):
    agents = AgentTable.create(N)
    n_live = 40
    agents = t_replace(
        agents,
        session=agents.session.at[:n_live].set(
            jnp.asarray(rng.randint(0, S_CAP, n_live), jnp.int32)
        ),
        flags=agents.flags.at[:n_live].set(FLAG_ACTIVE),
    )
    vouches = VouchTable.create(E)
    n_edges = 32
    vouches = t_replace(
        vouches,
        voucher=vouches.voucher.at[:n_edges].set(
            jnp.asarray(rng.randint(0, n_live, n_edges), jnp.int32)
        ),
        vouchee=vouches.vouchee.at[:n_edges].set(
            jnp.asarray(rng.randint(0, n_live, n_edges), jnp.int32)
        ),
        session=vouches.session.at[:n_edges].set(
            jnp.asarray(rng.randint(0, S_CAP, n_edges), jnp.int32)
        ),
        bond=vouches.bond.at[:n_edges].set(0.2),
        active=vouches.active.at[:n_edges].set(True),
    )
    return agents, vouches


@pytest.mark.parametrize("k", [1, 3, S_CAP])  # S_CAP < _BROADCAST_K_MAX
def test_broadcast_and_mask_paths_agree(k):
    rng = np.random.RandomState(11 + k)
    agents, vouches = _tables(rng)
    wave = jnp.asarray(
        rng.choice(S_CAP, size=k, replace=False).astype(np.int32)
    )
    in_wave = jnp.zeros((S_CAP,), bool).at[wave].set(True)

    a_mask, v_mask, rel_mask = release_session_scope(
        agents, vouches, in_wave, wave_sessions=None  # force gather path
    )
    assert k <= _BROADCAST_K_MAX
    a_bc, v_bc, rel_bc = release_session_scope(
        agents, vouches, in_wave, wave_sessions=wave  # broadcast path
    )

    np.testing.assert_array_equal(np.asarray(a_bc.flags), np.asarray(a_mask.flags))
    np.testing.assert_array_equal(
        np.asarray(v_bc.active), np.asarray(v_mask.active)
    )
    assert int(np.asarray(rel_bc)) == int(np.asarray(rel_mask))
    # Sanity: something actually released / deactivated in most draws.
    sess = np.asarray(vouches.session)[:32]
    expected = int(np.isin(sess, np.asarray(wave)).sum())
    assert int(np.asarray(rel_bc)) == expected


def test_free_rows_never_match_broadcast():
    # Free edge rows carry session == -1; the broadcast compare must not
    # release them (real slots are >= 0).
    agents, vouches = _tables(np.random.RandomState(0))
    wave = jnp.asarray(np.array([0], np.int32))
    in_wave = jnp.zeros((S_CAP,), bool).at[wave].set(True)
    _, v_out, _ = release_session_scope(
        agents, vouches, in_wave, wave_sessions=wave
    )
    # Rows beyond the populated 32 were inactive before and stay inactive.
    assert not np.asarray(v_out.active)[32:].any()
