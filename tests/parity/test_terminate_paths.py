"""release_session_scope: broadcast-compare path vs mask-gather path.

Small terminate waves (K <= _BROADCAST_K_MAX) test session membership
with a [E, K] broadcast compare instead of gathering from the [S_cap]
mask (docs/ROADMAP.md: the two edge/agent gathers measured ~0.19 ms of
the TPU wave p50). Both paths must release exactly the same bonds and
deactivate exactly the same participants.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from hypervisor_tpu.ops.terminate import _BROADCAST_K_MAX, release_session_scope
from hypervisor_tpu.tables.state import (
    AgentTable,
    FLAG_ACTIVE,
    VouchTable,
)
from hypervisor_tpu.tables.struct import replace as t_replace

N, E, S_CAP = 64, 48, 16


def _tables(rng):
    agents = AgentTable.create(N)
    n_live = 40
    agents = t_replace(
        agents,
        session=agents.session.at[:n_live].set(
            jnp.asarray(rng.randint(0, S_CAP, n_live), jnp.int32)
        ),
        flags=agents.flags.at[:n_live].set(FLAG_ACTIVE),
    )
    vouches = VouchTable.create(E)
    n_edges = 32
    vouches = t_replace(
        vouches,
        voucher=vouches.voucher.at[:n_edges].set(
            jnp.asarray(rng.randint(0, n_live, n_edges), jnp.int32)
        ),
        vouchee=vouches.vouchee.at[:n_edges].set(
            jnp.asarray(rng.randint(0, n_live, n_edges), jnp.int32)
        ),
        session=vouches.session.at[:n_edges].set(
            jnp.asarray(rng.randint(0, S_CAP, n_edges), jnp.int32)
        ),
        bond=vouches.bond.at[:n_edges].set(0.2),
        active=vouches.active.at[:n_edges].set(True),
    )
    return agents, vouches


@pytest.mark.parametrize("k", [1, 3, S_CAP])  # S_CAP < _BROADCAST_K_MAX
def test_broadcast_and_mask_paths_agree(k):
    rng = np.random.RandomState(11 + k)
    agents, vouches = _tables(rng)
    wave = jnp.asarray(
        rng.choice(S_CAP, size=k, replace=False).astype(np.int32)
    )
    in_wave = jnp.zeros((S_CAP,), bool).at[wave].set(True)

    a_mask, v_mask, rel_mask = release_session_scope(
        agents, vouches, in_wave, wave_sessions=None  # force gather path
    )
    assert k <= _BROADCAST_K_MAX
    a_bc, v_bc, rel_bc = release_session_scope(
        agents, vouches, in_wave, wave_sessions=wave  # broadcast path
    )

    np.testing.assert_array_equal(np.asarray(a_bc.flags), np.asarray(a_mask.flags))
    np.testing.assert_array_equal(
        np.asarray(v_bc.active), np.asarray(v_mask.active)
    )
    assert int(np.asarray(rel_bc)) == int(np.asarray(rel_mask))
    # Sanity: something actually released / deactivated in most draws.
    sess = np.asarray(vouches.session)[:32]
    expected = int(np.isin(sess, np.asarray(wave)).sum())
    assert int(np.asarray(rel_bc)) == expected


@pytest.mark.parametrize("lo,k", [(0, 1), (0, 5), (3, 9), (7, S_CAP - 7)])
def test_range_path_matches_mask_path(lo, k):
    # wave_range's contract: the wave IS the contiguous block [lo, lo+k).
    rng = np.random.RandomState(100 + lo * 31 + k)
    agents, vouches = _tables(rng)
    wave = jnp.asarray(np.arange(lo, lo + k, dtype=np.int32))
    in_wave = jnp.zeros((S_CAP,), bool).at[wave].set(True)

    a_mask, v_mask, rel_mask = release_session_scope(
        agents, vouches, in_wave, wave_sessions=None  # force gather path
    )
    a_rng, v_rng, rel_rng = release_session_scope(
        agents,
        vouches,
        None,  # the range path needs no mask at all
        wave_sessions=wave,
        wave_range=(jnp.asarray(lo, jnp.int32), jnp.asarray(lo + k, jnp.int32)),
    )

    np.testing.assert_array_equal(np.asarray(a_rng.flags), np.asarray(a_mask.flags))
    np.testing.assert_array_equal(
        np.asarray(v_rng.active), np.asarray(v_mask.active)
    )
    assert int(np.asarray(rel_rng)) == int(np.asarray(rel_mask))


def test_range_path_excludes_free_rows_at_lo_zero():
    # session == -1 (free/unattached rows) must not match even when
    # lo == 0 — the `session >= lo` guard is what excludes them. Plant
    # OBSERVABLE sentinels on both tables: an ACTIVE vouch edge and a
    # FLAG_ACTIVE agent row, each with session == -1.
    agents, vouches = _tables(np.random.RandomState(1))
    vouches = t_replace(
        vouches,
        session=vouches.session.at[40].set(-1),
        bond=vouches.bond.at[40].set(0.5),
        active=vouches.active.at[40].set(True),
    )
    agents = t_replace(
        agents,
        session=agents.session.at[N - 1].set(-1),
        flags=agents.flags.at[N - 1].set(FLAG_ACTIVE),
    )
    a_out, v_out, released = release_session_scope(
        agents,
        vouches,
        None,
        wave_range=(jnp.asarray(0, jnp.int32), jnp.asarray(S_CAP, jnp.int32)),
    )
    # The sentinel edge stays active; only the 32 real edges released.
    assert bool(np.asarray(v_out.active)[40])
    assert int(np.asarray(released)) == 32
    # The sentinel agent keeps FLAG_ACTIVE.
    assert int(np.asarray(a_out.flags)[N - 1]) & FLAG_ACTIVE


def test_terminate_batch_range_matches_mask():
    # The full terminate wave (root passthrough + bonds + FSM stamps)
    # with wave_range must equal the default path on a contiguous wave.
    # Roots arrive precomputed from the audit plane's frontier now
    # (ISSUE 7) — the wave passes them through untouched on both paths.
    from hypervisor_tpu.ops.terminate import terminate_batch
    from hypervisor_tpu.tables.state import SessionTable

    rng = np.random.RandomState(5)
    agents, vouches = _tables(rng)
    sessions = SessionTable.create(S_CAP)
    lo, k = 2, 6
    slots = jnp.asarray(np.arange(lo, lo + k, dtype=np.int32))
    roots = jnp.asarray(
        rng.randint(0, 2**32, size=(k, 8), dtype=np.uint64).astype(np.uint32)
    )

    plain = terminate_batch(
        agents, sessions, vouches, slots, roots, 9.0,
    )
    ranged = terminate_batch(
        agents, sessions, vouches, slots, roots, 9.0,
        wave_range=(jnp.asarray(lo, jnp.int32), jnp.asarray(lo + k, jnp.int32)),
    )
    np.testing.assert_array_equal(np.asarray(plain.roots), np.asarray(roots))
    np.testing.assert_array_equal(
        np.asarray(ranged.roots), np.asarray(plain.roots)
    )
    np.testing.assert_array_equal(
        np.asarray(ranged.agents.flags), np.asarray(plain.agents.flags)
    )
    np.testing.assert_array_equal(
        np.asarray(ranged.vouches.active), np.asarray(plain.vouches.active)
    )
    np.testing.assert_array_equal(
        np.asarray(ranged.sessions.state), np.asarray(plain.sessions.state)
    )
    np.testing.assert_array_equal(
        np.asarray(ranged.sessions.terminated_at),
        np.asarray(plain.sessions.terminated_at),
    )
    assert int(np.asarray(ranged.released)) == int(np.asarray(plain.released))


def test_free_rows_never_match_broadcast():
    # Free edge rows carry session == -1; the broadcast compare must not
    # release them (real slots are >= 0).
    agents, vouches = _tables(np.random.RandomState(0))
    wave = jnp.asarray(np.array([0], np.int32))
    in_wave = jnp.zeros((S_CAP,), bool).at[wave].set(True)
    _, v_out, _ = release_session_scope(
        agents, vouches, in_wave, wave_sessions=wave
    )
    # Rows beyond the populated 32 were inactive before and stay inactive.
    assert not np.asarray(v_out.active)[32:].any()
