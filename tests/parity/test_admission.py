"""Batched admission vs the reference join semantics."""

import numpy as np
import pytest

from hypervisor_tpu.config import DEFAULT_CONFIG
from hypervisor_tpu.models import SessionConfig, SessionState
from hypervisor_tpu.ops import admission
from hypervisor_tpu.state import HypervisorState


@pytest.fixture
def state():
    return HypervisorState(DEFAULT_CONFIG)


class TestBatchAdmission:
    def test_wave_of_joins(self, state):
        s = state.create_session("session:a", SessionConfig())
        state.enqueue_join(s, "did:hi", 0.9)
        state.enqueue_join(s, "did:mid", 0.7)
        state.enqueue_join(s, "did:lo", 0.2)
        status = state.flush_joins()
        assert status.tolist() == [admission.ADMIT_OK] * 3
        assert state.participant_count(s) == 3
        assert state.agent_row("did:hi")["ring"] == 2
        assert state.agent_row("did:lo")["ring"] == 3  # sandbox, floor-exempt

    def test_untrustworthy_sandboxed(self, state):
        s = state.create_session("session:a", SessionConfig())
        state.enqueue_join(s, "did:sus", 0.9, trustworthy=False)
        state.flush_joins()
        assert state.agent_row("did:sus")["ring"] == 3

    def test_duplicate_rejected_across_waves(self, state):
        s = state.create_session("session:a", SessionConfig())
        state.enqueue_join(s, "did:a", 0.8)
        assert state.flush_joins().tolist() == [admission.ADMIT_OK]
        state.enqueue_join(s, "did:a", 0.8)
        assert state.flush_joins().tolist() == [admission.ADMIT_DUPLICATE]
        assert state.participant_count(s) == 1

    def test_capacity_within_one_wave(self, state):
        s = state.create_session("session:a", SessionConfig(max_participants=2))
        for i in range(4):
            state.enqueue_join(s, f"did:a{i}", 0.8)
        status = state.flush_joins()
        assert status.tolist().count(admission.ADMIT_OK) == 2
        assert status.tolist().count(admission.ADMIT_CAPACITY) == 2
        assert state.participant_count(s) == 2

    def test_capacity_rank_skips_rejected(self, state):
        # 3 slots; one mid-wave reject (low sigma non-sandbox is impossible —
        # use duplicate) must not consume capacity.
        s = state.create_session("session:a", SessionConfig(max_participants=2))
        state.enqueue_join(s, "did:a", 0.8)
        state.flush_joins()
        state.enqueue_join(s, "did:a", 0.8)   # duplicate -> rejected
        state.enqueue_join(s, "did:b", 0.8)   # must still fit
        status = state.flush_joins()
        assert status.tolist() == [admission.ADMIT_DUPLICATE, admission.ADMIT_OK]
        assert state.participant_count(s) == 2

    def test_bad_session_state(self, state):
        s = state.create_session("session:a", SessionConfig())
        state.set_session_state(s, SessionState.ARCHIVED)
        state.enqueue_join(s, "did:a", 0.8)
        assert state.flush_joins().tolist() == [admission.ADMIT_BAD_STATE]

    def test_multi_session_wave(self, state):
        s1 = state.create_session("session:1", SessionConfig(max_participants=1))
        s2 = state.create_session("session:2", SessionConfig())
        state.enqueue_join(s1, "did:a", 0.8)
        state.enqueue_join(s2, "did:b", 0.8)
        state.enqueue_join(s1, "did:c", 0.8)  # over s1 capacity
        status = state.flush_joins()
        assert status.tolist() == [
            admission.ADMIT_OK,
            admission.ADMIT_OK,
            admission.ADMIT_CAPACITY,
        ]
        assert state.participant_count(s1) == 1
        assert state.participant_count(s2) == 1

    def test_10k_wave(self, state):
        sessions = [
            state.create_session(f"session:{i}", SessionConfig(max_participants=64))
            for i in range(256)
        ]
        n = 8192
        for i in range(n):
            state.enqueue_join(sessions[i % 256], f"did:bulk{i}", 0.8)
        status = state.flush_joins()
        assert len(status) == n
        assert (status == admission.ADMIT_OK).all()
        assert state.participant_count(sessions[0]) == 32
