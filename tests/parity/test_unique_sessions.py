"""unique_sessions fast path ≡ the ranked capacity path, pinned.

When every seat-consuming lane targets a distinct session (the bench's
one-join-per-session shape, host-verified by the bridge), admission can
skip the capacity-rank argsort — and the sharded wave its two
all_gathers — because every rank is 0. These tests pin bit-parity on
qualifying waves, including at-capacity refusals and duplicate-flagged
lanes sharing a session (which are refused before the seat check and so
do not break the contract). Reference semantics anchor:
`/root/reference/src/hypervisor/session/__init__.py:85-113` (capacity
guard at join).
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from hypervisor_tpu.models import SessionConfig, SessionState
from hypervisor_tpu.ops import admission
from hypervisor_tpu.parallel import make_mesh
from hypervisor_tpu.state import HypervisorState
from hypervisor_tpu.tables.state import AgentTable, SessionTable
from hypervisor_tpu.tables.struct import replace as t_replace

B = 16
S_CAP = 32


def _tables(at_capacity: set[int] = frozenset()):
    agents = AgentTable.create(64)
    sessions = SessionTable.create(S_CAP)
    ws = jnp.arange(B)
    sessions = t_replace(
        sessions,
        state=sessions.state.at[ws].set(
            jnp.int8(SessionState.HANDSHAKING.code)
        ),
        max_participants=sessions.max_participants.at[ws].set(4),
        min_sigma_eff=sessions.min_sigma_eff.at[ws].set(0.6),
    )
    if at_capacity:
        idx = jnp.asarray(sorted(at_capacity))
        sessions = t_replace(
            sessions,
            n_participants=sessions.n_participants.at[idx].set(4),
        )
    return agents, sessions


@pytest.mark.parametrize("full", [frozenset(), frozenset({0, 5})])
def test_unique_path_matches_ranked_path(full):
    agents, sessions = _tables(full)
    slot = jnp.arange(B, dtype=jnp.int32)
    did = jnp.arange(B, dtype=jnp.int32)
    session_slot = jnp.arange(B, dtype=jnp.int32)  # one join per session
    sigma = jnp.full((B,), 0.8, jnp.float32)
    trustworthy = jnp.ones((B,), bool)
    # Lane 7 is a host-known duplicate: refused before the seat check,
    # so it may share a session with lane 6 without breaking the
    # unique-sessions contract (the bridge's check exempts it).
    duplicate = jnp.zeros((B,), bool).at[7].set(True)
    session_slot = session_slot.at[7].set(6)

    kw = dict(
        slot=slot, did=did, session_slot=session_slot, sigma_raw=sigma,
        trustworthy=trustworthy, duplicate=duplicate, now=1.0,
    )
    ranked = admission.admit_batch(agents, sessions, **kw)
    fast = admission.admit_batch(
        agents, sessions, unique_sessions=True, **kw
    )
    np.testing.assert_array_equal(
        np.asarray(fast.status), np.asarray(ranked.status)
    )
    np.testing.assert_array_equal(
        np.asarray(fast.ring), np.asarray(ranked.ring)
    )
    np.testing.assert_array_equal(
        np.asarray(fast.agents.f32), np.asarray(ranked.agents.f32)
    )
    np.testing.assert_array_equal(
        np.asarray(fast.agents.i32), np.asarray(ranked.agents.i32)
    )
    np.testing.assert_array_equal(
        np.asarray(fast.sessions.n_participants),
        np.asarray(ranked.sessions.n_participants),
    )
    # At-capacity sessions refused on both paths.
    status = np.asarray(fast.status)
    for s in full:
        assert status[s] == admission.ADMIT_CAPACITY
    assert status[7] == admission.ADMIT_DUPLICATE


def test_bridge_detects_unique_and_matches_ranked_outcome():
    """The bridge's host check flips the fast path on for a one-join-
    per-session wave; outcome equal to a state driven WITHOUT the
    hint (forced via a colliding wave, which disables it)."""
    N_DEV = 8
    mesh = make_mesh(N_DEV, platform="cpu")
    from hypervisor_tpu.ops import merkle as merkle_ops

    def run(double_up: bool):
        st = HypervisorState()
        k = 8
        slots = st.create_sessions_batch(
            [f"us:s{i}" for i in range(k)], SessionConfig(min_sigma_eff=0.0)
        )
        b = 16
        if double_up:
            # two joins per session: ranked path (host check refuses).
            agent_sessions = np.asarray(slots, np.int32)[
                np.arange(b) % k
            ]
        else:
            # one join per session: fast path. Halve the wave.
            b = 8
            agent_sessions = np.asarray(slots, np.int32)
        dids = [f"did:us:{i}" for i in range(b)]
        rng = np.random.RandomState(3)
        bodies = rng.randint(
            0, 2**32, size=(2, k, merkle_ops.BODY_WORDS), dtype=np.uint64
        ).astype(np.uint32)
        res = st.run_governance_wave(
            slots, dids, agent_sessions,
            np.full(b, 0.8, np.float32), bodies, now=1.0, mesh=mesh,
        )
        return st, res

    st_fast, res_fast = run(double_up=False)
    assert (np.asarray(res_fast.status) == admission.ADMIT_OK).all()
    # The fast-path wave archived its sessions like any other.
    assert (
        np.asarray(st_fast.sessions.state)[:8] == SessionState.ARCHIVED.code
    ).all()

    st_ranked, res_ranked = run(double_up=True)
    assert (np.asarray(res_ranked.status) == admission.ADMIT_OK).all()
