"""MXU slash-cascade kernel parity vs the scatter/gather XLA op.

`slash_cascade_dense` runs the kernel's exact matmul math as plain XLA on
CPU; the compiled Pallas kernel itself is TPU-gated (HV_TPU_TESTS=1).
Reference semantics: `slashing.py:63-143` in /root/reference.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax.numpy as jnp
import pytest

from hypervisor_tpu.kernels.liability_pallas import (
    slash_cascade_dense,
    slash_cascade_pallas,
)
from hypervisor_tpu.kernels.sha256_pallas import pallas_available
from hypervisor_tpu.ops.liability import slash_cascade
from hypervisor_tpu.tables.state import VouchTable


def random_graph(n_agents=257, n_edges=1500, seed=0, sessions=2):
    rng = np.random.RandomState(seed)
    v = VouchTable.create(n_edges)
    v = dataclasses.replace(
        v,
        voucher=jnp.asarray(rng.randint(0, n_agents, n_edges, dtype=np.int64), jnp.int32),
        vouchee=jnp.asarray(rng.randint(0, n_agents, n_edges, dtype=np.int64), jnp.int32),
        session=jnp.asarray(rng.randint(0, sessions, n_edges, dtype=np.int64), jnp.int32),
        bond=jnp.asarray(rng.uniform(0.05, 0.2, n_edges).astype(np.float32)),
        active=jnp.asarray(rng.uniform(0, 1, n_edges) > 0.2),
        expiry=jnp.where(
            jnp.asarray(rng.uniform(0, 1, n_edges) > 0.1),
            jnp.inf,
            -1.0,  # a few expired edges
        ).astype(jnp.float32),
    )
    sigma = jnp.asarray(rng.uniform(0.05, 1.0, n_agents).astype(np.float32))
    seeds = jnp.asarray(rng.uniform(0, 1, n_agents) > 0.97)
    return v, sigma, seeds


def _assert_matches(got, want):
    np.testing.assert_allclose(
        np.asarray(got.sigma), np.asarray(want.sigma), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_array_equal(np.asarray(got.slashed), np.asarray(want.slashed))
    np.testing.assert_array_equal(np.asarray(got.clipped), np.asarray(want.clipped))
    np.testing.assert_array_equal(np.asarray(got.wave_of), np.asarray(want.wave_of))
    np.testing.assert_array_equal(
        np.asarray(got.vouch.active), np.asarray(want.vouch.active)
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_dense_math_matches_scatter_op(seed):
    v, sigma, seeds = random_graph(seed=seed)
    want = slash_cascade(v, sigma, seeds, 0, 0.95, 0.0)
    got = slash_cascade_dense(v, sigma, seeds, 0, 0.95, 0.0)
    _assert_matches(got, want)


def test_dense_math_partial_omega_cascades():
    # omega < 1 exercises the (1-omega)^k clip exponents across waves
    v, sigma, seeds = random_graph(seed=3, n_agents=64, n_edges=256)
    want = slash_cascade(v, sigma, seeds, 1, 0.6, 0.0)
    got = slash_cascade_dense(v, sigma, seeds, 1, 0.6, 0.0)
    _assert_matches(got, want)


def test_dense_math_session_scoping():
    v, sigma, seeds = random_graph(seed=4, sessions=3)
    for sess in range(3):
        want = slash_cascade(v, sigma, seeds, sess, 0.95, 0.0)
        got = slash_cascade_dense(v, sigma, seeds, sess, 0.95, 0.0)
        _assert_matches(got, want)


@pytest.mark.skipif(
    not pallas_available(),
    reason="compiled Mosaic kernel needs a TPU backend "
    "(opt in with HV_TPU_TESTS=1)",
)
def test_compiled_pallas_cascade_matches_on_tpu():
    v, sigma, seeds = random_graph(seed=5, n_agents=1000, n_edges=4096)
    want = slash_cascade(v, sigma, seeds, 0, 0.95, 0.0)
    got = slash_cascade_pallas(v, sigma, seeds, 0, 0.95, 0.0)
    _assert_matches(got, want)


def test_dense_math_matches_at_10k_agents():
    """The 10k north-star config runs the multi-tile matmul formulation
    (round 1 capped the kernel at one 1024-agent tile)."""
    v, sigma, seeds = random_graph(seed=6, n_agents=10_000, n_edges=8192)
    want = slash_cascade(v, sigma, seeds, 0, 0.95, 0.0)
    got = slash_cascade_dense(v, sigma, seeds, 0, 0.95, 0.0)
    _assert_matches(got, want)


@pytest.mark.skipif(
    not pallas_available(),
    reason="compiled Mosaic kernel needs a TPU backend "
    "(opt in with HV_TPU_TESTS=1)",
)
def test_compiled_pallas_cascade_matches_at_10k_agents():
    v, sigma, seeds = random_graph(seed=7, n_agents=10_000, n_edges=8192)
    want = slash_cascade(v, sigma, seeds, 0, 0.95, 0.0)
    got = slash_cascade_pallas(v, sigma, seeds, 0, 0.95, 0.0)
    _assert_matches(got, want)
