"""State-integrity plane: sanitizer, scrubber, corruption chaos, ladder.

The headline is the corruption property: every `InjectedCorruption`
class (bit-flip, row rewrite, chain-link tamper) must be detected
within K waves, then repaired in place or restored via `recover()`,
and under the restore ladder the final device tables + Merkle chain
heads must be bit-identical to an uninterrupted oracle run of the same
workload. A clean multi-hundred-wave run must report ZERO violations
(no false positives).
"""

from __future__ import annotations

import asyncio
import json

import numpy as np
import pytest

from hypervisor_tpu.config import HypervisorConfig, TableCapacity
from hypervisor_tpu.integrity import (
    CATALOG,
    IntegrityError,
    IntegrityPlane,
    MerkleScrubber,
)
from hypervisor_tpu.integrity import invariants as inv
from hypervisor_tpu.models import SessionConfig
from hypervisor_tpu.observability import EventType
from hypervisor_tpu.observability import metrics as mp
from hypervisor_tpu.resilience import Supervisor, WriteAheadLog
from hypervisor_tpu.runtime.checkpoint import state_arrays
from hypervisor_tpu.state import HypervisorState
from hypervisor_tpu.tables.state import FLAG_QUARANTINED
from hypervisor_tpu.testing.chaos import (
    InjectedCorruption,
    InjectedWaveFault,
    WaveChaosInjector,
    WaveChaosPlan,
)

SMALL = HypervisorConfig(
    capacity=TableCapacity(
        max_agents=512,  # governance waves bump-allocate fresh rows
        max_sessions=512,
        max_vouch_edges=64,
        max_sagas=16,
        max_steps_per_saga=8,
        max_elevations=16,
        delta_log_capacity=2048,
        event_log_capacity=128,
        trace_log_capacity=128,
    )
)


def drive_waves(st, rounds, base=0, lanes=2):
    for r in range(base, base + rounds):
        slots = st.create_sessions_batch(
            [f"w{r}:{i}" for i in range(lanes)],
            SessionConfig(min_sigma_eff=0.0),
        )
        st.run_governance_wave(
            slots, [f"did:w{r}:{i}" for i in range(lanes)], slots.copy(),
            np.full(lanes, 0.8, np.float32),
            np.zeros((1, lanes, 16), np.uint32), now=float(r),
        )


def chain_heads(st):
    return {s: tuple(int(w) for w in v) for s, v in st._chain_seed.items()}


def assert_bit_identical(a, b):
    for key, col in state_arrays(a).items():
        np.testing.assert_array_equal(
            col, state_arrays(b)[key], err_msg=f"column {key} diverged"
        )
    assert chain_heads(a) == chain_heads(b), "Merkle chain heads diverged"


# ── catalog sanity ───────────────────────────────────────────────────


class TestCatalog:
    def test_bits_unique_per_table_and_classes_valid(self):
        seen: dict[str, int] = {}
        for table, name, klass, bit in CATALOG:
            assert klass in ("repair", "contain", "restore"), (table, name)
            assert bit & (bit - 1) == 0, "violation bits are single bits"
            assert not seen.get(table, 0) & bit, f"{table}.{name} bit reused"
            seen[table] = seen.get(table, 0) | bit


# ── clean runs: no false positives ───────────────────────────────────


class TestCleanRuns:
    def test_200_clean_waves_report_zero_violations(self):
        """Sampling on at every dispatch: a long mixed clean workload
        must never trip a single invariant (the acceptance bar for
        false positives)."""
        st = HypervisorState(SMALL)
        plane = IntegrityPlane(st, every=1, scrub_every=4, scrub_budget=32)
        drive_waves(st, 200)
        snap = st.metrics_snapshot()
        assert snap.counter(mp.INTEGRITY_CHECKS) >= 200
        assert snap.counter(mp.INTEGRITY_VIOLATIONS) == 0
        assert snap.gauge(mp.INTEGRITY_VIOLATION_ROWS) == 0
        assert plane.scrubber.mismatches == 0
        assert plane.sanitize()["total"] == 0

    def test_mixed_workload_clean(self):
        """Joins, deltas, vouches, sagas, gateway, slash, quarantine,
        elevation, terminate: every legitimate transition satisfies the
        catalog."""
        st = HypervisorState(SMALL)
        plane = IntegrityPlane(st, every=1)
        slot = st.create_session("s:mix", SessionConfig(min_sigma_eff=0.0))
        st.enqueue_join(slot, "did:a", 0.8)
        st.enqueue_join(slot, "did:b", 0.97)
        st.flush_joins(now=1.0)
        a = st.agent_row("did:a")["slot"]
        b = st.agent_row("did:b")["slot"]
        st.add_vouch(b, a, slot, bond=0.15)
        st.stage_delta(slot, a, ts=2.0, change_words=np.arange(4, dtype=np.uint32))
        st.flush_deltas()
        g = st.create_saga("saga:mix", slot, [{"retries": 1}, {}])
        st.saga_round({g: True})
        st.check_actions_wave(
            [a, b], [2, 2], [False] * 2, [False] * 2, [False] * 2,
            [False] * 2, now=2.5,
        )
        st.grant_elevation(b, 1, now=2.6)
        st.quarantine_rows([a], now=2.7)
        st.apply_slash(slot, a, 0.9, now=2.8)
        st.record_calls([b], [2], now=2.9)
        st.breach_sweep_tick(3.0)
        st.terminate_sessions([slot], now=3.0)
        report = plane.sanitize()
        assert report["total"] == 0, report
        # and the scrubber re-hashes the whole history cleanly
        while True:
            tick = plane.scrub_tick()
            assert not tick["mismatches"], tick
            if tick["sweep_completed"]:
                break


# ── detection + in-place repair ──────────────────────────────────────


class TestDetectionAndRepair:
    def test_bit_flip_detected_within_k_waves_and_repaired(self):
        """Sampling every 2 dispatches: a sigma bit flip at dispatch d
        must show on the metrics drain within K=2 further waves, and
        the next gate repairs it in place."""
        st = HypervisorState(SMALL)
        IntegrityPlane(st, every=2)
        drive_waves(st, 2)
        inj = WaveChaosInjector(
            WaveChaosPlan(seed=3, corruptions=(
                InjectedCorruption("bit_flip", at_dispatch=1, table="agents"),
            ))
        )
        st.fault_injector = inj
        drive_waves(st, 2, base=2)  # K = 2 waves after the corruption
        assert len(inj.corruptions_applied) == 1
        snap = st.metrics_snapshot()
        assert snap.gauge(mp.INTEGRITY_VIOLATION_ROWS) >= 1, (
            "bit flip not detected within K waves"
        )
        # the drain marked the plane dirty; the next gate settles it
        st.fault_injector = None
        drive_waves(st, 1, base=4)
        snap = st.metrics_snapshot()
        assert snap.counter(mp.INTEGRITY_REPAIRS) >= 1
        assert snap.gauge(mp.INTEGRITY_VIOLATION_ROWS) == 0
        assert st.integrity.sanitize()["total"] == 0

    def test_row_rewrite_repairs_every_class(self):
        st = HypervisorState(SMALL)
        plane = IntegrityPlane(st, every=0)
        drive_waves(st, 1)
        inj = WaveChaosInjector(
            WaveChaosPlan(seed=5, corruptions=(
                InjectedCorruption("row_rewrite", at_dispatch=1, table="agents"),
            ))
        )
        inj.dispatches = 1
        (record,) = inj.apply_due_corruptions(st)
        report = plane.sanitize()
        checks = {
            c
            for row in report["violations"]["agents"]
            for c in row["checks"]
        }
        assert {"sigma_range", "ring_range", "rl_tokens", "flags"} <= checks
        assert report["repaired_rows"] >= 1
        after = plane.sanitize()
        assert after["total"] == 0
        row = record["row"]
        sigma = float(np.asarray(st.agents.sigma_eff)[row])
        ring = int(np.asarray(st.agents.ring)[row])
        assert 0.0 <= sigma <= 1.0 and 0 <= ring <= 3

    def test_corrupt_session_ref_quarantines_the_row(self):
        from hypervisor_tpu.tables.state import AI32_SESSION
        from hypervisor_tpu.tables.struct import replace
        import jax.numpy as jnp

        st = HypervisorState(SMALL)
        plane = IntegrityPlane(st, every=0)
        slot = st.create_session("s:q", SessionConfig(min_sigma_eff=0.0))
        st.enqueue_join(slot, "did:q", 0.8)
        st.flush_joins(now=1.0)
        row = st.agent_row("did:q")["slot"]
        i32 = np.array(st.agents.i32, copy=True)
        i32[row, AI32_SESSION] = 10_000  # way past the session table
        st.agents = replace(st.agents, i32=jnp.asarray(i32))
        report = plane.sanitize(now=2.0)
        assert report["quarantined_rows"] == 1
        assert np.asarray(st.agents.flags)[row] & FLAG_QUARANTINED
        snap = st.metrics_snapshot()
        assert snap.counter(mp.INTEGRITY_ROWS_QUARANTINED) == 1

    def test_vouch_bond_corruption_contained_and_escrow_escalates(self):
        st = HypervisorState(SMALL)
        plane = IntegrityPlane(st, every=0)
        slot = st.create_session("s:v", SessionConfig(min_sigma_eff=0.0))
        st.enqueue_join(slot, "did:a", 0.8)
        st.enqueue_join(slot, "did:b", 0.8)
        st.flush_joins(now=1.0)
        a = st.agent_row("did:a")["slot"]
        b = st.agent_row("did:b")["slot"]
        edge = st.add_vouch(a, b, slot, bond=0.15)
        # containment class: negative bond + dangling endpoint
        inj = WaveChaosInjector(
            WaveChaosPlan(seed=1, corruptions=(
                InjectedCorruption("row_rewrite", at_dispatch=1, table="vouches"),
            ))
        )
        inj.dispatches = 1
        inj.apply_due_corruptions(st)
        report = plane.sanitize()
        assert report["total"] >= 1
        assert not bool(np.asarray(st.vouches.active)[edge])
        # conservation class: an inflated bond breaks the escrow cap
        edge2 = st.add_vouch(a, b, slot, bond=0.15)
        inj2 = WaveChaosInjector(
            WaveChaosPlan(seed=2, corruptions=(
                InjectedCorruption("bit_flip", at_dispatch=1, table="vouches"),
            ))
        )
        inj2.dispatches = 1
        inj2.apply_due_corruptions(st)
        with pytest.raises(IntegrityError, match="restore"):
            plane.sanitize()
        assert plane.last_violations, "escrow break not recorded"
        del edge2


# ── the Merkle scrubber ──────────────────────────────────────────────


def _seed_history(st, sessions=3, deltas=4):
    slots = [
        st.create_session(f"s:scrub{i}", SessionConfig(min_sigma_eff=0.0))
        for i in range(sessions)
    ]
    for slot in slots:
        st.enqueue_join(slot, f"did:scrub{slot}", 0.8)
    st.flush_joins(now=1.0)
    for t in range(deltas):
        for slot in slots:
            st.stage_delta(
                slot, 0, ts=float(t),
                change_words=np.full(4, t + 1, np.uint32),
            )
        st.flush_deltas()
    return slots


class TestScrubber:
    def test_clean_sweep_verifies_every_link_and_head(self):
        st = HypervisorState(SMALL)
        _seed_history(st)
        scrub = MerkleScrubber(st, budget=5)
        ticks = 0
        while True:
            report = scrub.tick()
            ticks += 1
            assert not report["mismatches"]
            if report["sweep_completed"]:
                break
        # 3 sessions x 4 links (full history => seed link included) + 3 heads
        assert scrub.links_verified == 12
        assert scrub.heads_verified == 3
        assert ticks == -(-scrub.sweep_size // scrub.budget)

    def test_body_bit_rot_caught_within_one_sweep(self):
        st = HypervisorState(SMALL)
        plane = IntegrityPlane(st, every=0, scrub_budget=64)
        _seed_history(st)
        inj = WaveChaosInjector(
            WaveChaosPlan(seed=7, corruptions=(
                InjectedCorruption("bit_flip", at_dispatch=1, table="delta_log"),
            ))
        )
        inj.dispatches = 1
        (record,) = inj.apply_due_corruptions(st)
        with pytest.raises(IntegrityError, match="scrub mismatch"):
            while True:
                if plane.scrub_tick()["sweep_completed"]:
                    break
        assert plane.scrubber.mismatches >= 1
        assert plane.scrubber.last_mismatch is not None
        del record

    def test_ring_wrap_mid_sweep_skips_stale_lanes_not_flags_them(self):
        """A DeltaLog wrap between ticks recycles archived sessions'
        rows out from under the sweep snapshot; the scrubber must SKIP
        those lanes (the chain prefix is gone by design), never read
        recycled bytes as corruption and restore a healthy system."""
        tiny = HypervisorConfig(
            capacity=TableCapacity(
                max_agents=64, max_sessions=32, max_vouch_edges=64,
                max_sagas=16, max_steps_per_saga=8, max_elevations=16,
                delta_log_capacity=16, event_log_capacity=64,
                trace_log_capacity=64,
            )
        )
        st = HypervisorState(tiny)
        a = st.create_session("s:old", SessionConfig(min_sigma_eff=0.0))
        for t in range(8):
            st.stage_delta(a, 0, ts=float(t),
                           change_words=np.full(2, t + 1, np.uint32))
            st.flush_deltas()
        st.terminate_sessions([a], now=9.0)  # archived: rows may recycle
        scrub = MerkleScrubber(st, budget=2)
        first = scrub.tick()  # snapshot the sweep, verify a partial strip
        assert not first["mismatches"]
        # wrap the ring over s:old's earliest rows
        b = st.create_session("s:new", SessionConfig(min_sigma_eff=0.0))
        for t in range(12):
            st.stage_delta(b, 0, ts=float(t),
                           change_words=np.full(2, 100 + t, np.uint32))
            st.flush_deltas()
        while True:
            report = scrub.tick()
            assert not report["mismatches"], (
                "recycled rows misread as corruption"
            )
            if report["sweep_completed"]:
                break
        assert scrub.stale_skipped >= 1
        # the NEXT sweep (fresh snapshot) verifies everything cleanly
        while True:
            report = scrub.tick()
            assert not report["mismatches"]
            if report["sweep_completed"]:
                break

    def test_plane_attach_preserves_cumulative_scrub_stats(self):
        st = HypervisorState(SMALL)
        plane = IntegrityPlane(st, every=0, scrub_budget=64)
        _seed_history(st)
        while not plane.scrub_tick()["sweep_completed"]:
            pass
        links_before = plane.scrubber.links_verified
        assert links_before > 0
        plane.attach(HypervisorState(SMALL))
        assert plane.scrubber.links_verified == links_before
        assert plane.scrubber.sweeps_completed == 1

    def test_chain_tamper_caught_and_counted(self):
        st = HypervisorState(SMALL)
        plane = IntegrityPlane(st, every=0, scrub_budget=64)
        _seed_history(st)
        inj = WaveChaosInjector(
            WaveChaosPlan(seed=8, corruptions=(
                InjectedCorruption("chain_tamper", at_dispatch=1),
            ))
        )
        inj.dispatches = 1
        inj.apply_due_corruptions(st)
        with pytest.raises(IntegrityError):
            while True:
                if plane.scrub_tick()["sweep_completed"]:
                    break
        snap = st.metrics_snapshot()
        assert snap.counter(mp.INTEGRITY_SCRUB_MISMATCHES) >= 1
        assert snap.counter(mp.INTEGRITY_SCRUB_LINKS) >= 1


# ── the corruption property: oracle bit-identity via restore ─────────


class TestCorruptionOracleProperty:
    """Every corruption class: detected within K waves, escalated to
    recover(), and the final tables + chain heads are bit-identical to
    the uninterrupted oracle run of the same workload."""

    CLASSES = (
        InjectedCorruption("bit_flip", at_dispatch=2, table="agents"),
        InjectedCorruption("row_rewrite", at_dispatch=2, table="sessions"),
        InjectedCorruption("chain_tamper", at_dispatch=2),
    )

    @staticmethod
    def _wave(st, sup, r, lanes=2):
        """One production round with restore-retry semantics: the
        session rows commit (journaled) before the wave, so when the
        gate restores and refuses the dispatch, the SAME slots are
        valid on the recovered state (replayed from the WAL) and the
        wave re-issues there. Returns True when a restore fired."""
        from hypervisor_tpu.integrity import StateRestoredError

        slots = st.create_sessions_batch(
            [f"w{r}:{i}" for i in range(lanes)],
            SessionConfig(min_sigma_eff=0.0),
        )
        args = (
            slots, [f"did:w{r}:{i}" for i in range(lanes)], slots.copy(),
            np.full(lanes, 0.8, np.float32),
            np.zeros((1, lanes, 16), np.uint32),
        )
        try:
            st.run_governance_wave(*args, now=float(r))
        except StateRestoredError:
            sup.state.run_governance_wave(*args, now=float(r))
            return True
        return False

    @pytest.mark.parametrize(
        "corruption", CLASSES, ids=[c.kind for c in CLASSES]
    )
    def test_detect_restore_bit_identical(self, corruption, tmp_path):
        oracle = HypervisorState(SMALL)
        drive_waves(oracle, 6)

        st = HypervisorState(SMALL)
        st.journal = WriteAheadLog(tmp_path / "wal.log", fsync=False)
        sup = Supervisor(
            st, checkpoint_dir=str(tmp_path / "ckpt"), sleep=lambda s: None
        )
        plane = IntegrityPlane(
            st, every=1, scrub_every=1, scrub_budget=256, ladder="restore"
        )
        drive_waves(st, 3)
        sup.checkpoint()
        sup.state.fault_injector = WaveChaosInjector(
            WaveChaosPlan(seed=13, corruptions=(corruption,))
        )
        # The production loop: one wave + one metrics drain per round
        # (the drain is where sanitizer detection closes). K = 1 round
        # after detection: the NEXT gate settles the damage, restores,
        # and refuses the in-flight wave — which re-issues against the
        # recovered state (its session rows replayed from the WAL, so
        # the same slots are valid).
        detected_at = None
        for r in range(3, 6):
            if self._wave(sup.state, sup, r) and detected_at is None:
                detected_at = r
            sup.state.metrics_snapshot()
        st = sup.state
        if plane.restores == 0:
            # Corruption landed on the LAST gate: settle explicitly.
            report = plane.sanitize()
            assert report["restored"], f"{corruption.kind} never detected"
            st = sup.state
        assert plane.restores >= 1
        assert sup.state_restores >= 1
        if detected_at is not None:
            # K: the restore fired at most 2 waves after the round the
            # corruption landed on (round 3 + at_dispatch - 1).
            corruption_round = 3 + corruption.at_dispatch - 1
            assert detected_at - corruption_round <= 2
        assert_bit_identical(oracle, st)
        # the restored plane keeps serving (and stays journaled)
        drive_waves(st, 1, base=6)
        assert st.journal is not None and st.journal.last_seq > 0
        assert plane.sanitize()["total"] == 0

    def test_repair_ladder_reaches_clean_state_for_repairable_classes(
        self,
    ):
        """Default ladder: a repairable corruption is fixed IN PLACE
        (post-repair tables satisfy every invariant; governance keeps
        flowing) — containment, not oracle-identity."""
        st = HypervisorState(SMALL)
        plane = IntegrityPlane(st, every=1)
        drive_waves(st, 2)
        st.fault_injector = WaveChaosInjector(
            WaveChaosPlan(seed=21, corruptions=(
                InjectedCorruption("bit_flip", at_dispatch=1, table="agents"),
            ))
        )
        drive_waves(st, 2, base=2)
        st.metrics_snapshot()      # detection closes at the drain
        st.fault_injector = None
        drive_waves(st, 1, base=4)  # the next gate settles the damage
        assert plane.repairs >= 1
        assert plane.sanitize()["total"] == 0


# ── escalation without a restore path ────────────────────────────────


class TestEscalationSafety:
    def test_restore_class_without_supervisor_raises(self):
        st = HypervisorState(SMALL)
        plane = IntegrityPlane(st, every=0)
        drive_waves(st, 1)
        inj = WaveChaosInjector(
            WaveChaosPlan(seed=4, corruptions=(
                InjectedCorruption("row_rewrite", at_dispatch=1, table="sessions"),
            ))
        )
        inj.dispatches = 1
        inj.apply_due_corruptions(st)
        with pytest.raises(IntegrityError, match="no supervisor restore"):
            plane.sanitize()
        snap = st.metrics_snapshot()
        assert snap.counter(mp.INTEGRITY_RESTORES) == 1

    def test_supervisor_without_checkpoint_cannot_restore(self, tmp_path):
        st = HypervisorState(SMALL)
        Supervisor(st, sleep=lambda s: None)  # no checkpoint_dir
        plane = IntegrityPlane(st, every=0)
        drive_waves(st, 1)
        inj = WaveChaosInjector(
            WaveChaosPlan(seed=4, corruptions=(
                InjectedCorruption("row_rewrite", at_dispatch=1, table="sessions"),
            ))
        )
        inj.dispatches = 1
        inj.apply_due_corruptions(st)
        with pytest.raises(IntegrityError):
            plane.sanitize()


# ── schedule reproducibility across the corrupt-rate rename ──────────


class TestChaosScheduleCompat:
    def _schedule(self, plan):
        inj = WaveChaosInjector(plan)
        out = []
        for _ in range(48):
            try:
                inj.on_dispatch("governance_wave")
                out.append("ok")
            except InjectedWaveFault:
                out.append("fault")
        return out

    def test_corruptions_do_not_perturb_the_fault_schedule(self):
        base = WaveChaosPlan(seed=7, fail_rate=0.3)
        with_corrupt = WaveChaosPlan(
            seed=7, fail_rate=0.3,
            corruptions=(InjectedCorruption("bit_flip", at_dispatch=3),),
        )
        assert self._schedule(base) == self._schedule(with_corrupt)

    def test_corrupt_rate_alias_still_means_drain_loss(self):
        legacy = WaveChaosPlan(seed=3, corrupt_rate=1.0)
        renamed = WaveChaosPlan(seed=3, drain_loss_rate=1.0)
        assert legacy.effective_drain_loss_rate == 1.0
        from hypervisor_tpu.testing.chaos import InjectedDeviceLoss

        for plan in (legacy, renamed):
            inj = WaveChaosInjector(plan)
            with pytest.raises(InjectedDeviceLoss):
                inj.on_drain("metrics_drain")


# ── zero-recompile + unchanged-jaxpr pin (satellite) ─────────────────


class TestCompileHygiene:
    def test_sanitizer_adds_no_recompiles_to_wave_entry_points(self):
        """The sanitizer is its OWN program: attaching the plane and
        sampling at every dispatch must not re-trace ANY wrapped wave
        entry point (CompileWatch recompile counters are the proof),
        and the sanitizer itself compiles once."""
        from hypervisor_tpu.observability.health import compile_summary

        st = HypervisorState(SMALL)
        drive_waves(st, 2, lanes=2)

        def recompiles():
            return {
                row["program"]: row["recompiles"]
                for row in compile_summary(last=0)["by_program"]
            }

        before = recompiles()
        plane = IntegrityPlane(st, every=1)
        drive_waves(st, 4, base=2, lanes=2)
        plane.sanitize()
        after = recompiles()
        for program, count in before.items():
            if program.startswith("integrity"):
                continue
            assert after[program] == count, (
                f"{program} recompiled after the integrity plane attached"
            )
        # Sampling repeatedly at ONE shape never re-traces the
        # sanitizer. (Relative, not absolute-zero: compile counters are
        # process-global, and another suite — e.g. the adversarial
        # scenarios — may already have traced integrity_check at a
        # different table capacity before this test runs.)
        drive_waves(st, 4, base=6, lanes=2)
        plane.sanitize()
        settled = recompiles()
        assert settled.get("integrity_check", 0) == after.get(
            "integrity_check", 0
        ), "sanitizer re-traced across repeated same-shape sampling"

    def test_clean_path_jaxpr_unchanged_with_sampling_off(self):
        """The wave program the state dispatches is byte-identical with
        and without an attached (sampling-off) integrity plane — the
        sanitizer never rides the wave's lowering."""
        import jax
        import jax.numpy as jnp

        from hypervisor_tpu.observability import tracing
        from hypervisor_tpu.ops.pipeline import governance_wave
        from hypervisor_tpu.tables.logs import TraceLog
        from hypervisor_tpu.tables.state import (
            AgentTable,
            SessionTable,
            VouchTable,
        )
        from hypervisor_tpu.tables.struct import replace as t_replace

        def trace_wave():
            b = 4
            agents = AgentTable.create(16)
            sessions = SessionTable.create(16)
            vouches = VouchTable.create(8)
            sessions = t_replace(
                sessions, state=sessions.state.at[:b].set(1)
            )
            ctx = tracing.TraceContext(
                trace=jnp.uint32(1), span=jnp.uint32(2),
                wave_seq=jnp.int32(0), sampled=jnp.asarray(True),
            )
            return str(jax.make_jaxpr(
                lambda *a: governance_wave(
                    *a, use_pallas=False,
                    metrics=mp.REGISTRY.create_table(),
                    trace=TraceLog.create(64), trace_ctx=ctx,
                )
            )(
                agents, sessions, vouches,
                jnp.arange(b, dtype=jnp.int32),
                jnp.arange(b, dtype=jnp.int32),
                jnp.arange(b, dtype=jnp.int32),
                jnp.full((b,), 0.8, jnp.float32),
                jnp.ones((b,), bool), jnp.zeros((b,), bool),
                jnp.arange(b, dtype=jnp.int32),
                jnp.zeros((2, b, 16), jnp.uint32), 0.0,
            ))

        bare = trace_wave()
        st = HypervisorState(SMALL)
        IntegrityPlane(st, every=0)  # attached, sampling off
        with_plane = trace_wave()
        assert bare == with_plane


# ── surfaces: events, endpoints ──────────────────────────────────────


class TestSurfaces:
    def test_violations_reach_the_event_bus(self):
        from hypervisor_tpu.api import HypervisorService

        svc = HypervisorService()
        st = svc.hv.state
        plane = IntegrityPlane(st, every=0)
        slot = st.create_session("s:bus", SessionConfig(min_sigma_eff=0.0))
        st.enqueue_join(slot, "did:bus", 0.8)
        st.flush_joins(now=1.0)
        inj = WaveChaosInjector(
            WaveChaosPlan(seed=6, corruptions=(
                InjectedCorruption("bit_flip", at_dispatch=1, table="agents"),
            ))
        )
        inj.dispatches = 1
        inj.apply_due_corruptions(st)
        report = plane.sanitize()
        assert report["repaired_rows"] == 1
        events = svc.bus.query_by_type(EventType.INTEGRITY_VIOLATION)
        assert len(events) == 1
        assert events[0].payload["total"] == 1

    def test_debug_integrity_on_both_transports(self):
        import urllib.request

        from hypervisor_tpu.api import HypervisorService
        from hypervisor_tpu.api.server import HypervisorHTTPServer

        svc = HypervisorService()
        payload = asyncio.run(svc.debug_integrity())
        assert payload == {"enabled": False}
        plane = IntegrityPlane(svc.hv.state, every=4)
        payload = asyncio.run(svc.debug_integrity())
        json.dumps(payload)  # JSON-serializable contract
        assert payload["enabled"] is True
        assert payload["sampling"]["every"] == 4
        assert {"table", "check", "action"} <= set(payload["catalog"][0])
        server = HypervisorHTTPServer(svc).start()
        try:
            doc = json.loads(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/debug/integrity"
                ).read()
            )
        finally:
            server.stop()
        assert doc["enabled"] is True
        assert doc["scrub"]["budget"] == plane.scrubber.budget

    def test_health_summary_carries_the_integrity_panel(self):
        st = HypervisorState(SMALL)
        IntegrityPlane(st, every=2)
        drive_waves(st, 2)
        health = st.health_summary()
        json.dumps(health)
        assert health["integrity"]["enabled"] is True
        assert health["integrity"]["sampling"]["checks"] >= 1

    def test_repairable_bits_partition_matches_catalog(self):
        repairable = {
            (t, n) for t, n, k, _ in CATALOG if k == "repair"
        }
        assert ("agents", "sigma_range") in repairable
        assert ("vouches", "escrow_conservation") not in repairable
        agent_bits = 0
        for t, _n, k, bit in CATALOG:
            if t == "agents" and k == "repair":
                agent_bits |= bit
        assert agent_bits == inv.REPAIRABLE_AGENT_BITS
