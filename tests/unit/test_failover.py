"""Fleet failover plane: durable ownership, fencing, reassignment.

The headline test is the multi-tenant kill-at-every-WAL-record-boundary
property: a two-tenant arena journals an interleaved workload into
per-tenant fenced WALs under a `WorkerDurability` namespace, with a
mid-workload per-tenant checkpoint; then tenant 0's WAL is truncated at
every record boundary (and mid-record) to simulate the worker dying at
that byte, recovered per-tenant (`recover_tenant`), and SPLICED into a
DIFFERENT worker's arena — the survivor's materialized tables + Merkle
chain heads must land bit-identical to the uninterrupted oracle's
snapshot of the last committed op, per tenant.
"""

from __future__ import annotations

import asyncio
import json
import shutil

import numpy as np
import pytest

from hypervisor_tpu.config import HypervisorConfig, TableCapacity
from hypervisor_tpu.fleet.failover import (
    FailoverController,
    FailoverError,
    FencingError,
    ManagedWorker,
    OwnershipMap,
    WorkerDurability,
)
from hypervisor_tpu.models import SessionConfig
from hypervisor_tpu.resilience.recovery import recover_tenant
from hypervisor_tpu.resilience.wal import scan
from hypervisor_tpu.runtime.checkpoint import state_arrays
from hypervisor_tpu.tenancy import TenantArena
from hypervisor_tpu.testing.chaos import (
    InjectedFleetFault,
    WaveChaosInjector,
    WaveChaosPlan,
)

SMALL = HypervisorConfig(
    capacity=TableCapacity(
        max_agents=64,
        max_sessions=32,
        max_vouch_edges=64,
        max_sagas=16,
        max_steps_per_saga=8,
        max_elevations=16,
        delta_log_capacity=128,
        event_log_capacity=128,
        trace_log_capacity=128,
    )
)


def _fingerprint(st) -> dict:
    """Everything the reassignment property compares bit-for-bit."""
    return {
        "arrays": state_arrays(st),
        "chain": {
            s: tuple(int(w) for w in v) for s, v in st._chain_seed.items()
        },
        "members": set(st._members),
        "turns": dict(st._turns),
    }


def _assert_same(a: dict, b: dict, ctx: str = "") -> None:
    assert a["chain"] == b["chain"], f"chain head diverged {ctx}"
    assert a["members"] == b["members"], f"membership diverged {ctx}"
    assert a["turns"] == b["turns"], f"turn counters diverged {ctx}"
    for key in a["arrays"]:
        np.testing.assert_array_equal(
            a["arrays"][key], b["arrays"][key],
            err_msg=f"column {key} diverged {ctx}",
        )


# ── the journaled ownership map ──────────────────────────────────────


class TestOwnershipMap:
    def test_assign_fence_and_views(self):
        events = []
        om = OwnershipMap(seed=3, emit=lambda k, p: events.append(k))
        om.assign("w0", (0, 1), 0, 1.0)
        om.assign("w1", (2,), 0, 1.0)
        assert om.owner_of(1) == ("w0", 0)
        assert om.owner_of(9) is None
        assert om.tenants_of("w1") == (2,)
        assert om.epoch == 0
        om.fence("w0", 1, 2.0)
        assert om.is_fenced("w0", 0)
        assert not om.is_fenced("w0", 1)
        assert events == [
            "fleet_ownership_changed", "fleet_ownership_changed",
            "fleet_worker_fenced",
        ]
        doc = om.summary()
        json.dumps(doc)  # JSON-able contract (the /fleet/ownership body)
        assert doc["transition_count"] == 3

    def test_stale_epoch_assign_refuses_before_journaling(self):
        om = OwnershipMap(seed=0)
        om.assign("w0", (0,), 2, 1.0)
        n_obs = len(om.observations)
        with pytest.raises(FencingError):
            om.assign("w1", (1,), 1, 1.5)  # below the map's epoch
        om.fence("w2", 5, 2.0)
        with pytest.raises(FencingError):
            om.assign("w2", (3,), 3, 2.5)  # below w2's fence floor
        # refused ops never journaled: replay can't diverge on them
        assert len(om.observations) == n_obs + 1  # only the fence landed

    def test_replay_is_bit_identical(self):
        om = OwnershipMap(seed=42)
        om.assign("w0", (0, 1), 0, 1.0)
        om.assign("w1", (2, 3), 0, 1.25)
        om.fence("w0", 1, 2.0)
        om.assign("w1", (0, 1, 2, 3), 1, 2.5)
        om.assign("w0", (), 1, 2.5)
        again = OwnershipMap.replay(om.observations, seed=42)
        assert again.transition_digest() == om.transition_digest()
        assert [t.replay_key() for t in again.transitions] == [
            t.replay_key() for t in om.transitions
        ]
        other_seed = OwnershipMap.replay(om.observations, seed=43)
        assert other_seed.transition_digest() != om.transition_digest()


# ── the durability namespace + the fence ─────────────────────────────


class TestWorkerDurability:
    def test_shared_root_never_collides(self, tmp_path):
        """Satellite 2: two specs on ONE durability root get disjoint
        (worker id, epoch, tenant) namespaces."""
        d0 = WorkerDurability(
            tmp_path, "w0", epoch=0, tenants=(0,), fsync=False
        ).adopt()
        d1 = WorkerDurability(
            tmp_path, "w1", epoch=0, tenants=(0,), fsync=False
        ).adopt()
        with d0.wal(0).txn("op", {"who": "w0"}):
            pass
        with d1.wal(0).txn("op", {"who": "w1"}):
            pass
        p0 = tmp_path / "w0" / "epoch_0" / "tenant_0" / "wal.log"
        p1 = tmp_path / "w1" / "epoch_0" / "tenant_0" / "wal.log"
        assert p0 != p1 and p0.exists() and p1.exists()
        (r0,) = scan(p0).committed
        (r1,) = scan(p1).committed
        assert r0.args == {"who": "w0"} and r1.args == {"who": "w1"}

    def test_adopt_refuses_newer_epoch_loudly(self, tmp_path):
        WorkerDurability(
            tmp_path, "w0", epoch=4, tenants=(0,), fsync=False
        ).adopt()
        with pytest.raises(FencingError, match="epoch 4"):
            WorkerDurability(
                tmp_path, "w0", epoch=3, tenants=(0,), fsync=False
            ).adopt()
        # equal or newer adopters proceed (restart, then failover bump)
        WorkerDurability(
            tmp_path, "w0", epoch=4, tenants=(0,), fsync=False
        ).adopt()
        WorkerDurability(
            tmp_path, "w0", epoch=5, tenants=(0,), fsync=False
        ).adopt()

    def test_adopt_refuses_below_fence_floor(self, tmp_path):
        WorkerDurability.write_fence(tmp_path, "w0", 2)
        with pytest.raises(FencingError, match="fence floor 2"):
            WorkerDurability(
                tmp_path, "w0", epoch=1, tenants=(0,), fsync=False
            ).adopt()

    def test_fenced_append_writes_zero_bytes(self, tmp_path):
        d = WorkerDurability(
            tmp_path, "w0", epoch=0, tenants=(0,), fsync=False
        ).adopt()
        w = d.wal(0)
        with w.txn("before", {}):
            pass
        before = w.path.read_bytes()
        WorkerDurability.write_fence(tmp_path, "w0", 1)
        with pytest.raises(FencingError):
            with w.txn("zombie", {}):
                pass
        assert w.path.read_bytes() == before  # ZERO bytes reached disk
        assert w.fenced_appends == 1
        s = scan(w.path)
        assert [r.op for r in s.committed] == ["before"]

    def test_fenced_checkpoint_never_publishes(self, tmp_path):
        from hypervisor_tpu.state import HypervisorState

        d = WorkerDurability(
            tmp_path, "w0", epoch=0, tenants=(0,), fsync=False
        ).adopt()
        st = HypervisorState(SMALL)
        d.checkpoint(st, 0, step=1)
        WorkerDurability.write_fence(tmp_path, "w0", 1)
        with pytest.raises(FencingError):
            d.checkpoint(st, 0, step=2)
        steps = sorted(
            p.name for p in d.tenant_dir(0).iterdir()
            if p.name.startswith("step_")
        )
        assert steps == ["step_1"]  # the fenced save left nothing

    def test_fence_floors_only_rise_and_torn_fence_fails_closed(
        self, tmp_path
    ):
        WorkerDurability.write_fence(tmp_path, "w0", 3)
        WorkerDurability.write_fence(tmp_path, "w0", 1)  # ignored
        assert WorkerDurability.read_fence(tmp_path, "w0") == 3
        (tmp_path / "w0" / "FENCE").write_text("{torn garbag")
        assert WorkerDurability.read_fence(tmp_path, "w0") >= 1 << 62


# ── the reassignment property (satellite 3) ──────────────────────────


def _drive_tenant(st, tag: str, snap) -> int:
    """Pre-checkpoint workload for one arena tenant; returns nothing —
    the caller checkpoints. `snap()` records after every journaled op."""
    slot = st.create_session(
        f"s:{tag}", SessionConfig(min_sigma_eff=0.0), now=1.0
    )
    snap()
    st.enqueue_join(slot, f"did:{tag}:a", 0.8)
    snap()
    st.enqueue_join(slot, f"did:{tag}:b", 0.7)
    snap()
    st.flush_joins(now=2.0)
    snap()
    return slot


def _drive_tenant_suffix(st, tag: str, slot: int, snap) -> None:
    """The WAL suffix past the checkpoint."""
    a = st.agent_row(f"did:{tag}:a")["slot"]
    st.stage_delta(
        slot, a, ts=3.0, change_words=np.arange(4, dtype=np.uint32)
    )
    snap()
    st.flush_deltas()
    snap()
    st.terminate_sessions([slot], now=5.0)
    snap()


class TestReassignmentBitIdentity:
    def test_kill_at_every_wal_boundary_then_splice_elsewhere(
        self, tmp_path
    ):
        # ── the doomed worker: a 2-tenant arena, durable namespace ──
        arena = TenantArena(2, SMALL)
        dur = WorkerDurability(
            tmp_path / "root", "w-dead", epoch=0, tenants=(0, 1),
            fsync=False,
        ).adopt()
        snapshots: dict[int, dict] = {}

        def snap0():
            st = arena.tenants[0]
            snapshots[st.journal.last_seq] = _fingerprint(st)

        for t in (0, 1):
            arena.tenants[t].journal = dur.wal(t)
        slots = {}
        for t, tag in ((0, "t0"), (1, "t1")):
            st = arena.tenants[t]
            slots[t] = _drive_tenant(
                st, tag, snap0 if t == 0 else (lambda: None)
            )
        arena.sync()
        watermark = arena.tenants[0].journal.last_seq
        for t in (0, 1):
            dur.checkpoint(arena.tenants[t], t, step=1)
        for t, tag in ((0, "t0"), (1, "t1")):
            _drive_tenant_suffix(
                arena.tenants[t], tag, slots[t],
                snap0 if t == 0 else (lambda: None),
            )
        arena.sync()
        snap0()
        tip1 = _fingerprint(arena.tenants[1])
        for t in (0, 1):
            arena.tenants[t].journal.flush()

        # ── a DIFFERENT worker to splice into ──
        survivor = TenantArena(2, SMALL)
        raw = dur.tenant_dir(0).joinpath("wal.log").read_bytes()

        # One working copy of the dead worker's bundle whose tenant-0
        # WAL is rewritten per crash point.
        bundle = tmp_path / "bundle"
        shutil.copytree(dur.epoch_dir, bundle)
        torn_wal = bundle / "tenant_0" / "wal.log"

        boundaries = [0]
        for line in raw.splitlines(keepends=True):
            boundaries.append(boundaries[-1] + len(line))
        offsets = sorted(set(boundaries) | {b - 3 for b in boundaries[1:]})

        for off in offsets:
            torn_wal.write_bytes(raw[:off])
            committed = scan(torn_wal).committed
            expected_seq = max(
                max((r.seq for r in committed), default=0), watermark
            )
            back, report = recover_tenant(bundle, 0, config=SMALL)
            assert report["tenant"] == 0
            assert report["wal_records_replayed"] == len(
                [r for r in committed if r.seq > watermark]
            )
            # reassignment: the recovered tenant lands in ANOTHER
            # worker's arena slot; the comparison reads the SURVIVOR's
            # materialized view, so the splice itself is under test.
            survivor.splice_tenant(1, back)
            _assert_same(
                snapshots[expected_seq],
                _fingerprint(survivor.tenants[1]),
                ctx=f"(crash at byte {off}, seq {expected_seq})",
            )

        # the OTHER tenant recovers to tip independently — per-tenant
        # extraction never bleeds across tenant namespaces.
        back1, report1 = recover_tenant(bundle, 1, config=SMALL)
        survivor.splice_tenant(0, back1)
        _assert_same(
            tip1, _fingerprint(survivor.tenants[0]), ctx="(tenant 1 tip)"
        )
        with pytest.raises(Exception):
            recover_tenant(bundle, 7, config=SMALL)  # no such namespace

    def test_spliced_tenant_keeps_serving(self, tmp_path):
        """After a splice the survivor slot is a LIVE tenant: host ops
        and waves keep running on the adopted state."""
        donor = TenantArena(1, SMALL)
        dur = WorkerDurability(
            tmp_path, "w-d", epoch=0, tenants=(0,), fsync=False
        ).adopt()
        donor.tenants[0].journal = dur.wal(0)
        st = donor.tenants[0]
        slot = _drive_tenant(st, "live", lambda: None)
        donor.sync()
        dur.checkpoint(st, 0, step=1)
        back, _ = recover_tenant(dur.epoch_dir, 0, config=SMALL)

        survivor = TenantArena(2, SMALL)
        survivor.splice_tenant(1, back)
        adopted = survivor.tenants[1]
        assert adopted.agent_row("did:live:a")["slot"] >= 0
        s2 = adopted.create_session(
            "s:post-splice", SessionConfig(min_sigma_eff=0.0), now=6.0
        )
        adopted.enqueue_join(s2, "did:post", 0.9)
        assert (adopted.flush_joins(now=6.5) == 0).all()
        survivor.sync()
        assert adopted.agent_row("did:post")["slot"] >= 0
        assert slot != s2 or True  # slots may coincide; liveness is the pin

    def test_splice_refuses_capacity_mismatch(self, tmp_path):
        from hypervisor_tpu.fleet.worker import _small_capacity_config
        from hypervisor_tpu.state import HypervisorState

        other = HypervisorState(_small_capacity_config())
        arena = TenantArena(1, SMALL)
        with pytest.raises(ValueError, match="capacity"):
            arena.splice_tenant(0, other)
        with pytest.raises(ValueError, match="slot"):
            arena.splice_tenant(5, HypervisorState(SMALL))


# ── the failover controller drill ────────────────────────────────────


def _managed(tmp_path, wid, tenants, n_slots, config=SMALL, epoch=0):
    arena = TenantArena(n_slots, config)
    dur = WorkerDurability(
        tmp_path, wid, epoch=epoch, tenants=tenants, fsync=False
    ).adopt()
    slot_of = {}
    for slot, t in enumerate(tenants):
        arena.tenants[slot].journal = dur.wal(t)
        slot_of[t] = slot
    return ManagedWorker(
        wid, arena, dur, slot_of, list(range(len(tenants), n_slots))
    )


def _run_drill(tmp_path, seed=11):
    w0 = _managed(tmp_path, "w0", (0, 1), 2)
    w1 = _managed(tmp_path, "w1", (2,), 3)
    w2 = _managed(tmp_path, "w2", (3,), 3)
    slots = {}
    for t, slot in w0.slot_of.items():
        st = w0.arena.tenants[slot]
        slots[t] = _drive_tenant(st, f"d{t}", lambda: None)
    w0.arena.sync()
    for t, slot in w0.slot_of.items():
        w0.durability.checkpoint(w0.arena.tenants[slot], t, step=1)
    for t, slot in w0.slot_of.items():
        _drive_tenant_suffix(
            w0.arena.tenants[slot], f"d{t}", slots[t], lambda: None
        )
    w0.arena.sync()
    for slot in w0.slot_of.values():
        w0.arena.tenants[slot].journal.flush()

    om = OwnershipMap(seed=seed)
    ctl = FailoverController(om, config=SMALL)
    for w in (w0, w1, w2):
        ctl.register(w, now=0.0)
    report = ctl.failover("w0", now=10.0)
    return w0, w1, w2, om, ctl, report


class TestFailoverController:
    def test_drill_reassigns_fences_and_is_deterministic(self, tmp_path):
        w0, w1, w2, om, ctl, report = _run_drill(tmp_path / "a")
        # deficit-aware spread: the tie breaks to w1 by id, then w1's
        # load (2) exceeds w2's (1), so the second orphan spreads.
        assert report["tenants"][0]["survivor"] == "w1"
        assert report["tenants"][1]["survivor"] == "w2"
        assert report["replayed_ops"] > 0
        assert om.tenants_of("w0") == ()
        assert om.owner_of(0) == ("w1", 1)
        assert om.owner_of(1) == ("w2", 1)
        assert om.epoch == 1
        # survivors now durably own the spliced tenants
        for t, d in report["tenants"].items():
            mw = {"w1": w1, "w2": w2}[d["survivor"]]
            wal = mw.durability.tenant_dir(t) / "wal.log"
            assert wal.exists()
            assert (
                mw.durability.tenant_dir(t) / "latest" / ".done"
            ).exists()
        # the zombie is fenced at the durable boundary
        with pytest.raises(FencingError):
            with w0.durability.wal(0).txn("zombie", {}):
                pass
        # ... and the whole drill replays bit-identically
        _, _, _, om_b, _, report_b = _run_drill(tmp_path / "b")
        assert report_b["ownership_digest"] == report["ownership_digest"]
        assert OwnershipMap.replay(
            om.observations, seed=11
        ).transition_digest() == om.transition_digest()
        json.dumps(ctl.summary())  # the /fleet/failover body

    def test_no_spare_capacity_refuses(self, tmp_path):
        w0 = _managed(tmp_path, "w0", (0,), 1)
        w1 = _managed(tmp_path, "w1", (1,), 1)  # zero spare slots
        st = w0.arena.tenants[0]
        _drive_tenant(st, "full", lambda: None)
        w0.arena.sync()
        w0.durability.checkpoint(st, 0, step=1)
        om = OwnershipMap(seed=0)
        ctl = FailoverController(om, config=SMALL)
        ctl.register(w0, now=0.0)
        ctl.register(w1, now=0.0)
        with pytest.raises(FailoverError, match="spare"):
            ctl.failover("w0", now=1.0)

    def test_unknown_worker_refuses(self):
        ctl = FailoverController(OwnershipMap(seed=0))
        with pytest.raises(FailoverError, match="unknown"):
            ctl.failover("ghost", now=1.0)


# ── fleet-layer chaos scheduling ─────────────────────────────────────


class TestFleetChaos:
    def test_take_fleet_faults_is_seeded_and_once_only(self):
        plan = WaveChaosPlan(seed=5, fleet_faults=(
            InjectedFleetFault("worker_sigkill", at_round=2, worker="w0"),
            InjectedFleetFault("torn_checkpoint", at_round=4, worker="w1"),
            InjectedFleetFault("worker_sigstop", at_round=2, worker="w2"),
        ))
        inj = WaveChaosInjector(plan)
        assert inj.has_pending_fleet_faults
        assert inj.take_fleet_faults(1) == []
        due = inj.take_fleet_faults(2)
        assert sorted(f.kind for f in due) == [
            "worker_sigkill", "worker_sigstop",
        ]
        assert inj.take_fleet_faults(2) == []  # handed out exactly once
        (late,) = inj.take_fleet_faults(9)     # overdue faults still fire
        assert late.kind == "torn_checkpoint"
        assert not inj.has_pending_fleet_faults
        doc = inj.report()
        assert doc["fleet_faults_pending"] == 0
        assert [f["kind"] for f in doc["fleet_faults_taken"]] == [
            "worker_sigkill", "worker_sigstop", "torn_checkpoint",
        ]
        # adding fleet faults never perturbs the wave-layer schedule
        bare = WaveChaosInjector(WaveChaosPlan(seed=5, fail_rate=0.3))
        with_faults = WaveChaosInjector(
            WaveChaosPlan(seed=5, fail_rate=0.3, fleet_faults=(
                InjectedFleetFault(),
            ))
        )

        def sched(i):
            out = []
            for _ in range(32):
                try:
                    i.on_dispatch("governance_wave")
                    out.append(0)
                except Exception:
                    out.append(1)
            return out

        assert sched(bare) == sched(with_faults)


# ── API surface ──────────────────────────────────────────────────────


class TestFailoverApi:
    def _svc(self):
        from hypervisor_tpu.api.service import HypervisorService

        return HypervisorService()

    def test_routes_registered_on_the_shared_table(self):
        from hypervisor_tpu.api.server import ROUTES

        paths = {r[1] for r in ROUTES}
        assert "/fleet/ownership" in paths
        assert "/fleet/failover" in paths

    def test_503_without_fleet_then_without_plane(self):
        from hypervisor_tpu.api.service import ApiError
        from hypervisor_tpu.fleet import FleetObservatory

        svc = self._svc()
        for call in (svc.fleet_ownership(), svc.fleet_failover()):
            with pytest.raises(ApiError) as ei:
                asyncio.run(call)
            assert ei.value.status == 503
        svc.fleet = FleetObservatory({})
        with pytest.raises(ApiError, match="ownership"):
            asyncio.run(svc.fleet_ownership())
        with pytest.raises(ApiError, match="failover"):
            asyncio.run(svc.fleet_failover())

    def test_attached_planes_serve_their_summaries(self):
        from hypervisor_tpu.fleet import FleetObservatory

        svc = self._svc()
        svc.fleet = FleetObservatory({})
        om = OwnershipMap(seed=9)
        om.assign("w0", (0,), 0, 1.0)
        svc.fleet.ownership = om
        svc.fleet.failover = FailoverController(om)
        doc = asyncio.run(svc.fleet_ownership())
        assert doc["owners"]["w0"]["tenants"] == [0]
        assert doc["transition_digest"] == om.transition_digest()
        doc2 = asyncio.run(svc.fleet_failover())
        assert doc2["epoch"] == 0 and doc2["reassignments"] == []
        json.dumps(doc) and json.dumps(doc2)


# ── graceful drain (satellite 1) ─────────────────────────────────────


class TestGracefulDrain:
    def test_sigterm_drain_hands_off_with_zero_replay(self, tmp_path):
        """SIGTERM → the worker flushes its WALs, publishes final
        per-tenant checkpoints + `.done`, prints the DRAINED marker, and
        exits 0; the adopter's recovery replays ZERO WAL records."""
        from hypervisor_tpu.fleet import FleetSupervisor, WorkerSpec
        from hypervisor_tpu.fleet.worker import _small_capacity_config

        spec = WorkerSpec(
            worker_id="w0", tenants=(0, 1),
            durability_root=str(tmp_path), epoch=0,
        )
        sup = FleetSupervisor([spec])
        sup.start()
        try:
            marker = sup.drain("w0")
        finally:
            sup.stop()
        assert marker is not None
        assert marker["worker_id"] == "w0"
        assert set(marker["tenants"]) == {"0", "1"}
        cfg = _small_capacity_config()
        for t in (0, 1):
            wal_seq = marker["tenants"][str(t)]["wal_seq"]
            assert wal_seq > 0  # warm rounds DID journal
            _, report = recover_tenant(
                tmp_path / "w0" / "epoch_0", t, config=cfg
            )
            assert report["wal_records_replayed"] == 0
            assert report["wal_watermark_seq"] == wal_seq
