"""Extended VFS coverage: attribution detail, ACL semantics, snapshot edges.

Complements tests/unit/test_vfs.py toward the reference's depth
(`tests/unit/test_vfs_substrate.py` in /root/reference, its largest unit
suite): hash-chain attribution, permission enforcement across all verbs,
restore-as-rollback semantics, and SSO-integrated snapshots.
"""

from __future__ import annotations

import hashlib

import pytest

from hypervisor_tpu import (
    SessionConfig,
    SessionVFS,
    SharedSessionObject,
    VFSPermissionError,
)


@pytest.fixture
def vfs():
    return SessionVFS("session:ext")


class TestAttribution:
    def test_edit_content_hash_is_sha256_of_content(self, vfs):
        edit = vfs.write("/a.txt", "payload", "did:w")
        assert edit.content_hash == hashlib.sha256(b"payload").hexdigest()

    def test_update_edit_links_previous_hash(self, vfs):
        first = vfs.write("/a.txt", "v1", "did:w")
        second = vfs.write("/a.txt", "v2", "did:w")
        assert second.operation == "update"
        assert second.previous_hash == first.content_hash

    def test_delete_edit_records_previous_hash(self, vfs):
        first = vfs.write("/a.txt", "v1", "did:w")
        edit = vfs.delete("/a.txt", "did:w")
        assert edit.operation == "delete"
        assert edit.previous_hash == first.content_hash

    def test_file_hash_tracks_latest_content(self, vfs):
        vfs.write("/a.txt", "v1", "did:w")
        h1 = vfs.file_hash("/a.txt")
        vfs.write("/a.txt", "v2", "did:w")
        assert vfs.file_hash("/a.txt") != h1
        assert vfs.file_hash("/missing") is None

    def test_edits_by_agent_partitions_log(self, vfs):
        vfs.write("/a.txt", "1", "did:alice")
        vfs.write("/b.txt", "2", "did:bob")
        vfs.write("/a.txt", "3", "did:alice")
        assert len(vfs.edits_by_agent("did:alice")) == 2
        assert len(vfs.edits_by_agent("did:bob")) == 1
        assert vfs.edits_by_agent("did:nobody") == []

    def test_permission_change_is_logged(self, vfs):
        vfs.write("/a.txt", "1", "did:alice")
        vfs.set_permissions("/a.txt", ["did:alice"], "did:alice")
        assert vfs.edit_log[-1].operation == "permission"


class TestPermissions:
    def test_read_with_agent_enforces_acl(self, vfs):
        vfs.write("/secret", "x", "did:owner")
        vfs.set_permissions("/secret", ["did:owner"], "did:owner")
        with pytest.raises(VFSPermissionError):
            vfs.read("/secret", agent_did="did:intruder")

    def test_read_without_agent_is_system_level(self, vfs):
        # agent-less reads are the framework's own (snapshots, GC) and
        # bypass the ACL
        vfs.write("/secret", "x", "did:owner")
        vfs.set_permissions("/secret", ["did:owner"], "did:owner")
        assert vfs.read("/secret") == "x"

    def test_delete_respects_acl(self, vfs):
        vfs.write("/secret", "x", "did:owner")
        vfs.set_permissions("/secret", ["did:owner"], "did:owner")
        with pytest.raises(VFSPermissionError):
            vfs.delete("/secret", "did:intruder")
        assert vfs.read("/secret") == "x"

    def test_allowed_agent_full_verb_access(self, vfs):
        vfs.write("/shared", "x", "did:a")
        vfs.set_permissions("/shared", ["did:a", "did:b"], "did:a")
        vfs.write("/shared", "y", "did:b")
        assert vfs.read("/shared", agent_did="did:b") == "y"
        vfs.delete("/shared", "did:b")

    def test_get_permissions_returns_copy(self, vfs):
        vfs.write("/p", "x", "did:a")
        vfs.set_permissions("/p", ["did:a"], "did:a")
        perms = vfs.get_permissions("/p")
        perms.add("did:mallory")
        assert "did:mallory" not in vfs.get_permissions("/p")

    def test_open_path_reports_no_acl(self, vfs):
        vfs.write("/open", "x", "did:a")
        assert vfs.get_permissions("/open") is None


class TestSnapshotEdges:
    def test_custom_snapshot_id_round_trip(self, vfs):
        vfs.write("/a", "1", "did:w")
        sid = vfs.create_snapshot("snap:manual")
        assert sid == "snap:manual"
        assert "snap:manual" in vfs.list_snapshots()

    def test_restore_drops_files_created_after_snapshot(self, vfs):
        vfs.write("/old", "1", "did:w")
        sid = vfs.create_snapshot()
        vfs.write("/new", "2", "did:w")
        vfs.restore_snapshot(sid, "did:w")
        assert vfs.read("/old") == "1"
        assert vfs.read("/new") is None

    def test_restore_reverts_acl(self, vfs):
        vfs.write("/f", "1", "did:w")
        sid = vfs.create_snapshot()
        vfs.set_permissions("/f", ["did:w"], "did:w")
        vfs.restore_snapshot(sid, "did:w")
        assert vfs.get_permissions("/f") is None

    def test_snapshot_count_tracks_create_delete(self, vfs):
        a = vfs.create_snapshot()
        b = vfs.create_snapshot()
        assert vfs.snapshot_count == 2
        vfs.delete_snapshot(a)
        assert vfs.snapshot_count == 1
        assert vfs.list_snapshots() == [b]

    def test_delete_unknown_snapshot_raises(self, vfs):
        with pytest.raises(KeyError):
            vfs.delete_snapshot("snap:ghost")

    def test_snapshots_share_blobs_not_copies(self, vfs):
        # blob store is content-addressed: a snapshot must not duplicate
        # content, only the path->hash tree
        big = "x" * 10_000
        vfs.write("/big", big, "did:w")
        vfs.create_snapshot()
        vfs.write("/big", big + "y", "did:w")
        assert len(vfs._blobs) == 2  # two distinct contents, ever


class TestSSOVFSIntegration:
    def _active_sso(self):
        sso = SharedSessionObject(config=SessionConfig(), creator_did="did:c")
        sso.begin_handshake()
        sso.join("did:a", sigma_raw=0.8, sigma_eff=0.8)
        sso.activate()
        return sso

    def test_session_files_live_under_namespace(self):
        sso = self._active_sso()
        sso.vfs.write("/notes", "hello", "did:a")
        assert sso.vfs.namespace.startswith("/sessions/session:")
        assert sso.vfs.list_files() == ["/notes"]

    def test_terminated_session_rejects_snapshot(self):
        sso = self._active_sso()
        sso.terminate()
        with pytest.raises(Exception):
            sso.create_snapshot()

    def test_two_sessions_never_share_files(self):
        a, b = self._active_sso(), self._active_sso()
        a.vfs.write("/only-in-a", "1", "did:a")
        assert b.vfs.read("/only-in-a") is None


class TestNamespaceAndInventory:
    """Discrete reference behaviors (`test_vfs_substrate.py`) not covered
    by the merged scenarios above."""

    def test_list_files_and_count(self, vfs):
        assert vfs.list_files() == [] and vfs.file_count == 0
        vfs.write("/a.md", "1", "did:w")
        vfs.write("/b/c.md", "2", "did:w")
        assert sorted(vfs.list_files()) == ["/a.md", "/b/c.md"]
        assert vfs.file_count == 2
        vfs.delete("/a.md", "did:w")
        assert vfs.list_files() == ["/b/c.md"] and vfs.file_count == 1

    def test_custom_namespace(self):
        from hypervisor_tpu.session.vfs import SessionVFS

        vfs = SessionVFS("session:x", namespace="/tenants/acme")
        vfs.write("/doc", "hi", "did:w")
        assert vfs.namespace == "/tenants/acme"
        assert vfs.read("/doc") == "hi"
        assert vfs.list_files() == ["/doc"]

    def test_absolute_path_within_namespace_resolves(self, vfs):
        vfs.write("/plan.md", "v1", "did:w")
        absolute = f"{vfs.namespace}/plan.md"
        assert vfs.read(absolute) == "v1"

    def test_edits_by_agent_empty(self, vfs):
        vfs.write("/x", "1", "did:w")
        assert vfs.edits_by_agent("did:ghost") == []

    def test_snapshot_of_empty_vfs_restores_empty(self, vfs):
        snap = vfs.create_snapshot()
        vfs.write("/later", "x", "did:w")
        vfs.restore_snapshot(snap, "did:w")
        assert vfs.file_count == 0

    def test_multiple_snapshots_restore_independently(self, vfs):
        vfs.write("/f", "one", "did:w")
        s1 = vfs.create_snapshot()
        vfs.write("/f", "two", "did:w")
        s2 = vfs.create_snapshot()
        vfs.write("/f", "three", "did:w")
        vfs.restore_snapshot(s1, "did:w")
        assert vfs.read("/f") == "one"
        vfs.restore_snapshot(s2, "did:w")
        assert vfs.read("/f") == "two"

    def test_restore_through_sso_requires_active(self):
        import pytest

        from hypervisor_tpu.models import SessionConfig
        from hypervisor_tpu.session import (
            SessionLifecycleError,
            SharedSessionObject,
        )

        sso = SharedSessionObject(SessionConfig(), creator_did="did:c")
        sso.begin_handshake()
        sso.join("did:a", sigma_raw=0.8, sigma_eff=0.8)
        sso.activate()
        snap = sso.create_vfs_snapshot()
        sso.terminate()
        with pytest.raises(SessionLifecycleError):
            sso.restore_vfs_snapshot(snap, "did:a")
