"""Structural collective census of the fused governance wave, pinned.

The census is environment-independent: the same shard_map program
lowers to the same collective structure on any backend — only link
bandwidth changes. Round 4 shipped the fused wave at 9-12 all-reduces;
round 5 fused the payloads down to 4, which is the structural floor
given the data dependencies:

  1. the slot→session wave map psum (edges on any shard need the full
     map before contributions can be scored),
  2. the vouched-contribution psum (depends on 1),
  3. the admission session-count psum (depends on 2 via sigma_eff; the
     terminate membership mask rides this one as a stacked row on the
     non-contiguous path),
  4. the post-terminate fold (FSM owned/state/terminated rows + the
     released-bond total, stacked [4, S] — depends on the terminate
     release which depends on 3).

A regression here means someone added a collective without folding it
into an existing payload — wall-clock on ICI is latency-bound at wave
sizes, so every extra all-reduce is a full link round-trip.
"""

from __future__ import annotations

import re

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hypervisor_tpu.models import SessionState
from hypervisor_tpu.parallel import make_mesh
from hypervisor_tpu.parallel.collectives import sharded_governance_wave
from hypervisor_tpu.tables.state import AgentTable, SessionTable, VouchTable
from hypervisor_tpu.tables.struct import replace as t_replace

N_DEV = 4
ROWS = 8  # agent rows per shard


def _census(compiled, op: str) -> int:
    txt = compiled.as_text()
    return len(re.findall(re.escape(op) + r"[-.\"( ]", txt))


def _lowering_is_census_faithful() -> bool:
    """Capability probe: does ONE psum lower to ONE all-reduce here?

    The census pins the fused wave's structural collective count, which
    only means anything when the shard_map lowering is 1:1 — older jax
    (observed on 0.4.37: a single psum compiles to 2 all-reduce ops,
    two psums to 6) multiplies collectives in the compiled text, so the
    structural floor is unreachable REGARDLESS of program structure.
    Probing the actual lowering is honest where a version pin would
    guess: any jax that lowers 1:1 runs the census.
    """
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(N_DEV, platform="cpu")
    probe = jax.jit(
        shard_map(
            lambda x: jax.lax.psum(x, "agents"),
            mesh=mesh, in_specs=P("agents"), out_specs=P(),
        )
    )
    compiled = probe.lower(jnp.zeros((2 * N_DEV,), jnp.float32)).compile()
    return _census(compiled, "all-reduce") == 1


_census_faithful = pytest.mark.skipif(
    not _lowering_is_census_faithful(),
    reason=(
        "this jax's shard_map lowering emits >1 all-reduce per psum "
        "(capability probe); the structural census floor is "
        "unreachable here regardless of program structure"
    ),
)


def _wave_world(one_join_per_session: bool):
    b = 2 * N_DEV
    k = b if one_join_per_session else N_DEV
    agents = AgentTable.create(ROWS * N_DEV)
    sessions = SessionTable.create(2 * k)
    ws = jnp.arange(k)
    sessions = t_replace(
        sessions,
        state=sessions.state.at[ws].set(
            jnp.int8(SessionState.HANDSHAKING.code)
        ),
        max_participants=sessions.max_participants.at[ws].set(32),
        min_sigma_eff=sessions.min_sigma_eff.at[ws].set(0.0),
    )
    vouches = VouchTable.create(4 * N_DEV)
    per = b // N_DEV
    slots = jnp.asarray(
        [(i // per) * ROWS + (i % per) for i in range(b)], jnp.int32
    )
    sess_of = (
        jnp.arange(b, dtype=jnp.int32)
        if one_join_per_session
        else jnp.arange(b, dtype=jnp.int32) % k
    )
    bodies = jnp.asarray(
        np.random.RandomState(0).randint(
            0, 2**32, size=(2, k, 12), dtype=np.uint64
        ).astype(np.uint32)
    )
    return (
        agents, sessions, vouches, slots,
        jnp.arange(b, dtype=jnp.int32), sess_of,
        jnp.full((b,), 0.8, jnp.float32), jnp.ones((b,), bool),
        jnp.zeros((b,), bool), ws, bodies, 0.0, 0.5,
    ), b, k


class TestFusedWaveCensus:
    @_census_faithful
    def test_fastpath_wave_is_four_allreduces_zero_gathers(self):
        mesh = make_mesh(N_DEV, platform="cpu")
        args, b, k = _wave_world(one_join_per_session=True)
        fn = sharded_governance_wave(
            mesh, contiguous_waves=True, unique_sessions=True
        )
        compiled = fn.lower(
            *args, jnp.asarray(0, jnp.int32), jnp.asarray(k, jnp.int32)
        ).compile()
        assert _census(compiled, "all-reduce") <= 4
        assert _census(compiled, "all-gather") == 0
        assert _census(compiled, "all-to-all") == 0

    @_census_faithful
    def test_mask_terminate_wave_adds_no_extra_allreduce(self):
        """The non-contiguous path's terminate membership mask must ride
        the admission count psum (fold_extra), not its own collective."""
        mesh = make_mesh(N_DEV, platform="cpu")
        args, b, k = _wave_world(one_join_per_session=False)
        compiled = sharded_governance_wave(mesh).lower(*args).compile()
        assert _census(compiled, "all-reduce") <= 4


class TestDispatchStructure:
    def test_admit_row_blocks_lower_without_per_column_updates(self):
        """Round-5 dispatch fusion: the admission row blocks build as
        one stack per dtype. A regression to chained `.at[:, i].set`
        column writes shows up as dynamic-update-slice ops in the
        lowered HLO (each was its own TPU dispatch — admission carried
        7 of them before the fix)."""
        from hypervisor_tpu.ops.admission import admit_row_blocks

        b = 64
        compiled = (
            jax.jit(admit_row_blocks)
            .lower(
                jnp.zeros((b,), jnp.int32),
                jnp.zeros((b,), jnp.int32),
                jnp.zeros((b,), jnp.float32),
                jnp.zeros((b,), jnp.float32),
                jnp.float32(1.0),
            )
            .compile()
        )
        assert _census(compiled, "dynamic-update-slice") == 0
