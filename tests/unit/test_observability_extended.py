"""Extended observability coverage: wildcard pub/sub, time-range and
multi-filter queries, causal-trace ancestry.

Complements tests/unit/test_observability.py toward the reference's depth
(`tests/unit/test_observability.py`, 22 tests in /root/reference).
"""

from __future__ import annotations

from datetime import timedelta

from hypervisor_tpu import (
    CausalTraceId,
    EventType,
    HypervisorEvent,
    HypervisorEventBus,
)
from hypervisor_tpu.utils.clock import utc_now


def _ev(etype=EventType.SESSION_CREATED, sid=None, did=None, **payload):
    return HypervisorEvent(
        event_type=etype, session_id=sid, agent_did=did, payload=payload
    )


class TestPubSub:
    def test_wildcard_subscriber_sees_every_type(self):
        bus = HypervisorEventBus()
        seen = []
        bus.subscribe(handler=seen.append)
        bus.emit(_ev(EventType.SESSION_CREATED))
        bus.emit(_ev(EventType.SLASH_EXECUTED))
        assert [e.event_type for e in seen] == [
            EventType.SESSION_CREATED,
            EventType.SLASH_EXECUTED,
        ]

    def test_typed_subscriber_filters(self):
        bus = HypervisorEventBus()
        slashes = []
        bus.subscribe(EventType.SLASH_EXECUTED, slashes.append)
        bus.emit(_ev(EventType.SESSION_CREATED))
        bus.emit(_ev(EventType.SLASH_EXECUTED))
        assert len(slashes) == 1

    def test_typed_and_wildcard_both_fire(self):
        bus = HypervisorEventBus()
        hits = []
        bus.subscribe(EventType.SESSION_CREATED, lambda e: hits.append("typed"))
        bus.subscribe(handler=lambda e: hits.append("wild"))
        bus.emit(_ev(EventType.SESSION_CREATED))
        assert sorted(hits) == ["typed", "wild"]


class TestQueries:
    def test_time_range_query(self):
        bus = HypervisorEventBus()
        start = utc_now() - timedelta(seconds=1)
        bus.emit(_ev())
        bus.emit(_ev())
        assert len(bus.query_by_time_range(start)) == 2
        future = utc_now() + timedelta(seconds=5)
        assert bus.query_by_time_range(future) == []

    def test_query_combines_type_and_session(self):
        bus = HypervisorEventBus()
        bus.emit(_ev(EventType.SESSION_JOINED, sid="s1", did="a"))
        bus.emit(_ev(EventType.SESSION_JOINED, sid="s2", did="a"))
        bus.emit(_ev(EventType.SLASH_EXECUTED, sid="s1", did="a"))
        got = bus.query(event_type=EventType.SESSION_JOINED, session_id="s1")
        assert len(got) == 1 and got[0].session_id == "s1"

    def test_query_combines_session_and_agent(self):
        bus = HypervisorEventBus()
        bus.emit(_ev(sid="s1", did="a"))
        bus.emit(_ev(sid="s1", did="b"))
        got = bus.query(session_id="s1", agent_did="b")
        assert len(got) == 1 and got[0].agent_did == "b"

    def test_query_limit_returns_most_recent(self):
        bus = HypervisorEventBus()
        for i in range(5):
            bus.emit(_ev(payload_idx=i))
        got = bus.query(limit=2)
        assert [e.payload["payload_idx"] for e in got] == [3, 4]

    def test_payload_round_trips_through_to_dict(self):
        ev = _ev(EventType.VOUCH_CREATED, sid="s", did="a", bond=0.16)
        d = ev.to_dict()
        assert d["event_type"] == EventType.VOUCH_CREATED.value
        assert d["payload"] == {"bond": 0.16}


class TestCausalTrace:
    def test_is_ancestor_of_descendant(self):
        root = CausalTraceId.new_root() if hasattr(CausalTraceId, "new_root") else CausalTraceId(trace_id="t", span_id="s0")
        child = root.child()
        grandchild = child.child()
        assert root.is_ancestor_of(child)
        assert root.is_ancestor_of(grandchild)
        assert not child.is_ancestor_of(root)

    def test_sibling_not_ancestor(self):
        root = CausalTraceId(trace_id="t", span_id="s0")
        a = root.child()
        b = a.sibling()
        assert not a.is_ancestor_of(b)
        assert a.depth == b.depth

    def test_different_traces_unrelated(self):
        a = CausalTraceId(trace_id="t1", span_id="s")
        b = CausalTraceId(trace_id="t2", span_id="s").child()
        assert not a.is_ancestor_of(b)

    def test_event_carries_causal_ids(self):
        trace = CausalTraceId(trace_id="t", span_id="s0")
        ev = HypervisorEvent(
            event_type=EventType.SESSION_CREATED,
            causal_trace_id=str(trace),
            parent_event_id="parent123",
        )
        assert ev.to_dict()["causal_trace_id"] == str(trace)
        assert ev.to_dict()["parent_event_id"] == "parent123"


class TestProfilingHooks:
    def test_capture_writes_a_trace(self, tmp_path):
        import numpy as np
        import jax.numpy as jnp

        from hypervisor_tpu.observability import profiling

        log_dir = str(tmp_path / "trace")
        assert not profiling.is_active()
        with profiling.capture(log_dir):
            assert profiling.is_active()
            with profiling.span("test.wave"):
                jnp.asarray(np.arange(8)).sum().block_until_ready()
        assert not profiling.is_active()
        # A trace directory with at least one event file appeared.
        import os

        found = [
            os.path.join(dp, f)
            for dp, _, fns in os.walk(log_dir)
            for f in fns
        ]
        assert found, "no trace files written"

    def test_nested_capture_is_noop(self, tmp_path):
        from hypervisor_tpu.observability import profiling

        outer = str(tmp_path / "outer")
        with profiling.capture(outer):
            # Inner capture must not truncate the outer trace.
            with profiling.capture(str(tmp_path / "inner")):
                assert profiling.is_active()
            assert profiling.is_active()
        assert not profiling.is_active()
        assert profiling.stop() is None  # nothing left to stop

    def test_span_without_capture_is_safe(self):
        from hypervisor_tpu.observability import profiling

        with profiling.span("idle"):
            pass
        with profiling.step_span("tick", step=3):
            pass
