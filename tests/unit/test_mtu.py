"""Merkle Tree Unit + incremental frontier (ISSUE 7).

Pins, in one place:

* MTU-vs-reference sha256 bit-identity — the Pallas kernels' exact
  math (numpy twins `tree_roots_np` / `chain_digests_np`, same code
  the Mosaic kernel compiles) against the pure-XLA formulations and
  the reference host loop, across lane counts and odd tail sizes;
* the tree unit's HOST dispatch (native C++ on CPU) against the same
  references, including tamper detection;
* frontier == batch-recompute root equivalence as a hypothesis
  property over random append / wrap / restore sequences, including a
  checkpoint/restore of the frontier mid-stream;
* the O(log n) incremental-update acceptance bound as a HASH-COUNT
  assertion (never wall clock);
* the `HV_SHA256_PALLAS` per-call env arming (satellite);
* the packed-body cache per (session, turn-range) + wrap invalidation
  (satellite);
* the scrubber's native strip path vs its jitted path.
"""

from __future__ import annotations

import dataclasses
import json
import math

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

try:  # hypothesis drives the property walks where available (CI
    # image); the seeded twins below keep the same properties pinned
    # in environments without it.
    from hypothesis import given, settings, strategies as st

    HAS_HYPOTHESIS = True
except ImportError:  # pragma: no cover - environment-dependent
    HAS_HYPOTHESIS = False

from hypervisor_tpu.audit.commitment import CommitmentEngine
from hypervisor_tpu.audit.delta import merkle_root_host
from hypervisor_tpu.audit.frontier import MerkleFrontier
from hypervisor_tpu.config import DEFAULT_CONFIG
from hypervisor_tpu.kernels import mtu_pallas as mtu
from hypervisor_tpu.models import SessionConfig
from hypervisor_tpu.ops import merkle as merkle_ops
from hypervisor_tpu.ops import sha256 as sha_ops
from hypervisor_tpu.state import HypervisorState


def _leaves(rng, s, p):
    return rng.randint(0, 2**32, (s, p, 8), dtype=np.uint64).astype(np.uint32)


def _ref_roots(leaves, counts):
    """Reference roots via the host hex loop (the semantics anchor)."""
    s = leaves.shape[0]
    counts = np.broadcast_to(np.asarray(counts), (s,))
    out = np.zeros((s, 8), np.uint32)
    for i in range(s):
        c = int(counts[i])
        if c == 0:
            out[i] = leaves[i, 0]
            continue
        hexes = sha_ops.digests_to_hex(leaves[i, :c])
        out[i] = sha_ops.hex_to_words([merkle_root_host(hexes)])[0]
    return out


class TestMTUBitIdentity:
    """The kernel math (numpy twins) and every dispatch tier agree."""

    @pytest.mark.parametrize("p", [2, 4, 16, 64])
    def test_tree_twin_matches_reference_across_odd_tails(self, p):
        rng = np.random.RandomState(p)
        s = 3
        leaves = _leaves(rng, s, p)
        # Odd tails on purpose: 1, a mid odd count, p-1, p.
        for c in sorted({1, max(1, p // 2 - 1), p - 1, p}):
            ref = _ref_roots(leaves, c)
            xla = np.asarray(
                merkle_ops.merkle_root_lanes(
                    jnp.asarray(leaves), jnp.int32(c), use_pallas=False
                )
            )
            twin = mtu.tree_roots_np(leaves, c)
            np.testing.assert_array_equal(xla, ref)
            np.testing.assert_array_equal(twin, ref)

    @pytest.mark.parametrize("s", [1, 2, 5])
    def test_tree_twin_across_lane_counts(self, s):
        rng = np.random.RandomState(40 + s)
        p = 16
        leaves = _leaves(rng, s, p)
        counts = rng.randint(1, p + 1, s).astype(np.int32)
        np.testing.assert_array_equal(
            mtu.tree_roots_np(leaves, counts), _ref_roots(leaves, counts)
        )

    def test_tree_host_dispatch_matches_reference(self):
        rng = np.random.RandomState(7)
        leaves = _leaves(rng, 4, 32)
        counts = np.array([1, 9, 31, 32], np.int32)
        ref = _ref_roots(leaves, counts)
        host = merkle_ops.tree_roots_host(leaves, counts, use_pallas=False)
        np.testing.assert_array_equal(host, ref)
        # merkle_root (single-tree wrapper) folds through the same path.
        one = np.asarray(
            merkle_ops.merkle_root(
                jnp.asarray(leaves[1]), jnp.int32(9), use_pallas=False
            )
        )
        np.testing.assert_array_equal(one, ref[1])

    @pytest.mark.parametrize("t,l", [(1, 1), (3, 2), (7, 5)])
    def test_chain_twin_matches_scan(self, t, l):
        rng = np.random.RandomState(t * 10 + l)
        bodies = rng.randint(
            0, 2**32, (t, l, merkle_ops.BODY_WORDS), dtype=np.uint64
        ).astype(np.uint32)
        seeds = rng.randint(0, 2**32, (l, 8), dtype=np.uint64).astype(np.uint32)
        ref = np.asarray(
            merkle_ops.chain_digests(
                jnp.asarray(bodies), jnp.asarray(seeds), use_pallas=False
            )
        )
        np.testing.assert_array_equal(mtu.chain_digests_np(bodies, seeds), ref)

    def test_verify_chain_digests_host_counts_and_tamper(self):
        rng = np.random.RandomState(3)
        t, l = 6, 4
        bodies = rng.randint(
            0, 2**32, (t, l, merkle_ops.BODY_WORDS), dtype=np.uint64
        ).astype(np.uint32)
        recorded = np.asarray(
            merkle_ops.chain_digests(jnp.asarray(bodies), use_pallas=False)
        )
        counts = np.array([6, 3, 1, 0], np.int32)
        assert merkle_ops.verify_chain_digests_host(
            bodies, recorded, counts, use_pallas=False
        ).all()
        bad = recorded.copy()
        bad[4, 0, 2] ^= 1  # beyond lane 1's count, inside lane 0's
        got = merkle_ops.verify_chain_digests_host(
            bodies, bad, counts, use_pallas=False
        )
        assert list(got) == [False, True, True, True]

    def test_verify_chain_links_host_matches_jitted(self):
        rng = np.random.RandomState(9)
        c = 12
        bodies = rng.randint(
            0, 2**32, (c, 1, merkle_ops.BODY_WORDS), dtype=np.uint64
        ).astype(np.uint32)
        digests = np.asarray(
            merkle_ops.chain_digests(jnp.asarray(bodies), use_pallas=False)
        )[:, 0]
        body_col, digest_col = bodies[:, 0], digests.copy()
        digest_col[7] ^= 2  # tamper one interior digest
        rows = np.arange(c, dtype=np.int64)
        prev = np.concatenate([[0], rows[:-1]])
        use_seed = rows == 0
        valid = np.ones(c, bool)
        valid[5] = False
        host = merkle_ops.verify_chain_links_host(
            body_col, digest_col, rows, prev, use_seed, valid
        )
        jitted = np.asarray(
            merkle_ops.verify_chain_links(
                jnp.asarray(body_col),
                jnp.asarray(digest_col),
                jnp.asarray(rows, jnp.int32),
                jnp.asarray(prev, jnp.int32),
                jnp.asarray(use_seed),
                jnp.asarray(valid),
                use_pallas=False,
            )
        )
        np.testing.assert_array_equal(host, jitted)
        assert not host[7] and not host[8]  # link 8's parent is tampered too
        assert host[5]  # invalid lanes always pass


def _check_prefix_property(seed: int, n: int) -> None:
    rng = np.random.RandomState(seed)
    leaves = rng.randint(0, 2**32, (n, 8), dtype=np.uint64).astype(np.uint32)
    fr = MerkleFrontier()
    for i in range(n):
        fr.append(leaves[i])
        # Mid-stream serialization round-trip must be lossless.
        if i == n // 2:
            fr = MerkleFrontier.from_meta(json.loads(json.dumps(fr.to_meta())))
        assert fr.root_hex() == merkle_root_host(
            sha_ops.digests_to_hex(leaves[: i + 1])
        )
    assert fr.count == n


class TestFrontier:
    @pytest.mark.parametrize("seed,n", [(0, 1), (1, 17), (2, 64), (3, 97)])
    def test_root_equals_batch_recompute_at_every_prefix(self, seed, n):
        _check_prefix_property(seed, n)

    if HAS_HYPOTHESIS:

        @given(st.integers(0, 2**31 - 1), st.integers(1, 200))
        @settings(max_examples=30, deadline=None)
        def test_prefix_property_hypothesis(self, seed, n):
            _check_prefix_property(seed, n)

    def test_incremental_update_is_olog_n_hashes(self):
        """The acceptance bound: append + root <= O(log n) HASHES,
        pinned by the frontier's own combine counter."""
        rng = np.random.RandomState(0)
        fr = MerkleFrontier()
        for n in range(1, 1100):
            before = fr.hash_count
            fr.append(
                rng.randint(0, 2**32, 8, dtype=np.uint64).astype(np.uint32)
            )
            assert fr.root_hex() is not None
            spent = fr.hash_count - before
            bound = 3 * math.ceil(math.log2(n + 1)) + 2
            assert spent <= bound, (n, spent, bound)
        # And cumulatively nowhere near the O(n^2)/O(n log n) of
        # re-hashing history per append.
        assert fr.hash_count < 1100 * (3 * 11 + 2)

    def test_commit_and_verify_frontier(self):
        rng = np.random.RandomState(5)
        leaves = rng.randint(0, 2**32, (9, 8), dtype=np.uint64).astype(np.uint32)
        fr = MerkleFrontier.from_leaf_digests(leaves)
        eng = CommitmentEngine()
        rec = eng.commit_frontier("s:x", fr, ["did:a"])
        assert rec.delta_count == 9
        assert rec.merkle_root == merkle_root_host(
            sha_ops.digests_to_hex(leaves)
        )
        assert eng.verify_frontier("s:x", fr)
        assert eng.verify_device_root("s:x", fr.root_words())
        with pytest.raises(ValueError):
            eng.commit_frontier("s:y", MerkleFrontier(), [])


def _small_log_state(log_cap=16):
    cfg = dataclasses.replace(
        DEFAULT_CONFIG,
        capacity=dataclasses.replace(
            DEFAULT_CONFIG.capacity, delta_log_capacity=log_cap
        ),
    )
    return HypervisorState(cfg), cfg


def _stage_one(state, slot, rng, t):
    state.stage_delta(
        slot, 0, ts=float(t),
        change_words=rng.randint(0, 2**32, 8, dtype=np.uint64).astype(np.uint32),
    )
    state.flush_deltas()


def _assert_frontiers_match(state, must=()):
    """Every surviving frontier equals the batch recompute over its
    session's recorded leaves; sessions in `must` (live ones) are
    required to still HAVE a frontier. Archived sessions recycled by a
    ring wrap legitimately lose theirs."""
    for sess in must:
        assert state.session_frontier(sess) is not None, sess
    for sess, fr in state._frontier.items():
        rows = state._audit_rows.get(sess, [])
        assert fr.count == len(rows), sess
        if not rows:
            continue
        ref = merkle_root_host(
            sha_ops.digests_to_hex(state.session_leaf_digests(sess))
        )
        assert fr.root_hex() == ref, sess


def _run_state_walk(ops: list[str], seed: int, work) -> None:
    """Frontier == batch recompute under a random append / wrap /
    checkpoint-restore walk of the live state (one delta per flush
    keeps program shapes constant)."""
    from hypervisor_tpu.runtime.checkpoint import restore_state, save_state

    st_live, cfg = _small_log_state(log_cap=16)
    rng = np.random.RandomState(seed)
    live = st_live.create_session("fp:0", SessionConfig(), now=0.0)
    n_created, t = 1, 0
    for i, op in enumerate(ops):
        # Keep the live chain shorter than the 16-row log so wraps
        # only ever recycle ARCHIVED rows (live recycling refuses
        # loudly, by design).
        if op == "append" and len(st_live._audit_rows.get(live, [])) >= 10:
            op = "rotate"
        if op == "append":
            _stage_one(st_live, live, rng, t)
            t += 1
        elif op == "rotate":
            # Retire the live session (archived rows become wrappable)
            # and start a fresh chain; later appends wrap the 16-row
            # log over the retired history.
            st_live.terminate_sessions([live], now=float(t))
            live = st_live.create_session(
                f"fp:{n_created}", SessionConfig(), now=float(t)
            )
            n_created += 1
            for _ in range(3):
                _stage_one(st_live, live, rng, t)
                t += 1
        else:  # restore: checkpoint/restore of the frontier mid-stream
            target = work / f"ck{i}"
            save_state(st_live, target)
            st_live = restore_state(target / "latest", cfg)
        must = (live,) if st_live._audit_rows.get(live) else ()
        _assert_frontiers_match(st_live, must=must)


class TestFrontierStatePlane:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_append_wrap_restore_sequences(self, seed, tmp_path):
        rng = np.random.RandomState(1000 + seed)
        ops = [
            ["append", "rotate", "restore"][k]
            for k in rng.randint(0, 3, 10)
        ]
        _run_state_walk(ops, seed, tmp_path)

    if HAS_HYPOTHESIS:

        @given(
            st.lists(
                st.sampled_from(["append", "rotate", "restore"]),
                min_size=4, max_size=10,
            ),
            st.integers(0, 2**16),
        )
        @settings(max_examples=6, deadline=None)
        def test_random_sequences_hypothesis(self, ops, seed, tmp_path_factory):
            _run_state_walk(ops, seed, tmp_path_factory.mktemp("frontier_prop"))

    def test_wrap_drops_archived_frontier_and_cache(self):
        st_live, _ = _small_log_state(log_cap=8)
        rng = np.random.RandomState(1)
        a = st_live.create_session("wr:a", SessionConfig(), now=0.0)
        b = st_live.create_session("wr:b", SessionConfig(), now=0.0)
        for t in range(3):
            _stage_one(st_live, a, rng, t)
        st_live.terminate_sessions([a], now=3.0)
        assert st_live.session_frontier(a) is not None
        for t in range(8):  # wraps over a's rows
            _stage_one(st_live, b, rng, 10 + t)
        assert st_live.session_frontier(a) is None
        assert a not in st_live._packed_bodies
        _assert_frontiers_match(st_live)

    def test_legacy_checkpoint_restore_rebuilds_frontier(self):
        from hypervisor_tpu.runtime.checkpoint import restore_state, save_state

        st_live, cfg = _small_log_state(log_cap=64)
        rng = np.random.RandomState(2)
        s = st_live.create_session("lg:a", SessionConfig(), now=0.0)
        for t in range(5):
            _stage_one(st_live, s, rng, t)
        import tempfile
        from pathlib import Path

        work = Path(tempfile.mkdtemp(prefix="hv_legacy_fr_"))
        target = save_state(st_live, work)
        host = json.loads((target / "host.json").read_text())
        assert "frontier" in host
        del host["frontier"]  # simulate a pre-frontier save
        (target / "host.json").write_text(json.dumps(host))
        restored = restore_state(target, cfg)
        _assert_frontiers_match(restored)

    def test_terminate_falls_back_without_frontier(self):
        """A session whose frontier is missing (pre-frontier restore)
        still terminates with the correct root via the tree unit's
        host dispatch, and the frontier re-primes."""
        st_live, _ = _small_log_state(log_cap=64)
        rng = np.random.RandomState(3)
        s = st_live.create_session("tf:a", SessionConfig(), now=0.0)
        for t in range(6):
            _stage_one(st_live, s, rng, t)
        ref = merkle_root_host(
            sha_ops.digests_to_hex(st_live.session_leaf_digests(s))
        )
        st_live._frontier.pop(s)
        roots = st_live.terminate_sessions([s], now=9.0)
        assert sha_ops.digests_to_hex(roots[:1])[0] == ref
        assert st_live.session_frontier(s).root_hex() == ref


class TestPackedBodyCache:
    def test_lazy_prime_and_repeat_reads_hit(self):
        st_live, _ = _small_log_state(log_cap=64)
        rng = np.random.RandomState(4)
        s = st_live.create_session("pc:a", SessionConfig(), now=0.0)
        for t in range(3):
            _stage_one(st_live, s, rng, t)
        # The flush hot path never fills the cache — the first READ does.
        assert s not in st_live._packed_bodies
        first = st_live.session_packed_bodies(s)
        np.testing.assert_array_equal(
            first, np.asarray(st_live.delta_log.body)[np.asarray(st_live._audit_rows[s])]
        )
        # Same object on a second read: no host-side re-pack.
        assert st_live.session_packed_bodies(s) is first
        # New history invalidates the range; the next read re-primes.
        _stage_one(st_live, s, rng, 3)
        again = st_live.session_packed_bodies(s)
        lo, hi, arr = st_live._packed_bodies[s]
        assert (lo, hi) == (0, 4) and again.shape[0] == 4
        assert st_live.verify_session_chain(s)
        assert st_live.session_packed_bodies(s) is again

    def test_cache_miss_rebuilds_after_restore(self):
        from hypervisor_tpu.runtime.checkpoint import restore_state, save_state
        import tempfile

        st_live, cfg = _small_log_state(log_cap=64)
        rng = np.random.RandomState(6)
        s = st_live.create_session("pc:b", SessionConfig(), now=0.0)
        for t in range(4):
            _stage_one(st_live, s, rng, t)
        target = save_state(st_live, tempfile.mkdtemp(prefix="hv_pc_"))
        restored = restore_state(target, cfg)
        assert restored._packed_bodies == {}  # cold after restore
        bodies = restored.session_packed_bodies(s)
        np.testing.assert_array_equal(
            bodies, st_live.session_packed_bodies(s)
        )
        assert s in restored._packed_bodies  # re-primed
        assert restored.verify_session_chain(s)


class TestEnvArming:
    def test_hv_sha256_pallas_read_per_call(self, monkeypatch):
        # Post-import arming: the env var is consulted at CALL time.
        monkeypatch.delenv("HV_SHA256_PALLAS", raising=False)
        sha_ops.set_pallas(None)
        try:
            auto = sha_ops._pallas_enabled()
            monkeypatch.setenv("HV_SHA256_PALLAS", "0")
            assert sha_ops._pallas_enabled() is False
            monkeypatch.setenv("HV_SHA256_PALLAS", "1")
            assert sha_ops._pallas_enabled() is True
            # set_pallas() override outranks the env...
            sha_ops.set_pallas(False)
            assert sha_ops._pallas_enabled() is False
            # ...and clearing it restores env-driven dispatch.
            sha_ops.set_pallas(None)
            assert sha_ops._pallas_enabled() is True
            monkeypatch.delenv("HV_SHA256_PALLAS")
            assert sha_ops._pallas_enabled() is auto
        finally:
            sha_ops.set_pallas(None)


class TestScrubberNativePath:
    def _seeded_state(self):
        st_live, _ = _small_log_state(log_cap=64)
        rng = np.random.RandomState(8)
        slots = [
            st_live.create_session(f"sn:{i}", SessionConfig(), now=0.0)
            for i in range(2)
        ]
        for t in range(4):
            for s in slots:
                st_live.stage_delta(
                    s, 0, ts=float(t),
                    change_words=rng.randint(
                        0, 2**32, 8, dtype=np.uint64
                    ).astype(np.uint32),
                )
        st_live.flush_deltas()
        return st_live

    @pytest.mark.parametrize("native", ["1", "0"])
    def test_clean_sweep_and_tamper_agree(self, native, monkeypatch):
        from hypervisor_tpu.integrity.scrubber import MerkleScrubber
        from hypervisor_tpu.tables.struct import replace as t_replace

        monkeypatch.setenv("HV_SCRUB_NATIVE", native)
        st_live = self._seeded_state()
        scrub = MerkleScrubber(st_live, budget=32)
        rep = scrub.tick()
        assert rep["sweep_completed"] and not rep["mismatches"]
        # Tamper one recorded digest on device: the next sweep flags
        # the link (and its child, whose parent no longer matches).
        row = st_live._audit_rows[0][1]
        st_live.delta_log = t_replace(
            st_live.delta_log,
            digest=st_live.delta_log.digest.at[row, 0].add(jnp.uint32(1)),
        )
        rep = scrub.tick()
        assert rep["sweep_completed"]
        flagged = {m["row"] for m in rep["mismatches"]}
        assert row in flagged
        assert scrub.mismatches >= 1
