"""Native C++ runtime vs hashlib/device ops: chains, roots, staging queue."""

import hashlib
import threading

import numpy as np
import pytest

from hypervisor_tpu.runtime import (
    HAVE_NATIVE,
    StagingQueue,
    chain_digests_host,
    merkle_root_hex_host,
    sha256_batch_host,
    verify_chain_host,
)


def test_native_compiled():
    # g++ is baked into this image; the native path must be live here.
    assert HAVE_NATIVE


class TestHostHashing:
    def test_sha256_batch_matches_hashlib(self):
        rng = np.random.RandomState(0)
        msgs = rng.randint(0, 256, size=(5, 73), dtype=np.int64).astype(np.uint8)
        out = sha256_batch_host(msgs)
        for i in range(5):
            assert out[i].tobytes() == hashlib.sha256(msgs[i].tobytes()).digest()

    def test_chain_matches_device_format(self):
        import jax.numpy as jnp
        from hypervisor_tpu.ops import merkle as merkle_ops

        rng = np.random.RandomState(1)
        bodies = rng.randint(
            0, 2**32, size=(6, merkle_ops.BODY_WORDS), dtype=np.uint64
        ).astype(np.uint32)
        host = chain_digests_host(bodies)
        dev = np.asarray(
            merkle_ops.chain_digests(jnp.asarray(bodies[:, None, :]))
        )[:, 0]  # [N, 8] u32
        dev_bytes = np.ascontiguousarray(dev.astype(">u4")).view(np.uint8).reshape(6, 32)
        assert np.array_equal(host, dev_bytes)

    def test_verify_chain_detects_tamper_index(self):
        rng = np.random.RandomState(2)
        bodies = rng.randint(0, 2**32, size=(5, 16), dtype=np.uint64).astype(np.uint32)
        digests = chain_digests_host(bodies)
        assert verify_chain_host(bodies, digests) == -1
        tampered = digests.copy()
        tampered[3, 0] ^= 1
        assert verify_chain_host(bodies, tampered) == 3

    def test_merkle_root_matches_reference_semantics(self):
        from hypervisor_tpu.audit.delta import merkle_root_host

        leaves_hex = [hashlib.sha256(b"leaf%d" % i).hexdigest() for i in range(5)]
        leaves = np.stack(
            [np.frombuffer(bytes.fromhex(h), np.uint8) for h in leaves_hex]
        )
        assert merkle_root_hex_host(leaves) == merkle_root_host(leaves_hex)


class TestStagingQueue:
    def test_push_and_harvest(self):
        q = StagingQueue(capacity=8)
        assert q.push(0.8, 1, 2) == 0
        assert q.push(0.5, 3, 4, trustworthy=False) == 1
        n, sigma, agent, session, trust = q.harvest()
        assert n == 2
        assert sigma.tolist() == pytest.approx([0.8, 0.5])
        assert agent.tolist() == [1, 3]
        assert trust.tolist() == [1, 0]
        # Epoch reset.
        n, *_ = q.harvest()
        assert n == 0

    def test_overflow_returns_minus_one(self):
        q = StagingQueue(capacity=2)
        assert q.push(0.1, 0, 0) == 0
        assert q.push(0.2, 1, 0) == 1
        assert q.push(0.3, 2, 0) == -1

    def test_concurrent_producers_unique_slots(self):
        q = StagingQueue(capacity=4096)
        slots: list[int] = []
        lock = threading.Lock()

        def producer(base):
            mine = [q.push(0.5, base * 1000 + i, 0) for i in range(1000)]
            with lock:
                slots.extend(mine)

        threads = [threading.Thread(target=producer, args=(t,)) for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        n, _, agent, _, _ = q.harvest()
        assert n == 4000
        valid = [s for s in slots if s >= 0]
        assert len(valid) == 4000
        assert len(set(valid)) == 4000  # no slot claimed twice
        assert len(set(agent.tolist())) == 4000  # every payload distinct


class TestCrossStateStaging:
    def test_second_queue_does_not_corrupt_first(self):
        """The native staging buffer is a process-global registration:
        creating a second queue used to hijack it, so the first queue's
        pushes landed in the second's arrays (observed as garbage
        session slots admitting BAD_STATE). Each queue now re-binds on
        ownership change."""
        import pytest as _pytest

        if not HAVE_NATIVE:
            _pytest.skip("native queue not built (rebind path untestable)")
        q1 = StagingQueue(capacity=8)
        q2 = StagingQueue(capacity=8)  # binds the native side to q2
        assert q1.push(0.5, 3, 7) >= 0  # must re-bind to q1 first
        n, sigma, agent, session, trust = q1.harvest()
        assert n == 1
        assert agent[0] == 3 and session[0] == 7
        assert abs(float(sigma[0]) - 0.5) < 1e-6
        # q2 still works after the handoff back.
        assert q2.push(0.9, 1, 2) >= 0
        n2, _, agent2, session2, _ = q2.harvest()
        assert n2 == 1 and agent2[0] == 1 and session2[0] == 2

    def test_interleaved_staging_fails_loudly(self):
        """Entries staged before a foreign re-bind cannot be counted by
        the native epoch swap — the harvest must raise, not silently
        return a partial wave."""
        import pytest as _pytest

        from hypervisor_tpu.runtime import HAVE_NATIVE as _HN

        if not _HN:
            _pytest.skip("native queue not built")
        qa = StagingQueue(capacity=8)
        assert qa.push(0.5, 1, 1) >= 0
        qb = StagingQueue(capacity=8)  # foreign bind resets the epoch
        with _pytest.raises(RuntimeError, match="staged join"):
            qa.harvest()
        # qa recovers through the PUBLIC acknowledgement API.
        assert qa.acknowledge_lost_epoch() == 1
        assert qa.push(0.7, 2, 3) >= 0
        n, _, agent, session, _ = qa.harvest()
        assert n == 1 and agent[0] == 2 and session[0] == 3


class TestHostHelpers:
    def test_contiguous_range_gate(self):
        """The range fast-path gate accepts exactly arange blocks."""
        from hypervisor_tpu.state import _contiguous_range

        ok = _contiguous_range(np.arange(5, 12, dtype=np.int32))
        assert ok is not None
        lo, hi = int(ok[0]), int(ok[1])
        assert (lo, hi) == (5, 12)
        assert _contiguous_range(np.zeros(0, np.int32)) is None
        assert _contiguous_range(np.array([-1, 0, 1], np.int32)) is None
        assert _contiguous_range(np.array([3, 5, 6], np.int32)) is None   # gap
        assert _contiguous_range(np.array([3, 3, 4], np.int32)) is None   # dup
        assert _contiguous_range(np.array([4, 3, 2], np.int32)) is None   # desc

    def test_membership_keys_roundtrip(self):
        from hypervisor_tpu.state import _mkey, _mkeys

        rng = np.random.RandomState(7)
        sessions = rng.randint(0, 2**20, 256).astype(np.int32)
        dids = rng.randint(0, 2**20, 256).astype(np.int32)
        keys = _mkeys(sessions, dids)
        for i in range(256):
            k = int(keys[i])
            assert k == _mkey(int(sessions[i]), int(dids[i]))
            assert (k >> 32, k & 0xFFFFFFFF) == (sessions[i], dids[i])
        # Distinct pairs -> distinct keys.
        assert len(set(keys.tolist())) == len(
            {(int(s), int(d)) for s, d in zip(sessions, dids)}
        )
