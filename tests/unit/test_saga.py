"""Saga state machines, fan-out policies, checkpoints, DSL.

Mirrors reference `test_saga.py` + `test_saga_improvements.py`: transition
table violations, fan-out policies, checkpoint replay plans, DSL errors.
"""


import pytest

from hypervisor_tpu.saga import (
    CheckpointManager,
    FanOutOrchestrator,
    FanOutPolicy,
    Saga,
    SagaDSLError,
    SagaDSLParser,
    SagaOrchestrator,
    SagaState,
    SagaStateError,
    SagaStep,
    StepState,
    STEP_TRANSITION_MATRIX,
)

S = "session:test-1"


class TestStateMachine:
    def _step(self):
        return SagaStep(step_id="st", action_id="a", agent_did="d", execute_api="/x")

    def test_valid_forward_path(self):
        step = self._step()
        step.transition(StepState.EXECUTING)
        step.transition(StepState.COMMITTED)
        step.transition(StepState.COMPENSATING)
        step.transition(StepState.COMPENSATED)
        assert step.completed_at is not None

    def test_illegal_transition_raises(self):
        step = self._step()
        with pytest.raises(SagaStateError, match="Invalid step transition"):
            step.transition(StepState.COMMITTED)  # PENDING -> COMMITTED

    def test_terminal_states_frozen(self):
        step = self._step()
        step.transition(StepState.EXECUTING)
        step.transition(StepState.FAILED)
        with pytest.raises(SagaStateError):
            step.transition(StepState.EXECUTING)

    def test_saga_transitions(self):
        saga = Saga(saga_id="sg", session_id=S)
        saga.transition(SagaState.COMPENSATING)
        saga.transition(SagaState.ESCALATED)
        with pytest.raises(SagaStateError):
            saga.transition(SagaState.RUNNING)

    def test_transition_matrix_shape(self):
        assert STEP_TRANSITION_MATRIX.shape == (7, 7)
        assert STEP_TRANSITION_MATRIX.sum() == 6  # exactly 6 legal moves

    def test_committed_steps_reversed(self):
        saga = Saga(saga_id="sg", session_id=S)
        for i in range(3):
            step = SagaStep(
                step_id=f"st{i}", action_id=f"a{i}", agent_did="d", execute_api="/x"
            )
            step.transition(StepState.EXECUTING)
            step.transition(StepState.COMMITTED)
            saga.steps.append(step)
        assert [s.step_id for s in saga.committed_steps_reversed] == [
            "st2", "st1", "st0",
        ]

    def test_to_dict_from_dict_roundtrip(self):
        saga = Saga(saga_id="sg", session_id=S)
        saga.steps.append(self._step())
        data = saga.to_dict()
        back = Saga.from_dict(data)
        assert back.saga_id == "sg" and back.steps[0].step_id == "st"
        assert back.state == SagaState.RUNNING


class TestFanOut:
    async def _run_group(self, policy, outcomes):
        fan = FanOutOrchestrator()
        orch = SagaOrchestrator()
        saga = orch.create_saga(S)
        group = fan.create_group(saga.saga_id, policy)
        executors = {}
        for i, ok in enumerate(outcomes):
            step = orch.add_step(saga.saga_id, f"a{i}", "did:x", "/x")
            fan.add_branch(group.group_id, step)

            async def run(ok=ok):
                if not ok:
                    raise RuntimeError("branch failed")
                return "ok"

            executors[step.step_id] = run
        return await fan.execute(group.group_id, executors)

    async def test_all_must_succeed(self):
        group = await self._run_group(FanOutPolicy.ALL_MUST_SUCCEED, [True, True])
        assert group.policy_satisfied and group.compensation_needed == []
        group = await self._run_group(FanOutPolicy.ALL_MUST_SUCCEED, [True, False])
        assert not group.policy_satisfied
        assert len(group.compensation_needed) == 1  # the winner rolls back

    async def test_majority(self):
        group = await self._run_group(
            FanOutPolicy.MAJORITY_MUST_SUCCEED, [True, True, False]
        )
        assert group.policy_satisfied
        group = await self._run_group(
            FanOutPolicy.MAJORITY_MUST_SUCCEED, [True, False, False]
        )
        assert not group.policy_satisfied

    async def test_any(self):
        group = await self._run_group(
            FanOutPolicy.ANY_MUST_SUCCEED, [False, False, True]
        )
        assert group.policy_satisfied
        group = await self._run_group(FanOutPolicy.ANY_MUST_SUCCEED, [False, False])
        assert not group.policy_satisfied

    async def test_missing_executor_is_failure(self):
        fan = FanOutOrchestrator()
        orch = SagaOrchestrator()
        saga = orch.create_saga(S)
        group = fan.create_group(saga.saga_id)
        step = orch.add_step(saga.saga_id, "a", "did:x", "/x")
        fan.add_branch(group.group_id, step)
        result = await fan.execute(group.group_id, executors={})
        assert not result.policy_satisfied
        assert "No executor" in result.branches[0].error


class TestCheckpoints:
    def test_save_and_skip_on_replay(self):
        mgr = CheckpointManager()
        mgr.save("sg", "st1", "Schema migrated", {"version": 5})
        assert mgr.is_achieved("sg", "Schema migrated", "st1")
        assert not mgr.is_achieved("sg", "Schema migrated", "st2")
        assert not mgr.is_achieved("other", "Schema migrated", "st1")

    def test_invalidate(self):
        mgr = CheckpointManager()
        mgr.save("sg", "st1", "Goal A")
        assert mgr.invalidate("sg", "st1", reason="state changed") == 1
        assert not mgr.is_achieved("sg", "Goal A", "st1")
        assert mgr.valid_checkpoints == 0 and mgr.total_checkpoints == 1

    def test_replay_plan(self):
        mgr = CheckpointManager()
        mgr.save("sg", "st1", "A")
        mgr.save("sg", "st3", "C")
        plan = mgr.get_replay_plan("sg", ["st1", "st2", "st3", "st4"])
        assert plan == ["st2", "st4"]

    def test_state_snapshot_preserved(self):
        mgr = CheckpointManager()
        mgr.save("sg", "st1", "A", {"rows": 42})
        ckpt = mgr.get_checkpoint("sg", "A", "st1")
        assert ckpt.state_snapshot == {"rows": 42}


class TestDSL:
    def _definition(self, **overrides):
        d = {
            "name": "deploy",
            "session_id": S,
            "steps": [
                {"id": "validate", "action_id": "m.validate", "agent": "did:v",
                 "execute_api": "/v", "undo_api": "/uv"},
                {"id": "deploy", "action_id": "m.deploy", "agent": "did:d",
                 "timeout": 600, "retries": 2},
            ],
        }
        d.update(overrides)
        return d

    def test_parse_valid(self):
        parsed = SagaDSLParser().parse(self._definition())
        assert parsed.name == "deploy"
        assert [s.id for s in parsed.steps] == ["validate", "deploy"]
        assert parsed.steps[1].timeout == 600 and parsed.steps[1].retries == 2

    def test_missing_name_session_steps(self):
        parser = SagaDSLParser()
        with pytest.raises(SagaDSLError, match="name"):
            parser.parse(self._definition(name=""))
        with pytest.raises(SagaDSLError, match="session_id"):
            parser.parse(self._definition(session_id=""))
        with pytest.raises(SagaDSLError, match="at least one step"):
            parser.parse(self._definition(steps=[]))

    def test_duplicate_step_ids(self):
        d = self._definition()
        d["steps"].append(dict(d["steps"][0]))
        with pytest.raises(SagaDSLError, match="Duplicate"):
            SagaDSLParser().parse(d)

    def test_fanout_validation(self):
        d = self._definition(
            fan_out=[{"policy": "majority_must_succeed", "branches": ["validate"]}]
        )
        with pytest.raises(SagaDSLError, match="at least 2"):
            SagaDSLParser().parse(d)
        d = self._definition(
            fan_out=[{"policy": "bogus", "branches": ["validate", "deploy"]}]
        )
        with pytest.raises(SagaDSLError, match="Invalid fan-out policy"):
            SagaDSLParser().parse(d)
        d = self._definition(
            fan_out=[{"policy": "any_must_succeed", "branches": ["validate", "ghost"]}]
        )
        with pytest.raises(SagaDSLError, match="not a valid step"):
            SagaDSLParser().parse(d)

    def test_to_saga_steps(self):
        parsed = SagaDSLParser().parse(self._definition())
        steps = SagaDSLParser.to_saga_steps(parsed)
        assert all(isinstance(s, SagaStep) for s in steps)
        assert steps[0].undo_api == "/uv"

    def test_validate_collects_errors(self):
        errors = SagaDSLParser.validate({"steps": [{"id": "a"}, {"id": "a"}]})
        assert "Missing 'name'" in errors
        assert any("Duplicate" in e for e in errors)
        assert any("action_id" in e for e in errors)

    def test_sequential_vs_fanout_steps(self):
        d = self._definition(
            fan_out=[{"policy": "any_must_succeed", "branches": ["validate", "deploy"]}]
        )
        parsed = SagaDSLParser().parse(d)
        assert parsed.sequential_steps == []
        assert parsed.fan_out_step_ids == {"validate", "deploy"}


class TestYAMLDSL:
    YAML = """
name: deploy
session_id: session:test-1
steps:
  - id: validate
    action_id: m.validate
    agent: did:v
    execute_api: /v
    undo_api: /uv
  - id: ship-a
    action_id: m.ship
    agent: did:a
    execute_api: /a
  - id: ship-b
    action_id: m.ship
    agent: did:b
    execute_api: /b
fan_out:
  - policy: majority_must_succeed
    branches: [ship-a, ship-b]
"""

    def test_parse_yaml_roundtrip(self):
        parsed = SagaDSLParser().parse_yaml(self.YAML)
        assert parsed.name == "deploy"
        assert [s.id for s in parsed.steps] == ["validate", "ship-a", "ship-b"]
        assert parsed.fan_outs[0].policy is FanOutPolicy.MAJORITY_MUST_SUCCEED
        assert parsed.fan_out_step_ids == {"ship-a", "ship-b"}

    def test_parse_yaml_rejects_non_mapping(self):
        with pytest.raises(SagaDSLError, match="mapping"):
            SagaDSLParser().parse_yaml("- just\n- a list\n")

    def test_parse_yaml_rejects_bad_yaml(self):
        with pytest.raises(SagaDSLError, match="Invalid YAML"):
            SagaDSLParser().parse_yaml("name: [unclosed\n  - x:")

    def test_yaml_validation_errors_surface(self):
        with pytest.raises(SagaDSLError, match="at least one step"):
            SagaDSLParser().parse_yaml("name: x\nsession_id: s\nsteps: []\n")
