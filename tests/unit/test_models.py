"""Core model semantics (mirrors reference `tests/unit/test_models.py` coverage)."""

from hypervisor_tpu.models import (
    ActionDescriptor,
    ConsistencyMode,
    ExecutionRing,
    ReversibilityLevel,
    SessionConfig,
    SessionState,
)


class TestExecutionRing:
    def test_ring_from_sigma_boundaries(self):
        # Strict > at both thresholds (reference boundary test: 0.60 vs 0.601).
        assert ExecutionRing.from_sigma_eff(0.60) == ExecutionRing.RING_3_SANDBOX
        assert ExecutionRing.from_sigma_eff(0.601) == ExecutionRing.RING_2_STANDARD
        assert ExecutionRing.from_sigma_eff(0.95, True) == ExecutionRing.RING_2_STANDARD
        assert ExecutionRing.from_sigma_eff(0.951, True) == ExecutionRing.RING_1_PRIVILEGED

    def test_ring1_requires_consensus(self):
        assert ExecutionRing.from_sigma_eff(0.99, False) == ExecutionRing.RING_2_STANDARD
        assert ExecutionRing.from_sigma_eff(0.99, True) == ExecutionRing.RING_1_PRIVILEGED

    def test_ordering(self):
        assert ExecutionRing.RING_0_ROOT < ExecutionRing.RING_3_SANDBOX


class TestReversibility:
    def test_risk_weight_ranges(self):
        assert ReversibilityLevel.FULL.risk_weight_range == (0.1, 0.3)
        assert ReversibilityLevel.PARTIAL.risk_weight_range == (0.5, 0.8)
        assert ReversibilityLevel.NONE.risk_weight_range == (0.9, 1.0)

    def test_default_risk_weight_is_midpoint(self):
        assert abs(ReversibilityLevel.FULL.default_risk_weight - 0.2) < 1e-9
        assert abs(ReversibilityLevel.PARTIAL.default_risk_weight - 0.65) < 1e-9
        assert abs(ReversibilityLevel.NONE.default_risk_weight - 0.95) < 1e-9


class TestActionDescriptor:
    def _action(self, **kw):
        return ActionDescriptor(
            action_id="a", name="a", execute_api="/x", **kw
        )

    def test_required_ring_admin(self):
        assert self._action(is_admin=True).required_ring == ExecutionRing.RING_0_ROOT

    def test_required_ring_nonreversible(self):
        a = self._action(reversibility=ReversibilityLevel.NONE)
        assert a.required_ring == ExecutionRing.RING_1_PRIVILEGED

    def test_required_ring_read_only(self):
        a = self._action(is_read_only=True, reversibility=ReversibilityLevel.NONE)
        assert a.required_ring == ExecutionRing.RING_3_SANDBOX

    def test_required_ring_reversible(self):
        a = self._action(reversibility=ReversibilityLevel.FULL)
        assert a.required_ring == ExecutionRing.RING_2_STANDARD

    def test_risk_weight_follows_reversibility(self):
        assert self._action(reversibility=ReversibilityLevel.PARTIAL).risk_weight == 0.65


class TestSessionConfig:
    def test_defaults(self):
        c = SessionConfig()
        assert c.consistency_mode == ConsistencyMode.EVENTUAL
        assert c.max_participants == 10
        assert c.min_sigma_eff == 0.60
        assert c.enable_audit is True


class TestStateCodes:
    def test_session_state_roundtrip(self):
        for s in SessionState:
            assert SessionState.from_code(s.code) == s

    def test_consistency_mode_codes(self):
        assert ConsistencyMode.STRONG.code == 0
        assert ConsistencyMode.from_code(1) == ConsistencyMode.EVENTUAL
