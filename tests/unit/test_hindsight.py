"""The hindsight plane (round 19): retained telemetry history +
black-box incident recorder, local and fleet-wide.

Covers the tiered history rings (fold conservation across tier
boundaries, eviction accounting, caller's-clock queries, digest
replay), the incident recorder (trigger taxonomy, cooldown/dedup,
advisory exclusion, bounded retention, same-seed drill bit-identity),
the shared snapshot-digest helper's stability against the pre-refactor
inline algorithms (the satellite-1 fixtures), the state/core wiring
(health fan-out -> capture -> bus event), both REST transports, and
the hv_top incidents panel.
"""

import dataclasses
import hashlib
import json

import numpy as np
import pytest

from hypervisor_tpu.observability.history import (
    DEFAULT_SERIES,
    HistoryConfig,
    HistoryPlane,
    _fold_aggs,
)
from hypervisor_tpu.observability.incidents import (
    ADVISORY_PAYLOAD_KEYS,
    IncidentConfig,
    IncidentRecorder,
    TRIGGER_TAXONOMY,
    incident_rule_payload,
)
from hypervisor_tpu.observability.snapshot import (
    canonical_blob,
    rule_digest,
)


def feed(plane: HistoryPlane, n: int, seed: int = 7, t0: float = 0.0):
    """Seeded deterministic sample feed on a virtual clock."""
    rng = np.random.default_rng(seed)
    t = t0
    for _ in range(n):
        t += 1.0
        plane.sample(
            {name: float(rng.integers(0, 1000)) for name in plane.series},
            now=t,
        )
    return t


# ── 1. tiered history: fold conservation + eviction accounting ───────


class TestHistoryTiers:
    def test_tier_folds_conserve_min_max_count_sum(self, monkeypatch):
        # Tight knobs force every ring past its budget, so the
        # conservation witness covers the eviction path too: each
        # sample must live in exactly one stratum (acc1 | acc2 |
        # tier-2 ring | folded-out mass).
        monkeypatch.setenv("HV_HISTORY_RAW_POINTS", "16")
        monkeypatch.setenv("HV_HISTORY_TIER_POINTS", "8")
        monkeypatch.setenv("HV_HISTORY_FOLD", "4")
        plane = HistoryPlane(series=("a", "b"))
        feed(plane, 500)
        assert plane.evictions_total > 0
        report = plane.verify_conservation()
        assert report["ok"], report
        assert report["retained_ok"]
        for name in ("a", "b"):
            assert report["series"][name]["count"] == 500

    def test_tier_boundary_aggregates(self, monkeypatch):
        # Hand-checkable fold: 4 raw points -> one tier-1 point
        # carrying exact min/max/count/sum/last.
        monkeypatch.setenv("HV_HISTORY_FOLD", "4")
        plane = HistoryPlane(series=("x",))
        for i, v in enumerate((3.0, 9.0, 1.0, 5.0)):
            plane.sample({"x": v}, now=float(i + 1))
        [agg] = plane.query("x", tier=1)
        assert agg["count"] == 4
        assert agg["min"] == 1.0 and agg["max"] == 9.0
        assert agg["mean"] == pytest.approx(4.5)
        assert agg["last"] == 5.0
        assert (agg["t_start"], agg["t_end"]) == (1.0, 4.0)

    def test_points_retained_counter_matches_recount(self, monkeypatch):
        monkeypatch.setenv("HV_HISTORY_RAW_POINTS", "10")
        monkeypatch.setenv("HV_HISTORY_TIER_POINTS", "10")
        monkeypatch.setenv("HV_HISTORY_FOLD", "3")
        plane = HistoryPlane(series=("a",))
        feed(plane, 333)
        h = plane._hist["a"]
        recount = len(h.raw) + len(h.tiers[0]) + len(h.tiers[1])
        assert plane.points_retained() == recount
        assert plane.verify_conservation()["retained_ok"]

    def test_query_on_callers_clock(self):
        plane = HistoryPlane(series=("a",))
        feed(plane, 50, t0=100.0)  # samples at t=101..150
        pts = plane.query("a", start=120.0, end=130.0, tier=0)
        assert [p["t"] for p in pts] == [float(t) for t in range(120, 131)]
        assert plane.query("a", start=9999.0) == []
        assert plane.query("missing") == []
        newest = plane.query("a", tier=0, limit=5)
        assert len(newest) == 5 and newest[-1]["t"] == 150.0

    def test_window_bounded_per_tier(self, monkeypatch):
        monkeypatch.setenv("HV_HISTORY_FOLD", "2")
        plane = HistoryPlane(series=("a", "b"))
        feed(plane, 200)
        win = plane.window(200.0, before=200.0, after=0.0,
                           limit_per_tier=8)
        assert win["start"] == 0.0 and win["end"] == 200.0
        for name in ("a", "b"):
            tiers = win["series"][name]
            assert set(tiers) == {"0", "1", "2"}
            assert all(len(pts) <= 8 for pts in tiers.values())
            assert tiers["0"]  # raw points present

    def test_digest_bit_identical_across_same_seed_replays(self):
        p1, p2 = HistoryPlane(), HistoryPlane()
        feed(p1, 300, seed=19)
        feed(p2, 300, seed=19)
        assert p1.digest() == p2.digest()
        p3 = HistoryPlane()
        feed(p3, 300, seed=20)
        assert p3.digest() != p1.digest()

    def test_budget_knobs_read_per_call(self, monkeypatch):
        # HVA002: a knob change applies to the NEXT sample, no
        # restart — the ring shrinks immediately and counts the
        # evictions it forces.
        plane = HistoryPlane(series=("a",))
        feed(plane, 100)
        assert len(plane._hist["a"].raw) == 100
        monkeypatch.setenv("HV_HISTORY_RAW_POINTS", "8")
        plane.sample({"a": 1.0}, now=1000.0)
        assert len(plane._hist["a"].raw) == 8
        assert plane.evictions_total >= 93
        assert plane.verify_conservation()["ok"]

    def test_sample_snapshot_reads_declared_registry_series(self):
        from hypervisor_tpu.observability.metrics import REGISTRY

        plane = HistoryPlane()

        class _Snap:
            registry = REGISTRY

            def counter(self, handle):
                return 5

            def gauge(self, handle):
                return 2.0

        plane.sample_snapshot(_Snap(), now=10.0)
        for name in DEFAULT_SERIES:
            pts = plane.query(name, tier=0)
            assert len(pts) == 1 and pts[0]["t"] == 10.0

    def test_config_from_env_floors_and_garbage(self, monkeypatch):
        monkeypatch.setenv("HV_HISTORY_RAW_POINTS", "1")
        monkeypatch.setenv("HV_HISTORY_FOLD", "garbage")
        cfg = HistoryConfig.from_env()
        assert cfg.raw_points == 8  # floor
        assert cfg.fold == HistoryConfig.fold  # garbage -> default


# ── 2. the incident recorder ─────────────────────────────────────────


def _recorder(**kw) -> IncidentRecorder:
    rec = IncidentRecorder(**kw)
    rec.events = []
    rec.emit = lambda kind, payload: rec.events.append((kind, payload))
    return rec


class TestIncidentRecorder:
    def test_kinds_outside_the_taxonomy_never_capture(self):
        rec = _recorder()
        assert rec.observe("wave_complete", {"now": 1.0}) is None
        # The recorder's own emissions are outside the taxonomy — the
        # recursion guard.
        assert rec.observe("incident_captured", {"now": 1.0}) is None
        assert rec.captured_total == 0 and rec.suppressed_total == 0

    def test_capture_bundle_shape(self):
        rec = _recorder(scope="local")
        rec.register_provider("knobs", lambda trig: {"fold": 10})
        iid = rec.observe(
            "degraded_enter", {"mode": "degraded", "now": 50.0}
        )
        bundle = rec.get(iid)
        assert bundle["scope"] == "local"
        assert bundle["class"] == "resilience.degraded_entered"
        assert bundle["kind"] == "degraded_enter"
        assert bundle["seq"] == 1 and bundle["now"] == 50.0
        assert bundle["context"]["knobs"] == {"fold": 10}
        assert bundle["bytes"] > 0
        [row] = rec.index()
        assert row["id"] == iid and row["class"] == bundle["class"]
        captured = [e for e in rec.events if e[0] == "incident_captured"]
        assert captured and captured[0][1]["id"] == iid

    def test_cooldown_suppresses_within_class(self):
        rec = _recorder()
        a = rec.observe("degraded_enter", {"now": 100.0})
        assert rec.observe("degraded_enter", {"now": 110.0}) is None
        assert rec.suppressed_total == 1
        # A different class is NOT suppressed by degraded's cooldown.
        b = rec.observe("slo_burn_critical", {"now": 111.0})
        assert a and b and a != b
        # Past the 30 s default cooldown the class captures again.
        c = rec.observe("degraded_enter", {"now": 140.0})
        assert c is not None and c != a

    def test_exact_digest_dedup(self):
        rec = _recorder()
        iid = rec.observe("straggler", {"stage": "wave", "now": 1.0})
        # Rewind the seq so the next capture recomputes the SAME rule
        # payload — the dedup's only reachable path, since seq is
        # otherwise monotonic.
        rec._seq -= 1
        rec._last_capture.clear()
        assert rec.observe("straggler", {"stage": "wave", "now": 1.0}) is None
        assert rec.suppressed_total == 1
        assert [r["id"] for r in rec.index()] == [iid]

    def test_advisory_payload_keys_ride_but_do_not_shift_the_id(self):
        base = {"worker": "w1", "lease_seq": 3, "now": 10.0}
        a = _recorder().observe(
            "fleet_worker_dead", dict(base, wall_ms=17.3, at=999.0)
        )
        b = _recorder().observe(
            "fleet_worker_dead",
            dict(base, wall_ms=9999.9, at=1.0, trace_id="t/x"),
        )
        assert a == b
        # ... but a RULE field shift does move the id.
        c = _recorder().observe(
            "fleet_worker_dead", dict(base, lease_seq=4)
        )
        assert c != a
        assert "trace_id" in ADVISORY_PAYLOAD_KEYS

    def test_retention_ring_evicts_loudly(self, monkeypatch):
        monkeypatch.setenv("HV_INCIDENT_RETAINED", "2")
        monkeypatch.setenv("HV_INCIDENT_COOLDOWN_S", "0")
        rec = _recorder()
        ids = [
            rec.observe("straggler", {"stage": f"s{i}", "now": float(i)})
            for i in range(4)
        ]
        assert rec.captured_total == 4 and rec.evicted_total == 2
        assert [r["id"] for r in rec.index()] == [ids[3], ids[2]]
        assert rec.get(ids[0]) is None  # evicted bundles are gone
        evictions = [e for e in rec.events if e[0] == "incident_evicted"]
        assert [e[1]["id"] for e in evictions] == [ids[0], ids[1]]
        assert rec.summary()["retained"] == 2

    def test_replay_check_recomputes_the_content_address(self):
        rec = _recorder()
        iid = rec.observe("integrity_violation", {"table": "x", "now": 5.0})
        assert rec.replay_check(iid)
        assert not rec.replay_check("deadbeef")
        rec.get(iid)["rule"]["trigger"]["table"] = "tampered"
        assert not rec.replay_check(iid)

    def test_provider_errors_survive_the_capture(self):
        rec = _recorder()

        def boom(trigger):
            raise RuntimeError("provider down")

        rec.register_provider("flaky", boom)
        iid = rec.observe("degraded_enter", {"now": 1.0})
        assert "RuntimeError" in rec.get(iid)["context"]["flaky"]["error"]

    def test_same_seed_drill_bit_identical_ids(self):
        def drill(rec):
            base = 1000.0
            out = []
            for i, (kind, payload) in enumerate((
                ("degraded_enter", {"mode": "degraded"}),
                ("slo_burn_critical", {"queue": "join", "burn": 14.6}),
                ("fleet_worker_dead", {"worker": "w1", "lease_seq": 2}),
            )):
                out.append(rec.observe(
                    kind, dict(payload, now=base + 40.0 * i)
                ))
            return out

        assert drill(_recorder()) == drill(_recorder())

    def test_rule_payload_quantizes_now_and_pops_advisories(self):
        rule = incident_rule_payload(
            "c", "k", 3, 1.23456789, {"x": 1, "wall_ms": 9.9}
        )
        assert rule["now"] == 1.234568
        assert rule["trigger"] == {"x": 1}
        assert rule_digest(rule) == hashlib.sha256(
            json.dumps(rule, sort_keys=True, default=list).encode()
        ).hexdigest()

    def test_config_from_env_per_call(self, monkeypatch):
        assert IncidentConfig.from_env().retained == 32
        monkeypatch.setenv("HV_INCIDENT_RETAINED", "5")
        monkeypatch.setenv("HV_INCIDENT_COOLDOWN_S", "garbage")
        cfg = IncidentConfig.from_env()
        assert cfg.retained == 5
        assert cfg.cooldown_s == IncidentConfig.cooldown_s

    def test_taxonomy_covers_the_issue_trigger_set(self):
        assert set(TRIGGER_TAXONOMY.values()) == {
            "resilience.degraded_entered",
            "slo.burn_rate_critical",
            "integrity.violation",
            "integrity.state_restored",
            "fleet.worker_suspected",
            "fleet.worker_dead",
            "watchdog.straggler",
            "adversarial.uncontained",
        }


# ── 3. satellite 1: shared digest helper, pinned to the pre-refactor
#      inline algorithms (before/after fixtures) ─────────────────────


class TestSnapshotDigestStability:
    def test_signal_snapshot_digest_matches_pre_refactor_algorithm(self):
        from hypervisor_tpu.autopilot.signals import SignalSnapshot

        snap = SignalSnapshot(
            seq=4, now=12.3456789,
            queue_depths=(("join", 3),), served=(("join", 10),),
            shed=(("overload", 2),), deadline_misses=7,
            buckets=(8, 16), burn_states=(("join", "warning"),),
            wal_backlog=5, floor_distance=3.14159,
        )
        # The OLD inline algorithm, verbatim from the pre-refactor
        # `SignalSnapshot.digest` — the re-point must not move ONE bit.
        payload = dataclasses.asdict(snap)
        for k in snap._ADVISORY_FIELDS:
            payload.pop(k, None)
        payload["now"] = round(snap.now, 6)
        if snap.floor_distance is not None:
            payload["floor_distance"] = round(snap.floor_distance, 1)
        blob = json.dumps(payload, sort_keys=True, default=list)
        assert snap.digest() == hashlib.sha256(blob.encode()).hexdigest()

    def test_fleet_snapshot_digest_matches_pre_refactor_algorithm(self):
        from hypervisor_tpu.fleet.drain import FleetSnapshot

        snap = FleetSnapshot(
            seq=3, now=12.5, workers=("w0", "w1"),
            states=(("w0", "alive"), ("w1", "suspected")),
            occupancy=(("w0", 4), ("w1", 2)),
            compiles=(("w0", 7), ("w1", 7)),
            recompiles=(("w0", 0), ("w1", 0)),
            series=(("w0", 100), ("w1", 100)),
            merged_series=200, transitions_digest="abc",
            floor_distance=(("w0", 3.14159), ("w1", None)),
            worst_burn=(("w1", "join", "warning"),),
            scrape_wall_ms=17.3, errors=(("w1", "slo"),),
        )
        payload = dataclasses.asdict(snap)
        for k in snap._ADVISORY_FIELDS:
            payload.pop(k, None)
        payload["now"] = round(snap.now, 6)
        payload["floor_distance"] = [
            (w, None if d is None else round(float(d), 1))
            for w, d in snap.floor_distance
        ]
        blob = json.dumps(payload, sort_keys=True, default=list)
        assert snap.digest() == hashlib.sha256(blob.encode()).hexdigest()

    def test_canonical_blob_is_the_one_encoding(self):
        assert canonical_blob({"b": 1, "a": (2, 3)}) == json.dumps(
            {"b": 1, "a": (2, 3)}, sort_keys=True, default=list
        )


# ── 4. state/core wiring: fan-out -> capture -> bus ──────────────────


class TestStateWiring:
    @pytest.fixture
    def svc(self):
        from hypervisor_tpu.api.service import HypervisorService

        return HypervisorService()

    def test_health_fanout_captures_and_bridges_to_bus(self, svc):
        from hypervisor_tpu.observability import EventType

        st = svc.hv.state
        st.health.emit_event(
            "degraded_enter", {"mode": "degraded", "now": 77.0}
        )
        [row] = st.incidents.index()
        assert row["class"] == "resilience.degraded_entered"
        bundle = st.incidents.get(row["id"])
        # Every wired context block landed: the bus slice (core), the
        # WAL watermark, the ledger + SLO snapshots, the trace block,
        # and the history window.
        assert {"events", "wal", "ledger", "slo", "trace", "history"} <= set(
            bundle["context"]
        )
        kinds = [
            e.event_type for e in svc.hv.event_bus.query(limit=8)
        ]
        assert EventType.INCIDENT_CAPTURED in kinds

    def test_health_summary_carries_hindsight_blocks(self, svc):
        out = svc.hv.state.health_summary()
        assert out["incidents"]["enabled"]
        assert out["history"]["samples"] >= 0

    def test_metrics_snapshot_feeds_history_on_the_hindsight_clock(
        self, svc
    ):
        st = svc.hv.state
        st.hindsight_clock = lambda: 555.0
        st.metrics_snapshot()
        pts = st.history.query("hv_sessions_live", tier=0)
        assert pts and pts[-1]["t"] == 555.0

    def test_history_query_and_incident_bundle_reads(self, svc):
        st = svc.hv.state
        st.metrics_snapshot()
        summary = st.history_query()
        assert summary["enabled"] and summary["conservation"]
        q = st.history_query(series="hv_sessions_live", tier=0)
        assert q["series"] == "hv_sessions_live" and q["points"]
        assert st.incident_bundle("nope") is None


# ── 5. both transports ───────────────────────────────────────────────


class TestHindsightTransports:
    def test_stdlib_routes(self):
        import urllib.request

        from hypervisor_tpu.api.server import HypervisorHTTPServer

        server = HypervisorHTTPServer().start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            def get(path):
                try:
                    with urllib.request.urlopen(base + path, timeout=10) as r:
                        return r.status, json.loads(r.read())
                except urllib.error.HTTPError as e:
                    return e.code, json.loads(e.read())

            st = server.service.hv.state
            st.metrics_snapshot()
            iid = st.incidents.observe(
                "slo_burn_critical", {"queue": "join", "now": 9.0}
            )
            status, body = get("/debug/incidents")
            assert status == 200 and body["enabled"]
            assert body["last"][0]["id"] == iid
            status, body = get(f"/incidents/{iid}")
            assert status == 200 and body["id"] == iid
            status, body = get("/incidents/unknown")
            assert status == 404 and "not found" in body["detail"]
            status, body = get(
                "/history/query?series=hv_sessions_live&tier=0"
            )
            assert status == 200 and body["points"]
            status, body = get("/history/query?tier=garbage")
            assert status == 400
            status, body = get("/fleet/incidents")
            assert status == 503  # no fleet attached
        finally:
            server.stop()

    def test_fleet_incidents_rollup_over_stdlib(self):
        import urllib.request

        from hypervisor_tpu.api.server import HypervisorHTTPServer
        from hypervisor_tpu.fleet import FleetObservatory, FleetRegistry

        server = HypervisorHTTPServer().start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            reg = FleetRegistry(seed=19)
            obs = FleetObservatory(
                {"w0": "http://127.0.0.1:1"}, registry=reg,
                timeout_s=0.2,
            )
            server.service.fleet = obs
            reg.register("w0", now=0.0)
            reg.heartbeat("w0", now=0.5)
            for t in (64.0, 128.0, 256.0):
                reg.evaluate(now=t)
            obs._capture_dead_transitions()
            with urllib.request.urlopen(
                base + "/fleet/incidents", timeout=10
            ) as r:
                body = json.loads(r.read())
            assert body["fleet"]["scope"] == "fleet"
            [row] = body["fleet_incidents"]
            assert row["class"] == "fleet.worker_dead"
            assert row["worker"] is None  # FLEET-scope, not a worker's
            # The dead (unreachable, pre-r19-shaped) worker degrades.
            assert body["workers"]["w0"]["unreachable"]
            assert body["merged"]
        finally:
            server.stop()

    def test_fastapi_routes(self):
        pytest.importorskip("fastapi")
        from fastapi.testclient import TestClient

        from hypervisor_tpu.api.server import create_app

        client = TestClient(create_app())
        assert client.get("/debug/incidents").json()["enabled"]
        assert client.get("/incidents/unknown").status_code == 404
        assert client.get("/history/query").json()["enabled"]
        assert client.get("/fleet/incidents").status_code == 503


# ── 6. the hv_top incidents panel ────────────────────────────────────


class TestHvTopPanel:
    def _hv_top(self):
        import importlib
        import sys
        from pathlib import Path

        sys.path.insert(
            0, str(Path(__file__).resolve().parents[2] / "examples")
        )
        return importlib.import_module("hv_top")

    def test_renders_na_against_pre_r19_servers(self):
        hv_top = self._hv_top()
        frame = hv_top.render({"stages": {}}, {}, [], None, None)
        assert "incidents  n/a" in frame

    def test_renders_the_panel_from_a_live_summary(self):
        from hypervisor_tpu.api.service import HypervisorService

        hv_top = self._hv_top()
        st = HypervisorService().hv.state
        st.health.emit_event(
            "degraded_enter", {"mode": "degraded", "now": 42.0}
        )
        (health, counters, roofline, tenants, autopilot, fleet,
         incidents) = hv_top.poll_state(st)
        assert incidents["enabled"] and incidents["captured"] == 1
        frame = hv_top.render(
            health, counters, [], roofline, tenants, autopilot, fleet,
            incidents,
        )
        assert "incidents  captured=1" in frame
        assert "resilience.degraded_entered" in frame
