"""Flight recorder: in-jit trace ring, span reconstruction, exporters.

Pins the trace plane's contracts:

  * ring math — `TraceLog.stamp_batch` appends at the cursor, wraps,
    and drops every row of an unsampled wave (one predicated store:
    the cursor does not move),
  * in-jit stamping — the stamped governance wave lowers with NO host
    transfer (no callback/infeed/outfeed primitive), same gate as the
    metrics plane,
  * span words — the device child-span derivation and the host
    recomputation agree bit-for-bit, and `device_key_of` round-trips
    through the `trace/span[/parent]` string form,
  * reconstruction — one pipeline wave on the CPU backend yields a
    root `hv.governance_wave` span with the five phase children of
    `WAVE_CHILD_STAGES`, correctly nested (the acceptance criterion),
  * mode parity — the sharded bridge's host-mirrored stamps reconstruct
    the same child structure as the single-device in-jit stamps,
  * exporters — valid Chrome `trace_event` JSON and OTLP-lite JSON,
  * endpoints — `GET /trace/{session_id}` and `GET /debug/flight`
    through the service layer,
  * plane joins — host bus rows and device EventLog rows fed from the
    same traffic carry identical (trace, span) device-key words.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hypervisor_tpu.observability import tracing
from hypervisor_tpu.observability.causal_trace import (
    CausalTraceId,
    device_key_of,
    fnv1a32,
)
from hypervisor_tpu.tables.logs import TraceLog


def _ctx(trace=7, span=9, wave_seq=0, sampled=True) -> tracing.TraceContext:
    return tracing.TraceContext(
        trace=jnp.asarray(trace, jnp.uint32),
        span=jnp.asarray(span, jnp.uint32),
        wave_seq=jnp.asarray(wave_seq, jnp.int32),
        sampled=jnp.asarray(sampled, bool),
    )


def _session_config():
    from hypervisor_tpu.models import SessionConfig

    return SessionConfig(min_sigma_eff=0.0)


def _drive_wave(state, tag: str, n: int = 2):
    slots = state.create_sessions_batch(
        [f"{tag}:{i}" for i in range(n)], _session_config()
    )
    state.run_governance_wave(
        slots,
        [f"did:{tag}:{i}" for i in range(n)],
        slots.copy(),
        np.full(n, 0.8, np.float32),
        np.zeros((1, n, 16), np.uint32),
    )
    return slots


class TestTraceRing:
    def test_stamp_batch_appends(self):
        log = TraceLog.create(8)
        ctx = _ctx()
        st = tracing.WaveStamps(ctx, "governance_wave")
        st.begin("governance_wave")
        st.begin("admission_wave")
        st.end("admission_wave")
        st.end("governance_wave")
        out = st.commit(log)
        assert int(out.cursor) == 4
        assert np.asarray(out.wave_seq)[:4].tolist() == [0, 0, 0, 0]
        assert np.asarray(out.kind)[:4].tolist() == [0, 0, 1, 1]
        assert np.asarray(out.seq)[:4].tolist() == [0, 1, 2, 3]
        # Root rows carry the context span; phase rows the derived word.
        adm = tracing.child_span_word(9, tracing.STAGE_ID["admission_wave"])
        assert np.asarray(out.span)[:4].tolist() == [9, adm, adm, 9]

    def test_ring_wraps(self):
        log = TraceLog.create(4)
        for wave in range(3):
            st = tracing.WaveStamps(_ctx(wave_seq=wave), "saga_round")
            st.begin("saga_round")
            st.end("saga_round")
            log = st.commit(log)
        assert int(log.cursor) == 6
        # seq words survive the wrap: live rows are the 4 newest stamps.
        assert sorted(np.asarray(log.seq).tolist()) == [2, 3, 4, 5]

    def test_tracer_overflow_keeps_newest_waves_reconstructable(self):
        """Stamping past the ring's capacity (health-plane edge case):
        the cursor keeps counting past capacity, evicted waves drop out
        of the reconstruction, and the NEWEST waves still rebuild with
        their full child structure."""
        tracer = tracing.Tracer(capacity=16, enabled=True, sample_rate=1.0)
        # Each host-mirrored wave writes 12 rows (root + 5 children x2),
        # so 5 waves overflow a 16-row host mirror decisively. Device
        # path: stamp via WaveStamps on the device ring.
        n_waves = 5
        for i in range(n_waves):
            handle = tracer.begin_wave("governance_wave", sessions=(i,))
            st = tracing.WaveStamps(handle.ctx, "governance_wave")
            st.begin("governance_wave")
            for child in tracing.WAVE_CHILD_STAGES["governance_wave"]:
                st.begin(child)
                st.end(child)
            st.end("governance_wave")
            tracer.end_wave(handle, st.commit(tracer.table))
        assert int(tracer.table.cursor) == n_waves * 12
        assert int(tracer.table.cursor) > tracer.capacity  # overflowed
        spans = tracer.drain()
        # Only fully-surviving waves reconstruct as roots; the newest
        # wave always does, with its complete child structure.
        assert spans, "overflowed ring lost every wave"
        newest = max(spans, key=lambda s: s.wave_seq)
        assert newest.wave_seq == n_waves - 1
        assert [c.stage for c in newest.children] == list(
            tracing.WAVE_CHILD_STAGES["governance_wave"]
        )
        summary = tracer.flight_summary()
        assert summary["ring_cursor"] == n_waves * 12
        assert summary["waves_indexed"] == n_waves

    def test_unsampled_wave_drops_rows(self):
        log = TraceLog.create(8)
        st = tracing.WaveStamps(_ctx(sampled=False), "gateway_wave")
        st.begin("gateway_wave")
        st.end("gateway_wave")
        out = st.commit(log)
        assert int(out.cursor) == 0
        assert (np.asarray(out.wave_seq) == -1).all()

    def test_sampled_flag_is_traced_not_static(self):
        """One compiled program serves sampled and unsampled waves."""
        log = TraceLog.create(8)

        @jax.jit
        def stamp(log, sampled):
            ctx = _ctx(sampled=sampled)
            st = tracing.WaveStamps(ctx, "saga_round")
            st.begin("saga_round")
            st.end("saga_round")
            return st.commit(log)

        on = stamp(log, jnp.asarray(True))
        off = stamp(log, jnp.asarray(False))
        assert int(on.cursor) == 2 and int(off.cursor) == 0
        assert stamp._cache_size() == 1


class TestSpanWords:
    def test_child_word_host_device_agree(self):
        for parent in (0, 9, 0xDEADBEEF, 0xFFFFFFFF):
            for stage in range(len(tracing.TRACE_STAGES)):
                host = tracing.child_span_word(parent, stage)
                dev = int(
                    tracing.child_span_word(
                        jnp.asarray(parent, jnp.uint32), stage
                    )
                )
                assert host == dev, (parent, stage)

    def test_device_key_of_round_trips_full_ids(self):
        """Seeded sweep twin of the hypothesis property: any span built
        by child/sibling derivations keys identically after a string
        round-trip — the join contract between bus, EventLog, and
        TraceLog rows."""
        rng = np.random.RandomState(11)
        span = CausalTraceId()
        for _ in range(64):
            span = span.child() if rng.rand() < 0.5 else span.sibling()
            parsed = CausalTraceId.from_string(span.full_id)
            assert parsed.device_key() == span.device_key()
            assert device_key_of(span.full_id) == span.device_key()

    def test_device_key_of_bare_and_absent(self):
        assert device_key_of(None) == (0, 0)
        assert device_key_of("") == (0, 0)
        assert device_key_of("opaque-id") == (fnv1a32("opaque-id"), 0)


class TestLoweringGate:
    def _wave_args(self, b=4):
        from hypervisor_tpu.tables.state import (
            AgentTable, SessionTable, VouchTable,
        )
        from hypervisor_tpu.tables.struct import replace as t_replace

        agents = AgentTable.create(16)
        sessions = SessionTable.create(16)
        sessions = t_replace(sessions, state=sessions.state.at[:b].set(1))
        vouches = VouchTable.create(8)
        return (
            agents, sessions, vouches,
            jnp.arange(b, dtype=jnp.int32),
            jnp.arange(b, dtype=jnp.int32),
            jnp.arange(b, dtype=jnp.int32),
            jnp.full((b,), 0.8, jnp.float32),
            jnp.ones((b,), bool),
            jnp.zeros((b,), bool),
            jnp.arange(b, dtype=jnp.int32),
            jnp.zeros((2, b, 16), jnp.uint32),
            0.0,
        )

    def test_stamped_governance_wave_lowers_clean(self):
        """The acceptance gate: flight-recorder stamps inside the jitted
        wave must introduce no host transfer — no callback, infeed, or
        outfeed primitive anywhere in the traced program (with the
        metrics table riding too, the production configuration)."""
        from hypervisor_tpu.observability import metrics as mp
        from hypervisor_tpu.ops.pipeline import governance_wave

        table = mp.REGISTRY.create_table()
        log = TraceLog.create(64)
        ctx = _ctx()
        jaxpr = jax.make_jaxpr(
            lambda *a: governance_wave(
                *a, metrics=table, use_pallas=False,
                trace=log, trace_ctx=ctx,
            )
        )(*self._wave_args())
        text = str(jaxpr)
        for forbidden in ("callback", "infeed", "outfeed"):
            assert forbidden not in text, (
                f"trace stamping pulled a {forbidden} into the wave"
            )

    def test_stamped_gateway_and_slash_lower_clean(self):
        from hypervisor_tpu.ops import gateway as gateway_ops
        from hypervisor_tpu.ops import liability as liability_ops
        from hypervisor_tpu.tables.state import (
            AgentTable, ElevationTable, VouchTable,
        )

        log = TraceLog.create(64)
        ctx = _ctx()
        b, n = 4, 16
        agents = AgentTable.create(n)
        false = jnp.zeros((b,), bool)
        jaxpr = jax.make_jaxpr(
            lambda *a: gateway_ops.check_actions(
                *a, trace=log, trace_ctx=ctx
            )
        )(
            agents, ElevationTable.create(4),
            jnp.arange(b, dtype=jnp.int32),
            jnp.full((b,), 2, jnp.int8),
            false, false, false, false, 0.0,
        )
        text = str(jaxpr)
        jaxpr2 = jax.make_jaxpr(
            lambda *a: liability_ops.slash_cascade(
                *a, trace=log, trace_ctx=ctx
            )
        )(
            VouchTable.create(8),
            jnp.full((n,), 0.8, jnp.float32),
            jnp.zeros((n,), bool),
            0, 0.9, 0.0,
        )
        text += str(jaxpr2)
        for forbidden in ("callback", "infeed", "outfeed"):
            assert forbidden not in text


class TestReconstruction:
    def test_pipeline_wave_yields_nested_stage_spans(self):
        """Acceptance criterion: a single pipeline wave on the CPU
        backend reconstructs to >= 5 correctly nested hv.<stage> spans."""
        from hypervisor_tpu.state import HypervisorState

        st = HypervisorState()
        _drive_wave(st, "rec")
        spans = st.tracer.drain()
        roots = [s for s in spans if s.stage == "governance_wave"]
        assert len(roots) == 1
        root = roots[0]
        assert root.name == "hv.governance_wave"
        children = [c.stage for c in root.children]
        assert children == list(
            tracing.WAVE_CHILD_STAGES["governance_wave"]
        )
        assert len(children) >= 5
        # Correct nesting: every child inside the root bracket, children
        # sequential in stamp order, parent words correct.
        prev_end = root.start_us
        for child in root.children:
            assert root.start_us <= child.start_us <= child.end_us
            assert child.end_us <= root.end_us
            assert child.start_us >= prev_end
            prev_end = child.end_us
            assert child.parent_span_word == root.span_word
            assert child.span_word == tracing.child_span_word(
                root.span_word, tracing.STAGE_ID[child.stage]
            )

    def test_admission_flush_traces_too(self):
        from hypervisor_tpu.state import HypervisorState

        st = HypervisorState()
        slot = st.create_session("fl:s", _session_config())
        st.enqueue_join(slot, "did:fl0", 0.8)
        st.flush_joins()
        spans = st.tracer.drain()
        assert any(s.stage == "admission_wave" for s in spans)
        assert spans == sorted(spans, key=lambda s: s.wave_seq)

    def test_session_trace_filters_by_slot(self):
        from hypervisor_tpu.state import HypervisorState

        st = HypervisorState()
        slots_a = _drive_wave(st, "fa")
        slots_b = _drive_wave(st, "fb")
        only_b = st.session_trace(int(slots_b[0]))
        assert only_b and all(
            int(slots_a[0])
            not in st.tracer._waves[s.wave_seq].sessions
            for s in only_b
        )


class TestSampling:
    def test_sample_rate_zero_records_nothing_on_device(self):
        from hypervisor_tpu.state import HypervisorState

        st = HypervisorState()
        st.tracer.sample_rate = 0.0
        _drive_wave(st, "s0")
        assert int(np.asarray(st.tracer.table.cursor)) == 0
        assert st.tracer.drain() == []  # unsampled: no rows, no spans

    def test_sample_bit_deterministic(self):
        for key in ("a", "b", "slot:7"):
            assert tracing._sample_bit(key, 0.5) == tracing._sample_bit(
                key, 0.5
            )
        assert tracing._sample_bit("x", 1.0)
        assert not tracing._sample_bit("x", 0.0)

    def test_partial_rate_splits_sessions(self):
        hits = sum(
            tracing._sample_bit(f"slot:{i}", 0.5) for i in range(256)
        )
        assert 64 < hits < 192  # deterministic, roughly the rate


class TestModeParity:
    def test_mesh_wave_reconstructs_same_child_structure(self):
        """The sharded bridge mirrors stamps on the host plane through
        the same WAVE_CHILD_STAGES rule set the in-jit stamps follow —
        both deployment modes reconstruct one structure."""
        from hypervisor_tpu.parallel import make_mesh
        from hypervisor_tpu.state import HypervisorState

        n_dev, b = 4, 8

        def run(mesh):
            st = HypervisorState()
            slots = st.create_sessions_batch(
                [f"mp:{'m' if mesh else 's'}{i}" for i in range(b)],
                _session_config(),
            )
            st.run_governance_wave(
                slots,
                [f"did:mp:{'m' if mesh else 's'}{i}" for i in range(b)],
                slots.copy(),
                np.full(b, 0.8, np.float32),
                np.zeros((1, b, 16), np.uint32),
                mesh=mesh,
            )
            return st.tracer.drain()

        single = run(None)
        mesh = run(make_mesh(n_dev, platform="cpu"))
        s_root = [s for s in single if s.stage == "governance_wave"][0]
        m_root = [
            s for s in mesh if s.stage == "governance_wave_sharded"
        ][0]
        assert [c.stage for c in s_root.children] == [
            c.stage for c in m_root.children
        ]
        assert [c.kind if hasattr(c, "kind") else 0 for c in s_root.children]
        for s_child, m_child in zip(s_root.children, m_root.children):
            assert s_child.parent_span_word == s_root.span_word
            assert m_child.parent_span_word == m_root.span_word


class TestExporters:
    def _spans(self):
        from hypervisor_tpu.state import HypervisorState

        st = HypervisorState()
        _drive_wave(st, "ex")
        return st, st.tracer.drain()

    def test_chrome_trace_event_json(self):
        st, spans = self._spans()
        doc = json.loads(json.dumps(tracing.to_chrome_trace(spans)))
        assert isinstance(doc["traceEvents"], list)
        xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert len(xs) >= 6  # root + 5 phases
        for e in xs:
            assert e["name"].startswith("hv.")
            assert isinstance(e["ts"], (int, float))
            assert e["dur"] >= 0
            assert e["pid"] == 1
        names = {e["name"] for e in xs}
        assert "hv.governance_wave" in names
        assert "hv.admission_wave" in names

    def test_otlp_lite_json(self):
        st, spans = self._spans()
        doc = json.loads(json.dumps(tracing.to_otlp(spans, st.tracer)))
        otlp_spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
        assert len(otlp_spans) >= 6
        root = [s for s in otlp_spans if s["parentSpanId"] == ""][0]
        assert len(root["traceId"]) == 32
        assert len(root["spanId"]) == 16
        children = [
            s for s in otlp_spans if s["parentSpanId"] == root["spanId"]
        ]
        assert len(children) == 5
        for s in otlp_spans:
            assert s["endTimeUnixNano"] >= s["startTimeUnixNano"] > 0


class TestEndpoints:
    async def test_trace_endpoint_serves_chrome_json(self):
        from hypervisor_tpu.api import models as M
        from hypervisor_tpu.api.service import HypervisorService

        svc = HypervisorService()
        resp = await svc.create_session(
            M.CreateSessionRequest(creator_did="did:admin")
        )
        await svc.join_session(
            resp.session_id,
            M.JoinSessionRequest(agent_did="did:tp", sigma_raw=0.8),
        )
        doc = await svc.trace_session(resp.session_id)
        assert json.loads(json.dumps(doc))["traceEvents"]
        assert any(
            e["name"] == "hv.admission_wave"
            for e in doc["traceEvents"]
            if e.get("ph") == "X"
        )
        otlp = await svc.trace_session(resp.session_id, format="otlp")
        assert otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]

    async def test_pipeline_wave_served_with_nested_spans(self):
        """The acceptance criterion end to end: a single pipeline wave
        on the CPU backend, served via GET /trace/{session_id}, exports
        valid Chrome trace JSON whose governance root carries the five
        correctly nested hv.<stage> phase spans."""
        from hypervisor_tpu.api.service import HypervisorService

        svc = HypervisorService()
        _drive_wave(svc.hv.state, "pipe")
        doc = json.loads(json.dumps(await svc.trace_session("pipe:0")))
        xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        by_name = {e["name"]: e for e in xs}
        root = by_name["hv.governance_wave"]
        phases = [
            e for e in xs
            if e["args"]["parent_span"] == root["args"]["span"]
        ]
        assert len(phases) == 5
        for e in phases:
            assert root["ts"] <= e["ts"]
            assert e["ts"] + e["dur"] <= root["ts"] + root["dur"] + 1e-6
        # The session's DeltaLog audit records ride the delta_chain span.
        assert any(
            e.get("name") == "audit.delta_recorded"
            for e in doc["traceEvents"]
            if e.get("ph") == "i"
        )

    async def test_trace_endpoint_errors(self):
        from hypervisor_tpu.api.service import ApiError, HypervisorService

        svc = HypervisorService()
        with pytest.raises(ApiError) as err:
            await svc.trace_session("nope")
        assert err.value.status == 404

    async def test_debug_flight(self):
        from hypervisor_tpu.api import models as M
        from hypervisor_tpu.api.service import HypervisorService

        svc = HypervisorService()
        resp = await svc.create_session(
            M.CreateSessionRequest(creator_did="did:admin")
        )
        await svc.join_session(
            resp.session_id,
            M.JoinSessionRequest(agent_did="did:fl", sigma_raw=0.8),
        )
        flight = await svc.debug_flight()
        assert flight["enabled"] is True
        assert flight["waves_indexed"] >= 1
        assert flight["recent_waves"][-1]["stage"].startswith("hv.")
        assert "/" in flight["recent_waves"][-1]["trace_id"]


class TestPlaneJoins:
    def test_bus_and_event_log_share_device_key_words(self):
        """Host bus rows and device EventLog rows fed from the same
        traffic join on identical (trace, span) word pairs — seeded
        sweep twin of the hypothesis property."""
        from datetime import datetime, timezone

        from hypervisor_tpu.observability.event_bus import (
            EventType, HypervisorEvent, HypervisorEventBus,
        )
        from hypervisor_tpu.tables.logs import EventLog

        rng = np.random.RandomState(3)
        bus = HypervisorEventBus()
        expected = []
        span = CausalTraceId()
        types = list(EventType)
        for i in range(40):
            span = span.child() if rng.rand() < 0.5 else span.sibling()
            bus.emit(
                HypervisorEvent(
                    event_type=types[int(rng.randint(len(types)))],
                    session_id=f"s{i % 3}",
                    causal_trace_id=span.full_id,
                    timestamp=datetime.now(timezone.utc),
                )
            )
            expected.append(span.device_key())
        codes, sess, agents, traces, stamps, spans = bus.device_rows(0)
        log = EventLog.create(64).append_batch(
            jnp.asarray(codes), jnp.asarray(sess), jnp.asarray(agents),
            jnp.asarray(traces), jnp.asarray(stamps), jnp.asarray(spans),
        )
        got = list(
            zip(
                np.asarray(log.trace)[:40].tolist(),
                np.asarray(log.span)[:40].tolist(),
            )
        )
        assert got == expected

    def test_attach_bus_events_joins_on_words(self):
        from datetime import datetime, timezone

        from hypervisor_tpu.observability.event_bus import (
            EventType, HypervisorEvent, HypervisorEventBus,
        )
        from hypervisor_tpu.state import HypervisorState

        st = HypervisorState()
        _drive_wave(st, "bj")
        spans = st.tracer.drain()
        root = spans[0]
        bus = HypervisorEventBus()
        record = st.tracer._waves[root.wave_seq]
        bus.emit(
            HypervisorEvent(
                event_type=EventType.SESSION_CREATED,
                session_id="bj:0",
                causal_trace_id=record.trace.full_id,
                timestamp=datetime.now(timezone.utc),
            )
        )
        attached = tracing.attach_bus_events(spans, bus)
        assert attached == 1
        assert root.events and root.events[0]["name"] == "session.created"
