"""Adversarial governance plane: seeded scenario determinism, containment
scoring, per-mechanism hardening deltas, and the round-5 satellite nits.

Property style without hypothesis (not installed in the bare image):
seeded sweeps + replay-twin comparisons, like tests/parity/test_invariants.py.
"""

from __future__ import annotations

import numpy as np
import pytest

from hypervisor_tpu.testing import scenarios

SEED = 11

# One shared cache so the jit-heavy scenarios run once per (name, mode)
# and every assertion class reads the same results.
_CACHE: dict = {}


def run(name: str, seed: int = SEED, hardened: bool = True):
    key = (name, seed, hardened)
    if key not in _CACHE:
        _CACHE[key] = scenarios.run_scenario(name, seed, hardened=hardened)
    return _CACHE[key]


# ── seed determinism: same seed -> same trace -> same score ──────────


class TestSeedDeterminism:
    @pytest.mark.parametrize("name", scenarios.SCENARIO_NAMES)
    def test_replay_twin_is_bit_identical(self, name):
        first = run(name)
        twin = scenarios.run_scenario(name, SEED, hardened=True)
        assert first.trace_digest == twin.trace_digest
        assert first.score == twin.score
        assert first.components == twin.components
        assert first.attack_events == twin.attack_events

    def test_seed_moves_the_trace(self):
        assert (
            run("slash_cascade").trace_digest
            != scenarios.run_scenario("slash_cascade", SEED + 1).trace_digest
        )

    def test_hardened_flag_is_part_of_the_identity(self):
        assert (
            run("sybil_flood").trace_digest
            != run("sybil_flood", hardened=False).trace_digest
        )

    def test_unknown_scenario_refuses(self):
        with pytest.raises(ValueError, match="unknown scenario"):
            scenarios.run_scenario("nope", SEED)


# ── containment: the hardened suite holds the floor ──────────────────


class TestContainment:
    @pytest.mark.parametrize("name", scenarios.SCENARIO_NAMES)
    def test_hardened_scenario_contains(self, name):
        result = run(name)
        assert result.score >= scenarios.DEFAULT_CONTAINMENT_FLOOR, (
            result.components
        )

    @pytest.mark.parametrize(
        "name,key",
        [
            ("sybil_flood", "invariants_clean"),
            ("collusion_ring", "escrow_conservation"),
            ("compensation_storm", "invariants_clean"),
            ("byzantine_fuzz", "invariants_clean"),
        ],
    )
    def test_invariants_survive_every_adversary(self, name, key):
        """Escrow conservation / σ ranges / FSM codes / turn chains —
        the PR 5 sanitizer must report ZERO violations after each
        adversary class runs its full attack."""
        assert run(name).components[key] == 1.0

    @pytest.mark.parametrize("name", scenarios.SCENARIO_NAMES)
    def test_honest_traffic_survives(self, name):
        comps = run(name).components
        honest_keys = [k for k in comps if k.startswith("honest")]
        assert honest_keys, comps
        assert all(comps[k] == 1.0 for k in honest_keys), comps


class TestHardeningDeltas:
    """Each hardening mechanism must be LOAD-BEARING: the unhardened
    twin of its scenario scores strictly lower (acceptance criterion:
    before/after containment delta per mechanism)."""

    @pytest.mark.parametrize(
        "name",
        [
            "sybil_flood",        # admission-rate sybil damper
            "collusion_ring",     # vouch-graph collusion detector
            "slash_cascade",      # deduped canonical cascade
            "compensation_storm", # supervisor comp backpressure
            "noisy_neighbor",     # per-tenant quotas + DRR fair share
        ],
    )
    def test_unhardened_twin_scores_strictly_lower(self, name):
        hard = run(name)
        bare = run(name, hardened=False)
        assert bare.score < hard.score, (
            name, bare.components, hard.components
        )

    def test_sybil_damper_protects_capacity(self):
        hard = run("sybil_flood").components
        bare = run("sybil_flood", hardened=False).components
        assert bare["flood_work_damped"] == 0.0
        assert hard["flood_work_damped"] > 0.5
        assert bare["capacity_preserved"] < hard["capacity_preserved"]
        assert bare["honest_admission"] < 1.0  # the flood took seats
        assert hard["honest_admission"] == 1.0

    def test_collusion_detector_neutralizes_before_defection(self):
        hard = run("collusion_ring")
        bare = run("collusion_ring", hardened=False)
        assert hard.components["pump_neutralized"] == 1.0
        assert bare.components["pump_neutralized"] == 0.0
        assert hard.components["detector_precision"] == 1.0
        assert hard.details["honest_flagged"] == []

    def test_cascade_dedupe_and_canonical_order(self):
        hard = run("slash_cascade")
        bare = run("slash_cascade", hardened=False)
        assert hard.components["single_settlement"] == 1.0
        assert bare.components["single_settlement"] < 1.0
        assert hard.components["deterministic_settlement"] == 1.0
        assert bare.components["deterministic_settlement"] == 0.0
        assert hard.details["dedupes"] >= 1
        assert bare.details["dedupes"] == 0

    def test_backpressure_drains_the_storm(self):
        hard = run("compensation_storm")
        bare = run("compensation_storm", hardened=False)
        assert hard.components["storm_drained"] == 1.0
        assert bare.components["storm_drained"] < 1.0
        assert hard.components["backpressure_engaged"] == 1.0
        assert hard.components["degraded_exited"] == 1.0
        assert hard.details["arrivals_deferred"] > 0
        assert bare.details["arrivals_deferred"] == 0


# ── hardening mechanisms, unit level ─────────────────────────────────


class TestAdmissionDamper:
    def _state(self):
        from hypervisor_tpu.state import HypervisorState

        return HypervisorState()

    def test_targeted_shed_lets_honest_joins_flow(self):
        from hypervisor_tpu.models import SessionConfig
        from hypervisor_tpu.resilience.policy import (
            AdmissionDamper,
            SybilShedRefusal,
        )

        st = self._state()
        st.admission_damper = AdmissionDamper(
            rate_threshold=5.0, low_sigma_fraction=0.5,
            sigma_floor=0.5, window_seconds=1.0,
        )
        slot = st.create_session(
            "damp:a", SessionConfig(min_sigma_eff=0.0), now=0.0
        )
        shed = 0
        for i in range(6):  # trip: 6 joins in 1 s, all low sigma; the
            try:            # attempt that crosses the threshold is
                st.enqueue_join(  # itself already damped
                    slot, f"did:low:{i}", 0.1, now=i * 0.01
                )
            except SybilShedRefusal:
                shed += 1
        assert shed == 1
        assert st.admission_damper.active
        assert st.degraded_policy is not None
        assert st.degraded_policy.admission_sigma_floor == 0.5
        with pytest.raises(SybilShedRefusal):
            st.enqueue_join(slot, "did:low:x", 0.2, now=0.07)
        # Honest sigma clears the targeted floor even while tripped.
        assert st.enqueue_join(slot, "did:ok", 0.9, now=0.08) >= 0
        assert st.admission_damper.damped == 2

    def test_damper_exits_when_the_flood_recedes(self):
        from hypervisor_tpu.resilience.policy import AdmissionDamper

        st = self._state()
        damper = AdmissionDamper(
            rate_threshold=5.0, window_seconds=1.0, sigma_floor=0.5
        )
        st.admission_damper = damper
        for i in range(6):
            damper.note_join(st, 0.1, i * 0.01)
        assert damper.active
        # Quiet period: the next sample, far later, sees an empty window.
        damper.note_join(st, 0.1, 100.0)
        assert not damper.active
        assert st.degraded_policy is None

    def test_supervisor_escalation_replaces_targeted_policy(self):
        """A live sybil damp (targeted policy) must not suppress
        supervisor escalation: a comp-backlog storm outranks it and the
        damper forgets its replaced handle."""
        from hypervisor_tpu.resilience.policy import AdmissionDamper
        from hypervisor_tpu.resilience.supervisor import Supervisor

        st = self._state()
        damper = AdmissionDamper(
            rate_threshold=2.0, window_seconds=1.0, sigma_floor=0.5
        )
        st.admission_damper = damper
        sup = Supervisor(
            st, degrade_after_comp_backlog=2, sleep=lambda s: None
        )
        for i in range(4):
            damper.note_join(st, 0.1, i * 0.01)
        assert damper.active
        assert not st.degraded_policy.shed_admissions
        st.health.emit_event("comp_backlog", {"backlog": 5})
        assert st.degraded_policy.shed_admissions  # full shed replaced it
        assert st.degraded_policy.pause_saga_fanout
        damper.note_join(st, 0.1, 0.05)
        assert not damper.active  # forgot the replaced handle
        _ = sup

    def test_restore_carries_the_damper_across(self, tmp_path):
        from hypervisor_tpu.resilience.policy import AdmissionDamper
        from hypervisor_tpu.resilience.supervisor import Supervisor
        from hypervisor_tpu.resilience.wal import WriteAheadLog

        st = self._state()
        st.journal = WriteAheadLog(tmp_path / "wal.log", fsync=False)
        damper = AdmissionDamper(rate_threshold=1e9)
        st.admission_damper = damper
        sup = Supervisor(
            st, checkpoint_dir=str(tmp_path / "ckpt"),
            sleep=lambda s: None,
        )
        sup.checkpoint()
        recovered = sup.restore_state("drill")
        assert recovered is not st
        assert recovered.admission_damper is damper

    def test_supervisor_clean_exit_leaves_targeted_policy_alone(self):
        """The supervisor's clean-streak exit clears only FULL degraded
        policies — a live sybil damp is the damper's to uninstall."""
        from hypervisor_tpu.resilience.policy import AdmissionDamper
        from hypervisor_tpu.resilience.supervisor import Supervisor

        st = self._state()
        damper = AdmissionDamper(
            rate_threshold=2.0, window_seconds=1.0, sigma_floor=0.5
        )
        st.admission_damper = damper
        sup = Supervisor(st, exit_after_clean=1, sleep=lambda s: None)
        for i in range(4):
            damper.note_join(st, 0.1, i * 0.01)
        assert damper.active
        sup.dispatch("wave", lambda: None)  # clean streak hits the exit
        assert st.degraded_policy is not None, (
            "clean-streak exit cleared the damper's targeted policy"
        )
        assert damper.active
        assert sup.degraded_exits == 0

    def test_damper_never_clobbers_supervisor_policy(self):
        from hypervisor_tpu.resilience.policy import (
            AdmissionDamper,
            DegradedPolicy,
        )

        st = self._state()
        damper = AdmissionDamper(rate_threshold=1.0, window_seconds=1.0)
        st.admission_damper = damper
        supervisor_policy = DegradedPolicy(reason="operator shed")
        st.degraded_policy = supervisor_policy
        for i in range(8):
            damper.note_join(st, 0.1, i * 0.01)
        assert st.degraded_policy is supervisor_policy
        assert not damper.active


class TestCollusionDetector:
    def _engine_with_clique(self):
        from hypervisor_tpu.liability.vouching import VouchingEngine

        eng = VouchingEngine()
        s = "s:collusion"
        # Honest reputable hub fanning out: dense-ish, single-role.
        for leaf in ("did:h1", "did:h2", "did:h3"):
            eng.vouch("did:hub", leaf, s, voucher_sigma=0.9)
        # The pump clique: layered DAG, every inner member dual-role.
        clique = [f"did:c{i}" for i in range(4)]
        for a, b in [(0, 1), (0, 2), (0, 3), (1, 2), (1, 3), (2, 3)]:
            eng.vouch(clique[a], clique[b], s, voucher_sigma=0.55)
        return eng, s, clique

    def test_flags_clique_not_hub(self):
        from hypervisor_tpu.liability.collusion import CollusionDetector

        eng, s, clique = self._engine_with_clique()
        findings = CollusionDetector().scan(eng, s)
        assert len(findings) == 1
        assert list(findings[0].members) == sorted(clique)
        assert findings[0].dual_role_fraction >= 0.5
        assert "did:hub" not in findings[0].members

    def test_scan_is_deterministic(self):
        from hypervisor_tpu.liability.collusion import CollusionDetector

        eng, s, _ = self._engine_with_clique()
        a = [f.to_dict() for f in CollusionDetector().scan(eng, s)]
        b = [f.to_dict() for f in CollusionDetector().scan(eng)]
        assert a == b

    def test_sweep_rescan_charges_each_finding_once(self):
        """Quarantined members keep live edges, so sweep-cadence
        re-scans re-surface the same component — the ledger must not
        ratchet per tick."""
        import asyncio

        from hypervisor_tpu.core import Hypervisor
        from hypervisor_tpu.models import SessionConfig

        async def drive():
            hv = Hypervisor()
            managed = await hv.create_session(
                SessionConfig(min_sigma_eff=0.5), "did:op"
            )
            sid = managed.sso.session_id
            clique = [f"did:c{i}" for i in range(4)]
            for did in clique:
                await hv.join_session(sid, did, sigma_raw=0.55)
            for a, b in [(0, 1), (0, 2), (0, 3), (1, 2), (1, 3), (2, 3)]:
                hv.vouching.vouch(
                    clique[a], clique[b], sid, voucher_sigma=0.55
                )
            first = hv.detect_collusion(sid)
            charges = len(hv.ledger.get_agent_history(clique[0]))
            again = hv.detect_collusion(sid)
            assert len(first) == len(again) == 1
            assert (
                len(hv.ledger.get_agent_history(clique[0])) == charges
            ), "sweep re-scan re-charged a persisting finding"

        asyncio.run(drive())

    def test_released_bonds_leave_the_graph(self):
        from hypervisor_tpu.liability.collusion import CollusionDetector

        eng, s, _ = self._engine_with_clique()
        for rec in eng.all_records():
            if rec.voucher_did.startswith("did:c"):
                eng.release_bond(rec.vouch_id)
        assert CollusionDetector().scan(eng, s) == []


class TestCascadeHardening:
    def _diamond(self, dedupe: bool):
        from hypervisor_tpu.liability.slashing import SlashingEngine
        from hypervisor_tpu.liability.vouching import VouchingEngine

        eng = VouchingEngine()
        s = "s:diamond"
        eng.vouch("did:m1", "did:root", s, voucher_sigma=0.8)
        eng.vouch("did:m2", "did:root", s, voucher_sigma=0.8)
        eng.vouch("did:w", "did:m1", s, voucher_sigma=0.8)
        eng.vouch("did:w", "did:m2", s, voucher_sigma=0.8)
        slashing = SlashingEngine(eng, dedupe_cascade=dedupe)
        scores = {d: 0.8 for d in ("did:root", "did:m1", "did:m2", "did:w")}
        slashing.slash("did:root", s, 0.8, 0.99, "diamond", scores)
        return slashing, scores

    def test_legacy_diamond_double_clips_the_shared_voucher(self):
        slashing, _ = self._diamond(dedupe=False)
        clipped = [
            c.voucher_did for e in slashing.history for c in e.voucher_clips
        ]
        assert clipped.count("did:w") == 2
        assert slashing.cascade_dedupes == 0

    def test_deduped_diamond_settles_each_agent_once(self):
        slashing, _ = self._diamond(dedupe=True)
        clipped = [
            c.voucher_did for e in slashing.history for c in e.voucher_clips
        ]
        assert clipped.count("did:w") == 1
        assert slashing.cascade_dedupes == 1
        # Every bond was still consumed: the edge backed the rogue.
        assert all(not r.is_active for r in slashing._vouching.all_records())

    def test_max_depth_override_stops_the_cascade(self):
        from hypervisor_tpu.liability.slashing import SlashingEngine
        from hypervisor_tpu.liability.vouching import VouchingEngine

        eng = VouchingEngine()
        s = "s:chain"
        for i in range(4):
            eng.vouch(f"did:c{i + 1}", f"did:c{i}", s, voucher_sigma=0.8)
        slashing = SlashingEngine(eng)
        scores = {f"did:c{i}": 0.8 for i in range(5)}
        slashing.slash(
            "did:c0", s, 0.8, 0.99, "bounded", scores, max_depth=0
        )
        assert len(slashing.history) == 1  # no recursion at depth 0
        assert scores["did:c2"] == 0.8  # beyond the horizon: untouched


class TestCompensationBackpressure:
    def test_saga_work_budget_is_deterministic_prefix(self):
        from hypervisor_tpu.models import SessionConfig
        from hypervisor_tpu.state import HypervisorState

        st = HypervisorState()
        sess = st.create_session(
            "bp:s", SessionConfig(min_sigma_eff=0.0), now=0.0
        )
        steps = [{"has_undo": True, "retries": 0}] * 2
        slots = [st.create_saga(f"bp:{i}", sess, steps) for i in range(6)]
        st.saga_round(exec_outcomes={s: True for s in slots})
        st.saga_round(exec_outcomes={s: False for s in slots})
        _, full = st.saga_work()
        _, capped = st.saga_work(comp_budget=2)
        assert len(full) == 6
        assert capped == full[:2]
        assert [s for s, _ in full] == sorted(s for s, _ in full)

    def test_backlog_event_flips_supervisor_degraded(self, monkeypatch):
        from hypervisor_tpu.models import SessionConfig
        from hypervisor_tpu.resilience.supervisor import Supervisor
        from hypervisor_tpu.state import HypervisorState

        # Read per saga_work call, so arming it here (post-import) works.
        monkeypatch.setenv("HV_COMP_BACKLOG_WARN", "3")
        st = HypervisorState()
        sup = Supervisor(
            st, degrade_after_comp_backlog=4, sleep=lambda s: None
        )
        sess = st.create_session(
            "bp:t", SessionConfig(min_sigma_eff=0.0), now=0.0
        )
        steps = [{"has_undo": True, "retries": 0}] * 2
        slots = [st.create_saga(f"bpt:{i}", sess, steps) for i in range(5)]
        st.saga_round(exec_outcomes={s: True for s in slots})
        assert not sup.degraded
        st.saga_round(exec_outcomes={s: False for s in slots})
        st.saga_work()  # backlog 5 >= warn 3 -> event; 5 >= 4 -> degrade
        assert sup.degraded
        assert "compensation storm" in st.degraded_policy.reason
        assert sup.summary()["pressure"]["comp_backlog"] == 5


class TestByzantineTransportHardening:
    @pytest.fixture()
    def server(self):
        from hypervisor_tpu.api.server import HypervisorHTTPServer

        server = HypervisorHTTPServer().start()
        yield server
        server.stop()

    def _req(self, server, method, path, body=None, headers=None):
        import http.client

        conn = http.client.HTTPConnection(
            "127.0.0.1", server.port, timeout=10
        )
        try:
            conn.request(
                method, path, body=body,
                headers={"Content-Type": "application/json",
                         **(headers or {})},
            )
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def test_malformed_json_is_a_400_not_a_dropped_connection(self, server):
        status, body = self._req(
            server, "POST", "/api/v1/sessions", b'{"creator_did": '
        )
        assert status == 400
        assert b"malformed JSON" in body

    def test_array_body_is_a_422(self, server):
        status, _ = self._req(
            server, "POST", "/api/v1/sessions", b"[1, 2, 3]"
        )
        assert status == 422

    def test_bad_limit_query_param_is_a_400(self, server):
        status, _ = self._req(server, "GET", "/api/v1/events?limit=abc")
        assert status == 400

    def _raw_status(self, server, content_length: str) -> int:
        """Raw-socket request with a forged Content-Length header
        (http.client would add its own, truthful one)."""
        import socket

        raw = (
            "POST /api/v1/sessions HTTP/1.1\r\n"
            "Host: t\r\nContent-Type: application/json\r\n"
            f"Content-Length: {content_length}\r\n"
            "Connection: close\r\n\r\n{}"
        ).encode()
        with socket.create_connection(
            ("127.0.0.1", server.port), timeout=10
        ) as sock:
            sock.sendall(raw)
            head = sock.recv(4096)
        return int(head.split(b" ")[1])

    def test_negative_content_length_is_a_400(self, server):
        assert self._raw_status(server, "-1") == 400

    def test_oversized_content_length_is_a_413(self, server):
        assert self._raw_status(server, str(64 << 20)) == 413

    def test_non_finite_sigma_refused_at_the_door(self):
        import asyncio

        from hypervisor_tpu.api import models as M
        from hypervisor_tpu.api.service import ApiError, HypervisorService

        svc = HypervisorService()
        run_ = asyncio.run
        created = run_(svc.create_session(
            M.CreateSessionRequest(creator_did="did:op")
        ))
        for bad in (float("nan"), float("inf"), -1.0, 2.0):
            with pytest.raises(ApiError) as err:
                run_(svc.join_session(
                    created.session_id,
                    M.JoinSessionRequest(agent_did="did:a", sigma_raw=bad),
                ))
            assert err.value.status == 400

    def test_non_finite_vouch_inputs_refused(self):
        from hypervisor_tpu.liability.vouching import (
            VouchingEngine,
            VouchingError,
        )

        eng = VouchingEngine()
        with pytest.raises(VouchingError, match="finite"):
            eng.vouch("did:a", "did:b", "s", voucher_sigma=float("nan"))
        with pytest.raises(VouchingError, match="finite"):
            eng.vouch(
                "did:a", "did:b", "s",
                voucher_sigma=0.8, bond_pct=float("inf"),
            )


# ── scenario plumbing: metrics + events ──────────────────────────────


class TestScenarioPlumbing:
    def test_metrics_and_events_mirror_a_run(self):
        from hypervisor_tpu.observability import (
            EventType,
            HypervisorEventBus,
        )
        from hypervisor_tpu.observability import metrics as mp
        from hypervisor_tpu.observability.metrics import Metrics, REGISTRY

        metrics = Metrics(REGISTRY)
        bus = HypervisorEventBus()
        result = scenarios.run_scenario(
            "slash_cascade", 3, metrics=metrics, event_bus=bus
        )
        snap = metrics.snapshot()
        assert snap.counter(mp.SCENARIO_RUNS) == 1
        assert snap.counter(mp.SCENARIO_ATTACK_EVENTS) == (
            result.attack_events
        )
        assert snap.gauge(mp.SCENARIO_CONTAINMENT) == result.score
        kinds = [e.event_type for e in bus.query(limit=10)]
        assert EventType.SCENARIO_STARTED in kinds
        assert EventType.SCENARIO_SCORED in kinds

    def test_aggregate_reports_the_floor_statistic(self):
        results = {
            name: run(name) for name in ("slash_cascade", "sybil_flood")
        }
        agg = scenarios.aggregate(results)
        assert agg["min_score"] == min(r.score for r in results.values())
        assert set(agg["trace_digests"]) == set(results)


# ── round-5 satellite nits ───────────────────────────────────────────


class TestSatelliteNits:
    def test_record_calls_non_monotonic_now_never_shrinks_the_window(self):
        """A stale `now=` targeting a bucket stamped with a NEWER epoch
        must accumulate into it (stamp preserved) instead of resetting
        the counts and regressing the stamp."""
        import jax.numpy as jnp

        from hypervisor_tpu.config import DEFAULT_CONFIG
        from hypervisor_tpu.ops import security_ops as so
        from hypervisor_tpu.tables.state import BD_BUCKETS

        cfg = DEFAULT_CONFIG.breach
        k = BD_BUCKETS
        sub = cfg.window_seconds / k
        win = jnp.zeros((1, 3 * k), jnp.int32)
        now1 = 100 * sub + 0.5 * sub            # epoch 100
        now0 = (100 - k) * sub + 0.5 * sub      # same bucket, K epochs older
        add = jnp.asarray([3], jnp.int32)
        win = so.window_commit(win, add, add, now1, cfg)
        win = so.window_commit(win, jnp.asarray([2], jnp.int32),
                               jnp.asarray([0], jnp.int32), now0, cfg)
        calls, priv = so.window_totals(win, now1, cfg)
        assert int(calls[0]) == 5, "stale commit erased newer counts"
        assert int(priv[0]) == 3
        assert int(win[0, 2 * k + (100 % k)]) == 100, "stamp regressed"

    def test_staged_since_harvest_floors_at_zero(self, caplog):
        from hypervisor_tpu.runtime.native import HAVE_NATIVE, StagingQueue

        if not HAVE_NATIVE:
            pytest.skip("native staging queue unavailable")
        q = StagingQueue(capacity=8)
        q.push(0.5, 0, 0)
        q.push(0.5, 1, 0)
        with q._count_lock:
            q._staged_since_harvest -= 1  # simulate an uncounted entry
        import logging

        with caplog.at_level(logging.WARNING,
                             logger="hypervisor_tpu.runtime.native"):
            n, *_ = q.harvest()
        assert n == 2
        assert q._staged_since_harvest == 0
        assert any("flooring" in r.message for r in caplog.records)

    def test_legacy_migration_warns_when_breach_counters_drop(
        self, tmp_path, caplog
    ):
        import logging

        from hypervisor_tpu.models import SessionConfig
        from hypervisor_tpu.runtime.checkpoint import (
            restore_state,
            save_state,
        )
        from hypervisor_tpu.state import HypervisorState
        from hypervisor_tpu.tables.state import AI32_BD_WIN_START

        st = HypervisorState()
        slot = st.create_session("ck:warn", SessionConfig())
        st.enqueue_join(slot, "did:w0", sigma_raw=0.8)
        assert (st.flush_joins() == 0).all()
        target = save_state(st, tmp_path, step=1)
        path = target / "tables.npz"
        data = dict(np.load(path))
        i32 = np.asarray(data["agents.i32"])
        legacy = np.zeros((i32.shape[0], 5), np.int32)
        legacy[:, :AI32_BD_WIN_START] = i32[:, :AI32_BD_WIN_START]
        legacy[0, 3] = 7  # in-flight breach counters a fast restore drops
        legacy[0, 4] = 2
        data["agents.i32"] = legacy
        with open(path, "wb") as f:
            np.savez(f, **data)
        with caplog.at_level(
            logging.WARNING, logger="hypervisor_tpu.runtime.checkpoint"
        ):
            back = restore_state(target)
        assert any(
            "breach-window counters" in r.message for r in caplog.records
        )
        assert np.asarray(back.agents.bd_window).sum() == 0

    def test_ignore_collect_defers_with_none(self, monkeypatch, tmp_path):
        import conftest as c

        monkeypatch.setenv("HV_HOST_PLANE_ONLY", "1")
        curated = tmp_path / "unit" / "test_models.py"
        other = tmp_path / "unit" / "test_state_things.py"
        assert c.pytest_ignore_collect(curated, None) is None
        assert c.pytest_ignore_collect(other, None) is True
        monkeypatch.delenv("HV_HOST_PLANE_ONLY")
        assert c.pytest_ignore_collect(other, None) is None
